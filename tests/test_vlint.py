"""vlint: per-checker fixtures (violating / clean / annotated), the
runtime lock-order sanitizer, the CLI exit codes, and the tier-1 gate
asserting the repo itself is clean against the committed baseline."""

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.vlint.core import (load_baseline, new_findings, run_paths,
                              run_source)
from tools.vlint.runtime import (InstrumentedLock, LockOrderSanitizer,
                                 install, uninstall)


def lint(src: str, path: str = "victorialogs_tpu/mod.py"):
    return run_source(path, textwrap.dedent(src))


def checkers(findings):
    return {f.checker for f in findings}


# ---------------- lock discipline ----------------

LOCK_BASE = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self.x = 0

        def good(self):
            with self._lock:
                self.x += 1
"""


def test_unguarded_write_flagged():
    out = lint(LOCK_BASE + """
        def bad(self):
            self.x = 5
    """)
    assert "lock-unguarded-write" in checkers(out)
    assert any("self.x" in f.message for f in out)


def test_unguarded_write_clean_and_init_exempt():
    assert "lock-unguarded-write" not in checkers(lint(LOCK_BASE))


def test_unguarded_write_annotated():
    out = lint(LOCK_BASE + """
        def bad(self):
            # vlint: allow-lock-unguarded-write(single-writer thread)
            self.x = 5
    """)
    assert "lock-unguarded-write" not in checkers(out)


def test_unguarded_write_through_private_helper():
    # a private method reached both locked and unlocked: the unlocked
    # path must flag (the indexdb._account_write class of race)
    out = lint(LOCK_BASE + """
        def _bump(self):
            self.x += 1

        def locked_path(self):
            with self._lock:
                self._bump()

        def unlocked_path(self):
            self._bump()
    """)
    assert "lock-unguarded-write" in checkers(out)


def test_blocking_call_under_lock_flagged():
    out = lint(LOCK_BASE + """
        def bad(self):
            with self._lock:
                with open("/tmp/f") as f:
                    return f.read()
    """)
    assert "lock-blocking-call" in checkers(out)


def test_blocking_call_outside_lock_clean():
    out = lint(LOCK_BASE + """
        def fine(self):
            with open("/tmp/f") as f:
                return f.read()
    """)
    assert "lock-blocking-call" not in checkers(out)


def test_blocking_call_annotated():
    out = lint(LOCK_BASE + """
        # vlint: allow-lock-blocking-call(durability by design)
        def bad(self):
            with self._lock:
                with open("/tmp/f") as f:
                    return f.read()
    """)
    assert "lock-blocking-call" not in checkers(out)


def test_os_path_join_not_blocking():
    out = lint(LOCK_BASE + """
        def fine(self):
            import os
            with self._lock:
                return os.path.join("a", "b")
    """)
    assert "lock-blocking-call" not in checkers(out)


def test_lock_order_cycle_flagged():
    out = lint("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def m1(self):
                with self._a:
                    with self._b:
                        pass

            def m2(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert "lock-order-cycle" in checkers(out)


def test_lock_order_consistent_clean():
    out = lint("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def m1(self):
                with self._a:
                    with self._b:
                        pass

            def m2(self):
                with self._a:
                    with self._b:
                        pass
    """)
    assert "lock-order-cycle" not in checkers(out)


def test_self_reacquire_flagged():
    out = lint(LOCK_BASE + """
        def bad(self):
            with self._lock:
                with self._lock:
                    pass
    """)
    assert "lock-order-cycle" in checkers(out)


# ---------------- hygiene ----------------

def test_broad_except_flagged():
    out = lint("""
        def f():
            try:
                return 1
            except Exception:
                return 0
    """)
    assert "broad-except" in checkers(out)


def test_broad_except_reraise_clean():
    out = lint("""
        def f():
            try:
                return 1
            except Exception:
                raise
    """)
    assert "broad-except" not in checkers(out)


def test_broad_except_annotated():
    out = lint("""
        def f():
            try:
                return 1
            # vlint: allow-broad-except(best-effort)
            except Exception:
                return 0
    """)
    assert "broad-except" not in checkers(out)


def test_mutable_default_flagged_and_clean():
    assert "mutable-default" in checkers(lint("def f(a, b=[]): pass"))
    assert "mutable-default" not in checkers(
        lint("def f(a, b=None, c=()): pass"))


def test_wall_clock_flagged_clean_annotated():
    assert "wall-clock" in checkers(lint("""
        import time
        def f():
            return time.time()
    """))
    assert "wall-clock" not in checkers(lint("""
        import time
        def f():
            return time.monotonic(), time.time_ns()
    """))
    assert "wall-clock" not in checkers(lint("""
        import time
        def f():
            # vlint: allow-wall-clock(persisted timestamp)
            return time.time()
    """))


def test_nondaemon_thread_flagged_and_clean():
    assert "nondaemon-thread" in checkers(lint("""
        import threading
        def f():
            threading.Thread(target=f).start()
    """))
    assert "nondaemon-thread" not in checkers(lint("""
        import threading
        def f():
            threading.Thread(target=f, daemon=True).start()
    """))


# ---------------- JAX hot path ----------------

def test_host_sync_flagged():
    out = lint("""
        import jax.numpy as jnp
        def f(a):
            x = jnp.sum(a)
            return float(x)
    """, path="victorialogs_tpu/tpu/mod.py")
    assert "jax-host-sync" in checkers(out)


def test_host_sync_out_of_scope_and_clean():
    src = """
        import jax.numpy as jnp
        def f(a):
            x = jnp.sum(a)
            return float(x)
    """
    # same code outside tpu/ or engine/ is not hot-path scoped
    assert "jax-host-sync" not in checkers(
        lint(src, path="victorialogs_tpu/storage/mod.py"))
    clean = """
        import jax.numpy as jnp
        def f(a):
            x = jnp.sum(a)
            return x
    """
    assert "jax-host-sync" not in checkers(
        lint(clean, path="victorialogs_tpu/tpu/mod.py"))


def test_host_sync_annotated_and_variants():
    out = lint("""
        import jax.numpy as jnp
        import numpy as np
        def f(a):
            x = jnp.sum(a)
            # vlint: allow-jax-host-sync(result boundary)
            return np.asarray(x)
    """, path="victorialogs_tpu/tpu/mod.py")
    assert "jax-host-sync" not in checkers(out)
    out = lint("""
        import jax.numpy as jnp
        def f(a):
            x = jnp.sum(a)
            if x:
                return x.item()
            return 0
    """, path="victorialogs_tpu/tpu/mod.py")
    msgs = [f.message for f in out if f.checker == "jax-host-sync"]
    assert any("truth test" in m for m in msgs)
    assert any(".item()" in m for m in msgs)


def test_jit_closure_flagged_and_clean():
    out = lint("""
        import jax
        state = {"k": 1}
        @jax.jit
        def f(x):
            return x + state["k"]
    """, path="victorialogs_tpu/tpu/mod.py")
    assert "jax-jit-closure" in checkers(out)
    out = lint("""
        import jax
        K = 2
        @jax.jit
        def f(x):
            return x + K
    """, path="victorialogs_tpu/tpu/mod.py")
    assert "jax-jit-closure" not in checkers(out)


def test_static_arg_flagged_and_clean():
    out = lint("""
        import jax
        from functools import partial
        n = 3
        @partial(jax.jit, static_argnums=n)
        def f(x):
            return x
    """, path="victorialogs_tpu/tpu/mod.py")
    assert "jax-static-arg" in checkers(out)
    out = lint("""
        import jax
        from functools import partial
        @partial(jax.jit, static_argnums=(0, 1),
                 static_argnames=("mode",))
        def f(x, n, mode=0):
            return x
    """, path="victorialogs_tpu/tpu/mod.py")
    assert "jax-static-arg" not in checkers(out)


# ---------------- baseline workflow ----------------

def test_baseline_absorbs_then_catches_new(tmp_path):
    from tools.vlint.core import write_baseline
    src = textwrap.dedent("""
        def f():
            try:
                return 1
            except Exception:
                return 0
    """)
    found = run_source("victorialogs_tpu/mod.py", src)
    assert found
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(found, bl_path)
    assert new_findings(found, load_baseline(bl_path)) == []
    # a SECOND identical violation exceeds the baselined count
    src2 = src + textwrap.dedent("""
        def g():
            try:
                return 1
            except Exception:
                return 0
    """)
    found2 = run_source("victorialogs_tpu/mod.py", src2)
    fresh = new_findings(found2, load_baseline(bl_path))
    assert len(fresh) == 1


# ---------------- runtime lock-order sanitizer ----------------

def test_sanitizer_detects_inversion():
    san = LockOrderSanitizer()
    a = InstrumentedLock(san, "victorialogs_tpu/x.py:1")
    b = InstrumentedLock(san, "victorialogs_tpu/x.py:2")
    with a:
        with b:
            pass
    assert not san.violations
    with b:
        with a:
            pass
    assert san.violations, "A->B then B->A must be a violation"


def test_sanitizer_static_consistency():
    san = LockOrderSanitizer()
    a = InstrumentedLock(san, "victorialogs_tpu/x.py:1")
    b = InstrumentedLock(san, "victorialogs_tpu/x.py:2")
    with a:
        with b:
            pass
    site_map = {("victorialogs_tpu/x.py", 1): "C._a",
                ("victorialogs_tpu/x.py", 2): "C._b"}
    # observed a->b agrees with static a->b
    assert san.check_static_consistency({("C._a", "C._b")}, site_map) == []
    # observed a->b REVERSES a static b->a edge
    problems = san.check_static_consistency({("C._b", "C._a")}, site_map)
    assert problems


def test_sanitizer_install_scopes_to_repo(tmp_path):
    import threading

    import pytest

    from tools.vlint.runtime import get_sanitizer
    if get_sanitizer() is not None:
        pytest.skip("session-wide sanitizer active (VLINT_LOCK_ORDER=1);"
                    " uninstalling here would disarm it")
    try:
        san = install()
        # a lock created from repo code is instrumented ...
        from victorialogs_tpu.utils.cache import TwoGenCache
        c = TwoGenCache()
        assert isinstance(c._lock, InstrumentedLock)
        c.put("k", "v")
        assert c.get("k") == "v"
        # ... a lock created from non-repo code is not
        assert not isinstance(threading.Lock(), InstrumentedLock)
        assert not san.violations
    finally:
        uninstall()


def test_sanitizer_condition_wait_order():
    # Condition(instrumented lock): wait() releases out of LIFO order —
    # the held-stack bookkeeping must survive it
    import threading
    san = LockOrderSanitizer()
    lk = InstrumentedLock(san, "victorialogs_tpu/x.py:9")
    cond = threading.Condition(lk)
    with cond:
        cond.wait(timeout=0.01)
    assert not san.violations
    assert san._stack() == []


# ---------------- per-row-emit (columnar emit discipline) ----------------

EMIT_PATH = "victorialogs_tpu/server/mod.py"


def test_per_row_emit_dumps_in_loop_flagged():
    out = lint("""
        import json
        def encode(rows):
            out = []
            for r in rows:
                out.append(json.dumps(r))
            return out
    """, path=EMIT_PATH)
    assert "per-row-emit" in checkers(out)


def test_per_row_emit_dumps_in_comprehension_flagged():
    out = lint("""
        import json
        def encode(rows):
            return "\\n".join(json.dumps(r) for r in rows)
    """, path=EMIT_PATH)
    assert "per-row-emit" in checkers(out)


def test_per_row_emit_dict_comprehension_element_flagged():
    # a dict per iteration with no .append() call at all
    out = lint("""
        def build(br, names):
            return [{n: br.column(n)[i] for n in names}
                    for i in range(br.nrows)]
    """, path=EMIT_PATH)
    assert "per-row-emit" in checkers(out)


def test_per_row_emit_column_dict_clean():
    # ONE dict of columns (dict comprehension not nested in a list
    # comprehension) is the columnar shape — must not flag
    out = lint("""
        def build(br, names):
            return {n: br.column(n) for n in names}
    """, path=EMIT_PATH)
    assert "per-row-emit" not in checkers(out)


def test_per_row_emit_dict_append_flagged():
    # incl. the `append = out.append` bound-method alias
    out = lint("""
        def build(br, names):
            out = []
            append = out.append
            for i in range(br.nrows):
                append({n: br.column(n)[i] for n in names})
            return out
    """, path=EMIT_PATH)
    assert "per-row-emit" in checkers(out)


def test_per_row_emit_single_dumps_clean():
    out = lint("""
        import json
        def encode(obj):
            return json.dumps(obj)
    """, path=EMIT_PATH)
    assert "per-row-emit" not in checkers(out)


def test_per_row_emit_scope_excludes_other_dirs():
    src = """
        import json
        def encode(rows):
            return [json.dumps(r) for r in rows]
    """
    assert "per-row-emit" not in checkers(
        lint(src, path="victorialogs_tpu/logsql/mod.py"))
    assert "per-row-emit" in checkers(
        lint(src, path="victorialogs_tpu/engine/mod.py"))


def test_per_row_emit_annotated():
    out = lint("""
        import json
        def encode(rows):
            out = []
            for r in rows:
                # vlint: allow-per-row-emit(cold admin endpoint)
                out.append(json.dumps(r))
            return out
    """, path=EMIT_PATH)
    assert "per-row-emit" not in checkers(out)


# ---------------- the tier-1 gate + CLI ----------------

def test_hotpath_covers_pipeline_module():
    """The async pipeline (tpu/pipeline.py) is hot-path scoped: the
    checker must SEE the file (an unannotated sync there is flagged),
    the real module must run clean, and the single deliberate harvest
    sync must carry the allow-annotation with its rationale."""
    from tools.vlint import hotpath
    from tools.vlint.core import SourceFile

    # the file is in scope: a synthetic host sync at the same path flags
    out = lint("""
        import jax.numpy as jnp
        def harvest(window):
            x = jnp.zeros(8)
            return float(x)
    """, path="victorialogs_tpu/tpu/pipeline.py")
    assert "jax-host-sync" in checkers(out)

    # the real module runs clean under the full checker set
    path = os.path.join(REPO, "victorialogs_tpu", "tpu", "pipeline.py")
    sf = SourceFile.parse(path,
                          display_path="victorialogs_tpu/tpu/pipeline.py")
    found = [f for f in hotpath.check(sf)
             if not sf.allowed(f.checker, f.line)]
    assert found == [], [f.render() for f in found]

    # the ONE harvest sync point is annotated with a rationale
    assert "vlint: allow-jax-host-sync(" in sf.text
    assert sf.text.count("np.asarray") == 1   # a single sync site


def test_hotpath_covers_stats_seg_module():
    """The segment-major stats kernel module (tpu/stats_seg.py, PR 15)
    rides the tpu/ hot-path scope: the checker must SEE the file (an
    unannotated host sync there is flagged) and the real module must
    run clean — its kernels are traced inside the fused dispatch, so a
    hidden sync or jit-closure would stall every packed stats query."""
    from tools.vlint import hotpath
    from tools.vlint.core import SourceFile

    out = lint("""
        import jax.numpy as jnp
        def reduce_seg(x):
            return float(jnp.sum(x))
    """, path="victorialogs_tpu/tpu/stats_seg.py")
    assert "jax-host-sync" in checkers(out)

    path = os.path.join(REPO, "victorialogs_tpu", "tpu", "stats_seg.py")
    sf = SourceFile.parse(
        path, display_path="victorialogs_tpu/tpu/stats_seg.py")
    found = [f for f in hotpath.check(sf)
             if not sf.allowed(f.checker, f.line)]
    assert found == [], [f.render() for f in found]


def test_repo_is_clean_against_baseline():
    findings = run_paths([os.path.join(REPO, "victorialogs_tpu")],
                         root=REPO)
    fresh = new_findings(findings, load_baseline())
    assert fresh == [], "\n".join(f.render() for f in fresh)


def test_cli_exit_codes(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO)
    ok = subprocess.run(
        [sys.executable, "-m", "tools.vlint", "victorialogs_tpu"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    # one seeded violation per checker family must each fail the CLI
    seeds = {
        "locks.py": LOCK_BASE + """
        def bad(self):
            self.x = 5
        """,
        "hygiene.py": """
        def f():
            try:
                return 1
            except Exception:
                return 0
        """,
        os.path.join("tpu", "hot.py"): """
        import jax.numpy as jnp
        def f(a):
            return float(jnp.sum(a))
        """,
    }
    for rel, src in seeds.items():
        p = tmp_path / "pkg" / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        r = subprocess.run(
            [sys.executable, "-m", "tools.vlint", str(p.parent),
             "--no-baseline"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert r.returncode == 1, f"{rel}: {r.stdout}{r.stderr}"
        p.unlink()


# ---------------- span discipline (obs/tracing.py API) ----------------

SPAN_BAD_CTOR = """
    from victorialogs_tpu.obs.tracing import Span

    def f():
        sp = Span("query", {})
        return sp
"""

SPAN_BAD_OPEN = """
    from victorialogs_tpu.obs import tracing

    def f():
        sp = tracing.current_span().span("harvest")
        return sp
"""

SPAN_GOOD = """
    from victorialogs_tpu.obs import tracing

    def f():
        root = tracing.make_root("query")
        with tracing.activate(root):
            with tracing.current_span().span("harvest", unit=1) as h:
                h.add("rows", 5)
        return root.to_dict()
"""


def test_span_discipline_flags_direct_construction():
    out = lint(SPAN_BAD_CTOR)
    assert "span-discipline" in checkers(out)
    assert any("Span(...)" in f.message for f in out)


def test_span_discipline_flags_unclosed_open():
    out = lint(SPAN_BAD_OPEN)
    assert "span-discipline" in checkers(out)
    assert any("never close" in f.message for f in out)


def test_span_discipline_clean_and_annotated():
    assert "span-discipline" not in checkers(lint(SPAN_GOOD))
    annotated = """
        from victorialogs_tpu.obs import tracing

        def f():
            # vlint: allow-span-discipline(closed manually in a handle)
            sp = tracing.current_span().span("x")
            return sp
    """
    assert "span-discipline" not in checkers(lint(annotated))


def test_span_discipline_skips_tracing_module():
    out = lint(SPAN_BAD_CTOR,
               path="victorialogs_tpu/obs/tracing.py")
    assert "span-discipline" not in checkers(out)


def test_span_discipline_repo_instrumentation_is_clean():
    """Every .span()/make_root call site the tracing wiring added must
    honor the context-manager discipline across all instrumented
    layers."""
    from tools.vlint.core import SourceFile
    from tools.vlint import spans
    for rel in ("engine/searcher.py", "storage/filterbank.py",
                "tpu/pipeline.py", "tpu/batch.py", "tpu/layout.py",
                "parallel/distributed.py", "server/cluster.py",
                "server/vlselect.py", "server/app.py"):
        path = os.path.join(REPO, "victorialogs_tpu", rel)
        sf = SourceFile.parse(path,
                              display_path=f"victorialogs_tpu/{rel}")
        found = [f for f in spans.check(sf)
                 if not sf.allowed(f.checker, f.line)]
        assert found == [], [f.render() for f in found]


# ---------------- accounting discipline (obs/activity.py API) ----------------

ACCT_BAD_CTOR = """
    from victorialogs_tpu.obs.activity import QueryActivity

    def f():
        act = QueryActivity("1", "/x", "*", "0:0")
        return act
"""

ACCT_BAD_OPEN = """
    from victorialogs_tpu.obs import activity

    def f():
        act = activity.track("/select/logsql/query", "*", None)
        return act
"""

ACCT_GOOD = """
    from victorialogs_tpu.obs import activity

    def f(storage, run_query):
        with activity.track("/select/logsql/query", "*", None) as act:
            act.add("parts_scanned")
            run_query(storage)
        return activity.active_snapshot()
"""


def test_accounting_discipline_flags_direct_construction():
    out = lint(ACCT_BAD_CTOR)
    assert "accounting-discipline" in checkers(out)
    assert any("QueryActivity(...)" in f.message for f in out)


def test_accounting_discipline_flags_unclosed_track():
    out = lint(ACCT_BAD_OPEN)
    assert "accounting-discipline" in checkers(out)
    assert any("never deregister" in f.message for f in out)


def test_accounting_discipline_clean_and_annotated():
    assert "accounting-discipline" not in checkers(lint(ACCT_GOOD))
    annotated = """
        from victorialogs_tpu.obs import activity

        def f():
            # vlint: allow-accounting-discipline(deregistered in a handle)
            t = activity.track("/x", "*", None)
            return t
    """
    assert "accounting-discipline" not in checkers(lint(annotated))


def test_accounting_discipline_skips_activity_module():
    out = lint(ACCT_BAD_CTOR,
               path="victorialogs_tpu/obs/activity.py")
    assert "accounting-discipline" not in checkers(out)


def test_accounting_discipline_repo_instrumentation_is_clean():
    """Every track()/QueryActivity site the registry wiring added must
    honor the context-manager discipline across the registering
    layers."""
    from tools.vlint.core import SourceFile
    from tools.vlint import accounting
    for rel in ("engine/searcher.py", "server/vlselect.py",
                "server/cluster.py", "server/app.py",
                "server/vlagent.py", "tpu/pipeline.py"):
        path = os.path.join(REPO, "victorialogs_tpu", rel)
        sf = SourceFile.parse(path,
                              display_path=f"victorialogs_tpu/{rel}")
        found = [f for f in accounting.check(sf)
                 if not sf.allowed(f.checker, f.line)]
        assert found == [], [f.render() for f in found]


def test_accounting_discipline_flags_unclosed_reuse():
    out = lint("""
        from victorialogs_tpu.obs import activity

        def f():
            t = activity.reuse_or_track("/x", "*", None)
            return t
    """)
    assert "accounting-discipline" in checkers(out)


# ---------------- lease discipline (victorialogs_tpu/sched API) -------------

LEASE_BAD_CTOR = """
    from victorialogs_tpu.sched.scheduler import _SlotScope

    def f(s):
        scope = _SlotScope(s, None, "0:0")
        return scope
"""

LEASE_BAD_OPEN = """
    from victorialogs_tpu import sched

    def f():
        slots = sched.device_slots(None)
        slots.try_acquire()
        return slots
"""

LEASE_GOOD = """
    from victorialogs_tpu import sched

    def f(run_unit):
        with sched.device_slots(None) as slots:
            slots.acquire()
            try:
                run_unit()
            finally:
                slots.release()
"""


def test_lease_discipline_flags_direct_construction():
    out = lint(LEASE_BAD_CTOR)
    assert "lease-discipline" in checkers(out)
    assert any("_SlotScope(...)" in f.message for f in out)


def test_lease_discipline_flags_unclosed_scope():
    out = lint(LEASE_BAD_OPEN)
    assert "lease-discipline" in checkers(out)
    assert any("never drain" in f.message for f in out)


def test_lease_discipline_clean_and_annotated():
    assert "lease-discipline" not in checkers(lint(LEASE_GOOD))
    annotated = """
        from victorialogs_tpu import sched

        def f():
            # vlint: allow-lease-discipline(drained in a handle)
            slots = sched.device_slots(None)
            return slots
    """
    assert "lease-discipline" not in checkers(lint(annotated))


def test_lease_discipline_skips_sched_package():
    out = lint(LEASE_BAD_CTOR,
               path="victorialogs_tpu/sched/scheduler.py")
    assert "lease-discipline" not in checkers(out)


def test_lease_discipline_repo_instrumentation_is_clean():
    """The pipeline's slot leasing (the ONE consumer of device_slots)
    must honor the context-manager scope discipline, and the sched
    package itself must pass the lock-discipline pass."""
    from tools.vlint.core import SourceFile
    from tools.vlint import leases, locks
    for rel in ("tpu/pipeline.py", "engine/searcher.py",
                "server/app.py"):
        path = os.path.join(REPO, "victorialogs_tpu", rel)
        sf = SourceFile.parse(path,
                              display_path=f"victorialogs_tpu/{rel}")
        found = [f for f in leases.check(sf)
                 if not sf.allowed(f.checker, f.line)]
        assert found == [], [f.render() for f in found]
    for rel in ("sched/scheduler.py", "sched/admission.py"):
        path = os.path.join(REPO, "victorialogs_tpu", rel)
        sf = SourceFile.parse(path,
                              display_path=f"victorialogs_tpu/{rel}")
        found = [f for f in locks.check(sf)
                 if not sf.allowed(f.checker, f.line)]
        assert found == [], [f.render() for f in found]


# ---------------- net discipline ----------------

NET_BAD_URLOPEN = """
    import urllib.request

    def fetch(url):
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.read()
"""

NET_BAD_CONN = """
    import http.client

    def fetch(host):
        conn = http.client.HTTPConnection(host, 80)
        return conn
"""

NET_GOOD = """
    from . import netrobust

    def fetch(url):
        return netrobust.request(url, "/internal/insert", b"")
"""


def test_net_discipline_flags_raw_urlopen_in_server():
    out = lint(NET_BAD_URLOPEN,
               path="victorialogs_tpu/server/cluster.py")
    assert "net-discipline" in checkers(out)
    assert any("netrobust" in f.message for f in out)


def test_net_discipline_flags_direct_http_client():
    out = lint(NET_BAD_CONN,
               path="victorialogs_tpu/server/vlagent.py")
    assert "net-discipline" in checkers(out)


def test_net_discipline_scoped_to_server_package():
    # the same raw call OUTSIDE server/ is someone else's business
    # (tools, tests, benches talk to servers as plain HTTP clients)
    out = lint(NET_BAD_URLOPEN, path="victorialogs_tpu/cli/main.py")
    assert "net-discipline" not in checkers(out)


def test_net_discipline_skips_netrobust_module():
    out = lint(NET_BAD_CONN,
               path="victorialogs_tpu/server/netrobust.py")
    assert "net-discipline" not in checkers(out)


def test_net_discipline_clean_and_annotated():
    assert "net-discipline" not in checkers(
        lint(NET_GOOD, path="victorialogs_tpu/server/cluster.py"))
    annotated = """
        import urllib.request

        def probe(url):
            # vlint: allow-net-discipline(liveness probe, no policy wanted)
            return urllib.request.urlopen(url, timeout=1)
    """
    assert "net-discipline" not in checkers(
        lint(annotated, path="victorialogs_tpu/server/cluster.py"))


def test_net_discipline_repo_cluster_hops_are_clean():
    """Every cluster hop in server/ (cluster.py, vlagent.py, app.py)
    must ride the policy layer — zero raw-client findings."""
    from tools.vlint.core import SourceFile
    from tools.vlint import netdiscipline
    for rel in ("server/cluster.py", "server/vlagent.py",
                "server/app.py", "server/agent_http.py"):
        path = os.path.join(REPO, "victorialogs_tpu", rel)
        sf = SourceFile.parse(path,
                              display_path=f"victorialogs_tpu/{rel}")
        found = [f for f in netdiscipline.check(sf)
                 if not sf.allowed(f.checker, f.line)]
        assert found == [], [f.render() for f in found]


# ---------------- balance checker (acquire/release pairs) ----------------

def test_balance_pair_registry_inventory():
    """The declared registry covers every budgeted pair in the tree —
    the checker is driven by it, vlsan enforces the runtime_only rows."""
    from tools.vlint.balance import PAIRS
    names = {p.name for p in PAIRS}
    assert names == {"bloom-bank", "sched-lease", "admission",
                     "staging-cache", "events-subscription",
                     "journal-accounting", "net-probe", "insert-spool",
                     "result-cache", "standing-subscription",
                     "ingest-encoder-pool"}
    runtime = {p.name for p in PAIRS if p.runtime_only}
    assert runtime == {"staging-cache", "journal-accounting"}


def test_balance_double_release_sequence():
    """The PR 12 class seeded: a charge released twice drives the
    bank budget negative (= unbounded)."""
    out = lint("""
        from victorialogs_tpu.storage.filterbank import (
            _bank_release, _bank_try_charge)

        def seal(nbytes):
            if not _bank_try_charge(nbytes):
                return False
            _bank_release([nbytes])
            _bank_release([nbytes])
            return True
    """, path="victorialogs_tpu/storage/mod.py")
    assert "balance-double-release" in checkers(out)
    assert any("negative" in f.message for f in out)


def test_balance_double_release_except_plus_finally():
    out = lint("""
        from victorialogs_tpu.storage.filterbank import (
            _bank_release, _bank_try_charge)

        def seal(nbytes, build):
            if not _bank_try_charge(nbytes):
                return None
            try:
                return build()
            except RuntimeError:
                _bank_release([nbytes])
                raise
            finally:
                _bank_release([nbytes])
    """, path="victorialogs_tpu/storage/mod.py")
    assert "balance-double-release" in checkers(out)


def test_balance_release_in_loop_with_acquire_outside():
    out = lint("""
        from victorialogs_tpu.storage.filterbank import (
            _bank_release, _bank_try_charge)

        def seal(parts, nbytes):
            if not _bank_try_charge(nbytes):
                return
            try:
                for p in parts:
                    _bank_release([nbytes])
            finally:
                pass
    """, path="victorialogs_tpu/storage/mod.py")
    assert "balance-double-release" in checkers(out)


def test_balance_unguarded_acquire_flagged_and_finalize_clean():
    bad = lint("""
        from victorialogs_tpu.storage.filterbank import _bank_try_charge

        def charge(n, stage):
            if _bank_try_charge(n):
                stage(n)
    """, path="victorialogs_tpu/storage/mod.py")
    assert "balance-unguarded-acquire" in checkers(bad)
    good = lint("""
        import weakref

        from victorialogs_tpu.storage.filterbank import (
            _bank_release, _bank_try_charge)

        class Bank:
            def __init__(self):
                self._charged = []
                weakref.finalize(self, _bank_release, self._charged)

            def charge(self, n, stage):
                if _bank_try_charge(n):
                    self._charged.append(n)
                    stage(n)
    """, path="victorialogs_tpu/storage/mod.py")
    assert "balance-unguarded-acquire" not in checkers(good)
    guarded = lint("""
        from victorialogs_tpu.storage.filterbank import (
            _bank_release, _bank_try_charge)

        def charge(n, stage):
            if not _bank_try_charge(n):
                return
            try:
                stage(n)
            finally:
                _bank_release([n])
    """, path="victorialogs_tpu/storage/mod.py")
    assert "balance-unguarded-acquire" not in checkers(guarded)


def test_balance_sched_lease_outside_scope():
    bad = lint("""
        def f(scope):
            if scope.try_acquire():
                return True
    """, path="victorialogs_tpu/tpu/mod.py")
    assert "balance-unguarded-acquire" in checkers(bad)
    good = lint("""
        from victorialogs_tpu import sched

        def f(act, submit):
            with sched.device_slots(act) as slots:
                if slots.try_acquire():
                    submit()
    """, path="victorialogs_tpu/tpu/mod.py")
    assert "balance-unguarded-acquire" not in checkers(good)


def test_balance_admit_outside_with():
    bad = lint("""
        def f(pool):
            t = pool.admit("0:0", "/select/logsql/query")
            return t
    """, path="victorialogs_tpu/server/mod.py")
    assert "balance-ctx" in checkers(bad)
    good = lint("""
        def f(pool, run):
            with pool.admit("0:0", "/select/logsql/query"):
                return run()
    """, path="victorialogs_tpu/server/mod.py")
    assert "balance-ctx" not in checkers(good)


def test_balance_subscribe_needs_unsubscribe_in_file():
    bad = lint("""
        from victorialogs_tpu.obs import events

        class Watcher:
            def __init__(self):
                events.subscribe(self._on_event)

            def _on_event(self, ts_ns, event, fields):
                pass
    """, path="victorialogs_tpu/obs/mod.py")
    assert "balance-unguarded-acquire" in checkers(bad)
    good = lint("""
        from victorialogs_tpu.obs import events

        class Watcher:
            def __init__(self):
                events.subscribe(self._on_event)

            def _on_event(self, ts_ns, event, fields):
                pass

            def close(self):
                events.unsubscribe(self._on_event)
    """, path="victorialogs_tpu/obs/mod.py")
    assert "balance-unguarded-acquire" not in checkers(good)


def test_balance_net_probe_must_resolve():
    bad = lint("""
        def send(br, do_net):
            if not br.allow_insert():
                return None
            return do_net()
    """, path="victorialogs_tpu/server/mod.py")
    assert "balance-unguarded-acquire" in checkers(bad)
    good = lint("""
        def send(br, do_net):
            if not br.allow_insert():
                return None
            try:
                out = do_net()
                br.on_success()
                return out
            finally:
                br.abandon_probe()
    """, path="victorialogs_tpu/server/mod.py")
    assert "balance-unguarded-acquire" not in checkers(good)


def test_callable_identity_flagged_and_equality_clean():
    """The PR 8 class seeded: `is` against a bound method never
    matches — every unsubscribe leaked its subscription."""
    bad = lint("""
        class Journal:
            def _on_event(self, ts_ns, event, fields):
                pass

            def remove(self, subs):
                return tuple(s for s in subs
                             if s is not self._on_event)
    """)
    assert "callable-identity" in checkers(bad)
    good = lint("""
        class Journal:
            def _on_event(self, ts_ns, event, fields):
                pass

            def remove(self, subs):
                return tuple(s for s in subs
                             if s != self._on_event)
    """)
    assert "callable-identity" not in checkers(good)
    # `is` on plain data attributes stays legal (sentinel compares)
    sentinel = lint("""
        class C:
            def __init__(self, cb):
                self._cb = cb

            def same(self, other):
                return other is self._cb
    """)
    assert "callable-identity" not in checkers(sentinel)


# ---------------- config/metrics registry drift ----------------

def test_env_registry_flags_raw_read():
    out = lint("""
        import os

        def wire_typed():
            return os.environ.get("VL_WIRE_TYPED", "1") != "0"
    """)
    assert "env-registry" in checkers(out)
    out2 = lint("""
        import os

        def wire_typed():
            return os.getenv("VL_WIRE_TYPED")
    """)
    assert "env-registry" in checkers(out2)
    out3 = lint("""
        import os

        def wire_typed():
            return os.environ["VL_WIRE_TYPED"]
    """)
    assert "env-registry" in checkers(out3)


def test_env_registry_flags_undeclared_name():
    out = lint("""
        from victorialogs_tpu import config

        def f():
            return config.env("VL_TOTALLY_UNDECLARED")
    """)
    assert "env-registry" in checkers(out)
    good = lint("""
        from victorialogs_tpu import config

        def f():
            return config.env_flag("VL_SCHED")
    """)
    assert "env-registry" not in checkers(good)


def test_env_registry_repo_is_rerouted():
    """No raw environ read anywhere in victorialogs_tpu/ outside
    config.py (the CLI envflag mirror carries its annotation)."""
    found = run_paths([os.path.join(REPO, "victorialogs_tpu")],
                      root=REPO)
    raw = [f for f in found if f.checker == "env-registry"]
    assert raw == [], [f.render() for f in raw]


def test_metric_registry_flags_undeclared():
    out = lint("""
        def f(metrics):
            metrics.inc("vl_bogus_thing_total")
    """)
    assert "metric-registry" in checkers(out)
    out2 = lint("""
        from victorialogs_tpu.obs import hist

        H = hist.histogram("vl_bogus_hist_seconds", "nope", (1, 2))
    """)
    assert "metric-registry" in checkers(out2)
    good = lint("""
        def f(metrics):
            metrics.inc("vl_http_errors_total")
    """)
    assert "metric-registry" not in checkers(good)


def test_metric_double_roll_flagged():
    """The PR 4 / PR 6 class seeded: one event accumulated at two
    sites double-counts the series."""
    out = lint("""
        def cancel(metrics):
            metrics.inc("vl_queries_cancelled_total")

        def cancel_http(metrics):
            metrics.inc("vl_queries_cancelled_total")
    """)
    assert "metric-double-roll" in checkers(out)
    # multi-site counters that are DECLARED multi-site stay legal
    good = lint("""
        def a(metrics):
            metrics.inc("vl_http_errors_total")

        def b(metrics):
            metrics.inc("vl_http_errors_total")
    """)
    assert "metric-double-roll" not in checkers(good)


def test_canonical_helper_flags_inline_splitmix():
    """The PR 7/10/12 inline-copy-drift class seeded: a hand-copied
    splitmix64 finalizer outside utils/hashing.py."""
    out = lint("""
        def my_hash(x):
            x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
            z = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) \\
                & 0xFFFFFFFFFFFFFFFF
            return z
    """)
    assert "canonical-helper" in checkers(out)
    # the canonical module itself is exempt
    clean = lint("""
        def my_hash(x):
            return (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    """, path="victorialogs_tpu/utils/hashing.py")
    assert "canonical-helper" not in checkers(clean)


def test_canonical_helper_flags_inline_fastrange():
    out = lint("""
        import numpy as np

        def block_select(h, m):
            return (h * m) >> np.uint64(32)
    """)
    assert "canonical-helper" in checkers(out)
    clean = lint("""
        import numpy as np

        def block_select(h, m):
            return (h * m) >> np.uint64(32)
    """, path="victorialogs_tpu/storage/filterindex/sbbloom.py")
    assert "canonical-helper" not in checkers(clean)


def test_env_table_matches_registry():
    """README env table is byte-identical to the generated one —
    the same gate `make lint` runs."""
    from tools.vlint.__main__ import check_env_table
    assert check_env_table() == 0


def test_config_registry_shape():
    from tools.vlint.registry import config_module
    cfg = config_module()
    for m in cfg.metric_decls().values():
        if m.kind == "counter":
            assert m.name.endswith("_total"), m.name
        if m.kind == "gauge":
            assert not m.name.endswith("_total"), m.name
    for v in cfg.env_vars().values():
        assert v.doc and v.display, v.name
    import pytest
    with pytest.raises(cfg.UndeclaredEnvVar):
        cfg.env("VL_NOT_A_THING")


# ---------------- annotation hygiene ----------------

def test_bare_annotation_is_a_finding():
    out = lint("""
        # vlint: allow-wall-clock
        import time

        def f():
            return time.time()
    """)
    assert "annotation-reason" in checkers(out)
    # AND the bare form never suppressed the underlying finding
    assert "wall-clock" in checkers(out)


def test_empty_reason_is_a_finding():
    out = lint("""
        # vlint: allow-wall-clock( )
        import time

        def f():
            return time.time()
    """)
    assert "annotation-reason" in checkers(out)


def test_reasoned_annotation_is_clean():
    out = lint("""
        import time

        def f():
            # vlint: allow-wall-clock(row timestamps are wall time)
            return time.time()
    """)
    assert "annotation-reason" not in checkers(out)
    assert "wall-clock" not in checkers(out)


# ---------------- parallel runner + cache ----------------

def test_parallel_jobs_match_serial(tmp_path):
    src_ok = "x = 1\n"
    src_bad = ("import time\n\n\ndef f():\n"
               "    return time.time()\n")
    for i in range(4):
        (tmp_path / f"m{i}.py").write_text(src_bad if i % 2 else src_ok)
    serial = run_paths([str(tmp_path)], root=str(tmp_path), jobs=1)
    para = run_paths([str(tmp_path)], root=str(tmp_path), jobs=2)
    assert [f.render() for f in serial] == [f.render() for f in para]
    assert any(f.checker == "wall-clock" for f in serial)


def test_cache_roundtrip_and_invalidation(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text("import time\n\n\ndef f():\n    return time.time()\n")
    cache = str(tmp_path / "cache.json")
    first = run_paths([str(mod)], root=str(tmp_path), cache_path=cache)
    assert any(f.checker == "wall-clock" for f in first)
    assert os.path.exists(cache)
    # warm: identical findings straight from the cache
    warm = run_paths([str(mod)], root=str(tmp_path), cache_path=cache)
    assert [f.render() for f in first] == [f.render() for f in warm]
    # content change invalidates just that file
    mod.write_text("x = 1\n")
    third = run_paths([str(mod)], root=str(tmp_path), cache_path=cache)
    assert third == []


# ---------------- --explain CLI ----------------

def test_explain_cli(tmp_path, capsys):
    from tools.vlint.__main__ import main
    mod = tmp_path / "m.py"
    mod.write_text("import time\n\n\ndef f():\n    return time.time()\n")
    rc = main(["--json", "--no-baseline", "--no-cache", str(mod)])
    out = capsys.readouterr().out
    import json as _json
    finding = _json.loads(out)["findings"][0]
    assert rc == 1
    rc = main(["--explain", finding["fingerprint"], str(mod)])
    text = capsys.readouterr().out
    assert rc == 0
    assert "wall-clock" in text
    assert "allow-wall-clock(" in text        # the annotation recipe
    assert "tools/vlint/hygiene.py" in text   # the checker doc source
    # unknown fingerprint: clean error, exit 1
    rc = main(["--explain", "ffffffffffffffff", str(mod)])
    assert rc == 1


def test_baseline_stays_empty():
    """Fix-or-annotate discipline: the committed baseline has zero
    entries and the repo is clean against it."""
    baseline = load_baseline()
    assert baseline == {}


# ---------------- vlsan: end-of-test invariant sanitizer ----------------

def test_vlsan_clean_on_idle_process():
    from tools.vlint import vlsan
    san = vlsan.Sanitizer()
    san.begin_test()
    assert san.sweep() == []


def test_vlsan_detects_subscriber_leak():
    from tools.vlint import vlsan
    from victorialogs_tpu.obs import events
    san = vlsan.Sanitizer()
    san.begin_test()

    def cb(ts_ns, event, fields):
        pass

    events.subscribe(cb)
    try:
        problems = san.sweep()
        assert any("subscriber" in p for p in problems), problems
    finally:
        events.unsubscribe(cb)
    assert san.sweep() == []


def test_vlsan_detects_bank_double_release():
    """The historical negative-budget class, reproduced live: one
    release too many drives _bank_bytes negative and the sweep says
    so."""
    from tools.vlint import vlsan
    from victorialogs_tpu.storage import filterbank as fb
    san = vlsan.Sanitizer()
    san.begin_test()
    fb._bank_release([4096])         # release with no matching charge
    try:
        problems = san.sweep()
        assert any("bank" in p for p in problems), problems
    finally:
        assert fb._bank_try_charge(4096)   # restore the budget
    assert san.sweep() == []


def test_vlsan_detects_journal_imbalance():
    from tools.vlint import vlsan
    from victorialogs_tpu.obs import journal

    class _Sink:
        def must_add_rows(self, lr):
            pass

    san = vlsan.Sanitizer()
    san.begin_test()
    w = journal.JournalWriter(_Sink(), app="vlsan-test")
    try:
        ok, _ = w.check_balanced()
        assert ok
        w.accepted += 3                  # forge a torn counter
        problems = san.sweep()
        assert any("journal" in p for p in problems), problems
        w.accepted -= 3
    finally:
        w.close()
    assert san.sweep() == []


def test_vlsan_detects_sched_imbalance():
    from tools.vlint import vlsan
    from victorialogs_tpu import sched
    san = vlsan.Sanitizer()
    san.begin_test()
    scope = sched.device_slots(None, tenant="0:0")
    scope.__enter__()
    assert scope.try_acquire()
    try:
        problems = san.sweep()
        assert any("lease" in p for p in problems), problems
    finally:
        scope.__exit__(None, None, None)
    assert san.sweep() == []


def test_vlsan_kill_switch(monkeypatch):
    from tools.vlint import vlsan
    monkeypatch.setenv("VLSAN", "0")
    assert not vlsan.enabled()
    monkeypatch.delenv("VLSAN")
    assert vlsan.enabled()


# ---------------- post-review regressions ----------------

def test_journal_balance_survives_overflow_drops():
    """Queue-bound drops were never accepted — the invariant must hold
    through overflow, not just post-accept drops."""
    from victorialogs_tpu.obs import journal

    class _Sink:
        def must_add_rows(self, lr):
            pass

    w = journal.JournalWriter(_Sink(), max_queue=2, app="vlsan-test")
    try:
        for _ in range(5):
            w._on_event(1, "e", {})
        ok, detail = w.check_balanced()
        assert ok, detail
        assert w.stats()["dropped"] == 3     # public total unchanged
    finally:
        w.close()
    ok, detail = w.check_balanced()
    assert ok, detail


def test_scoped_run_preserves_cache(tmp_path):
    """A single-file run must not evict the rest of the repo's cache
    entries (only vanished files are pruned)."""
    for name in ("a.py", "b.py"):
        (tmp_path / name).write_text("x = 1\n")
    cache = str(tmp_path / "c.json")
    run_paths([str(tmp_path)], root=str(tmp_path), cache_path=cache)
    import json as _json
    with open(cache) as f:
        assert len(_json.load(f)["files"]) == 2
    run_paths([str(tmp_path / "a.py")], root=str(tmp_path),
              cache_path=cache)
    with open(cache) as f:
        kept = _json.load(f)["files"]
    assert set(kept) == {"a.py", "b.py"}
    (tmp_path / "b.py").unlink()
    run_paths([str(tmp_path / "a.py")], root=str(tmp_path),
              cache_path=cache)
    with open(cache) as f:
        assert set(_json.load(f)["files"]) == {"a.py"}


def test_explain_resolves_global_pass_fingerprint(tmp_path, capsys):
    """metric-double-roll / lock-order-cycle findings come from the
    cross-file passes — --explain must find their fingerprints too."""
    from tools.vlint.__main__ import main
    (tmp_path / "m.py").write_text(
        'def a(m):\n    m.inc("vl_queries_cancelled_total")\n\n\n'
        'def b(m):\n    m.inc("vl_queries_cancelled_total")\n')
    rc = main(["--json", "--no-baseline", "--no-cache", str(tmp_path)])
    import json as _json
    fnd = _json.loads(capsys.readouterr().out)["findings"]
    dbl = [f for f in fnd if f["checker"] == "metric-double-roll"]
    assert rc == 1 and dbl
    rc = main(["--explain", dbl[0]["fingerprint"], str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "metric-double-roll" in out and "registry.py" in out


def test_checker_module_map_covers_all_ids():
    """--explain cites the right checker source for every id the
    checkers can emit (the hygiene ids were once mis-keyed)."""
    from tools.vlint.core import checker_module_for
    for cid, mod in (("nondaemon-thread", "hygiene"),
                     ("broad-except", "hygiene"),
                     ("lock-order-cycle", "locks"),
                     ("jax-host-sync", "hotpath"),
                     ("per-row-emit", "hotpath"),
                     ("balance-double-release", "balance"),
                     ("callable-identity", "balance"),
                     ("metric-double-roll", "registry"),
                     ("env-registry", "registry"),
                     ("annotation-reason", "core"),
                     ("lock-blocking-deep", "effects"),
                     ("rpc-under-lock", "effects"),
                     ("hotpath-sync-deep", "effects"),
                     ("thread-lifecycle", "effects"),
                     ("wire-taint", "effects")):
        assert checker_module_for(cid) == mod, cid


# ---------------- v3 interprocedural graph passes ----------------
#
# The whole-program call graph (tools/vlint/callgraph.py) + effect
# propagation (tools/vlint/effects.py).  The first two tests pin the
# ISSUE acceptance fixtures: a >=3-call-deep transitive
# blocking-under-lock chain and a forged wire offset into frombuffer.

def test_lock_blocking_deep_three_deep_chain():
    """flush holds the lock and calls _compact -> _rewrite -> _settle
    -> time.sleep: blocking reachable at depth 3, crossing from the
    class into module helpers (which the per-file locks checker cannot
    see through)."""
    f = lint("""
        import threading
        import time


        def _settle():
            time.sleep(0.5)


        def _rewrite():
            _settle()


        class Store:
            def __init__(self):
                self._mu = threading.Lock()

            def flush(self):
                with self._mu:
                    self._compact()

            def _compact(self):
                _rewrite()
    """)
    deep = [x for x in f if x.checker == "lock-blocking-deep"]
    assert len(deep) == 1
    assert deep[0].symbol == "Store.flush"
    assert "Store._mu" in deep[0].message
    assert "depth 3" in deep[0].message
    assert "_rewrite -> _settle" in deep[0].message   # witness chain


def test_lock_blocking_deep_annotated():
    f = lint("""
        import threading
        import time


        def _settle():
            time.sleep(0.5)


        def _rewrite():
            _settle()


        class Store:
            def __init__(self):
                self._mu = threading.Lock()

            def flush(self):
                with self._mu:
                    # vlint: allow-lock-blocking-deep(bounded 0.5s settle)
                    self._compact()

            def _compact(self):
                _rewrite()
    """)
    assert not [x for x in f if x.checker == "lock-blocking-deep"]


def test_lock_blocking_deep_leaves_intraclass_to_locks():
    """A pure self.m() chain stays the per-file checker's finding —
    the graph pass must not double-report it."""
    f = lint("""
        import threading
        import time


        class Store:
            def __init__(self):
                self._mu = threading.Lock()

            def flush(self):
                with self._mu:
                    self._compact()

            def _compact(self):
                time.sleep(0.5)
    """)
    assert [x.checker for x in f] == ["lock-blocking-call"]


def test_rpc_under_lease_scope():
    """The ISSUE fixture: a scheduler dispatch lease held across a
    cluster RPC through a helper — a slow/partitioned peer now
    occupies a device slot for the full RPC deadline."""
    f = lint("""
        from . import netrobust
        from ..sched.scheduler import device_slots


        def _push(payload):
            return netrobust.request("POST", "http://n1/x", payload)


        def fan_out(payload):
            with device_slots(1):
                _push(payload)
    """, path="victorialogs_tpu/server/mod.py")
    rpc = [x for x in f if x.checker == "rpc-under-lock"]
    assert len(rpc) == 1
    assert rpc[0].symbol == "fan_out"
    assert "lease:device_slots" in rpc[0].message


def test_rpc_under_lock_direct_and_unheld_clean():
    held = lint("""
        import threading

        from . import netrobust


        class Agg:
            def __init__(self):
                self._mu = threading.Lock()

            def poll(self):
                with self._mu:
                    return netrobust.request("GET", "http://n1/x", None)
    """, path="victorialogs_tpu/server/mod.py")
    assert [x.checker for x in held] == ["rpc-under-lock"]
    free = lint("""
        from . import netrobust


        def _push(payload):
            return netrobust.request("POST", "http://n1/x", payload)


        def fan_out(payload):
            _push(payload)
    """, path="victorialogs_tpu/server/mod.py")
    assert not [x for x in free if x.checker == "rpc-under-lock"]


def test_thread_lifecycle_orphan_spawn():
    f = lint("""
        import threading


        def kick(fn):
            t = threading.Thread(target=fn)
            t.start()
    """)
    orphan = [x for x in f if x.checker == "thread-lifecycle"]
    assert len(orphan) == 1 and orphan[0].symbol == "kick"
    # joined / handed-off spawns are clean
    for tail in ("    t.join()\n", "    return t\n"):
        f = lint("import threading\n\n\ndef kick(fn):\n"
                 "    t = threading.Thread(target=fn)\n"
                 "    t.start()\n" + tail)
        assert not [x for x in f if x.checker == "thread-lifecycle"]


def test_thread_lifecycle_stored_thread_needs_owner_close():
    src = """
        import threading


        class Pump:
            def __init__(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                pass
    """
    f = lint(src)
    missing = [x for x in f if x.checker == "thread-lifecycle"]
    assert len(missing) == 1 and "self._t" in missing[0].message
    f = lint(textwrap.dedent(src) +
             "\n    def close(self):\n        self._t.join()\n")
    assert not [x for x in f if x.checker == "thread-lifecycle"]


def test_thread_lifecycle_shutdown_order():
    """The declared VLServer teardown order (PR 8): usage poller, then
    journal, then super().close() — any inversion is two findings here
    (each adjacent pair violated)."""
    f = lint("""
        class VLServer:
            def close(self):
                super().close()
                self.journal.close()
                self.clusterstats.close()
    """, path="victorialogs_tpu/server/app.py")
    order = [x for x in f if x.checker == "thread-lifecycle"]
    assert len(order) == 2
    assert all("shutdown order" in x.message for x in order)


def test_wire_taint_forged_offset_caught():
    """The ISSUE fixture: a wire-decoded offset flows into frombuffer
    with no dominating bounds guard — the PR 9/12 forged-frame class."""
    f = lint("""
        import struct

        import numpy as np


        def parse(buf):
            (off,) = struct.unpack_from("<I", buf, 0)
            return np.frombuffer(buf, np.uint8, 16, off)
    """, path="victorialogs_tpu/server/wire.py")
    taint = [x for x in f if x.checker == "wire-taint"]
    assert len(taint) == 1
    assert "off" in taint[0].message and "guard" in taint[0].message


def test_wire_taint_guarded_and_out_of_scope_clean():
    guarded = """
        import struct

        import numpy as np


        def parse(buf):
            (off,) = struct.unpack_from("<I", buf, 0)
            if off > len(buf) - 16:
                raise ValueError("forged offset")
            return np.frombuffer(buf, np.uint8, 16, off)
    """
    f = lint(guarded, path="victorialogs_tpu/server/wire.py")
    assert not [x for x in f if x.checker == "wire-taint"]
    # same unguarded flow OUTSIDE the wire-decode scope: not wire data
    f = lint("""
        import struct

        import numpy as np


        def parse(buf):
            (off,) = struct.unpack_from("<I", buf, 0)
            return np.frombuffer(buf, np.uint8, 16, off)
    """, path="victorialogs_tpu/tpu/mod.py")
    assert not [x for x in f if x.checker == "wire-taint"]


_GRAPH_A = ("import threading\n\nimport b\n\n\nclass S:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n\n"
            "    def flush(self):\n        with self._mu:\n"
            "            b.rewrite()\n")
_GRAPH_B = ("import time\n\n\ndef settle():\n    time.sleep(1.0)\n\n\n"
            "def rewrite():\n    settle()\n")


def test_graph_pass_parallel_matches_serial(tmp_path):
    """The graph pass runs once over merged summaries — worker count
    must not change its findings (cross-FILE chain on purpose)."""
    (tmp_path / "a.py").write_text(_GRAPH_A)
    (tmp_path / "b.py").write_text(_GRAPH_B)
    serial = run_paths([str(tmp_path)], root=str(tmp_path), jobs=1)
    para = run_paths([str(tmp_path)], root=str(tmp_path), jobs=2)
    assert [f.render() for f in serial] == [f.render() for f in para]
    assert any(f.checker == "lock-blocking-deep" for f in serial)


def test_graph_cache_unrelated_change_and_path_change(tmp_path):
    """Graph-pass cache key is the hash of ALL merged summaries: an
    edit to an unrelated file (same summary) reuses the cached graph
    findings; an edit to a function ON a reported path re-runs the
    graph and drops the finding."""
    (tmp_path / "a.py").write_text(_GRAPH_A)
    (tmp_path / "b.py").write_text(_GRAPH_B)
    (tmp_path / "c.py").write_text("x = 1\n")
    cache = str(tmp_path / "cache.json")
    first = run_paths([str(tmp_path)], root=str(tmp_path),
                      cache_path=cache)
    assert any(f.checker == "lock-blocking-deep" for f in first)
    import json as _json
    with open(cache) as fh:
        got = _json.load(fh)
    assert got.get("graph", {}).get("findings")
    # unrelated edit: summaries unchanged -> warm graph equivalence
    (tmp_path / "c.py").write_text("x = 2\n")
    warm = run_paths([str(tmp_path)], root=str(tmp_path),
                     cache_path=cache)
    assert [f.render() for f in first] == [f.render() for f in warm]
    # fix the blocking primitive: b.py is on the reported path
    (tmp_path / "b.py").write_text(
        "def settle():\n    return 1\n\n\ndef rewrite():\n"
        "    settle()\n")
    third = run_paths([str(tmp_path)], root=str(tmp_path),
                      cache_path=cache)
    assert not [f for f in third if f.checker == "lock-blocking-deep"]


def test_explain_resolves_graph_pass_fingerprint(tmp_path, capsys,
                                                 monkeypatch):
    """--explain must find fingerprints minted by the graph passes and
    cite tools/vlint/effects.py as the checker source."""
    from tools.vlint.__main__ import main
    (tmp_path / "a.py").write_text(_GRAPH_A)
    (tmp_path / "b.py").write_text(_GRAPH_B)
    monkeypatch.chdir(tmp_path)     # main() resolves modules from cwd
    rc = main(["--json", "--no-baseline", "--no-cache", "."])
    import json as _json
    fnd = _json.loads(capsys.readouterr().out)["findings"]
    deep = [f for f in fnd if f["checker"] == "lock-blocking-deep"]
    assert rc == 1 and deep
    rc = main(["--explain", deep[0]["fingerprint"], "."])
    out = capsys.readouterr().out
    assert rc == 0
    assert "lock-blocking-deep" in out
    assert "allow-lock-blocking-deep(" in out
    assert "tools/vlint/effects.py" in out


def test_balance_release_through_same_file_helper_clean():
    """The v3 see-through rule in balance.py: a finally that drains
    the pair via a same-file helper counts as a guaranteed release."""
    f = lint("""
        from victorialogs_tpu.storage.filterbank import (
            _bank_release, _bank_try_charge)


        def _drop(n):
            _bank_release([n])


        def load(n):
            if not _bank_try_charge(n):
                return None
            try:
                return object()
            finally:
                _drop(n)
    """, path="victorialogs_tpu/storage/mod.py")
    assert not [x for x in f if x.checker == "balance-unguarded-acquire"]
