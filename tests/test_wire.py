"""Typed-column cluster wire protocol (server/cluster.py wire format
"t1"): differential typed-vs-legacy frame suite (byte-identical final
NDJSON across query shapes incl. dict/const/_time/float columns,
restricted-field views, multibyte values), codec round trips incl.
invalid UTF-8 arenas, truncated/corrupted-frame IOError paths, and
mixed-version negotiation fallback (typed node + legacy frontend and
vice versa)."""

import http.client
import json
import os
import struct
import urllib.parse

import numpy as np
import pytest

from victorialogs_tpu.engine.block_result import (WIRE_CONST, WIRE_DICT,
                                                  WIRE_STR, WIRE_TIME,
                                                  BlockResult)
from victorialogs_tpu.engine.emit import ndjson_block, ndjson_block_py
from victorialogs_tpu.server import cluster
from victorialogs_tpu.storage.log_rows import LogRows, TenantID
from victorialogs_tpu.storage.storage import Storage
from victorialogs_tpu.utils import zstd as _zstd

NS = 1_000_000_000
T0 = 1_753_660_800_000_000_000  # 2025-07-28T00:00:00Z
TEN = TenantID(0, 0)


# ---------------- helpers ----------------

def _roundtrip(br: BlockResult) -> BlockResult:
    """Encode one block as a typed frame and decode it back."""
    f = cluster.write_typed_frame(br)
    n = struct.unpack(">I", f[:4])[0]
    payload = _zstd.decompress(f[4:4 + n], max_output_size=1 << 30)
    assert payload.startswith(cluster.TYPED_MAGIC)
    return cluster.decode_typed_frame(payload)


def _req(srv, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
    conn.request(method, path, body=body)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def _mk_server(path, **kw):
    from victorialogs_tpu.server.app import VLServer
    storage = Storage(str(path), retention_days=100000,
                      flush_interval=3600)
    return VLServer(storage, listen_addr="127.0.0.1", port=0, **kw)


@pytest.fixture(scope="module")
def cluster2(tmp_path_factory):
    """2 storage nodes + a scatter-gather frontend, seeded with every
    storage column encoding the wire must carry: string (multibyte,
    quotes, controls), dict, const (per-stream), uint, int64
    (negative), float, ISO8601, native _time."""
    base = tmp_path_factory.mktemp("wire")
    n1 = _mk_server(base / "n1")
    n2 = _mk_server(base / "n2")
    front = _mk_server(
        base / "front",
        storage_nodes=[f"http://127.0.0.1:{n1.port}",
                       f"http://127.0.0.1:{n2.port}"])
    rows = []
    for i in range(400):
        rows.append(json.dumps({
            "_time": T0 + i * 250_000_000,
            "_msg": f"msg {i} {'error' if i % 3 == 0 else 'ok'} "
                    f"é✓ \"q\" \t x{i % 11}",
            "app": f"app{i % 5}",                      # 5 streams
            "lvl": ["info", "warn", "error"][i % 3],   # dict column
            "dur": str(i % 97),                        # uint column
            "neg": str(-3 - i),                        # int64 column
            "score": str((i % 50) / 4),                # float column
            "iso": f"2025-07-28T00:00:{i % 60:02d}.250Z",  # iso8601
            "const_f": "same-everywhere",              # const column
        }, ensure_ascii=False))
    status, _ = _req(front, "POST",
                     "/insert/jsonline?_stream_fields=app",
                     body="\n".join(rows).encode())
    assert status == 200
    for n in (n1, n2):
        _req(n, "GET", "/internal/force_flush")
    yield front, n1, n2
    for s in (front, n1, n2):
        s.close()
        s.storage.close()


QUERY_SHAPES = [
    # rows incl. every typed column kind
    "*",
    "error",
    # dict/const/uint/int/float/iso columns under projection
    "* | fields _time, lvl, const_f, dur",
    "* | fields _msg, score, neg, iso",
    # restricted-field view with the block detached fields dropped
    "* | delete _stream, _stream_id",
    # pushed-down row-local transforms
    "* | copy lvl as level | fields _time, level",
    'lvl:error | extract " x<xn>" from _msg | fields _time, xn',
    # stats split (export/import state frames over the wire)
    "* | stats by (lvl) count() c, sum(dur) s",
    "* | stats by (app, lvl) count() c",
    "* | stats quantile(0.9, dur) p90, avg(score) m",
    # local sort + limit on the frontend over wire views
    "error | sort by (_time desc) | limit 17",
    # time-bucketed stats (hits shape)
    "* | stats by (_time:1m) count() hits",
]


def _fmt_frames(c: dict, fmt: str) -> int:
    """tx+rx frames of one format (in-process clusters count both
    directions in the same process-global registry)."""
    return c.get(f"tx_frames_{fmt}", 0) + c.get(f"rx_frames_{fmt}", 0)


def _query_front(front, qs, limit=0, extra=""):
    q = urllib.parse.quote(qs)
    status, data = _req(front, "GET",
                        f"/select/logsql/query?query={q}&limit={limit}"
                        f"{extra}")
    assert status == 200, data[:200]
    return data


# ---------------- differential: typed vs legacy, byte-identical -----

def test_differential_typed_vs_legacy(cluster2, monkeypatch):
    front, _n1, _n2 = cluster2
    for qs in QUERY_SHAPES:
        c0 = cluster.wire_counters()
        typed = _query_front(front, qs)
        c1 = cluster.wire_counters()
        # typed frames actually on the wire for this query
        assert _fmt_frames(c1, "typed") > _fmt_frames(c0, "typed"), qs

        monkeypatch.setenv("VL_WIRE_TYPED", "0")
        front.query_storage.wire_typed = cluster.wire_typed_enabled()
        try:
            legacy = _query_front(front, qs)
            c2 = cluster.wire_counters()
        finally:
            monkeypatch.delenv("VL_WIRE_TYPED")
            front.query_storage.wire_typed = cluster.wire_typed_enabled()
        # kill-switch restores legacy frames exactly: zero typed frames
        assert _fmt_frames(c2, "typed") == _fmt_frames(c1, "typed"), qs
        assert _fmt_frames(c2, "json") > _fmt_frames(c1, "json"), qs
        if "| sort" in qs:
            # frontend-local sort pins a total order: byte-identical
            assert typed == legacy, qs
        else:
            # scatter-gather interleaving across the two fetch threads
            # is nondeterministic run to run — the LINES must match
            # bit-exactly, their order may not (PR 3's hit-set
            # discipline)
            assert sorted(typed.splitlines()) == \
                sorted(legacy.splitlines()), qs
        assert typed.strip(), f"no rows for {qs!r}"


def test_differential_hits_facets_tail(cluster2, monkeypatch):
    """The dict-row consumers that moved onto columns (hits/facets)
    agree between wire formats too."""
    front, _n1, _n2 = cluster2
    q = urllib.parse.quote("*")
    paths = [
        f"/select/logsql/hits?query={q}&step=1m&field=lvl",
        f"/select/logsql/facets?query={q}&limit=5",
        f"/select/logsql/stats_query?query="
        f"{urllib.parse.quote('* | stats by (lvl) count() c')}"
        f"&time=2025-07-29T00:00:00Z",
    ]
    got_typed = [_req(front, "GET", p) for p in paths]
    monkeypatch.setenv("VL_WIRE_TYPED", "0")
    front.query_storage.wire_typed = cluster.wire_typed_enabled()
    try:
        got_legacy = [_req(front, "GET", p) for p in paths]
    finally:
        monkeypatch.delenv("VL_WIRE_TYPED")
        front.query_storage.wire_typed = cluster.wire_typed_enabled()
    for (st_t, d_t), (st_l, d_l), p in zip(got_typed, got_legacy, paths):
        assert st_t == st_l == 200, p
        assert _norm(json.loads(d_t)) == _norm(json.loads(d_l)), p


def _norm(obj):
    """Order-insensitive JSON view: scatter-gather arrival order (group
    emission, per-group timestamp appends) is nondeterministic run to
    run independently of the wire format — sort dict-lists and
    timestamp/value pairs so only CONTENT is compared."""
    if isinstance(obj, dict):
        o = {k: _norm(v) for k, v in obj.items()}
        if isinstance(o.get("timestamps"), list) and \
                isinstance(o.get("values"), list):
            pairs = sorted(zip(o["timestamps"], o["values"]))
            o["timestamps"] = [p[0] for p in pairs]
            o["values"] = [p[1] for p in pairs]
        return o
    if isinstance(obj, list):
        items = [_norm(x) for x in obj]
        if items and all(isinstance(x, dict) for x in items):
            return sorted(items,
                          key=lambda x: json.dumps(x, sort_keys=True))
        return items
    return obj


# ---------------- mixed-version negotiation ----------------

def test_legacy_frontend_typed_node(cluster2):
    """Old frontend (never sends wire=t1) against new nodes: nodes
    answer legacy JSON frames and the query completes."""
    front, _n1, _n2 = cluster2
    front.query_storage.wire_typed = False       # simulate old frontend
    try:
        c0 = cluster.wire_counters()
        data = _query_front(front, "error")
        c1 = cluster.wire_counters()
    finally:
        front.query_storage.wire_typed = cluster.wire_typed_enabled()
    assert data.strip()
    assert _fmt_frames(c1, "typed") == _fmt_frames(c0, "typed")
    assert _fmt_frames(c1, "json") > _fmt_frames(c0, "json")
    ref = _query_front(front, "error")
    assert sorted(data.splitlines()) == sorted(ref.splitlines())


def test_typed_frontend_legacy_node(cluster2, monkeypatch):
    """New frontend asking for typed frames against nodes that answer
    legacy JSON (simulated via the node-side kill-switch): per-frame
    format detection falls back, emits the journal wire_fallback event,
    and results stay identical."""
    from victorialogs_tpu.obs import events
    front, _n1, _n2 = cluster2
    ref = _query_front(front, "error")
    seen = []

    def sub(ts_ns, event, fields):
        if event == "wire_fallback":
            seen.append(dict(fields))
    events.subscribe(sub)
    # node side refuses typed (wire_typed_enabled() checked per request)
    # while the frontend keeps requesting it
    monkeypatch.setenv("VL_WIRE_TYPED", "0")
    assert front.query_storage.wire_typed     # frontend still asks
    try:
        c0 = cluster.wire_counters()
        data = _query_front(front, "error")
        c1 = cluster.wire_counters()
    finally:
        monkeypatch.delenv("VL_WIRE_TYPED")
        events.unsubscribe(sub)
    assert sorted(data.splitlines()) == sorted(ref.splitlines())
    assert _fmt_frames(c1, "typed") == _fmt_frames(c0, "typed")
    assert c1.get("fallbacks", 0) > c0.get("fallbacks", 0)
    assert seen and seen[0]["requested"] == cluster.WIRE_FORMAT


# ---------------- codec round trips ----------------

def test_codec_plain_columns_roundtrip():
    cols = {"_msg": ["héllo", "", 'q"uote', "x" * 300, "\x00\x1f tab\t"],
            "k": ["a", "b", "a", "", "c"]}
    br = BlockResult.from_columns(cols, timestamps=[5, 4, 3, 2, 1])
    br2 = _roundtrip(br)
    assert br2.nrows == 5
    assert br2.column_names() == ["_msg", "k"]
    assert br2.column("_msg") == cols["_msg"]
    assert br2.column("k") == cols["k"]
    assert br2.timestamps == [5, 4, 3, 2, 1]
    assert ndjson_block(br2) == ndjson_block_py(br)


def test_codec_storage_backed_typed_columns(tmp_path):
    """Every storage encoding crosses the wire in its typed shape and
    re-renders identically (dict codes, consts, int/uint/float, iso,
    native _time)."""
    s = Storage(str(tmp_path / "d"), retention_days=100000,
                flush_interval=3600)
    lr = LogRows(stream_fields=["app"])
    for i in range(64):
        lr.add(TEN, T0 + i * NS, [
            ("app", "web"),
            ("_msg", f"m{i} ünïcode ✓"),
            ("lvl", ["a", "b"][i % 2]),
            ("dur", str(i)),
            ("neg", str(-i - 1)),
            ("score", str(i / 4)),
            ("iso", f"2025-07-28T00:00:{i % 60:02d}.500Z"),
        ])
    s.must_add_rows(lr)
    s.debug_flush()
    from victorialogs_tpu.engine.searcher import run_query
    blocks = []
    run_query(s, [TEN], "*", write_block=blocks.append,
              timestamp=T0 + 3600 * NS)
    assert blocks
    try:
        for br in blocks:
            br2 = _roundtrip(br)
            # typed access survives the wire for the pipe fast paths
            dc = br2.dict_column("lvl")
            assert dc is not None and sorted(dc[1]) == ["a", "b"]
            assert br2.const_value("app") == "web"
            nums, is_int = br2.typed_numeric("dur")
            assert is_int and int(nums[0]) == 0
            assert br2.numeric_column("score") is not None
            assert br2.native_time_keys() is not None
            # and the rendered bytes are bit-identical to the local oracle
            assert ndjson_block(br2) == ndjson_block_py(br)
            assert br2.column("neg") == br.column("neg")
            assert br2.column("iso") == br.column("iso")
    finally:
        s.close()


def test_codec_restricted_view_and_filter_rows(tmp_path):
    s = Storage(str(tmp_path / "d"), retention_days=100000,
                flush_interval=3600)
    lr = LogRows(stream_fields=["app"])
    for i in range(32):
        lr.add(TEN, T0 + i * NS, [("app", "w"), ("_msg", f"m{i}"),
                                  ("dur", str(i))])
    s.must_add_rows(lr)
    s.debug_flush()
    from victorialogs_tpu.engine.searcher import run_query
    blocks = []
    run_query(s, [TEN], "* | fields _msg, dur",
              write_block=blocks.append, timestamp=T0 + 3600 * NS)
    try:
        for br in blocks:
            assert br._restrict is not None   # fields pipe kept the view
            br2 = _roundtrip(br)
            assert br2.column_names() == ["_msg", "dur"]
            assert ndjson_block(br2) == ndjson_block_py(br)
            # filter_rows on the wire view (frontend-local limit pipe)
            mask = np.zeros(br2.nrows, dtype=bool)
            mask[:5] = True
            small = br2.filter_rows(mask)
            assert small.nrows == 5
            assert ndjson_block(small) == \
                ndjson_block(br.filter_rows(mask))
            # restrict_fields on the wire view keeps typed backing
            proj = br2.restrict_fields(["dur"])
            assert proj._wire is not None
            assert proj.column_names() == ["dur"]
    finally:
        s.close()


def test_codec_invalid_utf8_arena_falls_back_identically():
    """A wire arena carrying invalid UTF-8 reaches the frontend as raw
    bytes; the native emit rejects it on BOTH sides, and the python
    fallback renders the same replacement chars the storage node's own
    decode would."""
    bad = b"ok \xff\xfe end"
    arena = np.frombuffer(bad, dtype=np.uint8)
    wcols = {"_msg": (WIRE_STR, arena,
                      np.array([0], dtype=np.int64),
                      np.array([len(bad)], dtype=np.int64))}
    br = BlockResult.from_wire(["_msg"], wcols, 1)
    out = ndjson_block(br)
    assert json.loads(out.decode()) == \
        {"_msg": bad.decode("utf-8", "replace")}
    # and the frame codec round-trips the raw bytes untouched
    br2 = _roundtrip(br)
    assert br2._wire["_msg"][1].tobytes() == bad


def test_codec_empty_block_and_empty_values():
    br = BlockResult(0)
    br2 = _roundtrip(br)
    assert br2.nrows == 0 and br2.column_names() == []
    br = BlockResult.from_columns({"a": ["", "", ""]})
    br2 = _roundtrip(br)
    assert br2.column("a") == ["", "", ""]
    assert ndjson_block(br2) == b"{}\n{}\n{}\n"


# ---------------- corrupted / truncated frames ----------------

def _typed_payload(br) -> bytes:
    f = cluster.write_typed_frame(br)
    n = struct.unpack(">I", f[:4])[0]
    return _zstd.decompress(f[4:4 + n], max_output_size=1 << 30)


def test_corrupted_frames_raise_ioerror():
    br = BlockResult.from_columns(
        {"a": ["xx", "yyy"], "b": ["1", "2"]}, timestamps=[1, 2])
    payload = _typed_payload(br)
    # truncation at every prefix length must raise IOError, never
    # crash with an unrelated exception or silently succeed
    for cut in range(len(cluster.TYPED_MAGIC), len(payload)):
        with pytest.raises(IOError):
            cluster.decode_typed_frame(payload[:cut])
    # trailing garbage
    with pytest.raises(IOError):
        cluster.decode_typed_frame(payload + b"junk")
    # unknown column kind
    mutated = bytearray(payload)
    # header: magic(5) + nrows(4) + ncols(2) + flags(1) + ts(16); the
    # first column record starts right after: namelen(2) + kind(1)
    kind_off = 5 + 7 + 16 + 2
    mutated[kind_off] = 250
    with pytest.raises(IOError):
        cluster.decode_typed_frame(bytes(mutated))


def test_str_slice_out_of_arena_bounds_raises():
    """Offsets/lengths pointing past the shipped arena must be
    rejected at decode — they would otherwise reach the native
    emitter's unchecked arena reads."""
    arena = np.frombuffer(b"tiny", dtype=np.uint8)
    br = BlockResult.from_wire(
        ["s"], {"s": (WIRE_STR, arena,
                      np.array([0x7fffffff], dtype=np.int64),
                      np.array([8], dtype=np.int64))}, 1)
    with pytest.raises(IOError):
        cluster.decode_typed_frame(_typed_payload_raw(br))
    # length overruns too, not just offsets
    br = BlockResult.from_wire(
        ["s"], {"s": (WIRE_STR, arena,
                      np.array([2], dtype=np.int64),
                      np.array([3], dtype=np.int64))}, 1)
    with pytest.raises(IOError):
        cluster.decode_typed_frame(_typed_payload_raw(br))


def _typed_payload_raw(br) -> bytes:
    """Encode WITHOUT the densify pass (write the wire tuples as-is)
    so corrupt offset/length vectors survive to the decoder."""
    import victorialogs_tpu.engine.block_result as brm
    orig = brm._dense_str_triple
    brm._dense_str_triple = lambda a, o, ln: (a, o, ln)
    try:
        return _typed_payload(br)
    finally:
        brm._dense_str_triple = orig


def test_iso_frac_width_out_of_range_raises():
    payload = bytearray(_typed_payload(BlockResult.from_wire(
        ["i"], {"i": (2, np.array([T0], dtype=np.int64), 3)}, 1)))
    # header(12) + namelen(2) + kind(1) + name("i",1) -> frac_w byte
    frac_off = 12 + 2 + 1 + 1
    assert payload[frac_off] == 3
    payload[frac_off] = 200
    with pytest.raises(IOError):
        cluster.decode_typed_frame(bytes(payload))


def test_dict_code_out_of_range_raises():
    codes = np.array([0, 5], dtype=np.uint8)   # 5 >= nvals
    br = BlockResult.from_wire(
        ["d"], {"d": (WIRE_DICT, codes, ["only"])}, 2)
    payload = _typed_payload(br)
    with pytest.raises(IOError):
        cluster.decode_typed_frame(payload)


def test_time_column_without_frame_ts_raises():
    br = BlockResult.from_wire(
        ["_time"], {"_time": (WIRE_TIME, np.array([1], dtype=np.int64))},
        1)                                     # no ts_np on purpose
    br._ts_np = None
    f = cluster.write_typed_frame(br)
    n = struct.unpack(">I", f[:4])[0]
    payload = _zstd.decompress(f[4:4 + n], max_output_size=1 << 30)
    with pytest.raises(IOError):
        cluster.decode_typed_frame(payload)


def test_truncated_stream_raises_ioerror(cluster2):
    """A storage node dying mid-stream surfaces as IOError (whole-query
    failure), for typed exactly like legacy."""
    import io
    br = BlockResult.from_columns({"a": ["x"]})
    frame = cluster.write_typed_frame(br)
    # frame announces more bytes than the stream holds
    stream = io.BytesIO(frame[:len(frame) - 3])
    with pytest.raises(IOError):
        list(cluster.read_frame_payloads(stream))


# ---------------- trace + metrics surface ----------------

def test_trace_carries_wire_attribution(cluster2):
    front, _n1, _n2 = cluster2
    data = _query_front(front, "error", extra="&trace=1")
    tree = json.loads(data.splitlines()[-1])["_trace"]

    def find(n, name, out):
        if n.get("name") == name:
            out.append(n)
        for c in n.get("children", ()):
            find(c, name, out)
    nodes: list = []
    find(tree, "storage_node", nodes)
    assert len(nodes) == 2
    for n in nodes:
        attrs = n["attrs"]
        assert attrs.get("typed_frames", 0) > 0
        assert attrs.get("wire_rx_bytes", 0) > 0
        assert "wire_decode_s" in attrs


def test_wire_metrics_on_metrics_endpoint(cluster2):
    front, n1, _n2 = cluster2
    _query_front(front, "error")
    _s, text = _req(front, "GET", "/metrics")
    body = text.decode()
    assert 'vl_wire_frames_total{dir="rx",fmt="typed"}' in body
    assert 'vl_wire_bytes_total{dir="rx",fmt="typed"}' in body
    assert 'vl_wire_bytes_total{dir="tx",fmt="json"}' in body
    m = [ln for ln in body.splitlines()
         if ln.startswith('vl_wire_frames_total{dir="rx",fmt="typed"}')]
    assert m and float(m[0].split()[-1]) > 0
