"""collapse_nums value-level table tests ported from the reference's
pipe_collapse_nums_test.go — the collapse and prettify rules must agree
exactly on the reference's own cases."""

import pytest

from victorialogs_tpu.logsql.pipes_aux import (collapse_nums,
                                               prettify_collapsed)

COLLAPSE_CASES = [
    ("", ""),
    ("foo", "foo"),
    ("ad", "ad"),
    ("abc", "abc"),
    ("deadbeef", "<N>"),
    ("a b c d e f ad be:eac,dead beef ab",
     "a b c d e f ad be:eac,<N> <N> ab"),
    ("ыва", "ыва"),
    ("0", "<N>"),
    ("1234567890", "<N>"),
    ("1foo", "1foo"),
    ("1 foo", "<N> foo"),
    ("a1foo2bar34", "a1foo2bar34"),
    ("a.1Zfoo.2Tbar:34", "a.<N>Zfoo.<N>Tbar:<N>"),
    ("ЫВА123bar45.78", "ЫВА123bar45.<N>"),
    ("ЫВА.123.bar.45.78", "ЫВА.<N>.bar.<N>.<N>"),
    ("1.23.45.67", "<N>.<N>.<N>.<N>"),
    ("2024-12-25T10:20:30Z foo", "<N>-<N>-<N>T<N>:<N>:<N>Z foo"),
    ("2024-12-25T10:20:30.123324+05:00 foo",
     "<N>-<N>-<N>T<N>:<N>:<N>.<N>+<N>:<N> foo"),
    ("release v1.2.3", "release v<N>.<N>.<N>"),
    ("2004-10-12T43:23:12Z abc:345", "<N>-<N>-<N>T<N>:<N>:<N>Z abc:<N>"),
    ("123.43s", "<N>.<N>s"),
    ("123ms 2us 3h5m6s43ms43μs324ns",
     "<N>ms <N>us <N>h<N>m<N>s<N>ms<N>μs<N>ns"),
    ("0x1234 0XFEAD12", "0x<N> 0X<N>"),
]


@pytest.mark.parametrize("inp,want", COLLAPSE_CASES,
                         ids=[c[0][:25] or "empty" for c in COLLAPSE_CASES])
def test_collapse_nums_reference_cases(inp, want):
    assert collapse_nums(inp) == want


PRETTIFY_CASES = [
    ("", ""),
    ("foo", "foo"),
    ("35.191.193.225:51648 - 2edfed59-3e98-4073-bbb2-28d321ca71a7 - - "
     "[2024/12/08 15:21:02] 10.71.20.32 GET /foo 200",
     "<IP4>:<N> - <UUID> - - [<DATETIME>] <IP4> GET /foo <N>"),
    ("E1208 15:21:02.748877 62 metric_reporter.go:182",
     "E1208 <TIME> <N> metric_reporter.go:<N>"),
    ("2024-12-08T15:22:32.342Z error exporterhelper/queued_retry.go:101",
     "<DATETIME> error exporterhelper/queued_retry.go:<N>"),
    ("2024-12-08 15:22:32Z error exporterhelper/queued_retry.go:101",
     "<DATETIME> error exporterhelper/queued_retry.go:<N>"),
    ("2024-12-08 15:22:32,123 error exporterhelper/queued_retry.go:101",
     "<DATETIME> error exporterhelper/queued_retry.go:<N>"),
    ("2024-12-08 15:22:32.123+10:30 error "
     "exporterhelper/queued_retry.go:101",
     "<DATETIME> error exporterhelper/queued_retry.go:<N>"),
    ("2024/12/08T15:22:32-10:30 error exporterhelper/queued_retry.go:101",
     "<DATETIME> error exporterhelper/queued_retry.go:<N>"),
]


@pytest.mark.parametrize("inp,want", PRETTIFY_CASES,
                         ids=[c[0][:25] or "empty" for c in PRETTIFY_CASES])
def test_prettify_reference_cases(inp, want):
    assert prettify_collapsed(collapse_nums(inp)) == want
