"""Sharded multi-threaded jsonline ingestion: results must be identical
to the serial path (as sets — shard interleaving changes arrival order
only), and errors must still surface as IngestError.

Reference: per-CPU rowsBuffer shards, lib/logstorage/datadb.go:667-747.
"""

import json

import pytest

from victorialogs_tpu.engine.searcher import run_query_collect
from victorialogs_tpu.server import vlinsert
from victorialogs_tpu.server.insertutil import (CommonParams,
                                                LogMessageProcessor)
from victorialogs_tpu.storage.log_rows import TenantID
from victorialogs_tpu.storage.storage import Storage

T0 = 1_753_660_800_000_000_000
TEN = TenantID(0, 0)


def _body(n):
    return ("\n".join(json.dumps({
        "_time": T0 + i * 1_000_000,
        "_msg": f"msg {i} " + ("x" * (i % 40)),
        "app": f"app{i % 5}",
        "level": "error" if i % 7 == 0 else "info",
    }) for i in range(n)) + "\n").encode()


def _ingest(tmp_path, name, body, threads, min_body=0, monkeypatch=None):
    if monkeypatch is not None:
        monkeypatch.setenv("VL_INGEST_THREADS", str(threads))
        if min_body:
            monkeypatch.setattr(vlinsert, "_MT_MIN_BODY", min_body)
    s = Storage(str(tmp_path / name), retention_days=100000,
                flush_interval=3600)
    cp = CommonParams(tenant=TEN, stream_fields=["app"])
    lmp = LogMessageProcessor(cp, s)
    n = vlinsert.handle_jsonline(cp, body, lmp)
    lmp.flush()
    s.debug_flush()
    return s, n


def _rows(s):
    out = run_query_collect(s, [TEN], "*")
    return sorted(json.dumps(r, sort_keys=True) for r in out)


def test_mt_matches_serial(tmp_path, monkeypatch):
    body = _body(20_000)
    s1, n1 = _ingest(tmp_path, "serial", body, 1)
    s2, n2 = _ingest(tmp_path, "mt", body, 8, min_body=1024,
                     monkeypatch=monkeypatch)
    try:
        assert n1 == n2 == 20_000
        assert _rows(s1) == _rows(s2)
    finally:
        s1.close()
        s2.close()


def test_mt_small_body_stays_serial(tmp_path, monkeypatch):
    monkeypatch.setenv("VL_INGEST_THREADS", "8")
    # default _MT_MIN_BODY is 8MB; a small body must not shard
    body = _body(100)
    s, n = _ingest(tmp_path, "small", body, 8)
    try:
        assert n == 100
        assert len(_rows(s)) == 100
    finally:
        s.close()


def test_mt_error_still_400_shape(tmp_path, monkeypatch):
    body = _body(30_000)[:-1] + b'\n{"_msg": tru\n'
    monkeypatch.setenv("VL_INGEST_THREADS", "4")
    monkeypatch.setattr(vlinsert, "_MT_MIN_BODY", 1024)
    s = Storage(str(tmp_path / "err"), retention_days=100000,
                flush_interval=3600)
    cp = CommonParams(tenant=TEN, stream_fields=["app"])
    lmp = LogMessageProcessor(cp, s)
    try:
        with pytest.raises(vlinsert.IngestError):
            vlinsert.handle_jsonline(cp, body, lmp)
    finally:
        s.close()
