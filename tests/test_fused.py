"""Single-dispatch fused filter|stats path (tpu/fused.py) vs the CPU
executor: bit-exact over adversarial tree shapes, with the residue
(maybe-row) machinery explicitly exercised.

The fused path's contract: same rows, same group keys, same aggregates
as the host executor for every query it accepts — and clean fallback
(still correct) for everything it declines."""

import numpy as np
import pytest

from victorialogs_tpu.engine.searcher import run_query_collect
from victorialogs_tpu.storage.log_rows import LogRows, TenantID
from victorialogs_tpu.storage.storage import Storage
from victorialogs_tpu.tpu.batch import BatchRunner

NS = 1_000_000_000
T0 = 1_753_660_800_000_000_000  # 2025-07-28T00:00:00Z
TEN = TenantID(0, 0)


@pytest.fixture(scope="module")
def storage(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("fused"))
    s = Storage(path, retention_days=100000, flush_interval=3600)
    lr = LogRows(stream_fields=["app"])
    words = ["deadline exceeded", "connection reset", "ok", "retry later",
             "cache miss", "flushed"]
    for i in range(9000):
        msg = f"GET /api/x{i % 71} {words[i % 6]} dur={i % 351}ms"
        if i % 37 == 0:
            # multibyte runes: len_range must route these through the
            # residue (code points != bytes)
            msg = f"GÉT /äpi/x{i % 71} {words[i % 6]} ⏱={i % 351}"
        if i % 97 == 0:
            # newline between the A..B literals: the ordered-pair scan
            # must route these rows through the host residue pass
            msg = f"GET /api\nlate {words[i % 6]} tail"
        fields = [
            ("app", f"app{i % 4}"),
            ("_msg", msg),
            ("lvl", ["info", "warn", "error"][i % 3]),   # dict column
            ("dur", str(i % 351)),                        # uint column
        ]
        lr.add(TEN, T0 + i * 200_000_000, fields)
    s.must_add_rows(lr)
    s.debug_flush()
    yield s
    s.close()


FUSED_QUERIES = [
    # plain scans, and/or/not trees
    '"deadline exceeded" | stats count() c',
    '"deadline exceeded" OR "connection reset" | stats count() c',
    'NOT "ok" | stats count() c',
    '("retry later" OR "cache miss") "GET" | stats count() c',
    'NOT ("ok" OR "retry later") | stats by (_time:5m) count() c',
    # time filter composes on device (inclusive-bound semantics)
    '_time:[2025-07-28T00:05:00Z, 2025-07-28T00:20:00Z] "deadline '
    'exceeded" | stats count() c',
    '_time:[2025-07-28T00:00:00Z, 2025-07-28T00:10:00Z] | stats '
    'by (_time:1m) count() c',
    # prefix / exact / contains / substring-regex leaves
    '_msg:"GET"* | stats count() c',
    # numeric-typed column scanned as text: stage_layout_column declines,
    # the unfused path answers (still bit-identical)
    'dur:13* | stats count() c',
    'lvl:exact("error") | stats by (_time:10m) count() c',
    'lvl:contains_any("warn", "error") | stats count() c',
    '_msg:~"deadline" | stats count() c',
    # ordered-pair regex incl. newline rows -> host residue partials
    '_msg:~"GET.*exceeded" | stats count() c',
    '_msg:~"GET.*tail" | stats count() c',                # only \n rows
    '_msg:~"GET.*exceeded" | stats by (_time:5m, app) count() c',
    '_msg:~"GET.*exceeded" | stats by (app) sum(dur) s, min(dur) mn, '
    'max(dur) mx, count_uniq(lvl) u',
    # dict-column scans (materialized into the fused matrix)
    'lvl:error | stats by (app) count() c',
    'NOT lvl:error "deadline exceeded" | stats count() c',
    # stream filters fold to constants / mask leaves
    '{app="app1"} | stats count() c',
    '{app=~"app[12]"} "deadline exceeded" | stats by (_time:5m) count() c',
    # value-column stats + group-by + uniq through one dispatch
    '"GET" | stats by (app, _time:10m) count() c, sum(dur) s',
    '* | stats count_uniq(app) u, count() c',
    # numeric range on the int column (device compare over uint32 offsets)
    'dur:>300 | stats count() c',
    'dur:range[100, 200] | stats by (app) count() c',
    'dur:<=5 "deadline exceeded" | stats count() c',
    'dur:>10000 | stats count() c',                      # empty range
    'NOT dur:>=175 | stats by (_time:10m) count() c',
    # in() = OR of exact scans (dict + string columns)
    'lvl:in(error, warn) | stats count() c',
    'app:in(app1, app3) "deadline exceeded" | stats count() c',
    'lvl:in() | stats count() c',                         # empty set
    # len_range: byte lengths decide ASCII rows; multibyte rows in the
    # ambiguous byte window route through residue
    '_msg:len_range(10, 30) | stats count() c',
    'NOT _msg:len_range(0, 25) | stats by (app) count() c',
    # value_type: block-uniform constant from the column encoding
    'dur:value_type(uint16) | stats count() c',
    'NOT dur:value_type(uint16) | stats by (app) count() c',
    'lvl:value_type(dict) "deadline exceeded" | stats count() c',
    # empty-ish matches
    'nosuchliteral42 | stats count() c',
    '_msg:"" | stats count() c',
    # sum_len/count_empty: derived uint32 columns through the standard
    # sum partials (code points, not bytes — the GÉT/⏱ rows check that)
    '* | stats sum_len(_msg) s, count_empty(_msg) e',
    '"deadline exceeded" | stats by (app) sum_len(_msg) s, count() c',
    '* | stats by (_time:10m) count_empty(lvl) e, sum_len(lvl) s',
    'NOT "ok" | stats sum_len(dur) s',         # int column digit count
    '* | stats count_empty(nosuchfield) e, sum_len(nosuchfield) s',
    'dur:>100 | stats by (app) count_empty(app) e, sum_len(app) s',
    # case-insensitive phrase/prefix: ASCII byte fold on device, rows
    # with multibyte bytes settled by the host residue
    'i("DEADLINE Exceeded") | stats count() c',
    'i("CONNECTION reset") OR i("CACHE Miss") | stats by (app) count() c',
    '_msg:i("GeT"*) | stats count() c',
    'NOT i("OK") | stats count() c',
    'lvl:i("ERROR") | stats by (_time:10m) count() c',
]


def _norm(rows):
    return sorted(tuple(sorted(r.items())) for r in rows)


def test_fused_parity_and_engagement(storage):
    runner = BatchRunner()
    engaged = 0
    for qs in FUSED_QUERIES:
        cpu = run_query_collect(storage, [TEN], qs, timestamp=T0)
        before = runner.fused_dispatches
        dev = run_query_collect(storage, [TEN], qs, timestamp=T0,
                                runner=runner)
        assert _norm(cpu) == _norm(dev), qs
        engaged += runner.fused_dispatches - before
    # most of the matrix must actually take the single-dispatch path
    assert engaged >= len(FUSED_QUERIES) // 2


def test_fused_residue_rows_are_settled(storage):
    """Newline rows flagged maybe by the pair kernel must contribute via
    the host residue: compare against CPU on a query whose ONLY hits are
    newline rows."""
    runner = BatchRunner()
    qs = '_msg:~"GET.*late" | stats count() c'
    cpu = run_query_collect(storage, [TEN], qs, timestamp=T0)
    before = runner.fused_dispatches
    dev = run_query_collect(storage, [TEN], qs, timestamp=T0,
                            runner=runner)
    assert runner.fused_dispatches > before
    assert cpu == dev
    assert int(cpu[0]["c"]) > 0  # the newline rows really match


def test_fused_declines_to_unfused_shapes(storage):
    """Non-fusable leaves (field-vs-field compare; non-ASCII any-case
    pattern) must fall back and still match the CPU executor."""
    runner = BatchRunner()
    for qs in ['lvl:eq_field(app) | stats count() c',
               'i("GÉT") | stats count() c']:
        cpu = run_query_collect(storage, [TEN], qs, timestamp=T0)
        before = runner.fused_dispatches
        dev = run_query_collect(storage, [TEN], qs, timestamp=T0,
                                runner=runner)
        assert runner.fused_dispatches == before, qs
        assert _norm(cpu) == _norm(dev), qs


def test_fused_any_case_unicode_divergence(tmp_path):
    """U+212A (KELVIN SIGN) lowercases to ASCII 'k': the device byte fold
    cannot see that match, so the row must reach the host residue and
    still count.  Pure-ASCII mixed-case rows are decided on device."""
    s = Storage(str(tmp_path), retention_days=100000, flush_interval=3600)
    lr = LogRows(stream_fields=["app"])
    bodies = ["TEMP 30K outside", "temp 30K inside", "Temp 30k mid",
              "cool 20c none"] * 500
    for i, b in enumerate(bodies):
        lr.add(TEN, T0 + i * NS, [("app", "a"), ("_msg", b)])
    s.must_add_rows(lr)
    s.debug_flush()
    try:
        runner = BatchRunner()
        for qs in ['i("30K") | stats count() c',
                   'i("TEMP 30k") | stats count() c',
                   'i("temp"*) | stats count() c']:
            cpu = run_query_collect(s, [TEN], qs, timestamp=T0)
            dev = run_query_collect(s, [TEN], qs, timestamp=T0,
                                    runner=runner)
            assert _norm(cpu) == _norm(dev), qs
        assert int(cpu[0]["c"]) == 1500  # all three temp variants match
        assert runner.fused_dispatches > 0
    finally:
        s.close()


def test_fused_row_queries_unaffected(storage):
    """Queries with row output (no stats pipe) keep the ordinary path."""
    runner = BatchRunner()
    qs = '"deadline exceeded" | fields _msg, app | limit 5'
    cpu = run_query_collect(storage, [TEN], qs, timestamp=T0)
    dev = run_query_collect(storage, [TEN], qs, timestamp=T0,
                            runner=runner)
    assert runner.fused_dispatches == 0
    assert _norm(cpu) == _norm(dev)


def test_fused_topk_parity(storage):
    """Device sort-topk prefilter: `<filter> | sort by (f) limit N` must
    return the SAME rows in the SAME order as the CPU path — including
    ties at the k-th boundary (broken by arrival order on both engines)
    and maybe rows (pair-regex newlines) verified on host."""
    runner = BatchRunner()
    queries = [
        '"GET" | sort by (dur desc) limit 7 | fields dur, app',
        'lvl:error | sort by (dur) limit 5 | fields dur, lvl',
        '* | sort by (dur desc) offset 3 limit 4 | fields dur',
        'dur:>340 | sort by (dur) limit 1000 | fields dur',  # k > matches
        '_msg:~"GET.*exceeded" | sort by (dur desc) limit 5 | fields dur',
        '"deadline exceeded" | sort by (dur) limit 3 rank as r '
        '| fields dur, r',
        # heavy boundary ties: every dur value repeats across apps
        'app:in(app1, app2) | sort by (dur desc) limit 9 | fields dur, app',
    ]
    engaged = 0
    for qs in queries:
        cpu = run_query_collect(storage, [TEN], qs, timestamp=T0)
        before = runner.topk_dispatches
        dev = run_query_collect(storage, [TEN], qs, timestamp=T0,
                                runner=runner)
        assert cpu == dev, qs          # exact rows, exact order
        engaged += runner.topk_dispatches - before
    assert engaged >= 5


def test_fused_topk_declines_cleanly(storage):
    """Shapes the topk prefilter must decline (string sort field,
    multi-field sort, partition_by) still match the CPU path through the
    ordinary device filter path."""
    runner = BatchRunner()
    for qs in ['* | sort by (lvl) limit 5 | fields lvl',
               '* | sort by (dur, app) limit 5 | fields dur, app',
               '* | sort by (dur) partition by (app) limit 2 '
               '| fields dur, app']:
        cpu = run_query_collect(storage, [TEN], qs, timestamp=T0)
        before = runner.topk_dispatches
        dev = run_query_collect(storage, [TEN], qs, timestamp=T0,
                                runner=runner)
        assert runner.topk_dispatches == before, qs
        assert _norm(cpu) == _norm(dev), qs


@pytest.fixture(scope="module")
def multipart_storage(tmp_path_factory):
    """The FUSED_QUERIES corpus spread over several small parts, so the
    async pipeline's window and small-part packing engage."""
    path = str(tmp_path_factory.mktemp("fusedmp"))
    s = Storage(path, retention_days=100000, flush_interval=3600)
    words = ["deadline exceeded", "connection reset", "ok", "retry later",
             "cache miss", "flushed"]
    n = 0
    for _pp in range(6):
        lr = LogRows(stream_fields=["app"])
        for _i in range(1500):
            i = n
            n += 1
            msg = f"GET /api/x{i % 71} {words[i % 6]} dur={i % 351}ms"
            if i % 37 == 0:
                msg = f"GÉT /äpi/x{i % 71} {words[i % 6]} ⏱={i % 351}"
            if i % 97 == 0:
                msg = f"GET /api\nlate {words[i % 6]} tail"
            lr.add(TEN, T0 + i * 200_000_000, [
                ("app", f"app{i % 4}"),
                ("_msg", msg),
                ("lvl", ["info", "warn", "error"][i % 3]),
                ("dur", str(i % 351)),
            ])
        s.must_add_rows(lr)
        s.debug_flush()
    yield s
    s.close()


@pytest.mark.parametrize("inflight,pack",
                         [("1", "1"), ("4", "1"), ("1", "8"), ("4", "8")])
def test_fused_parity_windowed_and_packed(multipart_storage, monkeypatch,
                                          inflight, pack):
    """The fused parity matrix re-run through the async pipeline over
    MANY small parts, at every window/packing config (tpu/pipeline.py):
    window depth and super-dispatch packing must be invisible in the
    results — residue rows, dict axes and value stats included."""
    monkeypatch.setenv("VL_INFLIGHT", inflight)
    monkeypatch.setenv("VL_PACK_PARTS", pack)
    runner = BatchRunner()
    for qs in FUSED_QUERIES[::3]:   # every 3rd query: runtime-bounded
        cpu = run_query_collect(multipart_storage, [TEN], qs,
                                timestamp=T0)
        dev = run_query_collect(multipart_storage, [TEN], qs,
                                timestamp=T0, runner=runner)
        assert _norm(cpu) == _norm(dev), (qs, inflight, pack)
    if pack != "1":
        assert runner.packed_dispatches > 0


def test_fused_truncation_overflow(tmp_path):
    """Values beyond MAX_ROW_WIDTH are truncated in staging; phrases
    hitting the truncated tail must be settled by the residue pass."""
    from victorialogs_tpu.tpu.layout import MAX_ROW_WIDTH
    s = Storage(str(tmp_path), retention_days=100000, flush_interval=3600)
    lr = LogRows(stream_fields=["app"])
    for i in range(4000):
        body = "x" * (MAX_ROW_WIDTH + 50) + " needle77" if i % 11 == 0 \
            else f"short {i}"
        lr.add(TEN, T0 + i * NS, [("app", "a"), ("_msg", body)])
    s.must_add_rows(lr)
    s.debug_flush()
    try:
        runner = BatchRunner()
        for qs in ['needle77 | stats count() c',
                   '"x" OR needle77 | stats by (_time:10m) count() c']:
            cpu = run_query_collect(s, [TEN], qs, timestamp=T0)
            dev = run_query_collect(s, [TEN], qs, timestamp=T0,
                                    runner=runner)
            assert _norm(cpu) == _norm(dev), qs
        assert int(cpu[0]["c"]) > 0
    finally:
        s.close()
