"""Cost-gate tests: the device-vs-host routing decision itself.

The rest of the suite pins VL_COST_FORCE=device so kernel parity stays
exercised on the fast-RTT CPU backend; THIS module is the dedicated
coverage the conftest comment refers to (verdict r4 weak #2).  It
exercises CostModel.prefer_host directly, the force overrides, the EWMA
feeders, the compile-timing discard, and end-to-end routing with the
force unset — asserting bit-identical results either way.

Reference analogue: the Go engine pays no per-query offload floor
(lib/logstorage/storage_search.go:1035-1067), so this gate is what makes
"device by default" safe on every query shape.
"""

import random

import numpy as np
import pytest

from victorialogs_tpu.engine.searcher import run_query_collect
from victorialogs_tpu.storage.log_rows import LogRows, TenantID
from victorialogs_tpu.storage.storage import Storage
from victorialogs_tpu.tpu.batch import BatchRunner, CostModel

NS = 1_000_000_000
T0 = 1_753_660_800_000_000_000
TEN = TenantID(0, 0)


def _model(rtt=0.065, dev_gbps=20.0, host_mrows=12.0):
    m = CostModel()
    m.force = ""
    m.rtt = rtt
    m.dev_bytes_per_s = dev_gbps * 1e9
    m.host_rows_per_s = host_mrows * 1e6
    return m


# ---------------- unit: prefer_host routings ----------------

def test_tiny_part_routes_to_host():
    m = _model()
    # 1k rows: host needs ~83us, device pays a 65ms RTT floor
    assert m.prefer_host(1000, 1000 * 128, 1, 0) is True


def test_large_part_routes_to_device():
    m = _model()
    # 4M rows: host ~333ms; device 65ms RTT + ~26ms scan
    assert m.prefer_host(4_000_000, 4_000_000 * 128, 1, 0) is False


def test_many_dispatches_push_to_host():
    m = _model()
    # same 4M rows but 10 leaf dispatches → 650ms of RTT alone
    assert m.prefer_host(4_000_000, 4_000_000 * 128, 10, 0) is True


def test_cold_staging_cost_counts():
    m = _model(rtt=0.0, dev_gbps=1000.0)
    m.upload_bytes_per_s = 1e9
    rows = 1_000_000          # host ~83ms
    cold = 2_000_000_000      # 2GB cold upload, amortized 0.25 → 500ms
    assert m.prefer_host(rows, rows * 128, 1, cold) is True
    assert m.prefer_host(rows, rows * 128, 1, 0) is False


def test_zero_dispatch_is_host():
    assert _model().prefer_host(10_000_000, 0, 0, 0) is True


def test_force_overrides():
    m = _model()
    m.force = "device"
    assert m.prefer_host(1, 1, 100, 10**12) is False
    m.force = "host"
    assert m.prefer_host(10**9, 10**9, 1, 0) is True


def test_fast_local_rtt_prefers_device_on_medium_parts():
    # on a local backend (sub-ms RTT) even ~200k-row parts win on device
    m = _model(rtt=0.0005)
    assert m.prefer_host(200_000, 200_000 * 128, 1, 0) is False


# ---------------- unit: EWMA feeders ----------------

def test_host_ewma_converges():
    m = _model(host_mrows=12.0)
    for _ in range(30):
        m.observe_host_scan(1_000_000, 1 / 50.0)   # 50M rows/s observed
    assert m.host_rows_per_s == pytest.approx(50e6, rel=0.05)


def test_host_ewma_ignores_tiny_samples():
    m = _model(host_mrows=12.0)
    m.observe_host_scan(100, 1e-9)                 # absurd rate, 100 rows
    assert m.host_rows_per_s == 12e6


def test_device_ewma_subtracts_rtt():
    m = _model(rtt=0.010)
    m.dev_bytes_per_s = None
    # 100MB in 110ms wall = 100ms compute after the 10ms RTT → 1 GB/s
    m.observe_device_scan(100_000_000, 0.110)
    assert m.dev_bytes_per_s == pytest.approx(1e9, rel=0.05)
    # second observation EWMA-blends (0.7*1e9 + 0.3*2e9)
    m.observe_device_scan(100_000_000, 0.060)
    assert m.dev_bytes_per_s == pytest.approx(1.3e9, rel=0.05)


def test_device_ewma_measures_rtt_lazily():
    # ADVICE r4: when prefer_host hasn't run yet, rtt must be measured
    # inside observe_device_scan rather than staying None (which
    # attributed the whole round trip to compute)
    m = CostModel()
    m.force = ""
    assert m.rtt is None
    m.observe_device_scan(50_000_000, 0.050)
    assert m.rtt is not None          # measured on the CPU backend
    assert m.dev_bytes_per_s is not None


def test_forced_runner_skips_ewma_and_probe():
    # the mesh runner pins force=device and never consults the estimate;
    # observe_device_scan must not pay the RTT probe to feed it
    m = CostModel()
    m.force = "device"
    m.observe_device_scan(50_000_000, 0.050)
    assert m.rtt is None
    assert m.dev_bytes_per_s is None


def test_drop_in_rate_flips_decision():
    # a deliberately-poisoned device rate must flip routing to host —
    # guards against sign errors in est_dev (verdict r4 "done" bar)
    m = _model(rtt=0.001)
    assert m.prefer_host(1_000_000, 1_000_000 * 128, 1, 0) is False
    m.dev_bytes_per_s = 1e6           # 1 MB/s: compile-poisoned
    assert m.prefer_host(1_000_000, 1_000_000 * 128, 1, 0) is True


# ---------------- integration: routing with the force unset ----------------

@pytest.fixture(scope="module")
def storage(tmp_path_factory):
    random.seed(7)
    s = Storage(str(tmp_path_factory.mktemp("coststore")),
                retention_days=100000, flush_interval=3600)
    lr = LogRows(stream_fields=["app"])
    words = ["alpha", "beta", "error", "GET", "timeout"]
    for i in range(4000):
        msg = " ".join(random.choice(words) for _ in range(6))
        lr.add(TEN, T0 + i * NS, [("app", f"app{i % 2}"),
                                  ("_msg", msg)])
    s.must_add_rows(lr)
    s.debug_flush()
    yield s
    s.close()


def _hits(storage, q, runner=None):
    rows = run_query_collect(storage, [TEN], q, runner=runner)
    return sorted(r.get("_time", "") + "|" + r.get("_msg", "")
                  for r in rows)


def test_forced_host_is_bit_identical(storage, monkeypatch):
    monkeypatch.setenv("VL_COST_FORCE", "host")
    runner = BatchRunner()
    assert runner.cost.force == "host"
    for q in ["error", '"error GET"', "error or timeout", "!alpha"]:
        assert _hits(storage, q, runner) == _hits(storage, q)
    assert runner.device_calls == 0
    assert runner.gated_host_parts > 0


def test_unforced_gate_routes_tiny_parts_to_host(storage, monkeypatch):
    monkeypatch.setenv("VL_COST_FORCE", "")
    monkeypatch.setenv("VL_COST_RTT_MS", "65")       # axon-tunnel RTT
    runner = BatchRunner()
    assert runner.cost.force == ""
    got = _hits(storage, "error", runner)
    assert got == _hits(storage, "error")
    # 4k-row parts can never beat a 65ms dispatch floor
    assert runner.device_calls == 0
    assert runner.gated_host_parts > 0


def test_unforced_gate_routes_to_device_when_cheap(storage, monkeypatch):
    monkeypatch.setenv("VL_COST_FORCE", "")
    monkeypatch.setenv("VL_COST_RTT_MS", "0")
    monkeypatch.setenv("VL_COST_DEV_GBPS", "1000")
    monkeypatch.setenv("VL_COST_HOST_MROWS", "0.001")  # pretend-slow host
    runner = BatchRunner()
    got = _hits(storage, "error", runner)
    assert got == _hits(storage, "error")
    assert runner.device_calls > 0
    assert runner.gated_host_parts == 0


def test_first_scan_timing_is_discarded(storage, monkeypatch):
    # ADVICE r4: the first call of a jit signature includes compilation;
    # it must NOT seed dev_bytes_per_s.  The EWMA is fed by the per-leaf
    # scan path — pin it on (row queries default to the fused filter
    # dispatch since the async pipeline round, which never calls _scan)
    monkeypatch.setenv("VL_FUSED_FILTER", "0")
    monkeypatch.setenv("VL_COST_FORCE", "")
    monkeypatch.setenv("VL_COST_RTT_MS", "0")
    monkeypatch.setenv("VL_COST_HOST_MROWS", "0.001")  # route to device
    runner = BatchRunner()
    assert runner.cost.dev_bytes_per_s is None
    _hits(storage, "timeout", runner)
    first_sigs = set(runner._scan_sigs)
    assert first_sigs                         # a scan dispatched
    assert runner.cost.dev_bytes_per_s is None  # first timing discarded
    _hits(storage, "timeout", runner)         # same signature, warm now
    assert runner.cost.dev_bytes_per_s is not None


def test_prefetch_gate_matches_eval_gate(tmp_path, monkeypatch):
    # ADVICE r4: prefetch used (n_dispatch=1, cold=0) while run_part
    # accounted both — they now share _gate_host_est by construction;
    # drive submit_prefetch DIRECTLY on a real part and assert the
    # shared estimator is consulted and declines staging (65ms RTT,
    # tiny part), exactly like the eval-side gate
    from victorialogs_tpu.logsql.parser import parse_query

    monkeypatch.setenv("VL_COST_FORCE", "")
    monkeypatch.setenv("VL_COST_RTT_MS", "65")
    s = Storage(str(tmp_path / "pfstore"), retention_days=100000,
                flush_interval=3600)
    try:
        for half in range(2):          # two flush cycles -> two parts
            lr = LogRows(stream_fields=["app"])
            for i in range(2000):
                lr.add(TEN, T0 + (half * 2000 + i) * NS,
                       [("app", "a"), ("_msg", f"error alpha {i}")])
            s.must_add_rows(lr)
            s.debug_flush()
        parts = [p for pt in s.partitions.values()
                 for p in pt.ddb.snapshot_parts()]
        assert len(parts) >= 2        # prefetch only fires with a next part
        runner = BatchRunner()
        calls = []
        orig = runner._gate_host_est

        def spy(f, part, cand_rows, stats_rows=0):
            r = orig(f, part, cand_rows, stats_rows=stats_rows)
            calls.append((cand_rows, stats_rows, r))
            return r

        monkeypatch.setattr(runner, "_gate_host_est", spy)
        q = parse_query("error")
        runner.submit_prefetch(parts[1], q.filter, None, cand_bis=None)
        runner._prefetcher().shutdown(wait=True)   # drain the worker
        runner._prefetch_pool = None               # fresh pool for queries
        assert calls, "submit_prefetch did not consult _gate_host_est"
        assert all(r is True for *_, r in calls)
        # the gate declined, so nothing was staged for that part
        assert not runner.cache.contains((parts[1].uid, "_msg"))
        # eval side agrees bit-for-bit on the same decision inputs
        got = run_query_collect(s, [TEN], "error", runner=runner)
        assert len(got) == 4000
        assert runner.device_calls == 0
        assert runner.gated_host_parts > 0
    finally:
        s.close()
