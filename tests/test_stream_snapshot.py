"""Stream-index snapshot levels (mergeset-style): tail flush at close,
bulk reopen, multi-level query merging, crash safety."""

import os

import pytest

from victorialogs_tpu.storage.indexdb import (MANIFEST_FILENAME,
                                              SNAPSHOT_FILENAME, IndexDB,
                                              SNAPSHOT_MIN_TAIL)


def _snap_paths(d):
    import json
    with open(os.path.join(d, MANIFEST_FILENAME)) as f:
        return [os.path.join(d, fn) for fn in json.load(f)["files"]]
from victorialogs_tpu.storage.log_rows import StreamID, TenantID
from victorialogs_tpu.storage.stream_filter import StreamFilter, TagFilter


def _sf(label, op, value):
    return StreamFilter(((TagFilter(label, op, value),),))
from victorialogs_tpu.utils.hashing import stream_id_hash

TEN = TenantID(0, 0)
TEN2 = TenantID(1, 0)


def _mk(i, tenant=TEN):
    tags = f'{{app="app{i % 37}",host="h{i}",dc="dc{i % 3}"}}'
    hi, lo = stream_id_hash(f"{tenant}:{tags}".encode())
    return StreamID(tenant, hi, lo), tags


def _fill(db, n, tenant=TEN):
    batch = [_mk(i, tenant) for i in range(n)]
    db.must_register_streams(batch)
    return batch


def test_snapshot_written_at_close_and_reopened(tmp_path):
    d = str(tmp_path / "idb")
    db = IndexDB(d)
    n = SNAPSHOT_MIN_TAIL + 500
    _fill(db, n)
    assert db.num_streams() == n
    db.close()
    paths = _snap_paths(d)
    assert paths and all(os.path.exists(p) for p in paths)

    db2 = IndexDB(d)
    assert db2.num_streams() == n
    assert len(db2._streams) == 0  # everything lives in the snapshot
    ids = db2.search_stream_ids([TEN], _sf("app", "=", "app7"))
    assert len(ids) == len([i for i in range(n) if i % 37 == 7])
    sid, tags = _mk(123)
    assert db2.get_stream_tags(sid) == tags
    assert db2.has_stream_id(sid)
    db2.close()


def test_snapshot_plus_tail_queries_merge(tmp_path):
    d = str(tmp_path / "idb")
    db = IndexDB(d)
    _fill(db, SNAPSHOT_MIN_TAIL)
    db.close()

    db2 = IndexDB(d)
    # tail registrations on top of the snapshot
    extra = [_mk(10_000_000 + i) for i in range(50)]
    db2.must_register_streams(extra)
    got = db2.search_stream_ids([TEN], _sf("app", "=", "app0"))
    expect_snap = len([i for i in range(SNAPSHOT_MIN_TAIL) if i % 37 == 0])
    expect_tail = len([i for i in range(50) if (10_000_000 + i) % 37 == 0])
    assert len(got) == expect_snap + expect_tail
    # negation crosses both levels
    neg = db2.search_stream_ids([TEN],
                                _sf("app", "!=", "app0"))
    assert len(neg) == SNAPSHOT_MIN_TAIL + 50 - len(got)
    # regex crosses both levels
    rx = db2.search_stream_ids([TEN],
                               _sf("dc", "=~", "dc[01]"))
    total = SNAPSHOT_MIN_TAIL + 50
    expect_rx = len([i for i in range(SNAPSHOT_MIN_TAIL) if i % 3 != 2]) \
        + len([i for i in range(50) if (10_000_000 + i) % 3 != 2])
    assert len(rx) == expect_rx
    assert len(db2.all_stream_ids([TEN])) == total
    db2.close()


def test_torn_snapshot_falls_back_to_log_replay(tmp_path):
    d = str(tmp_path / "idb")
    db = IndexDB(d)
    _fill(db, SNAPSHOT_MIN_TAIL)
    db.close()
    snap = _snap_paths(d)[0]
    with open(snap, "r+b") as f:
        f.truncate(os.path.getsize(snap) // 2)
    db2 = IndexDB(d)
    assert db2.num_streams() == SNAPSHOT_MIN_TAIL
    ids = db2.search_stream_ids([TEN], _sf("app", "=", "app3"))
    assert len(ids) == len(
        [i for i in range(SNAPSHOT_MIN_TAIL) if i % 37 == 3])
    db2.close()


def test_multi_tenant_snapshot(tmp_path):
    d = str(tmp_path / "idb")
    db = IndexDB(d)
    _fill(db, SNAPSHOT_MIN_TAIL // 2, TEN)
    _fill(db, SNAPSHOT_MIN_TAIL // 2 + 10, TEN2)
    db.close()
    db2 = IndexDB(d)
    assert len(db2.all_stream_ids([TEN])) == SNAPSHOT_MIN_TAIL // 2
    assert len(db2.all_stream_ids([TEN2])) == SNAPSHOT_MIN_TAIL // 2 + 10
    a = db2.search_stream_ids([TEN], _sf("app", "=", "app1"))
    b = db2.search_stream_ids([TEN2], _sf("app", "=", "app1"))
    assert a and b and set(a).isdisjoint(b)
    db2.close()


def test_reopen_compacts_large_replayed_tail(tmp_path):
    """A crash before close leaves only the log; the NEXT open replays it
    once, writes the snapshot immediately, and the open after that is a
    bulk load."""
    d = str(tmp_path / "idb")
    db = IndexDB(d)
    _fill(db, SNAPSHOT_MIN_TAIL + 100)
    db._file.flush()
    os.fsync(db._file.fileno())
    # simulate crash: no close() -> no snapshot level yet
    assert not os.path.exists(os.path.join(d, MANIFEST_FILENAME))
    db2 = IndexDB(d)  # replays, then self-flushes a level
    assert _snap_paths(d)
    assert db2.num_streams() == SNAPSHOT_MIN_TAIL + 100
    assert len(db2._streams) == 0
    db2.close()


def test_background_compaction_under_load(tmp_path, monkeypatch):
    """Live tail compaction: streams registered DURING the background
    merge survive, nothing is lost or duplicated, queries stay correct."""
    import threading
    import time

    from victorialogs_tpu.storage import indexdb as idb_mod
    from victorialogs_tpu.storage import stream_snapshot as snap_mod

    monkeypatch.setattr(idb_mod, "COMPACT_TAIL_STREAMS", 400)

    slow_gate = threading.Event()
    orig_write = snap_mod.write_snapshot

    def slow_write(path, streams, log_offset):
        slow_gate.wait(5)  # hold the flush open while we keep registering
        return orig_write(path, streams, log_offset)
    monkeypatch.setattr(idb_mod, "write_snapshot", slow_write)

    d = str(tmp_path / "idb")
    db = IndexDB(d)
    _fill(db, 400)  # hits the threshold -> background compaction starts
    t = db._compact_thread
    assert t is not None and t.is_alive()
    # register MORE while the compaction is writing
    extra = [_mk(20_000_000 + i) for i in range(120)]
    db.must_register_streams(extra)
    slow_gate.set()
    t.join(10)
    assert not t.is_alive()
    assert db.num_streams() == 520
    # tail kept exactly the mid-compaction registrations
    assert len(db._streams) == 120
    ids = db.search_stream_ids([TEN], _sf("app", "=", "app0"))
    expect = len([i for i in range(400) if i % 37 == 0]) + \
        len([i for i in range(120) if (20_000_000 + i) % 37 == 0])
    assert len(ids) == expect
    assert len(set(ids)) == len(ids)
    db.close()
    # reopen sees everything
    db2 = IndexDB(d)
    assert db2.num_streams() == 520
    db2.close()


def test_stale_query_does_not_poison_cache(tmp_path, monkeypatch):
    """search_stream_ids evaluates the snapshot OUTSIDE the lock; a
    registration landing in that window must keep the stale result out
    of the filter cache (generation guard)."""
    d = str(tmp_path / "idb")
    db = IndexDB(d)
    _fill(db, SNAPSHOT_MIN_TAIL)  # ensure a snapshot level exists
    db.close()
    db = IndexDB(d)

    app = 999_999 % 37
    sid, tags = _mk(999_999)
    sf = _sf("app", "=", f"app{app}")

    # register a matching stream DURING phase 2 (deterministic race):
    # streams_at runs unlocked right before the final cache put
    orig = type(db._snaps[0]).streams_at
    fired = []

    def racing_streams_at(self, idxs):
        if not fired:
            fired.append(1)
            db.must_register_streams([(sid, tags)])
        return orig(self, idxs)
    monkeypatch.setattr(type(db._snaps[0]), "streams_at",
                        racing_streams_at)

    stale = db.search_stream_ids([TEN], sf)
    assert sid not in stale          # raced query: allowed to miss it
    monkeypatch.setattr(type(db._snaps[0]), "streams_at", orig)
    fresh = db.search_stream_ids([TEN], sf)
    assert sid in fresh              # but it must NOT have been cached
    db.close()


def test_torn_log_tail_does_not_eat_next_registration(tmp_path):
    """A crash-torn final log line must not merge with the first
    post-restart append (which would silently drop that registration on
    the NEXT reopen)."""
    d = str(tmp_path / "idb")
    db = IndexDB(d)
    _fill(db, 20)
    db.close()
    log = os.path.join(d, "streams.jsonl")
    with open(log, "ab") as f:   # simulate a torn trailing write
        f.write(b'{"a":0,"p":0,"h":1,"l":2,"t":"{ap')

    db2 = IndexDB(d)
    assert db2.num_streams() == 20  # torn record ignored
    sid, tags = _mk(555_555)
    db2.must_register_streams([(sid, tags)])
    db2.close()

    db3 = IndexDB(d)
    assert db3.has_stream_id(sid)   # survived the torn tail
    assert db3.num_streams() == 21
    db3.close()


def test_merge_adds_tenant_between_existing(tmp_path):
    """Array-level merge: a tail tenant sorting BETWEEN existing tenants
    must keep the snapshot's sorted-t_idx invariant (searchsorted
    tenant bounds) — regression for silent lookup corruption."""
    d = str(tmp_path / "idb")
    db = IndexDB(d)
    _fill(db, SNAPSHOT_MIN_TAIL // 2, TenantID(1, 0))
    _fill(db, SNAPSHOT_MIN_TAIL // 2 + 7, TenantID(9, 0))
    db.close()

    db2 = IndexDB(d)
    mid = TenantID(5, 0)
    extra = [_mk(30_000_000 + i, mid) for i in range(200)]
    db2.must_register_streams(extra)
    with db2._lock:
        db2._flush_tail_locked()      # new level with the mid tenant
    db2.force_merge()                 # k-way merge across the levels
    db2.close()

    db3 = IndexDB(d)
    assert len(db3._streams) == 0  # all three tenants in the snapshot
    assert len(db3.all_stream_ids([TenantID(1, 0)])) == \
        SNAPSHOT_MIN_TAIL // 2
    assert len(db3.all_stream_ids([mid])) == 200
    assert len(db3.all_stream_ids([TenantID(9, 0)])) == \
        SNAPSHOT_MIN_TAIL // 2 + 7
    for sid, tags in extra[:5]:
        assert db3.has_stream_id(sid)
        assert db3.get_stream_tags(sid) == tags
    got = db3.search_stream_ids([TenantID(9, 0)], _sf("app", "=", "app1"))
    assert len(got) == len([i for i in range(SNAPSHOT_MIN_TAIL // 2 + 7)
                            if i % 37 == 1])
    db3.close()
