"""Ingest-path observability (obs/ingestledger.py): the
row-conservation ledger, per-hop batch tracing, /insert/status, spool
and queue depth gauges, freshness histograms, the idle-quiesce
recursion guard, and the vlint drop-discipline checker.

The cross-process acceptance round (stalled batches visible during an
outage, exact cluster-wide balance after the drain) lives in
tests/test_chaos.py; this module pins the in-process semantics."""

import json
import time
import urllib.request

import pytest

from victorialogs_tpu.obs import events, hist, ingestledger
from victorialogs_tpu.storage.log_rows import LogRows, TenantID
from victorialogs_tpu.storage.storage import Storage

NS = 1_000_000_000
T0 = 1_753_660_800_000_000_000  # 2025-07-28T00:00:00Z
TEN = TenantID(0, 0)


@pytest.fixture(autouse=True)
def _fresh_ledger():
    ingestledger.reset_for_tests()
    yield
    ingestledger.reset_for_tests()


# ---------------------------------------------------------------- units

def test_conservation_accept_store_balances():
    with ingestledger.begin_batch("0:0") as ctx:
        ingestledger.note_accepted("0:0", 100)
        ingestledger.note_stored("0:0", 100, max_ts_unix=T0 / 1e9)
    d = ingestledger.balance_snapshot()["0:0"]
    assert d["accepted"] == 100 and d["stored"] == 100
    assert d["in_flight"] == 0 and d["dropped_rows"] == 0
    assert ingestledger.check_balanced() == []
    assert ingestledger.inflight_batches() == 0
    assert ctx.state == "done"
    # the freshness watermark advanced to the max stored row time
    st = ingestledger.status_payload()
    assert st["watermark_unix"]["0:0"] == pytest.approx(T0 / 1e9)


def test_conservation_spool_detour_stalls_then_replay_resolves():
    with ingestledger.begin_batch("0:0") as ctx:
        bid = ctx.batch_id
        ingestledger.note_accepted("0:0", 60)
        ingestledger.note_forwarded("0:0", 40)
        ingestledger.note_spooled("0:0", 20)
    # rows parked in the spool: the batch is NOT done — it shows as a
    # stalled (spooled) entry on /insert/status
    assert ctx.state == "spooled"
    st = ingestledger.status_payload()
    assert st["stalled_batches"] >= 1
    assert any(b["batch_id"] == bid and b["state"] == "spooled"
               for b in st["in_flight"])
    d = ingestledger.balance_snapshot()["0:0"]
    assert d["in_flight"] == 20

    # replay re-ships from the spool record (no ambient ctx, found by
    # batch_id): rolls replayed AND forwarded, completes the batch
    ingestledger.note_replayed("0:0", 20, batch_id=bid)
    d = ingestledger.balance_snapshot()["0:0"]
    assert d["replayed"] == 20 and d["forwarded"] == 60
    assert d["in_flight"] == 0
    assert ctx.state == "done"
    assert ingestledger.check_balanced() == []


def test_conservation_drop_exits_with_reason():
    with ingestledger.begin_batch("0:0"):
        ingestledger.note_accepted("0:0", 10)
        ingestledger.note_dropped("0:0", 4, "too_old")
        ingestledger.note_stored("0:0", 6)
    d = ingestledger.balance_snapshot()["0:0"]
    assert d["dropped"] == {"too_old": 4}
    assert d["in_flight"] == 0
    assert ingestledger.check_balanced() == []


def test_begin_batch_reenters_known_id_and_system_tenant_skips():
    """An /internal/insert hop carrying a known batch_id re-enters the
    SAME record (the in-process cluster case: frontend + storage hops
    share one ctx), and system-tenant rolls stay off the ledger."""
    with ingestledger.begin_batch("0:0") as outer:
        ingestledger.note_accepted("0:0", 5)
        with ingestledger.begin_batch(
                "0:0", origin="internal",
                batch_id=outer.batch_id) as inner:
            assert inner is outer
            ingestledger.note_received("0:0", 5)
            ingestledger.note_stored("0:0", 5)
        # inner extent exit must not complete the still-open outer
        assert outer.state == "active"
        ingestledger.note_forwarded("0:0", 5)
    assert outer.state == "done"
    assert outer.rows == 10 and outer.resolved == 10

    ingestledger.note_accepted(events.SYSTEM_TENANT, 50)
    ingestledger.note_stored(events.SYSTEM_TENANT, 50)
    assert events.SYSTEM_TENANT not in ingestledger.balance_snapshot()


def test_wrap_unwrap_roundtrip_and_legacy_passthrough():
    body = b"\x28\xb5\x2f\xfdwire-bytes"
    rec = ingestledger.wrap_record(body, "abcd:7", "3:0", 123,
                                   accept_unix=1753660800.25)
    meta, out = ingestledger.unwrap_record(rec)
    assert out == body
    assert meta == {"batch_id": "abcd:7", "tenant": "3:0",
                    "nrows": 123, "ts": 1753660800.25}
    # headerless (pre-upgrade spool) records pass through untouched
    assert ingestledger.unwrap_record(body) == (None, body)
    # torn header: fail open, never lose the payload
    assert ingestledger.unwrap_record(b"VLB1\x00\x00\x00\xffxx")[0] is None


def test_hop_aggregates_always_on_trace_off():
    assert not ingestledger.trace_enabled()
    with ingestledger.begin_batch("0:0") as ctx:
        ingestledger.note_accepted("0:0", 1)
        with ingestledger.hop("parse"):
            pass
        with ingestledger.hop("parse"):
            pass
        assert ctx.span is None          # no span tree unless opted in
        ingestledger.note_stored("0:0", 1)
    st = ingestledger.status_payload()
    agg = st["hop_latency"]["0:0"]["parse"]
    assert agg["count"] == 2 and agg["total_s"] >= 0
    assert st["trace_enabled"] is False


def test_trace_opt_in_grows_span_tree(monkeypatch):
    monkeypatch.setenv("VL_INGEST_TRACE", "1")
    with ingestledger.begin_batch("0:0") as ctx:
        ingestledger.note_accepted("0:0", 1)
        with ingestledger.hop("parse"):
            pass
        ingestledger.note_stored("0:0", 1)
    snap = ctx.snapshot()
    assert snap["trace"]["name"] == "ingest_batch"
    assert [c["name"] for c in snap["trace"]["children"]] == ["parse"]


def test_eviction_bounds_inflight_registry(monkeypatch):
    monkeypatch.setenv("VL_INGEST_BATCHES_MAX", "8")
    extents = [ingestledger.begin_batch("0:0") for _ in range(12)]
    for e in extents:
        e.__enter__()
    assert ingestledger.inflight_batches() <= 8
    for e in reversed(extents):
        e.__exit__(None, None, None)


def test_ledger_metrics_samples_shapes():
    with ingestledger.begin_batch("9:0"):
        ingestledger.note_accepted("9:0", 7)
        ingestledger.note_dropped("9:0", 2, "too_new")
        ingestledger.note_stored("9:0", 5)
    samples = {(base, tuple(sorted(labels.items()))): v
               for base, labels, v in ingestledger.metrics_samples()}
    assert samples[("vl_ingest_ledger_rows_total",
                    (("state", "accepted"), ("tenant", "9:0")))] == 7
    assert samples[("vl_ingest_ledger_dropped_total",
                    (("reason", "too_new"), ("tenant", "9:0")))] == 2
    assert samples[("vl_ingest_ledger_in_flight",
                    (("tenant", "9:0"),))] == 0
    assert ("vl_ingest_batches_in_flight", ()) in samples


# ------------------------------------------- storage chokepoint rolls

def _mk_storage(tmp_path, name):
    # 10000 days keeps 2025-era fixture rows in range while leaving
    # min_ts positive, so the epoch-adjacent row really is too_old
    return Storage(str(tmp_path / name), retention_days=10000,
                   flush_interval=3600)


def test_storage_rolls_stored_and_range_drops_only_under_batch(tmp_path):
    s = _mk_storage(tmp_path, "ledgerstore")
    try:
        # no ambient batch: a direct test write stays OFF the ledger
        lr = LogRows(stream_fields=["app"])
        for i in range(10):
            lr.add(TEN, T0 + i * NS, [("app", "a"), ("_msg", f"m{i}")])
        s.must_add_rows(lr)
        assert "0:0" not in ingestledger.balance_snapshot()

        # under a batch: stored + too_old/too_new rolls, exact
        lr = LogRows(stream_fields=["app"])
        for i in range(8):
            lr.add(TEN, T0 + i * NS, [("app", "a"), ("_msg", f"g{i}")])
        lr.add(TEN, 1, [("app", "a"), ("_msg", "ancient")])
        with ingestledger.begin_batch("0:0"):
            ingestledger.note_accepted("0:0", 9)
            s.must_add_rows(lr)
        d = ingestledger.balance_snapshot()["0:0"]
        assert d["stored"] == 8
        assert d["dropped"] == {"too_old": 1}
        assert d["in_flight"] == 0
        # the ingest->queryable histogram observed this batch
        assert hist.INGEST_TO_QUERYABLE.snapshot()[2] >= 1
    finally:
        s.close()


def test_flush_observes_freshness_histogram(tmp_path):
    s = _mk_storage(tmp_path, "freshstore")
    try:
        lr = LogRows(stream_fields=["app"])
        for i in range(50):
            lr.add(TEN, T0 + i * NS, [("app", "a"), ("_msg", f"f{i}")])
        before = hist.INGEST_FRESHNESS.snapshot()[2]
        s.must_add_rows(lr)
        s.debug_flush()
        assert hist.INGEST_FRESHNESS.snapshot()[2] > before
    finally:
        s.close()


# -------------------------------------------------- HTTP plane

def _get_json(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
        return json.loads(resp.read())


def test_insert_status_endpoint_and_idle_quiesce(tmp_path):
    from victorialogs_tpu.server.app import VLServer
    s = _mk_storage(tmp_path, "statstore")
    srv = VLServer(s, port=0)
    got = []

    def tap(ts_ns, event, fields):
        if event == "ingest_batch":
            got.append(dict(fields))
    events.subscribe(tap)
    try:
        body = "\n".join(json.dumps(
            {"_time": T0 + i * NS, "_msg": f"hello {i}", "app": "web"})
            for i in range(40)).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}"
            f"/insert/jsonline?_stream_fields=app", data=body)
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.status == 200

        st = _get_json(srv.port, "/insert/status")
        assert st["status"] == "ok"
        led = st["ledger"]["0:0"]
        assert led["accepted"] == 40 and led["stored"] == 40
        assert led["in_flight"] == 0
        assert not st["in_flight"] and st["stalled_batches"] == 0
        assert st["hop_latency"]["0:0"]["parse"]["count"] >= 1
        assert st["recent"] and st["recent"][-1]["rows"] == 40
        # single-node servers have no cluster spool section
        assert "spool" not in st

        # the batch completion journaled exactly once, with row counts
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not got:
            time.sleep(0.05)
        assert [e["rows"] for e in got] == [40]
        assert got[0]["status"] == "ok"

        # ledger counters ride /metrics
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=30) as resp:
            metrics = resp.read().decode()
        assert ('vl_ingest_ledger_rows_total'
                '{state="accepted",tenant="0:0"} 40') in metrics
        assert "vl_ingest_batches_in_flight 0" in metrics
        assert 'vl_ingest_watermark_seconds{tenant="0:0"}' in metrics
        # and the per-tenant section rides /internal/usage for the
        # clusterstats rollup
        usage = _get_json(srv.port, "/internal/usage")
        assert usage["ingest_ledger"]["0:0"]["stored"] == 40

        # RECURSION GUARD (test-pinned): an idle server quiesces — the
        # journal observing the ledger must not tick new ingest_batch
        # events (system-tenant suppressed, zero-row batches silent)
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/internal/force_flush",
            timeout=30)
        n0 = len(got)
        time.sleep(1.0)
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/internal/force_flush",
            timeout=30)
        time.sleep(0.5)
        assert len(got) == n0, got[n0:]
        assert ingestledger.check_balanced() == []
    finally:
        events.unsubscribe(tap)
        srv.close()
        s.close()


# -------------------------------------------------- queue depth gauges

def test_persistentqueue_entry_and_age_gauges(tmp_path):
    from victorialogs_tpu.utils.persistentqueue import PersistentQueue
    q = PersistentQueue(str(tmp_path / "pq"))
    try:
        assert q.pending_entries() == 0
        assert q.oldest_age_seconds() == 0.0
        q.append(b"a" * 10)
        time.sleep(0.05)
        q.append(b"b" * 20)
        assert q.pending_entries() == 2
        assert q.oldest_age_seconds() >= 0.05
        first = q.read(timeout=1)
        assert first == b"a" * 10
        q.ack(len(first))
        # FIFO byte-drain: the oldest entry left, the younger remains
        assert q.pending_entries() == 1
        assert q.oldest_age_seconds() < 10.0
        second = q.read(timeout=1)
        q.ack(len(second))
        assert q.pending_entries() == 0
        assert q.oldest_age_seconds() == 0.0
    finally:
        q.close()


# -------------------------------------------------- drop-discipline lint

def test_vlint_drop_discipline_checker():
    from tools.vlint.core import SourceFile
    from tools.vlint.dropdiscipline import check

    src = '''
def bad(self, n):
    self.rows_dropped += n
    events.emit("spool_overflow", node=1)

def ledgered(self, t, n):
    ingestledger.note_dropped(t, n, "too_old")
    self.rows_dropped += n

def via_helper(self, t, n):
    self.rows_dropped += n
    self._roll(t, n)

def _roll(self, t, n):
    ingestledger.note_dropped(t, n, "x")

def annotated(self):
    # vlint: allow-drop-discipline(block-level, rows counted upstream)
    self.dropped_blocks += 1
    events.emit("queue_block_rejected")
'''
    sf = SourceFile.parse("victorialogs_tpu/server/fake.py", text=src)
    found = [f for f in check(sf)
             if not sf.allowed(f.checker, f.line)]
    assert {f.symbol for f in found} == {"bad"}
    assert len(found) == 2          # the emit and the tally advance

    # out-of-scope layers are never flagged
    sf2 = SourceFile.parse("victorialogs_tpu/engine/fake.py", text=src)
    assert check(sf2) == []


def test_repo_is_drop_discipline_clean():
    """Every drop site in server/ + storage/ goes through the ledger
    (or carries a reasoned annotation) — the satellite's whole point,
    pinned so a new bare drop site fails CI."""
    import os
    from tools.vlint.core import SourceFile
    from tools.vlint.dropdiscipline import check

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = []
    for sub in ("server", "storage"):
        root = os.path.join(repo, "victorialogs_tpu", sub)
        for fn in sorted(os.listdir(root)):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            sf = SourceFile.parse(
                f"victorialogs_tpu/{sub}/{fn}",
                text=open(path, encoding="utf-8").read())
            bad += [f.render() for f in check(sf)
                    if not sf.allowed(f.checker, f.line)]
    assert bad == []
