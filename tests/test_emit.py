"""Columnar emit differential suite: the native NDJSON serializer
(native/vlnative.cpp vl_emit_ndjson over BlockResult.emit_columns) must
be BYTE-IDENTICAL to the per-row path (dict per row + json.dumps with
ensure_ascii=False and (",", ":") separators) on every storage column
type and every escape class — VL_NATIVE_EMIT=0/1 x VL_FUSED_FILTER=0/1
matrix over the HTTP query path, plus randomized round-trips through
json.loads."""

import json
import random

import pytest

from victorialogs_tpu.engine.block_result import (BlockResult,
                                                  parse_rfc3339)
from victorialogs_tpu.engine.emit import (ndjson_block, ndjson_block_py,
                                          native_emit_enabled)
from victorialogs_tpu.engine.searcher import run_query, run_query_collect
from victorialogs_tpu.server import vlselect
from victorialogs_tpu.storage.log_rows import LogRows, TenantID
from victorialogs_tpu.storage.storage import Storage

NS = 1_000_000_000
T0 = 1_753_660_800_000_000_000
TEN = TenantID(0, 0)

# every escape class the serializer must reproduce: quotes, backslashes,
# named control escapes, \u00XX controls, DEL (NOT escaped), multibyte
# UTF-8 of 2/3/4 bytes, and an empty value (omitted field)
EDGE_VALUES = [
    'plain',
    'with "quotes" and \\backslashes\\',
    'tab\there\nnewline\rcr',
    'ctrl\x00\x01\x1f\x7fdel',
    'b\bf\f',
    'café 2-byte',
    '日本語 3-byte',
    'emoji \U0001f642 4-byte',
    '',
    ' leading and trailing ',
    '{"nested":"json"}',
    'sl/ash',
]


@pytest.fixture(scope="module")
def storage(tmp_path_factory):
    s = Storage(str(tmp_path_factory.mktemp("emitstore")),
                retention_days=100000, flush_interval=3600)
    lr = LogRows(stream_fields=["app"])
    for i in range(256):
        fields = [
            ("app", f"app{i % 2}"),
            ("_msg", f"edge row {i}: {EDGE_VALUES[i % len(EDGE_VALUES)]}"),
            ("lvl", ["info", "warn", "err"][i % 3]),       # dict column
            ("code", str(200 + i % 5)),                    # uint column
            ("ratio", str(float(i) / 8.0)),                # float column
            ("ip", f"10.0.{i % 4}.{i % 250}"),             # ipv4 column
            ("iso", f"2025-07-28T00:00:{i % 60:02d}Z"),    # iso8601 column
            ("konst", "same-everywhere"),                  # const column
            ("weird", EDGE_VALUES[(i * 7) % len(EDGE_VALUES)]),
        ]
        if i % 3 == 0:
            fields.append(("sparse", f"only-sometimes-{i}"))
        # empty value == absent field: must be omitted either way
        fields.append(("empty", "" if i % 2 else f"e{i}"))
        lr.add(TEN, T0 + i * 137_000_003, fields)  # uneven ns fractions
    s.must_add_rows(lr)
    s.debug_flush()
    yield s
    s.close()


def _http_query(storage, q, runner=None, **extra):
    args = {"query": q, "limit": "0"}
    args.update(extra)
    chunks = list(vlselect.handle_query(storage, args, {}, runner=runner))
    return b"".join(c if isinstance(c, bytes) else c.encode("utf-8")
                    for c in chunks)


QUERIES = [
    "*",
    "edge",
    '* | fields _time, lvl, code',
    '* | fields weird, _msg',                 # projection ORDER: weird first
    '* | fields lvl, lvl, code',              # duplicate names dedupe
    '* | fields sparse, empty, konst',
    '* | delete _msg, weird',
    'code:>=202 | fields code, ratio, ip, iso',
    '* | sort by (code) limit 7',
    '* | stats by (lvl) count() hits',
    '* | limit 5',
]


@pytest.mark.parametrize("fused", ["1", "0"])
def test_native_vs_python_http_matrix(storage, monkeypatch, fused):
    """Acceptance matrix: byte-identical NDJSON under VL_NATIVE_EMIT=0/1
    and VL_FUSED_FILTER=0/1, CPU executor and device runner."""
    from victorialogs_tpu.tpu.batch import BatchRunner
    monkeypatch.setenv("VL_FUSED_FILTER", fused)
    runner = BatchRunner()
    for q in QUERIES:
        outs = {}
        for native in ("0", "1"):
            monkeypatch.setenv("VL_NATIVE_EMIT", native)
            outs[native] = _http_query(storage, q, runner=runner)
        assert outs["0"] == outs["1"], f"native/python diverged on {q!r}"
        assert outs["1"] == _http_query(storage, q, runner=None), \
            f"runner/CPU diverged on {q!r}"


def test_native_serializer_is_active(storage, monkeypatch):
    """The parity matrix is meaningless if the native path silently fell
    back — pin that it engages on this image."""
    from victorialogs_tpu import native
    monkeypatch.setenv("VL_NATIVE_EMIT", "1")
    assert native.available()
    assert native_emit_enabled()
    blocks = []
    run_query(storage, [TEN], "*", write_block=blocks.append,
              timestamp=T0)
    assert blocks
    names, cols = blocks[0].emit_columns()
    data = native.emit_ndjson_native(
        [(json.dumps(n, ensure_ascii=False) + ":").encode("utf-8")
         for n in names], cols, blocks[0].nrows)
    assert data is not None
    assert data == ndjson_block_py(blocks[0])


def test_projection_order_and_empty_omission(storage, monkeypatch):
    monkeypatch.setenv("VL_NATIVE_EMIT", "1")
    out = _http_query(storage, '* | fields weird, _msg, empty')
    lines = out.splitlines()
    assert len(lines) == 256
    for ln in lines:
        row = json.loads(ln)
        assert "empty" not in row or row["empty"] != ""
        keys = [k for k in row if k in ("weird", "_msg")]
        assert keys == sorted(keys, key=["weird", "_msg"].index)


def test_duplicate_fields_never_duplicate_json_keys(storage, monkeypatch):
    """`fields lvl, lvl` must collapse to one key like the materialized
    path always did — never two identical keys in the emitted JSON."""
    monkeypatch.setenv("VL_NATIVE_EMIT", "1")
    out = _http_query(storage, 'edge | fields lvl, lvl')
    for ln in out.splitlines():
        assert ln.count(b'"lvl"') == 1, ln


def test_block_result_emit_time_dict_const_columns(storage):
    """Typed emit paths (_time vectorized RFC3339, dict codes, consts,
    numerics) against the rows() oracle, on raw storage-backed blocks."""
    blocks = []
    run_query(storage, [TEN], "*", write_block=blocks.append,
              timestamp=T0)
    for br in blocks:
        assert br._bs is not None          # storage-backed, not a copy
        assert ndjson_block(br) == ndjson_block_py(br)


def test_fields_restriction_keeps_block_backing(storage):
    """The fields pipe must project WITHOUT materializing: the emit sink
    sees a block-backed result (the tentpole's whole point)."""
    blocks = []
    run_query(storage, [TEN], "* | fields _time, lvl",
              write_block=blocks.append, timestamp=T0)
    assert blocks
    for br in blocks:
        assert br._bs is not None
        assert br.column_names() == ["_time", "lvl"]
        assert ndjson_block(br) == ndjson_block_py(br)


def test_randomized_roundtrip_1000_rows(monkeypatch):
    """>=1000 random rows of hostile strings through from_columns:
    native bytes == python bytes, and every line json.loads back to the
    expected dict (non-empty values only)."""
    rng = random.Random(0xE417)
    alphabet = ('ab"\\\n\r\t\x00\x01\x1f\x7f'
                'é日\U0001f642 /{}[]:,')
    nrows = 1200

    def rand_val():
        return "".join(rng.choice(alphabet)
                       for _ in range(rng.randrange(0, 24)))

    cols = {f"f{k}": [rand_val() for _ in range(nrows)] for k in range(6)}
    cols["fixed"] = ["x"] * nrows
    br = BlockResult.from_columns(cols)
    monkeypatch.setenv("VL_NATIVE_EMIT", "1")
    nat = ndjson_block(br)
    assert nat == ndjson_block_py(br)
    lines = nat.splitlines()
    assert len(lines) == nrows
    names = list(cols)
    for i, ln in enumerate(lines):
        row = json.loads(ln)
        assert row == {n: cols[n][i] for n in names if cols[n][i] != ""}


def test_invalid_utf8_falls_back_to_python():
    """A value with invalid UTF-8 bytes must push the whole block to the
    per-row path (whose errors='replace' decode defines the output)."""
    import numpy as np
    from victorialogs_tpu import native
    if not native.available():
        pytest.skip("native lib unavailable")
    def one_value(buf):
        return native.emit_ndjson_native(
            [b'"k":'], [(0, buf, np.zeros(1, dtype=np.int64),
                         np.array([buf.size], dtype=np.int64))], 1)

    assert one_value(np.frombuffer(b"ok\xff\xfebad",
                                   dtype=np.uint8)) is None
    # incomplete multibyte tail is invalid too
    assert one_value(np.frombuffer("café".encode("utf-8")[:-1],
                                   dtype=np.uint8)) is None
    # lone surrogate halves (CESU-8) are rejected like Python's strict
    # decoder would replace them
    assert one_value(np.frombuffer(b"\xed\xa0\x80",
                                   dtype=np.uint8)) is None
    # sanity: the same helper emits a valid value fine
    ok = np.frombuffer("café".encode("utf-8"), dtype=np.uint8)
    assert one_value(ok) == '{"k":"café"}\n'.encode("utf-8")


def test_kill_switch_forces_python_path(storage, monkeypatch):
    monkeypatch.setenv("VL_NATIVE_EMIT", "0")
    assert not native_emit_enabled()
    calls = []
    import victorialogs_tpu.native as native_mod
    orig = native_mod.emit_ndjson_native

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)
    monkeypatch.setattr("victorialogs_tpu.engine.emit.emit_ndjson_native",
                        spy)
    _http_query(storage, "edge")
    assert calls == []


def test_tail_sink_sorts_by_true_timestamp(storage, monkeypatch):
    """handle_tail's columnar (int64-ns, line) sort: same line set as
    the dict path, ordered by TRUE timestamp — which fixes the old
    lexical sort's sub-second misordering ("..00.5Z" < "..00Z"
    byte-wise; the fixture's uneven fractions hit that case)."""
    from victorialogs_tpu.engine.block_result import parse_rfc3339
    monkeypatch.setenv("VL_NATIVE_EMIT", "1")
    blocks = []
    run_query(storage, [TEN], "edge", write_block=blocks.append,
              timestamp=T0)
    pairs = []
    for br in blocks:
        lines = ndjson_block(br).split(b"\n")[:br.nrows]
        ts = br.timestamps_np() if "_time" in br.column_names() else None
        keys = ts.tolist() if ts is not None else [0] * br.nrows
        pairs.extend(zip(keys, lines))
    pairs.sort(key=lambda kv: kv[0])
    got = [ln for _k, ln in pairs]
    rows = run_query_collect(storage, [TEN], "edge", timestamp=T0)
    rows.sort(key=lambda r: parse_rfc3339(r.get("_time", "")) or 0)
    want = [json.dumps(r, ensure_ascii=False,
                       separators=(",", ":")).encode("utf-8")
            for r in rows]
    assert got == want
    # the fixture really exercises the lexical-vs-numeric divergence
    lex = sorted((r.get("_time", "") for r in rows))
    num = [r.get("_time", "") for r in
           sorted(rows, key=lambda r: parse_rfc3339(r["_time"]) or 0)]
    assert lex != num, "fixture no longer covers the sub-second case"


def _tail_keys(br):
    """Mirror of handle_tail's sink sort-key selection."""
    names = br.column_names()
    if "_time" not in names:
        return [0] * br.nrows
    if br._bs is not None and br.timestamps_np() is not None:
        return br.timestamps_np().tolist()
    return [parse_rfc3339(v) or 0 for v in br.column("_time")]


def test_tail_sort_follows_displayed_time(storage):
    """When a live-tailable pipe REWRITES _time (copy), the tail sort
    key must follow the displayed value, not the original ingestion
    timestamps the materialized block still carries."""
    from victorialogs_tpu.engine.block_result import parse_rfc3339
    q = "edge | copy iso as _time"
    keyed = []

    def sink(br):
        vals = br.column("_time")
        keyed.extend(zip(_tail_keys(br), vals))
    run_query(storage, [TEN], q, write_block=sink, timestamp=T0)
    assert keyed
    for k, v in keyed:
        assert k == parse_rfc3339(v), \
            "sort key diverged from the displayed _time"
    # and the fixture makes displayed order differ from ingestion order
    disp = [k for k, _v in keyed]
    assert disp != sorted(disp)
