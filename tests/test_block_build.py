"""Sharded block build (storage/block_build.py): parallel-vs-serial
flushed-part BYTE identity across thread counts and the arena/list
encode paths, direct arena-vs-list values-encoder differentials over
the typed-detection edge cases, the unified size-bounded chunker pin,
ledger conservation + per-hop `build` aggregates under concurrent
builds, pool drain on DataDB.close (vlsan-swept), the
VL_BLOCK_BUILD_THREADS=0 serial fallback, the VL_INSERT_PIPELINE
decode/store hop overlap, and syslog-vs-jsonline columnar parity."""

import os
import threading

import numpy as np
import pytest

from victorialogs_tpu.obs import ingestledger
from victorialogs_tpu.server import cluster, wire_ingest
from victorialogs_tpu.storage import block_build
from victorialogs_tpu.storage.block import chunk_end, row_cost_cum
from victorialogs_tpu.storage.log_rows import LogRows, TenantID
from victorialogs_tpu.storage.storage import Storage
from victorialogs_tpu.storage.values_encoder import (
    VT_CONST, VT_DICT, VT_FLOAT64, VT_INT64, VT_IPV4, VT_STRING,
    VT_TIMESTAMP_ISO8601, VT_UINT8, decode_values, encode_values)

NS = 1_000_000_000
T0 = 1_753_660_800_000_000_000  # 2025-07-28T00:00:00Z
TEN = TenantID(0, 0)

# the realistic invalid-UTF-8 ingest outcome: bytes that failed strict
# decode arrive as U+FFFD replacements (HTTP readers use errors="replace")
BAD_UTF8 = b"\xff\xfe broken \x80".decode("utf-8", "replace")


def _mixed_lr(nrows=4000, nstreams=9):
    """>=8 streams x 3 schema groups x every value type the encoder
    detects (const/dict/uint/int/float/ipv4/iso/string), plus empty
    values, embedded NULs and replacement chars from invalid UTF-8."""
    lr = LogRows(stream_fields=["app", "host"])
    for i in range(nrows):
        s = i % nstreams
        fields = [("app", f"a{s}"), ("host", f"h{s % 3}"),
                  ("_msg", f"msg {i} tok{i % 37} {'x' * (i % 23)}"),
                  ("level", ["info", "warn", "error"][i % 3]),
                  ("count", str(i)),
                  ("neg", str(-i)),
                  ("f", f"{i}.25"),
                  ("ip", f"10.0.{i % 256}.{i % 200}"),
                  ("iso", "2025-07-28T12:00:%02d.%03dZ" % (i % 60,
                                                           i % 1000)),
                  ("const", "xyz")]
        if i % 3 == 0:  # schema group 2: extra sparse field
            fields.append(("sparse", f"s{i % 4}"))
        if i % 7 == 0:  # schema group 3: nasty values
            fields.append(("nasty", ["", "12\x00", BAD_UTF8,
                                     "snow☃"][i % 4]))
        lr.add(TEN, T0 + (i % 500) * NS + i, fields)
    return lr


def _filedict(root):
    out = {}
    for dp, _dns, fns in os.walk(root):
        for fn in fns:
            p = os.path.join(dp, fn)
            with open(p, "rb") as f:
                out[os.path.relpath(p, root)] = f.read()
    return out


def _query_lines(s, q="*"):
    from victorialogs_tpu.engine.emit import ndjson_block
    from victorialogs_tpu.engine.searcher import run_query
    blocks = []
    run_query(s, [TEN], q, write_block=blocks.append,
              timestamp=T0 + 86400 * NS)
    out = []
    for br in blocks:
        out.extend(ndjson_block(br).splitlines())
    return sorted(out)


def _store(path, body, flush=True):
    s = Storage(str(path), retention_days=100000, flush_interval=3600)
    n = cluster.handle_internal_insert(s, {}, body)
    if flush:
        s.debug_flush()
    return s, n


# ---------------- parallel vs serial byte identity ----------------

def test_parallel_serial_arena_part_byte_identity(tmp_path, monkeypatch):
    """The acceptance pin: flushed parts from the sharded build are
    byte-identical to the serial build, and the arena (columnar)
    encode produces the same bytes as the materialized-string path —
    all four (threads x arena) combinations, through the real
    /internal/insert storage hop."""
    lr = _mixed_lr()
    body = wire_ingest.encode_rows(lr)
    fds = {}
    for threads, arena in [(4, "1"), (0, "1"), (4, "0"), (0, "0")]:
        monkeypatch.setenv("VL_BLOCK_BUILD_THREADS", str(threads))
        monkeypatch.setenv("VL_ARENA_BUILD", arena)
        root = tmp_path / f"t{threads}a{arena}"
        s, n = _store(root, body)
        s.close()
        assert n == len(lr)
        fds[(threads, arena)] = _filedict(str(root))
    ref = fds[(0, "0")]
    assert len(ref) > 5
    for key, fd in fds.items():
        assert fd.keys() == ref.keys(), key
        diff = [k for k in ref if fd[k] != ref[k]]
        assert not diff, (key, diff)


def test_row_vs_columnar_part_byte_identity(tmp_path, monkeypatch):
    """Same-schema batches produce byte-identical parts whether they
    enter as LogRows or as a columnar batch — the unified chunker +
    shared `_build_one_block`/`encode_values` core."""
    monkeypatch.setenv("VL_BLOCK_BUILD_THREADS", "4")

    def lr():
        out = LogRows(stream_fields=["app"])
        for i in range(3000):
            out.add(TEN, T0 + i * NS, [("app", f"a{i % 8}"),
                                       ("_msg", f"m {i}"),
                                       ("k", str(i % 5))])
        return out

    sa = Storage(str(tmp_path / "rows"), retention_days=100000,
                 flush_interval=3600)
    sa.must_add_rows(lr())
    sa.debug_flush()
    sa.close()
    sb = Storage(str(tmp_path / "cols"), retention_days=100000,
                 flush_interval=3600)
    sb.must_add_columns(wire_ingest.rows_to_columns(lr()))
    sb.debug_flush()
    sb.close()
    fa, fb = _filedict(str(tmp_path / "rows")), \
        _filedict(str(tmp_path / "cols"))
    assert fa.keys() == fb.keys() and len(fa) > 3
    assert [k for k in fa if fa[k] != fb[k]] == []


# ---------------- arena encoder differential ----------------

def _arena_of(vals):
    """list[str] (ASCII) -> dense (sub, offs, lens) arena triple."""
    raw = "".join(vals).encode("utf-8")
    lens = np.asarray([len(v) for v in vals], dtype=np.int64)
    offs = np.zeros(len(vals), dtype=np.int64)
    if len(vals) > 1:
        np.cumsum(lens[:-1], out=offs[1:])
    return np.frombuffer(raw, dtype=np.uint8), offs, lens


TRICKY_COLUMNS = [
    ("const", ["xyz"] * 64, VT_CONST),
    ("const_empty", [""] * 64, VT_CONST),
    ("dict8", [f"v{i % 8}" for i in range(64)], VT_DICT),
    ("dict9_overflow", [f"v{i % 9}" for i in range(64)], VT_STRING),
    # 8 distinct values, 32 bytes each = 256 distinct bytes: at the cap
    ("dict_256b", [("%d" % (i % 8)) * 32 for i in range(64)], VT_DICT),
    # 8 distinct, 33 bytes each = 264 > 256: over the cap
    ("dict_264b", [("%d" % (i % 8)) * 33 for i in range(64)], VT_STRING),
    # >8 distinct everywhere below: the dict trial must lose so the
    # typed trials (and their rejection paths) actually run
    ("uint8", [str(i % 200) for i in range(64)], VT_UINT8),
    ("uint_leading_zero", ["01"] + [str(i) for i in range(2, 65)],
     VT_STRING),
    ("int_neg", [str(-i) for i in range(10, 74)], VT_INT64),
    ("float", [f"{i}.5" for i in range(10, 74)], VT_FLOAT64),
    ("float_inf", ["inf"] + [f"{i}.5" for i in range(63)], VT_STRING),
    ("ipv4", [f"10.0.0.{i % 200}" for i in range(64)], VT_IPV4),
    ("ipv4_noncanon", ["10.0.00.1"] + [f"10.0.0.{i}" for i in range(63)],
     VT_STRING),
    ("iso", ["2025-07-28T12:00:%02d.%03dZ" % (i % 60, i % 1000)
             for i in range(64)], VT_TIMESTAMP_ISO8601),
    ("iso_mixed_frac", ["2025-07-28T12:00:01.5Z"]
     + ["2025-07-28T12:00:01.%03dZ" % i for i in range(63)], VT_STRING),
    ("nul_byte", [f"v{i}\x00" for i in range(64)], VT_STRING),
    ("empty_mixed", [""] + [f"a{i}" for i in range(63)], VT_STRING),
    ("plain", [f"word{i} and more" for i in range(64)], VT_STRING),
]


@pytest.mark.parametrize("name,vals,want_vtype",
                         TRICKY_COLUMNS,
                         ids=[c[0] for c in TRICKY_COLUMNS])
def test_encode_arena_column_matches_encode_values(name, vals,
                                                   want_vtype):
    """The columnar encoder must pick the SAME encoding with the SAME
    payload bytes as the per-row-string encoder, for every detection
    edge case — that equality is what makes VL_ARENA_BUILD invisible
    in the stored bytes."""
    got = block_build.encode_arena_column(name, *_arena_of(vals))
    want = encode_values(name, vals)
    assert want.vtype == want_vtype
    assert got.vtype == want.vtype
    for f in ("const_value", "dict_values", "ids", "nums", "arena",
              "offsets", "lengths", "min_val", "max_val", "iso_frac_w"):
        ga, wa = getattr(got, f), getattr(want, f)
        if isinstance(wa, np.ndarray):
            assert np.array_equal(np.asarray(ga), wa), f
        else:
            assert ga == wa, f
    assert decode_values(got, len(vals)) == vals


def test_gather_non_contiguous_rows():
    """_gather re-densifies an arbitrary row subset of an arena; the
    encoder over the subset matches encode_values over the same rows."""
    vals = [f"v{i % 3}" for i in range(100)]
    ac = block_build.ArenaColumn("".join(vals).encode(),
                                 *_arena_of(vals)[1:3], "".join(vals))
    idx = np.asarray([3, 5, 8, 13, 21, 34, 55, 89], dtype=np.int64)
    got = block_build.encode_arena_column(
        "x", *block_build._gather(ac, idx))
    want = encode_values("x", [vals[i] for i in idx])
    assert got.vtype == want.vtype == VT_DICT
    assert np.array_equal(got.ids, want.ids)
    assert got.dict_values == want.dict_values


# ---------------- unified chunker ----------------

def test_chunk_end_strict_boundary():
    """A row landing EXACTLY on max_bytes is excluded (strict <), at
    least one row always ships, and max_rows caps the chunk — the one
    canonical chunker both build paths now share."""
    rows = [[("k", "v" * 10)]] * 10          # cost/row: 1+10+16+8 = 35
    cum = row_cost_cum(rows)
    assert cum[0] == 35 and cum[-1] == 350
    # budget exactly 2 rows: cum[2]-0 = 105 > 70, cum[1] = 70 is NOT
    # < 70+base... strict: rows j with cum[j-1] - base < max_bytes
    assert chunk_end(cum, 0, max_bytes=70) == 2
    assert chunk_end(cum, 0, max_bytes=71) == 3
    assert chunk_end(cum, 0, max_bytes=1) == 1      # >=1 row always
    assert chunk_end(cum, 0, max_rows=4, max_bytes=10**9) == 4
    assert chunk_end(cum, 8, max_bytes=10**9) == 10  # tail clamp
    # walking the chunker covers every row exactly once
    s, seen = 0, 0
    while s < len(rows):
        e = chunk_end(cum, s, max_bytes=100)
        assert e > s
        seen += e - s
        s = e
    assert seen == len(rows)


# ---------------- ledger + hop aggregates under concurrency ----------

def test_ledger_conservation_concurrent_builds(tmp_path, monkeypatch):
    """N threads ingesting through /internal/insert while the build
    pool shards each batch: the row-conservation invariant holds, no
    rows stay in flight, and the per-hop latency aggregates grew a
    `build` hop nested under `store`."""
    monkeypatch.setenv("VL_BLOCK_BUILD_THREADS", "4")
    s = Storage(str(tmp_path / "s"), retention_days=100000,
                flush_interval=3600)
    bodies = [wire_ingest.encode_rows(_mixed_lr(nrows=800))
              for _ in range(4)]
    errs = []

    def one(body):
        try:
            cluster.handle_internal_insert(s, {}, body)
        except Exception as e:  # pragma: no cover - assertion surface
            errs.append(e)

    ts = [threading.Thread(target=one, args=(b,)) for b in bodies]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    s.debug_flush()
    s.close()
    assert not errs
    bal = ingestledger.balance_snapshot()["0:0"]
    assert bal["in_flight"] == 0
    assert bal["dropped_rows"] == 0
    hops = ingestledger.status_payload()["hop_latency"]["0:0"]
    assert hops["build"]["count"] >= 4
    assert hops["store"]["count"] >= 4


# ---------------- pool lifecycle ----------------

def test_build_pool_drains_on_close(tmp_path, monkeypatch):
    """DataDB.close() shuts the pool down: its vl-block-build workers
    exit and the vlsan live-pool registry goes back to zero, so the
    end-of-test non-daemon-thread sweep stays green."""
    monkeypatch.setenv("VL_BLOCK_BUILD_THREADS", "3")
    s = Storage(str(tmp_path / "s"), retention_days=100000,
                flush_interval=3600)
    s.must_add_rows(_mixed_lr(nrows=500))
    s.debug_flush()
    assert any(t.name.startswith("vl-block-build")
               for t in threading.enumerate())
    assert block_build.live_build_pools() > 0
    s.close()
    assert block_build.live_build_pools() == 0
    for t in threading.enumerate():
        if t.name.startswith("vl-block-build"):
            t.join(timeout=5)
    assert not any(t.name.startswith("vl-block-build")
                   for t in threading.enumerate())


def test_threads_zero_serial_fallback(monkeypatch):
    """VL_BLOCK_BUILD_THREADS=0 (and 1) never constructs an executor —
    the build runs inline on the caller."""
    monkeypatch.setenv("VL_BLOCK_BUILD_THREADS", "0")
    p = block_build.BuildPool()
    assert block_build.build_threads() == 0
    assert p.executor() is None
    monkeypatch.setenv("VL_BLOCK_BUILD_THREADS", "1")
    assert p.executor() is None
    p.close()
    assert p.executor() is None  # closed pools stay serial


# ---------------- insert pipeline (hop overlap) ----------------

def test_insert_pipeline_overlap(tmp_path, monkeypatch):
    """VL_INSERT_PIPELINE>0: the handler returns after decode + entry
    rolls, the drainer stores under the SAME batch record, and after
    drain() the rows are flushed, queryable-by-count and the ledger
    balances to zero in flight."""
    monkeypatch.setenv("VL_INSERT_PIPELINE", "2")
    s = Storage(str(tmp_path / "s"), retention_days=100000,
                flush_interval=3600)
    lrs = [_mixed_lr(nrows=300) for _ in range(3)]
    total = sum(len(lr) for lr in lrs)
    for i, lr in enumerate(lrs):
        n = cluster.handle_internal_insert(
            s, {"batch_id": f"pipe:{i}", "batch_tenant": "0:0"},
            wire_ingest.encode_rows(lr))
        assert n == len(lr)
    cluster.INSERT_PIPELINE.drain()
    assert cluster.INSERT_PIPELINE.stored_total >= total
    s.debug_flush()
    assert len(_query_lines(s)) == total
    s.close()
    bal = ingestledger.balance_snapshot()["0:0"]
    assert bal["in_flight"] == 0


# ---------------- syslog columnar parity ----------------

def test_syslog_columnar_parity(tmp_path):
    """Syslog ingest now batches into LogColumns and rides the same
    rows_to_columns -> must_add_columns block-build path as jsonline:
    the stored result matches a row-path ingest of the identically
    parsed fields."""
    from victorialogs_tpu.engine.block_result import parse_rfc3339
    from victorialogs_tpu.server.syslog import (SyslogServer,
                                                parse_syslog_message)

    lines = [
        "<34>1 2025-07-28T06:14:%02d.003Z host%d app %d - - boom %d"
        % (i % 60, i % 4, i, i)
        for i in range(200)
    ]

    s_sys = Storage(str(tmp_path / "sys"), retention_days=100000,
                    flush_interval=3600)
    srv = SyslogServer(s_sys, tcp_port=-1, udp_port=-1)
    for ln in lines:
        srv.ingest_line(ln)
    srv.close()
    s_sys.debug_flush()

    s_row = Storage(str(tmp_path / "row"), retention_days=100000,
                    flush_interval=3600)
    lr = LogRows(stream_fields=["hostname", "app_name"])
    for ln in lines:
        fields = parse_syslog_message(ln)
        ts = parse_rfc3339(dict(fields)["timestamp"])
        lr.add(TEN, ts, fields)
    s_row.must_add_rows(lr)
    s_row.debug_flush()

    got, want = _query_lines(s_sys), _query_lines(s_row)
    s_sys.close()
    s_row.close()
    assert len(want) == len(lines)
    assert got == want
