"""Multi-device mesh tests: the psum/shard_map stats path on the virtual
8-device CPU world the conftest provisions.

These exercise exactly what the driver's dryrun_multichip validates
(reference analogue: the remote/local stats split merged over the wire —
lib/logstorage/net_query_runner.go:67-96, pipe_stats.go:111-119 — mapped to
ICI psum in parallel/distributed.py)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from victorialogs_tpu.parallel.distributed import (  # noqa: E402
    distributed_scan_count, make_mesh, shard_batch, stage_block_batch)
from victorialogs_tpu.tpu import kernels as K  # noqa: E402


def _blocks(n_blocks, nrows=32, hit_every=4):
    out = []
    for b in range(n_blocks):
        vals = []
        for i in range(nrows):
            if i % hit_every == 0:
                vals.append(f"blk{b} error code={i}".encode())
            else:
                vals.append(f"blk{b} ok code={i}".encode())
        lengths = np.array([len(v) for v in vals], dtype=np.int64)
        offsets = np.zeros(nrows, dtype=np.int64)
        np.cumsum(lengths[:-1], out=offsets[1:])
        arena = np.frombuffer(b"".join(vals), dtype=np.uint8)
        out.append((arena, offsets, lengths))
    return out


def test_make_mesh_has_8_cpu_devices():
    mesh = make_mesh(8)
    assert mesh.devices.size == 8
    assert all(d.platform == "cpu" for d in mesh.devices.flat)


def test_make_mesh_raises_when_too_few():
    with pytest.raises(RuntimeError, match="need 64 devices"):
        make_mesh(64)


def test_distributed_scan_count_psum_exact():
    n_dev = 8
    mesh = make_mesh(n_dev)
    nrows, hit_every = 32, 4
    blocks = _blocks(2 * n_dev, nrows=nrows, hit_every=hit_every)
    rows, lengths, _rb = stage_block_batch(blocks, n_dev)
    bucket_ids = np.arange(rows.shape[0], dtype=np.int32) % 4
    arrs = shard_batch(mesh, rows, lengths, bucket_ids)
    pattern = jax.numpy.asarray(np.frombuffer(b"error", dtype=np.uint8))
    bms, total, hist = distributed_scan_count(
        mesh, *arrs, pattern, 5, K.MODE_PHRASE, True, True, 4)
    per_block = nrows // hit_every
    expect = per_block * 2 * n_dev
    assert int(total) == expect
    hist = np.asarray(hist)
    assert int(hist.sum()) == expect
    # per-bucket counts: blocks round-robin over 4 buckets
    assert hist.tolist() == [per_block * 4] * 4
    # the bitmaps must be bit-exact vs the scalar oracle
    from victorialogs_tpu.logsql.matchers import match_phrase
    bms = np.asarray(bms)
    for b, (arena, offsets, lens) in enumerate(blocks):
        for i in range(len(lens)):
            v = arena[offsets[i]:offsets[i] + lens[i]].tobytes().decode()
            assert bool(bms[b, i]) == match_phrase(v, "error"), (b, i, v)


def test_distributed_scan_uneven_blocks_padded():
    n_dev = 8
    mesh = make_mesh(n_dev)
    # 10 blocks pad to 16 so every device gets an equal shard
    blocks = _blocks(10, nrows=16, hit_every=2)
    rows, lengths, _rb = stage_block_batch(blocks, n_dev)
    assert rows.shape[0] % n_dev == 0
    bucket_ids = np.zeros(rows.shape[0], dtype=np.int32)
    arrs = shard_batch(mesh, rows, lengths, bucket_ids)
    pattern = jax.numpy.asarray(np.frombuffer(b"error", dtype=np.uint8))
    _bms, total, hist = distributed_scan_count(
        mesh, *arrs, pattern, 5, K.MODE_PHRASE, True, True, 1)
    assert int(total) == 8 * 10  # pad blocks are all-0xFF: no matches
    assert int(np.asarray(hist)[0]) == 8 * 10


# ---------------- MeshBatchRunner: the product multi-chip path ----------------

NS = 1_000_000_000
T0 = 1_753_660_800_000_000_000


def _mk_storage(tmp_path):
    from victorialogs_tpu.storage.log_rows import LogRows, TenantID
    from victorialogs_tpu.storage.storage import Storage
    ten = TenantID(0, 0)
    s = Storage(str(tmp_path / "mesh"), retention_days=100000,
                flush_interval=3600)
    lr = LogRows(stream_fields=["app"])
    for i in range(4000):
        lr.add(ten, T0 + i * 500_000_000, [
            ("app", f"app{i % 2}"),
            ("_msg", f"req {'deadline' if i % 5 == 0 else 'ok'} n{i % 20}"),
            ("dur", str(i % 311)),
        ])
    s.must_add_rows(lr)
    s.debug_flush()
    return s, ten


def test_mesh_batch_runner_query_parity(tmp_path):
    """run_query through MeshBatchRunner on the 8-device mesh must match
    the CPU executor bit-for-bit — filters AND device stats partials
    (psum/pmin/pmax over the mesh)."""
    from victorialogs_tpu.engine.searcher import run_query_collect
    from victorialogs_tpu.parallel.distributed import MeshBatchRunner

    s, ten = _mk_storage(tmp_path)
    try:
        runner = MeshBatchRunner(make_mesh(8))
        for qs in [
            "deadline | fields _time",
            "deadline | stats by (_time:5m) count() c, sum(dur) s, "
            "min(dur) mn, max(dur) mx",
            "* | stats count() c, avg(dur) a",
            '_msg:~"dead.*line" | stats by (_time:10m) count() c',
            "* | stats by (app) count() c, sum(dur) s",
            "deadline | stats by (app, _time:10m) count_uniq(app) u, "
            "min(dur) mn",
            "* | stats count_uniq(_stream_id) u",
        ]:
            cpu = run_query_collect(s, [ten], qs, timestamp=T0)
            dev = run_query_collect(s, [ten], qs, timestamp=T0,
                                    runner=runner)
            assert sorted(map(str, cpu)) == sorted(map(str, dev)), qs
        assert runner.stats_dispatches > 0
        assert runner.device_calls > 0
        # the SPMD fused single-dispatch path must have carried most of
        # these (shard_map + psum/pmin/pmax over the mesh)
        assert runner.fused_dispatches > 0
        # sort-topk prefilter compiles under GSPMD over the sharded
        # staging (exact order parity incl. boundary ties)
        for qs in ['deadline | sort by (dur desc) limit 6 | fields dur',
                   '* | sort by (dur) limit 9 | fields dur, app']:
            cpu = run_query_collect(s, [ten], qs, timestamp=T0)
            dev = run_query_collect(s, [ten], qs, timestamp=T0,
                                    runner=runner)
            assert cpu == dev, qs
        assert runner.topk_dispatches > 0
    finally:
        s.close()


def test_mesh_fused_residue_and_quantile(tmp_path):
    """Mesh fused path: the packed maybe-vector concatenates across
    shards (pair-regex newline rows settle via host residue) and the
    quantile histogram axis psums correctly."""
    from victorialogs_tpu.engine.searcher import run_query_collect
    from victorialogs_tpu.parallel.distributed import MeshBatchRunner
    from victorialogs_tpu.storage.log_rows import LogRows, TenantID
    from victorialogs_tpu.storage.storage import Storage

    ten = TenantID(0, 0)
    s = Storage(str(tmp_path / "mfr"), retention_days=100000,
                flush_interval=3600)
    lr = LogRows(stream_fields=["app"])
    for i in range(4000):
        msg = f"GET item deadline x{i}" if i % 9 else "GET\nitem deadline"
        lr.add(ten, T0 + i * 250_000_000,
               [("app", f"a{i % 2}"), ("_msg", msg), ("dur", str(i % 97))])
    s.must_add_rows(lr)
    s.debug_flush()
    try:
        runner = MeshBatchRunner(make_mesh(8))
        for qs in ['_msg:~"GET.*deadline" | stats count() c',
                   '_msg:~"GET.*item" | stats by (app) median(dur) m, '
                   'count() c']:
            cpu = run_query_collect(s, [ten], qs, timestamp=T0)
            dev = run_query_collect(s, [ten], qs, timestamp=T0,
                                    runner=runner)
            assert sorted(map(str, cpu)) == sorted(map(str, dev)), qs
        assert runner.fused_dispatches > 0
    finally:
        s.close()


def test_mesh_runner_staged_arrays_are_sharded(tmp_path):
    """The staged row matrices really spread over the mesh (not silently
    replicated): at least the stats-layout arrays shard on axis 0."""
    from victorialogs_tpu.engine.searcher import run_query_collect
    from victorialogs_tpu.parallel.distributed import MeshBatchRunner

    s, ten = _mk_storage(tmp_path)
    try:
        runner = MeshBatchRunner(make_mesh(8))
        run_query_collect(s, [ten],
                          "* | stats by (_time:5m) sum(dur) x",
                          timestamp=T0, runner=runner)
        staged = [v for k, v in runner.cache._lru.items()
                  if isinstance(k, tuple) and "#num" in k]
        assert staged
        sharding = staged[0].values.sharding
        assert len(sharding.device_set) == 8
        assert not sharding.is_fully_replicated  # really split, axis 0
    finally:
        s.close()
