"""Table tests for the transform pipes (extract/format/math/unpack/...).

Shape mirrors the reference's table-driven pipe tests
(lib/logstorage/pipe_extract_test.go etc.): run a query over in-memory rows
and compare the full result rows."""

import math

import pytest

from victorialogs_tpu.engine.searcher import run_query_collect
from victorialogs_tpu.logsql.parser import parse_query
from victorialogs_tpu.logsql.pipes_transform import (Pattern, parse_logfmt,
                                                     unpack_json_array)
from victorialogs_tpu.storage.log_rows import LogRows, TenantID
from victorialogs_tpu.storage.storage import Storage

NS = 1_000_000_000
T0 = 1_753_660_800_000_000_000
TEN = TenantID(0, 0)


@pytest.fixture()
def store(tmp_path):
    s = Storage(str(tmp_path), retention_days=100000, flush_interval=3600)
    yield s
    s.close()


def _ingest(s, rows):
    lr = LogRows(stream_fields=["app"])
    for i, fields in enumerate(rows):
        lr.add(TEN, T0 + i * NS, [("app", "a")] + list(fields.items()))
    s.must_add_rows(lr)
    s.debug_flush()


def q(s, query):
    return run_query_collect(s, [TEN], query, timestamp=T0)


# ---------------- pattern engine unit tests ----------------

def test_pattern_basic():
    p = Pattern("ip=<ip> port=<port>")
    assert p.apply("ip=1.2.3.4 port=80") == {"ip": "1.2.3.4", "port": "80"}
    assert p.apply("nope") == {"ip": "", "port": ""}
    # leading junk before the first prefix is skipped
    assert p.apply("xx ip=9.9.9.9 port=1")["ip"] == "9.9.9.9"


def test_pattern_last_field_takes_rest():
    p = Pattern("user=<user>")
    assert p.apply("user=alice bob") == {"user": "alice bob"}


def test_pattern_quoted():
    p = Pattern("msg=<msg> code=<code>")
    assert p.apply('msg="hello world" code=3') == \
        {"msg": "hello world", "code": "3"}
    # plain: option disables unquoting
    p2 = Pattern("msg=<plain:msg> code=<code>")
    assert p2.apply('msg="a b" code=3') == {"msg": '"a b"', "code": "3"}


def test_pattern_html_escaped_prefix():
    p = Pattern("&lt;<tag>&gt;")
    assert p.apply("<div>") == {"tag": "div"}


def test_logfmt_parser():
    assert parse_logfmt('a=1 b="x y" c=') == \
        [("a", "1"), ("b", "x y"), ("c", "")]


def test_unpack_json_array():
    assert unpack_json_array('[1,"a",true,null]') == ["1", "a", "true", ""]
    assert unpack_json_array('"scalar"') == []
    assert unpack_json_array("notjson") == []


# ---------------- extract ----------------

def test_extract_pipe(store):
    _ingest(store, [{"_msg": "ip=1.2.3.4 port=80 ok"},
                    {"_msg": "ip=5.6.7.8 port=443 ok"},
                    {"_msg": "garbage"}])
    rows = q(store, '* | extract "ip=<ip> port=<port> " | fields ip, port')
    assert rows == [{"ip": "1.2.3.4", "port": "80"},
                    {"ip": "5.6.7.8", "port": "443"},
                    {}]


def test_extract_if_and_keep_original(store):
    _ingest(store, [{"_msg": "x=new", "x": "old"},
                    {"_msg": "x=other", "x": ""}])
    rows = q(store, '* | extract if (x:"") "x=<x>" | fields x')
    assert rows == [{"x": "old"}, {"x": "other"}]
    rows = q(store, '* | extract "x=<x>" keep_original_fields | fields x')
    assert rows == [{"x": "old"}, {"x": "other"}]


def test_extract_regexp(store):
    _ingest(store, [{"_msg": "took 25ms"}, {"_msg": "took 1300ms"},
                    {"_msg": "no-match"}])
    rows = q(store, r'* | extract_regexp `took (?P<ms>\d+)ms` | fields ms')
    assert rows == [{"ms": "25"}, {"ms": "1300"}, {}]


# ---------------- format ----------------

def test_format_pipe(store):
    _ingest(store, [{"a": "1", "b": "x"}, {"a": "2", "b": "y"}])
    rows = q(store, '* | format "a=<a>, b=<b>" as out | fields out')
    assert rows == [{"out": "a=1, b=x"}, {"out": "a=2, b=y"}]


def test_format_options(store):
    _ingest(store, [{"v": "abC", "n": "3000000000", "ip": "16909060"}])
    rows = q(store, '* | format "<uc:v>|<lc:v>|<q:v>" as out | fields out')
    assert rows == [{"out": 'ABC|abc|"abC"'}]
    rows = q(store, '* | format "<duration:n>" as out | fields out')
    assert rows == [{"out": "3s"}]
    rows = q(store, '* | format "<ipv4:ip>" as out | fields out')
    assert rows == [{"out": "1.2.3.4"}]
    rows = q(store, '* | format "<base64encode:v>" as out | fields out')
    assert rows == [{"out": "YWJD"}]


# ---------------- math ----------------

def test_math_pipe(store):
    _ingest(store, [{"a": "10", "b": "3"}, {"a": "7", "b": "0"}])
    rows = q(store, "* | math a + b as s, a % b as m, a / b as d, "
                    "a ^ 2 as p | fields s, m, d, p")
    assert rows[0] == {"s": "13", "m": "1", "d": "3.3333333333333335",
                      "p": "100"}
    assert rows[1]["s"] == "7"
    assert rows[1]["m"] == "NaN"
    assert rows[1]["d"] == "NaN"


def test_math_precedence_and_funcs(store):
    _ingest(store, [{"a": "2", "b": "8"}])
    rows = q(store, "* | math a + b * 2 as x, (a + b) * 2 as y, "
                    "max(a, b, 5) as mx, min(a, b) as mn, "
                    "round(7.6) as r, floor(7.6) as fl, ceil(7.2) as ce, "
                    "abs(-3) as ab, b default 9 as df, "
                    "unknown_field default 9 as df2 "
                    "| fields x, y, mx, mn, r, fl, ce, ab, df, df2")
    assert rows == [{"x": "18", "y": "20", "mx": "8", "mn": "2", "r": "8",
                     "fl": "7", "ce": "8", "ab": "3", "df": "8",
                     "df2": "9"}]


def test_math_bitwise(store):
    _ingest(store, [{"a": "12", "b": "10"}])
    rows = q(store, "* | math a & b as x, a or b as o, a xor b as xo "
                    "| fields x, o, xo")
    assert rows == [{"x": "8", "o": "14", "xo": "6"}]


def test_math_durations(store):
    _ingest(store, [{"d": "2m30s"}])
    rows = q(store, "* | math d / 1e9 as secs | fields secs")
    assert rows == [{"secs": "150"}]


# ---------------- unpack ----------------

def test_unpack_json(store):
    _ingest(store, [{"_msg": '{"level":"info","nested":{"x":"1"},'
                             '"num":42}'},
                    {"_msg": "not json"}])
    rows = q(store, "* | unpack_json | fields level, nested.x, num")
    assert rows == [{"level": "info", "nested.x": "1", "num": "42"}, {}]


def test_unpack_json_opts(store):
    _ingest(store, [{"_msg": '{"a":"1","b":"2"}'}])
    rows = q(store, "* | unpack_json fields (a) result_prefix p_ "
                    "| fields p_a, p_b")
    assert rows == [{"p_a": "1"}]


def test_unpack_logfmt(store):
    _ingest(store, [{"_msg": 'level=warn msg="disk full" free=5GB'}])
    rows = q(store, "* | unpack_logfmt | fields level, msg, free")
    assert rows == [{"level": "warn", "msg": "disk full", "free": "5GB"}]


def test_unpack_syslog(store):
    _ingest(store, [{"_msg": "<165>1 2024-06-01T12:00:00Z host app 123 - "
                             "- boom happened"}])
    rows = q(store, "* | unpack_syslog | fields hostname, app_name, "
                    "severity")
    assert rows == [{"hostname": "host", "app_name": "app",
                     "severity": "5"}]


def test_unpack_words(store):
    _ingest(store, [{"_msg": "foo bar foo"}])
    rows = q(store, "* | unpack_words as w | fields w")
    assert rows == [{"w": '["foo","bar","foo"]'}]
    rows = q(store, "* | unpack_words as w drop_duplicates | fields w")
    assert rows == [{"w": '["foo","bar"]'}]


# ---------------- replace ----------------

def test_replace(store):
    _ingest(store, [{"_msg": "a-b-c-d"}])
    rows = q(store, '* | replace ("-", "_") | fields _msg')
    assert rows == [{"_msg": "a_b_c_d"}]
    rows = q(store, '* | replace ("-", "_") limit 2 | fields _msg')
    assert rows == [{"_msg": "a_b_c-d"}]


def test_replace_regexp(store):
    _ingest(store, [{"_msg": "id=12345 user=9"}])
    rows = q(store, r'* | replace_regexp (`\d+`, "N") | fields _msg')
    assert rows == [{"_msg": "id=N user=N"}]


def test_replace_at_field_with_if(store):
    _ingest(store, [{"u": "secret", "keep": "y"}, {"u": "secret"}])
    rows = q(store, '* | replace if (keep:"") ("secret", "xxx") at u '
                    '| fields u')
    assert rows == [{"u": "secret"}, {"u": "xxx"}]


# ---------------- top / len / pack / sample / unroll / misc ----------------

def test_top_pipe(store):
    _ingest(store, [{"k": "a"}] * 5 + [{"k": "b"}] * 3 + [{"k": "c"}])
    rows = q(store, "* | top 2 by (k)")
    assert rows == [{"k": "a", "hits": "5"}, {"k": "b", "hits": "3"}]
    rows = q(store, "* | top 2 by (k) rank as r")
    assert rows == [{"k": "a", "hits": "5", "r": "1"},
                    {"k": "b", "hits": "3", "r": "2"}]


def test_len_pipe(store):
    _ingest(store, [{"_msg": "hello"}, {"_msg": "日本"}])
    rows = q(store, "* | len(_msg) as l | fields l")
    assert rows == [{"l": "5"}, {"l": "6"}]  # utf-8 byte length


def test_pack_json(store):
    _ingest(store, [{"a": "1", "b": "x"}])
    rows = q(store, "* | pack_json fields (a, b) as out | fields out")
    assert rows == [{"out": '{"a":"1","b":"x"}'}]


def test_pack_logfmt(store):
    _ingest(store, [{"a": "1", "b": "x y"}])
    rows = q(store, "* | pack_logfmt fields (a, b) as out | fields out")
    assert rows == [{"out": 'a=1 b="x y"'}]


def test_sample_pipe(store):
    _ingest(store, [{"_msg": f"m{i}"} for i in range(300)])
    rows = q(store, "* | sample 1")
    assert len(rows) == 300
    rows = q(store, "* | sample 3 | stats count() n")
    n = int(rows[0]["n"])
    assert 30 <= n <= 250  # ~100 expected


def test_unroll_pipe(store):
    _ingest(store, [{"_msg": "r1", "tags": '["a","b"]'},
                    {"_msg": "r2", "tags": "notarray"}])
    rows = q(store, "* | unroll by (tags) | fields _msg, tags")
    assert rows == [{"_msg": "r1", "tags": "a"}, {"_msg": "r1", "tags": "b"},
                    {"_msg": "r2"}]


def test_drop_empty_fields(store):
    _ingest(store, [{"a": "1", "b": ""}, {"a": "", "b": ""}])
    rows = q(store, "* | fields a, b | drop_empty_fields")
    assert rows == [{"a": "1"}]


def test_field_names_values_pipes(store):
    _ingest(store, [{"x": "v1"}, {"x": "v2"}, {"x": "v1"}])
    rows = q(store, "* | field_values x")
    assert rows == [{"x": "v1", "hits": "2"}, {"x": "v2", "hits": "1"}]
    rows = q(store, "* | field_names")
    names = {r["name"] for r in rows}
    assert "x" in names and "_time" in names


def test_blocks_count(store):
    _ingest(store, [{"_msg": "a"}] * 10)
    rows = q(store, "* | blocks_count as bc")
    assert int(rows[0]["bc"]) >= 1


def test_pipe_roundtrip_to_string():
    for qs in [
        '* | extract "ip=<ip> port=<port>"',
        '* | extract if (x:y) "a=<a>" from f keep_original_fields',
        '* | format "a=<a>" as out',
        "* | math (a + b) * 2 as x",
        "* | unpack_json from f fields (a, b) result_prefix p_",
        "* | unpack_logfmt",
        "* | unpack_syslog",
        '* | replace ("a", "b") at f limit 3',
        '* | replace_regexp ("a.", "b") at f',
        "* | top 5 by (k) rank as r",
        "* | len(x) as l",
        "* | pack_json fields (a, b) as out",
        "* | sample 10",
        "* | unroll by (tags)",
        "* | field_names",
        "* | field_values x limit 5",
        "* | blocks_count",
        "* | drop_empty_fields",
        "* | unpack_words from f as w drop_duplicates",
    ]:
        parsed = parse_query(qs)
        again = parse_query(parsed.to_string())
        assert parsed.to_string() == again.to_string(), qs


def test_facets_pipe(store):
    _ingest(store, [{"k": "a", "lvl": "info"}] * 6
            + [{"k": "b", "lvl": "warn"}] * 3 + [{"k": "c", "lvl": "warn"}])
    rows = q(store, "* | facets 2")
    got = {(r["field_name"], r["field_value"]): int(r["hits"])
           for r in rows}
    assert got[("k", "a")] == 6 and got[("k", "b")] == 3
    assert got[("lvl", "info")] == 6 and got[("lvl", "warn")] == 4
    assert ("k", "c") not in got  # limit 2
    # const fields (app=a on every row) are dropped unless requested
    assert not any(f == "app" for f, _ in got)
    rows = q(store, "* | facets 2 keep_const_fields")
    assert any(r["field_name"] == "app" for r in rows)


def test_math_reference_eval_chain(store):
    # ported from pipe_math_test.go: results feed later expressions
    _ingest(store, [{"a": "v1", "b": "2", "c": "3"}])
    rows = q(store, "* | eval b+1 as a, a*2 as b, b-10.5+c as c "
                    "| fields a, b, c")
    assert rows == [{"a": "3", "b": "6", "c": "-1.5"}]


def test_math_reference_default_chain(store):
    _ingest(store, [{"a": "v1", "b": "2", "c": "3"},
                    {"a": "0", "b": "0", "c": "3"},
                    {"a": "3", "b": "2"},
                    {"a": "3", "b": "foo"}])
    rows = q(store, "* | math a / b default c as r | fields r")
    assert rows == [{"r": "3"}, {"r": "3"}, {"r": "1.5"}, {"r": "NaN"}]


def test_math_const_kinds(store):
    _ingest(store, [{"x": "1"}])
    rows = q(store, "* | math '123.45.67.89' + 1000 as ip, "
                    "10m5s + 10e9 as dur, 0xff & 0x0f as h, "
                    "'2024-05-30T01:02:03Z' ^ 1 as t "
                    "| fields ip, dur, h, t")
    assert rows == [{"ip": "2066564929", "dur": "615000000000",
                     "h": "15", "t": "1717030923000000000"}]


def test_math_optional_result_name(store):
    _ingest(store, [{"a": "6", "b": "2"}])
    rows = q(store, "* | math a / b")
    assert any(v == "3" for v in rows[0].values())


def test_format_hexnum_options(store):
    _ingest(store, [{"n": "123456789", "h": "75BCD15", "s": "AB",
                     "hx": "41"}])
    rows = q(store, '* | format "<hexnumencode:n>|<hexnumdecode:h>|'
                    '<hexencode:s>|<hexdecode:hx>" as out | fields out')
    assert rows == [{"out": "00000000075BCD15|123456789|4142|A"}]


def test_logfmt_reference_table():
    # ported from logfmt_parser_test.go
    cases = [
        ("", []),
        ("foo=bar", [("foo", "bar")]),
        ('foo="bar=baz x=y"', [("foo", "bar=baz x=y")]),
        ("foo=", [("foo", "")]),
        ("foo", [("foo", "")]),
        ("foo bar", [("foo", ""), ("bar", "")]),
        ("foo bar=baz", [("foo", ""), ("bar", "baz")]),
        ('foo=bar baz="x y" a=b',
         [("foo", "bar"), ("baz", "x y"), ("a", "b")]),
        ("  foo=bar  baz=x =z qwe",
         [("foo", "bar"), ("baz", "x"), ("_msg", "z"), ("qwe", "")]),
    ]
    for inp, want in cases:
        assert parse_logfmt(inp) == want, inp


def test_wildcard_field_selections(store):
    _ingest(store, [{"req_path": "/x", "req_method": "GET",
                     "resp_code": "200"}])
    rows = q(store, "* | fields req_*")
    assert rows == [{"req_path": "/x", "req_method": "GET"}]
    rows = q(store, "* | fields req_*, resp_code")
    assert rows == [{"req_path": "/x", "req_method": "GET",
                     "resp_code": "200"}]
    rows = q(store, '* | unpack_json from j fields (a*)',)
    # wildcard unpack: only a-prefixed keys surface
    _ingest(store, [{"j": '{"aa":"1","ab":"2","zz":"3"}'}])
    rows = q(store, '_msg:"" j:* | unpack_json from j fields (a*) '
                    '| fields aa, ab, zz')
    assert rows and rows[-1] == {"aa": "1", "ab": "2"}


def test_extract_reference_value_cases(store):
    # ported from pipe_extract_test.go (quoted-value unquoting + option
    # interactions); the skip_empty case's message has NO `a=...`, so the
    # empty <aa> extraction keeps the original value
    _ingest(store, [{"_msg": 'foo=bar baz="x y=z" ',
                     "aa": "foobar", "abc": "ippl"}])
    rows = q(store, '* | extract "baz=<abc> a=<aa>" skip_empty_results '
                    '| fields aa, abc')
    assert rows == [{"aa": "foobar", "abc": "x y=z"}]
    rows = q(store, '* | extract "baz=<abc> a=<aa>" | fields aa, abc')
    assert rows == [{"abc": "x y=z"}]  # aa extracted empty (omitted)


def test_extract_reference_quoted_value(store):
    _ingest(store, [{"_msg": 'foo=bar baz="x y=z" a=b',
                     "aa": "foobar", "abc": ""}])
    rows = q(store, '* | extract "baz=<abc> a=<aa>" | fields abc, aa')
    assert rows == [{"abc": "x y=z", "aa": "b"}]
    rows = q(store, '* | extract "baz=<abc> a=<aa>" keep_original_fields '
                    '| fields abc, aa')
    assert rows == [{"abc": "x y=z", "aa": "foobar"}]


def test_format_time_duration_reference_case(store):
    # ported from pipe_format_test.go
    _ingest(store, [{"foo": "1717328141123456789", "bar": "210123456789",
                     "baz": "1234567890", "d": "1h5m35s"}])
    rows = q(store, "* | format 'time=<time:foo>, "
                    "duration=<duration:bar>, "
                    "duration_secs=<duration_seconds:d> ip=<ipv4:baz>' "
                    "as x | fields x")
    assert rows == [{"x": "time=2024-06-02T11:35:41.123456789Z, "
                          "duration=3m30.123456789s, duration_secs=3935 "
                          "ip=73.150.2.210"}]


def test_format_time_decimal_unix(store):
    _ingest(store, [{"foo": "1717328141.123456789",
                     "bar": "1717328141.123456", "neg": "-1717328141"}])
    rows = q(store, "* | format 'a=<time:foo>, b=<time:bar>, "
                    "c=<time:neg>' as x | fields x")
    assert rows == [{"x": "a=2024-06-02T11:35:41.123456789Z, "
                          "b=2024-06-02T11:35:41.123456Z, "
                          "c=1915-08-01T12:24:19Z"}]


def test_unpack_json_reference_cases(store):
    # ported from pipe_unpack_json_test.go (option interactions with
    # pre-existing fields)
    _ingest(store, [{"_msg": '{"foo":"bar","z":"q","a":""}',
                     "foo": "x", "a": "foobar"}])
    rows = q(store, "* | unpack_json skip_empty_results "
                    "| fields foo, z, a")
    assert rows == [{"foo": "bar", "z": "q", "a": "foobar"}]
    rows = q(store, "* | unpack_json | fields foo, z, a")
    assert rows == [{"foo": "bar", "z": "q"}]  # a unpacked empty
    rows = q(store, "* | unpack_json keep_original_fields "
                    "| fields foo, z, a")
    assert rows == [{"foo": "x", "z": "q", "a": "foobar"}]
