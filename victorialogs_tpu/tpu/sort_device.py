"""Device top-k prefilter for `<filter> | sort by (field [desc]) limit N`.

The reference's pipe_sort_topk.go keeps only offset+limit rows in a heap
while every matching row still flows through the pipe (all values
materialize on the host).  The TPU-shaped move: the k-th best sort key
among the filter's definite matches is computed ON DEVICE in the same
dispatch as the filter tree (jax.lax.top_k over the staged uint32 value
offsets), and only rows at-or-above that threshold come back to the
host.  The host-side topk processor then runs unchanged over a few
hundred rows instead of millions — same comparator, same seq tie-breaks,
bit-identical output.

Soundness of the threshold (why the prefilter never drops a true top-k
row): let D = definite matches, M = maybe rows (truncation overflow
etc.), T = true matches (D ⊆ T ⊆ D ∪ M).  kv_D, the k-th best key over
D, satisfies kv_T >= kv_D (adding candidates only raises the k-th best),
so every true top-k row has key >= kv_T >= kv_D.  The dispatch returns
(D above threshold) plus (M above threshold); the host verifies the M
rows with the filter's own predicate before feeding them downstream.

Eligibility mirrors the host comparator: a single by-field whose
candidate blocks are all int-typed (canonical decimal encodings —
numeric order == _cmp_values order, ties only between equal values,
which the processor breaks by arrival order exactly like the CPU path).
"""

from __future__ import annotations

from dataclasses import dataclass

MAX_TOPK = 4096  # top_k cost grows with k; beyond this the host heap wins


@dataclass
class SortSpec:
    field: str
    desc: bool                    # effective order (field desc XOR global)
    k: int                        # limit + offset


def device_sort_spec(q) -> SortSpec | None:
    """Static per-query analysis: can pipes[0] run as a device top-k
    prefilter?  Shape: plain `sort by (one_field [desc]) [offset O]
    limit N` — partition_by, multi-field sorts and special fields
    decline (the host path handles them)."""
    if not q.pipes:
        return None
    ps = q.pipes[0]
    from ..logsql.pipes import PipeSort
    if type(ps) is not PipeSort or getattr(ps, "name", "") != "sort":
        return None
    if ps.partition_by or ps.limit <= 0 or len(ps.by) != 1:
        return None
    fld, fdesc = ps.by[0]
    if fld in ("_time", "_stream", "_stream_id") or "*" in fld:
        return None
    k = ps.limit + ps.offset
    if k <= 0 or k > MAX_TOPK:
        return None
    # effective descending iff field-desc XOR global desc (PipeSort._sort_cmp)
    return SortSpec(field=fld, desc=(bool(fdesc) != bool(ps.desc)), k=k)
