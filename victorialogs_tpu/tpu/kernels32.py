"""u32-lane scan kernels: the bandwidth-efficient device string scan.

The round-3 kernel (kernels.match_scan) tested every window offset with
`pat_len` byte-plane compares over a uint8[R, W] matrix.  On TPU every
uint8 lane occupies a full 32-bit VPU lane, so that design pays
~2*pat_len lane-ops per byte scanned — measured at ~6% of v5e HBM
bandwidth (PERF.md round-3 dissection).  This module is the round-4
rewrite; the same semantics (bit-identical vs logsql.matchers and
kernels.match_scan, which stays as the oracle) at ~4-8x fewer lane-ops:

- **u32 chunks**: the staged column is a uint32[W/4, R] matrix (4 bytes
  per lane, transposed so the ROW axis rides the 128-wide lane
  dimension and is shardable over a mesh).  A pattern compare tests 4
  bytes per lane-op: window starts split by alignment a in 0..3, and a
  window at s=4q+a matches iff ceil(pat_len/4) masked u32 compares hit.
- **SWAR byte predicates**: word-char table, ASCII case fold and
  newline detection run as parallel-per-byte bit tricks on u32 lanes
  (4 bytes/lane-op) instead of byte-plane compares.
- **exact/exact-prefix collapse**: whole-value equality only inspects
  window 0 — ceil(L/4) compares on (R,) vectors, no window matrix.

Layout contract (tpu/layout.py to_lanes32): lanes_t[q, r] is the
little-endian uint32 of bytes rows[r, 4q:4q+4]; tail padding is 0xFF
(never valid UTF-8, so padded windows cannot match and 0xFF is not a
word char).  Pattern chunk constants are built with the SAME in-trace
bitcast as the data, so data/pattern byte order always agree; the
byte-shift helpers assume a little-endian target (every XLA backend we
run — CPU x86-64, TPU — is little-endian; tests assert it).

Reference semantics anchored at filter_phrase.go:61-111 (word/phrase
match), filter_exact.go, filter_prefix.go; the tokenizer word table at
tokenizer.go:34-148.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .kernels import (MODE_EXACT, MODE_EXACT_PREFIX, MODE_PHRASE,
                      MODE_PREFIX, MODE_SUBSTRING)

_U32 = jnp.uint32


def _c(v: int) -> jnp.ndarray:
    return _U32(v & 0xFFFFFFFF)


# ---------------- SWAR byte predicates on u32 lanes ----------------
#
# All four bytes of a lane are tested in parallel; results arrive as a
# high-bit-per-byte mask (0x80 set in byte k iff byte k satisfies the
# predicate).  Range checks clear bit 7 first (x7) so per-byte adds
# never carry across byte boundaries; bytes >= 0x80 are handled via hb.

_LO7 = 0x7F7F7F7F
_HI1 = 0x80808080
_ONES = 0x01010101


def _rng(x7: jnp.ndarray, lo: int, hi: int) -> jnp.ndarray:
    """hi-bit-per-byte mask: lo <= byte7 <= hi (byte7 = byte & 0x7F;
    lo/hi must be < 0x80).  Carry-free: byte7 + (0x80-lo) <= 0xFE and
    (0x80+hi) - byte7 >= 1."""
    ge = x7 + _c((0x80 - lo) * _ONES)
    le = _c((0x80 + hi) * _ONES) - x7
    return ge & le


def word_hibits(x: jnp.ndarray) -> jnp.ndarray:
    """hi-bit-per-byte word-char mask (tokenizer table: [A-Za-z0-9_]
    plus any byte >= 0x80 except the 0xFF padding)."""
    x7 = x & _c(_LO7)
    hb = x & _c(_HI1)
    alnum = (_rng(x7, 0x61, 0x7A) | _rng(x7, 0x41, 0x5A) |
             _rng(x7, 0x30, 0x39) | _rng(x7, 0x5F, 0x5F))
    is_ff = _rng(x7, 0x7F, 0x7F) & hb
    return ((alnum & ~hb) | (hb & ~is_ff)) & _c(_HI1)


def fold_ascii32(x: jnp.ndarray) -> jnp.ndarray:
    """Per-byte ASCII lowercase fold (A-Z -> a-z), other bytes — incl.
    0xFF padding and multibyte UTF-8 — unchanged.  Exact counterpart of
    kernels._fold_ascii: adding 0x20 to bytes <= 0x5A never carries."""
    x7 = x & _c(_LO7)
    hb = x & _c(_HI1)
    upper = _rng(x7, 0x41, 0x5A) & ~hb & _c(_HI1)
    return x + (upper >> 2)


def any_byte_eq(x: jnp.ndarray, byte: int) -> jnp.ndarray:
    """hi-bit-per-byte mask of bytes == `byte` (haszero trick on
    x ^ byte*ONES).  May set a false hi bit only when a LOWER byte of
    the same lane is a true match (borrow propagation), so any-reduced
    uses are exact."""
    y = x ^ _c(byte * _ONES)
    return (y - _c(_ONES)) & ~y & _c(_HI1)


# ---------------- pattern chunking ----------------

def _pattern_chunks(pattern: jnp.ndarray, pat_len: int):
    """(chunk u32[nc], static mask ints): chunk c covers pattern bytes
    [4c, 4c+4); the last chunk's mask zeroes bytes past pat_len.  Built
    with the same bitcast the data layout uses, so byte order agrees on
    any backend."""
    nc = (pat_len + 3) // 4
    pad = nc * 4 - pat_len
    p = pattern
    if pad:
        p = jnp.concatenate([p, jnp.zeros((pad,), jnp.uint8)])
    pc = jax.lax.bitcast_convert_type(p.reshape(nc, 4), _U32)
    rem = pat_len % 4
    masks = [0xFFFFFFFF] * nc
    if rem:
        mb = np.array([0xFF] * rem + [0] * (4 - rem), dtype=np.uint8)
        masks[-1] = int(mb.view("<u4")[0])
    return pc, masks, nc


def _shifted(ext: jnp.ndarray, a: int, n: int) -> jnp.ndarray:
    """u32 at byte offset 4q+a for lane rows q in [0, n): little-endian
    combine of ext[q] and ext[q+1].  ext: u32[>=n+1, R]."""
    if a == 0:
        return ext[:n]
    return (ext[:n] >> _U32(8 * a)) | (ext[1:n + 1] << _U32(32 - 8 * a))


# ---------------- the scan ----------------

@partial(jax.jit, static_argnames=("pat_len", "mode", "starts_tok",
                                   "ends_tok", "fold"))
def match_scan_t(lanes_t: jnp.ndarray, lengths: jnp.ndarray,
                 pattern: jnp.ndarray, pat_len: int, mode: int,
                 starts_tok: bool, ends_tok: bool,
                 fold: bool = False) -> jnp.ndarray:
    """Per-row match bitmap over a lane-major staged string column.

    lanes_t: uint32[W/4, R] (layout.to_lanes32); lengths: int32[R] true
    byte lengths (truncated at W-1; overflow rows re-checked on host);
    pattern: uint8[pat_len], pre-lowered when fold=True.
    Semantics identical to kernels.match_scan (the oracle); returns
    bool[R].
    """
    nl, r = lanes_t.shape
    pc, masks, nc = _pattern_chunks(pattern, pat_len)
    if fold:
        lanes_t = fold_ascii32(lanes_t)

    if mode in (MODE_EXACT, MODE_EXACT_PREFIX):
        # window 0 only: compare the first nc lanes of each row
        acc = None
        for c in range(nc):
            lane = lanes_t[c] if c < nl else _c(0xFFFFFFFF)
            if masks[c] == 0xFFFFFFFF:
                t = lane == pc[c]
            else:
                t = ((lane ^ pc[c]) & _c(masks[c])) == 0
            acc = t if acc is None else acc & t
        if mode == MODE_EXACT:
            return acc & (lengths == pat_len)
        return acc & (lengths >= pat_len)

    # extension lanes of 0xFF padding: windows past the row width can
    # never match (patterns are UTF-8 and contain no 0xFF byte)
    ext = jnp.concatenate(
        [lanes_t, jnp.full((nc, r), 0xFFFFFFFF, _U32)], axis=0)

    need_start = starts_tok and mode in (MODE_PHRASE, MODE_PREFIX)
    need_end = ends_tok and mode == MODE_PHRASE
    wm = word_hibits(ext) if (need_start or need_end) else None
    if need_start:
        # wmp[q] = word mask of lane q-1 (lane -1 = before the string:
        # a zero row, so window 0 always has a start boundary)
        wmp = jnp.concatenate([jnp.zeros((1, r), _U32), wm], axis=0)

    hit = None
    for a in range(4):
        s = _shifted(ext, a, nl + nc - 1)
        acc = None
        for c in range(nc):
            lanes = s[c:c + nl]
            if masks[c] == 0xFFFFFFFF:
                t = lanes == pc[c]
            else:
                t = ((lanes ^ pc[c]) & _c(masks[c])) == 0
            acc = t if acc is None else acc & t
        if need_start:
            # byte before window s=4q+a is byte (a-1) of lane q, or
            # byte 3 of lane q-1 when a == 0
            if a == 0:
                pw = (wmp[:nl] >> _U32(31)) & _U32(1)
            else:
                pw = (wm[:nl] >> _U32(8 * (a - 1) + 7)) & _U32(1)
            acc = acc & (pw == 0)
        if need_end:
            # byte after window is byte offset 4q + a + pat_len
            t_off = a + pat_len
            lq, lb = t_off // 4, t_off % 4
            nw = (wm[lq:lq + nl] >> _U32(8 * lb + 7)) & _U32(1)
            acc = acc & (nw == 0)
        h = jnp.any(acc, axis=0)
        hit = h if hit is None else hit | h
    return hit & (lengths >= pat_len)


@partial(jax.jit, static_argnames=("pat_len", "mode", "starts_tok",
                                   "ends_tok", "fold"))
def match_scan_t_packed(lanes_t, lengths, pattern, pat_len, mode,
                        starts_tok, ends_tok, fold=False):
    """match_scan_t with the bitmap bit-packed on device before download
    (bool[4M] costs ~213ms through the tunnel; packed ~11ms)."""
    return jnp.packbits(match_scan_t(lanes_t, lengths, pattern, pat_len,
                                     mode, starts_tok, ends_tok,
                                     fold).astype(jnp.uint8))


def _window_hits(ext: jnp.ndarray, nl: int, pattern: jnp.ndarray,
                 pat_len: int):
    """Per-alignment window-equality masks: list of bool[nl, R] for
    a in 0..3 (window start s = 4q + a)."""
    pc, masks, nc = _pattern_chunks(pattern, pat_len)
    out = []
    for a in range(4):
        s = _shifted(ext, a, nl + nc - 1)
        acc = None
        for c in range(nc):
            lanes = s[c:c + nl]
            if masks[c] == 0xFFFFFFFF:
                t = lanes == pc[c]
            else:
                t = ((lanes ^ pc[c]) & _c(masks[c])) == 0
            acc = t if acc is None else acc & t
        out.append(acc)
    return out


@partial(jax.jit, static_argnames=("len_a", "len_b"))
def match_ordered_pair_t(lanes_t: jnp.ndarray, lengths: jnp.ndarray,
                         pat_a: jnp.ndarray, len_a: int,
                         pat_b: jnp.ndarray, len_b: int):
    """`A.*B` decomposition on the lane-major layout: matches iff the
    FIRST occurrence of A ends at or before the LAST occurrence of B.
    Rows containing a newline go to the needs-verify channel ('.' does
    not cross newlines).  Returns (definite bool[R], needs_verify
    bool[R]) — semantics identical to kernels.match_ordered_pair."""
    nl, r = lanes_t.shape
    nc_max = (max(len_a, len_b) + 3) // 4
    ext = jnp.concatenate(
        [lanes_t, jnp.full((nc_max, r), 0xFFFFFFFF, _U32)], axis=0)
    big = jnp.int32(4 * nl + 8)

    hits_a = _window_hits(ext, nl, pat_a, len_a)
    hits_b = _window_hits(ext, nl, pat_b, len_b)
    any_a = None
    first_a = big
    any_b = None
    last_b = jnp.int32(-1)
    for a in range(4):
        ha, hb = hits_a[a], hits_b[a]
        ra = jnp.any(ha, axis=0)
        rb = jnp.any(hb, axis=0)
        any_a = ra if any_a is None else any_a | ra
        any_b = rb if any_b is None else any_b | rb
        fq = jnp.argmax(ha, axis=0).astype(jnp.int32)       # first hit lane
        pa = jnp.where(ra, 4 * fq + a, big)
        first_a = jnp.minimum(first_a, pa)
        lq = (nl - 1) - jnp.argmax(hb[::-1], axis=0).astype(jnp.int32)
        pb = jnp.where(rb, 4 * lq + a, jnp.int32(-1))
        last_b = jnp.maximum(last_b, pb)
    any_a = any_a & (lengths >= len_a)
    any_b = any_b & (lengths >= len_b)
    ordered = any_a & any_b & (first_a + len_a <= last_b)
    has_nl = jnp.any(any_byte_eq(lanes_t, 0x0A) != 0, axis=0)
    return ordered & ~has_nl, ordered & has_nl


@partial(jax.jit, static_argnames=("len_a", "len_b"))
def match_ordered_pair_t_packed(lanes_t, lengths, pat_a, len_a,
                                pat_b, len_b):
    """Both result vectors packed into ONE uint8[2, R/8] download."""
    definite, needsv = match_ordered_pair_t(lanes_t, lengths, pat_a,
                                            len_a, pat_b, len_b)
    return jnp.stack([jnp.packbits(definite.astype(jnp.uint8)),
                      jnp.packbits(needsv.astype(jnp.uint8))], axis=0)
