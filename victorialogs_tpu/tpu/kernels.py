"""Device kernels for the block runner (jnp/XLA).

The flagship kernel is the byte-arena phrase/substring scan: a column block's
string values are staged as one padded uint8 arena plus row offsets, and the
kernel tests every window position against the pattern with word-boundary
semantics bit-identical to logsql.matchers.match_phrase / match_prefix (the
correctness oracle).  All control flow is static — one compile per
(arena bucket size, rows bucket, pattern length, mode) — so XLA fuses the
whole scan into a handful of vector loops over VMEM tiles.

Semantics notes:
- arena padding bytes are 0xFF: never part of valid UTF-8, so padded windows
  can't produce false matches; padded bytes map to segment `nrows`, which is
  dropped by the segment reduction.
- word chars = ASCII alnum + '_' + any byte >= 0x80 (same table as the
  tokenizer and matchers — utils/tokenizer.py).
- patterns are capped at MAX_PATTERN_LEN bytes; longer patterns fall back to
  the CPU path (runner.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

MAX_PATTERN_LEN = 64

MODE_PHRASE = 0        # substring with word boundaries on both sides
MODE_PREFIX = 1        # substring with word boundary before only
MODE_SUBSTRING = 2     # plain substring (regex literal prefilter)
MODE_EXACT = 3         # whole value equality
MODE_EXACT_PREFIX = 4  # value startswith


def _is_word_u8(b: jnp.ndarray) -> jnp.ndarray:
    """Word-char test on uint8 bytes (VPU compares, no gather).

    0xFF is excluded: it cannot occur in UTF-8 data, and staging uses it as
    the inter-value separator (row boundary)."""
    return ((b >= ord("a")) & (b <= ord("z"))) | \
           ((b >= ord("A")) & (b <= ord("Z"))) | \
           ((b >= ord("0")) & (b <= ord("9"))) | \
           (b == ord("_")) | ((b >= 0x80) & (b != 0xFF))


def _fold_ascii(rows: jnp.ndarray) -> jnp.ndarray:
    """ASCII-lowercase fold on uint8 bytes (A-Z -> a-z; everything else —
    including the 0xFF padding and multibyte UTF-8 — unchanged).  Exact
    vs Python str.lower() for pure-ASCII values; rows containing bytes
    >= 0x80 are routed to host verification by the callers (Unicode case
    folding can map non-ASCII onto ASCII, e.g. U+212A -> 'k')."""
    return jnp.where((rows >= 0x41) & (rows <= 0x5A), rows + 0x20, rows)


@partial(jax.jit, static_argnames=("pat_len", "mode", "starts_tok",
                                   "ends_tok", "fold"))
def match_scan(rows: jnp.ndarray, lengths: jnp.ndarray,
               pattern: jnp.ndarray, pat_len: int, mode: int,
               starts_tok: bool, ends_tok: bool,
               fold: bool = False) -> jnp.ndarray:
    """Per-row match bitmap over a fixed-width staged string column.

    rows: uint8[R, W] — one value per row starting at column 0, tail-padded
          with 0xFF (which never occurs in UTF-8 data).  The fixed-width
          layout is the TPU-shaped choice: the per-row `any()` reduction is
          a pure axis reduction over (8,128) VPU tiles — no scatter/segment
          ops (~80ms/block serialized), no cumsum+gather (~210ms/batch of
          gathers) — both measured dead ends on real hardware.  Values
          longer than W-1 are truncated at staging and re-checked on the
          host (runner overflow path).
    lengths: int32[R] true value byte lengths
    pattern: uint8[pat_len]
    fold: ASCII-case-insensitive compare (pattern must arrive pre-lowered;
          the word-boundary table is case-agnostic so boundaries are
          computed on the folded bytes without semantic drift)
    returns bool[R]
    """
    if fold:
        rows = _fold_ascii(rows)
    r, w = rows.shape
    nwc = w - pat_len + 1  # window start columns

    # window equality: acc[:, i] = rows[:, i:i+pat_len] == pattern
    acc = jnp.ones((r, nwc), dtype=bool)
    for j in range(pat_len):
        acc = acc & (jax.lax.slice(rows, (0, j), (r, j + nwc))
                     == pattern[j])

    if mode in (MODE_EXACT, MODE_EXACT_PREFIX):
        hit = acc[:, 0]
        if mode == MODE_EXACT:
            return hit & (lengths == pat_len)
        return hit & (lengths >= pat_len)

    # word-boundary checks; rows start at col 0 (string start => boundary)
    # and padding bytes are 0xFF (non-word), so edges need no special data
    if starts_tok and mode in (MODE_PHRASE, MODE_PREFIX):
        prev = jax.lax.slice(rows, (0, 0), (r, nwc - 1))
        start_ok = jnp.concatenate(
            [jnp.ones((r, 1), dtype=bool), ~_is_word_u8(prev)], axis=1)
        acc = acc & start_ok
    if ends_tok and mode == MODE_PHRASE:
        nxt = jax.lax.slice(rows, (0, pat_len), (r, w))
        end_ok = jnp.concatenate(
            [~_is_word_u8(nxt), jnp.ones((r, 1), dtype=bool)], axis=1)
        acc = acc & end_ok

    return jnp.any(acc, axis=1) & (lengths >= pat_len)


@partial(jax.jit, static_argnames=("pat_len", "mode", "starts_tok",
                                   "ends_tok"))
def match_scan_batch(rows: jnp.ndarray, lengths: jnp.ndarray,
                     pattern: jnp.ndarray, pat_len: int,
                     mode: int, starts_tok: bool, ends_tok: bool
                     ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Batched scan over B stacked blocks in ONE dispatch.

    rows: uint8[B, R, W]; lengths: int32[B, R].
    Dispatch latency is precious (under the axon tunnel each completed call
    costs a ~65ms round trip once any result has been fetched), so the
    runner amortizes by scanning many blocks per dispatch and downloading
    one (B, R) bitmap + counts.
    Returns (bool[B, R] bitmaps, int32[B] per-block match counts).
    """
    def one(rw, l):
        return match_scan(rw, l, pattern, pat_len, mode, starts_tok,
                          ends_tok)
    bms = jax.vmap(one)(rows, lengths)
    return bms, jnp.sum(bms.astype(jnp.int32), axis=1)


def _window_eq(rows: jnp.ndarray, pattern: jnp.ndarray, pat_len: int
               ) -> jnp.ndarray:
    """acc[:, i] = rows[:, i:i+pat_len] == pattern (bool[R, W-pat_len+1])."""
    r, w = rows.shape
    nwc = w - pat_len + 1
    acc = jnp.ones((r, nwc), dtype=bool)
    for j in range(pat_len):
        acc = acc & (jax.lax.slice(rows, (0, j), (r, j + nwc))
                     == pattern[j])
    return acc


@partial(jax.jit, static_argnames=("len_a", "len_b"))
def match_ordered_pair(rows: jnp.ndarray, lengths: jnp.ndarray,
                       pat_a: jnp.ndarray, len_a: int,
                       pat_b: jnp.ndarray, len_b: int):
    """Device decomposition of the `A.*B` regex family.

    A row matches /A.*B/ iff substring A ends at or before the LAST
    occurrence of B — computed from first-match(A) and last-match(B)
    positions, both pure argmax reductions over the window-equality matrix
    (no gather/scatter).  '.' does not cross newlines, so rows that contain
    a 0x0A byte are flagged for host re-verification instead of being
    decided on device.

    Returns (definite_match bool[R], needs_host_verify bool[R]).
    """
    acc_a = _window_eq(rows, pat_a, len_a)
    acc_b = _window_eq(rows, pat_b, len_b)
    any_a = jnp.any(acc_a, axis=1) & (lengths >= len_a)
    any_b = jnp.any(acc_b, axis=1) & (lengths >= len_b)
    first_a = jnp.argmax(acc_a, axis=1)
    last_b = (acc_b.shape[1] - 1) - jnp.argmax(acc_b[:, ::-1], axis=1)
    ordered = any_a & any_b & (first_a + len_a <= last_b)
    has_nl = jnp.any(rows == 0x0A, axis=1)
    return ordered & ~has_nl, ordered & has_nl


@partial(jax.jit, static_argnames=("pat_len", "mode", "starts_tok",
                                   "ends_tok", "fold"))
def match_scan_packed(rows: jnp.ndarray, lengths: jnp.ndarray,
                      pattern: jnp.ndarray, pat_len: int, mode: int,
                      starts_tok: bool, ends_tok: bool,
                      fold: bool = False) -> jnp.ndarray:
    """match_scan with the bitmap bit-packed ON DEVICE before download.

    A bool[4M] download costs ~213ms through the axon tunnel; the same
    bits packed cost ~11ms (tools/profile_device.py).  R is always a
    pad_bucket multiple, hence divisible by 8."""
    return jnp.packbits(match_scan(rows, lengths, pattern, pat_len, mode,
                                   starts_tok, ends_tok,
                                   fold).astype(jnp.uint8))


@partial(jax.jit, static_argnames=("len_a", "len_b"))
def match_ordered_pair_packed(rows: jnp.ndarray, lengths: jnp.ndarray,
                              pat_a: jnp.ndarray, len_a: int,
                              pat_b: jnp.ndarray, len_b: int) -> jnp.ndarray:
    """match_ordered_pair with BOTH result vectors packed into ONE
    download: uint8[2, R/8] — row 0 definite, row 1 needs-verify."""
    definite, needsv = match_ordered_pair(rows, lengths, pat_a, len_a,
                                          pat_b, len_b)
    return jnp.stack([jnp.packbits(definite.astype(jnp.uint8)),
                      jnp.packbits(needsv.astype(jnp.uint8))], axis=0)


# ---------------- bitmap combine (trivial but device-resident) ----------------

@jax.jit
def bitmap_and(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return a & b


@jax.jit
def bitmap_or(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return a | b


@jax.jit
def bitmap_not(a: jnp.ndarray) -> jnp.ndarray:
    return ~a


@jax.jit
def bitmap_count(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(a.astype(jnp.int32))


# ---------------- bucketed stats partials ----------------
#
# One-hot compare-and-reduce instead of segment_sum/min/max: scatter and
# segment ops serialize on this TPU (~80ms per 8MB block, measured round 1),
# while a (chunk, num_buckets) comparison matrix reduced along the row axis
# is pure VPU/MXU work.  The reduction runs as a lax.scan over fixed-size
# row chunks so peak memory stays bounded at any bucket count.  Sums are
# EXACT: the kernel reduces four uint8 byte-planes of the uint32 values
# (per-chunk plane sums < 2**24 stay exact in the f32 matmul; accumulation
# is uint32), and the host recombines planes with Python integers
# (tpu/stats_device.py).  This is the device half of the reference's stats
# partials contract (pipe_stats.go:354-377).

STATS_CHUNK = 8192  # rows per scan step; (chunk, buckets) tiles stay in VMEM


def stats_pad_rows(n: int) -> int:
    """Rows are staged padded to a STATS_CHUNK multiple (scan-friendly)."""
    return ((max(n, 1) + STATS_CHUNK - 1) // STATS_CHUNK) * STATS_CHUNK


def _vary(x, axes):
    """Mark a scan-carry constant as varying over shard_map manual axes
    (required so carry input/output types agree inside shard_map)."""
    if not axes:
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axes)
    return x  # pre-0.5 jax: carry types already agree without the cast


def shard_map_fn():
    """`jax.shard_map` graduated from jax.experimental between releases;
    resolve whichever this jax provides (same call signature)."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:  # pragma: no cover - depends on installed jax
        from jax.experimental.shard_map import shard_map as sm
    return sm


def stats_count_local(bucket_ids: jnp.ndarray, mask: jnp.ndarray,
                      num_buckets: int, vary_axes=()) -> jnp.ndarray:
    """Chunked masked-count body (also the per-shard body under
    shard_map — parallel/distributed.py reduces it with psum)."""
    b = bucket_ids.reshape(-1, STATS_CHUNK)
    m = mask.reshape(-1, STATS_CHUNK)
    buckets = jnp.arange(num_buckets, dtype=jnp.int32)

    def body(acc, xs):
        bi, mi = xs
        onehot = (bi[:, None] == buckets[None, :]) & mi[:, None]
        return acc + jnp.sum(onehot.astype(jnp.uint32), axis=0), None

    acc, _ = jax.lax.scan(
        body, _vary(jnp.zeros((num_buckets,), jnp.uint32), vary_axes),
        (b, m))
    return acc


def stats_values_local(values: jnp.ndarray, bucket_ids: jnp.ndarray,
                       mask: jnp.ndarray, num_buckets: int, vary_axes=()):
    """Chunked count/sum/min/max body; returns (cnt, sums[4,B], lo, hi)."""
    v = values.reshape(-1, STATS_CHUNK)
    b = bucket_ids.reshape(-1, STATS_CHUNK)
    m = mask.reshape(-1, STATS_CHUNK)
    buckets = jnp.arange(num_buckets, dtype=jnp.int32)
    u32max = jnp.uint32(0xFFFFFFFF)

    def body(carry, xs):
        cnt, sums, lo, hi = carry
        vi, bi, mi = xs
        onehot = (bi[:, None] == buckets[None, :]) & mi[:, None]
        cnt = cnt + jnp.sum(onehot.astype(jnp.uint32), axis=0)
        planes = jnp.stack(
            [((vi >> (8 * p)) & 0xFF).astype(jnp.float32)
             for p in range(4)], axis=1)                       # (C, 4)
        ps = jnp.einsum("cb,cp->pb", onehot.astype(jnp.float32),
                        planes)                                # exact < 2**24
        sums = sums + ps.astype(jnp.uint32)
        lo = jnp.minimum(lo, jnp.min(
            jnp.where(onehot, vi[:, None], u32max), axis=0))
        hi = jnp.maximum(hi, jnp.max(
            jnp.where(onehot, vi[:, None], jnp.uint32(0)), axis=0))
        return (cnt, sums, lo, hi), None

    init = tuple(
        _vary(a, vary_axes)
        for a in (jnp.zeros((num_buckets,), jnp.uint32),
                  jnp.zeros((4, num_buckets), jnp.uint32),
                  jnp.full((num_buckets,), u32max),
                  jnp.zeros((num_buckets,), jnp.uint32)))
    (cnt, sums, lo, hi), _ = jax.lax.scan(body, init, (v, b, m))
    return cnt, sums, lo, hi


def pack_stats(cnt, sums, lo, hi) -> jnp.ndarray:
    """One packed (7, B) result => ONE device->host download per dispatch
    (each download is a full ~65ms round trip under the axon tunnel)."""
    return jnp.concatenate([cnt[None], sums, lo[None], hi[None]], axis=0)


def combine_ids(ids_tuple, strides):
    """Row-major combined bucket index from per-axis id arrays
    (time buckets x group-by dict codes x quantile histograms); computed
    INSIDE the jit so multi-axis grouping costs no extra dispatch.
    Axes arrive as int32 (dict/time codes) or uint32 (quantile axes
    reusing the value staging) — cast unifies them."""
    c = None
    for a, s in zip(ids_tuple, strides):
        t = a.astype(jnp.int32)
        if s != 1:
            t = t * jnp.int32(s)
        c = t if c is None else c + t
    return c


@partial(jax.jit, static_argnames=("num_buckets", "strides"))
def stats_bucket_count(ids_tuple, strides, mask: jnp.ndarray,
                       num_buckets: int) -> jnp.ndarray:
    """Masked row count per combined bucket.

    ids_tuple: per-axis int32[R] arrays; strides: static per-axis
    multipliers; mask: bool[R]; R must be a STATS_CHUNK multiple (pad
    rows masked off).  Returns uint32[B]."""
    return stats_count_local(combine_ids(ids_tuple, strides), mask,
                             num_buckets)


@partial(jax.jit, static_argnames=("num_buckets", "strides"))
def stats_bucket_values(values: jnp.ndarray, ids_tuple, strides,
                        mask: jnp.ndarray, num_buckets: int):
    """count/sum/min/max partials per combined bucket for one uint32
    value column (offsets from the part minimum — see stage_numeric);
    returns uint32[7, B] packed as [count, plane_sums[0..3], vmin, vmax].
    Buckets with count 0 carry vmin=UINT32_MAX, vmax=0."""
    return pack_stats(*stats_values_local(
        values, combine_ids(ids_tuple, strides), mask, num_buckets))


def pad_bucket(n: int, minimum: int = 8192) -> int:
    """Pad sizes to coarse buckets so jit caches stay small."""
    b = minimum
    while b < n:
        b *= 2
    # refine with quarter steps of the previous power to cut waste
    for frac in (b // 2 + b // 8, b // 2 + b // 4, b // 2 + 3 * b // 8,
                 b // 2 + b // 2):
        if n <= frac:
            return frac
    return b
