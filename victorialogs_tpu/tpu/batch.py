"""Batched TPU execution: ONE device dispatch per filter leaf per part.

Round-1's BlockRunner dispatched one kernel per block per leaf with a
synchronous download each time (~65ms round trip under the axon tunnel once
sync mode engages), so an 8M-row query cost seconds on the device path.  This
module is the production path instead: a part's string column is staged into
HBM ONCE as a single fixed-width (rows, W) uint8 matrix covering every block
(parts are immutable, so the staging is cached across queries), and each
device-capable filter leaf becomes one `match_scan` dispatch over the whole
matrix, downloaded as one bool vector and sliced per block on the host.

This mirrors the reference's batched scanning (64-block batches per worker —
lib/logstorage/block_search.go:16, storage_search.go:1035-1121) reshaped for
a dispatch-latency-bound accelerator: fewer, bigger kernels win.

Filter-tree semantics are identical to the CPU path (the parity tests in
tests/test_tpu_runner.py and tests/test_batch_runner.py diff them bit-exactly):
- AND children evaluate left-to-right with block-level early exit;
- bloom pruning stays on the kill-path BEFORE staging
  (filter_phrase.go:302 analogue), but runs as one batched plane probe
  per (part, column) via the filter-index subsystem
  (storage/filterbank.py + tpu/bloom_device.py), not per block;
- rows longer than the staging width are truncated on device and re-checked
  on the host with the filter's full predicate;
- regex runs its mandatory-literal substring prefilter on device and
  re.search on the survivors only (filter_regexp.go:44-51 analogue).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field as dc_field

import os
import threading
import weakref

import numpy as np

from ..engine.block_search import BlockSearch
from .. import config
from ..logsql import filters as F
from ..obs import hist
from ..storage.filterbank import bloom_keep_mask
from ..storage.values_encoder import VT_DICT, VT_STRING
from ..utils.hashing import cached_token_hashes
from . import kernels as K
from . import kernels32 as K32
from .layout import StagingCache, row_width_bucket
from .kernels import pad_bucket


# ---------------- leaf planning ----------------

@dataclass
class ScanOp:
    pattern: bytes
    mode: int
    starts_tok: bool = False
    ends_tok: bool = False
    # specials that need no device scan:
    match_nonempty: bool = False   # prefix "": any non-empty value
    match_empty: bool = False      # contains "": only the empty value
    # ASCII-case-insensitive compare (pattern pre-lowered); rows with any
    # byte >= 0x80 are re-checked on the host — Unicode lower() can map
    # non-ASCII onto ASCII (U+212A -> 'k'), which the byte fold can't see
    fold: bool = False


@dataclass
class LeafPlan:
    filter: object                 # the original Filter (host fallback + pred)
    field: str
    ops: list
    combine: str                   # 'and' | 'or'
    bloom_tokens: list
    verify: bool = False           # re-check survivors with filter._pred
    pair: tuple | None = None      # (A, B) for the device `A.*B` fast path


def device_plan(f) -> LeafPlan | None:
    """Compile one filter leaf into device scan ops; None => host-only leaf."""
    from ..logsql.filters import canonical_field
    from ..logsql.matchers import is_word_char

    def ok(s: str) -> bool:
        return s.isascii() and 0 < len(s) <= K.MAX_PATTERN_LEN

    if isinstance(f, F.FilterPhrase):
        if not ok(f.phrase):
            return None
        return LeafPlan(f, canonical_field(f.field),
                        [ScanOp(f.phrase.encode(), K.MODE_PHRASE,
                                is_word_char(f.phrase[0]),
                                is_word_char(f.phrase[-1]))],
                        "and", f._tokens())

    if isinstance(f, F.FilterPrefix):
        fld = canonical_field(f.field)
        if not f.prefix:
            return LeafPlan(f, fld, [ScanOp(b"", 0, match_nonempty=True)],
                            "and", [])
        if not ok(f.prefix):
            return None
        return LeafPlan(f, fld,
                        [ScanOp(f.prefix.encode(), K.MODE_PREFIX,
                                is_word_char(f.prefix[0]), False)],
                        "and", f._tokens())

    if isinstance(f, F.FilterAnyCasePhrase):
        if not ok(f._lower):
            return None
        return LeafPlan(f, canonical_field(f.field),
                        [ScanOp(f._lower.encode(), K.MODE_PHRASE,
                                is_word_char(f._lower[0]),
                                is_word_char(f._lower[-1]), fold=True)],
                        "and", [])

    if isinstance(f, F.FilterAnyCasePrefix):
        fld = canonical_field(f.field)
        if not f._lower:
            # match_any_case_prefix("") == any non-empty value
            return LeafPlan(f, fld, [ScanOp(b"", 0, match_nonempty=True)],
                            "and", [])
        if not ok(f._lower):
            return None
        return LeafPlan(f, fld,
                        [ScanOp(f._lower.encode(), K.MODE_PREFIX,
                                is_word_char(f._lower[0]), False,
                                fold=True)],
                        "and", [])

    if isinstance(f, F.FilterExact):
        if not ok(f.value):
            return None
        return LeafPlan(f, canonical_field(f.field),
                        [ScanOp(f.value.encode(), K.MODE_EXACT)], "and", [])

    if isinstance(f, F.FilterExactPrefix):
        if not ok(f.prefix):
            return None
        return LeafPlan(f, canonical_field(f.field),
                        [ScanOp(f.prefix.encode(), K.MODE_EXACT_PREFIX)],
                        "and", [])

    if isinstance(f, F.FilterSequence):
        if not f.phrases or any(not ok(p) for p in f.phrases):
            return None
        # phrases carry word boundaries (match_sequence via phrase_pos):
        # MODE_PHRASE is exact per phrase; ORDER still needs host verify
        # when there is more than one
        ops = [ScanOp(p.encode(), K.MODE_PHRASE, is_word_char(p[0]),
                      is_word_char(p[-1])) for p in f.phrases]
        return LeafPlan(f, canonical_field(f.field), ops, "and",
                        f._tokens(), verify=len(f.phrases) > 1)

    if isinstance(f, F.FilterContainsAll):
        if f.subquery is not None and not f.values:
            return None
        return _contains_plan(f, require_all=True)

    if isinstance(f, F.FilterContainsAny):
        if f.subquery is not None and not f.values:
            return None
        return _contains_plan(f, require_all=False)

    if isinstance(f, F.FilterRegexp):
        from ..logsql.filters import canonical_field as cf
        import re
        # `A.*B` with literal A and B: decided fully on device (positions +
        # newline guard — kernels.match_ordered_pair); only rows containing
        # a newline fall back to host re.search
        pair = getattr(f, "_pair", None)  # computed once in __post_init__
        if pair is not None and all(len(p) <= K.MAX_PATTERN_LEN
                                    for p in pair):
            return LeafPlan(f, cf(f.field), [], "and", f._tokens(),
                            pair=pair)
        # full literal RUNS (partial words included) are sound for plain
        # substring prefilters; word tokens stay for the bloom kill-path
        literals = [t for t in getattr(f, "_substr_literals", []) if ok(t)]
        ops = [ScanOp(t.encode(), K.MODE_SUBSTRING) for t in literals]
        pure = (re.escape(f.pattern) == f.pattern and len(literals) == 1
                and literals[0] == f.pattern)
        return LeafPlan(f, cf(f.field), ops, "and", f._tokens(),
                        verify=not pure)

    return None


def device_plans(f) -> list:
    """All device-scannable leaf plans of a filter tree (prefetch uses the
    same bloom tokens / fields the evaluator will)."""
    out: list = []

    def walk(g):
        if isinstance(g, (F.FilterAnd, F.FilterOr)):
            for sub in g.filters:
                walk(sub)
        elif isinstance(g, F.FilterNot):
            walk(g.inner)
        else:
            plan = device_plan(g)
            if plan is not None and (plan.ops or plan.pair):
                out.append(plan)
    walk(f)
    return out


def _tree_has_time(f) -> bool:
    """Does the tree hold a FilterTime leaf (fused prefetch must stage
    the timestamp planes the planner's _time_leaf will ask for)?"""
    if isinstance(f, F.FilterTime):
        return True
    if isinstance(f, (F.FilterAnd, F.FilterOr)):
        return any(_tree_has_time(s) for s in f.filters)
    if isinstance(f, F.FilterNot):
        return _tree_has_time(f.inner)
    return False


def _contains_plan(f, require_all: bool) -> LeafPlan | None:
    from ..logsql.filters import canonical_field
    from ..logsql.matchers import is_word_char
    if not f.values:
        return None
    ops = []
    for p in f.values:
        if not p:
            ops.append(ScanOp(b"", 0, match_empty=True))
            continue
        if not p.isascii() or len(p) > K.MAX_PATTERN_LEN:
            return None
        ops.append(ScanOp(p.encode(), K.MODE_PHRASE, is_word_char(p[0]),
                          is_word_char(p[-1])))
    tokens = f._tokens() if require_all else []
    return LeafPlan(f, canonical_field(f.field), ops,
                    "and" if require_all else "or", tokens)


# ---------------- part-level staging ----------------

@dataclass
class StagedPart:
    rows: object                   # jax uint32[W/4, Rb] lane-major (kernels32)
    lengths: object                # jax int32[Rb]
    lengths_np: np.ndarray         # host copy (truncated at W-1)
    nrows: int                     # real staged rows
    width: int
    block_rows: dict               # block_idx -> (start, nrows)
    overflow: dict                 # block_idx -> np.ndarray of row idxs
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


_UNSTAGEABLE = object()  # cache marker: part+field can't be staged


def _row_accessor(bs: BlockSearch, field: str):
    """Per-row string access without materializing the whole column.

    Host verification touches only surviving rows; decoding the full
    block's value list (bs.values) wasted most of the device path's win
    on verify-heavy regex queries."""
    if field not in ("_time", "_stream", "_stream_id") and \
            field not in bs.consts():
        col = bs.column(field)
        if col is not None and col.vtype == VT_STRING:
            arena, offs, lens = col.arena, col.offsets, col.lengths

            def at(i: int) -> str:
                o = int(offs[i])
                return arena[o:o + int(lens[i])].tobytes().decode(
                    "utf-8", "replace")
            return at
    vals = bs.values(field)
    return vals.__getitem__


def stage_part_column(part, field: str,
                      max_bytes: int = 4 << 30,
                      put=None) -> StagedPart | None:
    """Stage every string-typed block of `field` in one (Rb, W) matrix.

    Blocks whose column is missing/const/dict/numeric are left out (the
    evaluator runs those on the host).  Returns None when nothing is
    stageable or the staged matrix would exceed max_bytes.
    put: host->device transfer (default jnp.asarray); a mesh runner passes
    a sharding device_put so the rows axis spreads over its devices."""
    import jax.numpy as jnp
    if put is None:
        def put(a, row_axis=0):
            return jnp.asarray(a)

    cols = {}
    total = 0
    max_len = 0
    for bi in range(part.num_blocks):
        col = part.block_column(bi, field)
        if col is None or col.vtype != VT_STRING:
            continue
        cols[bi] = col
        total += part.block_rows(bi)
        if col.lengths.size:
            max_len = max(max_len, int(col.lengths.max()))
    if not cols:
        return None
    w = row_width_bucket(max_len)
    rb = pad_bucket(max(total, 1), minimum=1024)
    if rb * (w + 4) > max_bytes:
        return None
    mat = np.full((rb, w), 0xFF, dtype=np.uint8)
    lens = np.zeros(rb, dtype=np.int32)
    block_rows = {}
    overflow = {}
    start = 0
    from .layout import to_fixed_width
    for bi, col in cols.items():
        r = int(col.offsets.shape[0])
        sub, _w, ov = to_fixed_width(col.arena, col.offsets, col.lengths,
                                     r, width=w)
        mat[start:start + r] = sub
        lens[start:start + r] = np.minimum(col.lengths, w - 1).astype(np.int32)
        block_rows[bi] = (start, r)
        if ov.size:
            overflow[bi] = ov
        start += r
    from .layout import to_lanes32
    return StagedPart(rows=put(to_lanes32(mat), row_axis=1),
                      lengths=put(lens),
                      lengths_np=lens, nrows=start, width=w,
                      block_rows=block_rows, overflow=overflow,
                      nbytes=rb * (w + 4))


# ---------------- stats staging (device partials) ----------------

_INT_VTYPES = None


def _int_vtypes():
    global _INT_VTYPES
    if _INT_VTYPES is None:
        from ..storage.values_encoder import (VT_INT64, VT_UINT8, VT_UINT16,
                                              VT_UINT32, VT_UINT64)
        _INT_VTYPES = (VT_UINT8, VT_UINT16, VT_UINT32, VT_UINT64, VT_INT64)
    return _INT_VTYPES


@dataclass
class StatsLayout:
    """Canonical whole-part row layout for stats dispatches: every block in
    index order (unlike string staging, which skips non-string blocks)."""
    starts: dict                   # block_idx -> row start
    nrows: int                     # real rows
    nrows_padded: int              # STATS_CHUNK multiple

    def device_bytes(self) -> int:
        return 64 * len(self.starts)


@dataclass
class StagedNumeric:
    """One value column staged for exact device stats.

    values: uint32 offsets from vmin over eligible (int-typed) blocks;
    other blocks hold 0 and must be masked off by the caller.  The same
    array doubles as the quantile-axis ids when vmax-vmin fits the
    histogram cap (combine_ids casts on device)."""
    values: object                 # jax uint32[Rp]
    vmin: int
    vmax: int
    eligible: frozenset            # block idxs with int-typed columns
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


@dataclass
class StagedBuckets:
    ids: object                    # jax int32[Rp]
    base: int                      # bucketed-ns value of bucket 0
    num_buckets: int
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


@dataclass
class StagedDict:
    """A group-by column staged as per-row GLOBAL dict codes.

    Eligible blocks are dict-encoded, const, or missing (missing/const
    map every row to one code; '' is a value like any other, matching the
    host's group-by semantics for absent fields)."""
    ids: object                    # jax int32[Rp]
    values: list                   # code -> value string (this part)
    eligible: frozenset            # block idxs covered
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


def stage_num_buckets(part, field: str, layout: StatsLayout,
                      fstep: float, foff: float,
                      put=None) -> StagedDict | None:
    """Stage a numeric group-by bucket axis: per-row codes into a table
    of bucket-KEY strings, using the HOST's exact formula
    (floor((v - off) / step) * step + off, keys via format_number) so
    group keys are bit-identical (pipes.PipeStats._bucket_value)."""
    import jax.numpy as jnp
    from ..logsql.stats_funcs import format_number
    from ..storage.values_encoder import VT_FLOAT64
    if put is None:
        put = jnp.asarray

    ids = np.zeros(layout.nrows_padded, dtype=np.int32)
    values: list[str] = []
    code_of: dict[str, int] = {}
    eligible = []
    numeric_vts = _int_vtypes() + (VT_FLOAT64,)
    for bi in range(part.num_blocks):
        meta = part.block_column_meta(bi, field)
        if meta is None or meta["t"] not in numeric_vts:
            continue  # const/dict/string/ipv4/ts blocks: host path
        col = part.block_column(bi, field)
        f = col.nums.astype(np.float64)
        vb = np.floor((f - foff) / fstep) * fstep + foff
        uniq, inv = np.unique(vb, return_inverse=True)
        remap = np.empty(uniq.shape[0], dtype=np.int32)
        for k, v in enumerate(uniq.tolist()):
            key = format_number(v)
            c = code_of.get(key)
            if c is None:
                c = code_of[key] = len(values)
                values.append(key)
            remap[k] = c
        start = layout.starts[bi]
        ids[start:start + f.shape[0]] = remap[inv]
        eligible.append(bi)
    if not eligible:
        return None
    return StagedDict(ids=put(ids), values=values,
                      eligible=frozenset(eligible),
                      nbytes=layout.nrows_padded * 4)


def stage_dict_codes(part, field: str, layout: StatsLayout,
                     put=None) -> StagedDict | None:
    """Stage one group-by column as int32 global codes per row."""
    import jax.numpy as jnp
    from ..storage.values_encoder import VT_DICT
    if put is None:
        put = jnp.asarray

    ids = np.zeros(layout.nrows_padded, dtype=np.int32)
    values: list[str] = []
    code_of: dict[str, int] = {}

    def code(v: str) -> int:
        c = code_of.get(v)
        if c is None:
            c = code_of[v] = len(values)
            values.append(v)
        return c

    eligible = []
    for bi in range(part.num_blocks):
        start = layout.starts[bi]
        n = part.block_rows(bi)
        if field in ("_stream", "_stream_id"):
            # virtual per-block constants
            v = part.block_tags(bi) if field == "_stream" else \
                part.block_stream_id(bi).as_string()
            ids[start:start + n] = code(v)
            eligible.append(bi)
            continue
        meta = part.block_column_meta(bi, field)
        if meta is None:
            consts = dict(part.block_consts(bi))
            ids[start:start + n] = code(consts.get(field, ""))
            eligible.append(bi)
            continue
        if meta["t"] != VT_DICT:
            continue  # string/numeric-encoded: host path for this block
        col = part.block_column(bi, field)
        remap = np.fromiter((code(v) for v in col.dict_values),
                            dtype=np.int32, count=len(col.dict_values))
        ids[start:start + n] = remap[col.ids]
        eligible.append(bi)
    if not eligible:
        return None
    return StagedDict(ids=put(ids), values=values,
                      eligible=frozenset(eligible),
                      nbytes=layout.nrows_padded * 4)


@dataclass
class AxesAssembly:
    """Everything _assemble_axes staged for one part's stats dispatch."""
    layout: StatsLayout
    numerics: dict                 # field -> StagedNumeric
    axes: list                     # (kind, ids_jax, size, decode_payload)
    eligibility: list              # frozensets of eligible block idxs
    ids_tuple: tuple
    strides: tuple
    nb: int
    uniq_shared: list              # (field, axis_idx)
    # packed super-dispatch segment count (0 = no seg axis).  When set,
    # ids_tuple[0] is the per-row segment ids and the fused kernel runs
    # the SEGMENT-MAJOR reduction (tpu/stats_seg.py): the one-hot
    # bucket width is nb // nseg — it no longer scales with the pack
    # size, and MAX_BUCKETS gates only that base product.
    nseg: int = 0


def part_stats_layout(part, shards: int = 1) -> StatsLayout:
    """shards: pad rows to a (STATS_CHUNK * shards) multiple so a mesh
    runner can split the row axis evenly with whole chunks per device."""
    from .kernels import stats_pad_rows, STATS_CHUNK
    starts = {}
    pos = 0
    for bi in range(part.num_blocks):
        starts[bi] = pos
        pos += part.block_rows(bi)
    padded = stats_pad_rows(pos)
    mult = STATS_CHUNK * max(shards, 1)
    padded = ((padded + mult - 1) // mult) * mult
    return StatsLayout(starts=starts, nrows=pos, nrows_padded=padded)


def stage_numeric(part, field: str, layout: StatsLayout,
                  max_abs_times_rows: int, put=None) -> StagedNumeric | None:
    """Stage one uint/int column as exact uint32 offsets from its minimum.

    Returns None when no block is int-typed, the value range exceeds
    uint32, or magnitudes could break float64 exactness on the host side
    (stats_device.py exactness contract)."""
    import jax.numpy as jnp
    if put is None:
        put = jnp.asarray

    cols = {}
    vmin = None
    vmax = None
    for bi in range(part.num_blocks):
        col = part.block_column(bi, field)
        if col is None or col.vtype not in _int_vtypes():
            continue
        cols[bi] = col
        lo, hi = int(col.nums.min()), int(col.nums.max())
        vmin = lo if vmin is None else min(vmin, lo)
        vmax = hi if vmax is None else max(vmax, hi)
    if not cols:
        return None
    if vmax - vmin >= 1 << 32:
        return None
    if max(abs(vmin), abs(vmax)) * max(layout.nrows, 1) >= \
            max_abs_times_rows:
        return None
    vals = np.zeros(layout.nrows_padded, dtype=np.uint32)
    for bi, col in cols.items():
        start = layout.starts[bi]
        vals[start:start + col.nums.shape[0]] = \
            (col.nums.astype(np.int64) - vmin).astype(np.uint32)
    return StagedNumeric(values=put(vals), vmin=vmin, vmax=vmax,
                         eligible=frozenset(cols),
                         nbytes=layout.nrows_padded * 4)


def stage_len_column(part, field: str, layout: StatsLayout,
                     max_abs_times_rows: int, put=None
                     ) -> StagedNumeric | None:
    """Per-row CODE-POINT length of `field` as a synthetic uint32 value
    column — the device carrier for `sum_len(field)` partials (the sum
    plane of the standard stats kernel IS the total length; host
    semantics: Python len(value)).  Eligible block kinds: string (bytes
    minus UTF-8 continuation bytes via prefix sums), dict, const,
    missing, and int-typed (canonical decimal digit count); float/ipv4/
    ts-typed blocks decline to the host path."""
    import jax.numpy as jnp
    if put is None:
        put = jnp.asarray
    virtual = field in ("_stream", "_stream_id")
    vals = np.zeros(layout.nrows_padded, dtype=np.uint32)
    eligible = []
    vmax = 0
    i64min = np.iinfo(np.int64).min
    for bi in range(part.num_blocks):
        start = layout.starts[bi]
        n = part.block_rows(bi)
        if virtual:
            v = part.block_tags(bi) if field == "_stream" else \
                part.block_stream_id(bi).as_string()
            vals[start:start + n] = len(v)
            vmax = max(vmax, len(v))
            eligible.append(bi)
            continue
        meta = part.block_column_meta(bi, field)
        if meta is None:
            consts = dict(part.block_consts(bi))
            ln = len(consts.get(field, ""))
            vals[start:start + n] = ln
            vmax = max(vmax, ln)
        elif meta["t"] == VT_STRING:
            col = part.block_column(bi, field)
            if col.arena.size:
                cs = np.zeros(col.arena.size + 1, dtype=np.int64)
                np.cumsum((col.arena & 0xC0) != 0x80, out=cs[1:])
                offs = col.offsets.astype(np.int64)
                lens = col.lengths.astype(np.int64)
                cp = cs[offs + lens] - cs[offs]
            else:
                cp = np.zeros(n, dtype=np.int64)
            vals[start:start + n] = cp.astype(np.uint32)
            vmax = max(vmax, int(cp.max(initial=0)))
        elif meta["t"] == VT_DICT:
            col = part.block_column(bi, field)
            remap = np.array([len(v) for v in col.dict_values],
                             dtype=np.uint32)
            if remap.size:
                rowl = remap[col.ids]
                vals[start:start + n] = rowl
                vmax = max(vmax, int(remap.max()))
        elif meta["t"] in _int_vtypes():
            col = part.block_column(bi, field)
            v = col.nums.astype(np.int64)
            a = np.abs(v)
            d = np.ones(n, dtype=np.int64)
            t = 10
            while t <= 10 ** 18:
                d += a >= t
                t *= 10
            d += v < 0
            d = np.where(v == i64min, 20, d)  # abs(int64 min) wraps
            vals[start:start + n] = d.astype(np.uint32)
            vmax = max(vmax, int(d.max(initial=0)))
        else:
            continue       # float/ipv4/ts: host decodes these
        eligible.append(bi)
    if not eligible:
        return None
    if vmax * max(layout.nrows, 1) >= max_abs_times_rows:
        return None
    return StagedNumeric(values=put(vals), vmin=0, vmax=vmax,
                         eligible=frozenset(eligible),
                         nbytes=layout.nrows_padded * 4)


def stage_empty_column(part, field: str, layout: StatsLayout,
                       put=None) -> StagedNumeric | None:
    """Synthetic 0/1 column: 1 where `field` is the empty string — the
    device carrier for `count_empty(field)` (its sum plane is the empty
    count).  Every block kind is eligible: numeric/ipv4/ts-typed blocks
    have a value in every row (never empty)."""
    import jax.numpy as jnp
    if put is None:
        put = jnp.asarray
    vals = np.zeros(layout.nrows_padded, dtype=np.uint32)
    eligible = []
    for bi in range(part.num_blocks):
        start = layout.starts[bi]
        n = part.block_rows(bi)
        if field in ("_stream", "_stream_id"):
            eligible.append(bi)   # virtual renderings are never empty
            continue
        meta = part.block_column_meta(bi, field)
        if meta is None:
            consts = dict(part.block_consts(bi))
            if consts.get(field, "") == "":
                vals[start:start + n] = 1
        elif meta["t"] == VT_STRING:
            col = part.block_column(bi, field)
            em = col.lengths == 0
            if em.any():
                vals[start:start + n] = em.astype(np.uint32)
        elif meta["t"] == VT_DICT:
            col = part.block_column(bi, field)
            remap = np.array([1 if v == "" else 0
                              for v in col.dict_values], dtype=np.uint32)
            if remap.size and remap.any():
                vals[start:start + n] = remap[col.ids]
        # numeric/ipv4/ts blocks: never empty
        eligible.append(bi)
    return StagedNumeric(values=put(vals), vmin=0, vmax=1,
                         eligible=frozenset(eligible),
                         nbytes=layout.nrows_padded * 4)


def stage_time_buckets(part, layout: StatsLayout, step: int, offset: int,
                       max_buckets: int, put=None) -> StagedBuckets | None:
    """Bucket ids per row from block timestamps, matching the host's
    `((ts - off) // step) * step + off` bucketing bit-for-bit."""
    import jax.numpy as jnp
    if put is None:
        put = jnp.asarray

    ids = np.zeros(layout.nrows_padded, dtype=np.int64)
    base = None
    hi = None
    for bi in range(part.num_blocks):
        ts = part.block_timestamps(bi)
        vb = ((ts.astype(np.int64) - offset) // step) * step + offset
        start = layout.starts[bi]
        ids[start:start + vb.shape[0]] = vb
        lo_b, hi_b = int(vb.min()), int(vb.max())
        base = lo_b if base is None else min(base, lo_b)
        hi = hi_b if hi is None else max(hi, hi_b)
    if base is None:
        return None
    nb = (hi - base) // step + 1
    if nb > max_buckets:
        return None
    ids[:layout.nrows] = (ids[:layout.nrows] - base) // step
    ids[layout.nrows:] = 0
    return StagedBuckets(ids=put(ids.astype(np.int32)), base=base,
                         num_buckets=int(nb),
                         nbytes=layout.nrows_padded * 4)


# ---------------- cost model: device vs host, per part ----------------

class CostModel:
    """Per-part device-vs-host dispatch decision.

    The device path must never lose to the CPU executor (VERDICT r3:
    under the ~65ms tunnel RTT, small parts and cheap filters ran
    slower on device than the native host scans).  This model estimates
    both sides and routes the part accordingly:

      est_host   = cand_rows / host_rows_per_s   (+ stats term)
      est_device = n_dispatch * rtt + scanned_bytes / dev_bytes_per_s
                   + amortized cold-staging upload

    The RTT is MEASURED on first use (a tiny dispatch round trip — ~65ms
    through the axon tunnel, ~0.1ms on a local backend), and the scan /
    host rates are EWMA-updated from real part runs, so the decision
    tracks the actual machine instead of hard-coded constants.  Env
    overrides: VL_COST_FORCE=device|host pins the decision (tests pin
    `device` so kernel parity stays exercised); VL_COST_RTT_MS,
    VL_COST_DEV_GBPS, VL_COST_HOST_MROWS preseed the calibration.

    This is the TPU analogue of the reference scheduling work budget:
    the reference never pays a fixed per-query offload floor, so its
    worker model needs no such gate (storage_search.go:1035-1067); here
    the gate is what makes "device by default" safe on every shape.
    """

    _EWMA = 0.3                    # weight of a new observation
    _COLD_AMORT = 0.25             # staging reused across queries (LRU)

    def __init__(self):
        self._mu = threading.Lock()
        v = config.env("VL_COST_RTT_MS")
        self.rtt = float(v) / 1e3 if v else None
        v = config.env("VL_COST_DEV_GBPS")
        self.dev_bytes_per_s = float(v) * 1e9 if v else None
        v = config.env("VL_COST_HOST_MROWS")
        # round-3 PERF.md: native host scans sustain 10-14M rows/s
        self.host_rows_per_s = float(v) * 1e6 if v else 12e6
        self.host_stats_rows_per_s = 30e6
        self.upload_bytes_per_s = 1e9
        # per-unit host EMIT time EWMA (block materialization +
        # downstream write, EXCLUDING the device_sync blocked wait) —
        # the VL_INFLIGHT=auto depth signal (tpu/pipeline.py).  Folding
        # the wait in would make the signal self-referential: at depth d
        # each harvest blocks ~rtt/d, the EWMA converges toward rtt/d,
        # and ceil(rtt/ewma) contracts to the clamp floor exactly on the
        # high-RTT backends that need a deep window.
        self.emit_ewma: float | None = None
        # observed submit-to-harvest round trip of REAL dispatch units
        # (tpu/pipeline.harvest_one) — the EXPLAIN pricing pass's
        # per-unit term.  The probe rtt above is a minimal round trip
        # for the host-vs-device decision; a real fused unit also pays
        # program-arg marshalling and result download, which must not
        # inflate the routing gate but should price the plan.
        self.unit_rtt_ewma: float | None = None
        self._unit_rtt_seen = False    # first unit pays jit compile
        self.force = config.env("VL_COST_FORCE") or ""

    # vlint: allow-jax-host-sync(the blocking round trip IS the probe)
    def measured_rtt(self) -> float:
        if self.rtt is None:
            import time

            import jax
            import jax.numpy as jnp
            f = jax.jit(lambda x: x + 1)
            x = jnp.zeros(8, jnp.int32)
            np.asarray(f(x))           # compile + warm the path
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(f(x))
                best = min(best, time.perf_counter() - t0)
            with self._mu:
                if self.rtt is None:
                    self.rtt = best
        return self.rtt

    def _dev_rate(self) -> float:
        if self.dev_bytes_per_s is not None:
            return self.dev_bytes_per_s
        import jax
        # defaults until the first measured dispatch lands
        return 20e9 if jax.default_backend() == "tpu" else 1.5e9

    # -- EWMA feeders --
    def observe_device_scan(self, nbytes: int, elapsed: float) -> None:
        if self.force:
            # forced runners (mesh default, parity suites) never consult
            # the estimate — don't pay the lazy RTT probe to feed it
            return
        # measure the RTT lazily so the dispatch overhead is subtracted
        # even when prefer_host hasn't run yet (ADVICE r4: otherwise the
        # full round trip is attributed to device compute, biasing
        # dev_bytes_per_s low)
        compute = elapsed - self.measured_rtt()
        if compute <= 0 or nbytes <= 0:
            return
        rate = nbytes / compute
        with self._mu:
            cur = self.dev_bytes_per_s
            self.dev_bytes_per_s = rate if cur is None else \
                (1 - self._EWMA) * cur + self._EWMA * rate

    def observe_emit(self, elapsed: float) -> None:
        """One harvested unit's emit-phase time (wait-free host work).
        Unlike the routing rates this records even under VL_COST_FORCE:
        it calibrates the window depth, not a device-vs-host decision."""
        if elapsed <= 0:
            return
        with self._mu:
            cur = self.emit_ewma
            self.emit_ewma = elapsed if cur is None else \
                (1 - self._EWMA) * cur + self._EWMA * elapsed

    def observe_unit_rtt(self, elapsed: float) -> None:
        """One real dispatch unit's submit-to-harvest round trip
        (records under VL_COST_FORCE too: it prices plans, it never
        routes device-vs-host).

        Robust to jit compilation: the very first unit pays a one-time
        program compile that can be 100x the steady round trip —
        seeding the EWMA with it would poison every prediction for tens
        of queries — so the first observation is discarded, and later
        spikes (fresh pad buckets compiling mid-stream) clamp at 10x
        the current estimate instead of jerking it."""
        if elapsed <= 0:
            return
        with self._mu:
            if not self._unit_rtt_seen:
                self._unit_rtt_seen = True
                return
            cur = self.unit_rtt_ewma
            if cur is None:
                self.unit_rtt_ewma = elapsed
                return
            self.unit_rtt_ewma = (1 - self._EWMA) * cur \
                + self._EWMA * min(elapsed, 10 * cur)

    def observe_host_scan(self, rows: int, elapsed: float) -> None:
        if elapsed <= 0 or rows < 10000:
            return                 # tiny samples are all overhead
        rate = rows / elapsed
        with self._mu:
            self.host_rows_per_s = (1 - self._EWMA) * self.host_rows_per_s \
                + self._EWMA * rate

    # -- the decision --
    def prefer_host(self, cand_rows: int, scan_bytes: int,
                    n_dispatch: int, cold_bytes: int,
                    stats_rows: int = 0) -> bool:
        if self.force == "device":
            return False
        if self.force == "host":
            return True
        if n_dispatch <= 0:
            return True
        est_host = cand_rows / self.host_rows_per_s \
            + stats_rows / self.host_stats_rows_per_s
        est_dev = n_dispatch * self.measured_rtt() \
            + n_dispatch * scan_bytes / self._dev_rate() \
            + self._COLD_AMORT * cold_bytes / self.upload_bytes_per_s
        return est_host < est_dev

    # -- probe-free reads (EXPLAIN pricing; /metrics-safe) --

    # cold-calibration RTT stand-in: a local-backend-scale figure, so an
    # uncalibrated model underprices tunnel backends instead of
    # overpricing local ones (the first real query measures the truth)
    _RTT_COLD_DEFAULT = 1e-3

    def peek(self) -> dict:
        """Calibration snapshot WITHOUT the lazy RTT probe: the raw
        EWMAs/fields plus cold-start defaults, for the EXPLAIN pricing
        pass (obs/explain.py) — `explain=1` must never dispatch, so it
        can't ride measured_rtt().  ``calibrated`` is False until a real
        query has measured the round trip."""
        with self._mu:
            rtt, dev, emit = self.rtt, self.dev_bytes_per_s, \
                self.emit_ewma
            unit_rtt = self.unit_rtt_ewma
            host, host_stats = self.host_rows_per_s, \
                self.host_stats_rows_per_s
        rtt_s = rtt if rtt is not None else self._RTT_COLD_DEFAULT
        return {
            "rtt_s": rtt_s,
            # the pricing term: observed whole-unit round trips when a
            # query has fed the EWMA, the probe rtt until then
            "unit_rtt_s": unit_rtt if unit_rtt is not None else rtt_s,
            "dev_bytes_per_s": dev if dev is not None
            else self._dev_rate(),
            "emit_unit_s": emit or 0.0,
            "host_rows_per_s": host,
            "host_stats_rows_per_s": host_stats,
            "upload_bytes_per_s": self.upload_bytes_per_s,
            "calibrated": rtt is not None or unit_rtt is not None,
            "force": self.force,
        }



# ---------------- the batch runner ----------------

# live runners, for the vlsan end-of-test sweep: a non-daemon
# vl-prefetch worker is fine while a reachable runner owns it (close()
# releases it; the long-lived server runner never closes), and a
# DROPPED runner's worker exits once the executor is collected — only
# an ownerless surviving worker is a leak
_live_runners: "weakref.WeakSet" = weakref.WeakSet()


def live_prefetch_pools() -> int:
    """How many live runners currently own a prefetch pool."""
    return sum(1 for r in list(_live_runners)
               if r._prefetch_pool is not None)


class BatchRunner:
    """Part-at-a-time filter evaluation with one dispatch per device leaf.

    Exposes run_part() (used by engine.searcher.run_query when present) and
    a per-block apply_filter() shim for callers holding one BlockSearch."""

    # single-dispatch filter->stats fusion (tpu/fused.py); MeshBatchRunner
    # keeps its shard_map stats path instead
    fused_enabled = True
    # below this many matched rows the unfused stats path hands the rows
    # to the host pipe instead of paying an upload + dispatch round trip
    stats_host_threshold = 1024

    def __init__(self, max_cache_bytes: int = 8 << 30,
                 max_part_bytes: int = 4 << 30):
        self.cache = StagingCache(max_cache_bytes)
        self.max_part_bytes = max_part_bytes
        self.cost = CostModel()
        self._scan_sigs: set = set()   # jit signatures already compiled
        self.device_calls = 0          # every dispatch issued to the device
        self.cpu_fallbacks = 0
        self.gated_host_parts = 0
        self.stats_dispatches = 0
        self.fused_dispatches = 0
        self.filter_dispatches = 0     # fused filter-only row dispatches
        self.topk_dispatches = 0
        self.bloom_plane_probes = 0
        self.agg_pruned_parts = 0
        self.maplet_probes = 0         # v2 maplet served a keep-mask
        self.maplet_pruned_blocks = 0  # blocks exact-killed pre-dispatch
        # async pipeline observability (tpu/pipeline.py)
        self.pipeline_units = 0        # units driven through the window
        self.packed_dispatches = 0     # super-dispatches over packed parts
        self.packed_parts = 0         # parts folded into super-dispatches
        self.packed_topk_dispatches = 0  # sort-topk super-dispatches
        self.cross_partition_packs = 0  # packs spanning a day boundary
        self.result_cache_units = 0    # units satisfied from the
        #                                per-part result cache (no
        #                                dispatch, no slot lease)
        # widest bucket one-hot any stats dispatch paid (the seg-major
        # kernel keeps this at the BASE bucket product — it must not
        # scale with VL_PACK_PARTS; bench-asserted)
        self.stats_onehot_width = 0
        self.inflight_hwm = 0          # in-flight window high-water mark
        self.host_sync_wait_s = 0.0    # time blocked materializing results
        self.sched_slot_wait_s = 0.0   # time leasing dispatch slots from
        #                                the shared scheduler (sched/)
        self.inflight_auto_depth = 0   # VL_INFLIGHT=auto chosen depth
        self.stats_shards = 1          # mesh runners stripe rows over >1
        # distinct dispatch shapes this runner has sent to the device —
        # the multichip dryrun asserts breadth here (verdict r4 weak #6)
        self.dispatch_kinds: set = set()
        self._counter_mu = threading.Lock()
        # striped staging locks: the prefetcher, concurrent partition
        # workers and the scan thread may race to stage the same
        # (part, field); the loser waits and takes the cache hit instead
        # of duplicating a multi-100MB upload.  A fixed stripe pool keeps
        # lock memory bounded across part churn (merges mint fresh uids).
        self._stage_locks = [threading.Lock() for _ in range(64)]
        # PackedPart instances (tpu/pipeline.py): a SMALL dedicated LRU,
        # not the byte-budgeted StagingCache — a pack strongly references
        # its member parts (incl. in-RAM InmemoryPart blocks), so its
        # true cost is member lifetime, not device bytes; the hard entry
        # cap bounds how long retired members can stay pinned.
        self._pack_mu = threading.Lock()
        self._packs: OrderedDict = OrderedDict()
        self._prefetch_pool = None  # lazy; see _prefetcher()
        _live_runners.add(self)

    def _bump(self, attr: str, n=1) -> None:
        with self._counter_mu:
            setattr(self, attr, getattr(self, attr) + n)

    def _bump_max(self, attr: str, v) -> None:
        with self._counter_mu:
            if v > getattr(self, attr):
                setattr(self, attr, v)

    def _set(self, attr: str, v) -> None:
        with self._counter_mu:
            setattr(self, attr, v)

    def _kind(self, label: str) -> None:
        with self._counter_mu:
            self.dispatch_kinds.add(label)

    def stats(self) -> dict:
        """Counter snapshot (served under /metrics as vl_tpu_*)."""
        with self._counter_mu:
            out = {
                "device_calls": self.device_calls,
                "cpu_fallbacks": self.cpu_fallbacks,
                "gated_host_parts": self.gated_host_parts,
                "stats_dispatches": self.stats_dispatches,
                "fused_dispatches": self.fused_dispatches,
                "filter_dispatches": self.filter_dispatches,
                "topk_dispatches": self.topk_dispatches,
                "bloom_plane_probes": self.bloom_plane_probes,
                "agg_pruned_parts": self.agg_pruned_parts,
                "maplet_probes": self.maplet_probes,
                "maplet_pruned_blocks": self.maplet_pruned_blocks,
                "pipeline_units": self.pipeline_units,
                "packed_dispatches": self.packed_dispatches,
                "packed_parts": self.packed_parts,
                "packed_topk_dispatches": self.packed_topk_dispatches,
                "cross_partition_packs": self.cross_partition_packs,
                "result_cache_units": self.result_cache_units,
                "stats_onehot_width": self.stats_onehot_width,
                "inflight_hwm": self.inflight_hwm,
                "host_sync_wait_s": self.host_sync_wait_s,
                "sched_slot_wait_s": self.sched_slot_wait_s,
                "inflight_auto_depth": self.inflight_auto_depth,
            }
        out.update({f"staging_cache_{k}": v
                    for k, v in self.cache.stats().items()})
        with self._pack_mu:
            out["pack_cache_entries"] = len(self._packs)
        # cost-model calibration gauges (ROADMAP "RTT-aware auto depth"
        # baseline signal): read raw fields, NEVER measured_rtt() — a
        # /metrics scrape must not trigger the lazy RTT probe dispatch
        out["cost_rtt_seconds"] = self.cost.rtt or 0.0
        out["cost_unit_rtt_seconds"] = self.cost.unit_rtt_ewma or 0.0
        out["cost_dev_bytes_per_s"] = self.cost.dev_bytes_per_s or 0.0
        out["cost_emit_ewma_seconds"] = self.cost.emit_ewma or 0.0
        if self.cost.rtt is not None:
            from .pipeline import pack_rows_cap
            cap = pack_rows_cap(self)
        else:
            # RTT not yet measured: report only an explicit VALID
            # override (a malformed value would make pack_rows_cap fall
            # through to measured_rtt and dispatch to the device from a
            # /metrics scrape)
            v = config.env_int("VL_PACK_MAX_ROWS")
            cap = max(1, v) if v is not None else 0
        out["pack_rows_cap"] = cap
        return out

    def _prefetcher(self):
        """Lazily create the single prefetch worker.  Fully under the
        counter lock: partition workers race here against each other AND
        against close(), and an unlocked fast-path read could return the
        pool close() is concurrently shutting down (or None)."""
        from concurrent.futures import ThreadPoolExecutor
        with self._counter_mu:
            if self._prefetch_pool is None:
                self._prefetch_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="vl-prefetch")
            return self._prefetch_pool

    def close(self) -> None:
        """Release the prefetch worker (callers owning a per-query runner
        should close it; the long-lived server runner never needs to)."""
        # under _counter_mu: a partition worker racing through
        # _prefetcher() must either see the live pool or rebuild one,
        # never shut down a pool it is about to submit to
        with self._counter_mu:
            pool, self._prefetch_pool = self._prefetch_pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def _key_lock(self, key) -> threading.Lock:
        return self._stage_locks[hash(key) % len(self._stage_locks)]

    # ---- prefetch (stage part N+k while parts N..N+k-1 scan) ----
    def submit_prefetch(self, part, f, stats_spec=None,
                        cand_bis=None, fused=False,
                        sort_field=None) -> None:
        """Queue background staging of what the query will need from
        `part`, so the host decode/upload of UPCOMING parts overlaps the
        device scans of the current ones (SURVEY §7 hard-part 3).  The
        async pipeline (tpu/pipeline.py) submits this for every part
        within its in-flight window, so staging depth follows
        VL_INFLIGHT instead of the old depth-1 double buffer.

        Applies the SAME gates as the evaluator so prefetch never stages
        a column it would skip: the bloom kill-path over the candidate
        blocks, and the narrow-candidate heuristic (a small candidate
        fraction takes the host path instead of staging).
        cand_bis: candidate block idxs (after tenant/stream/time
        pruning); None means every block is a candidate.
        fused=True stages for the single-dispatch fused programs
        (layout-coordinate columns + timestamp planes — what the
        windowed pipeline dispatches, including packed super-parts)
        instead of the per-leaf string staging.
        sort_field: the sort-topk by-column — its uint32 value staging
        (the fused topk dispatch's score operand) uploads ahead like
        the stats value columns."""
        from ..obs import activity, tracing
        # staging runs on the vl-prefetch worker: re-enter the caller's
        # span AND activity record there so staged_entries/staged_bytes
        # attribution isn't silently dropped on the dominant
        # (prefetched) path; attrs/counters are lock-guarded, so adds
        # racing the final to_dict/snapshot are safe
        caller_span = tracing.current_span()
        caller_act = activity.current_activity()

        def work():
            try:
                with tracing.use_span(caller_span), \
                        activity.use_activity(caller_act):
                    self._prefetch_work(part, f, stats_spec, cand_bis,
                                        fused, sort_field)
            # vlint: allow-broad-except(prefetch is best-effort)
            except Exception:
                pass  # prefetch is best-effort; the scan path re-stages
        try:
            self._prefetcher().submit(work)
        except RuntimeError:
            pass  # pool closed between return and submit; best-effort

    def _prefetch_work(self, part, f, stats_spec, cand_bis,
                       fused, sort_field=None) -> None:
        bis = list(cand_bis) if cand_bis is not None else \
            list(range(part.num_blocks))
        cand_rows = sum(part.block_rows(bi) for bi in bis)
        if self._gate_host_est(
                f, part, cand_rows,
                stats_rows=cand_rows if stats_spec or sort_field
                else 0):
            return     # the evaluator will take the host path
        layout = None
        if fused:
            from .stats_device import MAX_STAT_ROWS
            layout = self._stats_layout(part)
            if layout.nrows > MAX_STAT_ROWS:
                layout = None
            elif _tree_has_time(f):
                self._stage_ts_planes(part, layout)
        if sort_field is not None and layout is not None:
            # the topk score operand (fused_topk_submit's staging key).
            # A decline (non-numeric sort column for this part) means
            # the evaluator will decline the fused topk too and fall
            # back to per-leaf string scans — revert THIS part's
            # prefetch to the classic string staging instead of
            # uploading #fl matrices the dispatch will never read.
            from .stats_device import MAX_ABS_TIMES_ROWS
            if self._stage_numeric(part, sort_field, layout,
                                   MAX_ABS_TIMES_ROWS) is None:
                layout = None
        for plan in device_plans(f):
            surv = bis
            if plan.bloom_tokens:
                hashes = cached_token_hashes(plan.filter,
                                             plan.bloom_tokens)
                # observe=False: the evaluator/planner re-probes this
                # exact (part, field, bis) at dispatch — counting the
                # prefetch warm-up too would double every histogram
                # sample and trace counter
                keep = bloom_keep_mask(part, plan.field, hashes,
                                       bis, observe=False)
                surv = [bi for bi, k in zip(bis, keep) if k]
            if not surv:
                continue
            cand_rows = sum(part.block_rows(bi) for bi in surv)
            if layout is not None:
                # fused staging key (#fl) mirrors _scan_leaf's
                # narrowness gate
                if self.cache.contains(
                        (part.uid, "#fl", plan.field)) or \
                        cand_rows * 8 >= part.num_rows:
                    self._stage_fused_field(part, plan.field,
                                            layout)
                continue
            if not self.cache.contains((part.uid, plan.field)) \
                    and cand_rows * 8 < part.num_rows:
                continue  # evaluator will take the host path
            self.stage_part(part, plan.field)
        if stats_spec is not None:
            from .stats_device import MAX_ABS_TIMES_ROWS, \
                MAX_BUCKETS, MAX_STAT_ROWS
            layout = self._stats_layout(part)
            if layout.nrows > MAX_STAT_ROWS:
                return
            for fld in stats_spec.value_fields:
                self._stage_numeric(part, fld, layout,
                                    MAX_ABS_TIMES_ROWS)
            for bk in stats_spec.by:
                if bk.kind == "time":
                    self._stage_buckets(part, layout, bk.step,
                                        bk.offset, MAX_BUCKETS)
                else:
                    self._stage_dict(part, bk.name, layout)

    # ---- device placement hook (MeshBatchRunner shards the row axis) ----
    def _put(self, arr, row_axis: int = 0):
        import jax.numpy as jnp
        return jnp.asarray(arr)

    def _put_replicated(self, arr):
        """Placement for block-axis arrays (bloom planes): every device
        needs the whole array — a mesh runner replicates instead of
        striping (the block axis is not the sharded row axis)."""
        import jax.numpy as jnp
        return jnp.asarray(arr)

    # ---- stats dispatch hooks (MeshBatchRunner shard_maps + psum-reduces)
    def _dispatch_fused(self, prog, strides, nb, n_values, nrows,
                        cand_packed, seg_map, ids_tuple, values_tuple,
                        args):
        from .fused import _fused_dispatch
        return _fused_dispatch(prog, strides, nb, n_values, nrows,
                               cand_packed, seg_map, ids_tuple,
                               values_tuple, args)

    def _dispatch_topk(self, prog, k, desc, nseg, nrows, cand_packed,
                       seg_ids, seg_map, values, args):
        from .fused import _topk_dispatch
        return _topk_dispatch(prog, k, desc, nseg, nrows, cand_packed,
                              seg_ids, seg_map, values, args)

    def _dispatch_filter(self, prog, nrows, cand_packed, args):
        from .fused import _filter_dispatch
        return _filter_dispatch(prog, nrows, cand_packed, args)

    def _dispatch_stats_count(self, ids_tuple, strides, mask, nb):
        # vlint: allow-jax-host-sync(result readback at dispatch boundary)
        return np.array(K.stats_bucket_count(ids_tuple, strides, mask,
                                             nb))

    def _dispatch_stats_values(self, values, ids_tuple, strides, mask,
                               nb):
        # vlint: allow-jax-host-sync(result readback at dispatch boundary)
        return np.array(K.stats_bucket_values(values, ids_tuple, strides,
                                              mask, nb))

    # ---- staging (cached across queries; parts are immutable) ----
    def stage_part(self, part, field: str) -> StagedPart | None:
        key = (part.uid, field)
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is _UNSTAGEABLE:
                return None
            if got is not None:
                return got
            spc = stage_part_column(part, field, self.max_part_bytes,
                                    put=self._put)
            if spc is None:
                self.cache.put_small(key, _UNSTAGEABLE)
                return None
            self.cache.put(key, spc)
            return spc

    def _stage_nonascii(self, part, field: str) -> dict:
        """block_idx -> row idxs whose SOURCE value has a byte >= 0x80,
        for string-typed blocks.  Computed lazily on first use by a
        case-fold leaf (most queries never pay for it) and cached per
        (part, field)."""
        key = (part.uid, "#na", field)
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is None:
                from .layout import rows_with_multibyte
                na = {}
                for bi in range(part.num_blocks):
                    col = part.block_column(bi, field)
                    if col is None or col.vtype != VT_STRING:
                        continue
                    idx = np.nonzero(rows_with_multibyte(
                        col.arena, col.offsets, col.lengths))[0]
                    if idx.size:
                        na[bi] = idx
                got = na
                self.cache.put_small(key, got)
            return got

    # ---- per-block compatibility shim ----
    def apply_filter(self, f, bs: BlockSearch) -> np.ndarray:
        out = self.run_part(f, bs.part, {bs.block_idx: bs})
        return out[bs.block_idx]

    # ---- cost gate (device must never lose to the CPU executor) ----
    def _gate_host(self, f, part, bss: dict, stats_rows: int = 0) -> bool:
        """True => run this part through the host executor instead."""
        return self._gate_host_est(f, part,
                                   sum(bs.nrows for bs in bss.values()),
                                   stats_rows=stats_rows)

    def _gate_host_est(self, f, part, cand_rows: int,
                       stats_rows: int = 0) -> bool:
        """The estimate behind _gate_host, keyed on cand_rows only so the
        prefetcher can apply the SAME decision before BlockSearch objects
        exist (ADVICE r4: a diverging prefetch gate declined to stage
        parts run_part then routed to device, paying the cold upload
        synchronously)."""
        plans = device_plans(f)
        if not plans:
            if not stats_rows:
                return True        # nothing device-scannable
            # stats-only shape (`* | stats ...`): ids+mask traffic only
            return self.cost.prefer_host(0, cand_rows * 8, 1, 0,
                                         stats_rows=stats_rows)
        scan_bytes = cand_rows * 128        # W estimate; fidelity is low
        cold = 0
        for plan in plans:
            if not self.cache.contains((part.uid, plan.field)):
                cold += scan_bytes
        n_dispatch = 1 if stats_rows else \
            sum(max(len(p.ops), 1) for p in plans)
        return self.cost.prefer_host(cand_rows, scan_bytes, n_dispatch,
                                     cold, stats_rows=stats_rows)

    def _host_eval_part(self, f, bss: dict) -> dict:
        """The CPU executor's own per-block path (native scans inside the
        filters); timed to keep the cost model's host rate honest."""
        import time
        t0 = time.perf_counter()
        out = {}
        rows = 0
        for bi, bs in bss.items():
            bm = np.ones(bs.nrows, dtype=bool)
            f.apply_to_block(bs, bm)
            out[bi] = bm
            rows += bs.nrows
        self.cost.observe_host_scan(rows, time.perf_counter() - t0)
        return out

    # ---- part-level evaluation ----
    def run_part(self, f, part, bss: dict) -> dict:
        """Evaluate the filter tree over candidate blocks of one part.

        bss: block_idx -> BlockSearch (with .ctx set for stream filters).
        Returns block_idx -> bool bitmap, bit-identical to the CPU path."""
        if self._gate_host(f, part, bss):
            self._bump("gated_host_parts")
            return self._host_eval_part(f, bss)
        return self._run_part_device(f, part, bss)

    def _run_part_device(self, f, part, bss: dict) -> dict:
        """run_part past the host gate (run_part_submit's fused-decline
        fallback lands here directly — its gate already ran)."""
        trace_dir = config.env("VL_XLA_TRACE_DIR")
        if trace_dir:
            # XLA profiler hook at the block-runner seam (SURVEY §5);
            # inspect with tensorboard or xprof
            import jax
            with jax.profiler.trace(trace_dir):
                return self._eval(f, part, bss, list(bss))
        return self._eval(f, part, bss, list(bss))

    def _eval(self, f, part, bss, alive) -> dict:
        if isinstance(f, F.FilterAnd):
            acc = {bi: np.ones(bss[bi].nrows, dtype=bool) for bi in alive}
            cur = list(alive)
            for sub in f.filters:
                if not cur:
                    break
                sub_bms = self._eval(sub, part, bss, cur)
                nxt = []
                for bi in cur:
                    acc[bi] &= sub_bms[bi]
                    if acc[bi].any():
                        nxt.append(bi)
                cur = nxt
            return acc
        if isinstance(f, F.FilterOr):
            acc = {bi: np.zeros(bss[bi].nrows, dtype=bool) for bi in alive}
            cur = list(alive)
            for sub in f.filters:
                if not cur:
                    break
                sub_bms = self._eval(sub, part, bss, cur)
                nxt = []
                for bi in cur:
                    acc[bi] |= sub_bms[bi]
                    if not acc[bi].all():
                        nxt.append(bi)
                cur = nxt
            return acc
        if isinstance(f, F.FilterNot):
            inner = self._eval(f.inner, part, bss, alive)
            return {bi: ~inner[bi] for bi in alive}
        plan = device_plan(f)
        if plan is None:
            self._bump("cpu_fallbacks")
            out = {}
            for bi in alive:
                bm = np.ones(bss[bi].nrows, dtype=bool)
                f.apply_to_block(bss[bi], bm)
                out[bi] = bm
            return out
        return self._eval_leaf(plan, part, bss, alive)

    def _eval_leaf(self, plan: LeafPlan, part, bss, alive) -> dict:
        out = {}
        # bloom kill-path FIRST (cheap, mmap'd words): when a rare token
        # prunes every candidate block, the part is never staged.  The
        # probe is one dense gather over the part's packed bloom plane
        # (storage/filterbank.py + tpu/bloom_device.py), not a per-block
        # Python loop; columns without a plane keep the per-block path.
        survivors = list(alive)
        if plan.bloom_tokens:
            from ..storage.filterbank import filter_bank
            hashes = cached_token_hashes(plan.filter, plan.bloom_tokens)
            keep = bloom_keep_mask(part, plan.field, hashes, alive)
            from ..storage.filterindex import part_index
            if part_index(part) is not None:
                # evidence the v2 MAPLET served the probe (exact keep
                # set, no plane build at all)
                self._bump("maplet_probes")
            elif filter_bank(part).cached_plane(plan.field) is not None:
                # evidence the PLANE path served the probe (a declined
                # column rode the per-block fallback instead)
                self._bump("bloom_plane_probes")
            survivors = []
            for bi, k in zip(alive, keep):
                if k:
                    survivors.append(bi)
                else:
                    out[bi] = np.zeros(bss[bi].nrows, dtype=bool)
            if not survivors:
                return out

        # when the candidate blocks are a small fraction of the part (e.g.
        # a narrow stream filter) and the part isn't staged yet, the host
        # path over just those blocks beats staging + scanning everything
        cand_rows = sum(bss[bi].nrows for bi in survivors)
        already_staged = self.cache.contains((part.uid, plan.field))
        if not already_staged and cand_rows * 8 < part.num_rows:
            spc = None
        else:
            spc = self.stage_part(part, plan.field)
        if spc is None:
            dev_bis = []
            host_bis = survivors
        else:
            dev_bis = [bi for bi in survivors if bi in spc.block_rows]
            host_bis = [bi for bi in survivors if bi not in spc.block_rows]
        for bi in host_bis:
            bm = np.ones(bss[bi].nrows, dtype=bool)
            plan.filter.apply_to_block(bss[bi], bm)
            out[bi] = bm
        if not dev_bis:
            return out

        verify_mask = None     # None => verify ALL survivors when plan.verify
        need_verify = plan.verify
        if plan.pair is not None:
            combined, verify_mask = self._scan_pair(spc, plan.pair)
            need_verify = True
        else:
            combined = self._run_ops(spc, plan)
        na_map = self._stage_nonascii(part, plan.field) \
            if any(op.fold for op in plan.ops) else {}
        for bi in dev_bis:
            start, n = spc.block_rows[bi]
            bm = combined[start:start + n].copy() if combined is not None \
                else np.ones(n, dtype=bool)
            recheck = spc.overflow.get(bi)
            # case-fold leaves: rows with non-ASCII bytes can diverge
            # from the byte fold in EITHER direction (U+212A lowers to
            # ASCII 'k') — the host predicate decides them outright
            na = na_map.get(bi)
            if na is not None:
                recheck = na if recheck is None else \
                    np.union1d(recheck, na)
            value_at = None
            if recheck is not None and recheck.size:
                # truncated rows: ask the filter's full predicate
                value_at = _row_accessor(bss[bi], plan.field)
                for i in recheck:
                    bm[i] = plan.filter._pred(value_at(i))
            if need_verify and bm.any():
                check = np.nonzero(
                    bm & verify_mask[start:start + n]
                    if verify_mask is not None else bm)[0]
                if check.size:
                    if value_at is None:
                        value_at = _row_accessor(bss[bi], plan.field)
                    for i in check:
                        if not plan.filter._pred(value_at(i)):
                            bm[i] = False
            out[bi] = bm
        return out

    # ---- device stats partials (filter bitmap -> per-bucket aggregates) ----

    def _stats_layout(self, part) -> StatsLayout:
        key = (part.uid, "#layout")
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is None:
                got = part_stats_layout(part, shards=self.stats_shards)
                self.cache.put_small(key, got)
            return got

    def _stage_numeric(self, part, field: str, layout: StatsLayout,
                       max_abs_times_rows: int):
        """Stage a value column for device stats.  `field` may be a
        synthetic token (stats_device.SYNTH_LEN/SYNTH_EMPTY prefixes)
        carrying sum_len/count_empty as derived uint32 columns."""
        from .stats_device import SYNTH_EMPTY, SYNTH_LEN
        key = (part.uid, "#num", field)
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is _UNSTAGEABLE:
                return None
            if got is None:
                if field.startswith(SYNTH_LEN):
                    got = stage_len_column(part, field[len(SYNTH_LEN):],
                                           layout, max_abs_times_rows,
                                           put=self._put)
                elif field.startswith(SYNTH_EMPTY):
                    got = stage_empty_column(
                        part, field[len(SYNTH_EMPTY):], layout,
                        put=self._put)
                else:
                    got = stage_numeric(part, field, layout,
                                        max_abs_times_rows,
                                        put=self._put)
                if got is None:
                    self.cache.put_small(key, _UNSTAGEABLE)
                else:
                    self.cache.put(key, got)
            return got

    def _stage_dict(self, part, field: str, layout: StatsLayout):
        key = (part.uid, "#dict", field)
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is _UNSTAGEABLE:
                return None
            if got is None:
                got = stage_dict_codes(part, field, layout,
                                       put=self._put)
                if got is None:
                    self.cache.put_small(key, _UNSTAGEABLE)
                else:
                    self.cache.put(key, got)
            return got

    def _stage_segments(self, part, layout: StatsLayout):
        """Per-row segment ids for a packed part (block -> member
        ordinal); None when the part has no segment map (plain parts
        never see a 'seg' by-key)."""
        seg_of = getattr(part, "segment_of_block", None)
        nseg = getattr(part, "num_segments", 0)
        if seg_of is None or not nseg:
            return None
        key = (part.uid, "#seg")
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is None:
                ids = np.zeros(layout.nrows_padded, dtype=np.int32)
                for bi in range(part.num_blocks):
                    start = layout.starts[bi]
                    ids[start:start + part.block_rows(bi)] = seg_of(bi)
                got = StagedDict(
                    ids=self._put(ids),
                    values=[str(s) for s in range(nseg)],
                    eligible=frozenset(range(part.num_blocks)),
                    nbytes=layout.nrows_padded * 4)
                self.cache.put(key, got)
            return got

    def _stage_seg_slots(self, part, layout: StatsLayout,
                         min_len: int = 0):
        """Segment-aligned slot map of a packed part (int32[S, Lp] row
        indices, -1 padding — tpu/stats_seg.build_seg_slot_map): the
        single-device seg-major kernels and the packed topk k-selection
        gather members into their own padded slot rows through it.
        min_len: floor on Lp (a topk dispatch needs >= k slots)."""
        from .stats_seg import build_seg_slot_map, pad_slots
        lp = pad_slots(max(p.num_rows for p in part.members), min_len)
        key = (part.uid, "#segslots", lp)
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is None:
                idx = build_seg_slot_map(part, layout, min_len)
                # small and consumed whole by every device (the topk
                # k-selection runs under GSPMD on mesh runners):
                # replicated placement, like the bloom planes
                got = StagedBuckets(ids=self._put_replicated(idx),
                                    base=0,
                                    num_buckets=idx.shape[1],
                                    nbytes=int(idx.nbytes))
                self.cache.put(key, got)
            return got

    def _stage_buckets(self, part, layout: StatsLayout, step: int,
                       offset: int, max_buckets: int):
        key = (part.uid, "#tb", step, offset)
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is _UNSTAGEABLE:
                return None
            if got is None:
                got = stage_time_buckets(part, layout, step, offset,
                                         max_buckets, put=self._put)
                if got is None:
                    self.cache.put_small(key, _UNSTAGEABLE)
                else:
                    self.cache.put(key, got)
            return got

    def _assemble_axes(self, part, spec) -> "AxesAssembly | None":
        """Stage everything the stats dispatch needs (value columns,
        bucket/dict/uniq axes); None => this part can't run device stats."""
        from .stats_device import (MAX_ABS_TIMES_ROWS, MAX_BUCKETS,
                                   MAX_QUANTILE_RANGE, MAX_STAT_ROWS)
        layout = self._stats_layout(part)
        if layout.nrows > MAX_STAT_ROWS:
            return None
        numerics = {}
        for fld in spec.value_fields:
            sn = self._stage_numeric(part, fld, layout, MAX_ABS_TIMES_ROWS)
            if sn is None:
                return None
            numerics[fld] = sn

        # one id axis per by key (time buckets / dict-code tables), plus
        # one axis per count_uniq field (its codes enumerate the set)
        axes = []          # (kind, ids_jax, size, decode_payload)
        eligibility = [numerics[fld].eligible
                       for fld in spec.value_fields]
        for bk in spec.by:
            if bk.kind == "seg":
                # per-part segment axis of a packed super-dispatch: the
                # PackedPart's block->member map as per-row int32 ids
                # (tpu/pipeline.py; stats_device.with_segment_axis)
                sg = self._stage_segments(part, layout)
                if sg is None:
                    return None
                axes.append(("s", sg.ids, len(sg.values), None))
                # every block belongs to exactly one segment
                eligibility.append(sg.eligible)
                continue
            if bk.kind == "time":
                sb = self._stage_buckets(part, layout, bk.step, bk.offset,
                                         MAX_BUCKETS)
                if sb is None:
                    return None
                axes.append(("t", sb.ids, sb.num_buckets,
                             (sb.base, bk.step)))
            elif bk.kind == "numbucket":
                key = (part.uid, "#nb", bk.name, bk.fstep, bk.foff)
                with self._key_lock(key):
                    sd = self.cache.get(key)
                    if sd is _UNSTAGEABLE:
                        return None
                    if sd is None:
                        sd = stage_num_buckets(part, bk.name, layout,
                                               bk.fstep, bk.foff,
                                               put=self._put)
                        if sd is None:
                            self.cache.put_small(key, _UNSTAGEABLE)
                            return None
                        self.cache.put(key, sd)
                # payload name None: a uniq axis must never share a
                # BUCKETED axis (it needs raw value codes)
                axes.append(("v", sd.ids, len(sd.values),
                             (None, sd.values)))
                eligibility.append(sd.eligible)
            else:
                sd = self._stage_dict(part, bk.name, layout)
                if sd is None:
                    return None
                axes.append(("v", sd.ids, len(sd.values),
                             (bk.name, sd.values)))
                eligibility.append(sd.eligible)
        uniq_shared = []   # (field, axis_idx): by-field doubles as uniq
        for fld in spec.uniq_fields:
            shared = next((i for i, (k, _i, _s, p) in enumerate(axes)
                           if k == "v" and p[0] == fld), None)
            if shared is not None:
                # same field grouped AND counted: its group axis already
                # enumerates the codes (the S x S product would only fill
                # the diagonal and trip MAX_BUCKETS needlessly)
                uniq_shared.append((fld, shared))
                continue
            sd = self._stage_dict(part, fld, layout)
            if sd is None:
                return None
            axes.append(("u", sd.ids, len(sd.values), (fld, sd.values)))
            eligibility.append(sd.eligible)
        for fld in spec.quantile_fields:
            # the value staging doubles as the histogram axis: same
            # uint32 offsets, cast to int32 inside the jit (combine_ids)
            sn = self._stage_numeric(part, fld, layout,
                                     MAX_ABS_TIMES_ROWS)
            if sn is None or sn.vmax - sn.vmin + 1 > MAX_QUANTILE_RANGE:
                return None
            axes.append(("q", sn.values, sn.vmax - sn.vmin + 1,
                         (fld, sn.vmin)))
            eligibility.append(sn.eligible)
        nb = 1
        nseg = 0
        for k, _i, size, _p in axes:
            nb *= size
            if k == "s":
                nseg = size
        # the segment axis of a packed super-dispatch does NOT count
        # toward the bucket cap: the segment-major kernels
        # (tpu/stats_seg.py) reduce it outside the bucket one-hot, so
        # only the per-member base product pays VMEM/compare width.
        # The [S, buckets] accumulator still scales with the pack —
        # bounded by VL_PACK_PARTS * MAX_BUCKETS output cells.
        if nb // max(nseg, 1) > MAX_BUCKETS:
            return None
        if axes:
            ids_tuple = tuple(a[1] for a in axes)
            # row-major strides in by order
            strides = []
            s = 1
            for _k, _i, size, _p in reversed(axes):
                strides.append(s)
                s *= size
            strides = tuple(reversed(strides))
        else:
            key = (part.uid, "#tb0")
            sb0 = self.cache.get(key)
            if sb0 is None:
                sb0 = StagedBuckets(
                    ids=self._put(np.zeros(layout.nrows_padded,
                                           np.int32)),
                    base=0, num_buckets=1,
                    nbytes=layout.nrows_padded * 4)
                self.cache.put(key, sb0)
            ids_tuple, strides = (sb0.ids,), (1,)
        return AxesAssembly(layout=layout, numerics=numerics, axes=axes,
                            eligibility=eligibility, ids_tuple=ids_tuple,
                            strides=strides, nb=nb,
                            uniq_shared=uniq_shared, nseg=nseg)

    def _key_parts(self, asm: "AxesAssembly", idx: int) -> tuple:
        """(group-key components, uniq-axis values) for one cell."""
        ks = [(idx // stride) % size
              for (_k, _i, size, _p), stride in zip(asm.axes, asm.strides)]
        out = []
        uniq = {}
        qv = {}
        for (kind, _ids, size, payload), k in zip(asm.axes, ks):
            if kind == "s":
                # packed-part segment: stripped (and used to route the
                # partial to its member part) by the pipeline harvest
                out.append(("s", k))
            elif kind == "t":
                base, step = payload
                out.append(("t", base + k * step))
            elif kind == "v":
                out.append(("v", payload[1][k]))
            elif kind == "q":     # quantile histogram: numeric cell value
                fld, vmin0 = payload
                qv[fld] = vmin0 + k
            else:  # uniq axis: not part of the group key
                fld, values = payload
                uniq[fld] = values[k]
        for fld, ai in asm.uniq_shared:
            uniq[fld] = asm.axes[ai][3][1][ks[ai]]
        return tuple(out), uniq, qv

    def _partials_from_counts(self, asm: "AxesAssembly", counts,
                              stats_np: dict) -> list:
        from .stats_device import combine_plane_sums
        partials = []
        for idx in np.nonzero(counts)[0]:
            cnt = int(counts[idx])
            fs = {}
            for fld, packed in stats_np.items():
                vmin0 = asm.numerics[fld].vmin
                s = combine_plane_sums(packed[1:5, idx]) + cnt * vmin0
                fs[fld] = (s, int(packed[5, idx]) + vmin0,
                           int(packed[6, idx]) + vmin0)
            kp, uniq, qv = self._key_parts(asm, int(idx))
            partials.append((kp, cnt, fs, uniq, qv))
        return partials

    # -- fused-path staging hooks (layout-coordinate columns, ts planes) --

    def _stage_fused_field(self, part, field: str, layout):
        from .fused import stage_layout_column
        key = (part.uid, "#fl", field)
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is _UNSTAGEABLE:
                return None
            if got is None:
                got = stage_layout_column(part, field, layout,
                                          self.max_part_bytes,
                                          put=self._put)
                if got is None:
                    self.cache.put_small(key, _UNSTAGEABLE)
                else:
                    self.cache.put(key, got)
            return got

    def _stage_multibyte(self, part, field: str, layout):
        from .fused import stage_multibyte_mask
        key = (part.uid, "#mb", field)
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is None:
                got = stage_multibyte_mask(part, field, layout,
                                           put=self._put)
                self.cache.put(key, got)
            return got

    def _stage_ts_planes(self, part, layout):
        from .fused import stage_ts_planes
        key = (part.uid, "#ts2")
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is None:
                got = stage_ts_planes(part, layout, put=self._put)
                self.cache.put(key, got)
            return got

    def _stage_bloom_plane(self, part, field: str):
        """HBM-resident packed bloom plane for the fused in-dispatch
        bloom kill (tpu/bloom_device.py); cached like all staging."""
        from .bloom_device import stage_bloom_plane
        key = (part.uid, "#bloom", field)
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is _UNSTAGEABLE:
                return None
            if got is None:
                got = stage_bloom_plane(part, field,
                                        put=self._put_replicated)
                if got is None:
                    self.cache.put_small(key, _UNSTAGEABLE)
                else:
                    self.cache.put(key, got)
            return got

    def _stage_sb_plane(self, part, field: str):
        """HBM-resident split-block plane (sealed-part filter index v2)
        for the fused in-dispatch bloom kill: ONE contiguous 8-lane
        gather per (block, token) instead of 6 scattered lane selects.
        None when the part has no valid v2 sidecar for the column."""
        from .bloom_device import stage_sb_plane
        key = (part.uid, "#sbbloom", field)
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is _UNSTAGEABLE:
                return None
            if got is None:
                got = stage_sb_plane(part, field,
                                     put=self._put_replicated)
                if got is None:
                    self.cache.put_small(key, _UNSTAGEABLE)
                else:
                    self.cache.put(key, got)
            return got

    def _stage_block_ids(self, part, layout):
        from .bloom_device import stage_block_ids
        key = (part.uid, "#bid")
        with self._key_lock(key):
            got = self.cache.get(key)
            if got is None:
                got = stage_block_ids(part, layout, put=self._put)
                self.cache.put(key, got)
            return got

    def run_part_topk(self, f, part, bss: dict, spec):
        """Filter + sort-topk threshold prefilter for one part in ONE
        dispatch (tpu/fused.py try_fused_topk; spec from
        sort_device.device_sort_spec).  Returns block_idx -> bitmap
        holding exactly the filter-matching rows at-or-above the part's
        k-th best sort key (a superset of the part's contribution to the
        global top-k — the host sort processor resolves order and ties
        exactly like the CPU path), or None when the shape declines."""
        pending = self.run_part_topk_submit(f, part, bss, spec)
        return None if pending is None else pending.harvest()

    def run_part_topk_submit(self, f, part, bss: dict, spec):
        """Async variant of run_part_topk: the dispatch (packed or
        single-part) is ISSUED now and materialized at harvest(), so
        the windowed pipeline keeps sort-topk units outstanding like
        every other query shape.  None when the host gate or the fused
        planner declines (caller falls back to ordinary evaluation)."""
        cand_rows = sum(bs.nrows for bs in bss.values())
        if self._gate_host(f, part, bss, stats_rows=max(cand_rows, 1)):
            return None               # run_part re-gates and runs host
        from .fused import fused_topk_submit
        return fused_topk_submit(self, f, part, bss, spec)

    def run_part_stats(self, f, part, bss: dict, spec):
        """Filter + stats partials for one part.

        Fast path (tpu/fused.py): when the whole filter tree is
        device-expressible and every candidate block is stats-eligible,
        filter AND stats run as ONE device dispatch — the row bitmap
        never leaves HBM.  Otherwise: ordinary filter evaluation
        (run_part), then per-bucket count/sum/min/max partials on
        device with the row bitmap uploaded once and only
        (buckets,)-sized results downloaded.  This is the fused
        analogue of the reference's per-worker stats shards merged at
        flush (pipe_stats.go:354-377).

        Returns (bms, handled, partials):
        - bms: block_idx -> bitmap (covers at least the non-handled
          blocks; empty when everything was handled on device);
        - handled: block idxs fully accounted for by the partials (the
          caller must NOT feed them through the row path);
        - partials: list of
          (key_parts, count, field_stats, uniq_vals, quant_vals) where
          key_parts follows the spec's by order with elements
          ("t", bucket_ns) for the time axis and ("v", value_str) for
          group-by fields, field_stats maps
          field -> (sum:int, vmin:int, vmax:int), uniq_vals maps
          count_uniq fields to the cell's value string, and quant_vals
          maps quantile/median fields to the cell's numeric value.
        """
        return self.run_part_stats_submit(f, part, bss, spec).harvest()

    def run_part_stats_submit(self, f, part, bss: dict, spec):
        """Async variant of run_part_stats: the fused dispatch (when the
        shape allows one) is ISSUED now and materialized at harvest(), so
        the windowed pipeline can keep several parts outstanding.  Host-
        gated and unfused shapes compute synchronously and come back as
        ready handles — one protocol either way."""
        from .fused import _Ready, fused_stats_submit
        cand_rows = sum(bs.nrows for bs in bss.values())
        if self._gate_host(f, part, bss, stats_rows=max(cand_rows, 1)):
            self._bump("gated_host_parts")
            return _Ready((self._host_eval_part(f, bss), set(), []))
        asm = self._assemble_axes(part, spec)
        if asm is not None and self.fused_enabled:
            pending = fused_stats_submit(self, f, part, bss, spec, asm)
            if pending is not None:
                return pending
        return _Ready(self._run_part_stats_unfused(f, part, bss, spec,
                                                   asm))

    def run_part_submit(self, f, part, bss: dict):
        """Async variant of run_part for ROW queries: the whole filter
        tree compiles into ONE fused dispatch (fused.fused_filter_submit)
        whose packed result is materialized at harvest(); shapes the
        planner declines fall back to the per-leaf path synchronously."""
        from .fused import _Ready, fused_filter_submit
        if self._gate_host(f, part, bss):
            self._bump("gated_host_parts")
            return _Ready(self._host_eval_part(f, bss))
        if self.fused_enabled:
            pending = fused_filter_submit(self, f, part, bss)
            if pending is not None:
                return pending
        return _Ready(self._run_part_device(f, part, bss))

    def _run_part_stats_unfused(self, f, part, bss: dict, spec, asm):
        """The two-dispatch fallback: ordinary filter evaluation, then
        per-bucket partials over the uploaded row mask."""
        bms = self.run_part(f, part, bss)
        if asm is None:
            return bms, set(), []
        layout = asm.layout
        handled = {bi for bi in bss
                   if all(bi in el for el in asm.eligibility)}
        if not handled:
            return bms, set(), []
        mask = np.zeros(layout.nrows_padded, dtype=bool)
        matched = 0
        for bi in handled:
            bm = bms[bi]
            if bm.any():
                start = layout.starts[bi]
                mask[start:start + bm.shape[0]] = bm
                matched += int(bm.sum())
        if not matched:
            return bms, handled, []
        if matched < self.stats_host_threshold:
            # a handful of rows: the host pipe aggregates them faster
            # than a mask upload (+~97ms) and a dispatch (+~65ms)
            return bms, set(), []
        mask_j = self._put(mask)

        if spec.value_fields:
            counts = None
            stats_np = {}
            for fld in spec.value_fields:
                self._bump("device_calls")
                self._bump("stats_dispatches")
                self._kind("stats_values")
                packed = self._dispatch_stats_values(
                    asm.numerics[fld].values, asm.ids_tuple, asm.strides,
                    mask_j, asm.nb)
                counts = packed[0]
                stats_np[fld] = packed
            return bms, handled, self._partials_from_counts(
                asm, counts, stats_np)

        self._bump("device_calls")
        self._bump("stats_dispatches")
        self._kind("stats_count")
        counts = self._dispatch_stats_count(asm.ids_tuple, asm.strides,
                                            mask_j, asm.nb)
        return bms, handled, self._partials_from_counts(asm, counts, {})

    def _scan_pair(self, spc: StagedPart, pair: tuple):
        """Device `A.*B` evaluation; returns (survivors, host_verify_mask)."""
        import jax.numpy as jnp
        a, b = pair
        if max(len(a), len(b)) >= spc.width:
            return np.zeros(spc.nrows, dtype=bool), None
        self._bump("device_calls")
        self._kind("scan_pair")
        # vlint: allow-jax-host-sync(bit-packed survivor download)
        packed = np.array(K32.match_ordered_pair_t_packed(
            spc.rows, spc.lengths,
            jnp.asarray(np.frombuffer(a, dtype=np.uint8)), len(a),
            jnp.asarray(np.frombuffer(b, dtype=np.uint8)), len(b)))
        definite = np.unpackbits(packed[0])[:spc.nrows].astype(bool)
        needs_verify = np.unpackbits(packed[1])[:spc.nrows].astype(bool)
        return definite | needs_verify, needs_verify

    def _run_ops(self, spc: StagedPart, plan: LeafPlan) -> np.ndarray | None:
        """AND/OR the leaf's scan ops over the whole staged part.

        Returns bool[spc.nrows], or None for an op-less leaf (regex with no
        safe literals => everything survives to verification)."""
        combined = None
        for op in plan.ops:
            m = self._scan(spc, op)
            if combined is None:
                combined = m
            elif plan.combine == "and":
                combined &= m
            else:
                combined |= m
            if plan.combine == "and" and combined is not None and \
                    not combined.any():
                break
        return combined

    def _scan(self, spc: StagedPart, op: ScanOp) -> np.ndarray:
        import jax.numpy as jnp
        if op.match_nonempty:
            return spc.lengths_np[:spc.nrows] > 0
        if op.match_empty:
            return spc.lengths_np[:spc.nrows] == 0
        if len(op.pattern) >= spc.width:
            # no staged (truncated) value can contain it; overflow rows are
            # re-checked from the full values by the caller
            return np.zeros(spc.nrows, dtype=bool)
        self._bump("device_calls")
        self._kind(f"scan:m{op.mode}" + (":fold" if op.fold else ""))
        import time
        # calls of a not-yet-compiled jit signature pay (or block on a
        # concurrent worker's) XLA compilation — seconds; feeding such a
        # timing to the EWMA would poison dev_bytes_per_s into the MB/s
        # range and route everything to host (ADVICE r4).  Only timings
        # whose signature was compiled BEFORE the dispatch started count.
        sig = (spc.rows.shape, len(op.pattern), op.mode,
               op.starts_tok, op.ends_tok, op.fold)
        with self._counter_mu:
            pre_compiled = sig in self._scan_sigs
        t0 = time.perf_counter()
        pat = jnp.asarray(np.frombuffer(op.pattern, dtype=np.uint8))
        res = K32.match_scan_t_packed(spc.rows, spc.lengths, pat,
                                      len(op.pattern), op.mode,
                                      op.starts_tok, op.ends_tok, op.fold)
        # bit-packed download (~20x less transfer); unpack is a writable copy
        # vlint: allow-jax-host-sync(bit-packed survivor download)
        out = np.unpackbits(np.array(res))[:spc.nrows].astype(bool)
        elapsed = time.perf_counter() - t0
        with self._counter_mu:
            self._scan_sigs.add(sig)
        if pre_compiled:
            self.cost.observe_device_scan(spc.nbytes, elapsed)
            # per-leaf dispatches are full round trips too; compile-time
            # samples are excluded for the same poisoning reason
            hist.DISPATCH_RTT.observe(elapsed)
        return out
