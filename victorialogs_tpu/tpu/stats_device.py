"""Device-side stats partials: query analysis + partial-state assembly.

The reference's stats engine computes per-shard partial states and merges
them at flush (lib/logstorage/pipe_stats.go:354-377); its cluster mode ships
mergeable states between nodes (pipe_stats.go:93-125).  The TPU-shaped
analogue: when a query is `<filter> | stats [by (_time:step)] <funcs...>`,
the per-bucket partials (count / sum / min / max) are computed ON DEVICE in
one dispatch fused after the filter bitmap — the per-row bitmap and the
column values never leave HBM; the host downloads a few (num_buckets,)
vectors and merges them into the ordinary PipeStats group map, so the rest
of the pipe chain (and the cluster export/import contract) is unchanged.

Exactness contract (why this path is bit-equal to the CPU executor):
- eligible value columns are storage-typed uint/int (VT_UINT8..64,
  VT_INT64), whose encodings are round-trip exact — every stored string is
  the canonical decimal of its value, so min/max chosen numerically on
  device map back to the same strings the host would pick, and there are
  no numeric ties between distinct strings;
- sums are computed exactly: values are staged as uint32 offsets from the
  part minimum and the kernel accumulates four uint8 byte-planes (each
  plane sum bounded by 255 * R < 2**32), which the host recombines with
  Python integers — no float rounding anywhere on the device path;
- a part is only eligible while max|value| * num_rows < 2**53, keeping the
  HOST executor's float64 accumulation exact too (otherwise the exact
  device sum could disagree with a rounded host sum).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from ..logsql import stats_funcs as sf
from ..logsql.duration import parse_duration
from ..logsql.matchers import parse_number as _parse_num

MAX_BUCKETS = 8192
MAX_STAT_ROWS = 16 << 20          # plane-sum bound: 255 * R < 2**32
MAX_ABS_TIMES_ROWS = 1 << 53      # keep the host float64 path exact as well
MAX_QUANTILE_RANGE = 2048         # per-value histogram axis width cap

# synthetic value-column tokens: sum_len/count_empty ride the standard
# stats kernel over DERIVED uint32 columns (code-point lengths / 0-1
# empty flags) — the sum plane is the answer (batch.stage_len_column,
# stage_empty_column).  Tokens flow through value_fields/staging keys;
# the prefixes cannot collide with parsed field names in practice.
SYNTH_LEN = "#synth:len:"
SYNTH_EMPTY = "#synth:empty:"


@dataclass
class FuncSpec:
    kind: str                     # count | count_field | sum | avg | min | max
    field: str | None             # value field (None for plain count)


@dataclass
class ByKey:
    kind: str                     # 'time' | 'field' | 'numbucket' | 'seg'
    #                               ('seg': per-part segment axis of a
    #                               packed super-dispatch — see
    #                               with_segment_axis below)
    name: str = ""                # field name ('field'/'numbucket')
    step: int = 0                 # ns (kind == 'time')
    offset: int = 0               # ns (kind == 'time')
    fstep: float = 0.0            # numeric bucket size ('numbucket')
    foff: float = 0.0             # numeric bucket offset ('numbucket')


@dataclass
class StatsSpec:
    by: list                      # list[ByKey] in the pipe's by order
    funcs: list                   # list[FuncSpec], parallel to pipe.funcs
    value_fields: list            # distinct numeric fields, staging order
    uniq_fields: list             # distinct count_uniq fields (dict axes)
    quantile_fields: list         # distinct quantile/median fields
    #                               (per-value histogram axes)


def with_segment_axis(spec: StatsSpec) -> StatsSpec:
    """Pack-dispatch variant of a stats spec: a LEADING per-part segment
    axis (ByKey kind 'seg') so ONE fused super-dispatch over several
    concatenated small parts yields per-part partials.

    The segment axis multiplies the bucket product by the pack's member
    count and every partial's key_parts leads with ("s", member_idx) —
    batch._assemble_axes stages the per-row segment ids from the packed
    part's block->member map and fused._residue_partials keys residue
    rows the same way.  The pipeline (tpu/pipeline.py) strips that
    component and absorbs each member's partials in submission order, so
    the stats processor sees EXACTLY the per-part absorb granularity the
    serial path produces.  (The funcs' merge() is commutative, so this
    is an auditability/parity guarantee, not a correctness requirement.)
    """
    return StatsSpec(by=[ByKey("seg")] + list(spec.by),
                     funcs=spec.funcs,
                     value_fields=spec.value_fields,
                     uniq_fields=spec.uniq_fields,
                     quantile_fields=spec.quantile_fields)


def _func_spec(fn) -> FuncSpec | None:
    """Map one parsed stats function to its device partial kind.

    Exact type() checks: subclasses may change update/finalize semantics
    (StatsRate and StatsRateSum are explicitly allowed — they reuse the
    count/sum STATE and only change finalize, which stays on the host)."""
    t = type(fn)
    if t in (sf.StatsCount, sf.StatsRate):
        if not fn.fields:
            return FuncSpec("count", None)
        if len(fn.fields) == 1 and "*" not in fn.fields[0]:
            # int-typed blocks have a value in every row, so count(field)
            # over an eligible block is just the masked row count
            return FuncSpec("count_field", fn.fields[0])
        return None
    if t in (sf.StatsSum, sf.StatsRateSum):
        if len(fn.fields) == 1 and "*" not in fn.fields[0]:
            return FuncSpec("sum", fn.fields[0])
        return None
    if t is sf.StatsAvg:
        if len(fn.fields) == 1 and "*" not in fn.fields[0]:
            return FuncSpec("avg", fn.fields[0])
        return None
    if t is sf.StatsMin:
        if len(fn.fields) == 1 and "*" not in fn.fields[0]:
            return FuncSpec("min", fn.fields[0])
        return None
    if t is sf.StatsMax:
        if len(fn.fields) == 1 and "*" not in fn.fields[0]:
            return FuncSpec("max", fn.fields[0])
        return None
    if t is sf.StatsSumLen:
        # total CODE-POINT length per group: a derived uint32 column
        # (stage_len_column) through the standard sum partials
        if len(fn.fields) == 1 and "*" not in fn.fields[0] and \
                fn.fields[0] != "_time":
            return FuncSpec("sum_len", SYNTH_LEN + fn.fields[0])
        return None
    if t is sf.StatsCountEmpty:
        # empty-value count per group: a derived 0/1 column
        # (stage_empty_column) through the standard sum partials
        if len(fn.fields) == 1 and "*" not in fn.fields[0] and \
                fn.fields[0] != "_time":
            return FuncSpec("count_empty", SYNTH_EMPTY + fn.fields[0])
        return None
    if t in (sf.StatsQuantile, sf.StatsMedian):
        # exact per-value histogram over an int column with a SMALL value
        # range: the (group, value) counts reconstruct the host's value
        # list bit-for-bit ([v]*c per cell), so finalize's sort+select is
        # unchanged; several quantiles of one field share the axis
        if len(fn.fields) == 1 and "*" not in fn.fields[0] and \
                fn.fields[0] != "_time":
            return FuncSpec("quantile", fn.fields[0])
        return None
    if t is sf.StatsCountUniq:
        # distinct values ride an extra bucket axis over the field's
        # per-part dict codes; the state stays the exact value SET, so
        # host/device/cluster merging is unchanged (limit only caps
        # finalize).  _stream_id/_stream are block constants, so the
        # flagship `count_uniq(_stream_id)` shape is eligible.
        if len(fn.fields) == 1 and "*" not in fn.fields[0] and \
                fn.fields[0] != "_time":
            # _time is a virtual column the dict stager cannot see (it
            # would stage as the constant '' and silently drop values)
            return FuncSpec("uniq", fn.fields[0])
        return None
    return None


def device_stats_spec(q) -> StatsSpec | None:
    """Static per-query analysis: can pipes[0] run as device partials?

    Eligible shape: first pipe is a plain `stats` (or the cluster's
    stats_export wrapper — same grouping semantics), grouped by nothing,
    by ONE `_time:<duration>` bucket, and/or by plain fields (those ride
    the per-part dict-code tables when the columns are dict/const-typed —
    decided per part at staging), with every function mapping to a device
    partial and no per-function `if (...)` guards."""
    if not q.pipes:
        return None
    ps = q.pipes[0]
    from ..logsql.pipes import PipeStats
    if not isinstance(ps, PipeStats) or \
            getattr(ps, "name", "") not in ("stats", "stats_export"):
        return None
    by: list[ByKey] = []
    n_time = 0
    for b in ps.by:
        if b.name == "_time" and b.bucket:
            if b.bucket.lower() in ("week", "month", "year"):
                return None
            d = parse_duration(b.bucket)
            if not d or d <= 0:
                return None
            n_time += 1
            if n_time > 1:
                return None
            by.append(ByKey("time", step=int(d), offset=b.offset_ns()))
            continue
        if b.name in ("_time", "_stream", "_stream_id") or "*" in b.name:
            return None  # special fields: host path
        if b.bucket:
            fstep = _parse_num(b.bucket)
            if math.isnan(fstep) or fstep <= 0:
                # invalid bucket: the host keys on the raw value, which
                # is exactly the plain dict-code axis
                by.append(ByKey("field", name=b.name))
                continue
            foff = _parse_num(b.bucket_offset) if b.bucket_offset else 0.0
            if math.isnan(foff):
                foff = 0.0
            by.append(ByKey("numbucket", name=b.name, fstep=fstep,
                            foff=foff))
            continue
        by.append(ByKey("field", name=b.name))
    funcs = []
    for fn in ps.funcs:
        if fn.iff is not None:
            return None
        spec = _func_spec(fn)
        if spec is None:
            return None
        funcs.append(spec)
    fields: list[str] = []
    uniq: list[str] = []
    quant: list[str] = []
    for f in funcs:
        if f.kind == "uniq":
            if f.field not in uniq:
                uniq.append(f.field)
        elif f.kind == "quantile":
            if f.field not in quant:
                quant.append(f.field)
        elif f.field is not None and f.field not in fields:
            fields.append(f.field)
    return StatsSpec(by=by, funcs=funcs, value_fields=fields,
                     uniq_fields=uniq, quantile_fields=quant)


def combine_plane_sums(planes) -> int:
    """Exact uint sum from the kernel's four uint8-plane partials."""
    total = 0
    for p, s in enumerate(planes):
        total += int(s) << (8 * p)
    return total


def build_partial_states(spec: StatsSpec, pipe_funcs, bucket_key,
                         count: int, field_stats: dict,
                         uniq_vals: dict | None = None,
                         quant_vals: dict | None = None) -> list:
    """Per-bucket states list (parallel to pipe_funcs) from kernel outputs.

    field_stats: field -> (sum:int, vmin:int, vmax:int) exact integers.
    uniq_vals: field -> the uniq-axis value this partial covers (one
    partial is emitted per (group, uniq-code) cell; same-key partials
    merge through the funcs' own merge(), unioning the value sets).
    quant_vals: field -> the quantile-axis numeric value of this cell;
    the state contribution is [v]*count — the exact list the host's
    update() would have built for these rows.
    The states are merged into the stats processor with the funcs' own
    merge(), so downstream behavior (finalize, export/import for cluster
    pushdown) is identical to the host path."""
    states = []
    for fs, fn in zip(spec.funcs, pipe_funcs):
        if fs.kind in ("count", "count_field"):
            states.append(count)
        elif fs.kind == "sum":
            s = field_stats[fs.field][0]
            states.append(float(s) if count else math.nan)
        elif fs.kind == "avg":
            s = field_stats[fs.field][0]
            states.append((float(s), count))
        elif fs.kind in ("sum_len", "count_empty"):
            # host state is a plain int; the derived column's sum plane
            # is exactly the total length / empty count for these rows
            states.append(int(field_stats[fs.field][0]))
        elif fs.kind == "min":
            states.append(str(field_stats[fs.field][1]) if count else None)
        elif fs.kind == "max":
            states.append(str(field_stats[fs.field][2]) if count else None)
        elif fs.kind == "uniq":
            v = (uniq_vals or {}).get(fs.field, "")
            states.append({(v,)} if count and v != "" else set())
        elif fs.kind == "quantile":
            v = (quant_vals or {}).get(fs.field)
            states.append([float(v)] * count if count and v is not None
                          else [])
        else:  # pragma: no cover - _func_spec gates kinds
            raise AssertionError(fs.kind)
    return states
