"""HBM staging: storage blocks -> fixed-shape device tensors.

A string column stages as (padded uint8 arena, int32 offsets, int32 lengths);
shapes are bucketed (kernels.pad_bucket) so the jit cache stays small.  Staged
columns are LRU-cached across queries keyed by (part, block, column) — the
device-side analogue of the reference's per-block value caches
(block_search.go:411-474), and the practical expression of "decompressed
columnar blocks staged into HBM" from the north star.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import activity, tracing
from .kernels import pad_bucket


MAX_ROW_WIDTH = 2048  # values longer than W-1 overflow to the host path


@dataclass
class StagedStringColumn:
    rows: jax.Array           # uint8[rows_bucket, W]: values at col 0,
    #                           tail-padded with 0xFF
    lengths: jax.Array        # int32[rows_bucket] (tail rows: 0)
    nrows: int                # true row count
    nrows_padded: int
    width: int                # W
    overflow: np.ndarray      # int64[] row indices longer than W-1
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


def row_width_bucket(max_len: int) -> int:
    """Fixed row width: power of two >= max_len+1, capped at MAX_ROW_WIDTH."""
    w = 32
    while w <= max_len and w < MAX_ROW_WIDTH:
        w *= 2
    return w


def to_fixed_width(arena_np: np.ndarray, offsets_np: np.ndarray,
                   lengths_np: np.ndarray, rb: int, width: int | None = None
                   ) -> tuple[np.ndarray, int, np.ndarray]:
    """Transpose a packed string column into (rows_bucket, W) uint8.

    Returns (matrix, W, overflow_row_indices).  Overflow rows (longer than
    W-1) are truncated in the matrix; the runner re-checks them on host.
    Uses the C++ host core when available (native/vlnative.cpp); numpy
    fancy-indexing fallback otherwise.
    """
    r = int(offsets_np.shape[0])
    max_len = int(lengths_np.max()) if r else 0
    w = width if width is not None else row_width_bucket(max_len)
    from .. import native
    nat = native.to_fixed_width_native(arena_np, offsets_np, lengths_np,
                                       rb, w)
    if nat is not None:
        overflow = np.nonzero(lengths_np > w - 1)[0]
        return nat, w, overflow
    out = np.full((rb, w), 0xFF, dtype=np.uint8)
    if r:
        copy_lens = np.minimum(lengths_np, w - 1)
        idx = (np.repeat(np.arange(r, dtype=np.int64) * w, copy_lens)
               + _ranges(copy_lens))
        src = (np.repeat(offsets_np, copy_lens) + _ranges(copy_lens))
        out.reshape(-1)[idx] = arena_np[src]
    overflow = np.nonzero(lengths_np > w - 1)[0]
    return out, w, overflow


def to_lanes32(mat: np.ndarray) -> np.ndarray:
    """(R, W) uint8 staging matrix -> (W/4, R) uint32 lane-major layout
    for the u32-chunk kernels (tpu/kernels32.py): lanes[q, r] is the
    little-endian word of bytes mat[r, 4q:4q+4].  Transposed so the row
    axis rides the 128-wide TPU lane dimension (and shards over a mesh
    along axis 1).  W is always a multiple of 4 (row_width_bucket)."""
    r, w = mat.shape
    assert w % 4 == 0
    return np.ascontiguousarray(
        mat.reshape(r, w // 4, 4).view("<u4")[:, :, 0].T)


def rows_with_multibyte(arena_np: np.ndarray, offsets_np: np.ndarray,
                        lengths_np: np.ndarray) -> np.ndarray:
    """Per-row any(byte >= 0x80) over the SOURCE values (truncated tails
    included), via prefix sums — exact even for zero-length rows.
    Returns bool[r].  Consumed by case-fold and len_range device leaves,
    whose byte-level compares are only definitive for pure-ASCII rows."""
    r = int(offsets_np.shape[0])
    if not arena_np.size or not (arena_np >= 0x80).any():
        return np.zeros(r, dtype=bool)
    cs = np.zeros(arena_np.size + 1, dtype=np.int64)
    np.cumsum(arena_np >= 0x80, out=cs[1:])
    offs = offsets_np.astype(np.int64)
    lens = lengths_np.astype(np.int64)
    return cs[offs + lens] > cs[offs]


def _ranges(lengths: np.ndarray) -> np.ndarray:
    """Concatenated [0..l) ranges for each l in lengths."""
    total = int(lengths.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    ends = np.cumsum(lengths)
    out = np.arange(total, dtype=np.int64)
    out -= np.repeat(ends - lengths, lengths)
    return out


def stage_string_column(arena_np: np.ndarray, offsets_np: np.ndarray,
                        lengths_np: np.ndarray) -> StagedStringColumn:
    r = int(offsets_np.shape[0])
    rb = pad_bucket(max(r, 1), minimum=1024)
    mat, w, overflow = to_fixed_width(arena_np, offsets_np, lengths_np, rb)
    # overflow rows carry their truncated length; the runner re-evaluates
    # them on host regardless of the device verdict
    lens = np.zeros(rb, dtype=np.int32)
    lens[:r] = np.minimum(lengths_np, w - 1).astype(np.int32)
    return StagedStringColumn(
        rows=jnp.asarray(mat), lengths=jnp.asarray(lens),
        nrows=r, nrows_padded=rb, width=w, overflow=overflow,
        nbytes=rb * w + rb * 4)


import threading as _threading
import weakref as _weakref

_caches_mu = _threading.Lock()
_caches: "_weakref.WeakSet" = _weakref.WeakSet()


def staging_caches() -> list:
    """Every live StagingCache (vlsan sweeps check_balanced on each
    after every test)."""
    with _caches_mu:
        return list(_caches)


class StagingCache:
    """LRU over staged columns, bounded by device bytes.

    Thread-safe: the prefetcher, concurrent partition scans and the query
    thread all touch it (batch.py)."""

    def __init__(self, max_bytes: int = 4 << 30):
        import threading
        with _caches_mu:
            _caches.add(self)
        self.max_bytes = max_bytes
        self._lru: OrderedDict[tuple, StagedStringColumn] = OrderedDict()
        self._bytes = 0
        self._mu = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple):
        with self._mu:
            got = self._lru.get(key)
            if got is not None:
                self._lru.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
            return got

    @staticmethod
    def _cost(col) -> int:
        # markers without device buffers still occupy a nominal slot so the
        # LRU eventually evicts them (long-running servers mint a fresh part
        # uid every flush/merge)
        return col.device_bytes() if hasattr(col, "device_bytes") else 4096

    def put(self, key: tuple, col) -> None:
        cost = self._cost(col)
        with self._mu:
            if key in self._lru:
                return
            self._lru[key] = col
            self._bytes += cost
            while self._bytes > self.max_bytes and self._lru:
                _, old = self._lru.popitem(last=False)
                self._bytes -= self._cost(old)
        # staging attribution on the active trace (noop when off); the
        # insert above returned early on a duplicate, so this counts
        # each staged value exactly once
        sp = tracing.current_span()
        if sp.enabled:
            sp.add("staged_entries")
            sp.add("staged_bytes", cost)
        activity.current_activity().add("bytes_staged", cost)

    def put_small(self, key: tuple, marker) -> None:
        """Cache a marker (e.g. 'this column is unstageable')."""
        self.put(key, marker)

    def contains(self, key: tuple) -> bool:
        """Membership probe without touching LRU order or hit counters."""
        with self._mu:
            return key in self._lru

    def stats(self) -> dict:
        """Observability snapshot (runner stats / pipeline tests)."""
        with self._mu:
            return {"hits": self.hits, "misses": self.misses,
                    "bytes": self._bytes, "entries": len(self._lru)}

    def check_balanced(self) -> bool:
        """Budget-accounting invariant: the running byte total equals
        the recomputed cost of every live entry.  The pipeline's
        cancellation tests assert this after draining an in-flight
        window (a poisoned/partial entry would break the equality)."""
        with self._mu:
            return self._bytes == sum(self._cost(c)
                                      for c in self._lru.values())

    def clear(self) -> None:
        with self._mu:
            self._lru.clear()
            self._bytes = 0
