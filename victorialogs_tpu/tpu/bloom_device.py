"""Batched bloom-plane probes: one dense bit-test over all blocks.

The host packs a part column's bloom filters into a zero-padded uint32
plane `[B, 2*Wmax]` and derives per-block probe coordinates from the
query tokens (storage/filterbank.py — positions come from
``bloom_probe_positions`` so host and device share one derivation).
This module evaluates the keep-mask three ways off those SAME
arguments:

- ``probe_np``: vectorized numpy — the host kill-path in
  tpu/batch.py's leaf evaluation and the prefetcher (a probe over 10k
  blocks is one gather + bit-test instead of 10k Python calls).
- ``plane_keep``: the jnp expression, traceable inside the fused
  single-dispatch jit (tpu/fused.py) — the per-block keep-mask gathers
  to rows through the staged block-id column and ANDs against the scan
  tree IN HBM, no host round-trip.
- ``plane_keep_pallas``: a VMEM-tiled Pallas variant (gate behind
  VL_PALLAS=1, exactly like kernels_pallas.match_scan) replacing the
  gather with a lane-select so the probe stays a dense VPU op;
  interpret-mode parity is pinned in tests/pallas_check.py.

Layout contract (split-block style, Lang et al. arXiv:2101.01719):
  plane  uint32[B, WP]  2 little-endian lanes per uint64 word, 0-padded
  idx    int32[B, P]    uint32-lane index of each probe bit (< 2*nwords)
  shift  int32[B, P]    bit position within the lane (0..31)
  nwords int32[B]       0 => block has no bloom => always keep
returns bool[B]: True where the block may contain ALL probed tokens.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .kernels_pallas import _VMEM, PALLAS_AVAILABLE, pl

PROBE_TILE_B = 128     # pallas block-axis tile (int32 sublane multiple)
PROBE_LANE = 128       # pallas lane width; also the max probe count
MAX_PALLAS_PROBES = PROBE_LANE


def probe_np(plane: np.ndarray, idx: np.ndarray, shift: np.ndarray,
             nwords: np.ndarray) -> np.ndarray:
    """Vectorized host probe; bit-identical to per-block
    bloom_contains_all (tests/test_filterbank.py differentials)."""
    if idx.shape[1] == 0:
        return np.ones(plane.shape[0], dtype=bool)
    words = np.take_along_axis(plane, idx, axis=1)
    bits = (words >> shift.astype(np.uint32)) & np.uint32(1)
    return (bits != 0).all(axis=1) | (nwords == 0)


def plane_keep(plane, idx, shift, nwords, use_pallas: bool = False,
               interpret: bool = False):
    """jnp keep-mask; traceable inside an outer jit (fused dispatch)."""
    if use_pallas and PALLAS_AVAILABLE and \
            _pallas_ok(plane.shape, idx.shape):
        return plane_keep_pallas(plane, idx, shift, nwords,
                                 interpret=interpret)
    words = jnp.take_along_axis(plane, idx, axis=1)
    bits = (words >> shift.astype(jnp.uint32)) & jnp.uint32(1)
    return jnp.all(bits != 0, axis=1) | (nwords == 0)


@jax.jit
def plane_probe(plane, idx, shift, nwords):
    """Standalone jitted probe -> bool[B] (bench/parity entry point)."""
    return plane_keep(plane, idx, shift, nwords)


# ---------------- pallas variant ----------------

def _pallas_ok(plane_shape, idx_shape) -> bool:
    b, wp = plane_shape
    return (b % PROBE_TILE_B == 0 and wp % PROBE_LANE == 0
            and 0 < idx_shape[1] <= MAX_PALLAS_PROBES)


def _probe_kernel(plane_ref, idx_ref, shift_ref, nw_ref, out_ref, *,
                  nprobes: int, wp: int):
    """One (PROBE_TILE_B, WP) tile: all probes tested from VMEM.

    No gather: each probe selects its lane by comparing a broadcast
    iota against the per-block lane index and sum-reducing the masked
    plane (exactly one lane matches; idx < 2*nwords <= WP always), so
    the probe lowers to dense VPU compare/select/reduce ops — the same
    Mosaic-friendly shape discipline as kernels_pallas._scan_kernel.
    """
    plane = plane_ref[:]                       # int32[TB, WP] bit pattern
    tb = plane.shape[0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (tb, wp), 1)
    ok = jnp.ones((tb, 1), dtype=jnp.bool_)
    for j in range(nprobes):
        sel = lane == idx_ref[:, j:j + 1]
        word = jnp.sum(jnp.where(sel, plane, 0), axis=1, keepdims=True)
        # arithmetic >> then &1 extracts the bit regardless of sign
        bit = (word >> shift_ref[:, j:j + 1]) & 1
        ok = jnp.logical_and(ok, bit > 0)
    keep = jnp.logical_or(ok, nw_ref[:, :] == 0)
    out_ref[:, :] = keep.astype(jnp.int8)


@partial(jax.jit, static_argnames=("interpret",))
def plane_keep_pallas(plane, idx, shift, nwords, interpret: bool = False):
    """Pallas drop-in for the jnp probe on aligned shapes -> bool[B]."""
    b, wp = plane.shape
    assert _pallas_ok(plane.shape, idx.shape), (plane.shape, idx.shape)
    nprobes = idx.shape[1]
    g = b // PROBE_TILE_B
    # uint32 planes ride as int32 bit patterns (Mosaic int32 lanes)
    plane_i = jax.lax.bitcast_convert_type(plane, jnp.int32)
    pad = PROBE_LANE - nprobes
    if pad:
        idx = jnp.pad(idx, ((0, 0), (0, pad)))
        shift = jnp.pad(shift, ((0, 0), (0, pad)))
    nw_col = nwords.reshape(b, 1).astype(jnp.int32)
    vmem = None if interpret else _VMEM

    def spec(block, index_map):
        if vmem is None:
            return pl.BlockSpec(block, index_map)
        return pl.BlockSpec(block, index_map, memory_space=vmem)

    kernel = partial(_probe_kernel, nprobes=nprobes, wp=wp)
    out = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            spec((PROBE_TILE_B, wp), lambda i: (i, 0)),
            spec((PROBE_TILE_B, PROBE_LANE), lambda i: (i, 0)),
            spec((PROBE_TILE_B, PROBE_LANE), lambda i: (i, 0)),
            spec((PROBE_TILE_B, 1), lambda i: (i, 0)),
        ],
        out_specs=spec((PROBE_TILE_B, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 1), jnp.int8),
        interpret=interpret,
    )(plane_i, idx.astype(jnp.int32), shift.astype(jnp.int32), nw_col)
    return out.reshape(b).astype(jnp.bool_)


# ---------------- device staging helpers ----------------

@dataclass
class StagedBloomPlane:
    """One part column's bloom plane resident in HBM (replicated on a
    mesh: every shard probes the full block axis)."""
    plane: object                  # jax uint32[Bp, WP]
    nwords: object                 # jax int32[Bp]; 0 = always keep
    bp: int                        # padded block count
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


@dataclass
class StagedBlockIds:
    """Layout-coordinate block id per row: the gather bridge from a
    bool[B] keep-mask to a row bitmap, staged once per part."""
    ids: object                    # jax int32[RLp], row-aligned
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


def stage_bloom_plane(part, field: str, put) -> StagedBloomPlane | None:
    """Upload the part column's packed plane (padded to device tiles);
    None when the column has no plane (no blooms / oversized)."""
    from ..storage.filterbank import filter_bank
    plb = filter_bank(part).plane(part, field)
    if plb is None:
        return None
    plane, nw = pad_plane(plb.plane, plb.nwords)
    return StagedBloomPlane(plane=put(plane), nwords=put(nw),
                            bp=plane.shape[0],
                            nbytes=plane.nbytes + nw.nbytes)


def stage_block_ids(part, layout, put) -> StagedBlockIds:
    bid = np.zeros(layout.nrows_padded, dtype=np.int32)
    for bi in range(part.num_blocks):
        s = layout.starts[bi]
        bid[s:s + part.block_rows(bi)] = bi
    return StagedBlockIds(ids=put(bid), nbytes=bid.nbytes)

def pad_plane(plane: np.ndarray, nwords: np.ndarray
              ) -> tuple[np.ndarray, np.ndarray]:
    """Pad a host plane to the device layout: block axis to a
    PROBE_TILE_B multiple, lanes to a PROBE_LANE multiple.  Pad blocks
    carry nwords=0 (always-keep) and are never gathered by a real row;
    padding also buckets jit signatures so part-shape churn doesn't
    recompile the fused program per part."""
    b, wp = plane.shape
    bp = ((b + PROBE_TILE_B - 1) // PROBE_TILE_B) * PROBE_TILE_B
    wpp = max(PROBE_LANE,
              ((wp + PROBE_LANE - 1) // PROBE_LANE) * PROBE_LANE)
    if bp == b and wpp == wp:
        return plane, nwords
    out = np.zeros((bp, wpp), dtype=np.uint32)
    out[:b, :wp] = plane
    nw = np.zeros(bp, dtype=np.int32)
    nw[:b] = nwords
    return out, nw


def pad_probe_args(idx: np.ndarray, shift: np.ndarray,
                   bp: int) -> tuple[np.ndarray, np.ndarray]:
    """Pad per-block (idx, shift) to the padded block count."""
    b = idx.shape[0]
    if bp == b:
        return idx, shift
    out_i = np.zeros((bp, idx.shape[1]), dtype=np.int32)
    out_s = np.zeros((bp, idx.shape[1]), dtype=np.int32)
    out_i[:b] = idx
    out_s[:b] = shift
    return out_i, out_s


# ---------------- split-block (v2) probe ----------------
# Sealed-part filter index (storage/filterindex): every token's 6
# probe bits live in ONE 256-bit block, so the probe is a single
# contiguous 8-lane gather per (block, token) + an AND-compare against
# a per-token mask — no scattered lane selects.  Layout contract:
#   plane  uint32[B, LP]   per-block sb filters, 0-padded
#   sbidx  int32[B, T]     lane base of each token's selected block
#                          (sb block index * 8; 0 when nsb==0)
#   mask   uint32[T, 8]    the token's 256-bit probe mask
#   nsb    int32[B]        0 => block has no filter => always keep
# returns bool[B]: True where the block may contain ALL probed tokens.

SB_PROBE_LANES = 8


def probe_np_sb(plane: np.ndarray, sbidx: np.ndarray, mask: np.ndarray,
                nsb: np.ndarray) -> np.ndarray:
    """Vectorized host probe of the split-block layout; bit-identical
    to sbbloom.sb_contains_all per block (tests/test_filterindex.py)."""
    b, t = sbidx.shape
    if t == 0:
        return np.ones(b, dtype=bool)
    lane = (sbidx[:, :, None]
            + np.arange(SB_PROBE_LANES, dtype=np.int32)) \
        .reshape(b, t * SB_PROBE_LANES)
    words = np.take_along_axis(plane, lane, axis=1) \
        .reshape(b, t, SB_PROBE_LANES)
    ok = ((words & mask[None, :, :]) == mask[None, :, :]).all(axis=2)
    return ok.all(axis=1) | (nsb == 0)


def plane_keep_sb(plane, sbidx, mask, nsb):
    """jnp split-block keep-mask; traceable inside the fused dispatch
    (the `bloom_sb` program node in tpu/fused.py)."""
    b, t = sbidx.shape
    lane = (sbidx[:, :, None]
            + jnp.arange(SB_PROBE_LANES, dtype=jnp.int32)) \
        .reshape(b, t * SB_PROBE_LANES)
    words = jnp.take_along_axis(plane, lane, axis=1) \
        .reshape(b, t, SB_PROBE_LANES)
    ok = jnp.all((words & mask[None, :, :]) == mask[None, :, :], axis=2)
    return jnp.all(ok, axis=1) | (nsb == 0)


@jax.jit
def sb_plane_probe(plane, sbidx, mask, nsb):
    """Standalone jitted sb probe -> bool[B] (bench/parity entry)."""
    return plane_keep_sb(plane, sbidx, mask, nsb)


@dataclass
class StagedSBPlane:
    """One part column's split-block plane resident in HBM."""
    plane: object                  # jax uint32[Bp, LPp]
    nsb: object                    # jax int32[Bp]; 0 = always keep
    bp: int                        # padded block count
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


def stage_sb_plane(part, field: str, put) -> StagedSBPlane | None:
    """Upload the sealed part's packed split-block plane; None when the
    part has no v2 sidecar (or the column no sb filters) — the caller
    falls back to the classic plane staging."""
    from ..storage.filterindex import sb_plane_for_staging
    got = sb_plane_for_staging(part, field)
    if got is None:
        return None
    plane, nsb = pad_sb_plane(*got)
    return StagedSBPlane(plane=put(plane), nsb=put(nsb),
                         bp=plane.shape[0],
                         nbytes=plane.nbytes + nsb.nbytes)


def pad_sb_plane(plane: np.ndarray, nsb: np.ndarray
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Pad to device tiles exactly like pad_plane: block axis to a
    PROBE_TILE_B multiple, lanes to a PROBE_LANE multiple (bucketing
    jit signatures against part-shape churn).  Pad blocks carry nsb=0
    (always keep) and all-zero lanes (safe to gather)."""
    b, lp = plane.shape
    bp = ((b + PROBE_TILE_B - 1) // PROBE_TILE_B) * PROBE_TILE_B
    lpp = max(PROBE_LANE,
              ((lp + PROBE_LANE - 1) // PROBE_LANE) * PROBE_LANE)
    if bp == b and lpp == lp:
        return plane, np.ascontiguousarray(nsb, dtype=np.int32)
    out = np.zeros((bp, lpp), dtype=np.uint32)
    out[:b, :lp] = plane
    ns = np.zeros(bp, dtype=np.int32)
    ns[:b] = nsb
    return out, ns


def pad_sb_idx(sbidx: np.ndarray, bp: int) -> np.ndarray:
    """Pad per-block sb lane bases to the padded block count."""
    b = sbidx.shape[0]
    if bp == b:
        return sbidx
    out = np.zeros((bp, sbidx.shape[1]), dtype=np.int32)
    out[:b] = sbidx
    return out
