"""TPU block runner: executes the filter tree over staged blocks.

This is the pluggable `blockSearch` replacement from the north star: the
searcher hands it (filter, BlockSearch) and gets back a bitmap identical to
the CPU path's.  Device-capable leaves (phrase/prefix/exact/exact-prefix
matches, sequences, contains_*, regex literal prefilters on string-arena
columns) run as arena-scan kernels; everything else (numeric compares, dict
columns, time filters, cross-field compares) stays on the host where numpy is
already bandwidth-bound.  Bitmaps combine host-side; bloom probes stay on the
host kill-path so most blocks never touch HBM.

Regex: device runs the mandatory-literal substring prefilter, then the host
re.search verifies only surviving rows (mirrors the reference's bloom+scan
split — filter_regexp.go:44-51); a pure-literal pattern skips verification.
"""

from __future__ import annotations

import numpy as np

from ..engine.block_search import BlockSearch, new_bitmap
from ..logsql import filters as F
from ..storage.values_encoder import VT_STRING
from . import kernels as K
from .layout import StagingCache, stage_string_column


class BlockRunner:
    def __init__(self, max_cache_bytes: int = 4 << 30):
        self.cache = StagingCache(max_cache_bytes)
        self.device_calls = 0
        self.cpu_fallbacks = 0

    # ---- staging ----
    def stage(self, bs: BlockSearch, field: str):
        key = (id(bs.part), bs.block_idx, field)
        got = self.cache.get(key)
        if got is not None:
            return got
        col = bs.column(field)
        if col is None or col.vtype != VT_STRING:
            return None
        staged = stage_string_column(col.arena, col.offsets, col.lengths)
        self.cache.put(key, staged)
        return staged

    # ---- filter evaluation ----
    def apply_filter(self, f, bs: BlockSearch) -> np.ndarray:
        bm = new_bitmap(bs.nrows)
        self._apply(f, bs, bm)
        return bm

    def _apply(self, f, bs: BlockSearch, bm: np.ndarray) -> None:
        if isinstance(f, F.FilterAnd):
            for sub in f.filters:
                if not bm.any():
                    return
                self._apply(sub, bs, bm)
            return
        if isinstance(f, F.FilterOr):
            acc = np.zeros(bs.nrows, dtype=bool)
            for sub in f.filters:
                tmp = bm.copy()
                self._apply(sub, bs, tmp)
                acc |= tmp
                if acc.all():
                    break
            bm &= acc
            return
        if isinstance(f, F.FilterNot):
            tmp = new_bitmap(bs.nrows)
            self._apply(f.inner, bs, tmp)
            bm &= ~tmp
            return
        leaf = self._apply_leaf_device(f, bs)
        if leaf is None:
            self.cpu_fallbacks += 1
            f.apply_to_block(bs, bm)
        else:
            bm &= leaf

    def _scan(self, staged, pattern: bytes, mode: int, starts_tok: bool,
              ends_tok: bool, bs=None, fld=None, pred=None) -> np.ndarray:
        import jax.numpy as jnp
        self.device_calls += 1
        pat = jnp.asarray(np.frombuffer(pattern, dtype=np.uint8))
        out = K.match_scan(staged.rows, staged.lengths, pat,
                           len(pattern), mode, starts_tok, ends_tok)
        bm = np.array(out[:staged.nrows])  # writable host copy
        if staged.overflow.size and bs is not None and pred is not None:
            # rows longer than the staging width were truncated on device;
            # re-evaluate them on the host with the scalar oracle
            vals = bs.values(fld)
            for i in staged.overflow:
                bm[i] = pred(vals[i])
        return bm

    def _apply_leaf_device(self, f, bs: BlockSearch) -> np.ndarray | None:
        """Evaluate one leaf on device; None => caller falls back to CPU."""
        from ..logsql.filters import canonical_field, _bloom_prunes
        from ..logsql.matchers import is_word_char

        if isinstance(f, F.FilterPhrase):
            if not f.phrase or not f.phrase.isascii() or \
                    len(f.phrase) > K.MAX_PATTERN_LEN:
                return None
            fld = canonical_field(f.field)
            if _bloom_prunes(bs, fld, f._tokens()):
                return np.zeros(bs.nrows, dtype=bool)
            staged = self.stage(bs, fld)
            if staged is None:
                return None
            pat = f.phrase.encode("utf-8")
            return self._scan(staged, pat, K.MODE_PHRASE,
                              is_word_char(f.phrase[0]),
                              is_word_char(f.phrase[-1]),
                              bs=bs, fld=fld, pred=f._pred)

        if isinstance(f, F.FilterPrefix):
            if not f.prefix.isascii() or len(f.prefix) > K.MAX_PATTERN_LEN:
                return None
            fld = canonical_field(f.field)
            if _bloom_prunes(bs, fld, f._tokens()):
                return np.zeros(bs.nrows, dtype=bool)
            staged = self.stage(bs, fld)
            if staged is None:
                return None
            if not f.prefix:
                bm = np.asarray(staged.lengths)[:staged.nrows] > 0
                for i in staged.overflow:
                    bm[i] = True  # overflow rows are non-empty
                return bm
            return self._scan(staged, f.prefix.encode("utf-8"),
                              K.MODE_PREFIX, is_word_char(f.prefix[0]),
                              False, bs=bs, fld=fld, pred=f._pred)

        if isinstance(f, F.FilterExact):
            if not f.value or not f.value.isascii() or \
                    len(f.value) > K.MAX_PATTERN_LEN:
                return None
            fld = canonical_field(f.field)
            staged = self.stage(bs, fld)
            if staged is None:
                return None
            return self._scan(staged, f.value.encode("utf-8"),
                              K.MODE_EXACT, False, False,
                              bs=bs, fld=fld, pred=f._pred)

        if isinstance(f, F.FilterExactPrefix):
            if not f.prefix or not f.prefix.isascii() or \
                    len(f.prefix) > K.MAX_PATTERN_LEN:
                return None
            fld = canonical_field(f.field)
            staged = self.stage(bs, fld)
            if staged is None:
                return None
            return self._scan(staged, f.prefix.encode("utf-8"),
                              K.MODE_EXACT_PREFIX, False, False,
                              bs=bs, fld=fld, pred=f._pred)

        if isinstance(f, F.FilterSequence):
            # all phrases must occur; ordering verified on survivors (host)
            if not f.phrases:
                return None
            fld = canonical_field(f.field)
            if any(not p or not p.isascii() or len(p) > K.MAX_PATTERN_LEN
                   for p in f.phrases):
                return None
            if _bloom_prunes(bs, fld, f._tokens()):
                return np.zeros(bs.nrows, dtype=bool)
            staged = self.stage(bs, fld)
            if staged is None:
                return None
            cand = np.ones(staged.nrows, dtype=bool)
            for p in f.phrases:
                cand &= self._scan(staged, p.encode("utf-8"),
                                   K.MODE_SUBSTRING, False, False,
                                   bs=bs, fld=fld,
                                   pred=lambda v, p=p: p in v)
                if not cand.any():
                    return cand[:bs.nrows]
            if len(f.phrases) == 1:
                return cand[:bs.nrows]
            return self._verify_rows(bs, fld, cand, f._pred)

        if isinstance(f, F.FilterContainsAll):
            if f.subquery is not None and not f.values:
                return None
            return self._contains(bs, f, require_all=True)

        if isinstance(f, F.FilterContainsAny):
            if f.subquery is not None and not f.values:
                return None
            return self._contains(bs, f, require_all=False)

        if isinstance(f, F.FilterRegexp):
            return self._regexp(bs, f)

        return None

    def _contains(self, bs, f, require_all: bool) -> np.ndarray | None:
        from ..logsql.filters import canonical_field
        from ..logsql.matchers import is_word_char, match_phrase
        fld = canonical_field(f.field)
        phrases = f.values
        if not phrases:
            return None
        if any(not p.isascii() or len(p) > K.MAX_PATTERN_LEN
               for p in phrases):
            return None
        staged = self.stage(bs, fld)
        if staged is None:
            return None
        if require_all:
            out = np.ones(staged.nrows, dtype=bool)
        else:
            out = np.zeros(staged.nrows, dtype=bool)
        for p in phrases:
            if not p:
                # empty phrase matches only the empty string
                hit = np.asarray(staged.lengths)[:staged.nrows] == 0
            else:
                hit = self._scan(staged, p.encode("utf-8"), K.MODE_PHRASE,
                                 is_word_char(p[0]), is_word_char(p[-1]),
                                 bs=bs, fld=fld,
                                 pred=lambda v, p=p: match_phrase(v, p))
            if require_all:
                out &= hit
                if not out.any():
                    break
            else:
                out |= hit
                if out.all():
                    break
        return out[:bs.nrows]

    def _regexp(self, bs, f) -> np.ndarray | None:
        from ..logsql.filters import canonical_field
        fld = canonical_field(f.field)
        staged = self.stage(bs, fld)
        if staged is None:
            return None
        # literal prefilter on device
        cand = np.ones(staged.nrows, dtype=bool)
        literals = [t for t in getattr(f, "_bloom_tokens", [])
                    if t.isascii() and 0 < len(t) <= K.MAX_PATTERN_LEN]
        for lit in literals:
            cand &= self._scan(staged, lit.encode("utf-8"),
                               K.MODE_SUBSTRING, False, False,
                               bs=bs, fld=fld,
                               pred=lambda v, lit=lit: lit in v)
            if not cand.any():
                return cand[:bs.nrows]
        # pure-literal regex needs no verification
        import re
        if re.escape(f.pattern) == f.pattern and len(literals) == 1 and \
                literals[0] == f.pattern:
            return cand[:bs.nrows]
        return self._verify_rows(bs, fld, cand, f._pred)

    def _verify_rows(self, bs, fld: str, cand: np.ndarray, pred
                     ) -> np.ndarray:
        """Host verification of device-surviving rows only."""
        out = cand[:bs.nrows].copy()
        if not out.any():
            return out
        vals = bs.values(fld)
        for i in np.nonzero(out)[0]:
            if not pred(vals[i]):
                out[i] = False
        return out
