"""Async multi-part device pipeline: in-flight dispatch window +
small-part packing.

PERF.md's hardware profile proved the device plane is transfer/RTT-bound
(~65 ms per completed dispatch under the tunnel; fused kernel time
~11 ms), which is why round 3 collapsed each part to ONE fused dispatch —
but the part walk itself stayed serial: every part's dispatch blocked on
the previous part's host materialization, so a query over P parts paid P
serial round trips even though the dispatches are independent.  This
module is the per-part execution driver that removes that serialization
(engine/searcher._scan_parts delegates here for batch runners):

1. **In-flight dispatch window** — fused dispatches return asynchronous
   jax arrays; nothing forces them to the host at submit time.  Up to
   ``VL_INFLIGHT`` (default 4) units keep their dispatches outstanding;
   completed results are harvested strictly in submission order, so the
   downstream block order (and the stats absorb order) is bit-identical
   to the serial walk.  Prefetch staging (BatchRunner.submit_prefetch)
   follows the same depth, so the host decode/upload of part N+k
   overlaps the device scans of parts N..N+k-1 instead of the old
   depth-1 double buffer.

2. **Small-part packing** — LSM partitions are full of small fresh
   parts, and each one still costs a full dispatch RTT.  Consecutive
   parts whose row counts share a padded-size bucket (kernels.pad_bucket
   — the same bucketing the staging layer uses to keep jit caches small)
   are presented to the fused planner as ONE part-like value
   (PackedPart: members' blocks concatenated, in member order) and
   evaluated in ONE fused super-dispatch.  Row bitmaps split back per
   member on the host; stats partials carry a per-part segment axis
   (stats_device.with_segment_axis) and are segment-reduced back to
   per-member partials, so the stats processor sees exactly the per-part
   absorb granularity of the serial path.  P small parts cost
   ceil(P / VL_PACK_PARTS) dispatches instead of P.

Cancellation (`QueryCancelled`) and deadline expiry
(`QueryTimeoutError`) drain the window without writing partial blocks
downstream: in-flight handles are simply dropped (jax buffers are
released when the device finishes; staging entries are complete,
keyed, budget-accounted values, so the StagingCache stays balanced).

Kill-switches: VL_INFLIGHT=1 reduces to the serial submit-then-harvest
walk; VL_PACK_PARTS=1 disables packing; VL_FUSED_FILTER=0 restores the
per-leaf row-query path inside each unit (tpu/fused.py).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass

import numpy as np
from .. import config

from ..obs import activity, events, hist, tracing
from .. import sched
from .kernels import pad_bucket

# adaptive pack-size clamps: parts below the floor always pack (the
# bench measures 1.4-4x wins for flush-sized parts even on a ~0.1ms
# local backend); parts above the ceiling never do
_PACK_ROWS_FLOOR = 16384
_PACK_ROWS_CEIL = 1 << 20


_AUTO_DEPTH_MIN = 2
_AUTO_DEPTH_MAX = 16
_AUTO_DEPTH_DEFAULT = 4


def inflight_auto() -> bool:
    return (config.env("VL_INFLIGHT") or "").strip().lower() == "auto"


def inflight_depth(runner=None, probe: bool = True) -> int:
    """VL_INFLIGHT: max units with outstanding dispatches (>=1).

    ``VL_INFLIGHT=auto`` derives the depth from the cost model's
    calibration EWMAs (vl_tpu_cost_rtt_seconds and the per-unit emit
    EWMA, both /metrics gauges): the window hides one dispatch RTT
    behind wait-free host emit work, so the device never idles once
    ``depth * emit_per_unit >= rtt`` — depth = ceil(rtt / emit_ewma),
    clamped to [2, 16].  An explicit integer always wins; cold
    calibration falls back to the default.

    probe=False never issues the lazy RTT calibration dispatch — the
    EXPLAIN pricing pass (obs/explain.py) prices with the SAME depth
    derivation but must stay zero-dispatch (like pack_rows_cap)."""
    v = config.env("VL_INFLIGHT")
    if v.strip().lower() == "auto":
        return _auto_depth(runner, probe)
    try:
        return max(1, int(v))
    except ValueError:
        return _AUTO_DEPTH_DEFAULT


def _auto_depth(runner, probe: bool = True) -> int:
    if runner is None:
        return _AUTO_DEPTH_DEFAULT
    host = runner.cost.emit_ewma
    if not host:
        # calibration cold: no harvested unit observed yet (first query
        # of this runner) — the default window, like VL_INFLIGHT unset
        return _AUTO_DEPTH_DEFAULT
    # we're on the query path already, so the lazy RTT probe is fair
    # game here (unlike /metrics scrapes — see BatchRunner.stats);
    # probe=False callers price with the unprobed calibration instead
    rtt = runner.cost.measured_rtt() if probe else runner.cost.rtt
    if not rtt:
        return _AUTO_DEPTH_DEFAULT
    import math
    return min(_AUTO_DEPTH_MAX,
               max(_AUTO_DEPTH_MIN, math.ceil(rtt / host)))


def pack_limit() -> int:
    """VL_PACK_PARTS: max parts per super-dispatch (<=1 disables)."""
    return max(1, config.env_int("VL_PACK_PARTS"))


def pack_topk_k() -> int:
    """VL_PACK_TOPK_K: largest `sort ... limit` k eligible for packed
    sort-topk super-dispatches (0 disables topk packing).  The packed
    dispatch k-selects per member over the segment slot grid, whose
    slot axis must hold at least k entries per member — a huge k
    inflates every member's padded slots, so past this cap the
    per-part dispatches win."""
    return max(0, config.env_int("VL_PACK_TOPK_K"))


def cross_partition_enabled() -> bool:
    """VL_CROSS_PARTITION=0 restores the per-partition dispatch window
    (the pre-PR-15 shape: the window drains at every day boundary)."""
    return config.env_flag("VL_CROSS_PARTITION")


def pack_policy(runner, sort_spec, probe: bool = True):
    """(packable, pack_max, rows_cap) — THE pack-eligibility rule, in
    one place for the execution planner (_unit_stream) and the EXPLAIN
    walk (obs/explain.py), so the displayed pack membership can never
    diverge from the dispatched one.  Sort-topk shapes pack when their
    k fits the VL_PACK_TOPK_K cap (the packed dispatch k-selects per
    member); stats/row shapes pack as before."""
    pack_max = pack_limit()
    packable = pack_max > 1 and (
        sort_spec is None or 0 < sort_spec.k <= pack_topk_k())
    rows_cap = pack_rows_cap(runner, probe) if packable else 0
    return packable, pack_max, rows_cap


def pack_rows_cap(runner, probe: bool = True) -> int:
    """Parts above this many rows never pack.

    Packing trades per-dispatch overhead for a bigger fused program, so
    it pays while a part's whole-part scan time is below the dispatch
    round trip — which the cost model MEASURES (65 ms through the axon
    tunnel, ~0.1 ms on a local backend).  The cap scales with rtt *
    device_rate (at ~128 scanned bytes/row), so big parts keep their own
    dispatches on fast-RTT backends (measured 0.5-0.7x regressions when
    packing 128k-row parts on jax-CPU) while the tunnel packs far larger
    parts.  VL_PACK_MAX_ROWS overrides the adaptive cap outright.

    probe=False never issues the lazy RTT calibration dispatch: the
    EXPLAIN pricing pass (obs/explain.py) plans with the floor until a
    real query has measured the round trip — `explain=1` must stay
    zero-dispatch."""
    v = config.env("VL_PACK_MAX_ROWS")
    if v:
        try:
            return max(1, int(v))
        except ValueError:
            pass
    rtt = runner.cost.measured_rtt() if probe else runner.cost.rtt
    if rtt is None:
        return _PACK_ROWS_FLOOR
    cap = rtt * runner.cost._dev_rate() / 128
    return int(min(max(cap, _PACK_ROWS_FLOOR), _PACK_ROWS_CEIL))


# ---------------- packed parts ----------------

class PackedPart:
    """Several small immutable parts presented as ONE part-like value.

    Blocks are the members' blocks concatenated in member order with
    re-based indices, so every staging/planning routine that walks
    ``range(part.num_blocks)`` (stage_layout_column, part_stats_layout,
    stage_numeric/dict/buckets, the bloom filterbank, the fused planner)
    works unchanged over the pack;  ``segment_of_block`` maps a pack
    block back to its member ordinal — the segment id of the
    super-dispatch.  The uid is the member-uid tuple, so StagingCache
    entries for a pack are stable across queries exactly like per-part
    staging (parts are immutable; a merge mints fresh member uids and
    therefore a fresh pack identity)."""

    def __init__(self, members: list):
        self.members = list(members)
        self.uid = ("pack",) + tuple(p.uid for p in self.members)
        self._offsets = []
        self._map = []
        for mi, p in enumerate(self.members):
            self._offsets.append(len(self._map))
            for bi in range(p.num_blocks):
                self._map.append((mi, p, bi))
        self.num_rows = sum(p.num_rows for p in self.members)
        self.min_ts = min(p.min_ts for p in self.members)
        self.max_ts = max(p.max_ts for p in self.members)

    @property
    def num_blocks(self) -> int:
        return len(self._map)

    @property
    def num_segments(self) -> int:
        return len(self.members)

    def block_offset(self, mi: int) -> int:
        """Pack block index of member mi's block 0."""
        return self._offsets[mi]

    def segment_of_block(self, bi: int) -> int:
        return self._map[bi][0]

    # -- block-level delegation (Part / InmemoryPart uniform API) --
    def block_rows(self, bi: int) -> int:
        _mi, p, b = self._map[bi]
        return p.block_rows(b)

    def block_min_ts(self, bi: int) -> int:
        _mi, p, b = self._map[bi]
        return p.block_min_ts(b)

    def block_stream_id(self, bi: int):
        _mi, p, b = self._map[bi]
        return p.block_stream_id(b)

    def block_tags(self, bi: int) -> str:
        _mi, p, b = self._map[bi]
        return p.block_tags(b)

    def block_consts(self, bi: int):
        _mi, p, b = self._map[bi]
        return p.block_consts(b)

    def block_column_meta(self, bi: int, name: str):
        _mi, p, b = self._map[bi]
        return p.block_column_meta(b, name)

    def block_column(self, bi: int, name: str):
        _mi, p, b = self._map[bi]
        return p.block_column(b, name)

    def block_column_bloom(self, bi: int, name: str):
        _mi, p, b = self._map[bi]
        return p.block_column_bloom(b, name)

    def block_timestamps(self, bi: int):
        _mi, p, b = self._map[bi]
        return p.block_timestamps(b)


# pack instances strongly reference their members (incl. in-RAM
# InmemoryPart blocks), so the cache is a SMALL hard-capped LRU — it
# only needs to keep the hot packs' filter banks warm across queries;
# the staged tensors live in the byte-budgeted StagingCache keyed by
# the (deterministic) pack uid and survive regardless of this cache
_PACK_CACHE_MAX = 32


def _get_pack(runner, members: list) -> PackedPart:
    key = tuple(p.uid for p in members)
    with runner._pack_mu:
        got = runner._packs.get(key)
        if got is None:
            got = runner._packs[key] = PackedPart(members)
        runner._packs.move_to_end(key)
        while len(runner._packs) > _PACK_CACHE_MAX:
            runner._packs.popitem(last=False)
        return got


# ---------------- units and harvested results ----------------

@dataclass
class _Member:
    """One member part's share of a harvested unit."""
    part: object
    blocks: list                   # [(orig block idx, BlockSearch)]
    bms: dict                      # orig block idx -> bool bitmap
    handled: set                   # orig idxs fully covered by partials
    partials: list


@dataclass
class _Unit:
    part: object                   # Part or PackedPart (dispatch target)
    bss: dict                      # dispatch-coord block idx -> BlockSearch
    members: list                  # [(member part, [(orig_bi, bs), ...])]
    pack: bool = False


class _UnitReady:
    """Already-materialized unit result (host paths, constant trees)."""

    def __init__(self, members: list):
        self._members = members

    def harvest(self, sync) -> list:
        return self._members


class _CacheHit:
    """Planning marker for a part whose result came from the per-part
    result cache (engine/standing/resultcache.py).  num_rows sits above
    every pack cap so iter_pack_groups keeps the hit in its own
    singleton group — a cached part must never join a pack dispatch."""

    __slots__ = ("part", "entry")
    num_rows = 1 << 62

    def __init__(self, part, entry):
        self.part = part
        self.entry = entry


class _CachedUnit:
    """A unit satisfied entirely from the result cache: no prefetch, no
    dispatch, no scheduler slot — it rides the window as an
    already-materialized member so harvest stays in submission order
    (downstream block order and stats absorb order bit-identical to the
    uncached walk)."""

    pack = False
    cached = True

    def __init__(self, part, member: "_Member"):
        self.part = part
        self.bss: dict = {}
        self.members = [(part, member.blocks)]
        self.ready = [member]


class _SingleRows:
    def __init__(self, unit: _Unit, pending):
        self.unit = unit
        self.pending = pending

    def harvest(self, sync) -> list:
        bms = self.pending.harvest(sync)
        part, blocks = self.unit.members[0]
        return [_Member(part, blocks, bms, set(), [])]


class _SingleStats:
    def __init__(self, unit: _Unit, pending):
        self.unit = unit
        self.pending = pending

    def harvest(self, sync) -> list:
        bms, handled, partials = self.pending.harvest(sync)
        part, blocks = self.unit.members[0]
        return [_Member(part, blocks, bms, handled, partials)]


class _PackRows:
    def __init__(self, unit: _Unit, pending):
        self.unit = unit
        self.pending = pending

    def harvest(self, sync) -> list:
        packbms = self.pending.harvest(sync)   # keyed by pack block idx
        out = []
        for mi, (p, blocks) in enumerate(self.unit.members):
            off = self.unit.part.block_offset(mi)
            bms = {bi: packbms[off + bi] for bi, _bs in blocks}
            out.append(_Member(p, blocks, bms, set(), []))
        return out


class _PackStats:
    """Harvest of a packed stats super-dispatch: partials come back with
    a leading ("s", member_idx) key component (the segment axis) and are
    segment-reduced to per-member partial lists, absorbed in member
    order — exactly the serial per-part granularity."""

    def __init__(self, unit: _Unit, pending):
        self.unit = unit
        self.pending = pending

    def harvest(self, sync) -> list:
        _bms, _handled, partials = self.pending.harvest(sync)
        per_seg: dict[int, list] = {}
        for kp, cnt, fs, uniq, qv in partials:
            seg = int(kp[0][1])     # leading component IS the segment
            per_seg.setdefault(seg, []).append((kp[1:], cnt, fs, uniq,
                                                qv))
        out = []
        for mi, (p, blocks) in enumerate(self.unit.members):
            out.append(_Member(p, blocks, {}, {bi for bi, _bs in blocks},
                               per_seg.get(mi, [])))
        return out


# ---------------- planning ----------------

def pack_bucket(part) -> int:
    """The padded-row bucket packing groups on (shared with the EXPLAIN
    planner so the displayed pack membership is the dispatched one)."""
    return pad_bucket(max(part.num_rows, 1), minimum=1024)


# widest time range one pack may cover: the fused ts staging carries
# ns offsets from the pack minimum as (hi >> 16) int32 planes, exact
# only below 2**47 ns (~39h).  Same-day packs never come close; packs
# spanning a partition boundary (cross-partition window) must split
# when the data really spans further.
PACK_TS_SPAN_MAX = 1 << 47


def iter_pack_groups(items, packable: bool, pack_max: int,
                     rows_cap: int):
    """Fold an iterable of pruned part items into dispatch-unit groups
    — THE pack-membership rules, in one place: consecutive small parts
    (<= rows_cap rows) sharing a padded-row bucket group up to
    pack_max, provided the group's combined time range stays inside
    the staging-exact PACK_TS_SPAN_MAX window; everything else is its
    own unit.  Items are tuples whose first element is the part (the
    execution stream carries (part, bis, ctx); EXPLAIN carries
    (part, bis)) — passed through untouched.  Lazy: pulls from `items`
    only as groups are consumed, so the execution stream's early exits
    (limit, deadline) stop the header walk exactly where the serial
    loop would, and the EXPLAIN pricing pass (obs/explain.py) walks
    the identical grouping without dispatching."""
    group: list = []        # packable run sharing one row bucket
    gmin = gmax = 0         # group's combined time range (ns)
    for it in items:
        part = it[0]
        small = packable and part.num_rows <= rows_cap
        if not small:
            if group:
                yield group
                group = []
            yield [it]
            continue
        if group and (
                pack_bucket(group[0][0]) != pack_bucket(part)
                or max(gmax, part.max_ts) - min(gmin, part.min_ts)
                >= PACK_TS_SPAN_MAX):
            yield group
            group = []
        if group:
            gmin = min(gmin, part.min_ts)
            gmax = max(gmax, part.max_ts)
        else:
            gmin, gmax = part.min_ts, part.max_ts
        group.append(it)
        if len(group) >= pack_max:
            yield group
            group = []
    if group:
        yield group


def _unit_stream(runner, items, head, stats_spec, sort_spec,
                 token_leaves, check_deadline, qcache=None):
    """Lazily fold the pruned part stream into dispatch units, in part
    order.  `items` yields (part, cand_fn, ctx) — the cross-partition
    window feeds parts from EVERY selected partition through one
    stream (each carrying its partition's SearchContext), so packs may
    span a day boundary when the members share a pad bucket.

    Consecutive parts pack when packing is on, the query shape supports
    a pack dispatch (pack_policy — sort-topk packs under the
    VL_PACK_TOPK_K cap via the per-member k-selection), every member is
    small (pack_rows_cap) and the members share a padded-row bucket
    (the shared width/nrows bucketing that keeps the jit cache small
    keeps pack shapes small too).  Lazy on purpose: a `limit`-style
    early exit (head.is_done) or a deadline must stop the header walk
    exactly like the serial loop did — the consumer only pulls the
    window's lookahead ahead of execution."""
    from ..engine.block_search import BlockSearch
    from ..engine.searcher import QueryCancelled
    from ..storage.filterbank import (maplet_prune_candidates,
                                      part_aggregate_prunes)
    packable, pack_max, rows_cap = pack_policy(runner, sort_spec)

    def make_unit(group):
        if len(group) == 1 and isinstance(group[0][0], _CacheHit):
            hit, bis, ctx = group[0]
            e = hit.entry
            if e.kind == "stats":
                member = _Member(hit.part, [], {}, set(),
                                 qcache.entry_partials(e))
            else:
                blocks = []
                for bi in bis:
                    bs = BlockSearch(hit.part, bi)
                    bs.ctx = ctx
                    blocks.append((bi, bs))
                member = _Member(hit.part, blocks, qcache.entry_bms(e),
                                 set(), [])
            return _CachedUnit(hit.part, member)
        if len(group) == 1:
            p, bis, ctx = group[0]
            bss = {}
            blocks = []
            for bi in bis:
                bs = BlockSearch(p, bi)
                bs.ctx = ctx
                bss[bi] = bs
                blocks.append((bi, bs))
            return _Unit(p, bss, [(p, blocks)])
        pack = _get_pack(runner, [g[0] for g in group])
        if len({id(g[2].partition) for g in group}) > 1:
            runner._bump("cross_partition_packs")
        bss = {}
        members = []
        for mi, (p, bis, ctx) in enumerate(group):
            off = pack.block_offset(mi)
            blocks = []
            for bi in bis:
                bs = BlockSearch(p, bi)
                bs.ctx = ctx
                bss[off + bi] = bs
                blocks.append((bi, bs))
            members.append((p, blocks))
        return _Unit(pack, bss, members, pack=True)

    act = activity.current_activity()

    def pruned():
        for part, cand_fn, ctx in items:
            check_deadline()
            if head.is_done():
                raise QueryCancelled()
            bis = cand_fn(part)
            if not bis:
                continue
            if token_leaves and part_aggregate_prunes(
                    part, token_leaves,
                    build=len(bis) * 4 >= part.num_blocks):
                runner._bump("agg_pruned_parts")
                continue
            if token_leaves:
                # sealed v2 parts: exact maplet block pruning before
                # staging/packing — the dropped blocks are the ones
                # the in-dispatch kill would have zeroed anyway
                pruned_bis = maplet_prune_candidates(part, token_leaves,
                                                     bis)
                if len(pruned_bis) != len(bis):
                    runner._bump("maplet_pruned_blocks",
                                 len(bis) - len(pruned_bis))
                    bis = pruned_bis
                if not bis:
                    continue
            # registry progress at part granularity (the planning pull
            # IS the prune stage, so these land as the walk advances)
            activity.note_part_scanned(act, part, bis)
            if qcache is not None:
                e = qcache.probe(part, bis)
                if e is not None:
                    # result cached from an earlier identical query:
                    # the part never enters the dispatch stream
                    yield _CacheHit(part, e), bis, ctx
                    continue
            yield part, bis, ctx

    for group in iter_pack_groups(pruned(), packable, pack_max,
                                  rows_cap):
        yield make_unit(group)


# ---------------- submission ----------------

def _submit(runner, f, unit: _Unit, stats_spec, sort_spec, spec_seg):
    if stats_spec is not None:
        if unit.pack:
            return _submit_pack_stats(runner, f, unit, stats_spec,
                                      spec_seg)
        return _SingleStats(unit, runner.run_part_stats_submit(
            f, unit.part, unit.bss, stats_spec))
    if sort_spec is not None:
        if unit.pack:
            return _submit_pack_topk(runner, f, unit, sort_spec)
        pending = runner.run_part_topk_submit(f, unit.part, unit.bss,
                                              sort_spec)
        if pending is not None:
            # async: the dispatch stays outstanding in the window like
            # every other shape (harvest -> block_idx -> bitmap)
            return _SingleRows(unit, pending)
        part, blocks = unit.members[0]
        bms = runner.run_part(f, part, unit.bss)
        return _UnitReady([_Member(part, blocks, bms, set(), [])])
    if unit.pack:
        return _submit_pack_rows(runner, f, unit)
    return _SingleRows(unit, runner.run_part_submit(f, unit.part,
                                                    unit.bss))


def _count_pack(runner, unit: _Unit, pending) -> None:
    """Count a packed SUPER-DISPATCH — constant-tree packs come back as
    _Ready without touching the device, and must not inflate the
    dispatch-reduction numbers the bench/PERF cost model reports."""
    from .fused import _Ready
    if isinstance(pending, _Ready):
        return
    runner._bump("packed_dispatches")
    runner._bump("packed_parts", len(unit.members))


def _host_members(runner, f, unit: _Unit) -> list:
    out = []
    for p, blocks in unit.members:
        mbss = dict(blocks)
        out.append(_Member(p, blocks, runner._host_eval_part(f, mbss),
                           set(), []))
    return out


def _submit_pack_rows(runner, f, unit: _Unit):
    if runner._gate_host(f, unit.part, unit.bss):
        runner._bump("gated_host_parts", len(unit.members))
        return _UnitReady(_host_members(runner, f, unit))
    pending = None
    if runner.fused_enabled:
        from .fused import fused_filter_submit
        pending = fused_filter_submit(runner, f, unit.part, unit.bss)
    if pending is not None:
        _count_pack(runner, unit, pending)
        return _PackRows(unit, pending)
    # the planner declined the pack: fall back to the serial per-member
    # path (results identical to the unpacked walk)
    out = []
    for p, blocks in unit.members:
        bms = runner.run_part_submit(f, p, dict(blocks)).harvest()
        out.append(_Member(p, blocks, bms, set(), []))
    return _UnitReady(out)


def _submit_pack_topk(runner, f, unit: _Unit, sort_spec):
    """Packed sort-topk super-dispatch: ONE fused dispatch k-selects
    per member over the concatenated pack (fused._topk_dispatch's
    segment unroll), so every member's harvested candidate set — and
    therefore the host sort processor's input, order and ties included
    — is bit-identical to its own single-part dispatch."""
    cand_rows = sum(bs.nrows for bs in unit.bss.values())
    if runner._gate_host(f, unit.part, unit.bss,
                         stats_rows=max(cand_rows, 1)):
        runner._bump("gated_host_parts", len(unit.members))
        return _UnitReady(_host_members(runner, f, unit))
    pending = None
    if runner.fused_enabled:
        from .fused import fused_topk_submit
        pending = fused_topk_submit(runner, f, unit.part, unit.bss,
                                    sort_spec)
    if pending is not None:
        _count_pack(runner, unit, pending)
        from .fused import _Ready
        if not isinstance(pending, _Ready):
            runner._bump("packed_topk_dispatches")
        return _PackRows(unit, pending)
    # decline (non-numeric sort column, unfusable leaf): serial
    # per-member path — results identical to the unpacked walk
    out = []
    for p, blocks in unit.members:
        mbss = dict(blocks)
        bms = runner.run_part_topk(f, p, mbss, sort_spec)
        if bms is None:
            bms = runner.run_part(f, p, mbss)
        out.append(_Member(p, blocks, bms, set(), []))
    return _UnitReady(out)


def _submit_pack_stats(runner, f, unit: _Unit, stats_spec, spec_seg):
    cand_rows = sum(bs.nrows for bs in unit.bss.values())
    if runner._gate_host(f, unit.part, unit.bss,
                         stats_rows=max(cand_rows, 1)):
        runner._bump("gated_host_parts", len(unit.members))
        return _UnitReady(_host_members(runner, f, unit))
    pending = None
    if runner.fused_enabled:
        from .fused import fused_stats_submit
        asm = runner._assemble_axes(unit.part, spec_seg)
        if asm is not None:
            pending = fused_stats_submit(runner, f, unit.part, unit.bss,
                                         spec_seg, asm)
    if pending is not None:
        _count_pack(runner, unit, pending)
        return _PackStats(unit, pending)
    # decline (ineligible column, bucket blowup, unfusable leaf): serial
    # per-member fallback with the ORIGINAL spec
    out = []
    for p, blocks in unit.members:
        bms, handled, partials = runner.run_part_stats(f, p, dict(blocks),
                                                       stats_spec)
        out.append(_Member(p, blocks, bms, handled, partials))
    return _UnitReady(out)


# ---------------- the window driver ----------------

def _make_sync(runner):
    """The window's SINGLE deliberate host-sync point: everything the
    device path downloads during a windowed scan funnels through here,
    so the blocked time is measurable (host_sync_wait_s) and the hot
    path stays statically clean (tools/vlint hotpath checker)."""

    def sync(arr):
        t0 = time.perf_counter()
        # the window's single harvest point — materializing a
        # completed dispatch in submission order IS the pipeline's
        # output step; everything upstream stays async
        # vlint: allow-jax-host-sync(the single deliberate harvest sync; upstream stays async)
        out = np.asarray(arr)
        dt = time.perf_counter() - t0
        runner._bump("host_sync_wait_s", dt)
        hist.HOST_SYNC_WAIT.observe(dt)
        tracing.current_span().add("host_sync_wait_s", dt)
        return out

    return sync


def scan_parts_device(parts, q, head, runner, cand_fn, ctx, needed,
                      deadline, stats_spec, sort_spec,
                      token_leaves, qcache=None) -> None:
    """Drive ONE partition's parts through the async dispatch window
    (the VL_CROSS_PARTITION=0 compatibility shape: the window drains at
    the partition boundary).  The default path is scan_device_stream,
    which engine/searcher feeds with parts from EVERY selected
    partition so the window never drains between days."""
    act = activity.current_activity()
    act.add("parts_total", len(parts))
    scan_device_stream(((p, cand_fn, ctx) for p in parts), q, head,
                       runner, needed, deadline, stats_spec, sort_spec,
                       token_leaves, qcache=qcache)


def scan_device_stream(items, q, head, runner, needed, deadline,
                       stats_spec, sort_spec, token_leaves,
                       qcache=None) -> None:
    """Drive a cross-partition part stream through the async dispatch
    window.

    Replaces the serial device walk of engine/searcher._scan_parts:
    candidate pruning and part-aggregate kills are unchanged; submission
    keeps up to VL_INFLIGHT units' dispatches outstanding; harvest is in
    submission order, so downstream block order and stats absorb
    granularity are identical to the serial path.  `items` yields
    (part, cand_fn, ctx) lazily — partitions resolve their stream
    filters and snapshot their parts only as the planning pull reaches
    them, so parts from partition N+1 submit while partition N
    harvests, prefetch depth survives the day boundary, and packs may
    span it (iter_pack_groups' pad-bucket + time-span rules)."""
    from ..engine.block_result import BlockResult
    from ..engine.searcher import (QueryCancelled, QueryTimeoutError,
                                   _absorb_stats_partials)

    def check_deadline():
        if deadline is not None and time.monotonic() > deadline:
            raise QueryTimeoutError(
                "query exceeded -search.maxQueryDuration")

    def _slot_check():
        # runs on every fair-queue wait tick: a cancelled or
        # over-deadline query must leave the queue, not hold its place
        check_deadline()
        if head.is_done():
            raise QueryCancelled()

    f = q.filter
    depth = inflight_depth(runner)
    if inflight_auto():
        runner._set("inflight_auto_depth", depth)
    sync = _make_sync(runner)
    act = activity.current_activity()
    window: deque = deque()
    spec_seg = None
    if stats_spec is not None and pack_limit() > 1 and sort_spec is None:
        from .stats_device import with_segment_axis
        spec_seg = with_segment_axis(stats_spec)

    def emit(members: list) -> None:
        sp = tracing.current_span()
        for m in members:
            if qcache is not None:
                # harvest-side population: a fully-materialized member
                # is the per-part answer a repeated query can replay
                # (store skips parts this query already hit on)
                qcache.store_member(m)
            if stats_spec is not None and m.partials:
                sp.add("stats_partials", len(m.partials))
                _absorb_stats_partials(head, q, stats_spec, m.partials)
            for bi, bs in m.blocks:
                if bi in m.handled:
                    continue
                if head.is_done():
                    raise QueryCancelled()
                bm = m.bms[bi]
                if not bm.any():
                    continue
                br = BlockResult.from_block_search(bs, bm, needed)
                sp.add("blocks_out")
                sp.add("rows_downloaded", br.nrows)
                head.write_block(br)

    stream = _unit_stream(runner, items, head, stats_spec, sort_spec,
                          token_leaves, check_deadline, qcache=qcache)
    lookahead: deque = deque()
    exhausted = False
    prefetched: set = set()
    # prefetch staging mode must match what the units will dispatch:
    # fused layout staging for stats, for sort-topk (now a fused
    # async dispatch — packed or single) and (unless the
    # VL_FUSED_FILTER kill-switch reverts to the per-leaf path) row
    # queries
    from .fused import fused_filter_enabled
    fused_pf = stats_spec is not None or (
        sort_spec is not None and runner.fused_enabled) or (
        sort_spec is None and fused_filter_enabled()
        and runner.fused_enabled)
    sort_field = sort_spec.field if sort_spec is not None and \
        runner.fused_enabled else None
    psp = tracing.current_span()
    seq = 0

    def refill() -> None:
        # plan only the window's lookahead ahead of execution: an early
        # exit (limit hit, deadline) stops the header walk right where
        # the serial loop would have
        nonlocal exhausted
        if exhausted or len(lookahead) >= depth + 1:
            return
        act.set_phase("prune")
        # the planning pull IS the prune stage: candidate selection +
        # part-aggregate kills run inside _unit_stream, so filterbank's
        # prune counters land on this span
        with psp.span("prune") as prsp:
            planned = 0
            while not exhausted and len(lookahead) < depth + 1:
                try:
                    lookahead.append(next(stream))
                    planned += 1
                except StopIteration:
                    exhausted = True
            prsp.set("units_planned", planned)

    def harvest_one() -> None:
        hseq, hunit, t_submit, pending, leased = window.popleft()
        act.set_phase("harvest")
        act.set("dispatches_in_flight", len(window))
        with psp.span("harvest", unit=hseq) as hsp:
            # device_sync: blocked materializing the dispatch result;
            # emit: host-side block materialization + downstream write
            # (for streaming sinks that includes NDJSON serialization).
            # Split children make the emit cost attributable per query
            # (?trace=1), not just in the bench.
            with hsp.span("device_sync"):
                members = pending.harvest(sync)
            # the dispatch is off the device: return the leased slot
            # BEFORE the host-side emit so contending queries overlap
            # their device work with our emit phase.  Known tradeoff:
            # the OTHER window entries' leases stay held while emit
            # runs, and a stalled streaming client (streamwork's
            # bounded queue) can block emit — pinning up to depth-1
            # slots per stalled query until its deadline/disconnect
            # drain fires.  Bounded and self-healing, but a
            # completion-driven release (harvest on dispatch-done
            # callbacks) would free them earlier — ROADMAP follow-on.
            # Cached units never leased a slot (nothing dispatched),
            # so only leased entries return one.
            if leased:
                slots.release()
            # _UnitReady units never dispatched (host gate / serial
            # fallback): their submit-to-harvest time is pure window
            # queue wait and must not pollute the device-RTT histogram
            dispatched = not isinstance(pending, _UnitReady)
            rtt = time.perf_counter() - t_submit
            if dispatched:
                hist.DISPATCH_RTT.observe(rtt)
                # the EXPLAIN pricing pass's per-unit round-trip term
                # (CostModel.predict) feeds on REAL unit RTTs, not the
                # minimal probe the routing gate uses
                runner.cost.observe_unit_rtt(rtt)
            if hsp.enabled:
                if dispatched:
                    hsp.set("dispatch_rtt_s", round(rtt, 6))
                else:
                    hsp.set("host_unit", True)
                if hunit.pack:
                    hsp.set("pack_members",
                            [str(p.uid) for p, _b in hunit.members])
            t_e0 = time.perf_counter()
            act.set_phase("emit")
            with hsp.span("emit"):
                emit(members)
            emit_dt = time.perf_counter() - t_e0
            hist.EMIT_SECONDS.observe(emit_dt)
            # ONLY the emit phase feeds the VL_INFLIGHT=auto
            # calibration: including the device_sync wait would make
            # the signal track rtt/depth and contract the window on
            # exactly the high-RTT backends that need it deep.
            # Known tradeoff: emit_dt still includes downstream SINK
            # time — for a streaming response that can be a slow
            # client's backpressure (streamwork's bounded queue), which
            # shallows the derived depth.  That query is output-bound
            # (a deeper device window buys it nothing), and the EWMA
            # (alpha 0.3) recovers within a few units once a fast
            # consumer runs on the shared runner.
            runner.cost.observe_emit(emit_dt)

    with sched.device_slots(act) as slots:
        try:
            with psp.span("pipeline", inflight_depth=depth) as plsp:
                psp = plsp
                while True:
                    refill()
                    if not lookahead:
                        break
                    unit = lookahead.popleft()
                    check_deadline()
                    if head.is_done():
                        raise QueryCancelled()
                    # deepened prefetch: stage every unit inside the
                    # window's lookahead, so part N+k's host decode/
                    # upload overlaps the scans of N..N+k-1 (packs
                    # prefetch as the pack, hitting the same #fl/#num
                    # staging keys the super-dispatch will use)
                    todo = [uj for uj in lookahead
                            if not getattr(uj, "cached", False)
                            and uj.part.uid not in prefetched]
                    if todo:
                        with psp.span("stage", units=len(todo)):
                            for uj in todo:
                                prefetched.add(uj.part.uid)
                                runner.submit_prefetch(
                                    uj.part, f, stats_spec,
                                    cand_bis=list(uj.bss),
                                    fused=fused_pf,
                                    sort_field=sort_field)
                    # our own window's depth backpressure is NOT
                    # scheduler wait: drain it untimed first, so the
                    # slot-wait metric means what it says
                    while len(window) >= depth:
                        check_deadline()
                        harvest_one()
                    if getattr(unit, "cached", False):
                        # a result-cache hit: rides the window for
                        # submission-order harvest but skips the slot
                        # lease, the dispatch counters and prefetch —
                        # the part's price collapsed to ~0
                        runner._bump("result_cache_units")
                        window.append((seq, unit, time.perf_counter(),
                                       _UnitReady(unit.ready), False))
                        seq += 1
                        runner._bump_max("inflight_hwm", len(window))
                        if act.enabled:
                            act.add("result_cache_hits")
                            act.set("dispatches_in_flight",
                                    len(window))
                        continue
                    # lease the submit slot from the shared scheduler:
                    # fast-path non-blocking grant (uncontended budget
                    # behaves exactly like the per-query window); under
                    # contention harvest our own oldest unit — freeing
                    # a slot the fair queue hands to whoever is
                    # furthest below their share — and block in the
                    # queue only once nothing of ours is in flight
                    t_w0 = time.perf_counter()
                    while not slots.try_acquire():
                        if window:
                            check_deadline()
                            harvest_one()
                        else:
                            with psp.span("sched_wait"):
                                slots.acquire(check=_slot_check)
                            break
                    slot_wait_s = time.perf_counter() - t_w0
                    hist.SLOT_WAIT.observe(slot_wait_s)
                    runner._bump("sched_slot_wait_s", slot_wait_s)
                    runner._bump("pipeline_units")
                    hist.PACK_SIZE.observe(len(unit.members))
                    with psp.span("submit", unit=seq,
                                  blocks=len(unit.bss)) as ssp:
                        if ssp.enabled:
                            ssp.set("rows",
                                    sum(bs.nrows
                                        for bs in unit.bss.values()))
                            ssp.set("slot_wait_s",
                                    round(slot_wait_s, 6))
                            if unit.pack:
                                ssp.set("pack_size", len(unit.members))
                                ssp.set("pack_members",
                                        [str(p.uid)
                                         for p, _b in unit.members])
                            else:
                                ssp.set("part", str(unit.part.uid))
                        act.set_phase("scan")
                        # test-only drain-path hook (inject_fault /
                        # VL_FAULT_SUBMIT): raises AFTER the lease was
                        # taken, pinning release-on-error
                        sched.maybe_fail_submit()
                        window.append((seq, unit, time.perf_counter(),
                                       _submit(runner, f, unit,
                                               stats_spec, sort_spec,
                                               spec_seg), True))
                    seq += 1
                    runner._bump_max("inflight_hwm", len(window))
                    if act.enabled:
                        act.add("dispatches_submitted")
                        act.set("dispatches_in_flight", len(window))
                while window:
                    check_deadline()
                    harvest_one()
                plsp.set("units", seq)
        finally:
            # cancellation/deadline/fault drain: drop in-flight handles
            # without writing anything downstream.  jax releases the
            # device buffers when the dispatches complete, and every
            # StagingCache entry is a complete, budget-accounted value
            # (staged under its key lock), so the cache stays balanced
            # for the next query; the device_slots scope releases every
            # slot the dropped window still held, so the scheduler's
            # global budget stays balanced too.
            if window:
                # abnormal drain (a clean completion harvested the
                # window empty): journal it so cancelled/faulted scans
                # correlate with their query_done record by qid
                events.emit(
                    "pipeline_drain",
                    tenant=act.tenant if act.enabled else None,
                    qid=act.qid if act.enabled else "",
                    units_dropped=len(window))
            window.clear()
            act.set("dispatches_in_flight", 0)
            stream.close()
