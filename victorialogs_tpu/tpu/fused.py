"""Fully-fused `filter | stats` device path: ONE dispatch per part.

Why this exists (measured on the real chip, tools/profile_device.py):
under the axon tunnel every completed device call costs ~65ms and a
bool[4M] bitmap download costs ~213ms, so the unfused pipeline
(scan dispatch -> bitmap download -> host slice -> mask re-upload ->
stats dispatch) spends ~90% of its time in transfers.  This module
evaluates the WHOLE filter tree and the stats partials inside a single
jit: the bitmap never leaves HBM, and the host downloads only the
(7, num_buckets) partials plus (when needed) a bit-packed
"needs-host-verify" vector (~R/8 bytes, ~12ms vs ~213ms unpacked).

Key design points:
- Staging is in STATS-LAYOUT coordinates (every block of the part, in
  index order — tpu/batch.py part_stats_layout), not the string-only
  packing of stage_part_column.  Dict/const/missing blocks are
  MATERIALIZED into the fixed-width matrix (a const block is one
  template row broadcast), so every filter leaf is a pure scan and the
  jitted program needs no per-block composition tables — which keeps
  the jit cache keyed on query SHAPE, not on part-specific block maps.
- Three-valued logic: each tree node evaluates to (definite, maybe)
  row vectors.  `maybe` collects truncation-overflow rows and the
  ordered-pair regex's newline rows; they are excluded from the device
  partials and settled by a host residue pass (filters' own
  apply_to_block over just those rows) whose per-row partials merge
  through the same absorb path — bit-identical to the CPU executor.
- The host-side planner simplifies the tree first: bloom kill-paths
  and block-uniform leaves (stream filters after candidate pruning)
  fold to constants, so `{app="x"} "y" | stats count()` compiles to a
  single scan + reduction.  Bloom planning probes the part's packed
  bloom plane in one batch (storage/filterbank.py); when only SOME
  candidate blocks die, the plane is staged to HBM and the keep-mask
  is re-probed INSIDE the dispatch (tpu/bloom_device.py), gathered to
  rows through a staged block-id column and ANDed with the scan tree —
  the bloom kill bitmap never crosses the host boundary.

Reference parity: this is the TPU-shaped fusion of the reference's
per-worker stats shards merged at flush (pipe_stats.go:354-377) with
its batched block scanning (storage_search.go:1035-1121); the
correctness oracle is the CPU executor (tests/test_fused.py diffs
them bit-exactly over randomized query matrices).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import numpy as np
from .. import config

from ..logsql import filters as F
from ..storage.filterbank import bloom_keep_mask, filter_bank
from ..storage.values_encoder import VT_DICT, VT_STRING
from ..utils.hashing import cached_token_hashes
from . import kernels as K
from . import kernels32 as K32
from .batch import device_plan, StatsLayout
from .bloom_device import (MAX_PALLAS_PROBES, pad_probe_args, pad_sb_idx,
                           plane_keep, plane_keep_sb)
from .layout import (row_width_bucket, rows_with_multibyte, to_fixed_width,
                     to_lanes32)


# ---------------- layout-coordinate string staging ----------------

@dataclass
class FusedField:
    """One column staged over EVERY block of a part, layout coords."""
    rows: object                   # jax uint32[W/4, RLp] lane-major
    lengths: object                # jax int32[RLp]
    width: int
    ovf_packed: object | None      # jax uint8[RLp//8] bit-packed overflow
    ovf_np: np.ndarray             # host bool[RLp] (residue bookkeeping)
    has_ovf: bool
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


def stage_layout_column(part, field: str, layout: StatsLayout,
                        max_bytes: int, put) -> FusedField | None:
    """Materialize `field` for all blocks into one (RLp, W) matrix.

    String blocks ride the native fixed-width transpose; dict blocks
    are gathered per code; const/missing blocks broadcast a template
    row ('' for missing — the host's value semantics for absent
    fields).  Returns None when any block is numeric/ipv4/ts-typed
    (the caller falls back to the unfused path) or the matrix would
    exceed max_bytes."""
    virtual = field in ("_stream", "_stream_id")
    plans = []        # (start, n, kind, payload)
    max_len = 0
    for bi in range(part.num_blocks):
        start = layout.starts[bi]
        n = part.block_rows(bi)
        if virtual:
            v = part.block_tags(bi) if field == "_stream" else \
                part.block_stream_id(bi).as_string()
            b = v.encode("utf-8", "replace")
            max_len = max(max_len, len(b))
            plans.append((start, n, "const", b))
            continue
        meta = part.block_column_meta(bi, field)
        if meta is None:
            consts = dict(part.block_consts(bi))
            b = consts.get(field, "").encode("utf-8", "replace")
            max_len = max(max_len, len(b))
            plans.append((start, n, "const", b))
            continue
        if meta["t"] == VT_STRING:
            col = part.block_column(bi, field)
            if col.lengths.size:
                max_len = max(max_len, int(col.lengths.max()))
            plans.append((start, n, "str", col))
        elif meta["t"] == VT_DICT:
            col = part.block_column(bi, field)
            enc = [v.encode("utf-8", "replace") for v in col.dict_values]
            if enc:
                max_len = max(max_len, max(len(b) for b in enc))
            plans.append((start, n, "dict", (col.ids, enc)))
        else:
            return None  # numeric/ipv4/ts block: host path decodes these
    w = row_width_bucket(max_len)
    rlp = layout.nrows_padded
    if rlp * (w + 4) > max_bytes:
        return None
    mat = np.full((rlp, w), 0xFF, dtype=np.uint8)
    lens = np.zeros(rlp, dtype=np.int32)
    ovf = np.zeros(rlp, dtype=bool)
    for start, n, kind, payload in plans:
        if kind == "str":
            col = payload
            sub, _w, ov = to_fixed_width(col.arena, col.offsets,
                                         col.lengths, n, width=w)
            mat[start:start + n] = sub
            lens[start:start + n] = np.minimum(col.lengths, w - 1)
            if ov.size:
                ovf[start + ov] = True
        elif kind == "dict":
            ids, enc = payload
            for code, b in enumerate(enc):
                sel = np.nonzero(ids == code)[0]
                if not sel.size:
                    continue
                cl = min(len(b), w - 1)
                row = np.full(w, 0xFF, dtype=np.uint8)
                row[:cl] = np.frombuffer(b[:cl], dtype=np.uint8)
                mat[start + sel] = row
                lens[start + sel] = cl
                if len(b) > w - 1:
                    ovf[start + sel] = True
        else:  # const ('' included)
            b = payload
            cl = min(len(b), w - 1)
            row = np.full(w, 0xFF, dtype=np.uint8)
            row[:cl] = np.frombuffer(b[:cl], dtype=np.uint8)
            mat[start:start + n] = row
            lens[start:start + n] = cl
            if len(b) > w - 1:
                ovf[start:start + n] = True
    has_ovf = bool(ovf.any())
    ovp = put(np.packbits(ovf)) if has_ovf else None
    return FusedField(rows=put(to_lanes32(mat), row_axis=1),
                      lengths=put(lens), width=w,
                      ovf_packed=ovp, ovf_np=ovf, has_ovf=has_ovf,
                      nbytes=rlp * (w + 5))


@dataclass
class MultibyteMask:
    """Per-row 'contains a byte >= 0x80' flags for one column, packed.
    A static property of the part, computed host-side from the SOURCE
    values (so truncated tails count) and staged lazily the first time
    a len_range leaf needs it.  any=False => the column is pure ASCII
    and len_range is fully definitive on byte lengths."""
    packed: object | None          # jax uint8[RLp/8]; None when not any
    any: bool
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


def stage_multibyte_mask(part, field: str, layout: StatsLayout,
                         put) -> MultibyteMask:
    virtual = field in ("_stream", "_stream_id")
    mb = np.zeros(layout.nrows_padded, dtype=bool)
    for bi in range(part.num_blocks):
        start = layout.starts[bi]
        n = part.block_rows(bi)
        if virtual:
            v = part.block_tags(bi) if field == "_stream" else \
                part.block_stream_id(bi).as_string()
            if max(v.encode("utf-8", "replace"), default=0) >= 0x80:
                mb[start:start + n] = True
            continue
        meta = part.block_column_meta(bi, field)
        if meta is None:
            consts = dict(part.block_consts(bi))
            b = consts.get(field, "").encode("utf-8", "replace")
            if b and max(b) >= 0x80:
                mb[start:start + n] = True
            continue
        if meta["t"] == VT_STRING:
            col = part.block_column(bi, field)
            mb[start:start + n] = rows_with_multibyte(
                col.arena, col.offsets, col.lengths)
        elif meta["t"] == VT_DICT:
            col = part.block_column(bi, field)
            flags = np.array([bool(v.encode("utf-8", "replace") and
                                   max(v.encode("utf-8", "replace"))
                                   >= 0x80)
                              for v in col.dict_values], dtype=bool)
            if flags.any():
                mb[start:start + n] = flags[col.ids]
        # numeric/ipv4/ts blocks: canonical decimals are pure ASCII
    has = bool(mb.any())
    return MultibyteMask(packed=put(np.packbits(mb)) if has else None,
                         any=has,
                         nbytes=layout.nrows_padded // 8 if has else 64)


@dataclass
class _CandMask:
    packed: object                 # jax uint8[RLp/8]
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


@dataclass
class TsPlanes:
    """Block timestamps as two int32 planes (hi = off>>16, lo = off&0xFFFF)
    of ns offsets from the part minimum — exact int64 compares without
    x64 mode (a per-day partition's offsets fit 47 bits)."""
    hi: object
    lo: object
    base: int                      # part min ts (ns)
    nbytes: int

    def device_bytes(self) -> int:
        return self.nbytes


def stage_ts_planes(part, layout: StatsLayout, put) -> TsPlanes:
    off = np.zeros(layout.nrows_padded, dtype=np.int64)
    # single decode pass per block: base comes from the header min-ts
    base = min((part.block_min_ts(bi) for bi in range(part.num_blocks)),
               default=0)
    for bi in range(part.num_blocks):
        ts = part.block_timestamps(bi).astype(np.int64)
        start = layout.starts[bi]
        off[start:start + ts.shape[0]] = ts - base
    hi = (off >> 16).astype(np.int32)
    lo = (off & 0xFFFF).astype(np.int32)
    return TsPlanes(hi=put(hi), lo=put(lo), base=base,
                    nbytes=layout.nrows_padded * 8)


def _split_bound(v: int) -> tuple[int, int]:
    return int(v) >> 16, int(v) & 0xFFFF


# ---------------- planner: filter tree -> static program ----------------

class _NoFuse(Exception):
    pass


class _Planner:
    """Walks the filter tree, staging what it needs and emitting a
    hashable program plus the parallel dynamic-argument list."""

    def __init__(self, runner, part, bss, layout):
        self.runner = runner
        self.part = part
        self.bss = bss
        self.layout = layout
        self.args: list = []
        self.arg_rows: list = []
        self.field_slots: dict[str, int] = {}
        self.fields: list[FusedField] = []
        self._slot_args: list = []
        self.ts_slot: tuple | None = None
        self.has_maybe = False

    def arg(self, a, row: int = 0) -> int:
        """Register a dynamic input; row marks row-aligned arrays that a
        mesh dispatch shards — recorded explicitly so sharding never
        relies on shape coincidences.  row=1 (or True): the row axis is
        axis 0 (RLp or RLp/8 leading dim); row=2: axis 1 (the lane-major
        uint32[W/4, RLp] string staging)."""
        self.args.append(a)
        self.arg_rows.append(int(row))
        return len(self.args) - 1

    def field_slot(self, field: str) -> tuple[int, FusedField]:
        slot = self.field_slots.get(field)
        if slot is not None:
            return slot, self.fields[slot]
        ff = self.runner._stage_fused_field(self.part, field, self.layout)
        if ff is None:
            raise _NoFuse(field)
        ri = self.arg(ff.rows, row=2)
        li = self.arg(ff.lengths, row=True)
        oi = self.arg(ff.ovf_packed, row=True) if ff.has_ovf else -1
        slot = len(self.fields)
        self.field_slots[field] = slot
        self.fields.append(ff)
        self._slot_args.append((ri, li, oi))
        if ff.has_ovf:
            self.has_maybe = True
        return slot, ff

    def slot_args(self, slot: int) -> tuple[int, int, int]:
        return self._slot_args[slot]

    # -- tree walk --

    def plan(self, f):
        if isinstance(f, F.FilterAnd):
            return self._combine("and", [self.plan(s) for s in f.filters])
        if isinstance(f, F.FilterOr):
            return self._combine("or", [self.plan(s) for s in f.filters])
        if isinstance(f, F.FilterNot):
            inner = self.plan(f.inner)
            if inner == ("true",):
                return ("false",)
            if inner == ("false",):
                return ("true",)
            return ("not", inner)
        if isinstance(f, F.FilterNoop):
            return ("true",)
        if isinstance(f, F.FilterNone):
            return ("false",)
        if isinstance(f, F.FilterTime):
            return self._time_leaf(f)
        if isinstance(f, (F.FilterStream, F.FilterStreamID,
                          F.FilterValueType)):
            return self._block_uniform_leaf(f)
        if isinstance(f, F.FilterRange):
            return self._numrange_leaf(f)
        if isinstance(f, F.FilterIn):
            return self._in_leaf(f)
        if isinstance(f, F.FilterLenRange):
            return self._lenrange_leaf(f)
        return self._scan_leaf(f)

    @staticmethod
    def _combine(op, kids):
        flat = []
        for k in kids:
            if op == "and":
                if k == ("false",):
                    return ("false",)
                if k == ("true",):
                    continue
            else:
                if k == ("true",):
                    return ("true",)
                if k == ("false",):
                    continue
            flat.append(k)
        if not flat:
            return ("true",) if op == "and" else ("false",)
        if len(flat) == 1:
            return flat[0]
        return (op, tuple(flat))

    def _time_leaf(self, f: F.FilterTime):
        ts = self.runner._stage_ts_planes(self.part, self.layout)
        if self.part.max_ts - ts.base >= (1 << 47):
            # the (hi >> 16) int32 plane is exact only below 2**47 ns
            # of offset (~39h).  Per-day parts never exceed it and
            # iter_pack_groups splits packs at PACK_TS_SPAN_MAX, so
            # this is a defensive decline (e.g. a part from a widened
            # retention layout), never a silent wrong compare.
            raise _NoFuse("ts-span")
        if self.ts_slot is None:
            hi = self.arg(ts.hi, row=True)
            lo = self.arg(ts.lo, row=True)
            self.ts_slot = (hi, lo)
        # clamp query bounds into the part's offset space; the leaf is
        # inclusive on both ends (FilterTime semantics)
        lo_off = max(0, f.min_ts - ts.base)
        hi_off = f.max_ts - ts.base
        if hi_off < 0 or lo_off >= (1 << 47):
            return ("false",)
        b = [self.arg(np.int32(x)) for x in
             (*_split_bound(lo_off),
              *_split_bound(min(hi_off, (1 << 47) - 1)))]
        return ("time", self.ts_slot[0], self.ts_slot[1], *b)

    def _block_uniform_leaf(self, f):
        """Per-block-constant filters (stream filters after candidate
        pruning; value_type, which depends only on the block's column
        encoding).  Uniform over the candidates -> constant; mixed -> a
        bit-packed row mask built host-side (cheap: range fills)."""
        truths = {}
        for bi, bs in self.bss.items():
            if isinstance(f, F.FilterStream):
                ctx = getattr(bs, "ctx", None)
                if ctx is None:
                    truths[bi] = True
                    continue
                sids = f.resolve(ctx.partition, ctx.tenants)
                truths[bi] = bs.stream_id in sids
            elif isinstance(f, F.FilterValueType):
                truths[bi] = bs.value_type_name(
                    F.canonical_field(f.field)) == f.type_name
            else:
                truths[bi] = bs.stream_id.as_string() in f._set
        vals = set(truths.values())
        if vals == {True}:
            return ("true",)
        if vals == {False}:
            return ("false",)
        m = np.zeros(self.layout.nrows_padded, dtype=bool)
        for bi, t in truths.items():
            if t:
                s = self.layout.starts[bi]
                m[s:s + self.part.block_rows(bi)] = True
        return ("maskleaf",
                self.arg(self.runner._put(np.packbits(m)), row=True))

    def _scan_leaf(self, f):
        plan = device_plan(f)
        if plan is None:
            raise _NoFuse(type(f).__name__)
        if plan.verify and plan.pair is None:
            raise _NoFuse("verify")          # multi-seq / impure regex
        if plan.field == "_time":
            raise _NoFuse("_time-as-string")
        # bloom kill-path: when a required token is absent from every
        # candidate block's bloom, the leaf is constant false — no scan.
        # And when bloom + candidate pruning leave only a small row
        # fraction, the host path over those few blocks beats staging +
        # whole-part scanning (same narrowness gate as _eval_leaf).
        # The probe is the packed-plane batch probe (filterbank); when
        # only SOME blocks die, the same plane is staged to HBM and the
        # kill bitmap ANDs into the tree inside the dispatch
        # (_bloom_node) — the device result needs no host mask.
        surv_rows = 0
        bloom_node = None
        if plan.bloom_tokens:
            hashes = cached_token_hashes(plan.filter, plan.bloom_tokens)
            bis = list(self.bss)
            keep = bloom_keep_mask(self.part, plan.field, hashes, bis)
            from ..storage.filterindex import part_index
            if part_index(self.part) is not None:
                # same evidence counters _eval_leaf keeps: the v2
                # maplet (exact) served this probe
                self.runner._bump("maplet_probes")
            elif filter_bank(self.part).cached_plane(plan.field) \
                    is not None:
                # same evidence counter _eval_leaf keeps on the per-leaf
                # path: the PLANE served this probe
                self.runner._bump("bloom_plane_probes")
            for i, bi in enumerate(bis):
                if keep[i]:
                    surv_rows += self.part.block_rows(bi)
            if surv_rows == 0:
                return ("false",)
            if not keep.all():
                bloom_node = self._bloom_node(plan.field, hashes)
        else:
            surv_rows = sum(self.part.block_rows(bi) for bi in self.bss)
        if surv_rows * 8 < self.part.num_rows and \
                not self.runner.cache.contains(
                    (self.part.uid, "#fl", plan.field)):
            raise _NoFuse("narrow")
        slot, ff = self.field_slot(plan.field)
        ri, li, oi = self.slot_args(slot)
        if plan.pair is not None:
            a, b = plan.pair
            if max(len(a), len(b)) >= ff.width:
                return self._with_bloom(bloom_node, self._ovf_only(oi))
            self.has_maybe = True
            pa = self.arg(np.frombuffer(a, dtype=np.uint8))
            pb = self.arg(np.frombuffer(b, dtype=np.uint8))
            return self._with_bloom(
                bloom_node, ("pair", ri, li, oi, pa, len(a), pb, len(b)))
        # case-fold leaves: non-ASCII rows diverge from the byte fold in
        # either direction, so they ride the maybe channel (host residue
        # settles them with the filter's own predicate)
        mb_mi = -1
        if any(op.fold for op in plan.ops):
            mbm = self.runner._stage_multibyte(self.part, plan.field,
                                               self.layout)
            if mbm.any:
                mb_mi = self.arg(mbm.packed, row=True)
                self.has_maybe = True
        kids = []
        for op in plan.ops:
            if op.match_nonempty:
                kids.append(("nonempty", li))
            elif op.match_empty:
                # truncated rows have true length > W-1 > 0: never empty,
                # so the lengths compare is definitive even for overflow
                kids.append(("empty", li))
            elif len(op.pattern) >= ff.width:
                kids.append(self._ovf_only(oi))
            else:
                pi = self.arg(np.frombuffer(op.pattern, dtype=np.uint8))
                kids.append(("scan", ri, li, oi,
                             mb_mi if op.fold else -1, pi,
                             len(op.pattern), op.mode, op.starts_tok,
                             op.ends_tok, op.fold))
        return self._with_bloom(bloom_node,
                                self._combine(plan.combine, kids))

    @staticmethod
    def _with_bloom(bloom_node, res):
        if bloom_node is None:
            return res
        return _Planner._combine("and", [bloom_node, res])

    def _bloom_node(self, field: str, hashes):
        """Emit the in-dispatch bloom kill: the packed plane rides HBM
        (staged once per part+column), the per-block keep-mask is
        probed INSIDE the fused jit from host-computed positions, and
        gathers to rows through the staged block-id column — so the
        bloom kill bitmap ANDs against the scan tree without any host
        round-trip.  None (leaf keeps host-planning semantics only)
        when staging declines or VL_DEVICE_BLOOM=0.

        Sealed parts with a v2 filter index ship the split-block
        layout instead (storage/filterindex): all 6 probe bits of a
        token live in one 256-bit block, so the device probe is ONE
        contiguous 8-lane gather + AND-compare per (block, token)
        (`bloom_sb` node, tpu/bloom_device.plane_keep_sb) instead of 6
        scattered lane selects."""
        if not config.env_flag("VL_DEVICE_BLOOM"):
            return None
        sb_node = self._bloom_sb_node(field, hashes)
        if sb_node is not None:
            return sb_node
        sp = self.runner._stage_bloom_plane(self.part, field)
        if sp is None:
            return None
        plb = filter_bank(self.part).plane(self.part, field)
        if plb is None:
            return None
        idx, shift = plb.block_probe_args(hashes)
        idx, shift = pad_probe_args(idx, shift, sp.bp)
        # the Pallas probe replaces the gather with a VMEM lane-select;
        # gated like kernels_pallas.match_scan, never on by default
        use_pallas = (config.env("VL_PALLAS") == "1"
                      and idx.shape[1] <= MAX_PALLAS_PROBES)
        bid = self.runner._stage_block_ids(self.part, self.layout)
        self.runner._kind("bloom_device")
        return ("bloom", self.arg(sp.plane), self.arg(sp.nwords),
                self.arg(idx), self.arg(shift),
                self.arg(bid.ids, row=True), use_pallas)

    def _bloom_sb_node(self, field: str, hashes):
        """The v2 split-block variant of _bloom_node, or None when the
        part has no valid sidecar for the column (classic plane path
        serves)."""
        from ..storage.filterindex import part_index
        fi = part_index(self.part)
        if fi is None or not fi.has_sb(field):
            return None
        sp = self.runner._stage_sb_plane(self.part, field)
        if sp is None:
            return None
        sbidx = pad_sb_idx(fi.sb_probe_idx(field, hashes), sp.bp)
        mask = fi.sb_masks(hashes)
        bid = self.runner._stage_block_ids(self.part, self.layout)
        self.runner._kind("bloom_sb_device")
        return ("bloom_sb", self.arg(sp.plane), self.arg(sp.nsb),
                self.arg(sbidx), self.arg(mask),
                self.arg(bid.ids, row=True))

    def _numrange_leaf(self, f: F.FilterRange):
        """`status:>=500`-family on int-typed columns: the uint32 offset
        staging the stats path already uses doubles as the compare
        operand (host analogue: FilterRange.apply_to_block's vectorized
        numeric branch).  Declines when any candidate block is not
        int-typed (string/float/missing: host semantics differ)."""
        from .stats_device import MAX_ABS_TIMES_ROWS
        field = F.canonical_field(f.field)
        if math.isnan(f.min_value) or math.isnan(f.max_value):
            raise _NoFuse("numrange-nan")
        sn = self.runner._stage_numeric(self.part, field, self.layout,
                                        MAX_ABS_TIMES_ROWS)
        if sn is None or any(bi not in sn.eligible for bi in self.bss):
            raise _NoFuse("numrange")
        # integer-exact bounds, mirroring the host's ceil/floor treatment;
        # +-inf saturates OUTWARD (>=inf matches nothing staged, <=-inf
        # likewise) — ceil/floor of an infinity would raise OverflowError
        lo = (-(1 << 62) if f.min_value < 0 else (1 << 62)) \
            if math.isinf(f.min_value) else math.ceil(f.min_value)
        hi = ((1 << 62) if f.max_value > 0 else -(1 << 62)) \
            if math.isinf(f.max_value) else math.floor(f.max_value)
        lo_off = lo - sn.vmin
        hi_off = hi - sn.vmin
        if lo_off > hi_off or hi_off < 0 or lo_off >= (1 << 32):
            return ("false",)
        lo_off = max(0, lo_off)
        hi_off = min(hi_off, (1 << 32) - 1)
        vi = self.arg(sn.values, row=True)
        a = self.arg(np.uint32(lo_off))
        b = self.arg(np.uint32(hi_off))
        return ("numrange", vi, a, b)

    def _lenrange_leaf(self, f: F.FilterLenRange):
        """len_range(lo, hi): rune counts equal byte lengths for pure
        ASCII, so the staged lengths decide those rows.  Multibyte rows
        (precomputed packed mask, a static property of the part) are
        ambiguous only inside [lo, 4*hi] bytes (codepoints <= bytes <=
        4*codepoints); a pure-ASCII column has no maybe rows at all.
        Truncated rows join the maybe set unless even the truncation
        floor (W-1 bytes) already exceeds 4*hi."""
        if f.max_len < max(0, f.min_len):
            return ("false",)
        field = F.canonical_field(f.field)
        if field == "_time":
            raise _NoFuse("_time-as-string")
        slot, ff = self.field_slot(field)
        _ri, li, oi = self.slot_args(slot)
        mbm = self.runner._stage_multibyte(self.part, field, self.layout)
        mi = self.arg(mbm.packed, row=True) if mbm.any else -1
        imax = (1 << 31) - 1
        a = self.arg(np.int32(min(max(0, f.min_len), imax)))
        b = self.arg(np.int32(min(f.max_len, imax)))
        b4 = self.arg(np.int32(min(4 * f.max_len, imax)))
        # overflow rows whose true length must exceed 4*hi are
        # definitively false (their staged length W-1 > hi keeps d false)
        if ff.width - 1 > min(4 * f.max_len, imax):
            oi = -1
        if mi >= 0 or oi >= 0:
            self.has_maybe = True
        return ("lenrange", li, oi, mi, a, b, b4)

    def _in_leaf(self, f: F.FilterIn):
        """`lvl:in(a, b, ...)` = OR of exact scans over the materialized
        matrix (dict/const blocks included)."""
        if f.subquery is not None and not f.values:
            raise _NoFuse("in-subquery")
        if len(f.values) > 16:
            raise _NoFuse("in-cardinality")
        field = F.canonical_field(f.field)
        if field == "_time":
            raise _NoFuse("_time-as-string")
        slot, ff = self.field_slot(field)
        ri, li, oi = self.slot_args(slot)
        kids = []
        for v in f.values:
            if not v:
                kids.append(("empty", li))
                continue
            if not v.isascii() or len(v) > K.MAX_PATTERN_LEN:
                raise _NoFuse("in-value")
            if len(v) >= ff.width:
                kids.append(self._ovf_only(oi))
                continue
            pi = self.arg(np.frombuffer(v.encode(), dtype=np.uint8))
            kids.append(("scan", ri, li, oi, -1, pi, len(v),
                         K.MODE_EXACT, False, False, False))
        return self._combine("or", kids)

    def _ovf_only(self, oi: int):
        """Pattern wider than the staging: no staged row can match; only
        overflow rows might."""
        if oi < 0:
            return ("false",)
        self.has_maybe = True
        return ("ovfmaybe", oi)


# ---------------- the jitted program evaluator ----------------

def _unpack_bits(packed, n):
    import jax.numpy as jnp
    bits = jnp.unpackbits(packed)
    return bits[:n].astype(jnp.bool_)


def _eval_node(node, args, rlp):
    """Recursive (definite, maybe) evaluation; maybe may be None (==0)."""
    import jax.numpy as jnp
    kind = node[0]
    if kind == "true":
        return jnp.ones(rlp, dtype=bool), None
    if kind == "false":
        return jnp.zeros(rlp, dtype=bool), None
    if kind == "maskleaf":
        return _unpack_bits(args[node[1]], rlp), None
    if kind == "nonempty":
        return args[node[1]] > 0, None
    if kind == "empty":
        return args[node[1]] == 0, None
    if kind == "ovfmaybe":
        ov = _unpack_bits(args[node[1]], rlp)
        return jnp.zeros(rlp, dtype=bool), ov
    if kind == "bloom":
        # per-block keep-mask probed from the HBM-resident bloom plane,
        # gathered to rows via the block-id column (tpu/bloom_device.py)
        _, pi, nwi, ii, si, bidi, use_pallas = node
        keep = plane_keep(args[pi], args[ii], args[si], args[nwi],
                          use_pallas=use_pallas)
        return keep[args[bidi]], None
    if kind == "bloom_sb":
        # split-block layout (sealed-part filter index v2): one
        # contiguous 8-lane gather + AND-compare per (block, token)
        _, pi, ni, ii, mi, bidi = node
        keep = plane_keep_sb(args[pi], args[ii], args[mi], args[ni])
        return keep[args[bidi]], None
    if kind == "lenrange":
        _, li, oi, mi, a, b, b4 = node
        lens = args[li]
        d = (lens >= args[a]) & (lens <= args[b])
        may = None
        if mi >= 0:
            multibyte = _unpack_bits(args[mi], rlp)
            may = multibyte & (lens >= args[a]) & (lens <= args[b4])
        if oi >= 0:
            ov = _unpack_bits(args[oi], rlp)
            may = ov if may is None else may | ov
        if may is None:
            return d, None
        return d & ~may, may
    if kind == "numrange":
        _, vi, a, b = node
        v = args[vi]
        return (v >= args[a]) & (v <= args[b]), None
    if kind == "time":
        _, hi_i, lo_i, a, b, c, d = node
        hi, lo = args[hi_i], args[lo_i]
        lo_hi, lo_lo, hi_hi, hi_lo = args[a], args[b], args[c], args[d]
        ge = (hi > lo_hi) | ((hi == lo_hi) & (lo >= lo_lo))
        le = (hi < hi_hi) | ((hi == hi_hi) & (lo <= hi_lo))
        return ge & le, None
    if kind == "scan":
        _, ri, li, oi, mi, pi, plen, mode, st, et, fold = node
        m = K32.match_scan_t(args[ri], args[li], args[pi], plen, mode, st,
                             et, fold)
        may = None
        if oi >= 0:
            may = _unpack_bits(args[oi], rlp)
        if mi >= 0:
            mb = _unpack_bits(args[mi], rlp)
            may = mb if may is None else may | mb
        if may is None:
            return m, None
        return m & ~may, may
    if kind == "pair":
        _, ri, li, oi, pa, la, pb, lb = node
        definite, needsv = K32.match_ordered_pair_t(args[ri], args[li],
                                                    args[pa], la,
                                                    args[pb], lb)
        may = needsv
        if oi >= 0:
            ov = _unpack_bits(args[oi], rlp)
            definite = definite & ~ov
            may = may | ov
        return definite, may
    if kind == "not":
        d, m = _eval_node(node[1], args, rlp)
        if m is None:
            return ~d, None
        return ~(d | m), m
    # and / or
    kids = [_eval_node(k, args, rlp) for k in node[1]]
    if kind == "and":
        d = kids[0][0]
        pos = d if kids[0][1] is None else d | kids[0][1]
        for kd, km in kids[1:]:
            d = d & kd
            pos = pos & (kd if km is None else kd | km)
        may = pos & ~d
        return d, (None if all(km is None for _, km in kids) else may)
    d = kids[0][0]
    pos = d if kids[0][1] is None else d | kids[0][1]
    for kd, km in kids[1:]:
        d = d | kd
        pos = pos | (kd if km is None else kd | km)
    may = pos & ~d
    return d, (None if all(km is None for _, km in kids) else may)


def _seg_base_ids(ids_tuple, strides):
    """Combined BASE bucket ids of a seg-major dispatch (everything
    after the leading segment axis; a seg-only grouping has base 0)."""
    import jax.numpy as jnp
    if len(ids_tuple) == 1:
        return jnp.zeros(ids_tuple[0].shape[0], dtype=jnp.int32)
    return K.combine_ids(ids_tuple[1:], strides[1:])


def _fused_local(prog, strides, nb, n_values, axis, nrows, cand_packed,
                 seg_map, ids_tuple, values_tuple, args):
    """The fused program body, single-device or per-shard.

    axis: None for single-device execution; a mesh axis name when
    running inside shard_map — row-sized inputs arrive as this shard's
    stripe, stats reduce with psum/pmin/pmax over ICI, and the row
    index for the rows<nrows candidate form is offset by the shard's
    global position.

    Packed super-dispatches (prog carries nseg > 0): ids_tuple[0] is
    the per-row segment ids and the reduction runs SEGMENT-MAJOR
    (tpu/stats_seg.py) — the bucket one-hot stays at the base product
    nb // nseg instead of widening to the full nb, and the flattened
    [S, base] result is bit-identical to the widened combined-id form
    (the seg axis led the by order with stride == base)."""
    import jax.numpy as jnp
    tree, _rlp_global, has_maybe, has_cand = prog[:4]
    nseg = prog[5] if len(prog) > 5 else 0
    seg_pallas = prog[6] if len(prog) > 6 else False
    rl = ids_tuple[0].shape[0]         # LOCAL rows (== global w/o axis)
    d, m = _eval_node(tree, args, rl)
    if has_cand:
        cand = _unpack_bits(cand_packed, rl)
    else:
        idx = jnp.arange(rl, dtype=jnp.int32)
        if axis is not None:
            idx = idx + jax.lax.axis_index(axis) * rl
        cand = idx < nrows
    d = d & cand
    vary = (axis,) if axis is not None else ()
    if nseg:
        from . import stats_seg as SS
        seg = ids_tuple[0]
        base = _seg_base_ids(ids_tuple, strides)
        nb_base = nb // nseg
        if axis is None and not seg_pallas:
            # single-device: the segment-ALIGNED slot grid — each
            # member reduces only its own padded slots (total work ~the
            # members' rows, not S * R); bit-identical to the striped
            # form below
            if n_values == 0:
                flat = SS.stats_count_slots(seg_map, base, d, nb_base)
            else:
                outs = [K.pack_stats(*SS.stats_values_slots(
                    v, seg_map, base, d, nb_base))
                    for v in values_tuple]
                flat = jnp.stack(outs, axis=0).reshape(-1)
        elif n_values == 0:
            # mesh stripes (manual shard_map rows can't gather the
            # global slot grid) and the VL_PALLAS count variant ride
            # the row-striped seg kernels
            flat = SS.stats_count_seg_local(seg, base, d, nseg, nb_base,
                                            vary_axes=vary,
                                            use_pallas=seg_pallas)
            if axis is not None:
                flat = jax.lax.psum(flat, axis)
        else:
            outs = []
            for v in values_tuple:
                cnt, sums, lo, hi = SS.stats_values_seg_local(
                    v, seg, base, d, nseg, nb_base, vary_axes=vary)
                if axis is not None:
                    cnt = jax.lax.psum(cnt, axis)
                    sums = jax.lax.psum(sums, axis)
                    lo = jax.lax.pmin(lo, axis)
                    hi = jax.lax.pmax(hi, axis)
                outs.append(K.pack_stats(cnt, sums, lo, hi))
            flat = jnp.stack(outs, axis=0).reshape(-1)
    elif n_values == 0:
        ids = K.combine_ids(ids_tuple, strides)
        flat = K.stats_count_local(ids, d, nb, vary_axes=vary)
        if axis is not None:
            flat = jax.lax.psum(flat, axis)
    else:
        ids = K.combine_ids(ids_tuple, strides)
        outs = []
        for v in values_tuple:
            cnt, sums, lo, hi = K.stats_values_local(v, ids, d, nb,
                                                     vary_axes=vary)
            if axis is not None:
                cnt = jax.lax.psum(cnt, axis)
                sums = jax.lax.psum(sums, axis)
                lo = jax.lax.pmin(lo, axis)
                hi = jax.lax.pmax(hi, axis)
            outs.append(K.pack_stats(cnt, sums, lo, hi))
        flat = jnp.stack(outs, axis=0).reshape(-1)
    # the maybe-any flag rides INSIDE the stats download so the host can
    # skip the packed-maybe transfer entirely in the common no-maybe case
    if has_maybe and m is not None:
        mc = m & cand
        many = jnp.any(mc).astype(jnp.uint32)
        if axis is not None:
            many = jax.lax.psum(many, axis)    # nonzero iff any shard hit
        mp = jnp.packbits(mc.astype(jnp.uint8))
    else:
        many = jnp.uint32(0)
        mp = jnp.zeros(1, dtype=jnp.uint8)
        if axis is not None:
            mp = K._vary(mp, (axis,))
    return jnp.concatenate([flat, many[None]]), mp


@partial(jax.jit, static_argnames=("prog", "strides", "nb", "n_values"))
def _fused_dispatch(prog, strides, nb, n_values, nrows, cand_packed,
                    seg_map, ids_tuple, values_tuple, args):
    """One device call: filter tree -> stats partials (+ packed maybe).

    prog: (tree, rlp, has_maybe, has_cand, arg_rows[, nseg,
    seg_pallas]) — static, hashable; arg_rows marks which leaf args are
    row-aligned (mesh sharding); nseg > 0 marks a packed super-dispatch
    (seg-major reduction, tpu/stats_seg.py).
    nrows: dynamic scalar (rows < nrows are live when cand_packed is
    None-shaped); cand_packed: uint8[RLp/8] or zeros(1) when unused;
    seg_map: the pack's int32[S, Lp] slot grid (zeros(1, 1) stub when
    nseg == 0).
    Returns (flat, maybe_packed): flat is uint32[nb + 1] for count-only
    or uint32[n_values*7*nb + 1] — the trailing element is the
    maybe-any flag; maybe_packed is uint8[RLp/8] (zeros(1) when the
    program proves no maybe rows exist) and is only worth downloading
    when the flag is nonzero."""
    return _fused_local(prog, strides, nb, n_values, None, nrows,
                        cand_packed, seg_map, ids_tuple, values_tuple,
                        args)


@partial(jax.jit, static_argnames=("prog", "strides", "nb", "n_values",
                                   "mesh", "axis"))
def _fused_dispatch_mesh(mesh, axis, prog, strides, nb, n_values, nrows,
                         cand_packed, seg_map, ids_tuple, values_tuple,
                         args):
    """The fused program under shard_map: each device evaluates the tree
    over its row stripe; stats partials psum/pmin/pmax over ICI; the
    packed maybe-vector concatenates along the row axis.  This is the
    multi-chip product form of the reference's mergeState split
    (pipe_stats.go:55-60) — one SPMD dispatch, in-network reduction.
    The seg slot grid is unused here (manual row stripes cannot gather
    global rows; the striped seg kernels serve) — it ships replicated
    as an inert operand so the submit path stays uniform."""
    from jax.sharding import PartitionSpec as P
    has_cand = prog[3]
    arg_rows = prog[4]
    # roles are explicit: the planner marked row-aligned leaf args;
    # ids/values axes are always row-aligned; cand is row-aligned only
    # when a real candidate mask was shipped (else it is a zeros(1) stub)
    in_specs = (P(), P(axis) if has_cand else P(), P(None, None),
                tuple(P(axis) for _ in ids_tuple),
                tuple(P(axis) for _ in values_tuple),
                tuple(P(None, axis) if r == 2 else
                      (P(axis) if r else P()) for r in arg_rows))

    def fn(nrows, cp, sm, ids, vals, leaf_args):
        return _fused_local(prog, strides, nb, n_values, axis, nrows,
                            cp, sm, ids, vals, leaf_args)

    return K.shard_map_fn()(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=(P(), P(axis)))(
        nrows, cand_packed, seg_map, ids_tuple, values_tuple, args)


# ---------------- residue: host settles the maybe rows ----------------

def _residue_partials(f, bss, spec, layout, maybe_np: np.ndarray,
                      part=None) -> list:
    """Verify maybe rows with the filters' own host path and emit one
    partial per surviving row, keyed exactly like the device cells.

    part: the dispatched part — only consulted for 'seg' by-keys, whose
    component is the packed part's member ordinal for the block
    (PackedPart.segment_of_block)."""
    from ..logsql.matchers import parse_number
    from ..logsql.stats_funcs import format_number
    from .stats_device import SYNTH_EMPTY, SYNTH_LEN
    partials = []
    for bi, bs in bss.items():
        start = layout.starts[bi]
        n = bs.nrows
        sel = maybe_np[start:start + n]
        if not sel.any():
            continue
        bm = sel.copy()
        f.apply_to_block(bs, bm)
        rows = np.nonzero(bm)[0]
        if not rows.size:
            continue
        ts = None
        val_cache: dict[str, list] = {}

        def vals(field):
            got = val_cache.get(field)
            if got is None:
                got = val_cache[field] = bs.values(field)
            return got

        for i in rows:
            key_parts = []
            uniq = {}
            for bk in spec.by:
                if bk.kind == "seg":
                    key_parts.append(("s", part.segment_of_block(bi)))
                elif bk.kind == "time":
                    if ts is None:
                        ts = bs.timestamps()
                    t = int(ts[i])
                    vb = (t - bk.offset) // bk.step * bk.step + bk.offset
                    key_parts.append(("t", vb))
                elif bk.kind == "numbucket":
                    v = parse_number(vals(bk.name)[i])
                    vb = np.floor((v - bk.foff) / bk.fstep) * bk.fstep \
                        + bk.foff
                    key_parts.append(("v", format_number(vb)))
                else:
                    key_parts.append(("v", vals(bk.name)[i]))
            for fld in spec.uniq_fields:
                uniq[fld] = vals(fld)[i]
            qv = {}
            for fld in spec.quantile_fields:
                qv[fld] = parse_number(vals(fld)[i])
            fs = {}
            for fld in spec.value_fields:
                if fld.startswith(SYNTH_LEN):
                    v = len(vals(fld[len(SYNTH_LEN):])[i])
                elif fld.startswith(SYNTH_EMPTY):
                    v = 1 if vals(fld[len(SYNTH_EMPTY):])[i] == "" else 0
                else:
                    v = int(vals(fld)[i])
                fs[fld] = (v, v, v)
            partials.append((tuple(key_parts), 1, fs, uniq, qv))
    return partials


# ---------------- entry ----------------

def _stage_cand_mask(runner, part, bss, layout):
    """Candidate-row mask for a dispatch: all-blocks-candidate uses the
    cheap rows<nrows form (no upload); partial candidate sets ship as
    packed bits, cached per (part, block-set)."""
    import jax.numpy as jnp
    all_cand = len(bss) == part.num_blocks
    if all_cand:
        return jnp.zeros(1, dtype=jnp.uint8), False
    ckey = (part.uid, "#cand", tuple(sorted(bss)))
    with runner._key_lock(ckey):
        cm = runner.cache.get(ckey)
        if cm is None:
            m = np.zeros(layout.nrows_padded, dtype=bool)
            for bi in bss:
                s = layout.starts[bi]
                m[s:s + part.block_rows(bi)] = True
            cm = _CandMask(packed=runner._put(np.packbits(m)),
                           nbytes=layout.nrows_padded // 8)
            runner.cache.put(ckey, cm)
    return cm.packed, True


class _Ready:
    """A pending-result shim for values already materialized (constant
    trees, host-gated parts): harvest() is a no-op handoff, so callers
    drive one protocol whether or not a dispatch is in flight."""

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def harvest(self, sync=None):
        return self._value


class _StatsPending:
    """An in-flight fused filter|stats dispatch.

    Holds the asynchronous jax result arrays; nothing blocks until
    harvest(), so a caller can keep several parts' dispatches
    outstanding (tpu/pipeline.py) and materialize them in submission
    order.  sync: host-materialization hook (np.asarray semantics) —
    the pipeline passes a timed wrapper so host-sync wait is counted."""

    __slots__ = ("runner", "f", "part", "bss", "spec", "asm", "handled",
                 "flat", "mp")

    def __init__(self, runner, f, part, bss, spec, asm, handled, flat,
                 mp):
        self.runner = runner
        self.f = f
        self.part = part
        self.bss = bss
        self.spec = spec
        self.asm = asm
        self.handled = handled
        self.flat = flat
        self.mp = mp

    def harvest(self, sync=None):
        sync = sync or np.asarray
        asm, spec = self.asm, self.spec
        flat = np.asarray(sync(self.flat))
        any_maybe = bool(flat[-1])
        if spec.value_fields:
            stats = flat[:-1].reshape(len(spec.value_fields), 7, asm.nb)
            counts = stats[0][0]
            stats_np = {fld: stats[k] for k, fld in
                        enumerate(spec.value_fields)}
        else:
            counts = flat[:-1]
            stats_np = {}
        partials = self.runner._partials_from_counts(asm, counts,
                                                     stats_np)
        if any_maybe:
            maybe_np = np.unpackbits(np.asarray(sync(self.mp))) \
                [:asm.layout.nrows_padded].astype(bool)
            partials.extend(_residue_partials(self.f, self.bss, spec,
                                              asm.layout, maybe_np,
                                              part=self.part))
        return {}, self.handled, partials


def fused_stats_submit(runner, f, part, bss, spec, asm):
    """Plan + DISPATCH the single fused filter|stats program without
    materializing anything; returns a pending handle (harvest() ->
    (bms, handled, partials)) or None when the shape declines.

    asm: the runner's assembled stats axes (AxesAssembly).  Requires
    every candidate block to be stats-eligible (the fused path never
    routes blocks through the row pipeline)."""
    import jax.numpy as jnp
    layout = asm.layout
    if any(any(bi not in el for el in asm.eligibility) for bi in bss):
        return None
    planner = _Planner(runner, part, bss, layout)
    try:
        tree = planner.plan(f)
    except _NoFuse:
        return None

    handled = set(bss)
    if tree == ("false",):
        return _Ready(({}, handled, []))

    cand_packed, has_cand = _stage_cand_mask(runner, part, bss, layout)
    # prog slots 5/6: segment count of a packed super-dispatch and the
    # VL_PALLAS gate for the seg-major count kernel — static, so the
    # jitted program specializes per (pack size, gate) like every other
    # static knob (stats_seg.py)
    seg_pallas = bool(asm.nseg) and config.env("VL_PALLAS") == "1"
    prog = (tree, layout.nrows_padded, planner.has_maybe, has_cand,
            tuple(planner.arg_rows), asm.nseg, seg_pallas)
    seg_map = jnp.zeros((1, 1), dtype=jnp.int32)
    if asm.nseg:
        seg_map = runner._stage_seg_slots(part, layout).ids
    values_tuple = tuple(asm.numerics[fld].values
                         for fld in spec.value_fields)
    runner._bump("device_calls")
    runner._bump("stats_dispatches")
    runner._bump("fused_dispatches")
    runner._bump_max("stats_onehot_width",
                     asm.nb // max(asm.nseg, 1))
    runner._kind("fused_stats")
    if asm.nseg:
        runner._kind("fused_stats_seg")
    if spec.uniq_fields:
        runner._kind("fused_uniq")
    if spec.quantile_fields:
        runner._kind("fused_quantile")
    flat, mp = runner._dispatch_fused(
        prog, asm.strides, asm.nb, len(values_tuple),
        jnp.int32(layout.nrows), cand_packed, seg_map, asm.ids_tuple,
        values_tuple, tuple(planner.args))
    return _StatsPending(runner, f, part, bss, spec, asm, handled, flat,
                         mp)




# ---------------- fused filter | sort-topk prefilter ----------------

@partial(jax.jit, static_argnames=("prog", "k", "desc", "nseg"))
def _topk_dispatch(prog, k, desc, nseg, nrows, cand_packed, seg_ids,
                   seg_map, values, args):
    """One device call: filter tree -> top-k threshold -> packed row sets.

    values: uint32[RLp] offsets from the part's column minimum (the same
    staging the stats path uses); the threshold is the k-th best key
    among DEFINITE filter matches, and the return is
    (packed definite rows >= threshold, packed maybe rows >= threshold)
    — see sort_device.py for the soundness argument.  Scores ride int32
    (eligibility caps vmax-vmin below 2**31-2); -1 marks non-candidates,
    so a part with fewer than k matches degenerates to the full match
    set.  Runs unchanged over mesh-sharded inputs (GSPMD inserts the
    top_k gather; only the packed bits come back).

    nseg > 0: a packed super-dispatch — members gather into their own
    padded rows of the seg slot grid (seg_map int32[S, Lp], Lp >= k;
    stats_seg.build_seg_slot_map) and ONE batched lax.top_k over the
    slot axis yields every member's k-th-best threshold at once, which
    scatters back per row through seg_ids.  Each member gets exactly
    the threshold its own single-part dispatch would have computed
    (padding slots score -1, the same sentinel as non-matches), so the
    harvested per-member candidate sets are bit-identical to the
    serial per-part walk — and the k-selection work is the members'
    own padded slots, LESS than a per-part dispatch's chunk-padded
    scan.  nseg == 0: seg_ids/seg_map are ignored zeros stubs.
    """
    import jax.numpy as jnp
    tree, _rlp, has_maybe, has_cand = prog[:4]
    rl = values.shape[0]
    d, m = _eval_node(tree, args, rl)
    if has_cand:
        cand = _unpack_bits(cand_packed, rl)
    else:
        cand = jnp.arange(rl, dtype=jnp.int32) < nrows
    d = d & cand
    mv = (m & cand) if (has_maybe and m is not None) else None
    v = values.astype(jnp.int32)
    if not desc:
        v = jnp.int32((1 << 31) - 2) - v   # ascending: reverse the order
    if nseg == 0:
        s = jnp.where(d, v, jnp.int32(-1))
        kv = jax.lax.top_k(s, k)[0][k - 1]
        out_d = d & (s >= kv)
        if mv is not None:
            out_m = mv & (jnp.where(mv, v, jnp.int32(-1)) >= kv)
        else:
            out_m = jnp.zeros(rl, dtype=bool)
    else:
        s = jnp.where(d, v, jnp.int32(-1))
        safe = jnp.maximum(seg_map, 0)
        s2 = jnp.where(seg_map >= 0, s[safe], jnp.int32(-1))
        kv = jax.lax.top_k(s2, k)[0][:, k - 1]       # (S,) thresholds
        thr = kv[seg_ids.astype(jnp.int32)]          # scatter per row
        out_d = d & (s >= thr)
        if mv is not None:
            out_m = mv & (v >= thr)
        else:
            out_m = jnp.zeros(rl, dtype=bool)
    return (jnp.packbits(out_d.astype(jnp.uint8)),
            jnp.packbits(out_m.astype(jnp.uint8)))


def fused_topk_submit(runner, f, part, bss, spec):
    """Plan + DISPATCH the filter|sort-topk program without
    materializing anything; returns a pending handle (harvest() ->
    block_idx -> bitmap, the _FilterPending protocol — maybe rows above
    threshold settle through the filter's own host predicate), a _Ready
    result for constant-false trees, or None when the shape declines
    (caller falls back to ordinary filter evaluation).

    part may be a PackedPart (tpu/pipeline.py): its per-row segment ids
    stage like the stats seg axis and the dispatch k-selects per
    member, so flush-sized parts under `sort | head` stop paying one
    dispatch each."""
    import jax.numpy as jnp
    from .stats_device import MAX_ABS_TIMES_ROWS, MAX_STAT_ROWS
    layout = runner._stats_layout(part)
    if layout.nrows > MAX_STAT_ROWS:
        return None
    sn = runner._stage_numeric(part, spec.field, layout,
                               MAX_ABS_TIMES_ROWS)
    if sn is None or any(bi not in sn.eligible for bi in bss):
        return None
    if sn.vmax - sn.vmin > (1 << 31) - 2:
        return None                # int32 score space
    k = min(spec.k, layout.nrows_padded)
    nseg = 0
    seg_ids = jnp.zeros(1, dtype=jnp.int32)
    seg_map = jnp.zeros((1, 1), dtype=jnp.int32)
    if getattr(part, "num_segments", 0) > 1:
        sg = runner._stage_segments(part, layout)
        if sg is None:
            return None
        nseg = len(sg.values)
        seg_ids = sg.ids
        # the slot grid needs >= k slots per member for the batched
        # k-selection (padding slots carry the -1 sentinel)
        seg_map = runner._stage_seg_slots(part, layout, min_len=k).ids
    planner = _Planner(runner, part, bss, layout)
    try:
        tree = planner.plan(f)
    except _NoFuse:
        return None
    if tree == ("false",):
        return _Ready({bi: np.zeros(bss[bi].nrows, dtype=bool)
                       for bi in bss})

    cand_packed, has_cand = _stage_cand_mask(runner, part, bss, layout)
    prog = (tree, layout.nrows_padded, planner.has_maybe, has_cand,
            tuple(planner.arg_rows))
    runner._bump("device_calls")
    runner._bump("topk_dispatches")
    runner._kind("topk_seg" if nseg else "topk")
    dm, mm = runner._dispatch_topk(
        prog, k, spec.desc, nseg, jnp.int32(layout.nrows), cand_packed,
        seg_ids, seg_map, sn.values, tuple(planner.args))
    # the maybe vector is only meaningful when the program proved maybe
    # rows can exist; _FilterPending's harvest applies the same residue
    # discipline as the fused stats/filter paths
    return _FilterPending(runner, f, part, bss, layout, dm, mm,
                          planner.has_maybe)


def try_fused_topk(runner, f, part, bss, spec):
    """Synchronous shim over fused_topk_submit (single-part callers):
    block_idx -> bitmap covering EVERY candidate block (exactly the
    filter-matching rows at-or-above the part's k-th best key — a
    superset of the part's contribution to the global top-k), or None
    when the shape declines."""
    pending = fused_topk_submit(runner, f, part, bss, spec)
    if pending is None:
        return None
    return pending.harvest()


# ---------------- fused filter-only dispatch (row queries) ----------------

def _filter_local(prog, axis, nrows, cand_packed, args, rl):
    """Whole-filter-tree evaluation body: bit-packed (definite, maybe)
    row vectors.  axis/rl as in _fused_local (rl is this shard's rows)."""
    import jax.numpy as jnp
    tree, _rlp, has_maybe, has_cand = prog[:4]
    d, m = _eval_node(tree, args, rl)
    if has_cand:
        cand = _unpack_bits(cand_packed, rl)
    else:
        idx = jnp.arange(rl, dtype=jnp.int32)
        if axis is not None:
            idx = idx + jax.lax.axis_index(axis) * rl
        cand = idx < nrows
    d = d & cand
    if has_maybe and m is not None:
        mp = jnp.packbits((m & cand).astype(jnp.uint8))
    else:
        mp = jnp.zeros(1, dtype=jnp.uint8)
        if axis is not None:
            mp = K._vary(mp, (axis,))
    return jnp.packbits(d.astype(jnp.uint8)), mp


@partial(jax.jit, static_argnames=("prog",))
def _filter_dispatch(prog, nrows, cand_packed, args):
    """One device call: the WHOLE filter tree -> bit-packed (definite,
    maybe) row vectors — the row-query analogue of _fused_dispatch.

    Round 3 evaluated row-query trees leaf-by-leaf (one dispatch per
    device leaf, host AND/OR combination); this compiles the same
    three-valued program the stats/topk paths already trust into a
    single dispatch per part whose only downloads are two R/8-byte
    packed vectors, which is what makes the dispatch window's
    submit/harvest split (tpu/pipeline.py) worthwhile: one async
    handle per part instead of a host sync per leaf."""
    return _filter_local(prog, None, nrows, cand_packed, args, prog[1])


@partial(jax.jit, static_argnames=("prog", "mesh", "axis"))
def _filter_dispatch_mesh(mesh, axis, prog, nrows, cand_packed, args):
    """The filter-only program under shard_map: each device evaluates
    its row stripe, the packed (definite, maybe) vectors concatenate
    along the row axis (rl per shard is a multiple of 8, so the bit
    packing aligns across shard boundaries)."""
    from jax.sharding import PartitionSpec as P
    has_cand = prog[3]
    arg_rows = prog[4]
    rl = prog[1] // int(mesh.devices.size)
    in_specs = (P(), P(axis) if has_cand else P(),
                tuple(P(None, axis) if r == 2 else
                      (P(axis) if r else P()) for r in arg_rows))

    def fn(nrows, cp, leaf_args):
        return _filter_local(prog, axis, nrows, cp, leaf_args, rl)

    return K.shard_map_fn()(fn, mesh=mesh, in_specs=in_specs,
                            out_specs=(P(axis), P(axis)))(
        nrows, cand_packed, args)


class _FilterPending:
    """An in-flight fused filter dispatch for a row query; harvest()
    returns block_idx -> bool bitmap, bit-identical to the CPU path
    (maybe rows are settled by the filter tree's own apply_to_block,
    the same residue discipline as try_fused/try_fused_topk)."""

    __slots__ = ("runner", "f", "part", "bss", "layout", "dm", "mm",
                 "has_maybe")

    def __init__(self, runner, f, part, bss, layout, dm, mm, has_maybe):
        self.runner = runner
        self.f = f
        self.part = part
        self.bss = bss
        self.layout = layout
        self.dm = dm
        self.mm = mm
        self.has_maybe = has_maybe

    def harvest(self, sync=None):
        sync = sync or np.asarray
        rlp = self.layout.nrows_padded
        dm = np.unpackbits(np.asarray(sync(self.dm)))[:rlp].astype(bool)
        mm = None
        if self.has_maybe:
            mm = np.unpackbits(np.asarray(sync(self.mm)))[:rlp] \
                .astype(bool)
        bms = {}
        for bi, bs in self.bss.items():
            start = self.layout.starts[bi]
            n = bs.nrows
            bm = dm[start:start + n].copy()
            if mm is not None:
                sel = mm[start:start + n]
                if sel.any():
                    vbm = sel.copy()
                    self.f.apply_to_block(bs, vbm)
                    bm |= vbm
            bms[bi] = bm
        return bms


def fused_filter_enabled() -> bool:
    """The VL_FUSED_FILTER kill-switch, shared by the dispatch gate and
    the pipeline's prefetch-mode decision so the two can never diverge
    (prefetching #fl layout staging for a path that will dispatch
    per-leaf would waste the upload AND leave the real staging cold)."""
    return config.env_flag("VL_FUSED_FILTER")


def fused_filter_submit(runner, f, part, bss):
    """Single-dispatch evaluation of a row query's whole filter tree.

    Returns a pending handle (harvest() -> block_idx -> bitmap), a
    _Ready result for constant trees, or None when the shape declines
    (caller falls back to the per-leaf run_part path).  Kill-switch:
    VL_FUSED_FILTER=0 restores the round-3 per-leaf behavior."""
    import jax.numpy as jnp
    from .stats_device import MAX_STAT_ROWS
    if not fused_filter_enabled():
        return None
    layout = runner._stats_layout(part)
    if layout.nrows > MAX_STAT_ROWS:
        return None
    planner = _Planner(runner, part, bss, layout)
    try:
        tree = planner.plan(f)
    except _NoFuse:
        return None
    if tree == ("false",):
        return _Ready({bi: np.zeros(bss[bi].nrows, dtype=bool)
                       for bi in bss})
    if tree == ("true",):
        return _Ready({bi: np.ones(bss[bi].nrows, dtype=bool)
                       for bi in bss})
    cand_packed, has_cand = _stage_cand_mask(runner, part, bss, layout)
    prog = (tree, layout.nrows_padded, planner.has_maybe, has_cand,
            tuple(planner.arg_rows))
    runner._bump("device_calls")
    runner._bump("filter_dispatches")
    runner._kind("fused_filter")
    dm, mm = runner._dispatch_filter(prog, jnp.int32(layout.nrows),
                                     cand_packed, tuple(planner.args))
    return _FilterPending(runner, f, part, bss, layout, dm, mm,
                          planner.has_maybe)
