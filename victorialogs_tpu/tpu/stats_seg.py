"""Segment-major stats kernels for packed super-dispatches.

The PR 3 segment axis rode the generic bucket product: a pack of S
member parts prepended a ``ByKey('seg')`` axis, so the one-hot
compare-and-reduce in kernels.stats_count_local widened from
(STATS_CHUNK, buckets) to (STATS_CHUNK, S*buckets) — every chunk's VMEM
tile and VPU compare count scaled with the pack size, and MAX_BUCKETS
gated the MULTIPLIED product, so wide group-bys taxed (or declined)
packing exactly on the shape packing exists for.

This module is the segment-major replacement: the segment axis is
reduced OUTSIDE the bucket one-hot —

- counts/sums: TWO small one-hots, (C, S) segment membership and
  (C, buckets) bucket membership, contracted on the row axis as an
  (S, C) x (C, B) matmul (MXU work; exact — per-chunk cell counts and
  uint8 plane sums stay < 2**24, the f32 mantissa);
- min/max: a static per-segment unroll of the classic (C, B) masked
  reduction (S <= VL_PACK_PARTS, so the unroll is a handful of steps
  and peak VMEM per step stays (C, B), not (C, S*B)).

The accumulator is the [S, buckets] layout the harvest already decodes
(the 'seg' axis was FIRST in the by order, so its stride equals the
base bucket product — the flattened seg-major result is bit-identical
to what the widened kernel produced), and the per-chunk working-set
width no longer scales with the pack size.  tpu/batch._assemble_axes
therefore stops counting the segment axis toward MAX_BUCKETS.

A Pallas variant of the count reduction (the dominant shape: plain
``count()`` group-bys) is gated behind VL_PALLAS=1 like every Pallas
kernel in this repo (kernels_pallas.py — never on by default, parity
checked in a clean subprocess via tests/pallas_check.py); the values
variant stays jnp until profiled on hardware.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from . import kernels as K
from .kernels import STATS_CHUNK, _vary
from .kernels_pallas import _VMEM, PALLAS_AVAILABLE, pl

# Pallas tile geometry: segments pad to one f32 sublane tile, buckets
# to the 128-lane vector width (same discipline as kernels_pallas).
SEG_TILE = 8
LANE = 128


def _onehots(si, bi, mi, segs, buckets):
    """The two small one-hot operands of the seg-major contraction."""
    seg1h = (si[:, None] == segs[None, :]) & mi[:, None]      # (C, S)
    b1h = bi[:, None] == buckets[None, :]                     # (C, B)
    return seg1h, b1h


def stats_count_seg_local(seg_ids: jnp.ndarray, bucket_ids: jnp.ndarray,
                          mask: jnp.ndarray, nseg: int, nb: int,
                          vary_axes=(), use_pallas: bool = False,
                          interpret: bool = False) -> jnp.ndarray:
    """Masked per-(segment, bucket) row counts, flattened seg-major.

    seg_ids/bucket_ids: int-typed [R] (R a STATS_CHUNK multiple);
    mask: bool[R] (padding rows False).  Returns uint32[nseg*nb] in the
    exact order kernels.stats_count_local produced for the widened
    combined id (seg stride == nb) — the host decode is unchanged."""
    if use_pallas and PALLAS_AVAILABLE and nseg <= SEG_TILE:
        return stats_count_seg_pallas(seg_ids, bucket_ids, mask, nseg,
                                      nb, interpret=interpret)
    sg = seg_ids.astype(jnp.int32).reshape(-1, STATS_CHUNK)
    b = bucket_ids.astype(jnp.int32).reshape(-1, STATS_CHUNK)
    m = mask.reshape(-1, STATS_CHUNK)
    segs = jnp.arange(nseg, dtype=jnp.int32)
    buckets = jnp.arange(nb, dtype=jnp.int32)

    def body(acc, xs):
        si, bi, mi = xs
        seg1h, b1h = _onehots(si, bi, mi, segs, buckets)
        # (S, C) x (C, B) matmul: per-chunk cell counts <= STATS_CHUNK
        # < 2**24, exact in the f32 contraction
        acc = acc + jnp.einsum("cs,cb->sb", seg1h.astype(jnp.float32),
                               b1h.astype(jnp.float32)).astype(jnp.uint32)
        return acc, None

    acc, _ = jax.lax.scan(
        body, _vary(jnp.zeros((nseg, nb), jnp.uint32), vary_axes),
        (sg, b, m))
    return acc.reshape(-1)


def stats_values_seg_local(values: jnp.ndarray, seg_ids: jnp.ndarray,
                           bucket_ids: jnp.ndarray, mask: jnp.ndarray,
                           nseg: int, nb: int, vary_axes=()):
    """Seg-major count/sum/min/max partials for one uint32 value column.

    Returns (cnt, sums[4, .], lo, hi), each flattened over nseg*nb in
    seg-major order — drop-in for kernels.stats_values_local over the
    widened combined id, with the same exactness contract (uint8 byte
    planes contracted in f32, per-chunk plane sums < 2**24)."""
    v = values.reshape(-1, STATS_CHUNK)
    sg = seg_ids.astype(jnp.int32).reshape(-1, STATS_CHUNK)
    b = bucket_ids.astype(jnp.int32).reshape(-1, STATS_CHUNK)
    m = mask.reshape(-1, STATS_CHUNK)
    segs = jnp.arange(nseg, dtype=jnp.int32)
    buckets = jnp.arange(nb, dtype=jnp.int32)
    u32max = jnp.uint32(0xFFFFFFFF)

    def body(carry, xs):
        cnt, sums, lo, hi = carry
        vi, si, bi, mi = xs
        seg1h, b1h = _onehots(si, bi, mi, segs, buckets)
        seg_f = seg1h.astype(jnp.float32)
        b_f = b1h.astype(jnp.float32)
        cnt = cnt + jnp.einsum("cs,cb->sb", seg_f,
                               b_f).astype(jnp.uint32)
        # four byte planes, each its own (S, C) x (C, B) contraction of
        # the plane-weighted bucket one-hot — peak working set stays
        # (C, max(S, B)), never (C, S*B)
        ps = []
        for p in range(4):
            plane = ((vi >> (8 * p)) & 0xFF).astype(jnp.float32)
            ps.append(jnp.einsum("cs,cb->sb", seg_f,
                                 b_f * plane[:, None]))
        sums = sums + jnp.stack(ps, axis=0).astype(jnp.uint32)
        # min/max: static per-segment unroll of the classic masked
        # reduction (S <= VL_PACK_PARTS)
        los = []
        his = []
        for s in range(nseg):
            sel = b1h & seg1h[:, s][:, None]
            los.append(jnp.min(jnp.where(sel, vi[:, None], u32max),
                               axis=0))
            his.append(jnp.max(jnp.where(sel, vi[:, None],
                                         jnp.uint32(0)), axis=0))
        lo = jnp.minimum(lo, jnp.stack(los, axis=0))
        hi = jnp.maximum(hi, jnp.stack(his, axis=0))
        return (cnt, sums, lo, hi), None

    init = tuple(
        _vary(a, vary_axes)
        for a in (jnp.zeros((nseg, nb), jnp.uint32),
                  jnp.zeros((4, nseg, nb), jnp.uint32),
                  jnp.full((nseg, nb), u32max),
                  jnp.zeros((nseg, nb), jnp.uint32)))
    (cnt, sums, lo, hi), _ = jax.lax.scan(body, init, (v, sg, b, m))
    return (cnt.reshape(-1), sums.reshape(4, -1), lo.reshape(-1),
            hi.reshape(-1))


# ---------------- slot-map (segment-aligned) kernels ----------------
#
# The scan kernels above reduce every segment against every row chunk
# (the unroll/min-max term costs S passes per chunk), which is what
# shard_map's manual row stripes require — but a single-device dispatch
# can do better: gather the pack's rows into a [S, Lp] SEGMENT-ALIGNED
# grid (members are contiguous row ranges of the pack layout, so the
# map is a host-built static index table, cached per pack like any
# staging), then reduce each member against only ITS OWN padded slots.
# Total reduction work drops from S * R_padded to ~R (the members' own
# rows), the (S, SLOT_CHUNK, B) one-hot tile matches the classic
# (STATS_CHUNK, B) footprint, and results stay bit-identical.

SLOT_CHUNK = 1024      # slots per scan step; S*SLOT_CHUNK ~ STATS_CHUNK


def pad_slots(n: int, k: int = 0) -> int:
    """Slot-axis length: a SLOT_CHUNK multiple >= max(n, k, 1) (k: a
    topk dispatch needs at least k slots per member to select on)."""
    need = max(n, k, 1)
    return ((need + SLOT_CHUNK - 1) // SLOT_CHUNK) * SLOT_CHUNK


def build_seg_slot_map(part, layout, min_len: int = 0):
    """int32[S, Lp] row-index table of a packed part: row idx of member
    s's slot j, -1 on padding slots.  Members occupy contiguous row
    ranges of the pack layout (blocks concatenate in member order), so
    the table is pure host arithmetic over the block map."""
    import numpy as np
    nseg = part.num_segments
    starts = []
    lens = []
    for mi in range(nseg):
        first = part.block_offset(mi)
        nxt = part.block_offset(mi + 1) if mi + 1 < nseg else \
            part.num_blocks
        starts.append(layout.starts[first])
        lens.append(sum(part.block_rows(bi) for bi in range(first,
                                                            nxt)))
    lp = pad_slots(max(lens), min_len)
    idx = np.full((nseg, lp), -1, dtype=np.int32)
    for mi, (st, ln) in enumerate(zip(starts, lens)):
        idx[mi, :ln] = np.arange(st, st + ln, dtype=np.int32)
    return idx


def _slot_gather(seg_map, arr, fill=None):
    """arr[seg_map] with -1 slots masked (bool arrs -> False)."""
    valid = seg_map >= 0
    safe = jnp.maximum(seg_map, 0)
    got = arr[safe]
    if fill is None:
        return got, valid
    return jnp.where(valid, got, fill), valid


def stats_count_slots(seg_map, bucket_ids, mask, nb: int):
    """Seg-major masked counts via the slot grid; uint32[S*nb]."""
    s, _lp = seg_map.shape
    b2, valid = _slot_gather(seg_map, bucket_ids.astype(jnp.int32))
    m2 = mask[jnp.maximum(seg_map, 0)] & valid
    buckets = jnp.arange(nb, dtype=jnp.int32)
    bc = jnp.moveaxis(b2.reshape(s, -1, SLOT_CHUNK), 1, 0)
    mc = jnp.moveaxis(m2.reshape(s, -1, SLOT_CHUNK), 1, 0)

    def body(acc, xs):
        bi, mi = xs
        oh = (bi[:, :, None] == buckets[None, None, :]) \
            & mi[:, :, None]
        return acc + jnp.sum(oh.astype(jnp.uint32), axis=1), None

    acc, _ = jax.lax.scan(body, jnp.zeros((s, nb), jnp.uint32),
                          (bc, mc))
    return acc.reshape(-1)


def stats_values_slots(values, seg_map, bucket_ids, mask, nb: int):
    """Seg-major count/sum/min/max via the slot grid — each member
    reduces only its own slots; exactness contract as the scan form
    (per-cell plane sums <= 255 * SLOT_CHUNK < 2**24 in f32)."""
    s, _lp = seg_map.shape
    safe = jnp.maximum(seg_map, 0)
    valid = seg_map >= 0
    v2 = values[safe]
    b2 = bucket_ids.astype(jnp.int32)[safe]
    m2 = mask[safe] & valid
    buckets = jnp.arange(nb, dtype=jnp.int32)
    u32max = jnp.uint32(0xFFFFFFFF)
    vc = jnp.moveaxis(v2.reshape(s, -1, SLOT_CHUNK), 1, 0)
    bc = jnp.moveaxis(b2.reshape(s, -1, SLOT_CHUNK), 1, 0)
    mc = jnp.moveaxis(m2.reshape(s, -1, SLOT_CHUNK), 1, 0)

    def body(carry, xs):
        cnt, sums, lo, hi = carry
        vi, bi, mi = xs                              # (S, CL) each
        oh = (bi[:, :, None] == buckets[None, None, :]) \
            & mi[:, :, None]                         # (S, CL, B)
        cnt = cnt + jnp.sum(oh.astype(jnp.uint32), axis=1)
        ohf = oh.astype(jnp.float32)
        ps = []
        for p in range(4):
            plane = ((vi >> (8 * p)) & 0xFF).astype(jnp.float32)
            ps.append(jnp.einsum("sc,scb->sb", plane, ohf))
        sums = sums + jnp.stack(ps, axis=0).astype(jnp.uint32)
        lo = jnp.minimum(lo, jnp.min(
            jnp.where(oh, vi[:, :, None], u32max), axis=1))
        hi = jnp.maximum(hi, jnp.max(
            jnp.where(oh, vi[:, :, None], jnp.uint32(0)), axis=1))
        return (cnt, sums, lo, hi), None

    init = (jnp.zeros((s, nb), jnp.uint32),
            jnp.zeros((4, s, nb), jnp.uint32),
            jnp.full((s, nb), u32max),
            jnp.zeros((s, nb), jnp.uint32))
    (cnt, sums, lo, hi), _ = jax.lax.scan(body, init, (vc, bc, mc))
    return (cnt.reshape(-1), sums.reshape(4, -1), lo.reshape(-1),
            hi.reshape(-1))


# ---------------- Pallas count variant (VL_PALLAS gate) ----------------

def _count_seg_kernel(seg_ref, b_ref, m_ref, out_ref, *, nseg: int,
                      nbp: int):
    """One (STATS_CHUNK, 1) id-column tile: both one-hots built from
    broadcast iotas (dense VPU compares, no gather) and contracted on
    the MXU; the [SEG_TILE, nbp] accumulator lives in the revisited
    output block (same multi-step accumulation discipline as
    kernels_pallas, init on the first grid step)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    sg = seg_ref[:, :]                         # int32[C, 1]
    bi = b_ref[:, :]
    mi = m_ref[:, :]                           # int32[C, 1] 0/1
    c = sg.shape[0]
    seg_iota = jax.lax.broadcasted_iota(jnp.int32, (c, SEG_TILE), 1)
    b_iota = jax.lax.broadcasted_iota(jnp.int32, (c, nbp), 1)
    # padding segments/buckets never match a real id: rows land only in
    # their own (segment, bucket) cell, mask zeroes dead rows
    seg1h = ((sg == seg_iota) & (mi > 0)).astype(jnp.float32)
    b1h = (bi == b_iota).astype(jnp.float32)
    out_ref[:, :] += jax.lax.dot_general(
        seg1h, b1h, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@partial(jax.jit, static_argnames=("nseg", "nb", "interpret"))
def stats_count_seg_pallas(seg_ids, bucket_ids, mask, nseg: int,
                           nb: int, interpret: bool = False):
    """Pallas seg-major count; bit-identical to the jnp path (padded
    segments/buckets reduce to zero and are sliced off)."""
    r = seg_ids.shape[0]
    g = r // STATS_CHUNK
    nbp = ((nb + LANE - 1) // LANE) * LANE
    sg = seg_ids.astype(jnp.int32).reshape(r, 1)
    b = bucket_ids.astype(jnp.int32).reshape(r, 1)
    m = mask.astype(jnp.int32).reshape(r, 1)

    def spec(block, index_map):
        if interpret or _VMEM is None:
            return pl.BlockSpec(block, index_map)
        return pl.BlockSpec(block, index_map, memory_space=_VMEM)

    kernel = partial(_count_seg_kernel, nseg=nseg, nbp=nbp)
    out = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            spec((STATS_CHUNK, 1), lambda i: (i, 0)),
            spec((STATS_CHUNK, 1), lambda i: (i, 0)),
            spec((STATS_CHUNK, 1), lambda i: (i, 0)),
        ],
        out_specs=spec((SEG_TILE, nbp), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((SEG_TILE, nbp), jnp.float32),
        interpret=interpret,
    )(sg, b, m)
    return out[:nseg, :nb].astype(jnp.uint32).reshape(-1)


# ---------------- reference (differential-test oracle) ----------------

def stats_count_seg_reference(seg_ids, bucket_ids, mask, nseg: int,
                              nb: int) -> jnp.ndarray:
    """The widened-combined-id formulation this module replaces, kept
    as the parity oracle: seg stride == nb, one (C, S*B) one-hot."""
    combined = K.combine_ids(
        (seg_ids, bucket_ids), (nb, 1))
    return K.stats_count_local(combined, mask, nseg * nb)
