"""Pallas TPU scan kernels: VMEM-tiled string matching.

Why: the XLA `match_scan` kernel (kernels.py) expresses the windowed
compare as pat_len full-array slices, so XLA re-streams the (R, W) rows
matrix from HBM up to pat_len times.  This kernel tiles the matrix through
VMEM once — each (TILE_ROWS, W) tile is loaded a single time and ALL window
offsets are tested from on-chip memory — so HBM traffic drops from
pat_len×R×W to R×W and the scan becomes bandwidth-bound at one read of the
data (the VERDICT r1 #8 target).

Semantics are bit-identical to kernels.match_scan (same modes, same
word-boundary rules, 0xFF padding); tests/test_pallas.py diffs them
exhaustively in interpret mode, and the real-TPU path is gated behind
VL_PALLAS=1 until profiled on hardware (the axon tunnel was down for all
of round 2 — see BENCH notes).

Layout contract (caller pads; pallas_ok() checks):
  rows    uint8[R, W]   R % TILE_ROWS == 0, W % 128 == 0, 0xFF padded
  lengths int32[R]
returns bool[R].
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import kernels as K

# The pallas import itself can fail in environments where the axon
# sitecustomize pre-registered a partial tpu platform (checkify's lowering
# registration then sees an unknown 'tpu' platform).  Degrade to
# unavailable: every caller must check PALLAS_AVAILABLE.
try:
    from jax.experimental import pallas as pl
    try:
        from jax.experimental.pallas import tpu as pltpu
        _VMEM = pltpu.VMEM
    # vlint: allow-broad-except(pallas probe: any import failure = off)
    except Exception:  # pragma: no cover - slim builds
        _VMEM = None
    PALLAS_AVAILABLE = True
# vlint: allow-broad-except(pallas probe: any import failure = off)
except Exception:  # pragma: no cover
    pl = None
    _VMEM = None
    PALLAS_AVAILABLE = False

TILE_ROWS = 512
LANE = 128


def pallas_ok(r: int, w: int) -> bool:
    return r % TILE_ROWS == 0 and w % LANE == 0 and w >= LANE


def _scan_kernel(rows_ref, len_ref, pat_ref, out_ref, *, pat_len: int,
                 mode: int, starts_tok: bool, ends_tok: bool, w: int):
    """One (TILE_ROWS, W) tile: test every window offset from VMEM."""
    # Single VMEM read, then widen to int32: this Mosaic target supports
    # neither 8-bit vector compares nor 8-bit scalar extracts, so all
    # byte math runs as i32 lanes (the load itself stays uint8 in HBM —
    # traffic is still R×W bytes; widening happens on-chip).
    rows = rows_ref[:].astype(jnp.int32)    # int32[TR, W]
    tr = rows.shape[0]
    ff = jnp.int32(0xFF)
    # lengths/out ride as (TR, 1) column blocks: Mosaic requires the last
    # two block dims to be (8k, 128k) or equal to the array dims, so a
    # column vector is the only legal per-tile 1-value-per-row layout —
    # and it matches the sublane-resident layout of a lane-axis reduction.

    def shifted(j):
        # rows shifted left by j columns, tail-filled with 0xFF (never a
        # pattern byte, so windows running off the end can't match)
        if j == 0:
            return rows
        return jnp.concatenate(
            [rows[:, j:], jnp.full((tr, j), ff, dtype=jnp.int32)], axis=1)

    acc = jnp.ones((tr, w), dtype=jnp.bool_)
    for j in range(pat_len):
        # pattern rides as int32 (Mosaic only extracts 32-bit scalars);
        # cast the scalar back down for the byte compare
        acc = jnp.logical_and(acc, shifted(j) == pat_ref[0, j])

    lengths = len_ref[:, :]                 # int32[TR, 1] — stay 2-D:
    # Mosaic's layout inference crashes on rank-1 intermediates here

    if mode in (K.MODE_EXACT, K.MODE_EXACT_PREFIX):
        hit = acc[:, 0:1]
        if mode == K.MODE_EXACT:
            hit = jnp.logical_and(hit, lengths == pat_len)
        else:
            hit = jnp.logical_and(hit, lengths >= pat_len)
        out_ref[:, :] = hit.astype(jnp.int8)
        return

    def is_word(b):
        return ((b >= ord("a")) & (b <= ord("z"))) | \
               ((b >= ord("A")) & (b <= ord("Z"))) | \
               ((b >= ord("0")) & (b <= ord("9"))) | \
               (b == ord("_")) | ((b >= 0x80) & (b != 0xFF))

    if starts_tok and mode in (K.MODE_PHRASE, K.MODE_PREFIX):
        prev = jnp.concatenate(
            [jnp.full((tr, 1), ff, dtype=jnp.int32), rows[:, :w - 1]],
            axis=1)
        acc = jnp.logical_and(acc, jnp.logical_not(is_word(prev)))
    if ends_tok and mode == K.MODE_PHRASE:
        nxt = shifted(pat_len)
        acc = jnp.logical_and(acc, jnp.logical_not(is_word(nxt)))

    # reduce through int32 — Mosaic rejects the bool any() relayout
    anyhit = jnp.max(acc.astype(jnp.int32), axis=1, keepdims=True)
    hit = jnp.logical_and(anyhit > 0, lengths >= pat_len)
    out_ref[:, :] = hit.astype(jnp.int8)


@partial(jax.jit, static_argnames=("pat_len", "mode", "starts_tok",
                                   "ends_tok", "interpret"))
def match_scan_pallas(rows: jnp.ndarray, lengths: jnp.ndarray,
                      pattern: jnp.ndarray, pat_len: int, mode: int,
                      starts_tok: bool, ends_tok: bool,
                      interpret: bool = False) -> jnp.ndarray:
    """Pallas drop-in for kernels.match_scan on aligned shapes."""
    vmem = None if interpret else _VMEM
    r, w = rows.shape
    assert pallas_ok(r, w), (r, w)
    g = r // TILE_ROWS
    lengths_col = lengths.reshape(r, 1).astype(jnp.int32)
    pat128 = jnp.zeros((1, LANE), dtype=jnp.int32)
    pat128 = pat128.at[0, :pat_len].set(pattern[:pat_len].astype(jnp.int32))

    kernel = partial(_scan_kernel, pat_len=pat_len, mode=mode,
                     starts_tok=starts_tok, ends_tok=ends_tok, w=w)

    def spec(block, index_map):
        if vmem is None:
            return pl.BlockSpec(block, index_map)
        return pl.BlockSpec(block, index_map, memory_space=vmem)

    out = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[
            spec((TILE_ROWS, w), lambda i: (i, 0)),
            spec((TILE_ROWS, 1), lambda i: (i, 0)),
            spec((1, LANE), lambda i: (0, 0)),
        ],
        out_specs=spec((TILE_ROWS, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((r, 1), jnp.int8),
        interpret=interpret,
    )(rows, lengths_col, pat128)
    return out.reshape(r).astype(jnp.bool_)


def pad_for_pallas(mat: np.ndarray, lengths: np.ndarray
                   ) -> tuple[np.ndarray, np.ndarray]:
    """Pad a staged (R, W) matrix to the pallas layout contract."""
    r, w = mat.shape
    rp = ((r + TILE_ROWS - 1) // TILE_ROWS) * TILE_ROWS
    wp = max(LANE, ((w + LANE - 1) // LANE) * LANE)
    if rp == r and wp == w:
        return mat, lengths
    out = np.full((rp, wp), 0xFF, dtype=np.uint8)
    out[:r, :w] = mat
    lens = np.zeros(rp, dtype=np.int32)
    lens[:r] = lengths[:r]
    return out, lens
