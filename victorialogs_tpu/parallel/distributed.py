"""Multi-chip query execution: blocks sharded over a device mesh, stats
partials reduced over ICI.

This maps the reference's two parallelism mechanisms (SURVEY.md §2.6) onto a
TPU mesh:

- intra-query data parallelism (N workers over a block channel —
  storage_search.go:1035-1067) -> a `blocks` mesh axis: each device scans its
  shard of the staged block batch;
- the stats remote/local pushdown split (pipe_stats.go:55-60, mergeState over
  exported states) -> `jax.lax.psum` over ICI: per-device partial aggregates
  are reduced in-network, the host only finalizes.

The step below is the distributed analogue of a training step: jit once over
the mesh, run per staged batch.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import tracing
from ..tpu import kernels as K
from ..tpu.batch import BatchRunner

BLOCK_AXIS = "blocks"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """Build the block-parallel mesh.

    devices: explicit device list (e.g. a virtual CPU world); defaults to
    jax.devices().  Raises when fewer than n_devices are attached instead of
    silently building a smaller mesh — callers that want a virtual mesh must
    provision one (see __graft_entry__.dryrun_multichip).
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devs)}; provision a "
                f"virtual CPU world with JAX_PLATFORMS=cpu "
                f"XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{n_devices}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (BLOCK_AXIS,))


@partial(jax.jit, static_argnames=("pat_len", "mode", "starts_tok",
                                   "ends_tok", "num_buckets", "mesh"))
def distributed_scan_count(mesh, rows, lengths,
                           bucket_ids, pattern, pat_len: int, mode: int,
                           starts_tok: bool, ends_tok: bool,
                           num_buckets: int):
    """One distributed query step.

    rows: uint8[B, R, W] — B fixed-width blocks sharded across the mesh's
    block axis; lengths: int32[B, R];
    bucket_ids: int32[B] — per-BLOCK stats group (e.g. the block's time
    bucket; blocks are the stats unit here since rows within a block share
    a stream and close timestamps);
    returns (match bitmaps bool[B, R], total count, per-bucket counts) with
    the two aggregates psum-reduced across devices.
    """

    def per_block(rw, lens):
        bm = K.match_scan(rw, lens, pattern, pat_len, mode, starts_tok,
                          ends_tok)
        return bm, jnp.sum(bm.astype(jnp.int32))

    def shard_fn(rows, lengths, bucket_ids):
        bms, cnts = jax.vmap(per_block)(rows, lengths)
        # stats partials merge over ICI — the psum analogue of mergeState
        total = jax.lax.psum(jnp.sum(cnts), BLOCK_AXIS)
        # per-bucket counts: one-hot matmul instead of segment ops (scatter
        # serializes on TPU; a (B, num_buckets) one-hot contraction rides
        # the MXU instead)
        onehot = jax.nn.one_hot(bucket_ids, num_buckets, dtype=jnp.float32)
        hist = jax.lax.psum(
            jnp.einsum("b,bk->k", cnts.astype(jnp.float32), onehot),
            BLOCK_AXIS)
        return bms, total, hist.astype(jnp.int32)

    spec = P(BLOCK_AXIS)
    return K.shard_map_fn()(
        shard_fn, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, P(), P()))(rows, lengths, bucket_ids)


def stage_block_batch(blocks: list[tuple[np.ndarray, np.ndarray, np.ndarray]],
                      n_devices: int):
    """Pad a list of (arena, offsets, lengths) into fixed-width batch
    tensors whose block count divides the mesh size.  Returns
    (rows uint8[B, R, W], lengths int32[B, R], rows_bucket)."""
    from ..tpu.kernels import pad_bucket
    from ..tpu.layout import to_fixed_width, row_width_bucket
    rb = pad_bucket(max(max((o.shape[0] for _a, o, _l in blocks),
                            default=1), 1), minimum=1024)
    w = max(row_width_bucket(int(l.max()) if l.size else 0)
            for _a, _o, l in blocks)
    b = len(blocks)
    bpad = ((b + n_devices - 1) // n_devices) * n_devices
    rows = np.full((bpad, rb, w), 0xFF, dtype=np.uint8)
    lengths = np.zeros((bpad, rb), dtype=np.int32)
    for i, (a, o, l) in enumerate(blocks):
        mat, _wi, _overflow = to_fixed_width(a, o, l, rb, width=w)
        rows[i] = mat
        lengths[i, :l.shape[0]] = np.minimum(l, w - 1).astype(np.int32)
    return rows, lengths, rb


def shard_batch(mesh: Mesh, *arrays):
    """Device-put batch tensors with the block axis sharded over the mesh."""
    sharding = NamedSharding(mesh, P(BLOCK_AXIS))
    return tuple(jax.device_put(a, sharding) for a in arrays)


# ---------------- the multi-chip product runner ----------------

@partial(jax.jit, static_argnames=("num_buckets", "strides", "mesh"))
def _stats_values_mesh(mesh, values, ids_tuple, strides, mask,
                       num_buckets):
    """Sharded stats partials: each device reduces its row shard with the
    same chunked kernel body, then count/sums ride psum and min/max ride
    pmin/pmax over ICI — the mesh analogue of the reference's mergeState
    (pipe_stats.go:354-377)."""
    def shard_fn(v, ids, m):
        b = K.combine_ids(ids, strides)
        cnt, sums, lo, hi = K.stats_values_local(v, b, m, num_buckets,
                                                 vary_axes=(BLOCK_AXIS,))
        cnt = jax.lax.psum(cnt, BLOCK_AXIS)
        sums = jax.lax.psum(sums, BLOCK_AXIS)
        lo = jax.lax.pmin(lo, BLOCK_AXIS)
        hi = jax.lax.pmax(hi, BLOCK_AXIS)
        return K.pack_stats(cnt, sums, lo, hi)

    spec = P(BLOCK_AXIS)
    return K.shard_map_fn()(
        shard_fn, mesh=mesh,
        in_specs=(spec, tuple(spec for _ in ids_tuple), spec),
        out_specs=P())(values, ids_tuple, mask)


@partial(jax.jit, static_argnames=("num_buckets", "strides", "mesh"))
def _stats_count_mesh(mesh, ids_tuple, strides, mask, num_buckets):
    def shard_fn(ids, m):
        b = K.combine_ids(ids, strides)
        cnt = K.stats_count_local(b, m, num_buckets,
                                  vary_axes=(BLOCK_AXIS,))
        return jax.lax.psum(cnt, BLOCK_AXIS)

    spec = P(BLOCK_AXIS)
    return K.shard_map_fn()(
        shard_fn, mesh=mesh,
        in_specs=(tuple(spec for _ in ids_tuple), spec),
        out_specs=P())(ids_tuple, mask)


class MeshBatchRunner(BatchRunner):
    """BatchRunner over a device mesh: the PRODUCT multi-chip query path.

    Staged arrays (string matrices, numeric columns, bucket ids, masks)
    are device_put with their row axis sharded over the mesh, so:
    - filter scans (match_scan & friends) compile SPMD under jit — each
      device scans its row stripe, no collectives needed (the bitmap
      gathers on download);
    - stats partials run under shard_map with psum/pmin/pmax over ICI and
      only the (7, buckets) reduced result reaches the host.

    Single-device behavior is identical to BatchRunner (the sharding
    degenerates); engine.searcher drives both through the same interface.
    """

    # the fused single-dispatch path runs SPMD here: the program is
    # shard_mapped over the row axis with psum'd partials (ICI), so a
    # fused query is ONE collective dispatch across the whole mesh
    fused_enabled = True
    # always reduce on device: the point of the mesh runner is that
    # partials ride psum over ICI, however small the shard's share
    stats_host_threshold = 0

    def __init__(self, mesh: Mesh | None = None, **kw):
        super().__init__(**kw)
        # the mesh runner exists to run SPMD — the whole point is ICI
        # reductions, so the per-part cost gate never routes it to host
        # (an explicit VL_COST_FORCE still wins)
        if not self.cost.force:
            self.cost.force = "device"
        self.mesh = mesh if mesh is not None else make_mesh()
        self.ndev = int(self.mesh.devices.size)
        self.stats_shards = self.ndev
        self._row_sharding = NamedSharding(self.mesh, P(BLOCK_AXIS))
        self._replicated = NamedSharding(self.mesh, P())

    def _put(self, arr, row_axis: int = 0):
        # shard the row axis when it divides evenly (stats layouts always
        # do; string-staging row buckets do for power-of-two mesh sizes),
        # else replicate — correctness never depends on the placement.
        # row_axis=1: lane-major uint32[W/4, R] string staging.
        if arr.shape[row_axis] % self.ndev == 0:
            if row_axis == 0:
                return jax.device_put(arr, self._row_sharding)
            return jax.device_put(
                arr, NamedSharding(self.mesh, P(None, BLOCK_AXIS)))
        return jax.device_put(arr, self._replicated)

    def _put_replicated(self, arr):
        # block-axis arrays (bloom planes / keep-mask operands): every
        # shard probes the full block axis, so these never stripe —
        # matches the P() in_specs the fused mesh dispatch declares for
        # non-row args
        return jax.device_put(arr, self._replicated)

    def _trace_collective(self) -> None:
        """Mesh attribution on the active trace: fused dispatches here
        are ONE collective program over every device (psum/pmin/pmax
        over ICI), which a trace reader must be able to tell apart from
        the single-chip dispatch counts."""
        sp = tracing.current_span()
        if sp.enabled:
            sp.add("mesh_collective_dispatches")
            sp.set("mesh_devices", self.ndev)

    def _dispatch_fused(self, prog, strides, nb, n_values, nrows,
                        cand_packed, seg_map, ids_tuple, values_tuple,
                        args):
        from ..tpu.fused import _fused_dispatch_mesh
        self._trace_collective()
        return _fused_dispatch_mesh(self.mesh, BLOCK_AXIS, prog, strides,
                                    nb, n_values, nrows, cand_packed,
                                    seg_map, ids_tuple, values_tuple,
                                    args)

    def _dispatch_filter(self, prog, nrows, cand_packed, args):
        # row-query fused filter under shard_map: each device evaluates
        # its row stripe, packed (definite, maybe) bits concatenate over
        # the row axis.  Layouts are padded to STATS_CHUNK * ndev rows
        # (stats_shards), so stripes are whole and byte-aligned — this
        # holds for packed super-parts too (their layout rides the same
        # padding).  The async window (tpu/pipeline.py) drives this
        # exactly like the single-chip runner: submission issues the
        # collective dispatch, harvest materializes in order.
        from ..tpu.fused import _filter_dispatch_mesh
        self._trace_collective()
        return _filter_dispatch_mesh(self.mesh, BLOCK_AXIS, prog, nrows,
                                     cand_packed, args)

    def _dispatch_stats_count(self, ids_tuple, strides, mask, nb):
        return np.array(_stats_count_mesh(self.mesh, ids_tuple, strides,
                                          mask, nb))

    def _dispatch_stats_values(self, values, ids_tuple, strides, mask,
                               nb):
        return np.array(_stats_values_mesh(self.mesh, values, ids_tuple,
                                           strides, mask, nb))
