// Native host core for victorialogs_tpu (C ABI, loaded via ctypes).
//
// The reference is an AOT-compiled native binary; these are our equivalents
// of its hottest host paths (the device plane stays JAX/XLA):
//
//   vl_to_fixed_width      — staging transpose: packed string column ->
//                            (rows, W) 0xFF-padded matrix (the HBM layout;
//                            tpu/layout.py fallback is numpy fancy indexing)
//   vl_tokenize_arena      — word tokenizer over a packed column
//                            (lib/logstorage/tokenizer.go:34-148 semantics:
//                            ASCII alnum + '_' + any >=0x80 byte)
//   vl_unique_token_hashes — tokenize + xxh64 + dedupe in ONE pass, feeding
//                            bloom construction without materializing any
//                            Python token objects
//                            (bloomfilter.go:126-170 consumes hashes only)
//   vl_emit_ndjson         — columnar NDJSON serializer for the query emit
//                            hot path: per-column (arena, offsets, lengths)
//                            in, escaped response bytes out — byte-identical
//                            to json.dumps(row, ensure_ascii=False,
//                            separators=(",", ":")) over per-row dicts
//
// Build: g++ -O3 -shared -fPIC (see native/__init__.py, Makefile).

#ifndef _GNU_SOURCE
#define _GNU_SOURCE  // memmem
#endif
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <string.h>

namespace {

constexpr uint64_t P1 = 11400714785074694791ULL;
constexpr uint64_t P2 = 14029467366897019727ULL;
constexpr uint64_t P3 = 1609587929392839161ULL;
constexpr uint64_t P4 = 9650029242287828579ULL;
constexpr uint64_t P5 = 2870177450012600261ULL;

inline uint64_t rotl64(uint64_t x, int r) {
    return (x << r) | (x >> (64 - r));
}

inline uint64_t rd64(const uint8_t* p) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    return v;
}

inline uint32_t rd32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
}

// Canonical XXH64 (public spec); bit-identical to the python `xxhash`
// package used by utils/hashing.py.
uint64_t xxh64(const uint8_t* p, size_t len, uint64_t seed) {
    const uint8_t* end = p + len;
    uint64_t h;
    if (len >= 32) {
        uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed,
                 v4 = seed - P1;
        const uint8_t* limit = end - 32;
        do {
            v1 = rotl64(v1 + rd64(p) * P2, 31) * P1; p += 8;
            v2 = rotl64(v2 + rd64(p) * P2, 31) * P1; p += 8;
            v3 = rotl64(v3 + rd64(p) * P2, 31) * P1; p += 8;
            v4 = rotl64(v4 + rd64(p) * P2, 31) * P1; p += 8;
        } while (p <= limit);
        h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) +
            rotl64(v4, 18);
        v1 *= P2; v1 = rotl64(v1, 31); v1 *= P1; h ^= v1; h = h * P1 + P4;
        v2 *= P2; v2 = rotl64(v2, 31); v2 *= P1; h ^= v2; h = h * P1 + P4;
        v3 *= P2; v3 = rotl64(v3, 31); v3 *= P1; h ^= v3; h = h * P1 + P4;
        v4 *= P2; v4 = rotl64(v4, 31); v4 *= P1; h ^= v4; h = h * P1 + P4;
    } else {
        h = seed + P5;
    }
    h += (uint64_t)len;
    while (p + 8 <= end) {
        uint64_t k = rd64(p);
        k *= P2; k = rotl64(k, 31); k *= P1;
        h ^= k;
        h = rotl64(h, 27) * P1 + P4;
        p += 8;
    }
    if (p + 4 <= end) {
        h ^= (uint64_t)rd32(p) * P1;
        h = rotl64(h, 23) * P2 + P3;
        p += 4;
    }
    while (p < end) {
        h ^= (*p) * P5;
        h = rotl64(h, 11) * P1;
        p++;
    }
    h ^= h >> 33; h *= P2;
    h ^= h >> 29; h *= P3;
    h ^= h >> 32;
    return h;
}

inline bool word_char(uint8_t b) {
    return (b >= 'a' && b <= 'z') || (b >= 'A' && b <= 'Z') ||
           (b >= '0' && b <= '9') || b == '_' || b >= 0x80;
}

}  // namespace

extern "C" {

void vl_to_fixed_width(const uint8_t* arena, const int64_t* offsets,
                       const int64_t* lengths, int64_t nrows,
                       uint8_t* out, int64_t rb, int64_t w) {
    std::memset(out, 0xFF, (size_t)(rb * w));
    for (int64_t i = 0; i < nrows; i++) {
        int64_t len = lengths[i];
        if (len > w - 1) len = w - 1;
        if (len > 0) {
            std::memcpy(out + i * w, arena + offsets[i], (size_t)len);
        }
    }
}

int64_t vl_tokenize_arena(const uint8_t* arena, const int64_t* offsets,
                          const int64_t* lengths, int64_t nrows,
                          int64_t* tok_start, int64_t* tok_end,
                          int64_t* tok_row, int64_t cap) {
    int64_t nt = 0;
    for (int64_t r = 0; r < nrows; r++) {
        const int64_t off = offsets[r], len = lengths[r];
        int64_t i = 0;
        while (i < len) {
            while (i < len && !word_char(arena[off + i])) i++;
            if (i >= len) break;
            int64_t s = i;
            while (i < len && word_char(arena[off + i])) i++;
            if (nt >= cap) return -1;
            tok_start[nt] = off + s;
            tok_end[nt] = off + i;
            tok_row[nt] = r;
            nt++;
        }
    }
    return nt;
}

// Tokenize + hash + dedupe in one pass.  Dedup keys on the xxh64 hash:
// for bloom construction this is exactly equivalent to deduping on token
// bytes (identical hashes set identical bloom bits).  Returns the number
// of unique hashes written to out (first-seen order), or -1 if out_cap
// would overflow.
int64_t vl_unique_token_hashes(const uint8_t* arena, const int64_t* offsets,
                               const int64_t* lengths, int64_t nrows,
                               uint64_t* out, int64_t out_cap) {
    // open-addressing set sized to the next power of two >= 2*out_cap
    size_t table_size = 64;
    while ((int64_t)table_size < out_cap * 2) table_size <<= 1;
    uint64_t* table = (uint64_t*)std::calloc(table_size, sizeof(uint64_t));
    if (table == nullptr) return -1;
    const size_t mask = table_size - 1;
    int64_t n_out = 0;
    for (int64_t r = 0; r < nrows; r++) {
        const int64_t off = offsets[r], len = lengths[r];
        int64_t i = 0;
        while (i < len) {
            while (i < len && !word_char(arena[off + i])) i++;
            if (i >= len) break;
            int64_t s = i;
            while (i < len && word_char(arena[off + i])) i++;
            uint64_t h = xxh64(arena + off + s, (size_t)(i - s), 0);
            // 0 is the empty slot marker; remap the (essentially
            // impossible) zero hash onto a fixed sentinel
            if (h == 0) h = 0x9E3779B97F4A7C15ULL;
            size_t slot = (size_t)h & mask;
            bool found = false;
            while (table[slot] != 0) {
                if (table[slot] == h) { found = true; break; }
                slot = (slot + 1) & mask;
            }
            if (!found) {
                if (n_out >= out_cap) { std::free(table); return -1; }
                table[slot] = h;
                out[n_out++] = h;
            }
        }
    }
    std::free(table);
    return n_out;
}

uint64_t vl_xxh64(const uint8_t* data, int64_t len, uint64_t seed) {
    return xxh64(data, (size_t)len, seed);
}

// Arena-level string scan: the host analogue of the device match_scan
// kernel (tpu/kernels.py), byte-for-byte the same semantics as the
// per-row Python matchers (logsql/matchers.py) that remain the oracle.
// Modes mirror tpu/kernels.py: 0 phrase (word boundaries per
// starts_tok/ends_tok), 1 prefix (boundary before only), 2 plain
// substring, 3 whole-value equality, 4 value startswith.
//
// Substring-family modes scan the WHOLE arena once with memmem (glibc's
// SIMD path) and map hits back to rows by binary search — a rare phrase
// costs one pass at memory bandwidth instead of nrows Python calls.
// Word-boundary checks run on bytes: UTF-8 continuation bytes are >=
// 0x80 and count as word chars exactly like the Python matcher treats
// their characters, and an ASCII pattern can never match mid-codepoint.
void vl_phrase_scan(const uint8_t* arena, const int64_t* offsets,
                    const int64_t* lengths, int64_t nrows,
                    const uint8_t* pat, int64_t pat_len,
                    int32_t mode, int32_t starts_tok, int32_t ends_tok,
                    uint8_t* out_bm) {
    std::memset(out_bm, 0, (size_t)nrows);
    if (pat_len <= 0) return;  // caller keeps empty patterns on the
                               // Python path (match-all / match-empty)
    if (mode == 3 || mode == 4) {           // exact / exact-prefix
        for (int64_t r = 0; r < nrows; r++) {
            const int64_t len = lengths[r];
            if (len < pat_len || (mode == 3 && len != pat_len)) continue;
            if (std::memcmp(arena + offsets[r], pat, (size_t)pat_len)
                    == 0) {
                out_bm[r] = 1;
            }
        }
        return;
    }
    const int64_t total =
        nrows ? offsets[nrows - 1] + lengths[nrows - 1] : 0;
    const uint8_t* base = arena;
    const uint8_t* end = arena + total;
    const uint8_t* p = base;
    int64_t row = 0;
    while (p < end) {
        const uint8_t* q = (const uint8_t*)memmem(
            p, (size_t)(end - p), pat, (size_t)pat_len);
        if (q == nullptr) break;
        const int64_t pos = q - base;
        // advance the row cursor (hits arrive in increasing pos)
        while (row + 1 < nrows && offsets[row + 1] <= pos) row++;
        const int64_t r_start = offsets[row];
        const int64_t r_end = r_start + lengths[row];
        if (pos + pat_len <= r_end && !out_bm[row]) {
            bool ok = true;
            if (mode != 2) {
                if (starts_tok && pos > r_start &&
                        word_char(base[pos - 1])) {
                    ok = false;
                }
                if (ok && mode == 0 && ends_tok &&
                        pos + pat_len < r_end &&
                        word_char(base[pos + pat_len])) {
                    ok = false;
                }
            }
            if (ok) out_bm[row] = 1;
        }
        p = q + 1;
    }
}

// `A.*B` regex family, decided per row (host analogue of the device
// match_ordered_pair kernel): a row DEFINITELY matches /A.*B/ when the
// first A occurrence ends at or before the last B occurrence and the row
// has no newline ('.' does not cross newlines); rows that are ordered
// but contain a newline are flagged for re.search verification.
void vl_ordered_pair_scan(const uint8_t* arena, const int64_t* offsets,
                          const int64_t* lengths, int64_t nrows,
                          const uint8_t* pat_a, int64_t len_a,
                          const uint8_t* pat_b, int64_t len_b,
                          uint8_t* out_match, uint8_t* out_verify) {
    std::memset(out_match, 0, (size_t)nrows);
    std::memset(out_verify, 0, (size_t)nrows);
    if (len_a <= 0 || len_b <= 0) return;
    for (int64_t r = 0; r < nrows; r++) {
        const uint8_t* row = arena + offsets[r];
        const size_t len = (size_t)lengths[r];
        if ((int64_t)len < len_a + len_b) continue;
        const uint8_t* a = (const uint8_t*)memmem(row, len, pat_a,
                                                  (size_t)len_a);
        if (a == nullptr) continue;
        const size_t after = (size_t)(a - row) + (size_t)len_a;
        if (len < after + (size_t)len_b) continue;
        const uint8_t* b = (const uint8_t*)memmem(row + after, len - after,
                                                  pat_b, (size_t)len_b);
        if (b == nullptr) continue;
        if (memchr(row, '\n', len) != nullptr) {
            out_verify[r] = 1;   // '.' must not cross the newline: verify
        } else {
            out_match[r] = 1;
        }
    }
}


// ---------------- jsonline scanner (native data loader) ----------------
//
// Strict-subset JSON-lines parser for the columnar ingest fast path
// (server/vlinsert.py).  Handles flat objects whose values are strings,
// numbers, true or false; everything else (nested objects, arrays,
// nulls, lone surrogates, duplicate keys, malformed lines) flags the
// line for the Python fallback, which re-parses it with json.loads so
// semantics (including error behavior) stay identical to the per-row
// path.  The reference's equivalent is the fastjson-backed parser in
// lib/logstorage/json_parser.go.
//
// Output layout:
//   arena      : unescaped key/value bytes (escapes only shrink text,
//                so cap = body_len is always enough)
//   fields i32 : per field [key_off, key_len, val_off, val_len, kind]
//                kind 0 = string, 1 = exact-int raw text,
//                2 = float raw text (Python re-formats via json.dumps),
//                3 = true, 4 = false
//   lines  i32 : per line  [field_start, nfields, flags, raw_off, raw_len]
//                flags bit0 = Python fallback required
//   sigs   i64 : per line xxh64 over (key_len, key bytes)* — the schema
//                signature the Python side keys its plan cache on
//   counts i64 : [nlines, nfields_total, arena_used, arena_is_ascii]
// Returns 0 on success, -1 when a capacity limit would be exceeded
// (caller falls back to the per-line path).

static inline bool js_ws(uint8_t c) {
    return c == ' ' || c == '\t' || c == '\r';
}

// Unescape one JSON string: body[*pi] is the first char AFTER the
// opening quote; on success *pi points at the closing quote, the
// unescaped bytes are appended at *app, and *ascii drops to 0 when any
// non-ASCII byte lands in the arena.  Returns false on any invalid
// escape, control char, lone surrogate, or missing close quote —
// the caller falls back to the Python parser for the line.
static bool js_unescape(const uint8_t* body, int64_t* pi, int64_t e,
                        uint8_t* arena, int64_t* app, int64_t* ascii) {
    int64_t i = *pi, ap = *app;
    while (i < e) {
        uint8_t c = body[i];
        if (c == '"') {
            *pi = i;
            *app = ap;
            return true;
        }
        if (c != '\\') {
            if (c < 0x20) return false;
            if (c >= 0x80) *ascii = 0;
            arena[ap++] = c;
            i++;
            continue;
        }
        if (i + 1 >= e) return false;
        uint8_t n = body[i + 1];
        i += 2;
        switch (n) {
            case '"': arena[ap++] = '"'; break;
            case '\\': arena[ap++] = '\\'; break;
            case '/': arena[ap++] = '/'; break;
            case 'b': arena[ap++] = '\b'; break;
            case 'f': arena[ap++] = '\f'; break;
            case 'n': arena[ap++] = '\n'; break;
            case 'r': arena[ap++] = '\r'; break;
            case 't': arena[ap++] = '\t'; break;
            case 'u': {
                if (i + 4 > e) return false;
                uint32_t cp = 0;
                for (int k = 0; k < 4; k++) {
                    uint8_t h = body[i + k];
                    cp <<= 4;
                    if (h >= '0' && h <= '9') cp |= h - '0';
                    else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
                    else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
                    else return false;
                }
                i += 4;
                if (cp >= 0xD800 && cp <= 0xDBFF) {
                    // high surrogate: require the low half
                    if (i + 6 > e || body[i] != '\\' || body[i + 1] != 'u')
                        return false;
                    uint32_t lo = 0;
                    for (int k = 0; k < 4; k++) {
                        uint8_t h = body[i + 2 + k];
                        lo <<= 4;
                        if (h >= '0' && h <= '9') lo |= h - '0';
                        else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
                        else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
                        else return false;
                    }
                    if (lo < 0xDC00 || lo > 0xDFFF) return false;
                    i += 6;
                    cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
                    return false;  // lone low surrogate
                }
                if (cp < 0x80) {
                    arena[ap++] = (uint8_t)cp;
                } else if (cp < 0x800) {
                    arena[ap++] = 0xC0 | (cp >> 6);
                    arena[ap++] = 0x80 | (cp & 0x3F);
                    *ascii = 0;
                } else if (cp < 0x10000) {
                    arena[ap++] = 0xE0 | (cp >> 12);
                    arena[ap++] = 0x80 | ((cp >> 6) & 0x3F);
                    arena[ap++] = 0x80 | (cp & 0x3F);
                    *ascii = 0;
                } else {
                    arena[ap++] = 0xF0 | (cp >> 18);
                    arena[ap++] = 0x80 | ((cp >> 12) & 0x3F);
                    arena[ap++] = 0x80 | ((cp >> 6) & 0x3F);
                    arena[ap++] = 0x80 | (cp & 0x3F);
                    *ascii = 0;
                }
                break;
            }
            default: return false;
        }
    }
    return false;  // no closing quote before end of line
}

extern "C" int64_t vl_jsonline_scan(
        const uint8_t* body, int64_t body_len,
        uint8_t* arena, int64_t arena_cap,
        int32_t* fields, int64_t fields_cap,
        int32_t* lines, int64_t lines_cap,
        int64_t* sigs, int64_t* counts) {
    int64_t nl = 0, nf = 0, ap = 0;
    int64_t ascii = 1;
    int64_t pos = 0;
    while (pos < body_len) {
        int64_t eol = pos;
        while (eol < body_len && body[eol] != '\n') eol++;
        int64_t s = pos, e = eol;
        pos = eol + 1;
        while (s < e && (js_ws(body[s]) || body[s] == '\n')) s++;
        while (e > s && js_ws(body[e - 1])) e--;
        if (s >= e) continue;          // blank line
        if (nl >= lines_cap) return -1;
        int32_t* L = lines + nl * 5;
        L[0] = (int32_t)nf;
        L[1] = 0;
        L[2] = 0;
        L[3] = (int32_t)s;
        L[4] = (int32_t)(e - s);
        sigs[nl] = 0;
        nl++;
        int64_t i = s;
        bool fall = false;
        int64_t line_fields = nf;
        uint64_t sig = 1469598103934665603ULL;  // seed only
        if (body[i] != '{') { L[2] = 1; continue; }
        i++;
        while (i < e && js_ws(body[i])) i++;
        if (i < e && body[i] == '}') {
            i++;
            while (i < e && js_ws(body[i])) i++;
            if (i != e) L[2] = 1;
            continue;                  // empty object: zero fields
        }
        for (;;) {
            while (i < e && js_ws(body[i])) i++;
            if (i >= e || body[i] != '"') { fall = true; break; }
            int64_t ko = ap;
            i++;
            if (!js_unescape(body, &i, e, arena, &ap, &ascii)) {
                fall = true; break;
            }
            i++;                       // past the closing quote
            int64_t klen = ap - ko;
            while (i < e && js_ws(body[i])) i++;
            if (i >= e || body[i] != ':') { fall = true; break; }
            i++;
            while (i < e && js_ws(body[i])) i++;
            if (i >= e) { fall = true; break; }
            int64_t vo = ap, vlen = 0;
            int32_t kind;
            uint8_t c = body[i];
            if (c == '"') {
                i++;
                if (!js_unescape(body, &i, e, arena, &ap, &ascii)) {
                    fall = true; break;
                }
                i++;
                vlen = ap - vo;
                kind = 0;
            } else if (c == 't') {
                if (e - i < 4 || memcmp(body + i, "true", 4) != 0) {
                    fall = true; break;
                }
                i += 4; kind = 3;
            } else if (c == 'f') {
                if (e - i < 5 || memcmp(body + i, "false", 5) != 0) {
                    fall = true; break;
                }
                i += 5; kind = 4;
            } else if (c == '-' || (c >= '0' && c <= '9')) {
                // strict JSON number grammar
                int64_t ns = i;
                bool neg = false, isflt = false, ok = true;
                if (c == '-') { neg = true; i++; }
                if (i >= e || body[i] < '0' || body[i] > '9') ok = false;
                else if (body[i] == '0') { i++; }
                else { while (i < e && body[i] >= '0' && body[i] <= '9') i++; }
                if (ok && i < e && body[i] == '.') {
                    isflt = true; i++;
                    if (i >= e || body[i] < '0' || body[i] > '9')
                        ok = false;
                    while (i < e && body[i] >= '0' && body[i] <= '9') i++;
                }
                if (ok && i < e && (body[i] == 'e' || body[i] == 'E')) {
                    isflt = true; i++;
                    if (i < e && (body[i] == '+' || body[i] == '-')) i++;
                    if (i >= e || body[i] < '0' || body[i] > '9')
                        ok = false;
                    while (i < e && body[i] >= '0' && body[i] <= '9') i++;
                }
                if (!ok) { fall = true; break; }
                vlen = i - ns;
                if (!isflt && neg && i - ns == 2 && body[ns + 1] == '0') {
                    // JSON "-0": json.loads -> int 0 -> dumps -> "0"
                    arena[ap++] = '0';
                    vlen = 1;
                } else {
                    std::memcpy(arena + ap, body + ns, (size_t)vlen);
                    ap += vlen;
                }
                kind = isflt ? 2 : 1;
            } else {
                fall = true; break;    // null / object / array / other
            }
            if (nf >= fields_cap) return -1;
            int32_t* F = fields + nf * 5;
            F[0] = (int32_t)ko; F[1] = (int32_t)klen;
            F[2] = (int32_t)vo; F[3] = (int32_t)vlen; F[4] = kind;
            nf++;
            // schema signature: xxh64 chained over (klen, key bytes)
            sig = xxh64(arena + ko, (size_t)klen, sig ^ (uint64_t)klen);
            while (i < e && js_ws(body[i])) i++;
            if (i < e && body[i] == ',') { i++; continue; }
            if (i < e && body[i] == '}') {
                i++;
                while (i < e && js_ws(body[i])) i++;
                if (i != e) fall = true;
                break;
            }
            fall = true; break;
        }
        int64_t cnt = nf - line_fields;
        if (!fall) {
            // duplicate keys: Python dict keeps the LAST value; fall back
            for (int64_t a = line_fields; a < nf && !fall; a++) {
                for (int64_t b = a + 1; b < nf; b++) {
                    if (fields[a * 5 + 1] == fields[b * 5 + 1] &&
                        memcmp(arena + fields[a * 5],
                               arena + fields[b * 5],
                               (size_t)fields[a * 5 + 1]) == 0) {
                        fall = true; break;
                    }
                }
            }
        }
        if (fall) {
            nf = line_fields;          // discard partial fields
            L[2] = 1;
            continue;
        }
        L[1] = (int32_t)cnt;
        sigs[nl - 1] = (int64_t)sig;
        (void)arena_cap;
    }
    counts[0] = nl; counts[1] = nf; counts[2] = ap; counts[3] = ascii;
    return 0;
}

// ---------------- columnar NDJSON emit (query hot path) ----------------
//
// The emit-side mirror of vl_jsonline_scan: server/vlselect.py streams
// query results as NDJSON, and the per-row path (dict per row + a
// json.dumps call per row) dominated harvest time (PERF.md "vltrace").
// This serializer takes the columns of one result block — each as the
// same (arena, offsets, lengths) packed form the storage layer already
// holds — and writes the response bytes directly.
//
// Output contract (enforced by the differential suite in
// tests/test_emit.py): byte-identical to
//   json.dumps({k: v for k, v in row if v != ""}, ensure_ascii=False,
//              separators=(",", ":")) + "\n"
// per row, keys in column order.  That means:
//   - zero-length values are omitted (empty string == absent field);
//   - rows with no non-empty values still emit "{}";
//   - escapes match CPython's ensure_ascii=False encoder exactly:
//     '"' and '\\', \b \t \n \f \r for their control chars, \u00XX for
//     the remaining bytes < 0x20, everything else verbatim;
//   - key tokens arrive pre-quoted from Python (json.dumps of the name,
//     + ':'), so key escaping is Python's own by construction.
//
// Columns arrive TYPED (kinds[c]), so the storage's native arrays feed
// the serializer directly — no intermediate string materialization on
// the Python side at all:
//   kind 0  byte arena + per-row offsets/lengths (strings, dicts
//           gathered to (arena, offsets, lengths) on the Python side)
//   kind 1  int64 epoch-ns timestamps -> RFC3339Nano (_time: trailing
//           fraction zeros trimmed, whole seconds carry no fraction)
//   kind 2  int64 epoch-ns timestamps -> ISO8601 with params[c]
//           fixed fractional digits (VT_TIMESTAMP_ISO8601 columns)
//   kind 3  int64  -> decimal (VT_INT64)
//   kind 4  uint64 -> decimal (VT_UINT8..64)
// For kinds != 0 the arenas[c] pointer is reinterpreted as the numeric
// array and offsets/lengths are not read.
//
// Python decodes arenas with errors="replace"; to stay bit-identical
// the scan validates UTF-8 strictly and returns -1 on any invalid
// sequence (incl. surrogate halves and overlongs) — the caller falls
// back to the per-row Python path for that block.  Returns bytes
// written, -1 on invalid UTF-8, -2 if out_cap would overflow.

namespace {

const char HEXD[] = "0123456789abcdef";

// Escape one value into out at p; returns the new p, or -1 on invalid
// UTF-8 (caller falls back to Python for the whole block).
inline int64_t emit_escaped(const uint8_t* v, int64_t len,
                            uint8_t* out, int64_t p) {
    for (int64_t i = 0; i < len; i++) {
        const uint8_t c = v[i];
        if (c == '"') {
            out[p++] = '\\'; out[p++] = '"';
        } else if (c == '\\') {
            out[p++] = '\\'; out[p++] = '\\';
        } else if (c < 0x20) {
            out[p++] = '\\';
            switch (c) {
                case '\b': out[p++] = 'b'; break;
                case '\t': out[p++] = 't'; break;
                case '\n': out[p++] = 'n'; break;
                case '\f': out[p++] = 'f'; break;
                case '\r': out[p++] = 'r'; break;
                default:
                    out[p++] = 'u'; out[p++] = '0'; out[p++] = '0';
                    out[p++] = HEXD[c >> 4]; out[p++] = HEXD[c & 15];
            }
        } else if (c < 0x80) {
            out[p++] = c;
        } else {
            // strict UTF-8 validation (RFC 3629 table): continuation
            // ranges depend on the lead byte to reject overlongs,
            // surrogates and > U+10FFFF
            int need;
            uint8_t lo = 0x80, hi = 0xBF;
            if (c >= 0xC2 && c <= 0xDF) { need = 1; }
            else if (c == 0xE0) { need = 2; lo = 0xA0; }
            else if (c == 0xED) { need = 2; hi = 0x9F; }
            else if (c >= 0xE1 && c <= 0xEF) { need = 2; }
            else if (c == 0xF0) { need = 3; lo = 0x90; }
            else if (c >= 0xF1 && c <= 0xF3) { need = 3; }
            else if (c == 0xF4) { need = 3; hi = 0x8F; }
            else { return -1; }
            if (i + need >= len) return -1;
            const uint8_t b1 = v[i + 1];
            if (b1 < lo || b1 > hi) return -1;
            out[p++] = c;
            out[p++] = b1;
            for (int k = 2; k <= need; k++) {
                const uint8_t b = v[i + k];
                if (b < 0x80 || b > 0xBF) return -1;
                out[p++] = b;
            }
            i += need;
        }
    }
    return p;
}

inline int64_t fmt_u64(uint64_t v, uint8_t* out) {
    uint8_t tmp[20];
    int n = 0;
    do {
        tmp[n++] = (uint8_t)('0' + v % 10);
        v /= 10;
    } while (v);
    for (int i = 0; i < n; i++) out[i] = tmp[n - 1 - i];
    return n;
}

inline int64_t fmt_i64(int64_t v, uint8_t* out) {
    if (v < 0) {
        out[0] = '-';
        // -(v+1)+1 avoids INT64_MIN overflow
        return 1 + fmt_u64((uint64_t)(-(v + 1)) + 1, out + 1);
    }
    return fmt_u64((uint64_t)v, out);
}

// Epoch-ns -> 'YYYY-MM-DDTHH:MM:SS[.f...]Z'.  trim=true is RFC3339Nano
// (_time: trailing zeros trimmed, no fraction on whole seconds);
// trim=false renders exactly frac_w digits (stored ISO8601 columns are
// multiples of 10^(9-frac_w) by the round-trip property).  Digit-exact
// with storage/values_encoder.format_iso8601 (same civil-from-days
// algorithm, Howard Hinnant's).
inline int64_t fmt_ts(int64_t ns, int frac_w, bool trim, uint8_t* out) {
    const int64_t DAY = 86400LL * 1000000000LL;
    int64_t days = ns / DAY, rem = ns % DAY;
    if (rem < 0) { days -= 1; rem += DAY; }      // floor division
    const int64_t z = days + 719468;
    const int64_t era = (z >= 0 ? z : z - 146096) / 146097;
    const int64_t doe = z - era * 146097;
    const int64_t yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096)
        / 365;
    int64_t y = yoe + era * 400;
    const int64_t doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
    const int64_t mp = (5 * doy + 2) / 153;
    const int64_t d = doy - (153 * mp + 2) / 5 + 1;
    const int64_t m = mp + (mp < 10 ? 3 : -9);
    if (m <= 2) y += 1;
    const int64_t secs = rem / 1000000000LL;
    int64_t frac = rem % 1000000000LL;
    const int64_t h = secs / 3600, mi = (secs % 3600) / 60,
                  s = secs % 60;
    out[0] = (uint8_t)('0' + (y / 1000) % 10);
    out[1] = (uint8_t)('0' + (y / 100) % 10);
    out[2] = (uint8_t)('0' + (y / 10) % 10);
    out[3] = (uint8_t)('0' + y % 10);
    out[4] = '-';
    out[5] = (uint8_t)('0' + m / 10);
    out[6] = (uint8_t)('0' + m % 10);
    out[7] = '-';
    out[8] = (uint8_t)('0' + d / 10);
    out[9] = (uint8_t)('0' + d % 10);
    out[10] = 'T';
    out[11] = (uint8_t)('0' + h / 10);
    out[12] = (uint8_t)('0' + h % 10);
    out[13] = ':';
    out[14] = (uint8_t)('0' + mi / 10);
    out[15] = (uint8_t)('0' + mi % 10);
    out[16] = ':';
    out[17] = (uint8_t)('0' + s / 10);
    out[18] = (uint8_t)('0' + s % 10);
    int64_t p = 19;
    int digits = 0;
    if (trim) {
        if (frac != 0) {
            digits = 9;
            while (frac % 10 == 0) { frac /= 10; digits--; }
        }
    } else if (frac_w > 0) {
        digits = frac_w;
        for (int k = 0; k < 9 - frac_w; k++) frac /= 10;
    }
    if (digits > 0) {
        out[p++] = '.';
        for (int i = digits - 1; i >= 0; i--) {
            out[p + i] = (uint8_t)('0' + frac % 10);
            frac /= 10;
        }
        p += digits;
    }
    out[p++] = 'Z';
    return p;
}

}  // namespace

extern "C" int64_t vl_emit_ndjson(
        int64_t ncols, int64_t nrows,
        const uint8_t* const* keys, const int64_t* key_lens,
        const uint8_t* const* arenas,
        const int64_t* const* offsets, const int64_t* const* lengths,
        const int64_t* kinds, const int64_t* params,
        uint8_t* out, int64_t out_cap) {
    for (int64_t c = 0; c < ncols; c++) {
        if (kinds[c] < 0 || kinds[c] > 4) return -3;
    }
    int64_t p = 0;
    for (int64_t r = 0; r < nrows; r++) {
        if (p + 3 > out_cap) return -2;
        out[p++] = '{';
        bool first = true;
        for (int64_t c = 0; c < ncols; c++) {
            const int64_t kind = kinds[c];
            if (kind == 0) {
                const int64_t len = lengths[c][r];
                if (len <= 0) continue;
                // worst case: ',' + key token + quotes + 6x value
                if (p + key_lens[c] + 6 * len + 6 > out_cap) return -2;
                if (!first) out[p++] = ',';
                first = false;
                std::memcpy(out + p, keys[c], (size_t)key_lens[c]);
                p += key_lens[c];
                out[p++] = '"';
                const int64_t np2 = emit_escaped(
                    arenas[c] + offsets[c][r], len, out, p);
                if (np2 < 0) return -1;
                p = np2;
                out[p++] = '"';
                continue;
            }
            // typed kinds: always present, pure ASCII, no escaping
            if (p + key_lens[c] + 40 > out_cap) return -2;
            if (!first) out[p++] = ',';
            first = false;
            std::memcpy(out + p, keys[c], (size_t)key_lens[c]);
            p += key_lens[c];
            out[p++] = '"';
            const int64_t* nums =
                reinterpret_cast<const int64_t*>(arenas[c]);
            switch (kind) {
                case 1:
                    p += fmt_ts(nums[r], 0, true, out + p);
                    break;
                case 2:
                    p += fmt_ts(nums[r], (int)params[c], false, out + p);
                    break;
                case 3:
                    p += fmt_i64(nums[r], out + p);
                    break;
                default:  // 4
                    p += fmt_u64(
                        reinterpret_cast<const uint64_t*>(arenas[c])[r],
                        out + p);
            }
            out[p++] = '"';
        }
        out[p++] = '}';
        out[p++] = '\n';
    }
    return p;
}

}  // extern "C"
