// Native host core for victorialogs_tpu (C ABI, loaded via ctypes).
//
// The reference is an AOT-compiled native binary; these are our equivalents
// of its hottest host paths (the device plane stays JAX/XLA):
//
//   vl_to_fixed_width      — staging transpose: packed string column ->
//                            (rows, W) 0xFF-padded matrix (the HBM layout;
//                            tpu/layout.py fallback is numpy fancy indexing)
//   vl_tokenize_arena      — word tokenizer over a packed column
//                            (lib/logstorage/tokenizer.go:34-148 semantics:
//                            ASCII alnum + '_' + any >=0x80 byte)
//   vl_unique_token_hashes — tokenize + xxh64 + dedupe in ONE pass, feeding
//                            bloom construction without materializing any
//                            Python token objects
//                            (bloomfilter.go:126-170 consumes hashes only)
//
// Build: g++ -O3 -shared -fPIC (see native/__init__.py, Makefile).

#include <cstdint>
#include <cstring>
#include <cstdlib>

namespace {

constexpr uint64_t P1 = 11400714785074694791ULL;
constexpr uint64_t P2 = 14029467366897019727ULL;
constexpr uint64_t P3 = 1609587929392839161ULL;
constexpr uint64_t P4 = 9650029242287828579ULL;
constexpr uint64_t P5 = 2870177450012600261ULL;

inline uint64_t rotl64(uint64_t x, int r) {
    return (x << r) | (x >> (64 - r));
}

inline uint64_t rd64(const uint8_t* p) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    return v;
}

inline uint32_t rd32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
}

// Canonical XXH64 (public spec); bit-identical to the python `xxhash`
// package used by utils/hashing.py.
uint64_t xxh64(const uint8_t* p, size_t len, uint64_t seed) {
    const uint8_t* end = p + len;
    uint64_t h;
    if (len >= 32) {
        uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed,
                 v4 = seed - P1;
        const uint8_t* limit = end - 32;
        do {
            v1 = rotl64(v1 + rd64(p) * P2, 31) * P1; p += 8;
            v2 = rotl64(v2 + rd64(p) * P2, 31) * P1; p += 8;
            v3 = rotl64(v3 + rd64(p) * P2, 31) * P1; p += 8;
            v4 = rotl64(v4 + rd64(p) * P2, 31) * P1; p += 8;
        } while (p <= limit);
        h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) +
            rotl64(v4, 18);
        v1 *= P2; v1 = rotl64(v1, 31); v1 *= P1; h ^= v1; h = h * P1 + P4;
        v2 *= P2; v2 = rotl64(v2, 31); v2 *= P1; h ^= v2; h = h * P1 + P4;
        v3 *= P2; v3 = rotl64(v3, 31); v3 *= P1; h ^= v3; h = h * P1 + P4;
        v4 *= P2; v4 = rotl64(v4, 31); v4 *= P1; h ^= v4; h = h * P1 + P4;
    } else {
        h = seed + P5;
    }
    h += (uint64_t)len;
    while (p + 8 <= end) {
        uint64_t k = rd64(p);
        k *= P2; k = rotl64(k, 31); k *= P1;
        h ^= k;
        h = rotl64(h, 27) * P1 + P4;
        p += 8;
    }
    if (p + 4 <= end) {
        h ^= (uint64_t)rd32(p) * P1;
        h = rotl64(h, 23) * P2 + P3;
        p += 4;
    }
    while (p < end) {
        h ^= (*p) * P5;
        h = rotl64(h, 11) * P1;
        p++;
    }
    h ^= h >> 33; h *= P2;
    h ^= h >> 29; h *= P3;
    h ^= h >> 32;
    return h;
}

inline bool word_char(uint8_t b) {
    return (b >= 'a' && b <= 'z') || (b >= 'A' && b <= 'Z') ||
           (b >= '0' && b <= '9') || b == '_' || b >= 0x80;
}

}  // namespace

extern "C" {

void vl_to_fixed_width(const uint8_t* arena, const int64_t* offsets,
                       const int64_t* lengths, int64_t nrows,
                       uint8_t* out, int64_t rb, int64_t w) {
    std::memset(out, 0xFF, (size_t)(rb * w));
    for (int64_t i = 0; i < nrows; i++) {
        int64_t len = lengths[i];
        if (len > w - 1) len = w - 1;
        if (len > 0) {
            std::memcpy(out + i * w, arena + offsets[i], (size_t)len);
        }
    }
}

int64_t vl_tokenize_arena(const uint8_t* arena, const int64_t* offsets,
                          const int64_t* lengths, int64_t nrows,
                          int64_t* tok_start, int64_t* tok_end,
                          int64_t* tok_row, int64_t cap) {
    int64_t nt = 0;
    for (int64_t r = 0; r < nrows; r++) {
        const int64_t off = offsets[r], len = lengths[r];
        int64_t i = 0;
        while (i < len) {
            while (i < len && !word_char(arena[off + i])) i++;
            if (i >= len) break;
            int64_t s = i;
            while (i < len && word_char(arena[off + i])) i++;
            if (nt >= cap) return -1;
            tok_start[nt] = off + s;
            tok_end[nt] = off + i;
            tok_row[nt] = r;
            nt++;
        }
    }
    return nt;
}

// Tokenize + hash + dedupe in one pass.  Dedup keys on the xxh64 hash:
// for bloom construction this is exactly equivalent to deduping on token
// bytes (identical hashes set identical bloom bits).  Returns the number
// of unique hashes written to out (first-seen order), or -1 if out_cap
// would overflow.
int64_t vl_unique_token_hashes(const uint8_t* arena, const int64_t* offsets,
                               const int64_t* lengths, int64_t nrows,
                               uint64_t* out, int64_t out_cap) {
    // open-addressing set sized to the next power of two >= 2*out_cap
    size_t table_size = 64;
    while ((int64_t)table_size < out_cap * 2) table_size <<= 1;
    uint64_t* table = (uint64_t*)std::calloc(table_size, sizeof(uint64_t));
    if (table == nullptr) return -1;
    const size_t mask = table_size - 1;
    int64_t n_out = 0;
    for (int64_t r = 0; r < nrows; r++) {
        const int64_t off = offsets[r], len = lengths[r];
        int64_t i = 0;
        while (i < len) {
            while (i < len && !word_char(arena[off + i])) i++;
            if (i >= len) break;
            int64_t s = i;
            while (i < len && word_char(arena[off + i])) i++;
            uint64_t h = xxh64(arena + off + s, (size_t)(i - s), 0);
            // 0 is the empty slot marker; remap the (essentially
            // impossible) zero hash onto a fixed sentinel
            if (h == 0) h = 0x9E3779B97F4A7C15ULL;
            size_t slot = (size_t)h & mask;
            bool found = false;
            while (table[slot] != 0) {
                if (table[slot] == h) { found = true; break; }
                slot = (slot + 1) & mask;
            }
            if (!found) {
                if (n_out >= out_cap) { std::free(table); return -1; }
                table[slot] = h;
                out[n_out++] = h;
            }
        }
    }
    std::free(table);
    return n_out;
}

uint64_t vl_xxh64(const uint8_t* data, int64_t len, uint64_t seed) {
    return xxh64(data, (size_t)len, seed);
}

}  // extern "C"
