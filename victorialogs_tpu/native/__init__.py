"""Native host core loader: builds/loads libvlnative.so via ctypes.

The shared library is compiled on first use with g++ (no pip deps, no
pybind11 — plain C ABI).  Every consumer has a pure-numpy fallback, so a
missing toolchain degrades performance, never correctness.  Set
VL_NO_NATIVE=1 to force the fallbacks (used in tests to diff outputs).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np
from .. import config

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "vlnative.cpp")
_SO = os.path.join(_HERE, "libvlnative.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           "-o", _SO + ".tmp", _SRC]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if res.returncode != 0:
        return False
    os.replace(_SO + ".tmp", _SO)
    return True


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if config.env("VL_NO_NATIVE"):
            return None
        try:
            if not os.path.exists(_SO) or \
                    os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                # vlint: allow-lock-blocking-deep(one-time lazy init — the compile is deliberately serialized under _lock; every contender needs the artifact and must wait for it)
                if not _build():
                    return None
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        i64 = ctypes.c_int64
        u64 = ctypes.c_uint64
        i32 = ctypes.c_int32
        p_u8 = ctypes.POINTER(ctypes.c_uint8)
        p_i64 = ctypes.POINTER(ctypes.c_int64)
        p_u64 = ctypes.POINTER(ctypes.c_uint64)
        try:
            lib.vl_to_fixed_width.argtypes = [p_u8, p_i64, p_i64, i64,
                                              p_u8, i64, i64]
            lib.vl_to_fixed_width.restype = None
            lib.vl_tokenize_arena.argtypes = [p_u8, p_i64, p_i64, i64,
                                              p_i64, p_i64, p_i64, i64]
            lib.vl_tokenize_arena.restype = i64
            lib.vl_unique_token_hashes.argtypes = [p_u8, p_i64, p_i64, i64,
                                                   p_u64, i64]
            lib.vl_unique_token_hashes.restype = i64
            lib.vl_xxh64.argtypes = [p_u8, i64, u64]
            lib.vl_xxh64.restype = u64
            lib.vl_phrase_scan.argtypes = [p_u8, p_i64, p_i64, i64, p_u8,
                                           i64, i32, i32, i32, p_u8]
            lib.vl_phrase_scan.restype = None
            lib.vl_ordered_pair_scan.argtypes = [p_u8, p_i64, p_i64, i64,
                                                 p_u8, i64, p_u8, i64,
                                                 p_u8, p_u8]
            lib.vl_ordered_pair_scan.restype = None
            p_i32 = ctypes.POINTER(ctypes.c_int32)
            lib.vl_jsonline_scan.argtypes = [p_u8, i64, p_u8, i64,
                                             p_i32, i64, p_i32, i64,
                                             p_i64, p_i64]
            lib.vl_jsonline_scan.restype = i64
            p_pp = ctypes.POINTER(ctypes.c_void_p)
            lib.vl_emit_ndjson.argtypes = [i64, i64, p_pp, p_i64,
                                           p_pp, p_pp, p_pp, p_i64,
                                           p_i64, p_u8, i64]
            lib.vl_emit_ndjson.restype = i64
        except AttributeError:
            # a stale .so without the newer symbols (mtime tricked the
            # rebuild check): degrade to the Python paths instead of
            # failing the first query
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def to_fixed_width_native(arena: np.ndarray, offsets: np.ndarray,
                          lengths: np.ndarray, rb: int, w: int
                          ) -> np.ndarray | None:
    """C++ staging transpose; None when the native lib is unavailable."""
    lib = _load()
    if lib is None:
        return None
    arena = np.ascontiguousarray(arena, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    out = np.empty((rb, w), dtype=np.uint8)
    lib.vl_to_fixed_width(
        _ptr(arena, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
        _ptr(lengths, ctypes.c_int64), len(offsets),
        _ptr(out, ctypes.c_uint8), rb, w)
    return out


def phrase_scan_native(arena: np.ndarray, offsets: np.ndarray,
                       lengths: np.ndarray, pattern: bytes, mode: int,
                       starts_tok: bool, ends_tok: bool
                       ) -> np.ndarray | None:
    """Arena-level scan (host analogue of the device match_scan kernel):
    one memmem pass over the packed column instead of a Python call per
    row.  Returns a bool[nrows] bitmap, or None when the native lib is
    unavailable or the pattern is empty (Python path handles those)."""
    lib = _load()
    if lib is None or not pattern:
        return None
    arena = np.ascontiguousarray(arena, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    pat = np.frombuffer(pattern, dtype=np.uint8)
    nrows = len(offsets)
    out = np.empty(nrows, dtype=np.uint8)
    lib.vl_phrase_scan(
        _ptr(arena, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
        _ptr(lengths, ctypes.c_int64), nrows,
        _ptr(pat, ctypes.c_uint8), len(pattern),
        mode, int(starts_tok), int(ends_tok),
        _ptr(out, ctypes.c_uint8))
    return out.view(np.bool_)


def ordered_pair_scan_native(arena: np.ndarray, offsets: np.ndarray,
                             lengths: np.ndarray, pat_a: bytes,
                             pat_b: bytes
                             ) -> tuple[np.ndarray, np.ndarray] | None:
    """Per-row `A.*B` decision (host analogue of match_ordered_pair):
    (definite_match bool[n], needs_verify bool[n]) or None."""
    lib = _load()
    if lib is None or not pat_a or not pat_b:
        return None
    arena = np.ascontiguousarray(arena, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    a = np.frombuffer(pat_a, dtype=np.uint8)
    b = np.frombuffer(pat_b, dtype=np.uint8)
    nrows = len(offsets)
    out_m = np.empty(nrows, dtype=np.uint8)
    out_v = np.empty(nrows, dtype=np.uint8)
    lib.vl_ordered_pair_scan(
        _ptr(arena, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
        _ptr(lengths, ctypes.c_int64), nrows,
        _ptr(a, ctypes.c_uint8), len(pat_a),
        _ptr(b, ctypes.c_uint8), len(pat_b),
        _ptr(out_m, ctypes.c_uint8), _ptr(out_v, ctypes.c_uint8))
    return out_m.view(np.bool_), out_v.view(np.bool_)


def unique_token_hashes_native(arena: np.ndarray, offsets: np.ndarray,
                               lengths: np.ndarray) -> np.ndarray | None:
    """Tokenize+hash+dedupe in one native pass; None when unavailable."""
    lib = _load()
    if lib is None:
        return None
    arena = np.ascontiguousarray(arena, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    cap = max(64, int(arena.shape[0]) // 2 + len(offsets) + 1)
    out = np.empty(cap, dtype=np.uint64)
    n = lib.vl_unique_token_hashes(
        _ptr(arena, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
        _ptr(lengths, ctypes.c_int64), len(offsets),
        _ptr(out, ctypes.c_uint64), cap)
    if n < 0:
        return None
    return out[:n].copy()


def tokenize_arena_native(arena: np.ndarray, offsets: np.ndarray,
                          lengths: np.ndarray):
    """Native tokenizer; None when unavailable."""
    lib = _load()
    if lib is None:
        return None
    arena = np.ascontiguousarray(arena, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    cap = max(64, int(arena.shape[0]) + 1)
    ts = np.empty(cap, dtype=np.int64)
    te = np.empty(cap, dtype=np.int64)
    tr = np.empty(cap, dtype=np.int64)
    n = lib.vl_tokenize_arena(
        _ptr(arena, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
        _ptr(lengths, ctypes.c_int64), len(offsets),
        _ptr(ts, ctypes.c_int64), _ptr(te, ctypes.c_int64),
        _ptr(tr, ctypes.c_int64), cap)
    if n < 0:
        return None
    return ts[:n].copy(), te[:n].copy(), tr[:n].copy()


def xxh64_native(data: bytes, seed: int = 0) -> int | None:
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    if buf.size == 0:
        buf = np.zeros(1, dtype=np.uint8)
        return int(lib.vl_xxh64(_ptr(buf, ctypes.c_uint8), 0, seed))
    return int(lib.vl_xxh64(_ptr(buf, ctypes.c_uint8), buf.size, seed))


_EMIT_DUMMY_I64 = np.zeros(1, dtype=np.int64)


def emit_ndjson_native(key_tokens: list, cols: list, nrows: int
                       ) -> bytes | None:
    """Columnar NDJSON serializer (the query emit hot path).

    key_tokens: per column, the pre-quoted b'"key":' token (json.dumps
    of the name + colon — key escaping is Python's own by construction);
    cols: per column a kind-tagged tuple (BlockResult.emit_columns):
      (0, arena uint8[], offsets int64[n], lengths int64[n]) — bytes,
          length 0 meaning "omit this field";
      (1, ts int64[n])           — RFC3339Nano timestamps (_time);
      (2, ts int64[n], frac_w)   — ISO8601, fixed fractional width;
      (3, nums int64[n])         — signed decimal;
      (4, nums uint64[n])        — unsigned decimal.
    Returns the response bytes, or None when the native lib is missing
    or a value holds invalid UTF-8 (caller uses the per-row Python path,
    whose errors='replace' decode that case would need)."""
    lib = _load()
    if lib is None:
        return None
    ncols = len(cols)
    keys = [np.frombuffer(t, dtype=np.uint8) for t in key_tokens]
    arenas, offs, lens = [], [], []
    kinds = np.empty(ncols, dtype=np.int64)
    params = np.zeros(ncols, dtype=np.int64)
    total_val = 0
    total_typed = 0
    total_key = 0
    for ci, (col, k) in enumerate(zip(cols, keys)):
        kind = col[0]
        kinds[ci] = kind
        if kind == 0:
            _k, arena, o, ln = col
            arenas.append(np.ascontiguousarray(arena, dtype=np.uint8))
            offs.append(np.ascontiguousarray(o, dtype=np.int64))
            lens.append(np.ascontiguousarray(ln, dtype=np.int64))
            total_val += int(lens[-1].sum())
        else:
            dt = np.uint64 if kind == 4 else np.int64
            arenas.append(np.ascontiguousarray(col[1], dtype=dt))
            offs.append(_EMIT_DUMMY_I64)
            lens.append(_EMIT_DUMMY_I64)
            if kind == 2:
                params[ci] = int(col[2])
            total_typed += 34 * nrows    # ts/decimal upper bound, exact
        total_key += k.size
    pp = ctypes.c_void_p * ncols
    key_ptrs = pp(*[k.ctypes.data for k in keys])
    arena_ptrs = pp(*[a.ctypes.data for a in arenas])
    off_ptrs = pp(*[o.ctypes.data for o in offs])
    len_ptrs = pp(*[ln.ctypes.data for ln in lens])
    key_lens = np.fromiter((k.size for k in keys), dtype=np.int64,
                           count=ncols)
    cap = 6 * total_val + total_typed \
        + nrows * (total_key + 6 * ncols + 8) + 16
    out = np.empty(cap, dtype=np.uint8)
    n = lib.vl_emit_ndjson(
        ncols, nrows,
        ctypes.cast(key_ptrs, ctypes.POINTER(ctypes.c_void_p)),
        _ptr(key_lens, ctypes.c_int64),
        ctypes.cast(arena_ptrs, ctypes.POINTER(ctypes.c_void_p)),
        ctypes.cast(off_ptrs, ctypes.POINTER(ctypes.c_void_p)),
        ctypes.cast(len_ptrs, ctypes.POINTER(ctypes.c_void_p)),
        _ptr(kinds, ctypes.c_int64), _ptr(params, ctypes.c_int64),
        _ptr(out, ctypes.c_uint8), cap)
    if n < 0:
        return None
    return out[:n].tobytes()


def jsonline_scan_native(body: bytes):
    """Native strict-subset JSON-lines scan (the columnar ingest fast
    path's parser).  Returns (arena_bytes, fields int32[N,5],
    lines int32[M,5], sigs int64[M], arena_is_ascii) or None when the
    native lib is unavailable or a capacity bound trips (caller uses the
    per-line Python parser)."""
    lib = _load()
    if lib is None or not body or len(body) >= (1 << 31) - 8:
        return None    # offsets are int32; huge bodies take the py path
    blen = len(body)
    buf = np.frombuffer(body, dtype=np.uint8)
    arena = np.empty(blen, dtype=np.uint8)
    fields_cap = blen // 4 + 64
    lines_cap = blen // 3 + 64
    fields = np.empty((fields_cap, 5), dtype=np.int32)
    lines = np.empty((lines_cap, 5), dtype=np.int32)
    sigs = np.empty(lines_cap, dtype=np.int64)
    counts = np.zeros(4, dtype=np.int64)
    rc = lib.vl_jsonline_scan(
        _ptr(buf, ctypes.c_uint8), blen,
        _ptr(arena, ctypes.c_uint8), blen,
        _ptr(fields, ctypes.c_int32), fields_cap,
        _ptr(lines, ctypes.c_int32), lines_cap,
        _ptr(sigs, ctypes.c_int64), _ptr(counts, ctypes.c_int64))
    if rc != 0:
        return None
    nl, nf, used, ascii_ = int(counts[0]), int(counts[1]), \
        int(counts[2]), bool(counts[3])
    return (arena[:used].tobytes(), fields[:nf], lines[:nl], sigs[:nl],
            ascii_)
