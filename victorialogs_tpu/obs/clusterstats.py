"""Cluster-wide per-tenant usage rollups: the frontend-owned poll loop.

Each storage node accounts its own per-tenant usage exactly
(obs/activity.py: ``vl_tenant_*`` on /metrics), but that signal is
node-local — a tenant hogging N storage nodes at once looks N times
smaller than it is from any single vantage point, which is exactly the
gap the ROADMAP's cluster-QoS item names.  Monarch's shape applies:
identity is pushed DOWN with the work (parent_qid, server/cluster.py)
and aggregates are pulled UP on a cadence — this module is the pull
side.

A :class:`ClusterStatsPoller` (one per cluster frontend, owned by
VLServer) polls every storage node's ``GET /internal/usage`` snapshot
(per-tenant totals, live/queued query depth, storage gauges) every
``VL_CLUSTER_STATS_MS`` and serves:

- ``vl_cluster_tenant_{select_seconds,bytes_scanned,rows_ingested}_total``
  on the frontend's /metrics — the sum of each tenant's last-seen
  per-node totals, the cluster-wide signal the admission scheduler
  will consume;
- ``vl_cluster_node_up{node=}`` + ``vl_cluster_stats_age_seconds{node=}``
  — per-node rollup liveness/staleness;
- ``GET /select/logsql/tenants`` — the same aggregation as JSON, with
  per-node metadata.

Design constraints (test-pinned in tests/test_cluster_obs.py):

- **reads are cache-only** — the HTTP endpoints and /metrics serve the
  poller's last-seen state and never fan out inline, so a hung node
  can never hang a scrape; staleness is bounded by one poll interval
  plus the per-request timeout and is exported per node as age;
- **counters never regress** — a node that stops answering keeps its
  last-seen totals in the aggregate (they are monotonic counters; the
  node being down does not un-spend its tenants' usage), it is just
  marked ``up: 0`` with its age growing;
- **polls ride the policy layer** — requests go through
  netrobust.request gated on the select-path breaker, so a dead node
  costs one timeout until its circuit opens, then near-zero until the
  half-open probe (which doubles as the recovery detector);
- **one daemon thread per frontend** (``vl-clusterstats``), owned and
  close()d by VLServer — the vlsan end-of-test sweep sees no orphan.
"""

from __future__ import annotations

import json
import threading
import time

from .. import config

USAGE_PATH = "/internal/usage"

# the /metrics rollup dimensions: (usage_snapshot key, metric name)
ROLLUP_SERIES = (
    ("select_seconds", "vl_cluster_tenant_select_seconds_total"),
    ("bytes_scanned", "vl_cluster_tenant_bytes_scanned_total"),
    ("rows_ingested", "vl_cluster_tenant_rows_ingested_total"),
)


class ClusterStatsPoller:
    """The poll loop + last-seen cache.  Construct via
    :func:`maybe_start` (honors VL_CLUSTER_STATS_MS=0 = disabled)."""

    def __init__(self, node_urls: list, interval_ms: int | None = None):
        self.urls = [u.rstrip("/") for u in node_urls]
        if interval_ms is None:
            interval_ms = config.env_int("VL_CLUSTER_STATS_MS")
        self.interval_s = max(0.05, interval_ms / 1e3)
        # a hung node must not starve the loop: each request is bounded
        # well under the transport timeout (and the breaker makes the
        # repeat case near-free)
        self.timeout_s = max(0.2, min(5.0, self.interval_s * 2))
        self._mu = threading.Lock()
        self._nodes: dict[str, dict] = {
            u: {"up": False, "mono": None, "tenants": {},
                "error": "not polled yet"}
            for u in self.urls}
        self.polls = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="vl-clusterstats",
                                        daemon=True)
        self._thread.start()

    # -- the loop --

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_now()
            # vlint: allow-broad-except(the poll loop must survive any node pathology; per-node errors are recorded in the cache)
            except Exception:
                pass
            self._stop.wait(self.interval_s)

    def poll_now(self) -> None:
        """One synchronous poll round (the loop body; tests and the
        bench call it directly for determinism).  Nodes are polled in
        PARALLEL: one hung node (breaker not open yet) costs the round
        its own timeout, never timeout x bad-node-count — healthy
        nodes' freshness must not degrade because a sibling died."""
        from concurrent.futures import ThreadPoolExecutor

        def one(url: str):
            if self._stop.is_set():
                return url, None, "poller stopped"
            # lazy import: obs sits below server in the layer order;
            # the poller only exists on servers, where it's loaded
            from ..server import netrobust
            try:
                status, _h, body = netrobust.request(
                    url, USAGE_PATH, method="GET",
                    timeout=self.timeout_s, gate="select")
            except (IOError, OSError) as e:
                return url, None, str(e)
            if status != 200:
                return url, None, f"HTTP {status}"
            try:
                return url, json.loads(body), None
            except ValueError as e:
                return url, None, f"bad JSON: {e}"

        with ThreadPoolExecutor(max_workers=len(self.urls)) as ex:
            rows = list(ex.map(one, self.urls))
        now = time.monotonic()
        with self._mu:
            for url, snap, err in rows:
                st = self._nodes[url]
                if snap is not None:
                    st.update(up=True, error=None, mono=now,
                              tenants=snap.get("tenants") or {},
                              active_queries=snap.get(
                                  "active_queries", 0),
                              queued=snap.get("queued", 0),
                              storage=snap.get("storage") or {},
                              ingest_ledger=snap.get(
                                  "ingest_ledger") or {})
                else:
                    # keep the last-seen tenant totals: monotonic
                    # counters must not regress because the node died
                    st.update(up=False, error=err)
            self.polls += 1

    # -- cache reads --

    def aggregated_tenants(self) -> dict[str, dict]:
        """tenant -> summed last-seen totals across all nodes."""
        agg: dict[str, dict] = {}
        with self._mu:
            node_tenants = [dict(st["tenants"])
                            for st in self._nodes.values()]
        for tenants in node_tenants:
            for t, slot in tenants.items():
                cur = agg.setdefault(t, {})
                for k, v in slot.items():
                    if isinstance(v, (int, float)):
                        cur[k] = cur.get(k, 0) + v
        return agg

    def ledger_rollup(self) -> dict[str, dict]:
        """tenant -> worst-case ingest-conservation view across nodes
        (from each node's /internal/usage ``ingest_ledger`` section).

        Uses MAX per counter, not SUM: in-process test clusters share
        one ledger registry so every node reports identical totals and
        a sum would multi-count N-fold, while for real per-process
        nodes the max is still the right *stall/loss indicator* —
        any tenant with rows stuck (in_flight) or lost (dropped) on ANY
        node shows a nonzero value here.  Exact cluster totals come
        from summing ``vl_ingest_ledger_*`` across scrapes, where the
        scraper sees one process per target."""
        agg: dict[str, dict] = {}
        with self._mu:
            node_ledgers = [dict(st.get("ingest_ledger") or {})
                            for st in self._nodes.values()]
        for ledger in node_ledgers:
            for t, slot in ledger.items():
                cur = agg.setdefault(t, {})
                for k, v in slot.items():
                    if isinstance(v, (int, float)):
                        cur[k] = max(cur.get(k, 0), v)
        return agg

    def nodes_snapshot(self) -> list[dict]:
        """Per-node poll metadata (liveness, staleness, live depth)."""
        now = time.monotonic()
        out = []
        with self._mu:
            for url in self.urls:
                st = self._nodes[url]
                d = {"node": url, "up": bool(st["up"])}
                if st["mono"] is not None:
                    d["age_s"] = round(now - st["mono"], 3)
                if st.get("error"):
                    d["error"] = st["error"]
                if "active_queries" in st:
                    d["active_queries"] = st["active_queries"]
                    d["queued"] = st.get("queued", 0)
                out.append(d)
        return out

    def tenants_payload(self, tenant: str | None = None) -> dict:
        """The GET /select/logsql/tenants response body."""
        agg = self.aggregated_tenants()
        if tenant is not None:
            agg = {t: s for t, s in agg.items() if t == tenant}
        ledger = self.ledger_rollup()
        if tenant is not None:
            ledger = {t: s for t, s in ledger.items() if t == tenant}
        return {
            "status": "ok", "cluster": True,
            "tenants": {t: agg[t] for t in sorted(agg)},
            "ingest_ledger": {t: ledger[t] for t in sorted(ledger)},
            "nodes": self.nodes_snapshot(),
            "poll_interval_ms": int(self.interval_s * 1e3),
        }

    # -- /metrics integration --

    def metrics_samples(self) -> list[tuple[str, dict, float]]:
        """(base, labels, value) samples for Metrics.render: the
        cluster-wide per-tenant rollups + per-node liveness."""
        out: list[tuple[str, dict, float]] = []
        agg = self.aggregated_tenants()
        for t in sorted(agg):
            slot = agg[t]
            for key, name in ROLLUP_SERIES:
                # vlint: allow-per-row-emit(metric samples, bounded by tenant cap x 3 series)
                out.append((name, {"tenant": t}, slot.get(key, 0)))
        ledger = self.ledger_rollup()
        for t in sorted(ledger):
            # vlint: allow-per-row-emit(metric samples, bounded by tenant cap x 2 series)
            out.append(("vl_cluster_ingest_in_flight", {"tenant": t},
                        ledger[t].get("in_flight", 0)))
            out.append(("vl_cluster_ingest_dropped", {"tenant": t},
                        ledger[t].get("dropped", 0)))
        now = time.monotonic()
        with self._mu:
            metas = [(url, dict(st)) for url, st in self._nodes.items()]
        for url, st in metas:
            # vlint: allow-per-row-emit(metric samples, bounded by node count)
            out.append(("vl_cluster_node_up", {"node": url},
                        1 if st["up"] else 0))
            if st["mono"] is not None:
                # vlint: allow-per-row-emit(metric samples, bounded by node count)
                out.append(("vl_cluster_stats_age_seconds",
                            {"node": url},
                            round(now - st["mono"], 3)))
        return out

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def maybe_start(node_urls) -> ClusterStatsPoller | None:
    """The server-side constructor: a poller when VL_CLUSTER_STATS_MS
    is positive (default), None when 0/negative (rollups off)."""
    interval_ms = config.env_int("VL_CLUSTER_STATS_MS")
    if not node_urls or interval_ms <= 0:
        return None
    return ClusterStatsPoller(node_urls, interval_ms=interval_ms)
