"""Row-conservation ledger + per-hop batch tracing for the ingest path.

The write-path twin of obs/activity.py: every batch of rows entering
the process is minted a cluster-unique ``batch_id`` at its accept point
(vlinsert HTTP handlers, vlagent pickup, /internal/insert decode), and
every hop it crosses — parse, encode, shard, ship, spool, replay,
decode, store — rolls into one process-global registry:

- **conservation counters** per tenant: ``accepted`` (client-facing
  entry) and ``received`` (internal-hop entry) on the way in;
  ``stored``, ``forwarded`` and ``dropped{reason}`` as terminal states;
  ``spooled`` / ``replayed`` as the durable detour.  The invariant

      accepted + received == stored + forwarded + dropped + in_flight

  holds per process at all times (entry counters roll BEFORE terminal
  ones on every path, so the derived ``in_flight`` never goes
  negative), and telescopes cluster-wide — summing over all nodes,
  every ``forwarded`` row is some node's ``received`` row, leaving the
  ISSUE form ``accepted == stored + dropped + in_flight``.  The vlsan
  end-of-test sweep calls :func:`check_balanced`, making "zero lost
  rows" a machine-checked invariant instead of a test assertion;
- **per-hop latency aggregates** per (tenant, hop): count / total_s /
  max_s, always on and amortized per batch (never per row).  With
  ``VL_INGEST_TRACE=1`` each batch additionally grows a real
  obs/tracing.py span tree (root ``ingest_batch``, one child per hop)
  surfaced on ``GET /insert/status`` and in the ``ingest_batch``
  journal event;
- **freshness watermarks** per tenant: the max stored row timestamp
  (``vl_ingest_watermark_seconds``) plus the accept-wall-clock →
  queryable latency histogram fed from the storage chokepoint.

Batch identity propagates ambiently via a contextvar
(:func:`current_batch`; :func:`use_batch` re-enters on worker threads)
and across processes as the ``batch_id`` query arg on
``/internal/insert`` — the ingest twin of ``parent_qid`` — plus a
small header on spool / vlagent queue records (:func:`wrap_record`),
so replay after a restart still attributes rows to their batch.

The reserved system tenant (journal self-ingest) is excluded from the
ledger entirely and its ``ingest_batch`` events are suppressed by the
events-bus recursion guard, so the database observing itself cannot
unbalance — or re-enter — the ledger (test-pinned: idle server
quiesces).
"""

from __future__ import annotations

import contextvars
import json
import os
import struct
import threading
import time
from collections import deque

from .. import config
from . import events, hist, tracing

SYSTEM_TENANT = events.SYSTEM_TENANT

# the conservation counters rendered as
# vl_ingest_ledger_rows_total{tenant=,state=}
STATES = ("accepted", "received", "forwarded", "spooled", "replayed",
          "stored")

# an in-flight batch older than this (or parked in the spool) counts
# into /insert/status "stalled_batches" — the chaos-round signal
STALL_AGE_S = 5.0

# tenant labels come from client headers: cap the map like
# obs/activity.py so cycling AccountIDs can't explode /metrics
_TENANT_MAX = 1024
_TENANT_OVERFLOW = "other"
_COMPLETED_MAX = 64

# process-unique batch-id origin, the ingest twin of
# activity._ORIGIN/global_qid: local seqs collide across frontends,
# the prefixed spelling is what propagates on /internal/insert hops
_ORIGIN = os.urandom(4).hex()

_current: contextvars.ContextVar = contextvars.ContextVar(
    "vl_ingest_batch", default=None)

# one registry lock: counter rolls are per batch/hop (never per row),
# so contention is noise next to the work being measured
_mu = threading.Lock()
_seq = 0
_tenants: dict[str, dict] = {}        # tenant -> {state: n, "dropped": {}}
_hops: dict[str, dict] = {}           # tenant -> {hop: [count, total, max]}
_watermark: dict[str, float] = {}     # tenant -> max stored _time (unix s)
_inflight: dict[str, "BatchCtx"] = {}
_completed: deque = deque(maxlen=_COMPLETED_MAX)


def trace_enabled() -> bool:
    """VL_INGEST_TRACE=1 grows a real span tree per batch; default off
    (the always-on hop aggregates are the zero-config signal — the
    bench asserts tracing-off overhead stays within 1.10x)."""
    return config.env_bool("VL_INGEST_TRACE")


def _batches_max() -> int:
    return max(8, config.env_int("VL_INGEST_BATCHES_MAX"))


class BatchCtx:
    """One ingest batch's lifetime record.  Mint only via
    :func:`begin_batch`; fields are mutated under the module lock."""

    __slots__ = ("batch_id", "tenant", "accept_unix", "t0", "t1",
                 "state", "origin", "rows", "resolved", "spool_pending",
                 "dropped_rows", "hops", "span", "extents")

    def __init__(self, batch_id: str, tenant: str, origin: str,
                 accept_unix: float):
        self.batch_id = batch_id
        self.tenant = tenant
        self.origin = origin
        self.accept_unix = accept_unix
        self.t0 = time.monotonic()
        self.t1: float | None = None
        self.state = "active"
        self.rows = 0            # entry-counted rows (accepted+received)
        self.resolved = 0        # terminal rows (stored+forwarded+dropped)
        self.spool_pending = 0   # rows parked in the durable spool
        self.dropped_rows = 0
        self.hops: dict[str, list] = {}   # hop -> [count, total_s, max_s]
        self.span = tracing.make_root(
            "ingest_batch", batch_id=batch_id,
            tenant=tenant, origin=origin) if trace_enabled() else None
        self.extents = 0         # live begin_batch/use_batch extents

    def unresolved(self) -> int:
        return self.rows - self.resolved

    def snapshot(self, now: float | None = None) -> dict:
        if now is None:
            now = time.monotonic()
        end = self.t1 if self.t1 is not None else now
        out = {
            "batch_id": self.batch_id,
            "tenant": self.tenant,
            "origin": self.origin,
            "state": self.state,
            "rows": self.rows,
            "resolved": self.resolved,
            "age_s": round(end - self.t0, 3),
        }
        if self.spool_pending:
            out["spool_pending_rows"] = self.spool_pending
        if self.dropped_rows:
            out["dropped_rows"] = self.dropped_rows
        if self.hops:
            out["hops"] = {h: {"count": c[0],
                               "total_s": round(c[1], 6),
                               "max_s": round(c[2], 6)}
                           for h, c in sorted(self.hops.items())}
        if self.span is not None:
            out["trace"] = self.span.to_dict()
        return out


def current_batch() -> BatchCtx | None:
    """The ambient batch of this thread's ingest extent, or None — the
    storage chokepoint gates its ``stored`` roll on this, so direct
    storage writes (tests, journal self-ingest) stay off the ledger."""
    return _current.get()


def _tenant_cap(tenant: str) -> str:
    # caller holds _mu
    if tenant in _tenants or len(_tenants) < _TENANT_MAX:
        return tenant
    return _TENANT_OVERFLOW


def _slot(tenant: str) -> dict:
    # caller holds _mu
    tenant = _tenant_cap(tenant)
    slot = _tenants.get(tenant)
    if slot is None:
        slot = _tenants[tenant] = {s: 0 for s in STATES}
        slot["dropped"] = {}
    return slot


class _BatchExtent:
    """Dynamic extent of one batch hop on this thread: sets the ambient
    ctx, finishes the batch bookkeeping on every exit path."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: BatchCtx):
        self._ctx = ctx
        self._token = None

    def __enter__(self) -> BatchCtx:
        with _mu:
            self._ctx.extents += 1
        self._token = _current.set(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc, tb) -> bool:
        _current.reset(self._token)
        _finish_extent(self._ctx)
        return False


def begin_batch(tenant, origin: str = "http", batch_id: str | None = None,
                accept_unix: float | None = None) -> _BatchExtent:
    """Enter one batch's tracking extent (context-manager-only).

    Without ``batch_id`` a fresh cluster-unique id is minted — the
    accept point.  With one (an /internal/insert or replay hop) the
    existing in-flight record is re-entered when this process already
    tracks it (the in-process cluster case), so a batch's frontend and
    storage hops share one record; otherwise a record is registered
    under the propagated id (the separate-process case)."""
    global _seq
    from . import activity
    tenant = activity.tenant_str(tenant)
    if accept_unix is None:
        # vlint: allow-wall-clock(accept time anchors the ingest->queryable latency, real wall time by design)
        accept_unix = time.time()
    with _mu:
        ctx = _inflight.get(batch_id) if batch_id else None
        if ctx is None:
            if batch_id is None:
                _seq += 1
                batch_id = f"{_ORIGIN}:{_seq}"
            ctx = BatchCtx(batch_id, tenant, origin, accept_unix)
            if tenant != SYSTEM_TENANT:
                _inflight[batch_id] = ctx
                _evict_locked()
    return _BatchExtent(ctx)


def use_batch(ctx: BatchCtx | None) -> _BatchExtent | tracing._NoopCtx:
    """Re-enter an existing batch in another thread — the propagation
    shim for ingest worker fan-outs (the sharded-parse pool)."""
    if ctx is None:
        return tracing._NOOP_CTX
    return _BatchExtent(ctx)


def _evict_locked() -> None:
    over = len(_inflight) - _batches_max()
    if over <= 0:
        return
    for bid in sorted(_inflight, key=lambda b: _inflight[b].t0)[:over]:
        ctx = _inflight.pop(bid)
        ctx.state = "evicted"
        ctx.t1 = time.monotonic()
        _completed.append(ctx.snapshot(ctx.t1))


def _finish_extent(ctx: BatchCtx) -> None:
    done = None
    with _mu:
        ctx.extents -= 1
        if ctx.extents > 0 or ctx.state in ("done", "evicted"):
            return
        if ctx.unresolved() > 0 or ctx.spool_pending > 0:
            # rows parked in the durable spool (or shipped but not yet
            # decoded): the batch stays in-flight until replay/decode
            # resolves it — what /insert/status shows as stalled
            ctx.state = "spooled" if ctx.spool_pending > 0 else "shipping"
            return
        done = _complete_locked(ctx)
    if done is not None:
        _emit_done(done)


def _complete_locked(ctx: BatchCtx) -> BatchCtx:
    ctx.state = "done"
    ctx.t1 = time.monotonic()
    if ctx.span is not None:
        ctx.span.close()
    _inflight.pop(ctx.batch_id, None)
    if ctx.rows > 0:
        # zero-row batches (system-tenant journal flushes riding
        # /internal/insert, empty client posts) leave no trace: the
        # idle-quiesce guarantee
        _completed.append(ctx.snapshot(ctx.t1))
    return ctx


def _emit_done(ctx: BatchCtx) -> None:
    # outside the lock; system-tenant batches suppress in events.emit,
    # zero-row batches (journal self-ingest hops) emit nothing at all
    if ctx.rows <= 0:
        return
    events.emit("ingest_batch", tenant=ctx.tenant,
                batch_id=ctx.batch_id, origin=ctx.origin,
                rows=ctx.rows, dropped_rows=ctx.dropped_rows,
                duration_ms=round((ctx.t1 - ctx.t0) * 1e3, 3),
                status="dropped" if ctx.dropped_rows else "ok")


def _maybe_complete_locked(ctx: BatchCtx) -> BatchCtx | None:
    """A terminal roll resolved rows on a batch whose extents already
    exited (spool replay, cross-thread decode): complete it."""
    if ctx.extents == 0 and ctx.state not in ("done", "evicted") and \
            ctx.unresolved() <= 0 and ctx.spool_pending <= 0:
        return _complete_locked(ctx)
    return None


# ---------------------------------------------------------------- counters

def _enter_rows(tenant: str, state: str, n: int,
                ctx: BatchCtx | None) -> None:
    with _mu:
        _slot(tenant)[state] += n
        if ctx is not None:
            ctx.rows += n


def _terminal_rows(tenant: str, state: str, n: int,
                   ctx: BatchCtx | None) -> None:
    done = None
    with _mu:
        _slot(tenant)[state] += n
        if ctx is not None:
            ctx.resolved += n
            done = _maybe_complete_locked(ctx)
    if done is not None:
        _emit_done(done)


def note_accepted(tenant, n: int) -> None:
    """Rows entered at a client-facing accept point (vlinsert HTTP,
    vlagent pickup).  Entry counters roll BEFORE any terminal counter
    on every path, so derived in_flight never dips negative."""
    from . import activity
    tenant = activity.tenant_str(tenant)
    if tenant == SYSTEM_TENANT or n <= 0:
        return
    _enter_rows(tenant, "accepted", n, _current.get())


def note_received(tenant, n: int) -> None:
    """Rows entered via an internal hop (/internal/insert decode) —
    the counter that cancels ``forwarded`` in the cluster-wide sum."""
    from . import activity
    tenant = activity.tenant_str(tenant)
    if tenant == SYSTEM_TENANT or n <= 0:
        return
    _enter_rows(tenant, "received", n, _current.get())


def note_forwarded(tenant, n: int, batch: BatchCtx | None = None) -> None:
    """Rows shipped to another node (terminal for THIS process)."""
    from . import activity
    tenant = activity.tenant_str(tenant)
    if tenant == SYSTEM_TENANT or n <= 0:
        return
    _terminal_rows(tenant, "forwarded", n,
                   batch if batch is not None else _current.get())


def note_stored(tenant, n: int, max_ts_unix: float | None = None) -> None:
    """Rows written into local storage (terminal).  ``max_ts_unix``
    advances the tenant's freshness watermark."""
    from . import activity
    tenant = activity.tenant_str(tenant)
    if tenant == SYSTEM_TENANT or n <= 0:
        return
    ctx = _current.get()
    done = None
    with _mu:
        _slot(tenant)["stored"] += n
        if max_ts_unix is not None:
            t = _tenant_cap(tenant)
            if max_ts_unix > _watermark.get(t, 0.0):
                _watermark[t] = max_ts_unix
        if ctx is not None:
            ctx.resolved += n
            done = _maybe_complete_locked(ctx)
    if done is not None:
        _emit_done(done)
    if ctx is not None and ctx.accept_unix:
        # accept wall clock -> rows queryable (snapshot_parts serves
        # in-memory parts the moment must_add returns): the
        # ingest-to-queryable latency, observed per batch
        # vlint: allow-wall-clock(latency vs the batch's accept wall time)
        now = time.time()
        hist.INGEST_TO_QUERYABLE.observe(
            max(0.0, now - ctx.accept_unix))


def note_spooled(tenant, n: int) -> None:
    """Rows parked in the durable spool (NOT terminal: they stay
    in-flight until replay forwards or drops them)."""
    from . import activity
    tenant = activity.tenant_str(tenant)
    if tenant == SYSTEM_TENANT or n <= 0:
        return
    ctx = _current.get()
    with _mu:
        _slot(tenant)["spooled"] += n
        if ctx is not None:
            ctx.spool_pending += n


def note_replayed(tenant, n: int, batch_id: str | None = None) -> None:
    """Rows successfully re-shipped from the spool: rolls ``replayed``
    AND ``forwarded`` (the terminal state), and drains the owning
    batch's spool-pending count (found by the spool record's
    ``batch_id`` header — the replay loop has no ambient ctx)."""
    from . import activity
    tenant = activity.tenant_str(tenant)
    if tenant == SYSTEM_TENANT or n <= 0:
        return
    done = None
    with _mu:
        slot = _slot(tenant)
        slot["replayed"] += n
        slot["forwarded"] += n
        ctx = _inflight.get(batch_id) if batch_id else None
        if ctx is not None:
            ctx.spool_pending = max(0, ctx.spool_pending - n)
            ctx.resolved += n
            done = _maybe_complete_locked(ctx)
    if done is not None:
        _emit_done(done)


def note_dropped(tenant, n: int, reason: str,
                 batch_id: str | None = None,
                 from_spool: bool = False) -> None:
    """Rows terminally dropped, with a reason label — the ONE exit
    every drop site in server/ and storage/ must take (enforced by the
    vlint drop-discipline checker)."""
    from . import activity
    tenant = activity.tenant_str(tenant)
    if tenant == SYSTEM_TENANT or n <= 0:
        return
    done = None
    with _mu:
        slot = _slot(tenant)
        slot["dropped"][reason] = slot["dropped"].get(reason, 0) + n
        ctx = _inflight.get(batch_id) if batch_id else _current.get()
        if ctx is not None:
            if from_spool:
                ctx.spool_pending = max(0, ctx.spool_pending - n)
            ctx.resolved += n
            ctx.dropped_rows += n
            done = _maybe_complete_locked(ctx)
    if done is not None:
        _emit_done(done)


# ---------------------------------------------------------------- hops

def _note_hop(tenant: str, name: str, dt: float,
              ctx: BatchCtx | None) -> None:
    with _mu:
        agg = _hops.setdefault(_tenant_cap(tenant), {})
        cell = agg.setdefault(name, [0, 0.0, 0.0])
        cell[0] += 1
        cell[1] += dt
        cell[2] = max(cell[2], dt)
        if ctx is not None:
            cell = ctx.hops.setdefault(name, [0, 0.0, 0.0])
            cell[0] += 1
            cell[1] += dt
            cell[2] = max(cell[2], dt)


class _Hop:
    """Times one hop's extent into the per-(tenant, hop) aggregates;
    under VL_INGEST_TRACE it also opens a real child span on the
    batch's trace tree.  Cost when tracing is off: one perf_counter
    pair + one locked dict roll per batch hop — never per row."""

    __slots__ = ("_name", "_tenant", "_ctx", "_t0", "_spanctx")

    def __init__(self, name: str, tenant: str | None):
        self._name = name
        self._tenant = tenant
        self._ctx = None
        self._t0 = 0.0
        self._spanctx = None

    def __enter__(self) -> "_Hop":
        ctx = _current.get()
        self._ctx = ctx
        if ctx is not None and ctx.span is not None:
            # vlint: allow-span-discipline(_Hop IS the with-block: the child span enters here and closes in __exit__ on every unwind path)
            self._spanctx = ctx.span.span(self._name)
            self._spanctx.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dt = time.perf_counter() - self._t0
        if self._spanctx is not None:
            self._spanctx.__exit__(exc_type, exc, tb)
        tenant = self._tenant or \
            (self._ctx.tenant if self._ctx is not None else None)
        if tenant and tenant != SYSTEM_TENANT:
            _note_hop(tenant, self._name, dt, self._ctx)
        return False


def hop(name: str, tenant: str | None = None) -> _Hop:
    """Context manager timing one ingest hop (parse/encode/shard/ship/
    spool/replay/decode/store).  ``tenant`` overrides the ambient
    batch's attribution (the replay loop runs without one)."""
    return _Hop(name, tenant)


# ------------------------------------------------- spool record framing

# spool / vlagent queue records gain a small self-describing header so
# replay AFTER a process restart still attributes rows to their batch
# and tenant; headerless records (pre-upgrade spools) pass through
_REC_MAGIC = b"VLB1"


def wrap_record(body: bytes, batch_id: str, tenant, nrows: int,
                accept_unix: float | None = None) -> bytes:
    from . import activity
    m = {"batch_id": batch_id, "tenant": activity.tenant_str(tenant),
         "nrows": nrows}
    if accept_unix:
        # the batch's original accept wall clock survives the spool, so
        # ingest->queryable latency measured after replay still spans
        # the outage it sat out
        m["ts"] = round(accept_unix, 6)
    meta = json.dumps(m, separators=(",", ":")).encode()
    return _REC_MAGIC + struct.pack(">I", len(meta)) + meta + body


def unwrap_record(rec: bytes) -> tuple[dict | None, bytes]:
    """(meta, body); meta is None for a headerless legacy record."""
    if not rec.startswith(_REC_MAGIC):
        return None, rec
    try:
        n = struct.unpack(">I", rec[4:8])[0]
        meta = json.loads(rec[8:8 + n])
        return meta, rec[8 + n:]
    except (struct.error, ValueError):
        return None, rec


# ---------------------------------------------------------------- reads

def _derived_locked(slot: dict) -> tuple[int, int]:
    dropped = sum(slot["dropped"].values())
    in_flight = (slot["accepted"] + slot["received"] - slot["stored"]
                 - slot["forwarded"] - dropped)
    return dropped, in_flight


def balance_snapshot() -> dict[str, dict]:
    """tenant -> counters + derived dropped_rows / in_flight — what the
    chaos tests assert exact conservation on."""
    out = {}
    with _mu:
        for t, slot in _tenants.items():
            dropped, in_flight = _derived_locked(slot)
            d = {s: slot[s] for s in STATES}
            d["dropped"] = dict(slot["dropped"])
            d["dropped_rows"] = dropped
            d["in_flight"] = in_flight
            out[t] = d
    return out


def check_balanced() -> list[str]:
    """Conservation problems, empty when the ledger balances — the
    vlsan end-of-test sweep's check.  in_flight is derived, so the
    invariant reduces to: no counter negative, no tenant resolved more
    rows than entered, replays bounded by spools."""
    problems = []
    for t, d in balance_snapshot().items():
        for s in STATES:
            if d[s] < 0:
                problems.append(f"tenant {t}: {s} negative ({d[s]})")
        for reason, n in d["dropped"].items():
            if n < 0:
                problems.append(
                    f"tenant {t}: dropped[{reason}] negative ({n})")
        if d["in_flight"] < 0:
            problems.append(
                f"tenant {t}: conservation violated — "
                f"accepted+received={d['accepted'] + d['received']} < "
                f"stored+forwarded+dropped="
                f"{d['stored'] + d['forwarded'] + d['dropped_rows']}")
        if d["replayed"] > d["spooled"]:
            problems.append(
                f"tenant {t}: replayed {d['replayed']} > "
                f"spooled {d['spooled']}")
    return problems


def inflight_batches() -> int:
    with _mu:
        return len(_inflight)


def status_payload() -> dict:
    """The ledger's part of GET /insert/status (server/app.py adds the
    spool / vlagent queue sections and the cluster federation)."""
    now = time.monotonic()
    with _mu:
        inflight = [c.snapshot(now)
                    for c in sorted(_inflight.values(),
                                    key=lambda c: c.t0)]
        recent = list(_completed)
        hops = {t: {h: {"count": c[0], "total_s": round(c[1], 6),
                        "max_s": round(c[2], 6)}
                    for h, c in sorted(agg.items())}
                for t, agg in sorted(_hops.items())}
        wm = {t: round(w, 3) for t, w in sorted(_watermark.items())}
    stalled = sum(1 for b in inflight
                  if b["state"] == "spooled" or b["age_s"] > STALL_AGE_S)
    return {
        "ledger": balance_snapshot(),
        "in_flight": inflight,
        "recent": recent,
        "hop_latency": hops,
        "watermark_unix": wm,
        "stalled_batches": stalled,
        "trace_enabled": trace_enabled(),
    }


def usage_section() -> dict:
    """Per-tenant conservation totals for GET /internal/usage — what
    the frontend's clusterstats poll loop rolls up cluster-wide."""
    out = {}
    for t, d in balance_snapshot().items():
        out[t] = {"accepted": d["accepted"], "received": d["received"],
                  "forwarded": d["forwarded"], "stored": d["stored"],
                  "dropped": d["dropped_rows"],
                  "in_flight": d["in_flight"]}
    return out


def metrics_samples() -> list[tuple[str, dict, float]]:
    """(base, labels, value) samples for Metrics.render + the vlsan
    counter sweep: the conservation counters, derived in-flight rows,
    freshness watermarks and the in-flight batch gauge."""
    out: list[tuple[str, dict, float]] = [
        ("vl_ingest_batches_in_flight", {}, inflight_batches())]
    snap = balance_snapshot()
    # vlint: allow-wall-clock(watermark age is vs real wall time by definition)
    now = time.time()
    with _mu:
        wm = dict(_watermark)
    for t in sorted(snap):
        d = snap[t]
        lbl = {"tenant": t}
        for s in STATES:
            # vlint: allow-per-row-emit(metric samples, bounded by tenant cap x 6 states)
            out.append(("vl_ingest_ledger_rows_total",
                        {"tenant": t, "state": s}, d[s]))
        for reason in sorted(d["dropped"]):
            # vlint: allow-per-row-emit(metric samples, bounded by drop-reason count)
            out.append(("vl_ingest_ledger_dropped_total",
                        {"tenant": t, "reason": reason},
                        d["dropped"][reason]))
        out.append(("vl_ingest_ledger_in_flight", lbl, d["in_flight"]))
        if t in wm:
            out.append(("vl_ingest_watermark_seconds", lbl,
                        round(max(0.0, now - wm[t]), 3)))
    return out


def reset_for_tests() -> None:
    global _seq
    with _mu:
        _seq = 0
        _tenants.clear()
        _hops.clear()
        _watermark.clear()
        _inflight.clear()
        _completed.clear()
