"""Slow-query log: one structured JSON line per query over the
VL_SLOW_QUERY_MS threshold (default: off).

When the threshold is armed, the query handlers force tracing on for
every query (the no-op path costs nothing when the log is off, and a
slow query without a trace is exactly the situation the log exists to
avoid), so the emitted line carries the flattened per-stage summary:

    {"msg": "slow query", "endpoint": "/select/logsql/query",
     "duration_ms": 812.4, "threshold_ms": 500.0, "query": "...",
     "trace": {"query": {"count": 1, "total_ms": 812.4},
               "harvest": {"count": 9, "total_ms": 617.0}, ...},
     "attrs": {...root span counters...}, "ts": "..."}

Lines go to stderr by default (the single binary's log stream); tests
inject their own sink via set_sink().
"""

from __future__ import annotations

import json
import sys
import time
from .. import config

from . import events

_sink = None


def set_sink(fn) -> None:
    """Test hook: fn(line_str) replaces the stderr write (None resets)."""
    global _sink
    _sink = fn


def threshold_ms() -> float | None:
    """The armed threshold, or None when the log is off."""
    v = config.env("VL_SLOW_QUERY_MS")
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


def enabled() -> bool:
    return threshold_ms() is not None


def maybe_log(endpoint: str, query: str, duration_s: float,
              root=None, qid: str | None = None) -> bool:
    """Emit the slow-query line when duration exceeds the threshold.
    Returns True when a line was emitted (test convenience).

    qid: the active-query registry id (obs/activity.py) — carried on
    the line so slowlog records, ?trace=1 trees, and active_queries
    snapshots correlate by id."""
    thr = threshold_ms()
    if thr is None or duration_s * 1e3 < thr:
        return False
    rec = {
        "msg": "slow query",
        "endpoint": endpoint,
        "duration_ms": round(duration_s * 1e3, 3),
        "threshold_ms": thr,
        "query": query,
        # vlint: allow-wall-clock(log-line timestamp is real wall time)
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if qid:
        rec["qid"] = qid
    if root is not None and getattr(root, "enabled", False):
        rec["trace"] = root.flatten()
        if root.attrs:
            rec["attrs"] = root.attrs
    line = json.dumps(rec, ensure_ascii=False, separators=(",", ":"))
    # the same record rides the event bus into the self-telemetry
    # journal (obs/journal.py), so slow queries are LogsQL-queryable
    # over hours instead of scrolling off stderr; the bus suppresses
    # system-tenant queries (recursion guard) via the ambient record
    events.emit("slow_query", endpoint=endpoint, qid=qid or "",
                duration_ms=rec["duration_ms"],
                threshold_ms=thr, query=query)
    sink = _sink
    try:
        if sink is not None:
            sink(line)
        else:
            sys.stderr.write(line + "\n")
    # vlint: allow-broad-except(a dead sink must not fail the query; counted)
    except Exception:
        # previously silent: a failing sink write now shows up as
        # vl_slowlog_emit_failures_total on /metrics
        events.note("slowlog_emit_failures")
    return True
