"""Self-telemetry journal: bus events batched into LogRows and ingested
through the normal storage path under the reserved system tenant.

A :class:`JournalWriter` subscribes to obs/events.py and turns every
delivered event into one log row:

- tenant ``(0, 0xFFFFFFFE)`` (``events.SYSTEM_TENANT``) — invisible to
  normal-tenant queries, queryable by setting AccountID/ProjectID;
- ``_stream`` fields ``{app, event}`` so LogsQL stream filters work
  naturally: ``{app="victorialogs-tpu",event="admission_shed"} | ...``;
- every event field as a first-class log field (stats-pipe-able:
  ``_time:1h {app="victorialogs-tpu",event="query_done"}
  | stats by (endpoint) quantile(0.99, duration_ms)``);
- ``_msg`` as a compact ``event k=v ...`` line for full-text search.

``query_done`` events carry the cost-accountability pairs since the
EXPLAIN PR: ``predicted_duration_s`` / ``predicted_bytes`` /
``predicted_dispatches`` (plan-time pricing, obs/explain.py) next to
the measured counters, the per-dimension relative errors
(``cost_err_duration`` / ``cost_err_bytes`` / ``cost_err_dispatches``,
folded at deregistration in obs/activity.py), and the sink-side
exec/drain split (``exec_s`` stamped at the last harvest, ``drain_s``
what the client spent pulling the response) — so cost-model drift and
slow-consumer pathologies are LogsQL-queryable history, not just live
/metrics histograms.

Safety properties (the point of the subsystem — test-pinned in
tests/test_journal.py):

- **bounded queue, never block** — ``_on_event`` appends under a lock
  or drops; ``dropped`` is the exact count (vl_journal_dropped_total).
  A wedged flush (storage stall) fills the queue and everything past
  VL_JOURNAL_MAX_QUEUE drops — the emitting query never waits;
- **its own flush thread with its own deadline** — batches drain every
  VL_JOURNAL_FLUSH_MS; a single flush that outlives
  VL_JOURNAL_FLUSH_DEADLINE_MS is counted (``flushes_slow``) so a
  stalling storage is visible on /metrics instead of silent;
- **exempt from admission control** — rows go straight into the
  configured sink's ``must_add_rows`` (the local Storage, or the
  cluster NetInsertStorage on a frontend), never through the HTTP
  admission gate: the journal must not be shed by the very overload it
  is recording;
- **recursion guard** — the flush extent runs under
  ``events.guarded()``, so anything the ingest triggers synchronously
  is counted, not re-journaled (suppression of system-tenant query
  events lives in events.emit);
- **clean shutdown** — ``close()`` unsubscribes, stops the thread and
  drains every accepted (non-dropped) event into storage; a dead sink
  at shutdown counts the remainder dropped instead of voiding it.

Topology: the event bus is PROCESS-global, so the intended deployment
is one JournalWriter per process (the server's).  Multiple servers in
one process (in-process cluster tests) each journal every process-wide
event into their own sink — harmless duplication in tests, not a
production topology.  A writer owns a flush thread and a bus
subscription: it must be ``close()``d (VLServer.close does).
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque

from . import events
from .. import config
from ..storage.log_rows import LogRows, TenantID

APP_NAME = "victorialogs-tpu"

SYSTEM_TENANT_ID = TenantID(events.SYSTEM_ACCOUNT_ID,
                            events.SYSTEM_PROJECT_ID)

# field names the event schema owns; an event field colliding with one
# is prefixed so it cannot corrupt the stream identity or timestamps
_RESERVED = frozenset(("app", "event", "_time", "_msg", "_stream",
                       "_stream_id"))

_writers_mu = threading.Lock()
_writers: "weakref.WeakSet[JournalWriter]" = weakref.WeakSet()


class JournalWriter:
    """One journal: bus subscription + bounded queue + flush thread
    writing LogRows into a sink with ``must_add_rows``.

    Construct via :func:`maybe_start` on servers (honors VL_JOURNAL);
    tests construct directly against a bare Storage."""

    def __init__(self, sink, max_queue: int | None = None,
                 flush_ms: float | None = None, app: str = APP_NAME):
        self.sink = sink
        self.app = app
        self.max_queue = max_queue if max_queue is not None else \
            config.env_int("VL_JOURNAL_MAX_QUEUE")
        if flush_ms is None:
            flush_ms = config.env_int("VL_JOURNAL_FLUSH_MS")
        self.flush_s = max(0.01, flush_ms / 1e3)
        self.flush_deadline_s = max(
            self.flush_s,
            config.env_int("VL_JOURNAL_FLUSH_DEADLINE_MS") / 1e3)
        self._mu = threading.Lock()
        self._q: deque = deque()
        # exact accounting (test-pinned): everything emitted to this
        # writer is either accepted (and eventually written) or dropped
        self.dropped = 0
        # drops at the queue bound were never accepted; the flush-
        # failure/close paths drop ACCEPTED events — check_balanced
        # needs the split, stats()/metrics keep the one public total
        self._dropped_overflow = 0
        self.accepted = 0
        self.rows_written = 0
        self.flushes = 0
        self.flushes_slow = 0
        self.flush_errors = 0
        self._inflight = 0   # batch popped by the flush thread, mid-write
        # test hook: a threading.Event the flush thread waits on before
        # touching storage — simulates a wedged flush (sink stall) the
        # same way sched.inject_fault simulates a failed submit
        self._stall_gate = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="vl-journal", daemon=True)
        events.subscribe(self._on_event)
        self._thread.start()
        with _writers_mu:
            _writers.add(self)

    # -- the bus subscriber (emitter's thread: enqueue-or-drop only) --

    def _on_event(self, ts_ns: int, event: str, fields: dict) -> None:
        with self._mu:
            if len(self._q) >= self.max_queue:
                self.dropped += 1
                self._dropped_overflow += 1
                return
            self._q.append((ts_ns, event, fields))
            self.accepted += 1
            depth = len(self._q)
        if depth * 2 >= self.max_queue:
            # early wake under pressure; the periodic tick handles the
            # common trickle
            self._wake.set()

    # -- the flush thread --

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self._flush_once()
            # vlint: allow-broad-except(journal flusher must survive; errors counted)
            except Exception:
                self.flush_errors += 1

    def inject_flush_stall(self, gate) -> None:
        """Arm the wedged-flush hook: the next flush blocks on
        ``gate.wait()`` before writing (None disarms)."""
        self._stall_gate = gate

    def _flush_once(self) -> None:
        with self._mu:
            if not self._q:
                return
            batch = list(self._q)
            self._q.clear()
            # visible to close(): a join-timeout must account for the
            # batch this thread is holding mid-write
            self._inflight = len(batch)
        gate = self._stall_gate
        if gate is not None:
            gate.wait()
        t0 = time.monotonic()
        lr = LogRows(stream_fields=["app", "event"])
        for ts_ns, event, fields in batch:
            lr.add(SYSTEM_TENANT_ID, ts_ns, self._row_fields(event,
                                                             fields))
        try:
            # the recursion guard: ingest work on THIS thread (datadb
            # backpressure, inline drops, anything storage emits
            # synchronously) is counted, never re-journaled
            with events.guarded():
                self.sink.must_add_rows(lr)
        except BaseException:
            # a failed write (read-only storage, cluster nodes down)
            # must not silently void accepted events: requeue them at
            # the FRONT so the next flush retries in order; whatever
            # the bound can't take back is counted dropped — the
            # accepted == written + dropped + queued invariant holds
            with self._mu:
                room = self.max_queue - len(self._q)
                keep = batch[:max(room, 0)]
                self.dropped += len(batch) - len(keep)
                self._q.extendleft(reversed(keep))
                self._inflight = 0
            raise
        took = time.monotonic() - t0
        # one locked update so accepted == written + dropped + queued
        # + in-flight holds at every instant an observer can look
        # (vlsan sweeps check_balanced between tests)
        with self._mu:
            self._inflight = 0
            self.flushes += 1
            if took > self.flush_deadline_s:
                # a stalling storage must be visible, not silent: the
                # flush deadline is observability, the bounded queue
                # is the actual protection
                self.flushes_slow += 1
            self.rows_written += len(batch)

    def _row_fields(self, event: str, fields: dict) -> list:
        out = [("app", self.app), ("event", event)]
        msg = [event]
        for k in sorted(fields):
            v = fields[k]
            if isinstance(v, float):
                v = format(v, ".6f").rstrip("0").rstrip(".") or "0"
            elif not isinstance(v, str):
                v = str(v)
            if k in _RESERVED:
                k = "f_" + k
            out.append((k, v))
            msg.append(f"{k}={v}")
        out.append(("_msg", " ".join(msg)))
        return out

    # -- introspection / lifecycle --

    def queue_depth(self) -> int:
        with self._mu:
            return len(self._q)

    def stats(self) -> dict:
        with self._mu:
            depth = len(self._q)
        return {
            "queue_depth": depth, "max_queue": self.max_queue,
            "accepted": self.accepted, "dropped": self.dropped,
            "rows_written": self.rows_written, "flushes": self.flushes,
            "flushes_slow": self.flushes_slow,
            "flush_errors": self.flush_errors,
        }

    def check_balanced(self) -> tuple[bool, str]:
        """The accounting invariant on every path (flush failure,
        wedged close, bounded-queue drops included): every event this
        writer ever accepted is written, dropped, queued, or in the
        flush thread's hands right now."""
        with self._mu:
            lhs = self.accepted
            # overflow drops never entered `accepted` — only drops of
            # accepted events (failed flush, wedged close) balance it
            rhs = self.rows_written + \
                (self.dropped - self._dropped_overflow) + \
                len(self._q) + self._inflight
        return lhs == rhs, (f"accepted={lhs} != written+dropped(post-"
                            f"accept)+queued+inflight={rhs}")

    def flush(self) -> None:
        """Synchronous drain (tests / shutdown): write everything
        currently queued."""
        self._flush_once()

    def close(self) -> None:
        """Unsubscribe, stop the thread, drain the queue.  Every event
        accepted (not dropped) before close is in storage afterwards —
        or, when the sink is already dead, counted dropped so the
        accounting stays exact (never silently void)."""
        events.unsubscribe(self._on_event)
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)
        if self._thread.is_alive():
            # a wedged flush outlived the join: the batch it holds is
            # neither written nor queued — count it dropped so the
            # accounting never silently under-reports.  (If the stuck
            # write later lands, dropped over-counts by that batch —
            # preferred over pretending nothing was lost.)
            with self._mu:
                self.dropped += self._inflight
                self._inflight = 0
        try:
            self._flush_once()
        # vlint: allow-broad-except(shutdown drain against an already-closed sink must not fail close; counted)
        except Exception:
            self.flush_errors += 1
            # nothing will ever retry these: the requeued remainder is
            # lost — say so in the drop counter
            with self._mu:
                self.dropped += len(self._q)
                self._q.clear()
        with _writers_mu:
            _writers.discard(self)


def maybe_start(sink) -> JournalWriter | None:
    """The server-side constructor: a JournalWriter when VL_JOURNAL is
    enabled (default), None when killed — the disabled path then has no
    bus subscriber and emit() is structurally free."""
    if not events.journal_enabled():
        return None
    return JournalWriter(sink)


def live_writers() -> list:
    """Every live JournalWriter (the vlsan sweep checks each one's
    accounting invariant after every test)."""
    with _writers_mu:
        return list(_writers)


def metrics_samples() -> list[tuple[str, dict, float]]:
    """Aggregate journal samples for Metrics.render (summed over live
    writers — normally exactly one per process)."""
    with _writers_mu:
        writers = list(_writers)
    agg = {"queue_depth": 0, "dropped": 0, "rows_written": 0,
           "flushes": 0, "flushes_slow": 0, "flush_errors": 0}
    for w in writers:
        s = w.stats()
        for k in agg:
            agg[k] += s[k]
    return [
        ("vl_journal_queue_depth", {}, agg["queue_depth"]),
        ("vl_journal_dropped_total", {}, agg["dropped"]),
        ("vl_journal_rows_written_total", {}, agg["rows_written"]),
        ("vl_journal_flushes_total", {}, agg["flushes"]),
        ("vl_journal_flushes_slow_total", {}, agg["flushes_slow"]),
        ("vl_journal_flush_errors_total", {}, agg["flush_errors"]),
    ]
