"""Active-query registry + per-tenant resource accounting.

Every query execution registers a QueryActivity record for its whole
lifetime (HTTP query/hits/facets/stats/tail, cluster internal-select,
engine-level run_query_collect), carrying the query id, tenant,
endpoint, LogsQL text, start time, current phase and live progress
counters (parts pruned/scanned vs total, blocks killed by bloom, bytes
staged/scanned, dispatches in flight, rows emitted).  The record is the
signal layer the reference serves via /select/logsql/active_queries
(app/vlselect/main.go:240-247) and the admission-control input a
concurrent-query scheduler needs (ROADMAP).

Locking discipline mirrors obs/tracing.py:

- ambient propagation via a contextvar; when no activity is registered
  `current_activity()` returns a shared no-op singleton whose every
  method is a constant-time no-op — instrumented hot paths cost nothing
  for untracked work (engine internals, tests without the registry);
- progress updates are amortized adds onto the record under a
  per-record lock (per dispatch unit / per part / per block — never per
  row), so the hot path gains no new sync points beyond what tracing
  already pays;
- read-side snapshots take the registry lock, then each record's lock —
  one fixed order, no lock cycles (`VLINT_LOCK_ORDER=1` clean).

The API is context-manager-only: `with activity.track(...) as act:` is
what guarantees every registered record deregisters on every exit path
(limit/deadline/cancel/abandon unwinds included) — enforced by the
vlint `accounting-discipline` checker exactly like span-discipline.

Cancellation: `cancel(qid)` (the /select/logsql/cancel_query endpoint)
flips the record's cancel flag; the query's processor-chain head reads
it via is_done(), so the async device pipeline drains its in-flight
window without downstream writes (tpu/pipeline.py PR 3 semantics) and
the serial walk stops at its next block.  Client-disconnect
abandonment rides the same flag via `QueryActivity.abandon()`.

Completed queries land in a 256-entry ring buffer powering
/select/logsql/top_queries (heavy hitters by duration or bytes
scanned).  Per-tenant totals (select seconds, bytes scanned, rows/bytes
ingested, parse failures) accumulate forever and are rendered into
/metrics by server/app.py Metrics.render via metrics_samples().
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import deque

from . import events, hist

_current: contextvars.ContextVar = contextvars.ContextVar(
    "vl_query_activity", default=None)

PHASES = ("queued", "plan", "prune", "scan", "harvest", "emit")

_COMPLETED_MAX = 256

# Process-unique origin token for CLUSTER-wide query identity: local
# qids are a plain per-process counter ("1", "2", ...), so two
# frontends mint colliding qids.  global_qid() prefixes the origin,
# and that spelling is what frontends propagate as `parent_qid` on
# every /internal/select hop — storage-node records tagged with it are
# attributable to exactly one frontend query, cluster-wide.
_ORIGIN = os.urandom(4).hex()


def global_qid(qid) -> str:
    """Cluster-unique spelling of one of THIS process's qids (the
    `parent_qid` value shipped with internal sub-requests and matched
    by the federated active_queries merge)."""
    return f"{_ORIGIN}:{qid}"


def tenant_str(tenant) -> str:
    """Canonical 'account:project' label value for any tenant spelling
    (TenantID, list of tenants, pre-formatted string, None)."""
    if tenant is None:
        return "0:0"
    if isinstance(tenant, str):
        return tenant
    if isinstance(tenant, (list, tuple)):
        return tenant_str(tenant[0]) if tenant else "0:0"
    acc = getattr(tenant, "account_id", None)
    if acc is not None:
        return f"{acc}:{getattr(tenant, 'project_id', 0)}"
    return str(tenant)


class QueryActivity:
    """One live query's registry record.  Construct only via
    activity.track() — see the module docstring (vlint:
    accounting-discipline)."""

    __slots__ = ("qid", "tenant", "endpoint", "query", "start_unix",
                 "start_mono", "exec_mono", "phase", "abandoned", "_mu",
                 "_c", "_cancel", "_phase_t0", "parent_qid")

    enabled = True

    def __init__(self, qid: str, endpoint: str, query: str, tenant: str,
                 parent_qid: str = ""):
        self.qid = qid
        self.endpoint = endpoint
        self.query = query
        self.tenant = tenant
        # the propagated cluster identity: the frontend query this
        # record is a sub-query of (global_qid spelling), or ""
        self.parent_qid = parent_qid
        # vlint: allow-wall-clock(start timestamp shown to operators is real wall time)
        self.start_unix = time.time()
        self.start_mono = time.monotonic()
        self.exec_mono: float | None = None
        self.phase = "plan"
        self.abandoned = False
        self._mu = threading.Lock()
        self._c: dict = {}
        self._cancel = threading.Event()
        self._phase_t0 = self.start_mono

    # -- progress counters (amortized: per unit/part/block, never per row) --
    def add(self, key: str, n=1) -> None:
        with self._mu:
            self._c[key] = self._c.get(key, 0) + n

    def set(self, key: str, value) -> None:
        with self._mu:
            self._c[key] = value

    def set_phase(self, phase: str) -> None:
        # phase timings accumulate into the progress counters
        # (phase_s_<name>) so the completion record — and its journal
        # event — shows where the query's wall time went
        now = time.monotonic()
        with self._mu:
            if phase != self.phase:
                self._fold_phase_locked(now)
                self.phase = phase

    def _fold_phase_locked(self, now: float) -> None:
        """Close the running phase's timer into the counters (caller
        holds _mu; deregistration path)."""
        key = "phase_s_" + self.phase
        self._c[key] = round(
            self._c.get(key, 0.0) + (now - self._phase_t0), 6)
        self._phase_t0 = now

    def relabel(self, endpoint: str = "", query: str = "") -> None:
        """Refine the record's labels once the handler has canonical
        values (the route-level admission layer registers with the raw
        request strings before parsing — see reuse_or_track)."""
        with self._mu:
            if endpoint:
                self.endpoint = endpoint
            if query:
                self.query = query

    def mark_exec_done(self) -> None:
        """Stamp EXECUTION completion — the last dispatch unit
        harvested and the final sink write made — separately from
        response-drain completion (the _Track exit).  The sink side of
        the ROADMAP's exec/drain split: admission's duration EWMA feeds
        on execution time only (sched/admission.py reads exec_mono), so
        a stalled streaming client no longer poisons deadline
        feasibility; query_done journals both exec_s and drain_s.
        First call wins (a tail's repeated polls keep the first)."""
        if self.exec_mono is not None:
            return
        now = time.monotonic()
        self.exec_mono = now
        with self._mu:
            self._c["exec_s"] = round(now - self.start_mono, 6)

    def counter(self, key: str):
        with self._mu:
            return self._c.get(key, 0)

    # -- cancellation --
    def cancel(self) -> None:
        self._cancel.set()

    def abandon(self) -> None:
        """The HTTP peer went away mid-stream: mark the record and trip
        the same cancel flag cancel_query uses, so the pipeline drain
        path stops the device walk instead of finishing a dead query."""
        with self._mu:
            self.abandoned = True
        self._cancel.set()

    def is_cancelled(self) -> bool:
        return self._cancel.is_set()

    def wait_cancelled(self, timeout: float) -> bool:
        """Block up to `timeout` for a cancel/abandon (poll loops like
        /tail sleep on this so cancellation wakes them immediately)."""
        return self._cancel.wait(timeout)

    # -- export --
    def snapshot(self) -> dict:
        with self._mu:
            progress = dict(self._c)
            phase = self.phase
            abandoned = self.abandoned
        out = {
            "qid": self.qid,
            "endpoint": self.endpoint,
            "tenant": self.tenant,
            "query": self.query,
            "phase": phase,
            "start_ts": self.start_unix,
            "duration_s": round(time.monotonic() - self.start_mono, 6),
            "progress": progress,
        }
        if self.parent_qid:
            out["parent_qid"] = self.parent_qid
        if self._cancel.is_set():
            out["cancel_requested"] = True
        if abandoned:
            out["abandoned"] = True
        return out


class _NoopActivity:
    """The ambient record when no query is tracked: every operation is
    a constant-time no-op (shared singleton, no allocation)."""

    __slots__ = ()

    enabled = False
    qid = ""
    tenant = "0:0"
    endpoint = ""
    query = ""
    phase = ""
    abandoned = False
    exec_mono = None
    parent_qid = ""

    def add(self, key, n=1) -> None:
        pass

    def set(self, key, value) -> None:
        pass

    def set_phase(self, phase) -> None:
        pass

    def relabel(self, endpoint="", query="") -> None:
        pass

    def mark_exec_done(self) -> None:
        pass

    def counter(self, key):
        return 0

    def cancel(self) -> None:
        pass

    def abandon(self) -> None:
        pass

    def is_cancelled(self) -> bool:
        return False

    def wait_cancelled(self, timeout: float) -> bool:
        return False

    def snapshot(self) -> dict:
        return {}


_NOOP = _NoopActivity()


def current_activity():
    """This thread's active query record, or the shared no-op singleton
    when no query is being tracked."""
    act = _current.get()
    return act if act is not None else _NOOP


# ---------------- the registry ----------------

# lock order: _reg_mu, then a record's _mu (snapshot/deregister);
# never the reverse
_reg_mu = threading.Lock()
_active: dict[str, QueryActivity] = {}
_completed: deque = deque(maxlen=_COMPLETED_MAX)
_qid_next = 0

# forever-accumulating per-tenant resource totals ("a:p" -> dict);
# the admission-control input for the scheduler PR.  Tenant ids come
# straight from client headers, so the map is hard-capped: once
# _TENANT_MAX distinct tenants exist, new ones aggregate into the
# "other" slot — a client cycling AccountID values can neither leak
# server memory nor explode /metrics label cardinality.
_TENANT_MAX = 1024
_TENANT_OVERFLOW = "other"
_tenant_totals: dict[str, dict] = {}
# per-protocol ingest parse failures ("proto" -> count)
_parse_failures: dict[str, int] = {}


def _next_qid() -> str:
    global _qid_next
    _qid_next += 1
    return str(_qid_next)


def _tenant_slot(tenant: str) -> dict:
    slot = _tenant_totals.get(tenant)
    if slot is None:
        if len(_tenant_totals) >= _TENANT_MAX and \
                tenant != _TENANT_OVERFLOW:
            return _tenant_slot(_TENANT_OVERFLOW)
        slot = _tenant_totals[tenant] = {
            "select_queries": 0, "select_seconds": 0.0,
            "bytes_scanned": 0, "rows_ingested": 0, "bytes_ingested": 0,
        }
    return slot


class _Track:
    """Dynamic extent of one tracked query: registers the record and
    sets the ambient activity on enter; deregisters, restores the
    ambient, and rolls the per-tenant accounting on EVERY exit path."""

    __slots__ = ("_endpoint", "_query", "_tenant", "_act", "_token",
                 "_parent_qid")

    def __init__(self, endpoint: str, query: str, tenant,
                 parent_qid: str = ""):
        self._endpoint = endpoint
        self._query = query
        self._tenant = tenant_str(tenant)
        self._act = None
        self._token = None
        self._parent_qid = parent_qid

    def __enter__(self) -> QueryActivity:
        with _reg_mu:
            qid = _next_qid()
            act = QueryActivity(qid, self._endpoint, self._query,
                                self._tenant,
                                parent_qid=self._parent_qid)
            _active[qid] = act
        self._act = act
        self._token = _current.set(act)
        return act

    def __exit__(self, exc_type, exc, tb) -> bool:
        act = self._act
        _current.reset(self._token)
        duration = time.monotonic() - act.start_mono
        if act.abandoned:
            status = "abandoned"
        elif act.is_cancelled():
            status = "cancelled"
        elif exc_type is not None:
            status = exc_type.__name__
        else:
            status = "ok"
        with act._mu:
            act._fold_phase_locked(time.monotonic())
            progress = dict(act._c)
        if act.exec_mono is not None:
            # exec/drain split: exec_s was stamped at the last harvest
            # (mark_exec_done); everything after is response drain —
            # the part a slow client owns, not the engine
            progress["drain_s"] = round(
                max(duration - progress.get("exec_s", 0.0), 0.0), 6)
        cost_error = _fold_cost_errors(progress, status, duration)
        rec = {
            "qid": act.qid, "endpoint": act.endpoint,
            "tenant": act.tenant, "query": act.query,
            "start_ts": act.start_unix,
            "duration_s": round(duration, 6),
            "status": status,
            "bytes_scanned": progress.get("bytes_scanned", 0),
            "rows_emitted": progress.get("rows_emitted", 0),
            "progress": progress,
        }
        if cost_error is not None:
            # what top_queries?by=cost_error sorts on: the dimension
            # the plan-time pricing got MOST wrong for this query
            rec["cost_error"] = cost_error
        if act.parent_qid:
            # the propagated cluster identity survives into the
            # completed ring (federated top_queries attribution) and
            # the query_done journal event below
            rec["parent_qid"] = act.parent_qid
        with _reg_mu:
            _active.pop(act.qid, None)
            if len(_completed) == _COMPLETED_MAX:
                # the ring is full: this append evicts the oldest
                # record — previously a silent truncation
                events.note("top_queries_evicted")
            _completed.append(rec)
            slot = _tenant_slot(act.tenant)
            slot["select_queries"] += 1
            slot["select_seconds"] += duration
            slot["bytes_scanned"] += progress.get("bytes_scanned", 0)
        # query-lifecycle completion onto the event bus (outside every
        # lock; system-tenant completions are suppressed there — the
        # journal must not journal queries against itself)
        extra = {"parent_qid": act.parent_qid} if act.parent_qid else {}
        events.emit("query_done", tenant=act.tenant, qid=act.qid,
                    endpoint=act.endpoint, status=status,
                    duration_ms=round(duration * 1e3, 3), **extra,
                    **{k: v for k, v in sorted(progress.items())
                       if isinstance(v, (int, float))})
        return False


def _fold_cost_errors(progress: dict, status: str,
                      duration: float) -> float | None:
    """Predicted-vs-actual accountability at deregister: fold the
    plan-time predicted_* counters (obs/explain.price_into_activity)
    against this run's actuals into per-dimension relative errors —
    cost_err_* fields on the completion record / query_done event, and
    the vl_cost_model_rel_error_* histograms so EWMA drift is
    alarmable.  Returns the worst dimension's error (the
    top_queries?by=cost_error sort key), or None for unpriced or
    abnormally-ended queries (a cancelled walk's actuals measure the
    cancel point, not the model)."""
    if status != "ok" or "predicted_duration_s" not in progress:
        return None
    # the prediction prices the planned EXECUTION (prune/scan/harvest/
    # emit): drain belongs to the client, and the queued/plan phases
    # (admission wait, parse, the pricing walk itself) precede the plan
    # being priced — both come off the actual before comparing
    actual_d = progress.get("exec_s") or duration
    actual_d = max(actual_d - progress.get("phase_s_queued", 0.0)
                   - progress.get("phase_s_plan", 0.0), 1e-6)
    errs = {}
    pd = progress["predicted_duration_s"]
    errs["duration"] = abs(actual_d - pd) / max(actual_d, 1e-6)
    hist.COST_ERR_DURATION.observe(errs["duration"])
    pb = progress.get("predicted_bytes")
    if pb is not None:
        ab = progress.get("bytes_scanned", 0)
        errs["bytes"] = abs(ab - pb) / max(ab, 1.0) if (ab or pb) \
            else 0.0
        hist.COST_ERR_BYTES.observe(errs["bytes"])
    pn = progress.get("predicted_dispatches")
    if pn is not None:
        an = progress.get("dispatches_submitted", 0)
        errs["dispatches"] = abs(an - pn) / max(an, 1.0) if (an or pn) \
            else 0.0
        hist.COST_ERR_DISPATCHES.observe(errs["dispatches"])
    for k, v in errs.items():
        progress[f"cost_err_{k}"] = round(v, 6)
    return round(max(errs.values()), 6)


def track(endpoint: str, query: str, tenant=None,
          parent_qid: str = "") -> _Track:
    """Register one query execution for its dynamic extent; the ONLY
    way to mint a QueryActivity (context-manager-only, enforced by the
    vlint accounting-discipline checker).  ``parent_qid`` tags a
    cluster sub-query with its frontend query's global_qid."""
    return _Track(endpoint, query, tenant, parent_qid=parent_qid)


class _ReuseOrTrack:
    """Reuse the ambient record (relabeling it with the handler's
    canonical endpoint/query) or fall back to registering a new one.

    The admission layer (server/app.py) registers the record at the
    HTTP route — BEFORE query parsing, so a QUEUED query is already
    visible in active_queries and cancellable by qid — and the handler
    then enters its own tracking scope on the same thread.  Reusing
    the ambient record keeps it ONE query = ONE record (per-tenant
    select counters stay exact); handlers called without the route
    layer (tests, embedded use) still self-register."""

    __slots__ = ("_endpoint", "_query", "_tenant", "_inner")

    def __init__(self, endpoint: str, query: str, tenant):
        self._endpoint = endpoint
        self._query = query
        self._tenant = tenant
        self._inner = None

    def __enter__(self) -> QueryActivity:
        act = _current.get()
        if act is not None and act.enabled:
            act.relabel(self._endpoint, self._query)
            return act
        self._inner = _Track(self._endpoint, self._query, self._tenant)
        return self._inner.__enter__()

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._inner is not None:
            return self._inner.__exit__(exc_type, exc, tb)
        return False


def reuse_or_track(endpoint: str, query: str,
                   tenant=None) -> _ReuseOrTrack:
    """Handler-level tracking scope: reuse the route-registered ambient
    record or register one (context-manager-only, enforced like
    track)."""
    return _ReuseOrTrack(endpoint, query, tenant)


class _UseActivity:
    """Re-enter an existing record in another thread — the propagation
    shim for worker fan-outs (partition workers, streamwork's query
    thread, the staging prefetch worker).  Does NOT deregister."""

    __slots__ = ("_act", "_token")

    def __init__(self, act):
        self._act = act
        self._token = None

    def __enter__(self):
        if self._act is not None and self._act.enabled:
            self._token = _current.set(self._act)
        return self._act

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _current.reset(self._token)
        return False


def use_activity(act) -> _UseActivity:
    return _UseActivity(act)


# ---------------- registry reads / control ----------------

def active_snapshot(tenant: str | None = None) -> list[dict]:
    """Live records, registration order (the /select/logsql/
    active_queries payload).  ``tenant`` ("a:p") scopes the view to one
    tenant's queries."""
    with _reg_mu:
        acts = list(_active.values())
    snaps = [a.snapshot() for a in acts]
    if tenant is not None:
        snaps = [s for s in snaps if s.get("tenant") == tenant]
    return snaps


def cancel(qid: str) -> bool:
    """Flip a live query's cancel flag (POST /select/logsql/
    cancel_query).  False when no such query is active."""
    with _reg_mu:
        act = _active.get(str(qid))
    if act is None:
        return False
    act.cancel()
    return True


def cancel_by_parent(parent_qid: str) -> int:
    """Trip the cancel flag of every live record registered under
    ``parent_qid`` — the cluster cancel-propagation path (POST
    /internal/select/cancel): the flag folds into the processor head's
    is_done() exactly like a local cancel, so each sub-query's device
    window drains immediately instead of waiting for the frontend
    disconnect probe.  Returns how many records were cancelled."""
    if not parent_qid:
        return 0
    with _reg_mu:
        acts = [a for a in _active.values()
                if a.parent_qid == parent_qid]
    for a in acts:
        a.cancel()
    return len(acts)


# the top_queries sort dimensions (a request with anything else is a
# client error — server/app.py maps the ValueError to HTTP 400)
TOP_QUERIES_BY = ("duration", "bytes", "bytes_scanned", "cost_error")


def top_sort_key(by: str) -> tuple[str, float]:
    """(record key, missing-value default) for one top_queries sort
    dimension — shared by the local ring sort below and the federated
    cluster merge (server/cluster.py), so the two can never order
    differently.  Raises ValueError on an unknown ``by``."""
    if by not in TOP_QUERIES_BY:
        raise ValueError(
            f"invalid by={by!r}; allowed: {', '.join(TOP_QUERIES_BY)}")
    if by == "cost_error":
        return "cost_error", -1.0
    if by in ("bytes", "bytes_scanned"):
        return "bytes_scanned", 0
    return "duration_s", 0


def top_queries(n: int = 10, by: str = "duration",
                tenant: str | None = None) -> list[dict]:
    """Heavy hitters from the completed-query ring buffer, most
    expensive first.  by='duration' | 'bytes' — or 'cost_error' for
    the queries the plan-time cost model priced WORST (unpriced
    records sort last); anything else raises ValueError.  ``tenant``
    scopes the ring to one tenant's completions."""
    key, default = top_sort_key(by)
    with _reg_mu:
        recs = [r for r in _completed
                if tenant is None or r.get("tenant") == tenant]
    recs.sort(key=lambda r: r.get(key, default), reverse=True)
    return recs[:max(n, 0)]


def completed_snapshot() -> list[dict]:
    with _reg_mu:
        return list(_completed)


# ---------------- ingest-side accounting ----------------

def note_ingest(tenant, rows: int, nbytes: int = 0) -> None:
    """Per-tenant ingest accounting (called per accepted request/batch
    from the insert handlers — amortized, never per row)."""
    t = tenant_str(tenant)
    with _reg_mu:
        slot = _tenant_slot(t)
        slot["rows_ingested"] += rows
        slot["bytes_ingested"] += nbytes


def note_parse_failure(protocol: str) -> None:
    with _reg_mu:
        _parse_failures[protocol] = _parse_failures.get(protocol, 0) + 1


def usage_snapshot() -> dict:
    """This node's resource-usage snapshot for GET /internal/usage —
    the payload the cluster-stats poll loop (obs/clusterstats.py) pulls
    from every storage node: the forever-accumulating per-tenant
    totals plus the live registry depth.  Counters are monotonic, so
    the frontend rollup can sum last-seen values without re-reading
    history."""
    with _reg_mu:
        tenants = {t: dict(slot) for t, slot in _tenant_totals.items()}
        active = len(_active)
    return {"tenants": tenants, "active_queries": active}


# ---------------- /metrics integration ----------------

def metrics_samples() -> list[tuple[str, dict, float]]:
    """(base_name, labels, value) samples for Metrics.render: the
    vl_active_queries gauge by endpoint plus the per-tenant counters the
    scheduler's admission control will consume."""
    out: list[tuple[str, dict, float]] = []
    with _reg_mu:
        by_endpoint: dict[str, int] = {}
        for a in _active.values():
            by_endpoint[a.endpoint] = by_endpoint.get(a.endpoint, 0) + 1
        tenants = {t: dict(slot) for t, slot in _tenant_totals.items()}
        failures = dict(_parse_failures)
    # the unlabeled total is always present (a scrape of an idle server
    # still shows the gauge at 0); per-endpoint splits ride alongside
    out.append(("vl_active_queries", {}, sum(by_endpoint.values())))
    for ep, n in sorted(by_endpoint.items()):
        out.append(("vl_active_queries", {"endpoint": ep}, n))
    for t, slot in sorted(tenants.items()):
        lbl = {"tenant": t}
        out.append(("vl_tenant_select_queries_total", lbl,
                    slot["select_queries"]))
        out.append(("vl_tenant_select_seconds_total", lbl,
                    slot["select_seconds"]))
        out.append(("vl_tenant_bytes_scanned_total", lbl,
                    slot["bytes_scanned"]))
        out.append(("vl_tenant_rows_ingested_total", lbl,
                    slot["rows_ingested"]))
        out.append(("vl_tenant_ingest_bytes_total", lbl,
                    slot["bytes_ingested"]))
    for proto, n in sorted(failures.items()):
        out.append(("vl_ingest_parse_failures_total", {"type": proto}, n))
    return out


# ---------------- scan-cost estimation ----------------

def part_bytes_per_row(part) -> float:
    """Uncompressed bytes per row of a part — the bytes_scanned
    estimator's unit cost (file parts carry exact meta; in-memory parts
    get a nominal figure)."""
    meta = getattr(part, "meta", None)
    nrows = getattr(part, "num_rows", 0)
    if meta and nrows:
        return meta.get("uncompressed_size", 0) / nrows
    return 64.0


def note_part_scanned(act, part, bis) -> None:
    """One part's candidate blocks entered the scan: the
    parts/rows/bytes progress adds in ONE place, shared by the serial
    walk (engine/searcher._scan_parts) and the device planner
    (tpu/pipeline._unit_stream) so the estimator can't diverge."""
    if not act.enabled or not bis:
        return
    rows = sum(part.block_rows(bi) for bi in bis)
    act.add("parts_scanned")
    act.add("rows_scanned", rows)
    act.add("bytes_scanned", int(rows * part_bytes_per_row(part)))
