"""Observability layer: per-query hierarchical tracing (tracing.py),
fixed-bucket Prometheus histograms (hist.py), the slow-query log
(slowlog.py), the active-query registry with per-tenant resource
accounting (activity.py — /select/logsql/active_queries, cancel_query,
top_queries, vl_tenant_* /metrics series), query EXPLAIN with priced
physical plans and continuous cost-model error tracking (explain.py —
?explain=1/analyze, predicted_* on every query,
vl_cost_model_rel_error_* histograms), and the self-telemetry
journal: a process-wide structured event bus (events.py) whose
subscriber (journal.py) batches operational events — query
completions, admission sheds, merges/flushes, faults, slow queries —
into LogRows under the reserved system tenant (0, 0xFFFFFFFE), so the
database's own behavior is LogsQL-queryable with the engine it ships.

The tracing design constraint is that the DISABLED path must cost
nothing measurable on the hot query path: `tracing.current_span()`
returns a shared no-op singleton whenever no trace is active, and every
span operation on it (span()/set()/add()) is a constant-time no-op with
no allocation — asserted by tests/test_obs.py.  Real spans only exist
inside a `tracing.activate(root)` dynamic extent, which the query
handlers enter when the request carries `?trace=1` (or the slow-query
log is armed).
"""
