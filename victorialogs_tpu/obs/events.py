"""Process-wide structured event bus: the database's own operational
events as data.

Every layer reports what it does through ``emit(event, **fields)`` —
query completions (obs/activity.py deregister), admission sheds and
sched_config changes (sched/admission.py), scheduler fault injections
(sched/scheduler.py), storage merges/flushes/part GC (storage/
datadb.py), bloom-bank budget declines (storage/filterbank.py), slow-
query lines (obs/slowlog.py), pipeline window drains (tpu/pipeline.py)
and HTTP server errors (server/app.py).  Subscribers (obs/journal.py's
JournalWriter) turn those events into LogRows under the reserved
system tenant so the database logs itself into itself, queryable with
LogsQL — the VictoriaMetrics ecosystem's self-monitoring practice
(PAPER.md L1 vendored logger/metrics) closed into a loop.

Design constraints (the point of the subsystem):

- **structurally zero-cost when off** — ``emit()``'s first action is a
  single read of the subscriber tuple; with no subscriber (VL_JOURNAL=0
  or simply no journal constructed) it returns before building
  anything, taking a lock, or reading a clock.  Call-site kwargs are
  the only residue, and every instrumented site fires at most once per
  query / merge / shed — never per row or block;
- **never block the caller** — subscribers must enqueue-or-drop;
  a subscriber that raises is counted (``subscriber_errors``) and the
  event is still delivered to the rest;
- **recursion guard** — events produced while *handling* journal work
  must not re-enter the journal: ``guarded()`` marks the current
  thread (the journal's flush extent), and any event attributed to the
  reserved system tenant — explicitly via ``tenant=`` or ambiently via
  the active query record — is counted in ``suppressed`` instead of
  delivered, so queries against the journal and journal-triggered
  storage work cannot self-amplify.

The bus also hosts the small process-wide truncation counters that
previously vanished silently (``note()``): trace children dropped at
MAX_CHILDREN, slow-query lines whose sink write failed, top_queries
ring evictions.  ``metrics_samples()`` renders them (plus the bus's own
emitted/suppressed totals) for server/app.py Metrics.render.
"""

from __future__ import annotations

import threading
import time
from .. import config

# reserved self-telemetry tenant: (AccountID 0, ProjectID 0xFFFFFFFE).
# The project id sits at the top of the uint32 space where no real
# client tenant lives; journal rows are invisible to every normal-
# tenant query because block scans filter on the stream's TenantID
# (engine/searcher.py tenant_set).
SYSTEM_ACCOUNT_ID = 0
SYSTEM_PROJECT_ID = 0xFFFFFFFE
SYSTEM_TENANT = f"{SYSTEM_ACCOUNT_ID}:{SYSTEM_PROJECT_ID}"


def journal_enabled() -> bool:
    """VL_JOURNAL=0 is the kill-switch: server/app.py then never
    constructs a JournalWriter, so the bus has no subscriber and every
    emit() returns at its first instruction."""
    return config.env_flag("VL_JOURNAL")


# subscribers are kept in an immutable tuple swapped under _subs_mu so
# the emit hot path reads ONE global with no lock
_subs_mu = threading.Lock()
_subs: tuple = ()

_tl = threading.local()

_counts_mu = threading.Lock()
# pre-seeded so /metrics always renders the full counter set (a scrape
# of an idle server shows explicit zeros, not absent series)
_counts: dict[str, int] = {
    "emitted": 0,
    "suppressed": 0,
    "subscriber_errors": 0,
    "trace_children_dropped": 0,
    "slowlog_emit_failures": 0,
    "top_queries_evicted": 0,
}


def subscribe(fn) -> None:
    """Register fn(ts_ns, event, fields) — it runs on the EMITTER's
    thread and must enqueue-or-drop, never block."""
    global _subs
    with _subs_mu:
        if fn not in _subs:
            _subs = _subs + (fn,)


def unsubscribe(fn) -> None:
    global _subs
    with _subs_mu:
        # equality, NOT identity: a bound method is a fresh object on
        # every attribute access, so `is` would never match the one
        # subscribe() stored (subscribe's dedup already relies on ==)
        _subs = tuple(s for s in _subs if s != fn)


def subscriber_count() -> int:
    return len(_subs)


class _Guard:
    """Dynamic extent of journal-handling work on this thread: events
    emitted inside are counted, not delivered (see module docstring)."""

    __slots__ = ()

    def __enter__(self) -> "_Guard":
        _tl.depth = getattr(_tl, "depth", 0) + 1
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        _tl.depth -= 1
        return False


def guarded() -> _Guard:
    return _Guard()


def in_guard() -> bool:
    return getattr(_tl, "depth", 0) > 0


def _count(key: str, n: int = 1) -> None:
    with _counts_mu:
        _counts[key] = _counts.get(key, 0) + n


def note(key: str, n: int = 1) -> None:
    """Bump one of the process-wide truncation counters (they render as
    vl_<key>_total on /metrics)."""
    _count(key, n)


def counters() -> dict:
    with _counts_mu:
        return dict(_counts)


def emit(event: str, tenant=None, **fields) -> None:
    """Report one operational event.  ``tenant`` (an 'a:p' string or
    anything obs.activity.tenant_str accepts) attributes the event; the
    system tenant's own events are suppressed (recursion guard).  The
    remaining kwargs become the event's journal fields."""
    subs = _subs
    if not subs:
        return
    if getattr(_tl, "depth", 0):
        _count("suppressed")
        return
    if tenant is not None:
        tenant = tenant if isinstance(tenant, str) else _tenant_str(tenant)
        if tenant == SYSTEM_TENANT:
            _count("suppressed")
            return
        fields.setdefault("tenant", tenant)
    else:
        # ambient attribution: an event fired while executing a query
        # against the system tenant (any worker thread — the activity
        # record propagates via use_activity) must not re-journal
        act = _ambient_activity()
        if act is not None and act.enabled and \
                act.tenant == SYSTEM_TENANT:
            _count("suppressed")
            return
    # vlint: allow-wall-clock(journal rows carry real ingestion timestamps)
    ts_ns = time.time_ns()
    _count("emitted")
    for fn in subs:
        try:
            fn(ts_ns, event, fields)
        # vlint: allow-broad-except(a broken subscriber must never fail the emitting layer)
        except Exception:
            _count("subscriber_errors")


def _tenant_str(tenant) -> str:
    from . import activity
    return activity.tenant_str(tenant)


def _ambient_activity():
    from . import activity
    return activity.current_activity()


def metrics_samples() -> list[tuple[str, dict, float]]:
    """(base, labels, value) samples for Metrics.render: the bus totals
    plus the previously-silent truncation counters."""
    c = counters()
    out = [
        ("vl_journal_events_total", {}, c.pop("emitted", 0)),
        ("vl_journal_suppressed_total", {}, c.pop("suppressed", 0)),
        ("vl_journal_subscriber_errors_total", {},
         c.pop("subscriber_errors", 0)),
    ]
    for key in sorted(c):
        out.append((f"vl_{key}_total", {}, c[key]))
    return out
