"""Query EXPLAIN: priced physical plans and predicted-vs-actual cost
accountability.

Two consumers share one header-only plan walk:

- **`?explain=1`** (server/vlselect.handle_explain): the physical plan
  tree WITHOUT executing — partitions → parts (retained vs killed, with
  the reason: time range, tenant, stream filter, or the aggregate-bloom
  kill citing the filter leaf whose tokens are provably absent) →
  planned dispatch units (pack membership, pad bucket, fused program
  kind), each node annotated with cost-model predictions from the live
  calibration EWMAs (tpu/batch.CostModel.peek — never the lazy RTT
  probe, so a plain explain performs ZERO device dispatches and reads
  nothing past part headers, stream indexes and bloom sidecars).
  `?explain=analyze` executes the query and grafts actuals onto the
  same tree — per-unit dispatch_rtt_s/emit_s from the PR 4 span tree,
  query-level counters from the PR 6 activity record — sourced, never
  recomputed.  Cluster frontends merge per-node trees under
  `storage_node` nodes exactly like `?trace=1`
  (server/cluster.NetSelectStorage.net_explain).

- **continuous pricing** (engine/searcher hooks `predict_query` at plan
  time for every device-path query): the same walk at part granularity
  writes `predicted_duration_s` / `predicted_bytes` /
  `predicted_dispatches` onto the activity record, so `query_done`
  journal events carry predicted-vs-actual pairs, /metrics grows
  `vl_cost_model_rel_error_*` histograms (obs/activity computes the
  errors at deregister), and `top_queries?by=cost_error` surfaces the
  queries the model prices worst.  `predicted_duration_s` is shaped for
  sched/admission.py's deadline-feasibility gate to consume in a
  follow-up (a per-QUERY run estimate instead of the per-endpoint
  EWMA).  `VL_QUERY_PRICING=0` kills the continuous pass.

The plan walk deliberately REUSES the execution planner's own pieces —
`candidate_blocks` header selection, `filterbank.aggregate_kill_leaf`,
`pipeline.iter_pack_groups` pack membership, `CostModel` rates — so the
displayed plan cannot diverge from what a real run would dispatch.
"""

from __future__ import annotations

from .. import config

from . import activity, tracing

# cold-model host rates (CostModel defaults) for runner-less plans
_HOST_ONLY_PEEK = {
    "rtt_s": 0.0, "unit_rtt_s": 0.0, "dev_bytes_per_s": 1.0,
    "emit_unit_s": 0.0, "host_rows_per_s": 12e6,
    "host_stats_rows_per_s": 30e6, "upload_bytes_per_s": 1e9,
    "calibrated": False, "force": "host",
}

# the cost model's whole-query byte-per-row figure for device scan
# traffic (tpu/batch._gate_host_est's W estimate)
_SCAN_BYTES_PER_ROW = 128


def pricing_enabled() -> bool:
    """VL_QUERY_PRICING=0 kills the continuous plan-time pricing pass
    (the explain endpoints stay available either way)."""
    return config.env_flag("VL_QUERY_PRICING")


# ---------------- the plan walk ----------------

def build_plan(storage, tenants, q, runner=None) -> dict:
    """The priced physical plan tree without executing (?explain=1)."""
    return _walk(storage, tenants, q, runner, detail=True)


def predict_query(storage, tenants, q, runner=None) -> dict:
    """The cheap continuous pricing pass: predicted summary only (no
    per-part nodes, no cold aggregate builds — only aggregates a prior
    query already folded are probed, the execution walk that follows
    pays for new ones itself)."""
    return _walk(storage, tenants, q, runner, detail=False)["predicted"]


def _walk(storage, tenants, q, runner, detail: bool) -> dict:
    from ..logsql.filters import (filter_plan_tree,
                                  iter_and_path_token_leaves)
    from ..logsql.parser import MAX_TS, MIN_TS
    from ..storage.log_rows import TenantID
    from ..engine.searcher import _collect_stream_filters

    if isinstance(tenants, TenantID):
        tenants = [tenants]
    tenants = tuple(tenants)
    tenant_set = set(tenants)
    min_ts, max_ts = q.get_time_range()

    batch = runner is not None and hasattr(runner, "run_part")
    peek = runner.cost.peek() if batch else dict(_HOST_ONLY_PEEK)
    stats_spec = sort_spec = None
    plans = []
    fused = False
    if batch:
        from ..tpu.batch import device_plans
        from ..tpu.fused import fused_filter_enabled
        plans = device_plans(q.filter)
        fused = fused_filter_enabled() and runner.fused_enabled
        if hasattr(runner, "run_part_stats"):
            from ..tpu.stats_device import device_stats_spec
            stats_spec = device_stats_spec(q)
        if stats_spec is None and hasattr(runner, "run_part_topk"):
            from ..tpu.sort_device import device_sort_spec
            sort_spec = device_sort_spec(q)
    shape = "stats" if stats_spec is not None else \
        "topk" if sort_spec is not None else "rows"

    sfs: list = []
    _collect_stream_filters(q.filter, sfs)
    token_leaves = list(iter_and_path_token_leaves(q.filter))
    if batch:
        # the SAME depth derivation the window dispatches with, minus
        # the lazy RTT probe (explain must stay zero-dispatch)
        from ..tpu.pipeline import inflight_depth
        depth = inflight_depth(runner, probe=False)
    else:
        depth = 1

    tree: dict = {
        "name": "explain",
        "mode": "plan",
        "query": q.to_string(),
        "shape": shape,
        "executor": "device" if batch else "host",
        "fused_filter": bool(fused),
        "inflight_depth": depth,
        "time_range": {
            "min_ts": None if min_ts == MIN_TS else min_ts,
            "max_ts": None if max_ts == MAX_TS else max_ts,
        },
        "partitions": [],
    }
    if detail:
        tree["filter"] = filter_plan_tree(q.filter)

    tot = {"parts_total": 0, "parts_retained": 0, "parts_killed": 0,
           "parts_cached": 0, "blocks_candidate": 0, "rows_scanned": 0,
           "bytes_scanned": 0, "dispatches": 0, "bytes_staged": 0}
    cost = {"rtt_s": 0.0, "device_scan_s": 0.0, "upload_s": 0.0,
            "emit_s": 0.0, "host_s": 0.0}

    # result-cache peek (engine/standing/resultcache.py): parts whose
    # answer would replay from the cache are priced ~0 — the admission
    # layer then charges a repeated query only its post-cache residual
    # scan (price-after-cache).  peek touches no counters and no LRU
    # state, so explain=1 stays a pure read.
    from ..engine.standing.resultcache import QueryCache
    qcache = QueryCache.for_query(q, tenants, stats_spec, sort_spec,
                                  min_ts, max_ts)

    from ..tpu import pipeline as _pipeline
    cross = batch and _pipeline.cross_partition_enabled()
    active_pts = 0
    retained_all: list = []   # (pnode, part, bis, rows_cand, bytes_est)
    for pt in storage.select_partitions(min_ts, max_ts):
        pnode, retained = _walk_partition(
            pt, tenants, tenant_set, min_ts, max_ts, sfs,
            token_leaves, detail, tot, qcache)
        if retained:
            active_pts += 1
        retained_all.extend((pnode, p, b, rc, be)
                            for p, b, rc, be in retained)
        if detail:
            tree["partitions"].append(pnode)

    # planned dispatch units: THE pack-membership rules the window
    # dispatches with (pipeline.pack_policy + iter_pack_groups), run
    # over the CROSS-PARTITION retained stream exactly like the
    # execution planner — packs may span a day boundary, and the unit
    # seq is global (it matches the window's submit/harvest span
    # numbering, which _graft keys on).  A unit node hangs off the
    # partition of its FIRST member.  VL_CROSS_PARTITION=0 groups per
    # partition like the old drain-at-boundary walk did.
    _price_units(retained_all, runner, batch, peek, plans, shape,
                 fused, sort_spec, depth, detail, tot, cost,
                 per_partition=not cross)

    if not detail:
        tree.pop("partitions")

    # host-path per-day partitions scan concurrently under the worker
    # cap (engine/searcher._scan_partitions_parallel), so wall time
    # divides by the effective partition parallelism.  The device
    # path's cross-partition window overlaps round trips ACROSS
    # partitions already (depth folded above): no extra parallelism.
    npw = 1 if cross else max(1, min(active_pts, q.get_concurrency()))
    duration = sum(cost.values()) / npw
    tree["predicted"] = dict(tot)
    tree["predicted"].update({k: round(v, 6) for k, v in cost.items()})
    tree["predicted"]["duration_s"] = round(duration, 6)
    tree["predicted"]["calibrated"] = peek["calibrated"]
    return tree


def _maplet_exact(part, token_leaves, bis):
    """(exact_bis, killing_leaf, have_maplet): the sealed part's exact
    AND-path candidate blocks from its token→block maplets.  Pure
    probe — no trace/registry side effects, so both the explain
    endpoint and the continuous pricing pass may call it; the AND
    semantics live in ONE place (filterbank.maplet_leaf_keep, shared
    with the execution pruning).  Classic parts return
    (bis, None, False): their candidates stay the probabilistic
    per-block estimate."""
    from ..storage.filterbank import maplet_leaf_keep
    from ..storage.filterindex import part_index
    fi = part_index(part)
    if fi is None:
        return bis, None, False
    keep, kill_leaf = maplet_leaf_keep(fi, token_leaves, bis)
    if kill_leaf is not None:
        return [], kill_leaf, True
    if keep is None:
        return bis, None, True
    return [bi for bi, k in zip(bis, keep) if k], None, True


def _part_header_table(part) -> dict:
    """Per-part header summary cached on the (immutable) part object —
    the pricing walk runs on EVERY query, so the per-block header
    object churn (stream ids, row counts) is paid once per part
    lifetime instead of once per query.  Same attach idiom as
    storage/filterbank.filter_bank."""
    t = getattr(part, "_explain_htab", None)
    if t is None:
        nb = part.num_blocks
        sids = [part.block_stream_id(bi) for bi in range(nb)]
        rows = [part.block_rows(bi) for bi in range(nb)]
        tset = {s.tenant for s in sids}
        t = {
            "sids": sids, "rows": rows, "rows_total": sum(rows),
            "uniform_tenant": next(iter(tset)) if len(tset) == 1
            else None,
        }
        part._explain_htab = t
    return t


def _walk_partition(pt, tenants, tenant_set, min_ts, max_ts, sfs,
                    token_leaves, detail, tot, qcache=None):
    from ..storage.filterbank import aggregate_kill_leaf

    pnode: dict = {"name": "partition",
                   "day": getattr(pt, "day", None),
                   "parts": [], "units": []}
    allowed_sids = None
    if sfs:
        allowed_sids = set.intersection(
            *(f.resolve(pt, tenants) for f in sfs))
        if not allowed_sids:
            pnode["pruned_by_stream_filter"] = True
            return pnode, []

    retained: list = []      # (part, bis, rows_cand, bytes_est)
    for part in pt.ddb.snapshot_parts():
        if not part.num_rows:
            continue
        tot["parts_total"] += 1
        # per-part detail nodes only exist on the explain endpoint; the
        # continuous pricing pass (detail=False, every query) must not
        # allocate throwaway dicts per part
        node: dict = {"part": str(part.uid), "rows": part.num_rows,
                      "blocks": part.num_blocks} if detail else {}
        if part.min_ts > max_ts or part.max_ts < min_ts:
            tot["parts_killed"] += 1
            if detail:
                node.update(status="killed", reason="time_range")
                pnode["parts"].append(node)
            continue
        bis: list = []
        rows_cand = 0
        n_time = n_tenant = 0
        if part.min_ts >= min_ts and part.max_ts <= max_ts:
            # part fully inside the range: every block is a time
            # candidate — the cached header table answers the tenant/
            # stream filtering without touching header groups
            htab = _part_header_table(part)
            sids, rows = htab["sids"], htab["rows"]
            n_time = len(sids)
            if htab["uniform_tenant"] is not None and \
                    htab["uniform_tenant"] not in tenant_set:
                pass                       # n_tenant stays 0: killed
            elif htab["uniform_tenant"] is not None and \
                    allowed_sids is None:
                n_tenant = n_time
                bis = list(range(n_time))
                rows_cand = htab["rows_total"]
            else:
                for bi, sid in enumerate(sids):
                    if sid.tenant not in tenant_set:
                        continue
                    n_tenant += 1
                    if allowed_sids is not None and \
                            sid not in allowed_sids:
                        continue
                    bis.append(bi)
                    rows_cand += rows[bi]
        else:
            block_sid = part.block_stream_id
            block_rows = part.block_rows
            for bi in part.candidate_blocks(min_ts, max_ts):
                n_time += 1
                sid = block_sid(bi)
                if sid.tenant not in tenant_set:
                    continue
                n_tenant += 1
                if allowed_sids is not None and sid not in allowed_sids:
                    continue
                bis.append(bi)
                rows_cand += block_rows(bi)
        if not bis:
            tot["parts_killed"] += 1
            if detail:
                node.update(status="killed",
                            reason="time_range" if n_time == 0 else
                            "tenant" if n_tenant == 0 else
                            "stream_filter")
                pnode["parts"].append(node)
            continue
        if token_leaves:
            # detailed plans apply the execution walk's own build gate;
            # the cheap continuous pass probes CACHED aggregates only
            # (build=False) — with the result memo those repeats are
            # dict lookups, and a cold part the execution would build+
            # kill shows up as prediction error instead of a second
            # cold fold per query.  Sealed v2 parts (filter-index
            # sidecar) answer either way from the loaded xor aggregate.
            killed = aggregate_kill_leaf(
                part, token_leaves,
                build=detail and len(bis) * 4 >= part.num_blocks)
            if killed is not None:
                field, tokens, f, artifact = killed
                tot["parts_killed"] += 1
                if detail:
                    node.update(status="killed",
                                reason="xor_aggregate"
                                if artifact == "xor_aggregate"
                                else "aggregate_bloom",
                                killed_by={"field": field,
                                           "tokens": list(tokens),
                                           "filter": f.to_string(),
                                           "artifact": artifact})
                    pnode["parts"].append(node)
                continue
            # sealed v2 parts: the token→block maplet yields the EXACT
            # candidate block list for the AND-path leaves — priced
            # units reflect what the execution walk will dispatch, and
            # an emptied list kills the part with the maplet cited
            exact_bis, kill_leaf, have_maplet = _maplet_exact(
                part, token_leaves, bis)
            if kill_leaf is not None:
                field, tokens, f = kill_leaf
                tot["parts_killed"] += 1
                if detail:
                    node.update(status="killed", reason="maplet",
                                killed_by={"field": field,
                                           "tokens": list(tokens),
                                           "filter": f.to_string(),
                                           "artifact": "maplet"})
                    pnode["parts"].append(node)
                continue
            if have_maplet and len(exact_bis) != len(bis):
                bis = exact_bis
                rows_cand = sum(part.block_rows(bi) for bi in bis)
                if detail:
                    node["maplet_exact"] = True
        if qcache is not None and qcache.peek(part, bis):
            # the part's answer replays from the result cache: it is
            # retained but priced ~0 (no dispatch, no bytes scanned) —
            # the dashboard-refresh query pays only its unsealed head
            tot["parts_retained"] += 1
            tot["parts_cached"] += 1
            if detail:
                node.update(status="retained", cached=True,
                            blocks_candidate=len(bis))
                pnode["parts"].append(node)
            continue
        bytes_est = int(rows_cand * activity.part_bytes_per_row(part))
        tot["parts_retained"] += 1
        tot["blocks_candidate"] += len(bis)
        tot["rows_scanned"] += rows_cand
        tot["bytes_scanned"] += bytes_est
        if detail:
            node.update(status="retained", blocks_candidate=len(bis),
                        rows_candidate=rows_cand, bytes_est=bytes_est)
            pnode["parts"].append(node)
        retained.append((part, bis, rows_cand, bytes_est))

    return pnode, retained


def _price_units(retained_all, runner, batch, peek, plans, shape,
                 fused, sort_spec, depth, detail, tot, cost,
                 per_partition: bool) -> None:
    """Group the retained-part stream into planned dispatch units and
    price each one.  retained_all: (pnode, part, bis, rows, bytes)
    tuples in partition-walk order — grouping runs over the WHOLE
    stream (cross-partition window) or restarts at each partition
    boundary (per_partition=True, the VL_CROSS_PARTITION=0 walk); the
    unit seq is global either way, matching the execution window's
    submit/harvest span numbering."""
    from ..tpu import pipeline
    if not retained_all:
        return
    by_part = {p.uid: (rc, be) for _pn, p, _b, rc, be in retained_all}
    pnode_of = {p.uid: pn for pn, p, _b, _rc, _be in retained_all}
    if batch:
        packable, pack_max, rows_cap = pipeline.pack_policy(
            runner, sort_spec, probe=False)

        def groups_of(items):
            return pipeline.iter_pack_groups(items, packable, pack_max,
                                             rows_cap)
    else:
        def groups_of(items):
            return ([it] for it in items)

    def runs():
        if not per_partition:
            yield [(p, b) for _pn, p, b, _rc, _be in retained_all]
            return
        run: list = []
        cur = None
        for pn, p, b, _rc, _be in retained_all:
            if cur is not None and pn is not cur:
                yield run
                run = []
            cur = pn
            run.append((p, b))
        if run:
            yield run

    seq = 0
    for run in runs():
        for group in groups_of(iter(run)):
            unode = _price_unit(seq, group, by_part, runner, batch,
                                peek, plans, shape, fused, depth,
                                cost, tot, detail)
            seq += 1
            if detail and unode is not None:
                pnode_of[group[0][0].uid]["units"].append(unode)


def _price_unit(seq, group, by_part, runner, batch, peek, plans,
                shape, fused, depth, cost, tot,
                detail: bool) -> dict | None:
    from ..tpu import pipeline

    rows = sum(by_part[p.uid][0] for p, _b in group)
    nbytes = sum(by_part[p.uid][1] for p, _b in group)
    blocks = sum(len(b) for _p, b in group)
    scan_bytes = rows * _SCAN_BYTES_PER_ROW
    # topk units gate exactly like stats units do at execution time
    # (run_part_topk_submit passes stats_rows=cand_rows): one fused
    # dispatch whose host alternative pays the aggregate-scan rate
    stats_rows = rows if shape in ("stats", "topk") else 0

    cold = 0
    n_dispatch = 0
    if batch and plans:
        # staging keys are per DISPATCH TARGET: a packed unit stages
        # under the pack's uid (tpu/pipeline PackedPart), not its
        # members' — the cold-bytes estimate must probe the same keys
        uid = ("pack",) + tuple(p.uid for p, _b in group) \
            if len(group) > 1 else group[0][0].uid
        for plan in plans:
            key = (uid, "#fl", plan.field) if fused \
                else (uid, plan.field)
            if not runner.cache.contains(key):
                cold += scan_bytes
        n_dispatch = 1 if stats_rows or fused else \
            sum(max(len(p.ops), 1) for p in plans)
    elif batch and stats_rows:
        n_dispatch = 1

    host = _prefers_host(peek, rows, scan_bytes, n_dispatch, cold,
                         stats_rows)
    kind = "host" if host else (
        "stats" if shape == "stats" else
        "topk" if shape == "topk" else
        "fused_filter" if fused else "leaf_filter")

    # the unit detail node exists only for the explain endpoint; the
    # continuous pricing pass keeps the accounting without the dicts
    unode: dict | None = None
    if detail:
        unode = {
            "name": "unit", "seq": seq, "kind": kind,
            "pack": len(group) > 1,
            "members": [str(p.uid) for p, _b in group],
            "pad_bucket": pipeline.pack_bucket(group[0][0]),
            "blocks": blocks, "rows": rows, "bytes_est": nbytes,
        }
    # every planned unit is one pipeline submission (host-gated units
    # included — dispatches_submitted counts them the same way)
    tot["dispatches"] += 1
    if host:
        host_s = rows / peek["host_rows_per_s"] \
            + stats_rows / peek["host_stats_rows_per_s"]
        cost["host_s"] += host_s
        if unode is not None:
            unode["predicted"] = {"host_s": round(host_s, 6)}
        return unode

    tot["bytes_staged"] += cold
    # window-overlapped REAL unit round trip (CostModel.unit_rtt_ewma):
    # at steady state the window amortizes each submit-to-harvest
    # across depth outstanding units
    rtt_s = peek["unit_rtt_s"] / depth
    scan_s = scan_bytes / peek["dev_bytes_per_s"]
    upload_s = 0.25 * cold / peek["upload_bytes_per_s"]
    emit_s = peek["emit_unit_s"]
    cost["rtt_s"] += rtt_s
    cost["device_scan_s"] += scan_s
    cost["upload_s"] += upload_s
    cost["emit_s"] += emit_s
    if unode is not None:
        unode["predicted"] = {
            "bytes_staged_cold": cold,
            "scan_bytes_device": scan_bytes,
            "rtt_s": round(rtt_s, 6),
            "device_scan_s": round(scan_s, 6),
            "emit_s": round(emit_s, 6),
            "duration_s": round(rtt_s + scan_s + upload_s + emit_s,
                                6),
        }
    return unode


def _prefers_host(peek, cand_rows, scan_bytes, n_dispatch, cold_bytes,
                  stats_rows) -> bool:
    """CostModel.prefer_host on peeked rates (no RTT probe)."""
    if peek["force"] == "device":
        return False
    if peek["force"] == "host":
        return True
    if n_dispatch <= 0:
        return True
    est_host = cand_rows / peek["host_rows_per_s"] \
        + stats_rows / peek["host_stats_rows_per_s"]
    est_dev = n_dispatch * peek["rtt_s"] \
        + n_dispatch * scan_bytes / peek["dev_bytes_per_s"] \
        + 0.25 * cold_bytes / peek["upload_bytes_per_s"]
    return est_host < est_dev


# ---------------- continuous pricing (engine hook) ----------------

def price_into_activity(storage, tenants, q, runner, act) -> None:
    """Plan-time pricing for ONE query: predicted summary onto the
    activity record (counters named predicted_* so they ride the
    query_done journal event next to the actuals; obs/activity folds
    the pair into vl_cost_model_rel_error_* at deregister).  Advisory:
    never fails the query."""
    try:
        pred = predict_query(storage, tenants, q, runner)
    # vlint: allow-broad-except(pricing is advisory, the query must run)
    except Exception:
        return
    act.set("predicted_duration_s", pred["duration_s"])
    act.set("predicted_bytes", pred["bytes_scanned"])
    act.set("predicted_dispatches", pred["dispatches"])
    act.set("predicted_rows", pred["rows_scanned"])


# ---------------- explain=analyze grafting ----------------

def analyze(storage, tenants, q, tree, runner=None, deadline=None,
            endpoint="explain", include_trace=False) -> None:
    """Execute the query and graft actuals onto the plan tree.

    Actuals are SOURCED, not recomputed: query-level counters from the
    activity record (PR 6), per-unit dispatch_rtt_s / device_sync /
    emit from the span tree (PR 4) — the same numbers ?trace=1 and
    /metrics report for this run."""
    from ..engine.searcher import run_query

    root = tracing.make_root("query", query=q.to_string())
    rows_emitted = [0]

    def sink(br) -> None:
        rows_emitted[0] += br.nrows

    with activity.reuse_or_track(endpoint, q.to_string(),
                                 tenants[0] if tenants else None) as act:
        root.set("qid", act.qid)
        with tracing.activate(root):
            run_query(storage, tenants, q, write_block=sink,
                      runner=runner, deadline=deadline)
        act.mark_exec_done()
        snap = act.snapshot()
    tdict = root.to_dict()
    _graft(tree, tdict, snap.get("progress", {}), rows_emitted[0])
    if include_trace:
        tree["trace"] = tdict


def _graft(tree, tdict, progress, rows_emitted) -> None:
    tree["mode"] = "analyze"
    actual = {k: v for k, v in sorted(progress.items())
              if isinstance(v, (int, float))}
    actual["rows_emitted"] = rows_emitted
    tree["actual"] = actual
    flat = tracing.flatten_tree(tdict)
    tree["actual_spans"] = {
        name: flat[name]
        for name in ("pipeline", "prune", "stage", "submit", "harvest",
                     "device_sync", "emit", "sched_wait")
        if name in flat}
    _graft_units(tree, tdict)


def _graft_units(tree, tdict) -> None:
    """Per-unit actuals: submit/harvest spans keyed by the pipeline's
    GLOBAL unit sequence — the cross-partition window numbers units
    across the whole query, and the plan walk generated its unit list
    with the same grouping and numbering (pipeline.iter_pack_groups
    both times), so matching is tree-wide."""
    submits: dict = {}
    harvests: dict = {}
    dup = False
    for sp in tracing.iter_tree(tdict, "submit"):
        attrs = sp.get("attrs") or {}
        if "unit" in attrs:
            dup = dup or attrs["unit"] in submits
            submits[attrs["unit"]] = (sp, attrs)
    for sp in tracing.iter_tree(tdict, "harvest"):
        attrs = sp.get("attrs") or {}
        if "unit" in attrs:
            harvests[attrs["unit"]] = (sp, attrs)
    if dup:
        # VL_CROSS_PARTITION=0 restarts the unit sequence at every
        # partition boundary (submit/harvest spans nest under their
        # partition span there), so colliding global seqs mean the
        # compat walk ran: match per partition instead — a partition's
        # i-th planned unit IS its i-th executed unit
        _graft_units_compat(tree, tdict)
        return
    units = [u for pnode in tree.get("partitions", ())
             for u in pnode.get("units", ())]
    for unode in units:
        _attach_actual(unode, submits, harvests, unode.get("seq"))


def _graft_units_compat(tree, tdict) -> None:
    """Per-partition matching for the VL_CROSS_PARTITION=0 walk: each
    partition span subtree carries its own 0-based unit sequence, and
    the plan listed that partition's units in the same order."""
    by_day: dict = {}
    for psp in tracing.iter_tree(tdict, "partition"):
        by_day[(psp.get("attrs") or {}).get("day")] = psp
    for pnode in tree.get("partitions", ()):
        psp = by_day.get(pnode.get("day"))
        if psp is None:
            continue
        submits: dict = {}
        harvests: dict = {}
        for sp in tracing.iter_tree(psp, "submit"):
            attrs = sp.get("attrs") or {}
            if "unit" in attrs:
                submits[attrs["unit"]] = (sp, attrs)
        for sp in tracing.iter_tree(psp, "harvest"):
            attrs = sp.get("attrs") or {}
            if "unit" in attrs:
                harvests[attrs["unit"]] = (sp, attrs)
        for i, unode in enumerate(pnode.get("units", ())):
            _attach_actual(unode, submits, harvests, i)


def _attach_actual(unode, submits, harvests, seq) -> None:
    actual: dict = {}
    got = submits.get(seq)
    if got is not None:
        _sp, attrs = got
        for k in ("rows", "blocks", "slot_wait_s"):
            if k in attrs:
                actual[k] = attrs[k]
    got = harvests.get(seq)
    if got is not None:
        sp, attrs = got
        if "dispatch_rtt_s" in attrs:
            actual["dispatch_rtt_s"] = attrs["dispatch_rtt_s"]
        if attrs.get("host_unit"):
            actual["host_unit"] = True
        for child in sp.get("children", ()):
            if child.get("name") == "device_sync":
                actual["device_sync_s"] = round(
                    child.get("duration_ms", 0.0) / 1e3, 6)
            elif child.get("name") == "emit":
                actual["emit_s"] = round(
                    child.get("duration_ms", 0.0) / 1e3, 6)
    if actual:
        unode["actual"] = actual
