"""Hierarchical per-query span trees (the vltrace core).

A trace is a tree of Spans with monotonic (perf_counter) timings and
typed attributes (counters via add(), values via set()).  The API is
context-manager-only:

    root = tracing.make_root("query", query=qs)
    with tracing.activate(root):            # sets the ambient span
        ...
        sp = tracing.current_span()
        with sp.span("harvest", unit=3) as h:   # child span
            h.add("rows_downloaded", n)
    tree = root.to_dict()

Direct ``Span(...)`` construction and un-with'd ``.span(...)`` calls are
forbidden outside this module by the vlint `span-discipline` checker:
the with-block is what guarantees every span closes on every exit path
(including QueryCancelled / QueryTimeoutError unwinds), which the
no-open-spans tests pin.

Propagation is ambient via a contextvars.ContextVar, so the deep layers
(filterbank prune decisions, the async pipeline window, staging, the
mesh runner) read `current_span()` without any signature threading.
contextvars do NOT cross thread spawns; the three places the query
path hands work to other threads (partition fan-out in engine/searcher,
storage-node fetches in server/cluster, the staging prefetch worker in
tpu/batch.py) re-enter the caller's span with `use_span()`.

When no trace is active, `current_span()` returns _NOOP — a shared
singleton whose span() returns a shared reusable context manager and
whose set()/add() do nothing.  No allocation, no branching beyond the
method call: the disabled path is flat (asserted by test_obs).
"""

from __future__ import annotations

import contextvars
import threading
import time

from . import events

_current: contextvars.ContextVar = contextvars.ContextVar(
    "vl_trace_span", default=None)

# real-span creation counter: tests assert a tracing-disabled workload
# creates exactly zero spans (structural proof of zero overhead)
_created = 0
_created_mu = threading.Lock()

# attrs guard: set()/add() vs to_dict() snapshot — the prefetch worker
# (re-entered via use_span) can write attrs on a span the query thread
# is serializing; only real spans pay this, the no-op path never locks
_attrs_mu = threading.Lock()

# children cap per span: a pathological query must not balloon the
# trace without bound; drops are counted on the parent
# (children_dropped).  The pipeline span accrues ~3 children per
# dispatch unit (prune top-off, submit, harvest), so this covers
# queries beyond ~1300 units — past that the trace head plus the drop
# counter is the documented tradeoff (the tree is already ~MBs there).
MAX_CHILDREN = 4096


def spans_created() -> int:
    return _created


class Span:
    """One node of a trace tree.  Construct only via make_root() /
    parent.span() — see the module docstring (vlint: span-discipline)."""

    __slots__ = ("name", "t0", "t1", "attrs", "children")

    enabled = True

    def __init__(self, name: str, attrs: dict):
        global _created
        self.name = name
        self.t0 = time.perf_counter()
        self.t1 = None
        self.attrs = attrs
        self.children: list = []
        with _created_mu:
            _created += 1

    # -- attributes --
    def set(self, key: str, value) -> None:
        with _attrs_mu:
            self.attrs[key] = value

    def add(self, key: str, n=1) -> None:
        """Accumulate a numeric attribute (counter semantics)."""
        # one shared lock: the prefetch worker (re-entered via
        # use_span) may add to a span the query thread is concurrently
        # serializing — to_dict snapshots under the same lock
        with _attrs_mu:
            self.attrs[key] = self.attrs.get(key, 0) + n

    # -- children --
    def span(self, name: str, **attrs) -> "_SpanCtx":
        """Open a child span; must be used as a context manager."""
        return _SpanCtx(self, name, attrs)

    def attach(self, tree: dict) -> None:
        """Adopt a pre-built span dict (a storage node's remote trace)
        as a child — the scatter-gather merge point."""
        if len(self.children) < MAX_CHILDREN:
            self.children.append(tree)
        else:
            self.add("children_dropped")
            events.note("trace_children_dropped")

    # -- lifecycle --
    def close(self) -> None:
        if self.t1 is None:
            self.t1 = time.perf_counter()

    def open_spans(self) -> int:
        """Descendants (incl. self) not yet closed — 0 after any query
        exit path, including cancellation and deadline unwinds."""
        n = 0 if self.t1 is not None else 1
        for c in self.children:
            if isinstance(c, Span):
                n += c.open_spans()
        return n

    # -- export --
    def to_dict(self, base: float | None = None) -> dict:
        """JSON-ready tree; start_ms is relative to the root's t0 so a
        rendered trace reads as a waterfall."""
        if base is None:
            base = self.t0
        end = self.t1 if self.t1 is not None else time.perf_counter()
        out = {
            "name": self.name,
            "start_ms": round((self.t0 - base) * 1e3, 3),
            "duration_ms": round((end - self.t0) * 1e3, 3),
        }
        with _attrs_mu:
            attrs = dict(self.attrs) if self.attrs else None
        if attrs:
            out["attrs"] = attrs
        if self.children:
            out["children"] = [
                c.to_dict(base) if isinstance(c, Span) else c
                for c in self.children]
        return out

    def flatten(self) -> dict:
        """Per-span-name aggregate {name: {count, total_ms}} — the
        slow-query log's compact summary."""
        agg: dict[str, dict] = {}

        def walk(node) -> None:
            if isinstance(node, Span):
                name = node.name
                end = node.t1 if node.t1 is not None \
                    else time.perf_counter()
                ms = (end - node.t0) * 1e3
                kids = node.children
            else:
                name = node.get("name", "?")
                ms = node.get("duration_ms", 0.0)
                kids = node.get("children", ())
            a = agg.setdefault(name, {"count": 0, "total_ms": 0.0})
            a["count"] += 1
            a["total_ms"] += ms
            for c in kids:
                walk(c)

        walk(self)
        for a in agg.values():
            a["total_ms"] = round(a["total_ms"], 3)
        return agg


def flatten_tree(tree: dict) -> dict:
    """Span.flatten over an EXPORTED to_dict() tree: per-span-name
    aggregates {name: {count, total_ms}}.  The explain=analyze graft
    (obs/explain.py) and cluster-merged traces work on dict trees —
    storage-node frames arrive serialized, never as live Spans."""
    agg: dict[str, dict] = {}

    def walk(node: dict) -> None:
        name = node.get("name", "?")
        a = agg.setdefault(name, {"count": 0, "total_ms": 0.0})
        a["count"] += 1
        a["total_ms"] += node.get("duration_ms", 0.0)
        for c in node.get("children", ()):
            walk(c)

    if tree:
        walk(tree)
    for a in agg.values():
        a["total_ms"] = round(a["total_ms"], 3)
    return agg


def iter_tree(tree: dict, name: str):
    """Yield every node of an exported span tree with the given name
    (depth-first) — the explain graft's span lookup."""
    if not tree:
        return
    stack = [tree]
    while stack:
        node = stack.pop()
        if node.get("name") == name:
            yield node
        stack.extend(node.get("children", ()))


class _SpanCtx:
    """Context manager that creates the child at __enter__ and closes
    it (and restores the ambient span) on every exit path."""

    __slots__ = ("_parent", "_name", "_attrs", "_span", "_token")

    def __init__(self, parent: Span, name: str, attrs: dict):
        self._parent = parent
        self._name = name
        self._attrs = attrs
        self._span = None
        self._token = None

    def __enter__(self) -> Span:
        sp = Span(self._name, self._attrs)
        parent = self._parent
        if len(parent.children) < MAX_CHILDREN:
            parent.children.append(sp)
        else:
            parent.add("children_dropped")
            events.note("trace_children_dropped")
        self._span = sp
        self._token = _current.set(sp)
        return sp

    def __exit__(self, exc_type, exc, tb) -> bool:
        sp = self._span
        if exc_type is not None:
            sp.attrs.setdefault("error", exc_type.__name__)
        sp.close()
        _current.reset(self._token)
        return False


class _NoopCtx:
    """Shared reusable no-op context manager (no allocation per use)."""

    __slots__ = ()

    def __enter__(self):
        return _NOOP

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


class _NoopSpan:
    """The ambient span when tracing is off: every operation is a
    constant-time no-op returning shared singletons."""

    __slots__ = ()

    enabled = False
    name = "noop"
    attrs: dict = {}
    children: list = []

    def set(self, key, value) -> None:
        pass

    def add(self, key, n=1) -> None:
        pass

    def span(self, name, **attrs):
        return _NOOP_CTX

    def attach(self, tree) -> None:
        pass

    def close(self) -> None:
        pass

    def open_spans(self) -> int:
        return 0

    def to_dict(self, base=None) -> dict:
        return {}

    def flatten(self) -> dict:
        return {}


_NOOP = _NoopSpan()
_NOOP_CTX = _NoopCtx()


def current_span():
    """The ambient span of this thread's active trace, or the shared
    no-op singleton when tracing is off."""
    sp = _current.get()
    return sp if sp is not None else _NOOP


def make_root(name: str, **attrs) -> Span:
    """A detached root span; close it by exiting activate(root)."""
    return Span(name, attrs)


class _Activation:
    """Dynamic extent of a trace: sets the ambient span, closes the
    root on exit.  activate(None) is a no-op extent (tracing off)."""

    __slots__ = ("_root", "_token")

    def __init__(self, root):
        self._root = root
        self._token = None

    def __enter__(self):
        if self._root is not None:
            self._token = _current.set(self._root)
        return self._root

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._root is not None:
            if exc_type is not None:
                self._root.attrs.setdefault("error", exc_type.__name__)
            self._root.close()
            _current.reset(self._token)
        return False


def activate(root) -> _Activation:
    return _Activation(root)


class _UseSpan:
    """Re-enter an existing (still-open) span in another thread — the
    propagation shim for worker fan-outs.  Does NOT close the span."""

    __slots__ = ("_span", "_token")

    def __init__(self, span):
        self._span = span
        self._token = None

    def __enter__(self):
        if self._span is not None and self._span.enabled:
            self._token = _current.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _current.reset(self._token)
        return False


def use_span(span) -> _UseSpan:
    return _UseSpan(span)
