"""Fixed-bucket Prometheus histograms for the query path.

Unlike tracing (opt-in per request), histograms are ALWAYS on: each
observe() is a bisect over a small fixed bucket list under a lock, paid
at per-dispatch / per-part granularity (never per row), so the cost is
noise next to the work it measures.  server/app.py Metrics.render pulls
`render_all()` into /metrics with `# HELP` / `# TYPE` annotations.

The standard instruments are module attributes (QUERY_DURATION etc.) so
call sites hold direct references — no registry lookup on the hot path.
"""

from __future__ import annotations

import bisect
import threading


class Histogram:
    """One fixed-bucket histogram: cumulative `le` buckets + sum/count,
    rendered in Prometheus text exposition format."""

    def __init__(self, name: str, help_text: str, buckets):
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._mu = threading.Lock()
        # per-bucket increments (cumulated at render time) + +Inf slot
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._mu:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count)."""
        with self._mu:
            counts = list(self._counts)
            s, c = self._sum, self._count
        cum = []
        acc = 0
        for n in counts:
            acc += n
            cum.append(acc)
        return cum, s, c

    def render(self) -> list[str]:
        cum, s, c = self.snapshot()
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        for le, n in zip(self.buckets, cum):
            le_s = format(le, "g")
            out.append(f'{self.name}_bucket{{le="{le_s}"}} {n}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {cum[-1]}')
        out.append(f"{self.name}_sum {format(s, 'g')}")
        out.append(f"{self.name}_count {c}")
        return out

    def reset(self) -> None:
        with self._mu:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0


_registry: dict[str, Histogram] = {}
_registry_mu = threading.Lock()


def histogram(name: str, help_text: str, buckets) -> Histogram:
    with _registry_mu:
        h = _registry.get(name)
        if h is None:
            h = _registry[name] = Histogram(name, help_text, buckets)
        return h


def render_all() -> list[str]:
    with _registry_mu:
        hs = sorted(_registry.values(), key=lambda h: h.name)
    out = []
    for h in hs:
        out.extend(h.render())
    return out


def names() -> set:
    with _registry_mu:
        return set(_registry)


def reset_all() -> None:
    with _registry_mu:
        hs = list(_registry.values())
    for h in hs:
        h.reset()


# ---- the standard query-path instruments ----

QUERY_DURATION = histogram(
    "vl_query_duration_seconds",
    "end-to-end /select query execution time",
    (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
     1.0, 2.5, 5.0, 10.0, 30.0))

DISPATCH_RTT = histogram(
    "vl_tpu_dispatch_rtt_seconds",
    "device dispatch round trip: submit to harvested result "
    "(async window units and per-leaf scans)",
    (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
     0.05, 0.1, 0.25, 0.5, 1.0))

HOST_SYNC_WAIT = histogram(
    "vl_tpu_host_sync_wait_seconds",
    "time blocked materializing one dispatch result on the host "
    "(the window's single harvest sync point)",
    (0.00001, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
     0.025, 0.05, 0.1, 0.5))

EMIT_SECONDS = histogram(
    "vl_tpu_emit_seconds",
    "host-side emit phase of one harvested dispatch unit: block "
    "materialization + downstream write (NDJSON bytes on streaming "
    "sinks) — the columnar-emit counterpart of host_sync_wait",
    (0.00001, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
     0.025, 0.05, 0.1, 0.5))

PACK_SIZE = histogram(
    "vl_tpu_pack_size_parts",
    "parts per pipeline dispatch unit (1 = unpacked part)",
    (1, 2, 3, 4, 6, 8, 12, 16, 32))

PRUNE_RATIO = histogram(
    "vl_tpu_bloom_prune_ratio",
    "fraction of probed candidate blocks killed per bloom keep-mask "
    "probe (the filter-index kill path)",
    (0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0))

SCHED_QUEUE_WAIT = histogram(
    "vl_sched_queue_wait_seconds",
    "admission-queue wait before a query starts executing (0 = "
    "admitted immediately; sched/admission.py)",
    (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
     5.0, 10.0, 30.0))

SLOT_WAIT = histogram(
    "vl_sched_slot_wait_seconds",
    "wait for a device dispatch submit slot from the shared "
    "scheduler, incl. harvesting own units under contention "
    "(sched/scheduler.py, leased per pipeline dispatch unit)",
    (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
     0.05, 0.1, 0.25, 0.5, 1.0))

# cost-model accountability (obs/explain.py): per-query relative error
# |predicted - actual| / actual of the plan-time pricing pass, one
# histogram per priced dimension.  EWMA drift (backend change, tunnel
# degradation, workload shift) shows up here as a rightward creep —
# alarmable long before the VL_INFLIGHT=auto window or a future
# priced-admission gate start making bad calls on stale rates.
_COST_ERR_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0,
                     4.0, 8.0, 16.0)

COST_ERR_DURATION = histogram(
    "vl_cost_model_rel_error_duration",
    "relative error of the plan-time predicted execution duration vs "
    "the measured exec time (|pred-actual|/actual, per priced query)",
    _COST_ERR_BUCKETS)

COST_ERR_BYTES = histogram(
    "vl_cost_model_rel_error_bytes",
    "relative error of the plan-time predicted bytes scanned vs the "
    "query's actual bytes_scanned counter",
    _COST_ERR_BUCKETS)

COST_ERR_DISPATCHES = histogram(
    "vl_cost_model_rel_error_dispatches",
    "relative error of the planned dispatch-unit count vs the units "
    "actually submitted through the pipeline window",
    _COST_ERR_BUCKETS)

NET_FIRST_FRAME = histogram(
    "vl_net_first_frame_seconds",
    "cluster sub-query round trip to the node's first response frame "
    "(the hedging EWMA feeds on the same measurement — "
    "server/netrobust.py)",
    (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
     1.0, 2.5, 5.0, 10.0))

FILTER_INDEX_BUILD = histogram(
    "vl_filter_index_build_seconds",
    "wall time building one sealed part's v2 filter-index sidecar "
    "(split-block planes + xor aggregates + maplets, "
    "storage/filterindex — paid once per part at merge/flush seal)",
    (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
     2.5, 5.0))

INGEST_FRESHNESS = histogram(
    "vl_ingest_freshness_seconds",
    "how long flushed rows sat in memory: flush time minus the oldest "
    "flushed in-memory part's creation time (storage/datadb.py "
    "flush_inmemory_parts — the part-visible half of the freshness "
    "watermark pair)",
    (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0))

INGEST_TO_QUERYABLE = histogram(
    "vl_ingest_to_queryable_seconds",
    "accept wall clock to rows queryable: observed per batch at the "
    "storage chokepoint (snapshot_parts serves in-memory parts the "
    "moment must_add returns — obs/ingestledger.py)",
    (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
     2.5, 5.0, 10.0, 30.0))

MERGE_SECONDS = histogram(
    "vl_storage_merge_duration_seconds",
    "wall time of one background part merge (small/big tier "
    "compactions and force merges, storage/datadb.py)",
    (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
     30.0, 60.0))

INGEST_BLOCK_BUILD = histogram(
    "vl_ingest_block_build_seconds",
    "wall time of one format-independent block build: values encode + "
    "token blooms for one ingested batch, serial or sharded across the "
    "VL_BLOCK_BUILD_THREADS pool (storage/block_build.py, observed at "
    "the DataDB must_add chokepoint)",
    (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
     2.5, 5.0))
