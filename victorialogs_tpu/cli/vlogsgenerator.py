"""Benchmark load generator (reference: app/vlogsgenerator).

Emits synthetic log streams with a configurable mix of typed fields
(const/var/dict/uint/float/ip/timestamp/json — main.go:24-60) to stdout or
an ingest URL, reporting the achieved rate.

Usage:
  python -m victorialogs_tpu.cli.vlogsgenerator -logsPerStream 1000 \
      -streams 8 -addr http://127.0.0.1:9428 [-start ...] [-end ...]
  python -m victorialogs_tpu.cli.vlogsgenerator -out - > logs.jsonl
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
import urllib.request


WORDS = ["error", "warn", "info", "request", "response", "timeout",
         "connected", "closed", "retry", "flush", "compact", "merge",
         "alloc", "free", "login", "logout", "GET", "POST", "PUT"]


def gen_row(args, stream_id: int, seq: int, ts_ns: int) -> dict:
    rnd = random.Random((stream_id << 32) | seq)
    row = {
        "_time": ts_ns,
        "_msg": " ".join(rnd.choice(WORDS)
                         for _ in range(args.wordsPerMsg)),
        "stream_id": f"stream_{stream_id}",
        "host": f"host-{stream_id % args.hosts}",
    }
    for i in range(args.constFieldsPerLog):
        row[f"const_{i}"] = f"const_value_{i}"
    for i in range(args.varFieldsPerLog):
        row[f"var_{i}"] = str(rnd.randrange(1 << 30))
    for i in range(args.dictFieldsPerLog):
        row[f"dict_{i}"] = rnd.choice(("red", "green", "blue", "yellow"))
    for i in range(args.u8FieldsPerLog):
        row[f"u8_{i}"] = rnd.randrange(256)
    for i in range(args.u16FieldsPerLog):
        row[f"u16_{i}"] = rnd.randrange(1 << 16)
    for i in range(args.u32FieldsPerLog):
        row[f"u32_{i}"] = rnd.randrange(1 << 32)
    for i in range(args.u64FieldsPerLog):
        row[f"u64_{i}"] = rnd.randrange(1 << 64)
    for i in range(args.i64FieldsPerLog):
        row[f"i64_{i}"] = rnd.randrange(-(1 << 63), 1 << 63)
    for i in range(args.floatFieldsPerLog):
        row[f"float_{i}"] = round(rnd.random() * 100, 3)
    for i in range(args.ipFieldsPerLog):
        row[f"ip_{i}"] = f"10.{rnd.randrange(256)}.{rnd.randrange(256)}." \
                         f"{rnd.randrange(256)}"
    for i in range(args.timestampFieldsPerLog):
        row[f"timestamp_{i}"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts_ns / 1e9))
    for i in range(args.jsonFieldsPerLog):
        row[f"json_{i}"] = {"k": rnd.choice(WORDS),
                            "n": rnd.randrange(100)}
    return row


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="vlogsgenerator", prefix_chars="-")
    p.add_argument("-addr", default="",
                   help="ingest URL base (http://host:port); '-' or empty "
                        "writes ndjson to stdout")
    p.add_argument("-streams", type=int, default=8)
    p.add_argument("-logsPerStream", type=int, default=1000)
    p.add_argument("-wordsPerMsg", type=int, default=8)
    p.add_argument("-hosts", type=int, default=4)
    p.add_argument("-constFieldsPerLog", type=int, default=1)
    p.add_argument("-varFieldsPerLog", type=int, default=1)
    p.add_argument("-dictFieldsPerLog", type=int, default=1)
    p.add_argument("-u8FieldsPerLog", type=int, default=1)
    p.add_argument("-u16FieldsPerLog", type=int, default=0)
    p.add_argument("-u32FieldsPerLog", type=int, default=0)
    p.add_argument("-u64FieldsPerLog", type=int, default=0)
    p.add_argument("-i64FieldsPerLog", type=int, default=0)
    p.add_argument("-floatFieldsPerLog", type=int, default=1)
    p.add_argument("-ipFieldsPerLog", type=int, default=1)
    p.add_argument("-timestampFieldsPerLog", type=int, default=0)
    p.add_argument("-jsonFieldsPerLog", type=int, default=0)
    p.add_argument("-start", default="", help="start ts (ns or RFC3339)")
    p.add_argument("-end", default="", help="end ts (ns or RFC3339)")
    p.add_argument("-batchSize", type=int, default=10_000)
    args = p.parse_args(argv)

    from ..engine.block_result import parse_rfc3339
    end_ns = parse_rfc3339(args.end) if args.end else time.time_ns()
    start_ns = parse_rfc3339(args.start) if args.start else \
        end_ns - 3600 * 1_000_000_000
    total = args.streams * args.logsPerStream
    span = max(end_ns - start_ns, 1)

    t0 = time.monotonic()
    emitted = 0
    batch: list[str] = []

    def flush_batch():
        nonlocal batch
        if not batch:
            return
        data = ("\n".join(batch)).encode()
        if args.addr and args.addr != "-":
            req = urllib.request.Request(
                args.addr.rstrip("/") +
                "/insert/jsonline?_stream_fields=stream_id",
                data=data, method="POST")
            urllib.request.urlopen(req, timeout=60).read()
        else:
            sys.stdout.write("\n".join(batch) + "\n")
        batch = []

    for seq in range(args.logsPerStream):
        for sid in range(args.streams):
            ts = start_ns + span * (seq * args.streams + sid) // total
            batch.append(json.dumps(gen_row(args, sid, seq, ts),
                                    separators=(",", ":")))
            emitted += 1
            if len(batch) >= args.batchSize:
                flush_batch()
    flush_batch()
    dt = time.monotonic() - t0
    print(f"emitted {emitted} rows in {dt:.2f}s "
          f"({emitted / max(dt, 1e-9):.0f} rows/s)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
