"""Interactive LogsQL REPL (reference: app/vlogscli).

Talks to /select/logsql/query; output modes json / logfmt / compact;
`\\tail <query>` live-tails; readline history in ~/.vlogscli-history.

Usage:
  python -m victorialogs_tpu.cli.vlogscli -datasource.url \
      http://127.0.0.1:9428 [-accountID N] [-projectID N]
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.parse
import urllib.request

HELP = """\
Commands:
  <LogsQL query>        run a query (default limit 10)
  \\m json|logfmt|compact  set output mode
  \\limit N              set the default limit
  \\tail <query>         live-tail a query (Ctrl-C to stop)
  \\h                    this help
  \\q                    quit
"""


class Client:
    def __init__(self, base_url: str, account_id: int = 0,
                 project_id: int = 0, timeout: float = 60.0):
        self.base = base_url.rstrip("/")
        self.headers = {"AccountID": str(account_id),
                        "ProjectID": str(project_id)}
        self.timeout = timeout

    def query(self, q: str, limit: int = 10):
        url = (f"{self.base}/select/logsql/query?"
               f"query={urllib.parse.quote(q)}&limit={limit}")
        req = urllib.request.Request(url, headers=self.headers)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            for line in resp:
                line = line.strip()
                if line:
                    yield json.loads(line)

    def tail(self, q: str):
        url = (f"{self.base}/select/logsql/tail?"
               f"query={urllib.parse.quote(q)}")
        req = urllib.request.Request(url, headers=self.headers)
        with urllib.request.urlopen(req, timeout=3600) as resp:
            for line in resp:
                line = line.strip()
                if line:
                    yield json.loads(line)


def render(row: dict, mode: str) -> str:
    if mode == "json":
        return json.dumps(row, ensure_ascii=False)
    if mode == "logfmt":
        return " ".join(f"{k}={json.dumps(v, ensure_ascii=False)}"
                        for k, v in row.items())
    # compact: _time + _msg
    return f"{row.get('_time', '')} {row.get('_msg', '')}".strip()


def repl(client: Client) -> int:
    try:
        import readline  # noqa: F401 - side effect: line editing
        import os
        hist = os.path.expanduser("~/.vlogscli-history")
        try:
            readline.read_history_file(hist)
        except OSError:
            pass
        import atexit
        atexit.register(lambda: readline.write_history_file(hist))
    except ImportError:
        pass
    mode = "json"
    limit = 10
    print("victorialogs-tpu interactive shell; \\h for help")
    while True:
        try:
            line = input(";> ").strip()
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        if not line:
            continue
        if line in ("\\q", "q", "quit", "exit"):
            return 0
        if line == "\\h":
            print(HELP)
            continue
        if line.startswith("\\m "):
            m = line[3:].strip()
            if m in ("json", "logfmt", "compact"):
                mode = m
            else:
                print("unknown mode; want json|logfmt|compact")
            continue
        if line.startswith("\\limit "):
            try:
                limit = int(line[7:])
            except ValueError:
                print("invalid limit")
            continue
        if line.startswith("\\tail "):
            try:
                for row in client.tail(line[6:]):
                    print(render(row, mode))
            except KeyboardInterrupt:
                print()
            # vlint: allow-broad-except(REPL prints and keeps running)
            except Exception as e:
                print(f"error: {e}")
            continue
        try:
            n = 0
            for row in client.query(line, limit=limit):
                print(render(row, mode))
                n += 1
            print(f"-- {n} rows")
        # vlint: allow-broad-except(REPL prints and keeps running)
        except Exception as e:
            print(f"error: {e}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="vlogscli", prefix_chars="-")
    p.add_argument("-datasource.url", dest="url",
                   default="http://127.0.0.1:9428")
    p.add_argument("-accountID", type=int, default=0)
    p.add_argument("-projectID", type=int, default=0)
    args = p.parse_args(argv)
    return repl(Client(args.url, args.accountID, args.projectID))


if __name__ == "__main__":
    sys.exit(main())
