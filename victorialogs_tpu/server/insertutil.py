"""Shared ingestion plumbing: per-request params, row batching, timestamps.

Reference: app/vlinsert/insertutil — CommonParams extracted from headers/query
args (_time_field, _msg_field, _stream_fields, ignore_fields, extra_fields,
debug — common_params.go:30-100), tenant from AccountID/ProjectID headers
(tenant_id parsing — common_params.go:48), and LogMessageProcessor batching
rows with a 1s periodic flush + size-triggered flush (common_params.go:199-255).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field as dc_field

from ..engine.block_result import parse_rfc3339
from ..obs import ingestledger
from ..storage.log_rows import LogRows, TenantID

MAX_BATCH_ROWS = 100_000
MAX_BATCH_BYTES = 50 << 20
FLUSH_INTERVAL = 1.0


def get_tenant_id(headers, args) -> TenantID:
    """Tenant from AccountID/ProjectID headers or query args."""
    acc = headers.get("AccountID") or args.get("AccountID") or "0"
    proj = headers.get("ProjectID") or args.get("ProjectID") or "0"
    try:
        return TenantID(int(acc), int(proj))
    except ValueError:
        return TenantID()


def _csv(s: str | None) -> list[str]:
    if not s:
        return []
    return [x.strip() for x in s.split(",") if x.strip()]


@dataclass
class CommonParams:
    tenant: TenantID = dc_field(default_factory=TenantID)
    time_field: str = "_time"
    msg_fields: list = dc_field(default_factory=lambda: ["_msg"])
    stream_fields: list = dc_field(default_factory=list)
    ignore_fields: list = dc_field(default_factory=list)
    decolorize_fields: list = dc_field(default_factory=list)
    extra_fields: list = dc_field(default_factory=list)
    default_msg_value: str = ""
    debug: bool = False

    @staticmethod
    def from_request(headers, args) -> "CommonParams":
        def hv(name, hdr):
            return args.get(name) or headers.get(hdr) or ""
        cp = CommonParams()
        cp.tenant = get_tenant_id(headers, args)
        cp.time_field = hv("_time_field", "VL-Time-Field") or "_time"
        msg = _csv(hv("_msg_field", "VL-Msg-Field"))
        if msg:
            cp.msg_fields = msg
        cp.stream_fields = _csv(hv("_stream_fields", "VL-Stream-Fields"))
        cp.ignore_fields = _csv(hv("ignore_fields", "VL-Ignore-Fields"))
        cp.decolorize_fields = _csv(hv("decolorize_fields",
                                       "VL-Decolorize-Fields"))
        extra = _csv(hv("extra_fields", "VL-Extra-Fields"))
        cp.extra_fields = []
        for ef in extra:
            if "=" in ef:
                k, v = ef.split("=", 1)
                cp.extra_fields.append((k, v))
        cp.default_msg_value = args.get("default_msg_value") or ""
        cp.debug = (hv("debug", "VL-Debug").lower() in ("1", "true", "y"))
        return cp


def parse_timestamp(v, default_ns: int | None = None) -> int | None:
    """Parse a log timestamp: RFC3339 string, unix secs/millis/micros/nanos.

    Follows the reference's unit inference by magnitude
    (app/vlinsert/insertutil/timestamp.go).
    """
    if v is None or v == "" or v == 0:
        return default_ns if default_ns is not None else time.time_ns()
    if isinstance(v, str):
        if v.isascii() and v.isdigit():
            v = int(v)       # pure unix numbers skip the RFC3339 regex
        else:
            ts = parse_rfc3339(v)
            if ts is not None:
                return ts
            try:
                v = float(v) if ("." in v or "e" in v or "E" in v) \
                    else int(v)
            except ValueError:
                return None
    if isinstance(v, float):
        # floats are unix seconds with fraction
        return int(v * 1e9)
    if isinstance(v, int):
        if v < (1 << 32):           # seconds until year 2106
            return v * 1_000_000_000
        if v < (1 << 32) * 1_000:   # millis
            return v * 1_000_000
        if v < (1 << 32) * 1_000_000:
            return v * 1_000
        return v
    return None


_ANSI_CSI = "\x1b["


def decolorize(s: str) -> str:
    """Strip ANSI color/escape sequences (reference decolorize rules)."""
    if _ANSI_CSI not in s:
        return s
    out = []
    i, n = 0, len(s)
    while i < n:
        if s[i] == "\x1b" and i + 1 < n and s[i + 1] == "[":
            i += 2
            while i < n and not ("@" <= s[i] <= "~"):
                i += 1
            i += 1  # final byte
            continue
        out.append(s[i])
        i += 1
    return "".join(out)


class LogRowsStorage:
    """Destination indirection so vlinsert can feed either the local
    Storage or a remote forwarder (reference insertutil.LogRowsStorage —
    common_params.go:150-170)."""

    def must_add_rows(self, lr: LogRows) -> None:
        raise NotImplementedError


class LocalLogRowsStorage(LogRowsStorage):
    def __init__(self, storage):
        self.storage = storage

    def must_add_rows(self, lr: LogRows) -> None:
        self.storage.must_add_rows(lr)

    def must_add_columns(self, lc) -> None:
        self.storage.must_add_columns(lc)


class LogMessageProcessor:
    """Accumulates rows, flushing on size or (for long-lived processors
    like the syslog listeners) a periodic 1s timer — reference
    common_params.go:199-223."""

    def __init__(self, cp: CommonParams, sink: LogRowsStorage,
                 periodic_flush: bool = False, columnar: bool = False):
        self.cp = cp
        self.sink = sink
        # columnar: flushes convert the accumulated rows to a LogColumns
        # batch and ride must_add_columns -> the i1 columnar block-build
        # path (syslog sets this; silently off when the sink can't)
        self.columnar = columnar
        self.lr = LogRows(stream_fields=list(cp.stream_fields),
                          ignore_fields=list(cp.ignore_fields),
                          extra_fields=list(cp.extra_fields),
                          default_msg_value=cp.default_msg_value)
        self.bytes = 0
        self.rows_total = 0
        self._lock = threading.Lock()
        self._stop = None
        if periodic_flush:
            self._stop = threading.Event()
            t = threading.Thread(target=self._flush_loop, daemon=True)
            t.start()

    def _flush_loop(self) -> None:
        while not self._stop.wait(FLUSH_INTERVAL):
            try:
                self.flush()
            # vlint: allow-broad-except(flusher thread must survive)
            except Exception:  # pragma: no cover - keep the flusher alive
                pass

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        self.flush()

    def add_row(self, ts_ns: int | None, fields: list[tuple[str, str]],
                stream_fields: list[tuple[str, str]] | None = None) -> None:
        if ts_ns is None:
            ts_ns = time.time_ns()
        if self.cp.decolorize_fields:
            fields = [(k, decolorize(v))
                      if _match_any(k, self.cp.decolorize_fields) else (k, v)
                      for k, v in fields]
        if stream_fields is not None:
            # protocol-level stream labels (loki/datadog): prepend them and
            # scope the batch's stream fields accordingly
            names = [k for k, _ in stream_fields]
            self.lr.stream_fields = names
            fields = list(stream_fields) + \
                [f for f in fields if f[0] not in names]
        with self._lock:
            self.lr.add(self.cp.tenant, ts_ns, fields)
            self.rows_total += 1
            self.bytes += sum(len(k) + len(v) for k, v in fields)
            if len(self.lr) >= MAX_BATCH_ROWS or \
                    self.bytes >= MAX_BATCH_BYTES:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if len(self.lr):
            # the conservation ledger's entry roll sits at the sink
            # handoff (not the HTTP handler) so `accepted` always
            # precedes the sink's terminal stored/forwarded rolls —
            # derived in_flight can never dip negative.  Gated on the
            # ambient batch: non-batch users (syslog periodic flush)
            # stay off the ledger entirely, entry AND terminal side.
            if ingestledger.current_batch() is not None:
                ingestledger.note_accepted(self.cp.tenant, len(self.lr))
            if self.columnar and self.supports_columns():
                from . import wire_ingest
                self.sink.must_add_columns(
                    wire_ingest.rows_to_columns(self.lr))
            else:
                self.sink.must_add_rows(self.lr)
            self.lr = LogRows(stream_fields=list(self.lr.stream_fields),
                              ignore_fields=list(self.cp.ignore_fields),
                              extra_fields=list(self.cp.extra_fields),
                              default_msg_value=self.cp.default_msg_value)
            self.bytes = 0

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def supports_columns(self) -> bool:
        """True when the sink accepts columnar batches directly and no
        per-row transform (decolorize) is configured — the jsonline bulk
        fast path's gate."""
        return not self.cp.decolorize_fields and \
            hasattr(self.sink, "must_add_columns")

    def ingest_columns(self, lc) -> None:
        """Hand a pre-assembled columnar batch to the sink.  Flushes any
        pending row batch FIRST; callers that interleave fallback rows
        with columnar accumulation must flush the columnar batch before
        each fallback add_row (as _jsonline_fast does) so arrival order
        is preserved end to end."""
        if lc.nrows == 0:
            return
        with self._lock:
            self._flush_locked()
            if ingestledger.current_batch() is not None:
                ingestledger.note_accepted(self.cp.tenant, lc.nrows)
            self.sink.must_add_columns(lc)
            self.rows_total += lc.nrows


def _match_any(name: str, patterns: list[str]) -> bool:
    for p in patterns:
        if p.endswith("*"):
            if name.startswith(p[:-1]):
                return True
        elif name == p:
            return True
    return False
