"""Typed ingest wire format "i1": ONE LogRows frame at every hop.

Sibling of the SELECT wire "t1" (server/cluster.py framing section):
since format "i1" every insert hop — frontend→storage
(`NetInsertStorage` → `/internal/insert`), the durable insert spool,
and vlagent's persistent delivery queues — can carry the SAME
self-describing typed frame instead of per-row JSON lines, so a batch
is encoded ONCE and every retry/replay ships the identical bytes, and
the receiving storage node decodes straight into an arena-backed
columnar batch (LogColumns) with ZERO per-row ``json.loads``.

Frame layout (inside the zstd outer framing, little-endian):

    magic  b"\\x00VLI1"          (JSON lines start with "{" — a reader
                                 sniffs the format per body, so mixed
                                 senders need no handshake)
    u32    total_rows
    u32    n_streams             global stream table for the batch
    u16    n_groups              schema groups (exact field-name tuples)
    u32    tags_arena_len  + tags arena (canonical stream-tags strings)
    per stream: u32 tag_off, u32 tag_len, u32 account_id, u32 project_id
    per group:
      u16  n_names;  per name: u16 len + utf-8 bytes
      u16  n_stream_pos; per: u16
      u32  n_rows
      i64[n_rows]  timestamps
      u32[n_rows]  stream refs (into the global stream table)
      per column (n_names): u32 arena_len + value arena,
                            u32[n_rows] offsets, u32[n_rows] lengths

StreamIDs are NOT shipped: the receiver recomputes the 128-bit hash
from the canonical tags bytes (one hash per unique stream, never per
row) so a forged frame can't claim rows into a stream its tags don't
hash to.  Decode bounds-checks every offset/length against its arena
BEFORE any slicing (the wire-taint discipline the vlint
interprocedural checker enforces); any structural corruption raises
``WireInsertError`` (a ValueError → whole-batch HTTP 400, never a
partial silent ingest).

``VL_WIRE_TYPED_INSERT=0`` kills the format on either side: senders
stop encoding i1, receivers reject i1 bodies with a 400 so senders
fall back to legacy JSON lines — pinning legacy behavior in BOTH
mixed-version directions (same discipline as VL_WIRE_TYPED for t1).
"""

from __future__ import annotations

import struct
import threading
import time

import numpy as np

from .. import config
from ..obs import tracing
from ..storage.block_build import ArenaColumn as _ArenaColumn
from ..storage.block_build import arena_build_enabled as _arena_cols
from ..storage.log_rows import (LogColumns, LogRows, StreamID, TenantID)
from ..utils import zstd as _zstd
from ..utils.hashing import stream_id_hash

WIRE_INSERT_FORMAT = "i1"
INSERT_MAGIC = b"\x00VLI1"

# decompressed-size bound for one insert body (matches the legacy
# /internal/insert bound)
MAX_FRAME_BYTES = 1 << 30


def wire_typed_insert_enabled() -> bool:
    """VL_WIRE_TYPED_INSERT=0 kill-switch: this process neither encodes
    nor accepts i1 frames (legacy JSON lines exactly)."""
    return config.env_flag("VL_WIRE_TYPED_INSERT")


class WireInsertError(ValueError):
    """Structural corruption in an i1 frame.  A ValueError so the HTTP
    layer maps it to 400 (whole-batch reject) like any malformed body."""


# ---- ingest-wire observability (vl_ingest_wire_* on /metrics) ----

_mu = threading.Lock()
_counts: dict[str, int] = {}


def note(key: str, delta: int = 1) -> None:
    with _mu:
        _counts[key] = _counts.get(key, 0) + delta


def counters() -> dict:
    with _mu:
        return dict(_counts)


def metrics_samples() -> list:
    """(base, labels, value) samples for Metrics.render — the insert
    spine's sibling of cluster.wire_metrics_samples(): frame/byte
    counts by direction and format, plus sticky-fallback events."""
    c = counters()
    out = []
    for fmt in ("typed", "json"):
        for d in ("tx", "rx"):
            # vlint: allow-per-row-emit(metric label dicts, bounded constant set)
            out.append(("vl_ingest_wire_frames_total",
                        {"dir": d, "fmt": fmt},
                        c.get(f"{d}_frames_{fmt}", 0)))
            # vlint: allow-per-row-emit(metric label dicts, bounded constant set)
            out.append(("vl_ingest_wire_bytes_total",
                        {"dir": d, "fmt": fmt},
                        c.get(f"{d}_bytes_{fmt}", 0)))
    out.append(("vl_ingest_wire_fallbacks_total", {},
                c.get("fallbacks", 0)))
    return out


# ---- encode ----

def _arena(vals) -> tuple[bytes, np.ndarray, np.ndarray]:
    """One dense utf-8 arena + u32 offsets/lengths for a value list.
    ASCII fast path: byte lengths == str lengths, so ONE encode of the
    joined string replaces per-value encodes."""
    wa = getattr(vals, "wire_arena", None)
    if wa is not None:
        # decoded ArenaColumn (storage/block_build): the wire arena IS
        # the value arena — a shard re-route or spool re-encode of a
        # decoded frame skips the join+encode entirely
        arena, offs, lens = wa()
        if len(arena) >= 1 << 32:
            raise ValueError("i1 frame arena overflow")
        return arena, offs, lens
    joined = "".join(vals)
    arena = joined.encode("utf-8")
    n = len(vals)
    if len(arena) == len(joined):
        lens = np.fromiter(map(len, vals), dtype=np.uint32, count=n)
    else:
        lens = np.fromiter((len(v.encode("utf-8")) for v in vals),
                           dtype=np.uint32, count=n)
    offs = np.zeros(n, dtype=np.uint32)
    if n > 1:
        np.cumsum(lens[:-1], out=offs[1:], dtype=np.uint32)
    if len(arena) >= 1 << 32:
        # u32 offsets can't address it — caller falls back to legacy
        raise ValueError("i1 frame arena overflow")
    return arena, offs, lens


def encode_columns(lc: LogColumns) -> bytes:
    """One LogColumns batch -> a compressed i1 body.  Raises ValueError
    (not WireInsertError) when the batch can't ride the format (arena
    or tenant-id overflow) so callers fall back to legacy encoding."""
    t0 = time.perf_counter()
    # global stream table
    sid_to_ref: dict = {}
    tags_list: list = []
    tenant_rows: list = []
    for g in lc.groups.values():
        for sid, tenant, tags in g.streams:
            if sid in sid_to_ref:
                continue
            a, p = tenant.account_id, tenant.project_id
            if not (0 <= a < 1 << 32 and 0 <= p < 1 << 32):
                raise ValueError("i1 frame tenant id overflow")
            sid_to_ref[sid] = len(tags_list)
            tags_list.append(tags)
            tenant_rows.append((a, p))
    groups = [g for g in lc.groups.values() if g.ts]
    if len(groups) >= 1 << 16:
        raise ValueError("i1 frame group count overflow")
    parts = [INSERT_MAGIC,
             struct.pack("<IIH", lc.nrows, len(tags_list), len(groups))]
    tags_arena, tags_offs, tags_lens = _arena(tags_list)
    parts.append(struct.pack("<I", len(tags_arena)))
    parts.append(tags_arena)
    stream_tbl = np.empty((len(tags_list), 4), dtype="<u4")
    if len(tags_list):
        stream_tbl[:, 0] = tags_offs
        stream_tbl[:, 1] = tags_lens
        stream_tbl[:, 2] = [a for a, _p in tenant_rows]
        stream_tbl[:, 3] = [p for _a, p in tenant_rows]
    parts.append(stream_tbl.tobytes())
    for g in groups:
        if len(g.names) >= 1 << 16:
            raise ValueError("i1 frame column count overflow")
        parts.append(struct.pack("<H", len(g.names)))
        for nm in g.names:
            nb = nm.encode("utf-8")
            if len(nb) >= 1 << 16:
                raise ValueError("i1 frame field name overflow")
            parts.append(struct.pack("<H", len(nb)))
            parts.append(nb)
        parts.append(struct.pack("<H", len(g.stream_pos)))
        if g.stream_pos:
            parts.append(np.asarray(g.stream_pos,
                                    dtype="<u2").tobytes())
        n = len(g.ts)
        parts.append(struct.pack("<I", n))
        parts.append(np.asarray(g.ts, dtype="<i8").tobytes())
        # remap group-local stream refs -> global table refs
        local = np.fromiter((sid_to_ref[sid] for sid, _t, _s
                             in g.streams),
                            dtype=np.uint32, count=len(g.streams))
        parts.append(local[np.asarray(g.sref, dtype=np.int64)]
                     .astype("<u4", copy=False).tobytes())
        for col in g.cols:
            arena, offs, lens = _arena(col)
            parts.append(struct.pack("<I", len(arena)))
            parts.append(arena)
            parts.append(offs.astype("<u4", copy=False).tobytes())
            parts.append(lens.astype("<u4", copy=False).tobytes())
    body = _zstd.compress(b"".join(parts))
    note("tx_frames_typed")
    note("tx_bytes_typed", len(body))
    note("encodes_typed")
    sp = tracing.current_span()
    if sp.enabled:
        sp.add("typed_frames")
        sp.add("encode_s", time.perf_counter() - t0)
    return body


def encode_rows(lr: LogRows) -> bytes:
    """LogRows (the per-row batch form) -> a compressed i1 body."""
    return encode_columns(rows_to_columns(lr))


def rows_to_columns(lr: LogRows) -> LogColumns:
    """Regroup a LogRows batch by exact field schema so the row-path
    hops (syslog/OTLP handlers, vlagent fan-in) ride the same frame."""
    lc = LogColumns()
    for i in range(len(lr)):
        fields = lr.rows[i]
        names = tuple(k for k, _v in fields)
        g = lc.group(names, ())
        lc.add(g, lr.tenants[i], lr.timestamps[i],
               [v for _k, v in fields], lr.stream_ids[i],
               lr.stream_tags_str[i])
    return lc


def encode_legacy_columns(lc: LogColumns) -> bytes:
    """The mandatory legacy fallback body (zstd'd JSON lines, the
    format every version's /internal/insert speaks) from a columnar
    batch — used when a receiver rejects i1 (old node, or
    VL_WIRE_TYPED_INSERT=0 on its side)."""
    import json
    lines = []
    for g in lc.groups.values():
        names = g.names
        for k in range(len(g.ts)):
            sid, tenant, tags = g.streams[g.sref[k]]
            # vlint: allow-per-row-emit(legacy ingest wire format is per-row framed JSON; fallback path only)
            lines.append(json.dumps(
                {"t": g.ts[k], "a": tenant.account_id,
                 "p": tenant.project_id, "s": tags,
                 "f": [[nm, c[k]] for nm, c in zip(names, g.cols)]},
                ensure_ascii=False, separators=(",", ":")))
    body = _zstd.compress("\n".join(lines).encode("utf-8"))
    note("tx_frames_json")
    note("tx_bytes_json", len(body))
    note("encodes_json")
    return body


def reencode_legacy(body: bytes) -> bytes | None:
    """Re-encode a stored compressed body as legacy JSON lines if (and
    only if) it is a typed i1 frame; None when it isn't one or can't be
    decoded.  Used by spool/queue replay when a receiver stopped
    speaking i1 between spool time and replay time."""
    try:
        data = _zstd.decompress(body, max_output_size=MAX_FRAME_BYTES)
    except (ValueError, OSError, RuntimeError):
        return None
    if not data.startswith(INSERT_MAGIC):
        return None
    try:
        lc = decode_frame(data)
    except WireInsertError:
        return None
    return encode_legacy_columns(lc)


# ---- decode ----

class _Reader:
    """Bounds-checked cursor over one decompressed i1 payload (the
    ingest sibling of cluster._FrameReader; raises WireInsertError so
    corruption maps to HTTP 400 instead of a transport error)."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int):
        self.buf = buf
        self.pos = pos

    def take(self, n: int) -> bytes:
        end = self.pos + n
        if n < 0 or end > len(self.buf):
            raise WireInsertError(
                "corrupted i1 frame: truncated payload")
        out = self.buf[self.pos:end]
        self.pos = end
        return out

    def array(self, dtype, count: int) -> np.ndarray:
        it = np.dtype(dtype).itemsize
        end = self.pos + it * count
        if count < 0 or end > len(self.buf):
            raise WireInsertError(
                "corrupted i1 frame: truncated array")
        a = np.frombuffer(self.buf, dtype=dtype, count=count,
                          offset=self.pos)
        self.pos = end
        return a


def _check_slices(offs: np.ndarray, lens: np.ndarray, alen: int,
                  what: str) -> None:
    """Every (offset, length) slice must lie inside its arena BEFORE
    anything reads through it — offsets are wire-derived."""
    if offs.size and int((offs.astype(np.int64)
                          + lens.astype(np.int64)).max()) > alen:
        raise WireInsertError(
            f"corrupted i1 frame: {what} slice out of arena bounds")


def _arena_text(raw: bytes, what: str) -> str:
    try:
        return raw.decode("utf-8")
    except UnicodeDecodeError as e:
        raise WireInsertError(
            f"corrupted i1 frame: {what} arena is not UTF-8: {e}") \
            from None


def _slice_all(text: str, raw: bytes, offs: np.ndarray,
               lens: np.ndarray) -> list:
    """Arena -> per-value strings.  ASCII arenas slice the decoded str
    directly (byte offsets == char offsets); otherwise slice bytes and
    decode per value (rare: non-ASCII log payloads)."""
    ends = (offs.astype(np.int64) + lens.astype(np.int64)).tolist()
    o = offs.tolist()
    if len(text) == len(raw):
        return [text[s:e] for s, e in zip(o, ends)]
    return [raw[s:e].decode("utf-8", "strict") for s, e in zip(o, ends)]


def decode_frame(data: bytes) -> LogColumns:
    """One decompressed i1 payload -> an arena-backed LogColumns batch
    ready for Storage.must_add_columns — no per-row json.loads anywhere.
    StreamIDs are recomputed from the canonical tags (one hash per
    unique stream).  Raises WireInsertError on ANY structural problem:
    the whole batch is rejected, never partially ingested."""
    if not data.startswith(INSERT_MAGIC):
        raise WireInsertError("corrupted i1 frame: bad magic")
    r = _Reader(data, len(INSERT_MAGIC))
    total_rows, n_streams, n_groups = struct.unpack("<IIH", r.take(10))
    tags_alen = struct.unpack("<I", r.take(4))[0]
    tags_raw = r.take(tags_alen)
    tbl = r.array("<u4", n_streams * 4).reshape(n_streams, 4)
    _check_slices(tbl[:, 0], tbl[:, 1], tags_alen, "stream tags")
    tags_text = _arena_text(tags_raw, "stream tags")
    streams: list = []
    t_off = tbl[:, 0].tolist()
    t_end = (tbl[:, 0].astype(np.int64)
             + tbl[:, 1].astype(np.int64)).tolist()
    t_acc = tbl[:, 2].tolist()
    t_proj = tbl[:, 3].tolist()
    ascii_tags = len(tags_text) == len(tags_raw)
    for i in range(n_streams):
        raw = tags_raw[t_off[i]:t_end[i]]
        tags = tags_text[t_off[i]:t_end[i]] if ascii_tags \
            else raw.decode("utf-8", "strict")
        hi, lo = stream_id_hash(raw)
        tenant = TenantID(t_acc[i], t_proj[i])
        streams.append((StreamID(tenant, hi, lo), tenant, tags))
    lc = LogColumns()
    rows_seen = 0
    for _gi in range(n_groups):
        n_names = struct.unpack("<H", r.take(2))[0]
        names = []
        for _ni in range(n_names):
            nlen = struct.unpack("<H", r.take(2))[0]
            names.append(_arena_text(r.take(nlen), "field name"))
        names_t = tuple(names)
        n_spos = struct.unpack("<H", r.take(2))[0]
        spos = tuple(int(p) for p in r.array("<u2", n_spos))
        if any(p >= n_names for p in spos):
            raise WireInsertError(
                "corrupted i1 frame: stream position out of range")
        n = struct.unpack("<I", r.take(4))[0]
        ts = r.array("<i8", n)
        srefs = r.array("<u4", n)
        if srefs.size and int(srefs.max()) >= n_streams:
            raise WireInsertError(
                "corrupted i1 frame: stream ref out of range")
        cols = []
        for _ci in range(n_names):
            alen = struct.unpack("<I", r.take(4))[0]
            raw = r.take(alen)
            offs = r.array("<u4", n)
            lens = r.array("<u4", n)
            _check_slices(offs, lens, alen, "value")
            text = _arena_text(raw, "value")
            if len(text) == len(raw) and n and _arena_cols():
                # ASCII arena: keep it dense all the way to the block
                # build (storage/block_build) — no per-row strings
                # exist between here and BlockData
                cols.append(_ArenaColumn(raw, offs, lens, text))
                continue
            try:
                cols.append(_slice_all(text, raw, offs, lens))
            except UnicodeDecodeError as e:
                raise WireInsertError(
                    "corrupted i1 frame: value slice is not "
                    f"UTF-8: {e}") from None
        if names_t in lc.groups:
            raise WireInsertError(
                "corrupted i1 frame: duplicate schema group")
        g = lc.group(names_t, spos)
        # group-local stream table: only the streams this group uses,
        # refs remapped (np.unique is sorted+vectorized)
        if n:
            uniq, inv = np.unique(srefs, return_inverse=True)
            for ref in uniq.tolist():
                sid, tenant, tags = streams[ref]
                g.stream_idx[sid] = len(g.streams)
                g.streams.append((sid, tenant, tags))
                if sid not in lc.stream_tags:
                    lc.stream_tags[sid] = tags
            g.ts = ts.tolist()
            g.sref = inv.tolist()
            g.cols = cols
            lc.nrows += n
        rows_seen += n
    if rows_seen != total_rows:
        raise WireInsertError(
            "corrupted i1 frame: row count mismatch "
            f"(header {total_rows}, groups {rows_seen})")
    if r.pos != len(data):
        raise WireInsertError("corrupted i1 frame: trailing garbage")
    return lc


def columns_tenant_rows(lc: LogColumns) -> dict:
    """Per-tenant row counts for a decoded batch (ingest accounting
    without touching rows): tenant -> rows, via one bincount per
    group's stream refs."""
    out: dict = {}
    for g in lc.groups.values():
        if not g.ts:
            continue
        counts = np.bincount(np.asarray(g.sref, dtype=np.int64),
                             minlength=len(g.streams))
        for (sid, tenant, _tags), c in zip(g.streams, counts.tolist()):
            if c:
                out[tenant] = out.get(tenant, 0) + c
    return out


# ---- node sharding (cluster frontends) ----

def split_columns_by_node(lc: LogColumns, n_nodes: int) -> dict:
    """Shard a columnar batch by stream hash: node -> sub-LogColumns
    with remapped stream refs (the columnar form of NetInsertStorage's
    per-row (hi^lo) % n routing).  The common one-node / one-stream
    batch returns the input uncopied."""
    if n_nodes == 1:
        return {0: lc}
    nodes_used: set = set()
    per_group: list = []
    for g in lc.groups.values():
        snodes = np.fromiter(((sid.hi ^ sid.lo) % n_nodes
                              for sid, _t, _s in g.streams),
                             dtype=np.int64, count=len(g.streams))
        row_nodes = snodes[np.asarray(g.sref, dtype=np.int64)] \
            if g.ts else np.empty(0, dtype=np.int64)
        per_group.append((g, row_nodes))
        nodes_used.update(np.unique(row_nodes).tolist())
    if len(nodes_used) <= 1:
        return {nodes_used.pop() if nodes_used else 0: lc}
    out: dict = {}
    for node in nodes_used:
        sub = LogColumns()
        for g, row_nodes in per_group:
            idxs = np.nonzero(row_nodes == node)[0]
            if not idxs.size:
                continue
            sg = sub.group(g.names, g.stream_pos)
            srefs = np.asarray(g.sref, dtype=np.int64)[idxs]
            uniq, inv = np.unique(srefs, return_inverse=True)
            for ref in uniq.tolist():
                sid, tenant, tags = g.streams[ref]
                sg.stream_idx[sid] = len(sg.streams)
                sg.streams.append((sid, tenant, tags))
                if sid not in sub.stream_tags:
                    sub.stream_tags[sid] = tags
            il = idxs.tolist()
            sg.ts = [g.ts[k] for k in il]
            sg.sref = inv.tolist()
            sg.cols = [[c[k] for k in il] for c in g.cols]
            sub.nrows += len(il)
        out[node] = sub
    return out


# ---- shared encoder pool ----
#
# Cluster frontends and vlagent encode per-node shard bodies in
# parallel (numpy packing + zstd drop the GIL); the pool is shared
# process-wide and refcounted so N NetInsertStorage/VLAgent instances
# (tests spin up several) don't each own idle threads.  The vlint
# "ingest-encoder-pool" balance pair enforces that every acquire_pool()
# caller file also release_pool()s.

_pool_mu = threading.Lock()
_pool = None
_pool_refs = 0
_POOL_WORKERS = 4


def acquire_pool():
    """Refcounted shared ThreadPoolExecutor for shard encoding."""
    global _pool, _pool_refs
    from concurrent.futures import ThreadPoolExecutor
    with _pool_mu:
        if _pool is None:
            _pool = ThreadPoolExecutor(
                max_workers=_POOL_WORKERS,
                thread_name_prefix="vl-ingest-encode")
        _pool_refs += 1
        return _pool


def release_pool() -> None:
    global _pool, _pool_refs
    with _pool_mu:
        _pool_refs -= 1
        if _pool_refs > 0:
            return
        pool, _pool = _pool, None
        _pool_refs = 0
    if pool is not None:
        # wait: encode tasks are sub-ms, and an un-joined worker is a
        # non-daemon thread the vlsan leak sweep rightly flags
        pool.shutdown(wait=True)
