"""Shared streaming-query worker: run a query in a thread, hand encoded
blocks to an HTTP response generator through a bounded queue.

Used by both /select/logsql/query (vlselect) and /internal/select/query
(cluster) so the abandon-stream protocol lives in exactly one place:
- the bounded queue keeps server memory flat and time-to-first-byte at
  first-block time;
- closing the generator (client disconnect, or the cluster frontend's
  first-error/early-done cancel) sets `stop`, which unblocks any pending
  put() and aborts the query at its next sink() call, so the worker
  thread and the query's part snapshot never outlive the response.
"""

from __future__ import annotations

import queue
import threading


class StreamAbandoned(Exception):
    """Raised into the running query when the response stream went away."""


def stream_blocks(run, encode):
    """Generator of encoded items from a threaded query.

    run: callable(sink) that executes the query, calling sink(block) per
         result block and returning when done;
    encode: block -> item to yield, or None to skip the block.
    Exceptions from `run` re-raise in the consuming generator."""
    chunks: queue.Queue = queue.Queue(maxsize=64)
    stop = threading.Event()
    DONE = object()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                chunks.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def sink(block):
        item = encode(block)
        if item is not None and not put(item):
            raise StreamAbandoned("response stream abandoned")

    def work():
        try:
            run(sink)
            put(DONE)
        except StreamAbandoned:
            pass
        # vlint: allow-broad-except(propagated to the response loop)
        except Exception as e:  # propagate to the response loop
            put(e)

    threading.Thread(target=work, daemon=True).start()
    try:
        while True:
            item = chunks.get()
            if item is DONE:
                return
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        stop.set()
