"""victoria-logs single binary entry point.

Usage:
  python -m victorialogs_tpu.server \
      -storageDataPath /var/lib/victorialogs \
      -httpListenAddr :9428 -retentionPeriod 7d

Flag names mirror the reference binary (app/vlstorage/main.go:23-75,
app/victoria-logs/main.go); flags may also be set via environment variables
with the VL_ prefix (dots/dashes -> underscores), like the reference's
envflag support.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys

from ..logsql.duration import parse_duration
from ..storage.storage import Storage
from .app import VLServer
from .syslog import SyslogServer


# vlint: allow-env-registry(envflag mirror: names derive from the CLI flag spellings at runtime, not from fixed knobs the config registry could declare)
def _env_default(name: str, default):
    env = "VL_" + name.replace(".", "_").replace("-", "_")
    return os.environ.get(env, default)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="victoria-logs",
                                description=__doc__, prefix_chars="-")
    p.add_argument("-storageDataPath",
                   default=_env_default("storageDataPath",
                                        "victoria-logs-data"))
    p.add_argument("-httpListenAddr",
                   default=_env_default("httpListenAddr", ":9428"))
    p.add_argument("-retentionPeriod",
                   default=_env_default("retentionPeriod", "7d"))
    p.add_argument("-futureRetention",
                   default=_env_default("futureRetention", "2d"))
    p.add_argument("-inmemoryDataFlushInterval",
                   default=_env_default("inmemoryDataFlushInterval", "5s"))
    p.add_argument("-retention.maxDiskSpaceUsageBytes", type=int,
                   dest="max_disk_bytes",
                   default=int(_env_default(
                       "retention.maxDiskSpaceUsageBytes", 0)))
    p.add_argument("-syslog.listenAddr.tcp", dest="syslog_tcp", default="")
    p.add_argument("-syslog.listenAddr.udp", dest="syslog_udp", default="")
    p.add_argument("-syslog.tls.certFile", dest="syslog_tls_cert",
                   default="")
    p.add_argument("-syslog.tls.keyFile", dest="syslog_tls_key",
                   default="")
    p.add_argument("-search.maxConcurrentRequests", type=int,
                   dest="max_concurrent", default=8)
    p.add_argument("-search.maxQueueDuration", dest="max_queue_duration",
                   default="30s",
                   help="how long a query may wait for a free concurrency "
                        "slot before shedding with 429 (reference "
                        "app/vlselect/main.go:34-46)")
    p.add_argument("-tpu", action="store_true",
                   help="enable the TPU block runner for queries")
    p.add_argument("-storageNode", action="append", dest="storage_nodes",
                   default=None,
                   help="cluster mode: storage node base URL (repeatable); "
                        "this instance then shards ingest and "
                        "scatter-gathers queries over the nodes "
                        "(reference -storageNode)")
    args = p.parse_args(argv)

    retention_ns = parse_duration(args.retentionPeriod)
    if retention_ns is None:
        print(f"invalid -retentionPeriod {args.retentionPeriod!r}",
              file=sys.stderr)
        return 2
    # explicit 0 means shed immediately; only a missing/invalid value errors
    max_queue_ns = 0 if args.max_queue_duration.strip() == "0" \
        else parse_duration(args.max_queue_duration)
    if max_queue_ns is None:
        print(f"invalid -search.maxQueueDuration "
              f"{args.max_queue_duration!r}", file=sys.stderr)
        return 2
    flush_ns = parse_duration(args.inmemoryDataFlushInterval) or 5e9
    future_ns = parse_duration(args.futureRetention) or 2 * 86400e9

    storage = Storage(
        args.storageDataPath,
        retention_days=retention_ns / 86400e9,
        flush_interval=flush_ns / 1e9,
        future_retention_days=future_ns / 86400e9,
        max_disk_usage_bytes=args.max_disk_bytes,
    )

    runner = None
    if args.tpu:
        import jax
        if len(jax.devices()) > 1:
            # multi-chip: shard staged rows over the mesh, psum stats
            from ..parallel.distributed import MeshBatchRunner
            runner = MeshBatchRunner()
        else:
            from ..tpu.batch import BatchRunner
            runner = BatchRunner()

    host, _, port_s = args.httpListenAddr.rpartition(":")
    server = VLServer(storage, listen_addr=host or "0.0.0.0",
                      port=int(port_s or 9428), runner=runner,
                      max_concurrent=args.max_concurrent,
                      max_queue_duration=max_queue_ns / 1e9,
                      storage_nodes=args.storage_nodes)
    print(f"started victoria-logs server at "
          f"http://{host or '0.0.0.0'}:{server.port}/", flush=True)

    syslog_server = None
    if args.syslog_tcp or args.syslog_udp:
        def addr_port(a):
            if not a:
                return -1
            return int(a.rpartition(":")[2])
        syslog_server = SyslogServer(
            server.sink,
            tcp_port=addr_port(args.syslog_tcp),
            udp_port=addr_port(args.syslog_udp),
            tls_cert_file=args.syslog_tls_cert,
            tls_key_file=args.syslog_tls_key)
        print(f"syslog listeners: tcp={syslog_server.tcp_port} "
              f"udp={syslog_server.udp_port}", flush=True)

    stop = []

    def on_signal(_sig, _frm):
        stop.append(1)
    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    try:
        while not stop:
            signal.pause()
    except KeyboardInterrupt:
        pass
    # graceful shutdown: insert listeners first, then select, then storage
    # (reference app/victoria-logs/main.go:47-77 ordering)
    if syslog_server:
        syslog_server.close()
    server.close()
    storage.close()
    print("shut down gracefully", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
