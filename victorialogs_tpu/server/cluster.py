"""Cluster layer (L5): stream-hash sharded ingest + scatter-gather queries.

The TPU-native redesign of the reference's netinsert/netselect/
internalinsert/internalselect stack:

- ingest: rows shard to storage nodes by stream hash for locality
  (app/vlstorage/netinsert/netinsert.go:368-409), with a 10s circuit
  breaker per node and re-routing to healthy nodes
  (netinsert.go:283-289, 199-215);
- query: the pipe chain splits into a remote part (filters + streaming
  row-local pipes + per-node stats PARTIALS) and a local part (stats merge
  via the stats funcs' export/import contract + remaining pipes) —
  lib/logstorage/net_query_runner.go:67-96, pipe_stats.go:111-119; results
  stream back as length-prefixed zstd frames
  (app/vlselect/internalselect/internalselect.go:55-100);
- failure semantics: by default any node error fails the whole query (the
  reference's explicit no-partial-results design); ``?partial=1`` (or
  VL_PARTIAL_RESULTS=1) opts a request into merged results from the
  surviving nodes when a node is still down after the policy layer's
  retries, marked with X-VL-Partial + a ``partial.failed_nodes`` block.

Every HTTP hop here rides the fault-policy layer (server/netrobust.py:
per-node circuit breakers shared by select + insert, deadline-aware
retries, hedging, per-read deadlines, fault injection) — enforced by
the vlint ``net-discipline`` checker.  When re-routing exhausts healthy
nodes, ingest spools the serialized shard body to a per-node durable
queue and replays it when the node recovers, so an outage delays rows
instead of dropping them.

Wire formats are this repo's own: versioned via the `version` arg like
the reference's per-endpoint protocol versions (netselect.go:28-63).
Since wire format "t1", internal-select results ship as TYPED COLUMNAR
frames (string arenas + offsets, dict codes, native int64 _time —
BlockResult.wire_columns() on the wire) negotiated per request with the
legacy JSON frame as the mandatory fallback; see the framing section.
"""

from __future__ import annotations

import json
import os
import queue
import struct
import threading
import time

import numpy as np
from .. import config

from .. import sched
from ..engine.block_result import (WIRE_CONST, WIRE_DICT, WIRE_ISO,
                                   WIRE_STR, WIRE_TIME, BlockResult)
from ..logsql.parser import MAX_TS, MIN_TS, parse_query
from ..obs import activity, events, ingestledger, tracing
from ..logsql.pipes import PipeLimit, PipeStats, Processor
from ..storage.log_rows import LogRows, StreamID, TenantID
from ..utils.hashing import stream_id_hash
from . import netrobust, wire_ingest

PROTOCOL_VERSION = "v1"

# frames are written/read from many response and fetch threads; the
# utils.zstd helpers keep per-thread contexts (zstd objects are not
# thread-safe)
from ..utils import zstd as _zstd


# ---------------- stats split pipes ----------------

class PipeStatsExport(PipeStats):
    """Remote half of a stats split: emits per-group EXPORTED states
    instead of finalized values (reference `stats_remote` mode —
    pipe_stats.go:55-60)."""

    name = "stats_export"

    def __init__(self, ps: PipeStats):
        super().__init__(ps.by, ps.funcs)

    def to_string(self):
        return "stats_export:" + super().to_string()[len("stats "):]

    def make_processor(self, next_p):
        pipe = self
        inner = super().make_processor(None)

        class P(type(inner)):
            def flush(self):
                by_names = [b.name for b in pipe.by]
                cols: dict[str, list[str]] = {n: [] for n in by_names}
                for k in range(len(pipe.funcs)):
                    cols[f"__state_{k}"] = []
                for key, states in self.groups.items():
                    for n, kv in zip(by_names, key):
                        cols[n].append(kv)
                    for k, (fn, st) in enumerate(zip(pipe.funcs, states)):
                        # vlint: allow-per-row-emit(per-GROUP stats-state export, bounded by group count)
                        st_json = json.dumps(fn.export_state(st))
                        cols[f"__state_{k}"].append(st_json)
                self.next_p.write_block(
                    BlockResult.from_columns(cols)
                    if any(cols.values()) else BlockResult(0))
                self.next_p.flush()
        p = P(next_p)
        return p


class PipeStatsImport(PipeStats):
    """Local half: imports remote per-group states and merges them
    (reference `stats_local` — importState merging)."""

    name = "stats_import"

    def __init__(self, ps: PipeStats):
        super().__init__(ps.by, ps.funcs)

    def to_string(self):
        return "stats_import:" + super().to_string()[len("stats "):]

    def make_processor(self, next_p):
        pipe = self
        inner = super().make_processor(None)

        class P(type(inner)):
            def write_block(self, br):
                by_names = [b.name for b in pipe.by]
                key_cols = [br.column(n) for n in by_names]
                state_cols = [br.column(f"__state_{k}")
                              for k in range(len(pipe.funcs))]
                for i in range(br.nrows):
                    key = tuple(c[i] for c in key_cols)
                    states = self.groups.get(key)
                    incoming = [
                        fn.import_state(json.loads(state_cols[k][i]))
                        for k, fn in enumerate(pipe.funcs)]
                    if states is None:
                        self.groups[key] = incoming
                        self.budget.add(sum(len(k) for k in key) + 80)
                    else:
                        for k, fn in enumerate(pipe.funcs):
                            states[k] = fn.merge(states[k], incoming[k])
        return P(next_p)


def split_query(q):
    """(mode, split_at, local_pipes): remote part = pipes[:split_at]
    (+ stats export when mode == 'stats'); per-pipe pushdown follows the
    reference's splitToRemoteAndLocal contract (pipe.go:15-22) with
    can_live_tail() marking streaming row-local pipes."""
    for k, p in enumerate(q.pipes):
        if isinstance(p, PipeStats) and \
                all(pp.can_live_tail() for pp in q.pipes[:k]):
            return "stats", k, [PipeStatsImport(p)] + list(q.pipes[k + 1:])
    k = 0
    while k < len(q.pipes) and q.pipes[k].can_live_tail():
        k += 1
    local = list(q.pipes[k:])
    return "rows", k, local


# ---------------- framing ----------------
#
# Two frame payload formats share the outer framing (4-byte BE length +
# zstd payload):
#   - legacy JSON frames: {"cols": {name: [str,...]}, "ts": [...]} —
#     the mandatory fallback every version speaks;
#   - typed columnar frames (PROTOCOL since wire format "t1"): a binary
#     encoding of BlockResult.wire_columns() — string value arenas +
#     uint32 offsets/lengths, dict codes + tiny value arenas, native
#     int64 _time, consts — so the columnar representation survives the
#     network seam instead of being destroyed into row strings and
#     rebuilt on the frontend.
# Frames are self-describing: typed payloads start with a magic prefix
# no JSON document can (b"\x00VLT1"), so a reader handles a mixed
# stream (trace frames stay JSON) and a frontend that REQUESTED typed
# frames still decodes a legacy node's JSON replies — negotiation needs
# no handshake round-trip.  Storage nodes only ever send typed frames
# when the request carried `wire=t1`, so legacy frontends never see
# them.  VL_WIRE_TYPED=0 kills both sides (request and serve).

WIRE_FORMAT = "t1"
TYPED_MAGIC = b"\x00VLT1"

# wire-kind payload scalar dtypes (little-endian on the wire)
_W_NUM_DTYPES = {1: "<i8", 2: "<i8", 3: "<i8", 4: "<u8", 7: "<f8"}


def wire_typed_enabled() -> bool:
    """VL_WIRE_TYPED=0 kill-switch: restores legacy JSON frames exactly
    (this process neither requests nor serves typed frames)."""
    return config.env_flag("VL_WIRE_TYPED")


# ---- wire-protocol observability (vl_wire_* on /metrics) ----

_wire_mu = threading.Lock()
_wire_counts: dict[str, int] = {}


def _wire_note(key: str, delta: int = 1) -> None:
    with _wire_mu:
        _wire_counts[key] = _wire_counts.get(key, 0) + delta


def wire_counters() -> dict:
    with _wire_mu:
        return dict(_wire_counts)


def wire_metrics_samples() -> list:
    """(base, labels, value) samples for Metrics.render: frame counts
    and raw wire bytes (compressed, incl. frame headers), both labeled
    by direction and format — a combined frontend+storage node sends
    AND receives, so the two must not fold into one series.  Data and
    stats frames follow the negotiated format; trace frames always
    ride fmt="json"."""
    c = wire_counters()
    out = []
    for fmt in ("typed", "json"):
        for d in ("tx", "rx"):
            # vlint: allow-per-row-emit(metric label dicts, bounded constant set)
            out.append(("vl_wire_frames_total", {"dir": d, "fmt": fmt},
                        c.get(f"{d}_frames_{fmt}", 0)))
            # vlint: allow-per-row-emit(metric label dicts, bounded constant set)
            out.append(("vl_wire_bytes_total", {"dir": d, "fmt": fmt},
                        c.get(f"{d}_bytes_{fmt}", 0)))
    out.append(("vl_wire_fallbacks_total", {},
                c.get("fallbacks", 0)))
    return out


def write_frame(obj) -> bytes:
    payload = _zstd.compress(json.dumps(obj, ensure_ascii=False,
                                      separators=(",", ":")).encode("utf-8"))
    _wire_note("tx_frames_json")
    _wire_note("tx_bytes_json", len(payload) + 4)
    return struct.pack(">I", len(payload)) + payload


END_FRAME = struct.pack(">I", 0)


def write_typed_frame(br: BlockResult) -> bytes:
    """One result block as a typed columnar frame, serialized straight
    from BlockResult.wire_columns() — no per-row Python objects."""
    names, wcols = br.wire_columns()
    ts = br.timestamps_np()
    parts = [TYPED_MAGIC,
             struct.pack("<IHB", br.nrows, len(names),
                         1 if ts is not None else 0)]
    if ts is not None:
        parts.append(ts.astype("<i8", copy=False).tobytes())
    for name, wc in zip(names, wcols):
        nb = name.encode("utf-8")
        kind = wc[0]
        parts.append(struct.pack("<HB", len(nb), kind))
        parts.append(nb)
        if kind == WIRE_STR:
            arena, offs, lens = wc[1], wc[2], wc[3]
            if int(arena.shape[0]) >= 1 << 32:
                # uint32 offsets can't address it (never happens for
                # block-sized results) — caller falls back to JSON
                raise ValueError("typed frame arena overflow")
            parts.append(struct.pack("<I", int(arena.shape[0])))
            parts.append(arena.tobytes())
            parts.append(offs.astype("<u4").tobytes())
            parts.append(lens.astype("<u4").tobytes())
        elif kind == WIRE_TIME:
            pass            # value array IS the frame timestamps
        elif kind == WIRE_ISO:
            parts.append(struct.pack("<B", wc[2]))
            parts.append(wc[1].astype("<i8", copy=False).tobytes())
        elif kind == WIRE_DICT:
            codes, dvals = wc[1], wc[2]
            parts.append(struct.pack("<B", len(dvals)))
            for v in dvals:
                vb = v.encode("utf-8")
                parts.append(struct.pack("<H", len(vb)))
                parts.append(vb)
            parts.append(codes.astype(np.uint8, copy=False).tobytes())
        elif kind == WIRE_CONST:
            vb = wc[1].encode("utf-8")
            parts.append(struct.pack("<I", len(vb)))
            parts.append(vb)
        else:                # WIRE_INT / WIRE_UINT / WIRE_FLOAT
            parts.append(wc[1].astype(_W_NUM_DTYPES[kind],
                                      copy=False).tobytes())
    payload = _zstd.compress(b"".join(parts))
    _wire_note("tx_frames_typed")
    _wire_note("tx_bytes_typed", len(payload) + 4)
    return struct.pack(">I", len(payload)) + payload


class _FrameReader:
    """Bounds-checked cursor over one decompressed typed payload."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int):
        self.buf = buf
        self.pos = pos

    def take(self, n: int) -> bytes:
        end = self.pos + n
        if n < 0 or end > len(self.buf):
            raise IOError("corrupted typed frame: truncated payload")
        out = self.buf[self.pos:end]
        self.pos = end
        return out

    def array(self, dtype, count: int) -> np.ndarray:
        it = np.dtype(dtype).itemsize
        end = self.pos + it * count
        if end > len(self.buf):
            raise IOError("corrupted typed frame: truncated array")
        a = np.frombuffer(self.buf, dtype=dtype, count=count,
                          offset=self.pos)
        self.pos = end
        return a


def decode_typed_frame(payload: bytes) -> BlockResult:
    """Typed frame payload -> arena-backed BlockResult view.  Raises
    IOError on any structural corruption (the scatter-gather fan-out
    fails the whole query, like any other node transport error)."""
    r = _FrameReader(payload, len(TYPED_MAGIC))
    nrows, ncols, flags = struct.unpack("<IHB", r.take(7))
    ts = None
    if flags & 1:
        ts = r.array("<i8", nrows)
    names: list[str] = []
    wcols: dict = {}
    for _ in range(ncols):
        nlen, kind = struct.unpack("<HB", r.take(3))
        name = r.take(nlen).decode("utf-8", "replace")
        if kind == WIRE_STR:
            alen = struct.unpack("<I", r.take(4))[0]
            arena = np.frombuffer(r.take(alen), dtype=np.uint8)
            offs = r.array("<u4", nrows)
            lens = r.array("<u4", nrows)
            # bounds-check BEFORE these arrays can reach the native
            # emitter (which reads arena+offset unchecked): every
            # row's slice must lie inside the shipped arena
            if nrows and int((offs.astype(np.int64)
                              + lens.astype(np.int64)).max()) > alen:
                raise IOError("corrupted typed frame: string slice "
                              "out of arena bounds")
            wc = (WIRE_STR, arena, offs, lens)
        elif kind == WIRE_TIME:
            if ts is None:
                raise IOError("corrupted typed frame: _time column "
                              "without frame timestamps")
            wc = (WIRE_TIME, ts)
        elif kind == WIRE_ISO:
            frac_w = r.take(1)[0]
            if frac_w > 9:
                # encoders only produce 0-9 fractional digits; larger
                # values would overflow the native formatter's
                # fixed per-value output reservation
                raise IOError("corrupted typed frame: ISO8601 "
                              f"fractional width {frac_w}")
            wc = (WIRE_ISO, r.array("<i8", nrows), frac_w)
        elif kind == WIRE_DICT:
            nvals = r.take(1)[0]
            dvals = []
            for _j in range(nvals):
                vlen = struct.unpack("<H", r.take(2))[0]
                dvals.append(r.take(vlen).decode("utf-8", "replace"))
            codes = r.array(np.uint8, nrows)
            # nvals == 0 with rows present is out of range too (every
            # code must index a shipped value)
            if codes.size and (nvals == 0
                               or int(codes.max()) >= nvals):
                raise IOError("corrupted typed frame: dict code out "
                              "of range")
            wc = (WIRE_DICT, codes, dvals)
        elif kind == WIRE_CONST:
            vlen = struct.unpack("<I", r.take(4))[0]
            wc = (WIRE_CONST, r.take(vlen).decode("utf-8", "replace"))
        elif kind in _W_NUM_DTYPES:
            wc = (kind, r.array(_W_NUM_DTYPES[kind], nrows))
        else:
            raise IOError(f"corrupted typed frame: unknown column "
                          f"kind {kind}")
        names.append(name)
        wcols[name] = wc
    if r.pos != len(payload):
        raise IOError("corrupted typed frame: trailing garbage")
    return BlockResult.from_wire(names, wcols, nrows, ts_np=ts)


def read_frame_payloads(fp):
    """Yield (decompressed payload bytes, wire length) per frame until
    the end frame.  The payload's leading bytes identify its format
    (TYPED_MAGIC vs JSON) — see decode_typed_frame / json.loads."""
    while True:
        hdr = fp.read(4)
        if len(hdr) < 4:
            raise IOError("truncated frame header")
        n = struct.unpack(">I", hdr)[0]
        if n == 0:
            return
        payload = b""
        while len(payload) < n:
            chunk = fp.read(n - len(payload))
            if not chunk:
                raise IOError("truncated frame payload")
            payload += chunk
        yield (_zstd.decompress(payload, max_output_size=1 << 30),
               n + 4)


# ---------------- server side: /internal/select/query ----------------

def handle_internal_select(storage, args, runner=None):
    """Frames generator for one remote sub-query; validates EAGERLY.

    Validation and query parsing run before the generator is returned so
    bad requests surface as ValueError -> HTTP 400 instead of corrupting
    an already-started 200 chunked stream.  The worker thread never
    outlives the response: closing the generator (client disconnect, or
    the frontend's first-error/early-done cancel stopping mid-stream)
    aborts the query at the sink and unblocks any pending put (see
    streamwork).  The query runs under the same server-side deadline as
    single-node /select queries."""
    from ..engine.searcher import run_query
    from .vlselect import query_deadline
    if args.get("version", PROTOCOL_VERSION) != PROTOCOL_VERSION:
        raise ValueError(f"unsupported protocol version "
                         f"{args.get('version')!r}")
    qs = args["query"]
    ts = int(args.get("ts") or time.time_ns())
    mode = args.get("mode", "rows")
    split_at = int(args.get("split_at") or 0)
    limit = int(args.get("limit") or 0)
    tenants = [TenantID.parse(t)
               for t in (args.get("tenant", "0:0")).split(",") if t]
    q = parse_query(qs, timestamp=ts)
    all_pipes = q.pipes
    q.pipes = all_pipes[:split_at]
    if mode == "stats":
        ps = all_pipes[split_at]
        assert isinstance(ps, PipeStats), "split_at must point at stats"
        q.pipes = q.pipes + [PipeStatsExport(ps)]
    elif limit > 0:
        # pushed-down limit: each node returns at most N rows
        q.pipes.append(PipeLimit(limit))

    # EXPLAIN sub-request (frontend handle_explain fan-out): build —
    # and for analyze, execute — EAGERLY, then stream the one-frame
    # result; the tree covers this node's REMOTE half of the pipe
    # split, so the frontend's merged plan shows exactly what each
    # node would dispatch.  Frames stay legacy JSON (trees are small).
    explain_mode = args.get("explain", "")
    if explain_mode:
        if explain_mode not in ("plan", "analyze"):
            raise ValueError(f"invalid explain mode {explain_mode!r}")
        from ..obs import explain as _explain
        tree = _explain.build_plan(storage, tenants, q, runner=runner)
        if explain_mode == "analyze":
            _explain.analyze(storage, tenants, q, tree, runner=runner,
                             deadline=query_deadline(args),
                             endpoint="/internal/select/query",
                             include_trace=args.get("trace") == "1")

        def gen_explain():
            yield write_frame({"explain": tree})
            yield END_FRAME
        return gen_explain()

    # stream frames as blocks arrive; the shared worker protocol
    # (bounded queue + abandon-stream cancellation) lives in streamwork
    from .streamwork import stream_blocks

    # wire negotiation: typed frames only when the frontend asked for
    # them AND this node's kill-switch allows (old frontends never ask,
    # so they only ever see legacy JSON frames)
    typed_wire = args.get("wire") == WIRE_FORMAT and wire_typed_enabled()

    def encode(br):
        if typed_wire:
            try:
                return write_typed_frame(br)
            except ValueError:
                pass        # arena overflow: this block rides JSON
        # legacy frames materialize per-row strings — the fallback
        # every protocol version speaks
        cols = {n: br.column(n) for n in br.column_names()}
        return write_frame({"cols": cols, "ts": br.timestamps})

    deadline = query_deadline(args)
    # the frontend forwards ?trace=1: this node traces its own
    # execution and ships the tree back as the stream's last frame,
    # which the frontend attaches under its per-node span
    root = tracing.make_root("storage_node_query", query=qs) \
        if args.get("trace") == "1" else None
    # propagated query identity: the frontend ships its query's
    # global_qid as parent_qid, so this node's registry record, trace
    # tree and query_done journal event all correlate back to the ONE
    # frontend query that fanned out here
    parent_qid = args.get("parent_qid", "")

    def gen():
        # internal sub-queries register in the active-query registry
        # too: a storage node's active_queries shows the frontend fan-in
        # it is serving, and cancel_query on the node kills a runaway
        # sub-query with the same drain semantics
        with activity.track("/internal/select/query", qs,
                            tenants, parent_qid=parent_qid) as act:
            if root is not None:
                root.set("qid", act.qid)
                if parent_qid:
                    root.set("parent_qid", parent_qid)

            def run(sink):
                # the query executes on streamwork's worker thread:
                # activate the trace and re-enter the registry record
                # THERE (contextvars don't cross thread spawns)
                with tracing.activate(root), activity.use_activity(act):
                    run_query(storage, tenants, q, write_block=sink,
                              runner=runner, deadline=deadline)

            try:
                yield from stream_blocks(run, encode)
            except GeneratorExit:
                # frontend hung up (first-error/early-done cancel):
                # stop the device walk, don't finish a dead sub-query
                act.abandon()
                raise
            if root is not None:
                yield write_frame({"trace": root.to_dict()})
            yield END_FRAME
    return gen()


# ---------------- server side: /internal/insert ----------------

class _InsertPipeline:
    """Decode/store hop overlap for typed /internal/insert frames.

    With ``VL_INSERT_PIPELINE`` > 0 the request thread stops at the
    decode + ledger-entry rolls and hands the decoded batch to a
    bounded queue (maxsize = the configured depth, latched at first
    use); one daemon drainer re-enters the batch's ledger record via
    ``use_batch`` and runs the storage chokepoint, so frame N+1's
    decompress/decode overlaps frame N's block build.  The ledger
    stays exact: ``received`` rolls on the request thread, ``stored``
    (or ``dropped`` on a store error) rolls on the drainer under the
    SAME batch record, so derived in_flight counts queued rows until
    they land.  ``queue.Queue.put`` blocking on a full queue is the
    backpressure — at most ``depth`` batches ever wait.  Default 0
    keeps the store synchronous on the request thread (read-your-
    writes for every existing caller)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._q = None
        self.enqueued_total = 0
        self.stored_total = 0
        self.dropped_total = 0

    def submit(self, storage, lc, per_tenant: dict, nbytes: int) -> bool:
        depth = config.env_int("VL_INSERT_PIPELINE") or 0
        if depth <= 0:
            return False
        with self._mu:
            if self._q is None:
                self._q = queue.Queue(maxsize=max(1, depth))
                threading.Thread(target=self._run, daemon=True,
                                 name="vl-insert-pipeline").start()
            self.enqueued_total += 1
            q = self._q
        q.put((storage, lc, dict(per_tenant), nbytes,
               ingestledger.current_batch()))
        return True

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                self._store(*item)
            # vlint: allow-broad-except(drainer thread must survive)
            except Exception:  # pragma: no cover - keep draining
                pass
            finally:
                self._q.task_done()

    def _store(self, storage, lc, per_tenant, nbytes, ctx) -> None:
        try:
            with ingestledger.use_batch(ctx):
                with ingestledger.hop("store"):
                    storage.must_add_columns(lc)
        # vlint: allow-broad-except(async store: any failure must roll dropped so the ledger balances)
        except Exception:
            for tenant, rows in per_tenant.items():
                ingestledger.note_dropped(
                    tenant, rows, "pipeline_store_error",
                    batch_id=ctx.batch_id if ctx is not None else None)
            with self._mu:
                self.dropped_total += lc.nrows
            return
        for tenant, rows in per_tenant.items():
            activity.note_ingest(tenant, rows,
                                 nbytes=nbytes * rows // lc.nrows)
        with self._mu:
            self.stored_total += lc.nrows

    def drain(self) -> None:
        """Block until every queued batch has stored (tests + shutdown)."""
        q = self._q
        if q is not None:
            q.join()

    def metrics_samples(self) -> list:
        with self._mu:
            depth = self._q.qsize() if self._q is not None else 0
            return [
                ("vl_insert_pipeline_batches_total", {},
                 self.enqueued_total),
                ("vl_insert_pipeline_rows_stored_total", {},
                 self.stored_total),
                ("vl_insert_pipeline_rows_dropped_total", {},
                 self.dropped_total),
                ("vl_insert_pipeline_queue_depth", {}, depth),
            ]


INSERT_PIPELINE = _InsertPipeline()


def handle_internal_insert(storage, args, body: bytes) -> int:
    if args.get("version", PROTOCOL_VERSION) != PROTOCOL_VERSION:
        raise ValueError(f"unsupported protocol version "
                         f"{args.get('version')!r}")
    # the batch identity the sender propagated (the ingest twin of
    # parent_qid): re-enter the frontend's in-flight record when it
    # lives in THIS process (in-process clusters), else register the
    # propagated id so the hop still traces/ledgers.  Legacy senders
    # without batch args get a fresh internal-origin record.
    try:
        accept = float(args.get("batch_ts") or 0.0)
    except ValueError:
        accept = 0.0
    with ingestledger.begin_batch(
            args.get("batch_tenant") or "0:0", origin="internal",
            batch_id=args.get("batch_id") or None,
            accept_unix=accept or None):
        return _internal_insert(storage, args, body)


def _internal_insert(storage, args, body: bytes) -> int:
    with ingestledger.hop("decode"):
        try:
            data = _zstd.decompress(body, max_output_size=1 << 30)
        except Exception as e:
            # zlib.error / ZstdError are NOT ValueErrors; an
            # undecodable body is the sender's corruption, not our
            # 500 — whole-batch 400
            raise ValueError(f"undecodable insert body: {e}") from None
    if data.startswith(wire_ingest.INSERT_MAGIC):
        # typed i1 body (self-describing: JSON lines start with "{").
        # With the kill switch thrown this node speaks legacy ONLY —
        # the 400 tells the sender to re-encode and pin this node to
        # JSON lines (the mixed-version fallback discipline).
        if not wire_ingest.wire_typed_insert_enabled():
            raise ValueError(
                "typed insert frames disabled (VL_WIRE_TYPED_INSERT=0)")
        with ingestledger.hop("decode"):
            lc = wire_ingest.decode_frame(data)  # WireInsertError -> 400
        wire_ingest.note("rx_frames_typed")
        wire_ingest.note("rx_bytes_typed", len(body))
        wire_ingest.note("rx_rows_typed", lc.nrows)
        if lc.nrows:
            # entry roll BEFORE the storage chokepoint's `stored` roll
            per_tenant = wire_ingest.columns_tenant_rows(lc)
            for tenant, rows in per_tenant.items():
                ingestledger.note_received(tenant, rows)
            if not INSERT_PIPELINE.submit(storage, lc, per_tenant,
                                          len(data)):
                with ingestledger.hop("store"):
                    storage.must_add_columns(lc)
                for tenant, rows in per_tenant.items():
                    activity.note_ingest(
                        tenant, rows, nbytes=len(data) * rows // lc.nrows)
        return lc.nrows
    lr = LogRows()
    n = 0
    per_tenant: dict = {}
    with ingestledger.hop("decode"):
        for line in data.splitlines():
            if not line:
                continue
            row = json.loads(line)
            tenant = TenantID(int(row.get("a", 0)), int(row.get("p", 0)))
            tags_str = row.get("s", "")
            hi, lo = stream_id_hash(tags_str.encode("utf-8"))
            lr.timestamps.append(int(row["t"]))
            lr.rows.append([(k, v) for k, v in row["f"]])
            lr.stream_ids.append(StreamID(tenant, hi, lo))
            lr.stream_tags_str.append(tags_str)
            lr.tenants.append(tenant)
            per_tenant[tenant] = per_tenant.get(tenant, 0) + 1
            n += 1
    wire_ingest.note("rx_frames_json")
    wire_ingest.note("rx_bytes_json", len(body))
    wire_ingest.note("rx_rows_json", n)
    if n:
        for tenant, rows in per_tenant.items():
            ingestledger.note_received(tenant, rows)
        with ingestledger.hop("store"):
            storage.must_add_rows(lr)
        for tenant, rows in per_tenant.items():
            # apportion DECOMPRESSED bytes so vl_tenant_ingest_bytes_
            # total means the same thing on storage nodes as on
            # frontends (uncompressed request payload)
            activity.note_ingest(tenant, rows,
                                 nbytes=len(data) * rows // n)
    return n


# ---------------- client side: sharded ingest ----------------

# re-exported for callers that think in cluster terms; defined in the
# policy layer so the HTTP app can catch it without importing cluster
InsertRejectedError = netrobust.InsertRejectedError


class _ShardBodies:
    """Per-shard lazy wire-body cache: the typed i1 body and the legacy
    JSON-lines body are each built AT MOST ONCE per batch, whatever
    combination of preferred/fallback/re-routed sends ends up used —
    a retry never re-pays per-row encoding."""

    __slots__ = ("lc", "_typed", "_legacy")

    def __init__(self, lc):
        self.lc = lc
        self._typed = None
        self._legacy = None

    def typed(self) -> bytes | None:
        """The i1 body, or None when the batch can't ride the format
        (arena/tenant-id overflow — it falls back to legacy lines)."""
        if self._typed is None:
            try:
                self._typed = wire_ingest.encode_columns(self.lc)
            except ValueError:
                self._typed = b""
        return self._typed or None

    def legacy(self) -> bytes:
        if self._legacy is None:
            self._legacy = wire_ingest.encode_legacy_columns(self.lc)
        return self._legacy


class NetInsertStorage:
    """LogRowsStorage that ships rows to storage nodes by stream hash.

    Implements the reference's placement policy (stream-hash routing
    for locality, re-routing to the next healthy node —
    netinsert.go:368-409, 283-289) on top of the shared fault-policy
    layer: per-node circuit breakers (netrobust.breaker_for — the same
    breakers the select fan-out feeds), client-error classification
    (4xx surfaces, 5xx/transport breaks, ingest 429s honor Retry-After
    via breaker.throttle), and a durable per-node spool: when
    re-routing exhausts healthy nodes the already-serialized shard body
    lands in a PersistentQueue (bounded by VL_INSERT_SPOOL_MAX_BYTES)
    and a background thread replays it once the node's breaker lets a
    probe through — a storage-node outage delays rows instead of
    dropping them."""

    def __init__(self, node_urls: list, timeout: float = 30.0,
                 spool_dir: str | None = None):
        if not node_urls:
            raise ValueError("no storage nodes configured")
        self.urls = [u.rstrip("/") for u in node_urls]
        self.timeout = timeout
        # nodes that rejected an i1 frame stay pinned to legacy JSON
        # lines for this process's lifetime (mixed-version discipline);
        # plain set: single-item ops are atomic under the GIL
        self._legacy_nodes: set[int] = set()
        self._encode_pool = wire_ingest.acquire_pool()
        self._spool_dir = spool_dir
        self._spools: dict[int, object] = {}
        self._spool_mu = threading.Lock()
        self._replay_stop = threading.Event()
        self._replay_wake = threading.Event()
        self._replay_thread = None
        if self._spool_enabled():
            # leftover spools from a previous process must replay even
            # if this process never spools: open every existing queue
            for idx in range(len(self.urls)):
                if os.path.isdir(self._spool_path(idx)):
                    self._spool_queue(idx)
            self._start_replay()

    def _spool_enabled(self) -> bool:
        return self._spool_dir is not None and \
            netrobust.spool_max_bytes() > 0

    def _spool_path(self, idx: int) -> str:
        """One node's spool directory, keyed by URL hash so a node
        list reorder never mixes queues (the ONE place the layout is
        defined: startup discovery and queue creation both use it)."""
        import hashlib
        return os.path.join(
            self._spool_dir,
            hashlib.sha256(self.urls[idx].encode()).hexdigest()[:16])

    def must_add_rows(self, lr: LogRows) -> None:
        if not len(lr):
            return
        self.must_add_columns(wire_ingest.rows_to_columns(lr))

    def must_add_columns(self, lc) -> None:
        """Ship a columnar batch: shard by stream hash, encode each
        shard's wire body ONCE (i1 when the node speaks it, legacy
        JSON lines otherwise), deliver with re-route + durable-spool
        semantics.  Multi-shard encodes run on the shared encoder pool
        (numpy packing + zstd drop the GIL)."""
        if lc.nrows == 0:
            return
        batch = ingestledger.current_batch()
        with ingestledger.hop("shard"):
            shards = sorted(wire_ingest.split_columns_by_node(
                lc, len(self.urls)).items())
            items = [(node, _ShardBodies(slc)) for node, slc in shards]
        if len(items) > 1:
            with ingestledger.hop("encode"):
                for f in [self._encode_pool.submit(
                        self._preferred_body, node, bodies)
                        for node, bodies in items]:
                    f.result()
        errors = []
        for node, bodies in items:
            # per-tenant shard rows for the conservation rolls; only
            # batch-tracked flows ledger (journal self-ingest and
            # direct test writes carry no ambient batch)
            tenant_rows = wire_ingest.columns_tenant_rows(bodies.lc) \
                if batch is not None else None
            try:
                with ingestledger.hop("ship"):
                    delivered = self._send_shard(node, bodies) or any(
                        alt != node and self._send_shard(alt, bodies)
                        for alt in range(len(self.urls)))
            except InsertRejectedError:
                # the 400 path is terminal for these rows: the client
                # gets the rejection, nothing is retried or spooled
                if tenant_rows:
                    for t, rows in tenant_rows.items():
                        ingestledger.note_dropped(t, rows,
                                                  "rejected_by_node")
                raise
            if delivered:
                # re-route to any healthy node already folded in above
                # (data locality is a preference, not a correctness
                # requirement)
                if tenant_rows:
                    for t, rows in tenant_rows.items():
                        ingestledger.note_forwarded(t, rows)
                continue
            # every node is down/throttled: spool durably and replay
            # when the shard's node recovers — delay, don't drop.
            # The ALREADY-ENCODED body spools verbatim: replay ships
            # the same bytes, no re-encode per attempt.
            with ingestledger.hop("spool"):
                spooled = self._spool(
                    node, self._preferred_body(node, bodies),
                    nrows=bodies.lc.nrows, tenant_rows=tenant_rows,
                    batch=batch)
            if spooled:
                continue
            errors.append(f"all nodes down for shard {node}")
        if errors:
            raise IOError("; ".join(errors))

    def _node_speaks_typed(self, idx: int) -> bool:
        return wire_ingest.wire_typed_insert_enabled() and \
            idx not in self._legacy_nodes

    def _preferred_body(self, idx: int, bodies: _ShardBodies) -> bytes:
        """The wire body this node should receive (building it if
        needed) — the pool pre-encode and the spool both route here so
        format choice has exactly one home."""
        if self._node_speaks_typed(idx):
            body = bodies.typed()
            if body is not None:
                return body
        return bodies.legacy()

    def _send_shard(self, idx: int, bodies: _ShardBodies) -> bool:
        """One node delivery with the typed→legacy sticky fallback: a
        4xx on an i1 frame pins the node to legacy JSON lines and
        resends the SAME batch once (negotiation without a handshake,
        the t1 discipline on the insert hop)."""
        typed_body = bodies.typed() if self._node_speaks_typed(idx) \
            else None
        if typed_body is None:
            return self._send(idx, bodies.legacy())
        try:
            return self._send(idx, typed_body)
        except InsertRejectedError:
            self._legacy_nodes.add(idx)
            wire_ingest.note("fallbacks")
            events.emit("wire_fallback", url=self.urls[idx],
                        requested=wire_ingest.WIRE_INSERT_FORMAT,
                        hop="insert")
            try:
                return self._send(idx, bodies.legacy())
            except InsertRejectedError:
                # the legacy body was rejected too: the BATCH is the
                # problem, not the node's protocol — unpin it
                self._legacy_nodes.discard(idx)
                raise

    @staticmethod
    def _batch_args(batch_meta: dict | None) -> str:
        """The propagated batch identity on /internal/insert — the
        ingest twin of parent_qid.  From the spool record's header on
        replay (``batch_meta``), else from the ambient batch."""
        from urllib.parse import urlencode
        if batch_meta is not None:
            args = {"batch_id": batch_meta.get("batch_id", ""),
                    "batch_tenant": batch_meta.get("tenant", "")}
            if batch_meta.get("ts"):
                args["batch_ts"] = f"{batch_meta['ts']:.6f}"
        else:
            ctx = ingestledger.current_batch()
            if ctx is None:
                return ""
            args = {"batch_id": ctx.batch_id, "batch_tenant": ctx.tenant,
                    "batch_ts": f"{ctx.accept_unix:.6f}"}
        return "&" + urlencode(args)

    def _send(self, idx: int, body: bytes,
              batch_meta: dict | None = None) -> bool:
        """One policy-managed delivery attempt.  False means 'this node
        cannot take the batch right now' (down/throttled — breaker
        accounting already done inside netrobust.request); a 4xx
        rejection raises InsertRejectedError instead, because re-routing
        a malformed batch would just cascade the rejection."""
        url = self.urls[idx]
        try:
            status, _headers, rbody = netrobust.request(
                url, f"/internal/insert?version={PROTOCOL_VERSION}"
                     f"{self._batch_args(batch_meta)}",
                body,
                headers={"Content-Type": "application/octet-stream"},
                timeout=self.timeout)
        except (IOError, OSError):
            return False
        if 200 <= status < 300:
            return True
        if status != 429 and 400 <= status < 500:
            raise InsertRejectedError(
                f"storage node {url} rejected the batch: HTTP {status}: "
                f"{rbody[:200].decode('utf-8', 'replace')}")
        return False  # 429 (throttled via Retry-After) or 5xx

    # ---- the durable spool ----

    def _spool_queue(self, idx: int):
        from ..utils.persistentqueue import PersistentQueue
        with self._spool_mu:
            q = self._spools.get(idx)
            if q is None:
                q = PersistentQueue(
                    self._spool_path(idx),
                    max_pending_bytes=netrobust.spool_max_bytes())
                self._spools[idx] = q
            return q

    def _spool(self, idx: int, body: bytes, nrows: int,
               tenant_rows: dict | None = None, batch=None) -> bool:
        if not self._spool_enabled():
            if tenant_rows:
                # spool disabled is a hard drop for a batch-tracked
                # shard once every node refused it
                for t, rows in tenant_rows.items():
                    ingestledger.note_dropped(t, rows, "spool_disabled")
            return False
        from ..utils.persistentqueue import QueueOverflowError
        q = self._spool_queue(idx)
        was_empty = q.pending_bytes() == 0
        rec = body
        if batch is not None and tenant_rows:
            # self-describing spool record: replay (this process or the
            # next one after a restart) still attributes the rows to
            # their batch, tenant and accept time
            primary = max(tenant_rows, key=tenant_rows.get)
            rec = ingestledger.wrap_record(
                body, batch.batch_id, primary, nrows,
                accept_unix=batch.accept_unix)
        try:
            q.append(rec)
        except QueueOverflowError:
            netrobust.note("spool_overflow")
            events.emit("spool_overflow", node=self.urls[idx],
                        rows=nrows, pending_bytes=q.pending_bytes())
            if tenant_rows:
                for t, rows in tenant_rows.items():
                    ingestledger.note_dropped(t, rows, "spool_overflow")
            return False
        netrobust.note("spooled_blocks")
        netrobust.note("spooled_rows", nrows)
        if tenant_rows:
            for t, rows in tenant_rows.items():
                ingestledger.note_spooled(t, rows)
        if was_empty:
            # one event per outage burst, not per batch
            events.emit("ingest_spool_start", node=self.urls[idx])
        self._start_replay()
        self._replay_wake.set()
        return True

    def _start_replay(self) -> None:
        with self._spool_mu:
            if self._replay_thread is None:
                self._replay_thread = threading.Thread(
                    target=self._replay_loop, daemon=True,
                    name="vl-insert-spool-replay")
                self._replay_thread.start()

    def _replay_loop(self) -> None:
        """Drain per-node spools back to their nodes.  Paced by the
        breakers: while a node's circuit is open the send attempt is
        refused instantly, and the half-open probe IS the replay —
        recovery and replay are one mechanism."""
        while not self._replay_stop.is_set():
            self._replay_wake.wait(0.25)
            self._replay_wake.clear()
            if self._replay_stop.is_set():
                return
            with self._spool_mu:
                spools = list(self._spools.items())
            for idx, q in spools:
                drained = 0
                while not self._replay_stop.is_set() and \
                        q.pending_bytes() > 0:
                    data = q.read(timeout=None)
                    if data is None:
                        break
                    # batch-tracked records carry a self-describing
                    # header (wrap_record); pre-upgrade records pass
                    # through with meta=None and skip the ledger
                    meta, payload = ingestledger.unwrap_record(data)
                    # a node already pinned to legacy can't take a
                    # spooled i1 frame: re-encode the SAME rows as
                    # JSON lines (typed frames replay verbatim)
                    send_data = payload
                    if idx in self._legacy_nodes:
                        alt = wire_ingest.reencode_legacy(payload)
                        if alt is not None:
                            send_data = alt
                    try:
                        with ingestledger.hop(
                                "replay",
                                tenant=meta["tenant"] if meta else None):
                            sent = self._send(idx, send_data,
                                              batch_meta=meta)
                        if not sent:
                            break
                    except InsertRejectedError:
                        verdict = "poison"
                        if send_data is payload:
                            verdict = self._replay_reject_fallback(
                                idx, q, data, payload, meta)
                        if verdict == "ok":
                            drained += 1
                            continue
                        if verdict == "down":
                            break   # keep the block; retry later
                        # a poisoned block must not wedge the whole
                        # queue behind it: drop it, loudly
                        netrobust.note("spool_rejected_blocks")
                        events.emit("spool_block_rejected",
                                    node=self.urls[idx])
                        if meta:
                            ingestledger.note_dropped(
                                meta["tenant"], meta["nrows"],
                                "spool_block_rejected",
                                batch_id=meta.get("batch_id"),
                                from_spool=True)
                        q.ack(len(data))
                        continue
                    q.ack(len(data))
                    drained += 1
                    netrobust.note("replayed_blocks")
                    if meta:
                        ingestledger.note_replayed(
                            meta["tenant"], meta["nrows"],
                            batch_id=meta.get("batch_id"))
                if drained and q.pending_bytes() == 0:
                    events.emit("ingest_spool_replayed",
                                node=self.urls[idx], blocks=drained)

    def _replay_reject_fallback(self, idx: int, q, data: bytes,
                                payload: bytes,
                                meta: dict | None) -> str:
        """A spooled body was rejected: if it is an i1 frame, the node
        may have stopped speaking typed between spool time and replay
        (downgrade / kill switch) — pin the node to legacy and retry
        the SAME rows as JSON lines once.  Returns 'ok' (delivered +
        acked), 'down' (node unavailable: keep the block, retry
        later), or 'poison' (rejected either way: caller drops it).
        ``data`` is the raw spool record (what ack() measures),
        ``payload`` the wire body inside it."""
        legacy = wire_ingest.reencode_legacy(payload)
        if legacy is None:
            return "poison"       # not typed / undecodable
        self._legacy_nodes.add(idx)
        wire_ingest.note("fallbacks")
        events.emit("wire_fallback", url=self.urls[idx],
                    requested=wire_ingest.WIRE_INSERT_FORMAT,
                    hop="insert-replay")
        try:
            if self._send(idx, legacy, batch_meta=meta):
                q.ack(len(data))
                netrobust.note("replayed_blocks")
                if meta:
                    ingestledger.note_replayed(
                        meta["tenant"], meta["nrows"],
                        batch_id=meta.get("batch_id"))
                return "ok"
            return "down"
        except InsertRejectedError:
            # rejected as legacy too: genuinely poisoned — the batch
            # was the problem, not the node's protocol, so unpin
            self._legacy_nodes.discard(idx)
            return "poison"

    def spool_pending_bytes(self) -> int:
        with self._spool_mu:
            spools = list(self._spools.values())
        return sum(q.pending_bytes() for q in spools)

    def spool_metrics_samples(self) -> list:
        """(base, labels, value) gauges for Metrics.render."""
        with self._spool_mu:
            spools = list(self._spools.items())
        out = []
        for idx, q in spools:
            lbl = {"node": self.urls[idx]}
            # vlint: allow-per-row-emit(metric samples, bounded by node count)
            out.append(("vl_insert_spool_bytes", lbl,
                        q.pending_bytes()))
            out.append(("vl_insert_spool_entries", lbl,
                        q.pending_entries()))
            out.append(("vl_insert_spool_oldest_age_seconds", lbl,
                        round(q.oldest_age_seconds(), 3)))
        return out

    def spool_status(self) -> dict:
        """Per-node spool depth/age for GET /insert/status — the
        wedged-spool view that matters mid-outage."""
        with self._spool_mu:
            spools = list(self._spools.items())
        # vlint: allow-per-row-emit(introspection metadata, bounded by node count)
        nodes = [{"node": self.urls[idx],
                  "pending_bytes": q.pending_bytes(),
                  "entries": q.pending_entries(),
                  "oldest_age_seconds": round(q.oldest_age_seconds(), 3)}
                 for idx, q in spools]
        return {"pending_bytes": sum(n["pending_bytes"] for n in nodes),
                "nodes": nodes}

    def close(self) -> None:
        self._replay_stop.set()
        self._replay_wake.set()
        t = self._replay_thread
        if t is not None:
            t.join(timeout=5)
        with self._spool_mu:
            spools, self._spools = list(self._spools.values()), {}
        for q in spools:
            q.close()
        wire_ingest.release_pool()


# ---------------- client side: scatter-gather select ----------------

def _node_http_error(url: str,
                     e: netrobust.NodeHTTPError) -> Exception:
    """Map a storage node's HTTP error for the fan-out paths: a 429
    (the node's admission control shed us) becomes AdmissionShed so the
    frontend answers 429 + Retry-After with the node's reason and
    concurrency hints — overload propagates as overload, not as an
    internal error.  Other statuses keep the NodeHTTPError: a 4xx
    means this frontend's sub-request was rejected by a live node
    (version/endpoint skew) — never partial-eligible, never a breaker
    trip, surfaced as an internal cluster error (HTTP 500) like the
    legacy path's IOError; 5xx never reaches here (netrobust converts
    it to NodeDownError after retries)."""
    if e.status != 429:
        return e
    try:
        info = json.loads(e.body.decode("utf-8", "replace"))
    except ValueError:
        info = {}
    return sched.AdmissionShed(
        info.get("reason", "queue_full"),
        f"storage node {url} shed the sub-query: "
        f"{info.get('error', 'overloaded')}",
        retry_after=netrobust.retry_after_s(e.headers),
        # forward the node's concurrency hints so the frontend's 429
        # carries X-VL-Concurrency-* end to end
        limit=info.get("limit"),
        current=info.get("current"))


# ---------------- federated introspection (cluster observability) ----------------
#
# The cluster-wide views of the PR 6 registry endpoints: a frontend
# fans one introspection request out to every storage node through the
# netrobust policy layer (select-path breaker gating, injected faults)
# and merges the answers.  A down/hung node is DATA here — marked
# `up: false` in the per-node metadata — never a query failure: the
# federated view must work best exactly when part of the cluster does
# not.

# per-node bound on one introspection fan-out / cancel propagation;
# a hung node costs at most this, and its breaker opens for next time
FED_TIMEOUT_S = 5.0


def _fanout_json(urls, path: str, *, method: str = "GET",
                 timeout: float | None = None, retry: bool = True):
    """One introspection request to every node in parallel.  Returns
    (results, failures): url -> parsed JSON body / url -> error string.
    Never raises — node loss degrades the view, marked per node."""
    from concurrent.futures import ThreadPoolExecutor
    if not urls:
        return {}, {}
    if timeout is None:
        # late-bound so tests/operators can shrink the bound
        timeout = FED_TIMEOUT_S

    # one retry on a transport blip (idempotent introspection; the
    # breaker makes the repeat near-free when the node is truly down);
    # callers with side effects that COUNT (cancel propagation) pass
    # retry=False so a blip after the node acted can't double-count
    attempts = 1 + min(1, netrobust.net_retries()) if retry else 1

    def one(url: str):
        err = ""
        for _ in range(attempts):
            try:
                status, _h, body = netrobust.request(
                    url, path, method=method, timeout=timeout,
                    gate="select")
            except (IOError, OSError) as e:
                err = str(e)
                continue
            if status != 200:
                return url, None, f"HTTP {status}"
            try:
                return url, json.loads(body), None
            except ValueError as e:
                return url, None, f"bad JSON: {e}"
        return url, None, err

    with ThreadPoolExecutor(max_workers=len(urls)) as ex:
        rows = list(ex.map(one, list(urls)))
    results = {u: obj for u, obj, err in rows if err is None}
    failures = {u: err for u, _obj, err in rows if err is not None}
    return results, failures


def federated_active_queries(urls, tenant: str | None = None,
                             timeout: float | None = None) -> dict:
    """GET /select/logsql/active_queries?cluster=1: this frontend's
    live records with each node's sub-query records nested under their
    parent query (matched by the propagated parent_qid == the parent's
    global_qid).  Node records with no parent here (another frontend's
    fan-out, direct node queries) land in ``unlinked`` with node
    attribution; a node that cannot answer is marked down."""
    path = "/select/logsql/active_queries"
    if tenant:
        from urllib.parse import urlencode
        path += "?" + urlencode({"tenant": tenant})
    # local view: frontend-level records only — this process's OWN
    # internal sub-query records (combined frontend+storage deployments,
    # in-process clusters) are re-fetched via the node fan-out below
    # and must not show up twice
    local = [r for r in activity.active_snapshot(tenant=tenant)
             if r["endpoint"] != "/internal/select/query"]
    by_gqid: dict[str, dict] = {}
    for rec in local:
        rec["global_qid"] = activity.global_qid(rec["qid"])
        rec["storage_node_queries"] = []
        by_gqid[rec["global_qid"]] = rec
    results, failures = _fanout_json(urls, path, timeout=timeout)
    nodes, unlinked = [], []
    for url in urls:
        if url in failures:
            # vlint: allow-per-row-emit(introspection metadata, bounded by node count)
            nodes.append({"node": url, "up": False,
                          "error": failures[url]})
            continue
        data = results[url].get("data") or []
        sub = [r for r in data
               if r["endpoint"] == "/internal/select/query"]
        # vlint: allow-per-row-emit(introspection metadata, bounded by node count)
        nodes.append({"node": url, "up": True, "active": len(data)})
        for nrec in sub:
            nrec["node"] = url
            parent = by_gqid.get(nrec.get("parent_qid") or "")
            if parent is not None:
                parent["storage_node_queries"].append(nrec)
            else:
                unlinked.append(nrec)
    out = {"status": "ok", "cluster": True, "data": local,
           "nodes": nodes, "scheduler": sched.snapshot()}
    if unlinked:
        out["unlinked"] = unlinked
    if failures:
        out["failed_nodes"] = sorted(failures)
    return out


def _rec_fingerprint(rec: dict) -> str:
    """Content identity of one completed-query record, attribution
    excluded (the cross-process dedup key for the federated merge)."""
    return json.dumps({k: v for k, v in rec.items() if k != "node"},
                      sort_keys=True, default=str)


def federated_top_queries(urls, n: int = 10, by: str = "duration",
                          tenant: str | None = None,
                          timeout: float | None = None) -> dict:
    """GET /select/logsql/top_queries?cluster=1: this frontend's
    completed ring merged with every node's, re-sorted on the same
    dimension, each record attributed to where it ran (``node``:
    "frontend" or the node URL).  Raises ValueError on an unknown
    ``by`` (HTTP 400 upstream, same as the local form)."""
    from urllib.parse import urlencode
    key, default = activity.top_sort_key(by)
    merged = [dict(r, node="frontend")
              for r in activity.top_queries(n, by=by, tenant=tenant)]
    # dedup guard for combined frontend+storage deployments (and
    # in-process clusters), where the node fan-out re-fetches records
    # this process's own ring already contributed: a record's full
    # content minus the attribution IS its identity
    seen = {_rec_fingerprint(r) for r in merged}
    args = {"n": str(n), "by": by}
    if tenant:
        args["tenant"] = tenant
    path = "/select/logsql/top_queries?" + urlencode(args)
    results, failures = _fanout_json(urls, path, timeout=timeout)
    nodes = []
    for url in urls:
        if url in failures:
            # vlint: allow-per-row-emit(introspection metadata, bounded by node count)
            nodes.append({"node": url, "up": False,
                          "error": failures[url]})
            continue
        # vlint: allow-per-row-emit(introspection metadata, bounded by node count)
        nodes.append({"node": url, "up": True})
        for r in results[url].get("top_queries") or []:
            fp = _rec_fingerprint(r)
            if fp in seen:
                continue
            seen.add(fp)
            merged.append(dict(r, node=url))
    merged.sort(key=lambda r: r.get(key, default), reverse=True)
    out = {"status": "ok", "cluster": True,
           "top_queries": merged[:max(n, 0)], "nodes": nodes}
    if failures:
        out["failed_nodes"] = sorted(failures)
    return out


def federated_insert_status(urls, local: dict,
                            timeout: float | None = None) -> dict:
    """GET /insert/status?cluster=1: this frontend's own payload (the
    spool lives here) plus every storage node's, per node — never
    summed: combined frontend+storage deployments and in-process
    clusters share one process-global ledger, so summing would
    multi-count (the same reason federated_top_queries dedups).  A
    node that cannot answer is marked down — exactly the state in
    which its unshipped batches show as this frontend's stalled/
    spooled entries."""
    results, failures = _fanout_json(urls, "/insert/status",
                                     timeout=timeout)
    nodes = []
    stalled = local.get("stalled_batches", 0)
    for url in urls:
        if url in failures:
            # vlint: allow-per-row-emit(introspection metadata, bounded by node count)
            nodes.append({"node": url, "up": False,
                          "error": failures[url]})
            continue
        p = results[url]
        stalled = max(stalled, p.get("stalled_batches", 0))
        # vlint: allow-per-row-emit(introspection metadata, bounded by node count)
        nodes.append({"node": url, "up": True,
                      "stalled_batches": p.get("stalled_batches", 0),
                      "in_flight": len(p.get("in_flight") or []),
                      "spool": p.get("spool"),
                      "ledger": p.get("ledger")})
    out = dict(local)
    out.update({"cluster": True, "nodes": nodes,
                "stalled_batches_cluster": stalled})
    if failures:
        out["failed_nodes"] = sorted(failures)
    return out


def propagate_cancel(urls, qid: str, gqid: str,
                     timeout: float | None = None) -> dict:
    """Cascade one frontend cancel to every storage node (POST
    /internal/select/cancel?parent_qid=): each node trips the cancel
    flag of every record registered under the query's global_qid, so
    the sub-queries' device windows drain immediately — replacing the
    frontend-disconnect probe (which a node only notices at its next
    frame write) as the primary kill mechanism.  Best-effort by
    design: a dead node cannot be running the sub-query anyway, so its
    failure is recorded (journal ``query_cancel_propagated``), never
    raised."""
    from urllib.parse import urlencode
    path = ("/internal/select/cancel?"
            + urlencode({"parent_qid": gqid}))
    results, failures = _fanout_json(urls, path, method="POST",
                                     timeout=timeout, retry=False)
    cancelled = sum(int(r.get("cancelled") or 0)
                    for r in results.values())
    fail_fields = {"failed_nodes": ",".join(sorted(failures))} \
        if failures else {}
    events.emit("query_cancel_propagated", qid=qid, parent_qid=gqid,
                cancelled=cancelled, nodes_ok=len(results),
                nodes_failed=len(failures), **fail_fields)
    out = {"cancelled": cancelled, "nodes_ok": len(results),
           "nodes_failed": len(failures)}
    if failures:
        out["failed_nodes"] = sorted(failures)
    return out


def federated_standing_queries(urls,
                               timeout: float | None = None) -> dict:
    """GET /select/logsql/standing_query?cluster=1: this frontend's
    standing registrations plus every node's, each node's entries
    attributed to it.  A node that cannot answer is marked down —
    degraded view, never an error."""
    from ..engine.standing import manager as _standing
    path = "/select/logsql/standing_query"
    local = _standing.standing_snapshot()
    results, failures = _fanout_json(urls, path, timeout=timeout)
    nodes = []
    for url in urls:
        if url in failures:
            # vlint: allow-per-row-emit(introspection metadata, bounded by node count)
            nodes.append({"node": url, "up": False,
                          "error": failures[url]})
            continue
        entries = results[url].get("standing_queries") or []
        # vlint: allow-per-row-emit(introspection metadata, bounded by node count)
        nodes.append({"node": url, "up": True,
                      "standing_queries": entries})
    out = {"status": "ok", "cluster": True,
           "standing_queries": local, "nodes": nodes}
    if failures:
        out["failed_nodes"] = sorted(failures)
    return out


def federated_standing_unregister(urls, fp: str,
                                  timeout: float | None = None) -> dict:
    """Cascade one standing-query unregister to every storage node
    (POST /select/logsql/standing_query?unregister=1): a panel torn
    down at the frontend must not leave node-local registrations
    re-evaluating forever.  retry=False — an unregister that landed
    must not double-count on a transport blip; best-effort like cancel
    propagation (a dead node's registry died with it)."""
    from urllib.parse import urlencode
    path = ("/select/logsql/standing_query?"
            + urlencode({"unregister": "1", "fingerprint": fp}))
    results, failures = _fanout_json(urls, path, method="POST",
                                     timeout=timeout, retry=False)
    removed = sum(int(r.get("removed") or 0)
                  for r in results.values())
    out = {"removed": removed, "nodes_ok": len(results),
           "nodes_failed": len(failures)}
    if failures:
        out["failed_nodes"] = sorted(failures)
    return out


class NetSelectStorage:
    """Query layer over N storage nodes: remote/local pipe split, parallel
    fan-out, first-error cancellation (netselect.go:324-369)."""

    def __init__(self, node_urls: list, timeout: float = 120.0):
        if not node_urls:
            raise ValueError("no storage nodes configured")
        self.urls = [u.rstrip("/") for u in node_urls]
        self.timeout = timeout
        # request typed columnar frames from storage nodes (nodes that
        # predate the format, or run VL_WIRE_TYPED=0, ignore the arg
        # and answer with legacy JSON frames — handled per frame)
        self.wire_typed = wire_typed_enabled()

    def net_explain(self, tenants, q, mode: str,
                    timestamp: int | None = None,
                    deadline: float | None = None,
                    include_trace: bool = False) -> dict:
        """Cluster EXPLAIN: scatter the (pipe-split) query to every
        storage node with explain=<mode>, merge the per-node plan trees
        under storage_node nodes — the same merge shape ?trace=1 uses —
        and fold the node predictions into one cluster summary
        (counts/seconds sum; duration is the max, nodes run in
        parallel)."""
        from concurrent.futures import ThreadPoolExecutor
        from urllib.parse import urlencode
        if isinstance(q, str):
            q = parse_query(q, timestamp)
        ts = q.timestamp if getattr(q, "timestamp", None) else \
            (timestamp or time.time_ns())
        if mode == "analyze":
            # the run needs in(<subquery>) values; a plain explain=1
            # must not execute anything, so subqueries stay symbolic
            from ..engine.searcher import init_subqueries
            init_subqueries(self, tenants, q, detach=True)
        split_mode, split_at, local_pipes = split_query(q)
        # limit pushdown parity with net_run_query: the plan (and the
        # analyze execution) must describe the sub-query each node would
        # actually run, early-exit included
        push_limit = 0
        if split_mode == "rows" and local_pipes and \
                isinstance(local_pipes[0], PipeLimit):
            push_limit = local_pipes[0].n
        tenants = list(tenants) or [TenantID(0, 0)]
        tenant_arg = ",".join(f"{t.account_id}:{t.project_id}"
                              for t in tenants)
        remaining_s = None
        if deadline is not None:
            remaining_s = max(deadline - time.monotonic(), 0.001)
        act = activity.current_activity()

        def fetch(url: str) -> dict:
            form = {
                "version": PROTOCOL_VERSION,
                "query": q.to_string(),
                "ts": str(ts),
                "mode": split_mode,
                "split_at": str(split_at),
                "limit": str(push_limit),
                "tenant": tenant_arg,
                "explain": mode,
            }
            if act.enabled:
                # identity propagation parity with net_run_query: the
                # node's explain/analyze record correlates by qid too
                form["parent_qid"] = activity.global_qid(act.qid)
            if remaining_s is not None:
                form["timeout"] = f"{remaining_s:.3f}s"
            if include_trace:
                # trace parity with the single-node path: each node's
                # analyze tree then carries its own span tree
                form["trace"] = "1"
            http_timeout = self.timeout if remaining_s is None else \
                min(self.timeout, remaining_s + 5.0)
            tree = None
            try:
                # the policy layer owns retries/breaker/deadline; an
                # explain sub-request is idempotent by construction
                frames = netrobust.node_stream(
                    url, "/internal/select/query",
                    urlencode(form).encode("utf-8"),
                    {"Content-Type":
                     "application/x-www-form-urlencoded"},
                    io_timeout=http_timeout, deadline=deadline,
                    idempotent=True)
                try:
                    for payload, _n in frames:
                        frame = json.loads(payload)
                        if "explain" in frame:
                            tree = frame["explain"]
                finally:
                    frames.close()
            except netrobust.NodeHTTPError as e:
                # a node's admission control shedding the explain
                # sub-request must surface as 429 + Retry-After at the
                # frontend, exactly like net_run_query
                raise _node_http_error(url, e) from None
            if tree is None:
                raise IOError(f"{url}: no explain frame in reply")
            return {"name": "storage_node", "url": url,
                    "explain": tree}

        with ThreadPoolExecutor(max_workers=len(self.urls)) as ex:
            nodes = list(ex.map(fetch, self.urls))
        merged: dict = {
            "name": "explain", "mode": mode, "cluster": True,
            "query": q.to_string(), "storage_nodes": nodes,
        }
        pred: dict = {}
        calibrated = True
        for node in nodes:
            np_ = node["explain"].get("predicted") or {}
            calibrated = calibrated and bool(np_.get("calibrated"))
            for k, v in np_.items():
                if not isinstance(v, (int, float)) or \
                        isinstance(v, bool):
                    continue
                if k == "duration_s":
                    pred[k] = max(pred.get(k, 0.0), v)
                else:
                    pred[k] = round(pred.get(k, 0) + v, 6)
        pred["calibrated"] = calibrated
        merged["predicted"] = pred
        return merged

    def net_run_query(self, tenants, q, write_block=None,
                      timestamp: int | None = None,
                      deadline: float | None = None,
                      partial: bool | None = None) -> None:
        """Scatter-gather one query.  ``partial=None`` resolves the
        partial-results mode from the ambient activity record (the HTTP
        layer stamps ?partial=1 there) falling back to the
        VL_PARTIAL_RESULTS default; True/False pin it."""
        from ..engine.searcher import build_processor_chain, init_subqueries
        if isinstance(q, str):
            q = parse_query(q, timestamp)
        ts = q.timestamp if getattr(q, "timestamp", None) else \
            (timestamp or time.time_ns())
        # subqueries resolve against the WHOLE cluster here, then ship as
        # literal value lists (per-shard resolution would be wrong)
        init_subqueries(self, tenants, q, detach=True)
        # storage-backed pipes (join/union/stream_context) also query the
        # cluster through this front
        for p in q.pipes:
            if hasattr(p, "init_with_storage"):
                p.init_with_storage(self, tenants, None)
        mode, split_at, local_pipes = split_query(q)

        # rate()/rate_sum() step for locally-finalized stats
        min_ts, max_ts = q.get_time_range()
        if min_ts != MIN_TS and max_ts != MAX_TS:
            step_seconds = (max_ts - min_ts + 1) / 1e9
            for p in local_pipes:
                if isinstance(p, PipeStats):
                    for fn in p.funcs:
                        if hasattr(fn, "step_seconds"):
                            fn.step_seconds = step_seconds

        push_limit = 0
        if mode == "rows" and local_pipes and \
                isinstance(local_pipes[0], PipeLimit):
            push_limit = local_pipes[0].n

        head = build_processor_chain(local_pipes,
                                     write_block or (lambda br: None))
        # external cancellation (cancel_query / disconnect abandon):
        # the frontend's registry record ends the scatter-gather the
        # same way early-done does — fetch threads stop pulling frames
        act = activity.current_activity()
        if partial is not None:
            partial_ok = partial
        else:
            pf = act.counter("partial_ok")
            partial_ok = pf > 0 if pf else netrobust.partial_default()
        lock = threading.Lock()
        stop = threading.Event()
        errors: list = []          # (url, exception) per failed node
        tenants = list(tenants) or [TenantID(0, 0)]
        tenant_arg = ",".join(f"{t.account_id}:{t.project_id}"
                              for t in tenants)

        # forward the caller's remaining deadline so storage nodes enforce
        # the same budget the single-node path would (they re-derive it via
        # query_deadline(args) from this `timeout` arg)
        remaining_s = None
        if deadline is not None:
            remaining_s = max(deadline - time.monotonic(), 0.001)
        # scatter-gather tracing: each node fetch gets a child span under
        # the caller's trace, and nodes ship their own span tree back as
        # the stream's final frame, attached under that child — one
        # merged tree for the whole cluster query
        parent_span = tracing.current_span()

        def fetch(url: str):
            from urllib.parse import urlencode
            # POST the query as a form body: materialized in(...) value
            # lists can exceed sane URL lengths
            form = {
                "version": PROTOCOL_VERSION,
                "query": q.to_string(),
                "ts": str(ts),
                "mode": mode,
                "split_at": str(split_at),
                "limit": str(push_limit),
                "tenant": tenant_arg,
            }
            if act.enabled:
                # query identity propagation: every storage node tags
                # its sub-query record/trace/journal with the frontend
                # query's cluster-unique id — the primitive the
                # federated registry and cascading cancel ride
                form["parent_qid"] = activity.global_qid(act.qid)
            if remaining_s is not None:
                form["timeout"] = f"{remaining_s:.3f}s"
            if parent_span.enabled:
                form["trace"] = "1"
            if self.wire_typed:
                form["wire"] = WIRE_FORMAT
            body = urlencode(form).encode("utf-8")
            http_timeout = self.timeout if remaining_s is None else \
                min(self.timeout, remaining_s + 5.0)
            try:
                saw_json_data = False
                with tracing.use_span(parent_span), \
                        tracing.current_span().span("storage_node",
                                                    url=url) as nsp:
                    # ALL fault policy (breaker, retries, hedging,
                    # per-read deadlines, injected faults) lives in the
                    # policy layer; this loop only decodes frames
                    frames = netrobust.node_stream(
                        url, "/internal/select/query", body,
                        {"Content-Type":
                         "application/x-www-form-urlencoded"},
                        io_timeout=http_timeout, deadline=deadline,
                        idempotent=True, span=nsp)
                    try:
                        for payload, wire_n in frames:
                            if stop.is_set() or act.is_cancelled():
                                # abandoning the stream also abandons
                                # the node's trailing trace frame — the
                                # cancellation (which aborts the node's
                                # query) outranks trace completeness,
                                # so the cut is marked instead
                                nsp.set("trace_truncated", True)
                                return
                            t_dec = time.monotonic()
                            if payload.startswith(TYPED_MAGIC):
                                br = decode_typed_frame(payload)
                                _wire_note("rx_frames_typed")
                                _wire_note("rx_bytes_typed", wire_n)
                                nsp.add("typed_frames")
                            else:
                                frame = json.loads(payload)
                                _wire_note("rx_frames_json")
                                _wire_note("rx_bytes_json", wire_n)
                                if "trace" in frame:
                                    nsp.attach(frame["trace"])
                                    continue
                                if self.wire_typed and \
                                        not saw_json_data:
                                    # we asked for typed frames; the
                                    # node answered legacy — a
                                    # mixed-version cluster running on
                                    # the fallback is worth an
                                    # operator-visible journal event
                                    saw_json_data = True
                                    _wire_note("fallbacks")
                                    events.emit("wire_fallback",
                                                url=url,
                                                requested=WIRE_FORMAT)
                                br = BlockResult.from_columns(
                                    frame.get("cols") or {},
                                    timestamps=frame.get("ts"))
                            nsp.add("wire_decode_s",
                                    time.monotonic() - t_dec)
                            nsp.add("wire_rx_bytes", wire_n)
                            nsp.add("blocks_received")
                            with lock:
                                head.write_block(br)
                                if head.is_done():
                                    stop.set()
                                    nsp.set("trace_truncated", True)
                                    return
                    finally:
                        frames.close()
            except netrobust.NodeHTTPError as e:
                # 429 -> AdmissionShed, other 4xx stay client errors;
                # both always fail the whole query (partial covers node
                # LOSS, not a sub-query the node judged invalid)
                errors.append((url, _node_http_error(url, e)))
                stop.set()
            # collected errors re-raise on the caller thread after join
            # vlint: allow-broad-except(fan-out error channel)
            except Exception as e:
                errors.append((url, e))
                if not (partial_ok and isinstance(e, (IOError, OSError))):
                    # strict mode: first error cancels the other
                    # fetches.  In partial mode a transport failure
                    # must NOT stop the surviving nodes — their merged
                    # answer IS the degraded result.
                    stop.set()

        threads = [threading.Thread(target=fetch, args=(u,), daemon=True)
                   for u in self.urls]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            # Default: no partial results — any storage-node failure
            # fails the query.  Local typed errors (memory budget,
            # deadline) raised by head.write_block re-raise unwrapped so
            # the HTTP layer maps them to 422/503 exactly as in
            # single-node mode; only genuine transport failures become
            # IOError.  A shed outranks other failures
            # deterministically: the client must see 429 + Retry-After
            # whenever ANY node shed, not only when that node's fetch
            # thread happened to error first.
            shed = next((e for _u, e in errors
                         if isinstance(e, sched.AdmissionShed)), None)
            if shed is None and partial_ok and \
                    len(errors) < len(self.urls) and \
                    all(isinstance(e, (IOError, OSError))
                        for _u, e in errors):
                # opted-in degradation: at least one node survived and
                # every failure is an availability failure — answer
                # from the survivors, loudly marked
                failed = sorted({u for u, _e in errors})
                act.set("partial_failed_nodes", failed)
                parent_span.set("partial_failed_nodes", failed)
                netrobust.note("partial_results")
                events.emit("partial_result", query=q.to_string(),
                            failed_nodes=",".join(failed),
                            surviving=len(self.urls) - len(failed))
                head.flush()
                return
            err = shed if shed is not None else errors[0][1]
            if isinstance(err, (IOError, OSError)):
                raise IOError(f"cluster query failed: {err}")
            raise err
        head.flush()
