"""vlagent: lightweight log forwarder with disk-backed delivery queues.

Redesign of the reference app/vlagent: accepts every vlinsert protocol,
serializes rows to the native cluster wire format, appends them to a
persistent queue PER remote (replication: every -remoteWrite.url gets every
row — remotewrite.go:165-184), and background clients deliver each queue
with retries/backoff.  Rows survive agent restarts and remote outages
(remotewrite.go:188-214).

Run: python -m victorialogs_tpu.server.vlagent \
        -remoteWrite.url http://host:9428 -httpListenAddr :9429
"""

from __future__ import annotations

import argparse
import hashlib
import os
import signal
import sys
import threading
import time

from ..obs import events, ingestledger
from ..storage.log_rows import LogRows
from ..utils.persistentqueue import PersistentQueue
from . import netrobust, wire_ingest
from .cluster import NetInsertStorage, PROTOCOL_VERSION
from .insertutil import LogRowsStorage

def encode_rows(lr: LogRows) -> bytes:
    """One queue block (same wire body /internal/insert consumes):
    a typed i1 frame since wire format "i1" — encoded ONCE here, then
    replicated to every remote's queue and replayed VERBATIM across
    retries and restarts — with legacy zstd'd JSON lines under the
    VL_WIRE_TYPED_INSERT=0 kill switch (or when a batch can't ride
    the typed format: arena/tenant-id overflow)."""
    if wire_ingest.wire_typed_insert_enabled():
        try:
            return wire_ingest.encode_rows(lr)
        except ValueError:
            pass
    return wire_ingest.encode_legacy_columns(
        wire_ingest.rows_to_columns(lr))


class RemoteWriteClient:
    """Delivers one persistent queue to one remote URL with backoff."""

    def __init__(self, url: str, queue: PersistentQueue,
                 timeout: float = 30.0):
        self.url = url.rstrip("/")
        self.queue = queue
        self.timeout = timeout
        self.delivered_blocks = 0
        self.errors = 0
        self.retry_after_honored = 0
        self.dropped_blocks = 0
        # sticky: the remote rejected an i1 frame (old version or
        # VL_WIRE_TYPED_INSERT=0 on its side) — deliver legacy lines
        self._legacy_remote = False
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _wire_body(self, block: bytes) -> bytes:
        """The bytes to put on the wire for one queue block.  Typed
        blocks ship VERBATIM; only a legacy-pinned remote pays a
        re-encode (decode i1 -> JSON lines), and only once per block
        because the caller caches the result across retries."""
        if self._legacy_remote:
            legacy = wire_ingest.reencode_legacy(block)
            if legacy is not None:
                return legacy
        return block

    def _loop(self) -> None:
        backoff = 0.5
        # the in-flight block is read from disk ONCE and its wire body
        # built ONCE: every retry (backoff, Retry-After park, breaker
        # re-probe) reuses the same bytes instead of re-reading the
        # queue head and re-paying the encode per attempt.  ack() always
        # takes the RAW record length (batch header included) — the wire
        # body may be shorter (header stripped) or longer (legacy
        # re-encode)
        block: bytes | None = None
        payload: bytes | None = None
        body: bytes | None = None
        meta: dict | None = None
        while not self._stop.is_set():
            if block is None:
                block = self.queue.read(timeout=0.5)
                if block is None:
                    continue
                meta, payload = ingestledger.unwrap_record(block)
                body = self._wire_body(payload)
            ok, hint, rejected = self._send(body, meta)
            if ok:
                self.queue.ack(len(block))
                self.delivered_blocks += 1
                block = payload = body = meta = None
                backoff = 0.5
            elif rejected:
                self.errors += 1
                if body is payload and not self._legacy_remote:
                    legacy = wire_ingest.reencode_legacy(payload)
                    if legacy is not None:
                        # the remote can't speak i1: pin it to legacy
                        # lines and retry the SAME rows immediately
                        self._legacy_remote = True
                        wire_ingest.note("fallbacks")
                        events.emit("wire_fallback", url=self.url,
                                    requested=(wire_ingest
                                               .WIRE_INSERT_FORMAT),
                                    hop="agent")
                        body = legacy
                        continue
                # rejected in the format the remote speaks: a poisoned
                # block must not wedge the queue behind it — drop it,
                # loudly.  This is a replica-level drop (this remote's
                # copy only; the rows were forwarded-counted ONCE at
                # enqueue and other replicas may still deliver them), so
                # it stays out of the per-row ledger by design.
                # vlint: allow-drop-discipline(replica-level block drop; rows were forwarded-counted once at _append_block)
                self.dropped_blocks += 1
                events.emit("queue_block_rejected", url=self.url)
                self.queue.ack(len(block))
                block = payload = body = meta = None
            elif hint is not None:
                # the remote SAID how loaded it is (429 + Retry-After +
                # X-VL-Concurrency hints): honor its guidance instead
                # of blind exponential backoff, and restart the
                # exponential ladder — the next failure without a hint
                # starts cheap again
                self.errors += 1
                self.retry_after_honored += 1
                self._stop.wait(min(hint, 60.0))
                backoff = 0.5
            else:
                self.errors += 1
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 30.0)

    @staticmethod
    def _shed_hint(headers) -> float:
        """Retry delay from a 429's response headers: Retry-After,
        scaled up by how far over its concurrency limit the server
        reports itself (X-VL-Concurrency-Current/-Limit — the
        server-side adaptive-backoff contract in app.respond_shed)."""
        try:
            wait = float(headers.get("Retry-After") or 1.0)
        except ValueError:
            wait = 1.0
        try:
            limit = int(headers.get("X-VL-Concurrency-Limit") or 0)
            current = int(headers.get("X-VL-Concurrency-Current") or 0)
        except ValueError:
            limit = current = 0
        if limit > 0 and current > 0:
            # at/over capacity -> stretch; freeing up -> never below
            # half the advertised Retry-After
            wait *= min(4.0, max(0.5, current / limit))
        return max(0.1, wait)

    def _send(self, body: bytes,
              meta: dict | None = None) -> tuple[bool, float | None, bool]:
        """(delivered, retry_hint_s, rejected) — the hint is non-None
        only for an explicit overload shed (HTTP 429); rejected is True
        for a non-429 4xx (the remote REFUSED the body: retrying the
        same bytes can't succeed — the caller falls back to legacy
        lines or drops the block).  Rides the shared fault-policy layer
        with ``gate=False``: the agent's own backoff ladder owns the
        retry cadence (the queue IS the retry buffer), but deliveries
        still feed the per-node breaker/health state."""
        try:
            status, headers, _rbody = netrobust.request(
                self.url,
                f"/internal/insert?version={PROTOCOL_VERSION}"
                f"{NetInsertStorage._batch_args(meta) if meta else ''}",
                body,
                headers={"Content-Type": "application/octet-stream"},
                timeout=self.timeout, gate=False)
        except (IOError, OSError):
            return False, None, False
        if status == 429:
            return False, self._shed_hint(headers), False
        return (200 <= status < 300, None,
                400 <= status < 500)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


class VLAgent(LogRowsStorage):
    """LogRowsStorage fan-out: every batch goes to every remote's queue."""

    def __init__(self, remote_urls: list, queues_dir: str,
                 max_pending_bytes: int = 1 << 30):
        if not remote_urls:
            raise ValueError("vlagent needs at least one -remoteWrite.url")
        self.clients = []
        self._stats_mu = threading.Lock()
        self.rows_forwarded = 0
        self.bytes_forwarded = 0
        for url in remote_urls:
            qdir = os.path.join(
                queues_dir,
                hashlib.sha256(url.encode()).hexdigest()[:16])
            q = PersistentQueue(qdir, max_pending_bytes=max_pending_bytes)
            self.clients.append(RemoteWriteClient(url, q))

    def must_add_rows(self, lr: LogRows) -> None:
        if not len(lr):
            return
        self._append_block(encode_rows(lr), len(lr))

    def must_add_columns(self, lc) -> None:
        """Columnar twin of must_add_rows: the jsonline bulk fast path
        lands here (supports_columns), so the agent encodes the i1
        frame straight from the columnar batch — no per-row
        LogRows detour before the queue."""
        if lc.nrows == 0:
            return
        if wire_ingest.wire_typed_insert_enabled():
            try:
                block = wire_ingest.encode_columns(lc)
            except ValueError:
                block = wire_ingest.encode_legacy_columns(lc)
        else:
            block = wire_ingest.encode_legacy_columns(lc)
        self._append_block(block, lc.nrows)

    def _append_block(self, block: bytes, nrows: int) -> None:
        batch = ingestledger.current_batch()
        if batch is not None:
            # the queue record carries the batch identity + accept time
            # so delivery (possibly days later, after an agent restart)
            # still propagates them to the remote's ledger
            block = ingestledger.wrap_record(
                block, batch.batch_id, batch.tenant, nrows,
                accept_unix=batch.accept_unix)
            # ledger: rows leave this process at durable enqueue — the
            # queue owns delivery from here; replicas are transport
            # fan-out of the same rows, not new rows
            ingestledger.note_forwarded(batch.tenant, nrows, batch=batch)
        for c in self.clients:
            c.queue.append(block)
        # forwarded-traffic accounting: each batch counted ONCE (rows
        # and encoded bytes), regardless of how many remotes replicate
        # it — per-destination delivery is what the per-client
        # delivered_blocks counters measure.  Per-tenant registry
        # accounting already happened in the HTTP layer's
        # handle_insert (note_ingest), so none here.
        with self._stats_mu:
            self.rows_forwarded += nrows
            self.bytes_forwarded += len(block)

    def pending_bytes(self) -> int:
        return sum(c.queue.pending_bytes() for c in self.clients)

    def wait_drained(self, timeout: float = 30.0) -> bool:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if self.pending_bytes() == 0:
                return True
            time.sleep(0.05)
        return False

    def close(self) -> None:
        for c in self.clients:
            c.close()
            c.queue.close()


def main(argv=None) -> int:
    from .agent_http import AgentServer

    p = argparse.ArgumentParser(prog="vlagent", description=__doc__)
    p.add_argument("-remoteWrite.url", action="append", dest="remotes",
                   default=None, required=False)
    p.add_argument("-remoteWrite.tmpDataPath", dest="queues_dir",
                   default="vlagent-queues")
    p.add_argument("-httpListenAddr", default=":9429")
    p.add_argument("-remoteWrite.maxPendingBytes", type=int,
                   dest="max_pending", default=1 << 30)
    args = p.parse_args(argv)
    if not args.remotes:
        print("missing -remoteWrite.url", file=sys.stderr)
        return 2

    agent = VLAgent(args.remotes, args.queues_dir,
                    max_pending_bytes=args.max_pending)
    host, _, port_s = args.httpListenAddr.rpartition(":")
    server = AgentServer(agent, listen_addr=host or "0.0.0.0",
                         port=int(port_s or 9429))
    print(f"started vlagent at http://{host or '0.0.0.0'}:{server.port}/",
          flush=True)

    # the handler only flips a plain flag (no locks: Event.set() from a
    # signal handler can self-deadlock on the condition lock); the wait
    # loop re-checks after every sleep, so a signal landing anywhere costs
    # at most one poll interval instead of hanging until a second signal
    stop = []

    def on_signal(_sig, _frm):
        stop.append(1)
    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    try:
        while not stop:
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    server.close()
    agent.close()
    print("vlagent shut down", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
