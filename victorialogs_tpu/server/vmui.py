"""Embedded web UI (the vmui analogue, served at /select/vmui/).

The reference embeds a prebuilt React SPA (app/vlselect/main.go:71-74);
this is a self-contained single-file app over the same HTTP API — no
build step, no external assets (the image has zero egress):

- LogsQL query editor with time-range presets / custom range, limit and
  tenant controls, Ctrl+Enter to run;
- hits histogram over /select/logsql/hits (SVG, per-bar hover tooltip,
  light/dark aware — single series, labeled by the panel title);
- results as an expandable table or raw JSON (the table doubles as the
  chart's accessible data view);
- field browser over field_names/field_values with click-to-filter;
- live tail over /select/logsql/tail (streamed fetch).
"""

VMUI_HTML = r"""<!doctype html>
<html>
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>VictoriaLogs TPU</title>
<style>
  :root {
    color-scheme: light;
    --surface: #fcfcfb; --panel: #ffffff; --border: #e4e3df;
    --text: #0b0b0b; --text-2: #52514e; --muted: #8a897f;
    --accent: #2a78d6;           /* series-1: the hits histogram */
    --accent-soft: #2a78d622;
    --bad: #e34948; --grid: #edece8;
  }
  @media (prefers-color-scheme: dark) {
    :root {
      color-scheme: dark;
      --surface: #1a1a19; --panel: #232322; --border: #3a3936;
      --text: #ffffff; --text-2: #c3c2b7; --muted: #8a897f;
      --accent: #3987e5; --accent-soft: #3987e533;
      --bad: #e66767; --grid: #2e2d2b;
    }
  }
  * { box-sizing: border-box; }
  body { font: 14px/1.45 -apple-system, system-ui, sans-serif; margin: 0;
         background: var(--surface); color: var(--text); }
  header { display: flex; gap: 10px; align-items: center;
           padding: 10px 16px; border-bottom: 1px solid var(--border); }
  header h1 { font-size: 15px; margin: 0; font-weight: 650; }
  header .sub { color: var(--muted); font-size: 12px; }
  #bar { display: flex; gap: 8px; padding: 12px 16px 4px; flex-wrap: wrap; }
  #query { flex: 1 1 420px; font: 13px/1.4 ui-monospace, monospace;
           padding: 8px 10px; min-height: 38px; resize: vertical;
           background: var(--panel); color: var(--text);
           border: 1px solid var(--border); border-radius: 6px; }
  select, button, input {
    font-size: 13px; padding: 7px 10px; background: var(--panel);
    color: var(--text); border: 1px solid var(--border);
    border-radius: 6px; }
  button { cursor: pointer; }
  button.primary { background: var(--accent); color: #fff;
                   border-color: var(--accent); font-weight: 600; }
  button.on { outline: 2px solid var(--accent); }
  #opts { display: flex; gap: 8px; padding: 4px 16px 8px; flex-wrap: wrap;
          align-items: center; color: var(--text-2); font-size: 13px; }
  #opts input { width: 110px; }
  #opts input.wide { width: 180px; }
  #status { padding: 2px 16px 6px; font-size: 12px; color: var(--muted); }
  #error { margin: 0 16px 8px; padding: 8px 12px; border-radius: 6px;
           background: color-mix(in srgb, var(--bad) 12%, var(--panel));
           color: var(--bad); white-space: pre-wrap; display: none;
           font-family: ui-monospace, monospace; font-size: 12px; }
  .panel { margin: 0 16px 12px; background: var(--panel);
           border: 1px solid var(--border); border-radius: 8px; }
  .panel h2 { font-size: 12px; font-weight: 600; color: var(--text-2);
              margin: 0; padding: 8px 12px 0; }
  #histwrap { position: relative; padding: 4px 12px 8px; }
  #hist { width: 100%; height: 110px; display: block; }
  #tip { position: absolute; pointer-events: none; display: none;
         background: var(--panel); border: 1px solid var(--border);
         border-radius: 6px; padding: 4px 8px; font-size: 12px;
         box-shadow: 0 2px 8px #0003; white-space: nowrap; z-index: 5; }
  #tabs { display: flex; gap: 2px; padding: 0 16px; }
  #tabs button { border-radius: 6px 6px 0 0; border-bottom: none; }
  #tabs button.active { background: var(--panel); font-weight: 600; }
  #out { margin: 0 16px 16px; background: var(--panel);
         border: 1px solid var(--border); border-radius: 0 8px 8px 8px;
         overflow: auto; max-height: 70vh; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; }
  th, td { text-align: left; padding: 5px 10px;
           border-bottom: 1px solid var(--grid); vertical-align: top; }
  th { position: sticky; top: 0; background: var(--panel);
       color: var(--text-2); font-weight: 600; cursor: default; }
  td.msg { font-family: ui-monospace, monospace; font-size: 12px;
           white-space: pre-wrap; word-break: break-word; }
  tr.row:hover { background: var(--accent-soft); cursor: pointer; }
  tr.detail td { background: color-mix(in srgb, var(--accent) 4%,
                 var(--panel)); font-family: ui-monospace, monospace;
                 font-size: 12px; white-space: pre-wrap; }
  #json { font: 12px/1.5 ui-monospace, monospace; margin: 0;
          padding: 10px 12px; white-space: pre-wrap; }
  #fields { display: flex; min-height: 200px; }
  #fnames { width: 300px; border-right: 1px solid var(--grid);
            padding: 6px 0; }
  #fvals { flex: 1; padding: 6px 0; }
  .frow { padding: 4px 12px; display: flex; justify-content: space-between;
          cursor: pointer; }
  .frow:hover { background: var(--accent-soft); }
  .frow .hits { color: var(--muted); font-size: 12px; }
  .fhead { padding: 4px 12px; color: var(--muted); font-size: 12px; }
</style>
</head>
<body>
<header>
  <h1>VictoriaLogs <span style="color:var(--accent)">TPU</span></h1>
  <span class="sub">LogsQL over columnar parts + device kernels</span>
</header>

<div id="bar">
  <textarea id="query" rows="1" spellcheck="false"
    placeholder="LogsQL query, e.g.  error _time:5m | stats by (app) count()">*</textarea>
  <button class="primary" id="run" title="Ctrl+Enter">Run</button>
  <button id="tailbtn" title="live tail">Tail</button>
</div>
<div id="opts">
  <label>Range <select id="range">
    <option value="300s">last 5m</option>
    <option value="3600s">last 1h</option>
    <option value="86400s" selected>last 24h</option>
    <option value="604800s">last 7d</option>
    <option value="2592000s">last 30d</option>
    <option value="custom">custom…</option>
  </select></label>
  <span id="custom" style="display:none">
    <input id="start" class="wide" placeholder="start (RFC3339/unix/1d)">
    <input id="end" class="wide" placeholder="end (RFC3339/unix/now)">
  </span>
  <label>Limit <input id="limit" value="1000" size="6"></label>
  <label>Tenant <input id="tenant" value="0:0" size="5"
         title="AccountID:ProjectID"></label>
</div>
<div id="status"></div>
<pre id="error"></pre>

<div class="panel">
  <h2 id="histtitle">Hits over time</h2>
  <div id="histwrap">
    <svg id="hist" preserveAspectRatio="none"></svg>
    <div id="tip"></div>
  </div>
</div>

<div id="tabs">
  <button data-tab="table" class="active">Table</button>
  <button data-tab="json">JSON</button>
  <button data-tab="fields">Fields</button>
</div>
<div id="out">
  <div id="tableview"></div>
  <pre id="json" style="display:none"></pre>
  <div id="fields" style="display:none">
    <div id="fnames"></div>
    <div id="fvals"><div class="fhead">click a field to list its values
      — click a value to add a filter</div></div>
  </div>
</div>

<script>
"use strict";
const $ = id => document.getElementById(id);
let rows = [], tailing = false, tailAbort = null;

function tenant() {
  const [a, p] = ($("tenant").value || "0:0").split(":");
  return {AccountID: a || "0", ProjectID: p || "0"};
}
function timeRange() {
  const sel = $("range").value;
  if (sel === "custom") {
    return {start: $("start").value || "1d", end: $("end").value || "now"};
  }
  return {start: sel, end: "now"};
}
function durSecs(v) {
  const m = /^(\d+(?:\.\d+)?)([smhdw])$/.exec(v || "");
  return m ? m[1] * {s: 1, m: 60, h: 3600, d: 86400, w: 604800}[m[2]]
           : null;
}
function rangeSecs() {
  const sel = $("range").value;
  if (sel !== "custom") return parseInt(sel, 10);
  const s = $("start").value || "1d", e = $("end").value || "now";
  const ds = durSecs(s);
  if (ds && (e === "now" || !e)) return ds;
  const t0 = Date.parse(s);
  const t1 = (e === "now" || !e) ? Date.now() : Date.parse(e);
  if (!isNaN(t0) && !isNaN(t1) && t1 > t0) return (t1 - t0) / 1000;
  return 86400;
}
function hitsStep() {
  // ~60 buckets across the selected range
  return Math.max(1, Math.round(rangeSecs() / 60)) + "s";
}
// split at the first TOP-LEVEL '|' (quoted strings, backtick regexes and
// parenthesized subqueries can all contain pipes)
function splitTopPipe(q) {
  let depth = 0, quote = null, escp = false;
  for (let i = 0; i < q.length; i++) {
    const c = q[i];
    if (escp) { escp = false; continue; }
    if (quote) {
      if (c === "\\" && quote === '"') escp = true;
      else if (c === quote) quote = null;
      continue;
    }
    if (c === '"' || c === "'" || c === "`") quote = c;
    else if (c === "(") depth++;
    else if (c === ")") depth = Math.max(0, depth - 1);
    else if (c === "|" && depth === 0) return [q.slice(0, i), q.slice(i)];
  }
  return [q, ""];
}
function filterPart() {
  const q = $("query").value.trim() || "*";
  return splitTopPipe(q)[0].trim() || "*";
}
function qs(params) {
  return Object.entries(params)
    .map(([k, v]) => `${k}=${encodeURIComponent(v)}`).join("&");
}
async function api(path, params) {
  const t = tenant();
  const resp = await fetch(`${path}?${qs(params)}`, {
    headers: {AccountID: t.AccountID, ProjectID: t.ProjectID}});
  if (!resp.ok) throw new Error(`${path}: HTTP ${resp.status}: ` +
                                await resp.text());
  return resp;
}
function setError(msg) {
  $("error").style.display = msg ? "block" : "none";
  $("error").textContent = msg || "";
}

// ---- query run ----
async function run() {
  stopTail();
  const q = $("query").value.trim() || "*";
  const {start, end} = timeRange();
  setError(""); rows = [];
  $("status").textContent = "running…";
  const t0 = performance.now();
  try {
    const resp = await api("/select/logsql/query",
                           {query: q, start, end, limit: $("limit").value});
    const text = await resp.text();
    rows = text.split("\n").filter(l => l.trim())
               .map(l => JSON.parse(l));
    const ms = Math.round(performance.now() - t0);
    $("status").textContent = `${rows.length} rows in ${ms}ms`;
    render();
    drawHits(q, start, end).catch(() => {});
    if (currentTab === "fields") loadFields();
  } catch (e) {
    $("status").textContent = "";
    setError(String(e.message || e));
  }
}

// ---- hits histogram (single series: titled by the panel, no legend) ----
let hitsData = [];
async function drawHits(q, start, end) {
  // hits wants the filter part only
  const filt = filterPart();
  const resp = await api("/select/logsql/hits",
                         {query: filt, start, end, step: hitsStep()});
  const data = await resp.json();
  const buckets = new Map();
  for (const h of (data.hits || [])) {
    (h.timestamps || []).forEach((ts, i) => {
      buckets.set(ts, (buckets.get(ts) || 0) + (h.values[i] || 0));
    });
  }
  hitsData = [...buckets.entries()].sort((a, b) => a[0] < b[0] ? -1 : 1);
  const svg = $("hist");
  svg.innerHTML = "";
  const W = svg.clientWidth || 800, H = 110, pad = 2;
  svg.setAttribute("viewBox", `0 0 ${W} ${H}`);
  if (!hitsData.length) {
    $("histtitle").textContent = "Hits over time — no data";
    return;
  }
  const max = Math.max(...hitsData.map(d => d[1]));
  $("histtitle").textContent =
    `Hits over time — ${hitsData.reduce((s, d) => s + d[1], 0)} total`;
  const slot = (W - pad * 2) / hitsData.length;
  const bw = Math.max(1, slot - 2);  // 2px surface gap between bars
  hitsData.forEach(([ts, v], i) => {
    const h = max ? Math.max(1, (H - 18) * v / max) : 1;
    const x = pad + i * slot;
    const r = document.createElementNS("http://www.w3.org/2000/svg",
                                       "rect");
    // thin mark, 4px rounded data end anchored to the baseline
    r.setAttribute("x", x); r.setAttribute("y", H - h);
    r.setAttribute("width", bw); r.setAttribute("height", h);
    r.setAttribute("rx", Math.min(4, bw / 2));
    r.setAttribute("fill", "var(--accent)");
    r.addEventListener("mousemove", ev => {
      const tip = $("tip");
      tip.style.display = "block";
      tip.textContent = `${ts} — ${v} hits`;
      const wrap = $("histwrap").getBoundingClientRect();
      tip.style.left = Math.min(ev.clientX - wrap.left + 12,
                                wrap.width - 200) + "px";
      tip.style.top = "8px";
    });
    r.addEventListener("mouseleave", () => {
      $("tip").style.display = "none";
    });
    svg.appendChild(r);
  });
}

// ---- table / json rendering ----
function columnsOf(rows) {
  const pri = ["_time", "_stream", "_msg"];
  const seen = new Set();
  for (const r of rows) Object.keys(r).forEach(k => seen.add(k));
  const rest = [...seen].filter(c => !pri.includes(c)).sort();
  return pri.filter(c => seen.has(c)).concat(rest);
}
function render() {
  const cols = columnsOf(rows);
  const tbl = document.createElement("table");
  const thead = document.createElement("thead");
  thead.innerHTML = "<tr>" + cols.map(c =>
    `<th>${esc(c)}</th>`).join("") + "</tr>";
  tbl.appendChild(thead);
  const tb = document.createElement("tbody");
  const maxRender = 2000;
  rows.slice(0, maxRender).forEach(r => tb.appendChild(rowTr(r, cols)));
  tbl.appendChild(tb);
  const tv = $("tableview");
  tv.innerHTML = "";
  if (rows.length > maxRender) {
    const note = document.createElement("div");
    note.className = "fhead";
    note.textContent =
      `showing first ${maxRender} of ${rows.length} rows`;
    tv.appendChild(note);
  }
  tv.appendChild(tbl);
  renderedCols = cols;
  renderJson();
}
let renderedCols = [];
function renderJson() {
  // the hidden pane re-serializes lazily (tab switch / next render)
  $("json").textContent = currentTab === "json"
    ? rows.slice(0, 2000).map(r => JSON.stringify(r)).join("\n") : "";
}
function rowTr(r, cols) {
  const tr = document.createElement("tr");
  tr.className = "row";
  tr.innerHTML = cols.map(c => {
    const v = r[c] === undefined ? "" : String(r[c]);
    const cls = c === "_msg" ? "msg" : "";
    const shown = v.length > 300 ? v.slice(0, 300) + "…" : v;
    return `<td class="${cls}">${esc(shown)}</td>`;
  }).join("");
  tr.addEventListener("click", () => {
    if (tr.nextSibling && tr.nextSibling.className === "detail") {
      tr.nextSibling.remove(); return;
    }
    const d = document.createElement("tr");
    d.className = "detail";
    d.innerHTML = `<td colspan="${cols.length}">` +
      esc(JSON.stringify(r, null, 2)) + "</td>";
    tr.after(d);
  });
  return tr;
}
function appendRows(added) {
  const tb = $("tableview").querySelector("tbody");
  if (!tb || added.some(r =>
      Object.keys(r).some(k => !renderedCols.includes(k)))) {
    render();  // no table yet, or a new column appeared
    return;
  }
  for (const r of added) tb.appendChild(rowTr(r, renderedCols));
  renderJson();
}
function esc(s) {
  return String(s).replace(/[&<>"]/g,
    c => ({"&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;"}[c]));
}

// ---- fields browser ----
async function loadFields() {
  const q = filterPart();
  const {start, end} = timeRange();
  try {
    const resp = await api("/select/logsql/field_names",
                           {query: q, start, end});
    const data = await resp.json();
    const box = $("fnames");
    box.innerHTML = '<div class="fhead">fields</div>';
    (data.values || []).forEach(f => {
      const d = document.createElement("div");
      d.className = "frow";
      d.innerHTML = `<span>${esc(f.value)}</span>` +
                    `<span class="hits">${esc(f.hits)}</span>`;
      d.addEventListener("click", () => loadValues(f.value));
      box.appendChild(d);
    });
  } catch (e) { setError(String(e.message || e)); }
}
async function loadValues(field) {
  const q = filterPart();
  const {start, end} = timeRange();
  try {
    const resp = await api("/select/logsql/field_values",
                           {query: q, field, start, end, limit: 50});
    const data = await resp.json();
    const box = $("fvals");
    box.innerHTML = `<div class="fhead">${esc(field)} — click to filter` +
                    `</div>`;
    (data.values || []).forEach(v => {
      const d = document.createElement("div");
      d.className = "frow";
      d.innerHTML = `<span>${esc(v.value) || "&lt;empty&gt;"}</span>` +
                    `<span class="hits">${esc(v.hits)}</span>`;
      d.addEventListener("click", () => {
        const qa = $("query");
        const [filt, pipes] = splitTopPipe(qa.value.trim() || "*");
        const base = filt.trim() === "*" ? "" : filt.trim();
        const fl = `${field}:=${JSON.stringify(v.value)}`;
        qa.value = (base ? `${base} ${fl}` : fl) +
                   (pipes ? ` ${pipes}` : "");
        run();
      });
      box.appendChild(d);
    });
  } catch (e) { setError(String(e.message || e)); }
}

// ---- live tail ----
async function startTail() {
  const q = filterPart();
  tailing = true;
  $("tailbtn").classList.add("on");
  $("status").textContent = "tailing…";
  rows = []; render();
  tailAbort = new AbortController();
  try {
    const t = tenant();
    const resp = await fetch(`/select/logsql/tail?${qs({query: q})}`, {
      headers: {AccountID: t.AccountID, ProjectID: t.ProjectID},
      signal: tailAbort.signal});
    if (!resp.ok) {
      throw new Error(`tail: HTTP ${resp.status}: ${await resp.text()}`);
    }
    const reader = resp.body.getReader();
    const dec = new TextDecoder();
    let buf = "";
    for (;;) {
      const {done, value} = await reader.read();
      if (done || !tailing) break;
      buf += dec.decode(value, {stream: true});
      const lines = buf.split("\n");
      buf = lines.pop();
      const added = [];
      for (const l of lines) {
        if (!l.trim()) continue;
        try { added.push(JSON.parse(l)); } catch (e) {}
      }
      if (!added.length) continue;
      rows.push(...added);
      if (rows.length > 1000) {
        rows = rows.slice(-1000);
        render();           // trimmed: rebuild once
      } else {
        appendRows(added);  // steady state: append only the new rows
      }
      $("status").textContent = `tailing… ${rows.length} rows`;
    }
  } catch (e) {
    if (tailing) setError(String(e.message || e));
  }
  stopTail();
}
function stopTail() {
  if (!tailing) return;
  tailing = false;
  $("tailbtn").classList.remove("on");
  if (tailAbort) tailAbort.abort();
}

// ---- wiring ----
let currentTab = "table";
document.querySelectorAll("#tabs button").forEach(b => {
  b.addEventListener("click", () => {
    currentTab = b.dataset.tab;
    document.querySelectorAll("#tabs button").forEach(x =>
      x.classList.toggle("active", x === b));
    $("tableview").style.display =
      currentTab === "table" ? "block" : "none";
    $("json").style.display = currentTab === "json" ? "block" : "none";
    $("fields").style.display = currentTab === "fields" ? "flex" : "none";
    if (currentTab === "fields") loadFields();
    if (currentTab === "json") renderJson();
  });
});
$("run").addEventListener("click", run);
$("tailbtn").addEventListener("click", () =>
  tailing ? stopTail() : startTail());
$("range").addEventListener("change", () => {
  $("custom").style.display =
    $("range").value === "custom" ? "inline" : "none";
});
$("query").addEventListener("keydown", e => {
  if (e.key === "Enter" && (e.ctrlKey || e.metaKey)) {
    e.preventDefault(); run();
  }
});
run();
</script>
</body>
</html>
"""
