"""Minimal embedded web UI (the vmui analogue, served at /select/vmui/).

The reference embeds a prebuilt React SPA (app/vlselect/main.go:71-74);
this is a self-contained single-file UI over the same HTTP API: LogsQL
query box, time range, hits histogram, streaming results table."""

VMUI_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>VictoriaLogs TPU</title>
<style>
  body { font-family: -apple-system, system-ui, sans-serif; margin: 0;
         background: #f7f7f9; color: #222; }
  header { background: #1a1a2e; color: #eee; padding: 10px 16px;
           display: flex; gap: 12px; align-items: center; }
  header h1 { font-size: 16px; margin: 0; font-weight: 600; }
  #bar { display: flex; gap: 8px; padding: 12px 16px; }
  #query { flex: 1; font: 14px monospace; padding: 8px; }
  select, button, input { font-size: 14px; padding: 8px; }
  button { background: #4361ee; color: white; border: 0;
           border-radius: 4px; cursor: pointer; }
  #hits { display: flex; align-items: flex-end; gap: 1px; height: 64px;
          padding: 0 16px; }
  #hits div { background: #4361ee; flex: 1; min-width: 2px; }
  #meta { padding: 4px 16px; color: #666; font-size: 12px; }
  table { border-collapse: collapse; margin: 8px 16px; font-size: 13px;
          width: calc(100% - 32px); }
  th, td { border: 1px solid #ddd; padding: 4px 8px; text-align: left;
           font-family: monospace; vertical-align: top;
           word-break: break-all; }
  th { background: #eaeaef; position: sticky; top: 0; }
  #err { color: #b00020; padding: 0 16px; white-space: pre-wrap; }
</style>
</head>
<body>
<header><h1>VictoriaLogs <small>tpu-native</small></h1></header>
<div id="bar">
  <input id="query" value="*" placeholder="LogsQL query, e.g. error | stats count()">
  <select id="range">
    <option value="5m">last 5m</option>
    <option value="1h">last 1h</option>
    <option value="24h" selected>last 24h</option>
    <option value="7d">last 7d</option>
    <option value="">all time</option>
  </select>
  <input id="limit" type="number" value="100" style="width:70px">
  <button onclick="run()">Run</button>
</div>
<div id="hits"></div>
<div id="meta"></div>
<div id="err"></div>
<table id="out"></table>
<script>
async function run() {
  const q = document.getElementById('query').value;
  const range = document.getElementById('range').value;
  const limit = document.getElementById('limit').value || 100;
  const errEl = document.getElementById('err');
  errEl.textContent = '';
  let params = new URLSearchParams({query: q, limit: limit});
  if (range) params.set('start', new Date(Date.now() -
      {m: 6e4, h: 36e5, d: 864e5}[range.slice(-1)] *
      parseInt(range)).toISOString());
  try {
    const hp = new URLSearchParams({query: q, step: '1h'});
    if (range) hp.set('start', params.get('start'));
    fetch('/select/logsql/hits?' + hp).then(r => r.json()).then(h => {
      const el = document.getElementById('hits');
      el.innerHTML = '';
      const vals = (h.hits || []).flatMap(g => g.values);
      const mx = Math.max(1, ...vals);
      vals.forEach(v => {
        const d = document.createElement('div');
        d.style.height = (v / mx * 100) + '%';
        d.title = v;
        el.appendChild(d);
      });
    }).catch(() => {});
    const t0 = performance.now();
    const resp = await fetch('/select/logsql/query?' + params);
    const text = await resp.text();
    if (!resp.ok) { errEl.textContent = text; return; }
    const rows = text.trim() ? text.trim().split('\\n').map(JSON.parse)
        : [];
    const cols = [];
    rows.forEach(r => Object.keys(r).forEach(k => {
      if (!cols.includes(k)) cols.push(k); }));
    const tbl = document.getElementById('out');
    tbl.innerHTML = '';
    const hr = tbl.insertRow();
    cols.forEach(c => { const th = document.createElement('th');
                        th.textContent = c; hr.appendChild(th); });
    rows.forEach(r => { const tr = tbl.insertRow();
      cols.forEach(c => { tr.insertCell().textContent = r[c] ?? ''; }); });
    document.getElementById('meta').textContent =
      rows.length + ' rows in ' +
      Math.round(performance.now() - t0) + 'ms';
  } catch (e) { errEl.textContent = String(e); }
}
document.getElementById('query').addEventListener('keydown',
  e => { if (e.key === 'Enter') run(); });
run();
</script>
</body>
</html>"""
