"""The victoria-logs single binary: HTTP server wiring insert + select +
storage.

Reference: app/victoria-logs/main.go (request routing insert->select->storage
— main.go:79-103), app/vlinsert/main.go:61-89 (ingest routes),
app/vlselect/main.go:212-274 (query routes), app/vlstorage/main.go:208-255
(/internal/force_merge, /internal/force_flush) and the /metrics surface
(main.go:354-410).
"""

from __future__ import annotations

import gzip
import io
import json
import os
import queue
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


from ..engine.searcher import QueryTimeoutError
from ..obs import activity, events, hist, ingestledger, journal
from ..storage.storage import Storage
from ..utils.memory import QueryMemoryError
from .. import sched
from .insertutil import (CommonParams, LocalLogRowsStorage,
                         LogMessageProcessor, get_tenant_id)
from . import netrobust, vlinsert
from .vlselect import (HTTPError, handle_explain, handle_facets,
                       handle_field_names, handle_field_values,
                       handle_hits, handle_query, handle_stats_query,
                       handle_stats_query_range,
                       handle_stream_field_names, handle_stream_field_values,
                       handle_stream_ids, handle_streams, handle_tail,
                       parse_common_args, query_timeout_s, want_explain)


def escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def metric_name(base: str, **labels) -> str:
    """`base{k="escaped v",...}` — the ONE place sample names with
    labels are built, so arbitrary request strings (paths, types) can
    never corrupt the exposition format."""
    if not labels:
        return base
    inner = ",".join(f'{k}="{escape_label_value(str(v))}"'
                     for k, v in sorted(labels.items()))
    return f"{base}{{{inner}}}"


# full sample name -> (base, "{labels}" or "")
_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?$")

# the canonical tenant spelling for ?tenant= filters (activity
# records label tenants "accountID:projectID"); the literal "other"
# is the registry's hard-cap overflow bucket — the label an operator
# most needs to drill into when tenant cardinality overflows
_TENANT_ARG_RE = re.compile(r"^(\d+:\d+|other)$")


def _tenant_arg(args):
    """Validated optional ?tenant= filter for the registry views:
    None when absent, the canonical "a:p" string (or the "other"
    overflow bucket) when well-formed, HTTP 400 otherwise (a malformed
    filter silently matching nothing would read as 'no queries')."""
    t = args.get("tenant", "")
    if not t:
        return None
    if not _TENANT_ARG_RE.match(t):
        raise HTTPError(400, f"invalid tenant arg {t!r} "
                             f"(want 'accountID:projectID')")
    return t


def _want_cluster(args) -> bool:
    return args.get("cluster", "") in ("1", "true", "yes")

# endpoints whose wall time IS a query execution (vl_query_duration_
# seconds); excludes /tail (connection lifetime) and introspection
_QUERY_DURATION_PATHS = frozenset((
    "/select/logsql/query", "/select/logsql/hits",
    "/select/logsql/facets", "/select/logsql/stats_query",
    "/select/logsql/stats_query_range"))


class Metrics:
    """Prometheus-text metrics registry.

    render() emits VALID exposition text: every metric gets exactly one
    `# TYPE` line with all its samples grouped directly under it,
    label values ride escape_label_value, duplicate sample names merge
    by summation (a registry counter colliding with a runner counter
    must not emit the same series twice), and the obs.hist histograms
    render with `# HELP`/`# TYPE histogram` + cumulative `le` buckets.
    tests/test_obs.py validates the output with a small parser."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, int] = {}

    def inc(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    @staticmethod
    def _split(name: str) -> tuple[str, str]:
        m = _SAMPLE_RE.match(name)
        if m is None:
            # defensive: a malformed stored name becomes a label so the
            # exposition stays parseable
            return "vl_invalid_metric_name", \
                "{name=\"" + escape_label_value(name) + "\"}"
        return m.group(1), m.group(2) or ""

    def render(self, storage: Storage, runner=None, server=None) -> str:
        # base name -> {labels_str -> value}; insertion-ordered so each
        # metric's samples stay contiguous under its TYPE line
        metrics: dict[str, dict[str, float]] = {}

        def add(name: str, v) -> None:
            base, labels = self._split(name)
            series = metrics.setdefault(base, {})
            series[labels] = series.get(labels, 0) + v

        with self._lock:
            for name in sorted(self.counters):
                add(name, self.counters[name])
        if runner is not None and hasattr(runner, "stats"):
            # device-runner counters incl. the async pipeline's
            # (dispatches issued, packed parts, in-flight high-water
            # mark, host-sync wait — tpu/batch.py BatchRunner.stats)
            for name, v in sorted(runner.stats().items()):
                add(f"vl_tpu_{name}", v)
        # filter-index host-plane budget occupancy (storage/filterbank)
        from ..storage.filterbank import bank_stats
        bs = bank_stats()
        add("vl_tpu_bloom_bank_used_bytes", bs["used_bytes"])
        add("vl_tpu_bloom_bank_max_bytes", bs["max_bytes"])
        # active-query registry: vl_active_queries by endpoint plus the
        # per-tenant select/ingest accounting the scheduler's admission
        # control consumes (obs/activity.py)
        for base, labels, v in activity.metrics_samples():
            add(metric_name(base, **labels), v)
        # scheduler surface: dispatch budget/in-flight gauges plus the
        # per-tenant admitted/shed counters and admission-queue depth
        # (victorialogs_tpu/sched)
        for base, labels, v in sched.metrics_samples():
            add(metric_name(base, **labels), v)
        # self-telemetry: event-bus totals + the previously-silent
        # truncation counters (obs/events.py) and the journal writer's
        # queue/drop/write accounting (obs/journal.py)
        for base, labels, v in events.metrics_samples():
            add(metric_name(base, **labels), v)
        for base, labels, v in journal.metrics_samples():
            add(metric_name(base, **labels), v)
        # ingest conservation ledger: per-tenant accepted/forwarded/
        # stored/dropped{reason} rolls, derived in-flight rows and the
        # freshness watermark age (obs/ingestledger.py)
        for base, labels, v in ingestledger.metrics_samples():
            add(metric_name(base, **labels), v)
        # cluster wire-protocol accounting: typed vs legacy frame
        # counts and raw tx/rx bytes (server/cluster.py; lazy import —
        # cluster pulls in the whole select stack)
        from . import cluster as _cluster
        for base, labels, v in _cluster.wire_metrics_samples():
            add(metric_name(base, **labels), v)
        # storage-node insert pipeline (VL_INSERT_PIPELINE hop overlap):
        # queued-batch depth + stored/dropped row totals
        for base, labels, v in _cluster.INSERT_PIPELINE.metrics_samples():
            add(metric_name(base, **labels), v)
        # typed ingest wire accounting: i1 vs legacy insert bodies by
        # direction + sticky fallbacks (server/wire_ingest.py)
        from . import wire_ingest as _wire_ingest
        for base, labels, v in _wire_ingest.metrics_samples():
            add(metric_name(base, **labels), v)
        # cluster fault-policy surface: per-node breaker health
        # (vl_node_health), retry/hedge/partial counters and the
        # ingest-spool accounting (server/netrobust.py)
        from . import netrobust as _netrobust
        for base, labels, v in _netrobust.metrics_samples():
            add(metric_name(base, **labels), v)
        # standing-query plane: per-part result-cache occupancy and
        # hit/miss/eviction accounting plus the resident standing
        # registrations and their re-evaluation totals
        # (engine/standing/)
        from ..engine.standing import resultcache as _resultcache
        from ..engine.standing import manager as _standing
        for base, labels, v in _resultcache.metrics_samples():
            add(metric_name(base, **labels), v)
        for base, labels, v in _standing.metrics_samples():
            add(metric_name(base, **labels), v)
        if server is not None and \
                hasattr(getattr(server, "sink", None),
                        "spool_metrics_samples"):
            for base, labels, v in server.sink.spool_metrics_samples():
                add(metric_name(base, **labels), v)
        if server is not None and \
                getattr(server, "clusterstats", None) is not None:
            # cluster frontends: per-tenant usage rolled up across
            # storage nodes + per-node rollup liveness/staleness
            # (obs/clusterstats.py poll loop)
            for base, labels, v in server.clusterstats.metrics_samples():
                add(metric_name(base, **labels), v)
        if server is not None:
            from .. import __version__
            add(metric_name("vl_build_info", version=__version__,
                            app="victorialogs-tpu"), 1)
            add("vl_uptime_seconds",
                round(time.monotonic() - server.start_time, 3))
        s = storage.update_stats()
        gauges = {
            "vl_partitions": s["partitions"],
            "vl_streams_created_total": s["streams"],
            metric_name("vl_storage_rows", type="inmemory"):
                s["inmemory_rows"],
            metric_name("vl_storage_rows", type="file"): s["file_rows"],
            metric_name("vl_storage_rows", type="small"):
                s["small_rows"],
            metric_name("vl_storage_rows", type="big"): s["big_rows"],
            metric_name("vl_storage_parts", type="inmemory"):
                s["inmemory_parts"],
            metric_name("vl_storage_parts", type="small"):
                s["small_parts"],
            metric_name("vl_storage_parts", type="big"): s["big_parts"],
            "vl_data_size_bytes": s["compressed_size"],
            "vl_uncompressed_data_size_bytes": s["uncompressed_size"],
            metric_name("vl_rows_dropped_total", reason="too_old"):
                s["rows_dropped_too_old"],
            metric_name("vl_rows_dropped_total", reason="too_new"):
                s["rows_dropped_too_new"],
            "vl_storage_is_read_only": int(s["is_read_only"]),
            # merge/flush health (storage/datadb.py stats): queued tier
            # compactions, total merges, staleness of in-RAM rows
            "vl_storage_pending_merges": s["pending_merges"],
            "vl_storage_merges_total": s["merges_done"],
            "vl_storage_flush_age_seconds":
                round(s["flush_age_seconds"], 3),
        }
        for name, v in gauges.items():
            add(name, v)

        out = []
        for base, series in metrics.items():
            kind = "counter" if base.endswith("_total") else "gauge"
            out.append(f"# TYPE {base} {kind}")
            for labels, v in series.items():
                # ints render exactly (byte budgets overflow %g), floats
                # compactly
                v_s = str(v) if isinstance(v, int) else format(v, ".9g")
                out.append(f"{base}{labels} {v_s}")
        out.extend(hist.render_all())
        return "\n".join(out) + "\n"


class BaseHTTPApp:
    """HTTP scaffolding shared by the single binary and vlagent: request
    decompression, routing dispatch, response helpers."""

    def _start_http(self, listen_addr: str, port: int) -> None:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args_):
                pass

            def do_GET(self):
                outer.dispatch(self, b"")

            def do_HEAD(self):
                outer.dispatch(self, b"")

            def do_POST(self):
                ln = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(ln) if ln else b""
                enc = (self.headers.get("Content-Encoding") or "").lower()
                try:
                    if enc == "gzip":
                        body = gzip.decompress(body)
                    elif enc == "zstd":
                        from ..utils import zstd as _zstd
                        body = _zstd.decompress(
                            body, max_output_size=1 << 30)
                    elif enc == "deflate":
                        import zlib
                        body = zlib.decompress(body)
                    elif enc == "snappy":
                        pass  # loki protobuf handles snappy itself
                # vlint: allow-broad-except(malformed body maps to 400)
                except Exception:
                    outer.respond(self, 400, "text/plain",
                                  b"cannot decompress request body")
                    return
                outer.dispatch(self, body)

            do_PUT = do_POST
            do_DELETE = do_GET

        self.httpd = ThreadingHTTPServer((listen_addr, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    # ---- helpers ----
    def respond(self, h, status: int, ctype: str, body: bytes,
                extra_headers: dict | None = None) -> None:
        try:
            h.send_response(status)
            h.send_header("Content-Type", ctype)
            for k, v in (extra_headers or {}).items():
                h.send_header(k, v)
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            if h.command != "HEAD":
                h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def respond_json(self, h, obj, status: int = 200,
                     extra_headers: dict | None = None) -> None:
        self.respond(h, status, "application/json",
                     json.dumps(obj, ensure_ascii=False).encode("utf-8"),
                     extra_headers=extra_headers)

    def respond_stream(self, h, gen, ctype="application/x-ndjson",
                       headers_fn=None) -> None:
        try:
            # headers_fn: extra response headers computed AFTER the
            # first chunk exists (a partial-results marker is only
            # known once the scatter-gather has made progress); pulling
            # the first chunk before the status line keeps headers
            # truthful whenever the failure precedes the first output
            # block — and ALWAYS for stats-shaped queries, whose single
            # output chunk follows the full gather
            it = iter(gen)
            first = next(it, None)
            extra = headers_fn() if headers_fn is not None else {}
            # error paths that fire after this point (e.g. a storage
            # node shedding mid-stream) must not write a second status
            # line into the chunked body — see respond_shed
            h._vl_streamed = True
            h.send_response(200)
            h.send_header("Content-Type", ctype)
            for k, v in extra.items():
                h.send_header(k, v)
            h.send_header("Transfer-Encoding", "chunked")
            h.end_headers()

            def chunks():
                if first is not None:
                    yield first
                yield from it

            for chunk in chunks():
                if not chunk:
                    continue
                data = chunk.encode("utf-8") if isinstance(chunk, str) \
                    else chunk
                h.wfile.write(f"{len(data):x}\r\n".encode())
                h.wfile.write(data)
                h.wfile.write(b"\r\n")
                h.wfile.flush()
            h.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass

    # ---- routing ----
    def dispatch(self, h, body: bytes) -> None:
        # per-request state: the handler object is reused across
        # keep-alive requests on one connection
        h._vl_streamed = False
        parsed = urllib.parse.urlparse(h.path)
        path = parsed.path
        args = {k: v[0] for k, v in
                urllib.parse.parse_qs(parsed.query).items()}
        ctype = (h.headers.get("Content-Type") or "").split(";")[0].strip()
        if h.command == "POST" and ctype in (
                "application/x-www-form-urlencoded",):
            for k, v in urllib.parse.parse_qs(
                    body.decode("utf-8", "replace")).items():
                args.setdefault(k, v[0])
        try:
            self.route(h, path, args, body, ctype)
        except HTTPError as e:
            self.metrics.inc("vl_http_errors_total")
            events.emit("http_error", path=path, status=e.status,
                        error=e.message)
            self.respond(h, e.status, "text/plain",
                         e.message.encode("utf-8"))
        except sched.AdmissionShed as e:
            # a storage node shed our sub-query (cluster.py surfaces
            # its 429 as AdmissionShed): propagate overload AS
            # overload, with the node's reason and Retry-After
            self.respond_shed(h, e)
        except QueryTimeoutError as e:
            self.metrics.inc("vl_http_errors_total")
            events.emit("http_error", path=path, status=503,
                        error=str(e))
            self.respond(h, 503, "text/plain", str(e).encode("utf-8"))
        except QueryMemoryError as e:
            self.metrics.inc("vl_http_errors_total")
            events.emit("http_error", path=path, status=422,
                        error=str(e))
            self.respond(h, 422, "text/plain", str(e).encode("utf-8"))
        except (BrokenPipeError, ConnectionResetError):
            pass
        # vlint: allow-broad-except(last-resort 500 handler, logged)
        except Exception as e:  # pragma: no cover
            import traceback
            traceback.print_exc()
            self.metrics.inc("vl_http_errors_total")
            events.emit("http_error", path=path, status=500,
                        error=f"{type(e).__name__}: {e}")
            self.respond(h, 500, "text/plain", str(e).encode("utf-8"))

    @staticmethod
    def _insert_proto(path: str) -> str:
        """Protocol label for one insert path (ingest counters).

        Deliberately a separate path->label table rather than
        per-branch strings: the parse-failure counter in
        handle_insert's except path needs the protocol before/without
        a branch body running.  A new insert endpoint must add its row
        here too, or its traffic lands as type="unknown"."""
        if path == "/insert/jsonline":
            return "jsonline"
        if path.endswith("/_bulk"):
            return "elasticsearch"
        if path.startswith("/insert/loki/"):
            return "loki"
        if path.startswith("/insert/opentelemetry/"):
            return "opentelemetry"
        if path.startswith("/insert/datadog/"):
            return "datadog"
        if path.startswith("/insert/journald/"):
            return "journald"
        return "unknown"

    def handle_insert(self, h, path, args, body, ctype) -> None:
        m = self.metrics
        cp = CommonParams.from_request(h.headers, args)
        lmp = LogMessageProcessor(cp, self.sink)
        proto = self._insert_proto(path)

        def count(n: int) -> None:
            # per-protocol rows + request bytes, per-tenant rows/bytes
            # (the registry side feeds vl_tenant_* on /metrics)
            m.inc(metric_name("vl_rows_ingested_total", type=proto), n)
            m.inc(metric_name("vl_ingest_bytes_total", type=proto),
                  len(body))
            activity.note_ingest(cp.tenant, n, nbytes=len(body))

        # the accept point: mint the batch_id that rides every hop
        # (sink ship, /internal/insert, spool replay) — the ingest twin
        # of activity.track.  Everything below, final flush included,
        # runs inside the batch extent so the sink's ledger rolls
        # attribute here; the extent's exit settles the batch state
        # (done / shipping / spooled).
        with ingestledger.begin_batch(cp.tenant, origin=proto):
            try:
                if path == "/insert/jsonline":
                    with ingestledger.hop("parse"):
                        n = vlinsert.handle_jsonline(cp, body, lmp)
                    count(n)
                elif path.endswith("/_bulk"):
                    with ingestledger.hop("parse"):
                        n, resp = vlinsert.handle_elasticsearch_bulk(
                            cp, body, lmp)
                    count(n)
                    lmp.flush()
                    self.respond_json(h, resp)
                    return
                elif path == "/insert/loki/api/v1/push":
                    with ingestledger.hop("parse"):
                        if ctype == "application/x-protobuf" or \
                                (body[:1] != b"{" and
                                 ctype != "application/json"):
                            n = vlinsert.handle_loki_protobuf(
                                cp, body, lmp)
                        else:
                            n = vlinsert.handle_loki_json(cp, body, lmp)
                    count(n)
                    lmp.flush()
                    self.respond(h, 204, "text/plain", b"")
                    return
                elif path == "/insert/opentelemetry/v1/logs":
                    with ingestledger.hop("parse"):
                        if ctype == "application/json":
                            n = vlinsert.handle_otlp_json(cp, body, lmp)
                        else:
                            n = vlinsert.handle_otlp_protobuf(
                                cp, body, lmp)
                    count(n)
                    lmp.flush()
                    self.respond_json(h, {"partialSuccess": {}})
                    return
                elif path in ("/insert/datadog/api/v2/logs",
                              "/insert/datadog/api/v1/input"):
                    with ingestledger.hop("parse"):
                        n = vlinsert.handle_datadog(cp, body, lmp)
                    count(n)
                    lmp.flush()
                    self.respond_json(h, {})
                    return
                elif path == "/insert/journald/upload":
                    with ingestledger.hop("parse"):
                        n = vlinsert.handle_journald(cp, body, lmp)
                    count(n)
                elif path.startswith("/insert/elasticsearch"):
                    # ES-compat discovery endpoints
                    self.respond_json(h, {"version": {"number": "8.9.0"}})
                    return
                else:
                    raise HTTPError(404, f"unknown insert path {path}")
            except vlinsert.IngestError as e:
                # parse failures land in the registry's per-protocol
                # counter (vl_ingest_parse_failures_total on /metrics)
                activity.note_parse_failure(proto)
                raise HTTPError(400, str(e))
            except netrobust.InsertRejectedError as e:
                # a storage node judged the forwarded batch malformed
                # (cluster 4xx): a client error end to end, never a
                # 500 — and never a breaker trip / re-route cascade
                # (cluster.py)
                raise HTTPError(400, str(e))
            try:
                # small batches reach the sink HERE (no size-triggered
                # mid-parse flush happened): same rejection mapping
                lmp.flush()
            except netrobust.InsertRejectedError as e:
                raise HTTPError(400, str(e))
            self.respond_json(h, {"status": "ok", "ingested": n})

    def respond_shed(self, h, e) -> None:
        """429 (or 499 for cancelled-while-queued) with Retry-After and
        the machine-readable reason body — the shed response contract
        (sched/admission.py)."""
        self.metrics.inc("vl_http_errors_total")
        if e.reason == "queue_full":
            # continuity with the pre-scheduler queue-timeout counter
            self.metrics.inc("vl_http_request_queue_timeouts_total")
        if getattr(h, "_vl_streamed", False):
            # the 200 chunked headers are already on the wire (a
            # storage node shed mid-stream): writing a 429 status line
            # now would corrupt the chunked body — cut the connection
            # so the client sees a truncated response, not garbage
            h.close_connection = True
            return
        obj = {"error": e.message, "reason": e.reason}
        limit = getattr(e, "limit", None)
        current = getattr(e, "current", None)
        if limit is not None:
            obj["limit"] = limit
        if current is not None:
            obj["current"] = current
        body = json.dumps(obj, ensure_ascii=False).encode("utf-8")
        try:
            h.send_response(e.status)
            h.send_header("Content-Type", "application/json")
            if e.retry_after is not None:
                h.send_header("Retry-After",
                              str(max(1, int(e.retry_after))))
            # adaptive-backoff hints (reference X-Concurrency style):
            # clients like vlagent scale their retry delay by how far
            # over capacity the server is, instead of sleeping the
            # fixed Retry-After (server/vlagent.py honors these)
            if limit is not None:
                h.send_header("X-VL-Concurrency-Limit", str(limit))
            if current is not None:
                h.send_header("X-VL-Concurrency-Current", str(current))
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            if h.command != "HEAD":
                h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    @staticmethod
    def _peer_gone(h):
        """A zero-cost probe for 'the HTTP peer hung up': readable
        socket + EOF on a peek.  Lets the admission queue drop entries
        whose client is gone before any device work starts (pipelined
        request bytes read as alive, which is correct)."""
        import select as _select
        import socket as _socket
        sock = h.connection

        def gone() -> bool:
            try:
                r, _w, _x = _select.select([sock], [], [], 0)
                if not r:
                    return False
                return sock.recv(1, _socket.MSG_PEEK) == b""
            except (OSError, ValueError):
                return True
        return gone

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


class VLServer(BaseHTTPApp):
    """Single-binary server instance (storage + HTTP)."""

    def __init__(self, storage: Storage, listen_addr: str = "127.0.0.1",
                 port: int = 0, runner=None, max_concurrent: int = 8,
                 max_queue_duration: float = 30.0,
                 storage_nodes: list | None = None):
        self.storage = storage
        self.metrics = Metrics()
        self.runner = runner
        self.start_time = time.monotonic()
        # admission control (sched/admission.py) replaces the old raw
        # FIFO semaphores: per-tenant concurrency/bytes limits, a
        # bounded wait queue, deadline-aware shedding.  Internal
        # (cluster) sub-queries get their own pool: a node acting as
        # both frontend and storage node must not have frontend queries
        # starve the sub-queries they themselves fan out.
        self.admission = sched.AdmissionController(
            max_concurrent=max_concurrent,
            queue_timeout_s=max_queue_duration, pool="select")
        self.internal_admission = sched.AdmissionController(
            max_concurrent=max_concurrent,
            queue_timeout_s=max_queue_duration, pool="internal")
        self.max_queue_duration = max_queue_duration
        if storage_nodes:
            # cluster mode: ingest shards to the nodes, queries
            # scatter-gather over them (reference -storageNode switch —
            # app/vlstorage/main.go:87-93).  The ingest spool lives
            # next to the frontend's own data so a frontend restart
            # replays whatever a node outage left behind.
            from .cluster import NetInsertStorage, NetSelectStorage
            self.sink = NetInsertStorage(
                storage_nodes,
                spool_dir=os.path.join(storage.path,
                                       "cluster-insert-spool"))
            self.query_storage = NetSelectStorage(storage_nodes)
            # cluster-wide tenant usage rollups: the frontend-owned
            # poll loop over every node's /internal/usage
            # (obs/clusterstats.py; VL_CLUSTER_STATS_MS=0 disables)
            from ..obs import clusterstats
            self.clusterstats = clusterstats.maybe_start(storage_nodes)
        else:
            self.sink = LocalLogRowsStorage(storage)
            self.query_storage = storage
            self.clusterstats = None
        # self-telemetry journal (obs/journal.py): the event bus's
        # subscriber, writing operational events through the NORMAL
        # ingest path (self.sink — local storage, or the cluster
        # sharder on a frontend) under the reserved system tenant.
        # VL_JOURNAL=0 returns None and leaves the bus subscriber-free
        # (emit() structurally zero-cost).  Never behind admission: the
        # journal must not be shed by the overload it records.
        self.journal = journal.maybe_start(self.sink)
        # standing-query registry (engine/standing/manager.py):
        # resident merged state per distinct query fingerprint,
        # re-evaluated on flush/merge bus events, deltas fanned out to
        # tail-style subscriber streams.  Evaluates against the SAME
        # storage facade interactive queries use (local storage, or the
        # scatter-gather view on a cluster frontend) and is priced
        # through the select admission pool like any tenant workload.
        from ..engine.standing import StandingRegistry
        self.standing = StandingRegistry(
            self.query_storage, runner=runner,
            admission=self.admission)
        try:
            self._start_http(listen_addr, port)
        except BaseException:
            # a failed bind must not leak the journal's bus
            # subscription + flush thread (nor the usage poll loop or
            # the standing registry's worker/bus subscription)
            self.standing.close()
            if self.journal is not None:
                self.journal.close()
            if self.clusterstats is not None:
                self.clusterstats.close()
            raise

    def route(self, h, path, args, body, ctype) -> None:
        m = self.metrics
        headers = h.headers
        # ---- health / misc (deliberately OUTSIDE the admission gate:
        # a server shedding 429s must still answer its liveness and
        # readiness probes, or the orchestrator kills exactly the node
        # that is correctly protecting itself) ----
        if path in ("/health", "/-/healthy", "/ping"):
            self.respond(h, 200, "text/plain", b"OK")
            return
        if path in ("/ready", "/-/ready", "/insert/ready"):
            # readiness = the storage accepts writes; a read-only
            # storage (disk limit) should be rotated out of ingest LBs
            if self.storage.is_read_only:
                self.respond(h, 503, "text/plain",
                             b"storage is read-only")
            else:
                self.respond(h, 200, "text/plain", b"OK")
            return
        if path == "/metrics":
            self.respond(h, 200, "text/plain",
                         m.render(self.storage, runner=self.runner,
                                  server=self).encode())
            return
        if path == "/":
            self.respond_json(h, {
                "app": "victorialogs-tpu",
                "uptime_seconds": round(time.monotonic() - self.start_time, 1)})
            return

        # ---- embedded web UI (reference vmui — vlselect/main.go:71-74) ----
        if path in ("/select/vmui", "/select/vmui/", "/vmui", "/vmui/"):
            from .vmui import VMUI_HTML
            self.respond(h, 200, "text/html; charset=utf-8",
                         VMUI_HTML.encode("utf-8"))
            return

        # ---- ingest observability (before the /insert/ prefix match,
        # and deliberately outside any admission gate: the spool/ledger
        # view matters most exactly when a storage node is down) ----
        if path == "/insert/status":
            payload = self._insert_status_payload()
            urls = self._cluster_urls()
            if _want_cluster(args) and urls:
                from . import cluster
                payload = cluster.federated_insert_status(urls, payload)
            self.respond_json(h, payload)
            return

        # ---- ingestion ----
        if path.startswith("/insert/"):
            self.handle_insert(h, path, args, body, ctype)
            return

        # ---- active-query registry (reference-parity introspection:
        # /select/logsql/active_queries + cancel/top — obs/activity.py).
        # Deliberately NOT behind the query semaphore: a saturated
        # server is exactly when operators need to see and kill queries.
        if path == "/select/logsql/active_queries":
            # queued-but-not-admitted queries show up here too (phase
            # "queued") — that is what makes them cancellable by qid —
            # alongside the live scheduler state (budget, in-flight
            # leases, admission pools).  ?tenant= scopes the view;
            # ?cluster=1 on a frontend federates it: every node's
            # sub-query records nested under their parent query here
            tenant = _tenant_arg(args)
            urls = self._cluster_urls()
            if _want_cluster(args) and urls:
                from . import cluster
                self.respond_json(h, cluster.federated_active_queries(
                    urls, tenant=tenant))
                return
            self.respond_json(h, {
                "status": "ok",
                "data": activity.active_snapshot(tenant=tenant),
                "scheduler": sched.snapshot()})
            return
        if path == "/select/logsql/sched_config":
            # mutating (per-tenant QoS knobs): POST only, same
            # discipline as cancel_query
            if h.command != "POST":
                raise HTTPError(405, "sched_config requires POST")
            tenant = args.get("tenant", "")
            if not tenant:
                raise HTTPError(400, "missing tenant arg")
            try:
                if "weight" in args:
                    sched.set_tenant_weight(tenant,
                                            float(args["weight"]))
                if "max_concurrent" in args:
                    self.admission.set_tenant_limit(
                        tenant, int(args["max_concurrent"]))
            except ValueError as e:
                raise HTTPError(400, f"invalid sched_config arg: {e}")
            self.respond_json(h, {
                "status": "ok", "tenant": tenant,
                "weight": sched.tenant_weight(tenant),
                "admission": self.admission.snapshot()})
            return
        if path == "/select/logsql/cancel_query":
            # destructive: POST only (a GET from a crawler/prefetcher
            # must never kill a live query)
            if h.command != "POST":
                raise HTTPError(405, "cancel_query requires POST")
            qid = args.get("qid", "")
            if not qid:
                raise HTTPError(400, "missing qid arg")
            if not activity.cancel(qid):
                raise HTTPError(404, f"no active query with qid {qid!r}")
            m.inc("vl_queries_cancelled_total")
            resp = {"status": "ok", "qid": qid}
            urls = self._cluster_urls()
            if urls:
                # cascading cancel: every node trips the sub-queries
                # registered under this query's global_qid, draining
                # their device windows NOW instead of at the next
                # disconnect-probe/frame-write detection (best-effort:
                # a dead node isn't running the sub-query anyway)
                from . import cluster
                resp["propagated"] = cluster.propagate_cancel(
                    urls, qid, activity.global_qid(qid))
            self.respond_json(h, resp)
            return
        if path == "/select/logsql/tenants":
            # cluster-wide per-tenant usage (the clusterstats rollup
            # cache — never an inline fan-out, so a hung node can't
            # hang this view); single-node servers serve their local
            # registry totals under the same shape
            tenant = _tenant_arg(args)
            cs = self.clusterstats
            if cs is not None:
                self.respond_json(h, cs.tenants_payload(tenant=tenant))
                return
            tenants = activity.usage_snapshot()["tenants"]
            if tenant is not None:
                tenants = {t: s for t, s in tenants.items()
                           if t == tenant}
            self.respond_json(h, {
                "status": "ok", "cluster": False,
                "tenants": {t: tenants[t] for t in sorted(tenants)}})
            return
        if path == "/select/logsql/top_queries":
            try:
                n = int(args.get("n") or args.get("limit") or "10")
            except ValueError:
                raise HTTPError(400, "invalid n arg")
            # validated + clamped: an unknown by= is a client error
            # (400 with the allowed set), never a silent fallthrough,
            # and n is bounded by the completed-ring capacity region
            n = max(1, min(n, 1000))
            tenant = _tenant_arg(args)
            by = args.get("by", "duration")
            urls = self._cluster_urls()
            if _want_cluster(args) and urls:
                from . import cluster
                try:
                    out = cluster.federated_top_queries(
                        urls, n, by=by, tenant=tenant)
                except ValueError as e:
                    raise HTTPError(400, str(e))
                self.respond_json(h, out)
                return
            try:
                top = activity.top_queries(n, by=by, tenant=tenant)
            except ValueError as e:
                raise HTTPError(400, str(e))
            self.respond_json(h, {"status": "ok", "top_queries": top})
            return

        if path == "/select/logsql/standing_query":
            # standing queries (engine/standing): NOT behind the
            # select gate itself — registration/introspection must work
            # on a shedding server, and the re-evaluations the registry
            # runs are individually priced through the SAME admission
            # pool (manager._reeval), so the workload is still
            # accounted per tenant
            self.handle_standing_query(h, path, args, headers)
            return

        # ---- queries (admission-controlled: per-tenant limits, a
        # bounded queue with deadline-aware shedding — sched/admission;
        # replaces the raw FIFO semaphore + -search.maxQueueDuration
        # timeout of the reference main.go:34-46) ----
        if path.startswith("/select/"):
            # register the record BEFORE admission: a queued query is
            # already visible in active_queries (phase "queued") and
            # cancellable by qid; the handler reuses this record via
            # activity.reuse_or_track, so counters stay one-per-query
            tenant = get_tenant_id(headers, args)
            with activity.track(path, args.get("query", ""),
                                tenant) as act:
                # resolve the partial-results mode HERE (explicit
                # ?partial arg over the VL_PARTIAL_RESULTS default) and
                # stamp it on the record: the scatter-gather reads it
                # ambiently on whatever thread runs the fan-out.  Only
                # stamped when it deviates from default-strict, so
                # ordinary query_done records stay unchanged.
                want_partial = netrobust.partial_requested(args)
                if want_partial or "partial" in args:
                    act.set("partial_ok", 1 if want_partial else -1)
                act.set_phase("queued")
                try:
                    with self.admission.admit(
                            tenant=act.tenant, endpoint=path,
                            deadline_s=query_timeout_s(args), act=act,
                            disconnected=self._peer_gone(h)):
                        act.set_phase("plan")
                        self.handle_select(h, path, args, headers)
                except sched.AdmissionShed as e:
                    self.respond_shed(h, e)
            return

        # ---- cluster-internal endpoints ----
        if path == "/internal/usage":
            # the cluster-stats poll target (obs/clusterstats.py):
            # per-tenant totals + live/queued depth + storage gauges.
            # Outside the admission gate — the rollup must keep seeing
            # a node that is shedding queries.
            usage = activity.usage_snapshot()
            adm_sel = self.admission.snapshot()
            adm_int = self.internal_admission.snapshot()
            s = self.storage.update_stats()
            usage.update({
                "status": "ok",
                "queued": adm_sel["queued"] + adm_int["queued"],
                "admission": {"select": adm_sel, "internal": adm_int},
                # per-tenant conservation totals: what the frontend's
                # clusterstats poll rolls up into the cluster-wide
                # zero-lost-rows view (obs/ingestledger.py)
                "ingest_ledger": ingestledger.usage_section(),
                "storage": {
                    "rows_small": s["small_rows"],
                    "rows_big": s["big_rows"],
                    "rows_inmemory": s["inmemory_rows"],
                    "pending_merges": s["pending_merges"],
                    "flush_age_seconds":
                        round(s["flush_age_seconds"], 3),
                    "is_read_only": bool(s["is_read_only"]),
                },
            })
            self.respond_json(h, usage)
            return
        if path == "/internal/select/cancel":
            # the cancel-propagation target: trip every sub-query
            # registered under the frontend query's global_qid (and/or
            # one node-local qid).  POST-only like cancel_query.
            if h.command != "POST":
                raise HTTPError(405, "cancel requires POST")
            parent_qid = args.get("parent_qid", "")
            qid = args.get("qid", "")
            if not parent_qid and not qid:
                raise HTTPError(400, "missing parent_qid or qid arg")
            n = activity.cancel_by_parent(parent_qid) \
                if parent_qid else 0
            if qid and activity.cancel(qid):
                n += 1
            if n:
                m.inc("vl_queries_cancel_propagated_total", n)
            self.respond_json(h, {"status": "ok", "cancelled": n})
            return
        if path == "/internal/insert":
            from . import cluster
            try:
                n = cluster.handle_internal_insert(self.storage, args, body)
            except ValueError as e:
                raise HTTPError(400, str(e))
            m.inc("vl_rows_ingested_total{type=\"internal\"}", n)
            self.respond_json(h, {"status": "ok", "ingested": n})
            return
        if path == "/internal/select/query":
            # same admission gate + shedding as /select/ — a storage
            # node hammered by N frontends must shed, not pile up
            # threads; the shed 429 carries the reason body the
            # frontend re-raises as AdmissionShed (cluster.py)
            from . import cluster
            tenant_lbl = (args.get("tenant") or "0:0").split(",")[0]
            try:
                with self.internal_admission.admit(
                        tenant=tenant_lbl, endpoint=path,
                        deadline_s=query_timeout_s(args),
                        disconnected=self._peer_gone(h)):
                    try:
                        gen = cluster.handle_internal_select(
                            self.storage, args, runner=self.runner)
                    except ValueError as e:
                        raise HTTPError(400, str(e))
                    self.respond_stream(h, gen,
                                        ctype="application/octet-stream")
            except sched.AdmissionShed as e:
                self.respond_shed(h, e)
            return

        # ---- profiling (reference exposes net/http/pprof; we expose the
        # Python-native equivalents — SURVEY §5 tracing/profiling) ----
        if path == "/debug/pprof/threads":
            import sys
            import traceback
            names = {t.ident: t.name for t in threading.enumerate()}
            out = []
            for tid, frame in sys._current_frames().items():
                out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
                out.extend(s.rstrip()
                           for s in traceback.format_stack(frame))
            self.respond(h, 200, "text/plain",
                         ("\n".join(out) + "\n").encode())
            return
        if path == "/debug/pprof/profile":
            # statistical sampler over every thread's stack (cProfile only
            # instruments its own thread, which here would just sleep)
            import sys
            import traceback
            seconds = min(float(args.get("seconds", "5")), 30.0)
            me = threading.get_ident()
            samples: dict[str, int] = {}
            n_samples = 0
            deadline = time.monotonic() + seconds
            while time.monotonic() < deadline:
                for tid, frame in sys._current_frames().items():
                    if tid == me:
                        continue
                    stack = traceback.extract_stack(frame)[-6:]
                    key = " <- ".join(
                        f"{f.name}({os.path.basename(f.filename)}:"
                        f"{f.lineno})" for f in reversed(stack))
                    samples[key] = samples.get(key, 0) + 1
                n_samples += 1
                time.sleep(0.01)
            out = [f"# {n_samples} samples over {seconds}s "
                   f"(count stack)"]
            for key, cnt in sorted(samples.items(),
                                   key=lambda kv: -kv[1])[:60]:
                out.append(f"{cnt}\t{key}")
            self.respond(h, 200, "text/plain",
                         ("\n".join(out) + "\n").encode())
            return

        # ---- storage maintenance ----
        if path == "/internal/force_merge":
            self.storage.must_force_merge(args.get("partition_prefix", ""))
            self.respond(h, 200, "text/plain", b"OK")
            return
        if path == "/internal/force_flush":
            self.storage.debug_flush()
            self.respond(h, 200, "text/plain", b"OK")
            return

        self.respond(h, 404, "text/plain",
                     f"unknown path {path}".encode())

    def close(self) -> None:
        # stop standing re-evaluations FIRST: they run queries against
        # the storage being torn down and emit journal events the
        # (still-alive) journal should record
        self.standing.close()
        # stop the usage poll loop (reads only; before the sink so a
        # mid-poll node error can't race the teardown)
        if self.clusterstats is not None:
            self.clusterstats.close()
        # drain the journal FIRST (its flush writes through self.sink)
        if self.journal is not None:
            self.journal.close()
        # then the sink (the cluster sharder owns the spool-replay
        # thread + per-node durable queues)
        sink_close = getattr(self.sink, "close", None)
        if sink_close is not None:
            sink_close()
        super().close()

    def _cluster_urls(self) -> list | None:
        """Storage-node URLs when this server is a cluster frontend
        (the federated registry/cancel/rollup fan-out set), else
        None."""
        return getattr(self.query_storage, "urls", None)

    def _insert_status_payload(self) -> dict:
        """This node's GET /insert/status body: the ledger's in-flight/
        recent batches, conservation counters, hop latencies and
        freshness watermarks, plus the durable-spool depth/age when the
        sink is the cluster sharder."""
        payload = ingestledger.status_payload()
        payload["status"] = "ok"
        spool_status = getattr(self.sink, "spool_status", None)
        if spool_status is not None:
            payload["spool"] = spool_status()
        return payload

    @staticmethod
    def _partial_headers() -> dict:
        """X-VL-Partial marker when the ambient query record shows the
        scatter-gather degraded to surviving nodes (cluster.py stamps
        partial_failed_nodes).  Evaluated AFTER the handler produced
        its payload (JSON endpoints) or its first chunk (streams)."""
        if activity.current_activity().counter("partial_failed_nodes"):
            return {"X-VL-Partial": "true"}
        return {}

    def handle_standing_query(self, h, path, args, headers) -> None:
        """/select/logsql/standing_query — GET lists registrations
        (?cluster=1 federates the view on a frontend); POST with
        ?unregister=1&fingerprint= tears one down (federated on a
        frontend); POST with ?query= registers (or joins) the standing
        evaluation and streams result deltas until the client goes
        away.  N dashboard panels asking the same query collapse to
        ONE resident evaluation per node."""
        from ..engine.standing.manager import StandingLimit
        reg = self.standing
        urls = self._cluster_urls()
        if h.command != "POST":
            # introspection: local registrations, or the cluster-wide
            # view (every node's registry + this frontend's own)
            if _want_cluster(args) and urls:
                from . import cluster
                self.respond_json(
                    h, cluster.federated_standing_queries(urls))
                return
            self.respond_json(h, {
                "status": "ok", "cluster": False,
                "standing_queries": reg.snapshot()})
            return
        if args.get("unregister", "") not in ("", "0"):
            fp = args.get("fingerprint", "")
            if not fp:
                raise HTTPError(400, "missing fingerprint arg")
            resp = {"status": "ok", "fingerprint": fp,
                    "removed": int(reg.unregister(fp))}
            if urls:
                # best-effort cascade, retry=False like cancel
                # propagation: an unregister that already landed must
                # not double-count on a transport blip
                from . import cluster
                resp["propagated"] = \
                    cluster.federated_standing_unregister(urls, fp)
            self.respond_json(h, resp)
            return
        # POST with a query: register (or join) + subscribe; the
        # response is a tail-style chunked NDJSON stream whose first
        # line carries the fingerprint (the unregister/introspection
        # handle), followed by one payload per changed re-evaluation
        q, tenants = parse_common_args(self.query_storage, args,
                                       headers)
        try:
            fp = reg.register(q, tenants,
                              parent_qid=args.get("parent_qid", ""))
        except StandingLimit as e:
            status = 503 if "VL_STANDING=0" in str(e) else 429
            self.respond(h, status, "text/plain",
                         (str(e) + "\n").encode())
            return
        sub = reg.attach_subscriber(fp)
        gone = self._peer_gone(h)
        with activity.reuse_or_track(path, q.to_string(),
                                     tenants[0]) as act:
            def gen():
                yield (json.dumps({"standing_fingerprint": fp})
                       + "\n").encode()
                while True:
                    if gone() or act.is_cancelled():
                        return
                    try:
                        payload = sub.get(timeout=1.0)
                    except queue.Empty:
                        # keep-alive tick: respond_stream drops empty
                        # chunks, so this only drives the gone() probe
                        yield b""
                        continue
                    if payload is None:
                        return  # unregistered underneath us
                    yield payload
            try:
                self.respond_stream(h, gen())
            finally:
                reg.detach_subscriber(fp, sub)

    def handle_select(self, h, path, args, headers) -> None:
        s = self.query_storage
        m = self.metrics
        m.inc(metric_name("vl_http_requests_total", path=path))
        t0 = time.monotonic()
        if path in _QUERY_DURATION_PATHS and want_explain(args):
            # ?explain=1 / ?explain=analyze: the priced physical plan
            # (JSON document, not a row stream) — vlselect.handle_explain
            self.respond_json(h, handle_explain(s, path, args, headers,
                                                runner=self.runner))
        elif path == "/select/logsql/query":
            gen = handle_query(s, args, headers, runner=self.runner)
            self.respond_stream(h, gen,
                                headers_fn=self._partial_headers)
        elif path == "/select/logsql/hits":
            self.respond_json(h, handle_hits(s, args, headers,
                                             runner=self.runner),
                              extra_headers=self._partial_headers())
        elif path == "/select/logsql/facets":
            self.respond_json(h, handle_facets(s, args, headers,
                                               runner=self.runner),
                              extra_headers=self._partial_headers())
        elif path == "/select/logsql/field_names":
            self.respond_json(h, handle_field_names(s, args, headers))
        elif path == "/select/logsql/field_values":
            self.respond_json(h, handle_field_values(s, args, headers))
        elif path == "/select/logsql/streams":
            self.respond_json(h, handle_streams(s, args, headers))
        elif path == "/select/logsql/stream_ids":
            self.respond_json(h, handle_stream_ids(s, args, headers))
        elif path == "/select/logsql/stream_field_names":
            self.respond_json(h, handle_stream_field_names(s, args,
                                                           headers))
        elif path == "/select/logsql/stream_field_values":
            self.respond_json(h, handle_stream_field_values(s, args,
                                                            headers))
        elif path == "/select/logsql/stats_query":
            self.respond_json(h, handle_stats_query(s, args, headers,
                                                    runner=self.runner),
                              extra_headers=self._partial_headers())
        elif path == "/select/logsql/stats_query_range":
            self.respond_json(h, handle_stats_query_range(
                s, args, headers, runner=self.runner),
                extra_headers=self._partial_headers())
        elif path == "/select/logsql/tail":
            stop = {"flag": False}
            # empty keep-alive chunks are never written (a zero-length
            # chunk would TERMINATE the chunked stream), so an idle
            # tail has no write to fail on when the client goes away —
            # probe the socket instead, or the tail (and its registry
            # record) lingers until the next matching row
            gone = self._peer_gone(h)

            def stop_check():
                return stop["flag"] or gone()
            gen = handle_tail(s, args, headers, stop_check=stop_check,
                              runner=self.runner)
            try:
                self.respond_stream(h, gen)
            finally:
                stop["flag"] = True
        else:
            raise HTTPError(404, f"unknown select path {path}")
        dt = time.monotonic() - t0
        m.inc(metric_name("vl_http_request_duration_ms_total", path=path),
              int(dt * 1000))
        if path in _QUERY_DURATION_PATHS:
            # only query EXECUTION endpoints: a /tail connection's
            # lifetime or a cheap introspection call would drown the
            # distribution the histogram exists to show
            hist.QUERY_DURATION.observe(dt)
