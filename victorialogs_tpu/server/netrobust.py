"""Cluster fault-policy layer: every HTTP hop between cluster processes
rides this module (enforced by the vlint ``net-discipline`` checker).

The scatter-gather front (server/cluster.py) used to treat the network
as either perfect or fatal: one attempt per node, a 10s ad-hoc disable
array on the insert path, no deadline on a node that accepts a
connection and then streams nothing.  This module centralizes the
production behaviors:

- **per-node circuit breaker** (:class:`CircuitBreaker`, shared by the
  select and insert paths through :func:`breaker_for`): closed /
  open / half-open with single-probe recovery, ``node_down`` /
  ``node_recovered`` journal events on the transitions, health as
  ``vl_node_health{node=}`` on /metrics.  Ingest 429s move the breaker
  into a *throttled* open (honoring ``Retry-After``) without a
  node_down event — overload is not death;
- **deadline-aware retries** (:func:`node_stream`): idempotent select
  sub-queries retry transport/5xx failures with jittered exponential
  backoff, never past the request deadline and never after a frame was
  already delivered downstream (a mid-stream replay would double-count
  rows).  ``vl_net_retries_total`` counts them; per-node attempt counts
  ride the ?trace=1 ``storage_node`` spans;
- **request hedging**: when a node's first frame lags its own
  p95-style RTT estimate (EWMA of mean + deviation, or a pinned
  ``VL_NET_HEDGE_MS``), the sub-query is re-issued to the same node and
  the first answer wins (``vl_net_hedges_total{won=}``).  Hedging
  targets the SAME node because shards are not replicated — the hedge
  beats a wedged worker/connection, not a dead machine;
- **per-read deadlines**: the frame reader runs on its own thread with
  socket timeouts re-derived per read, and the consuming side bounds
  every wait by the query deadline — a hung or trickling node costs at
  most the remaining budget, never the full transport timeout;
- **fault injection**: every attempt consults
  ``sched.netfaults.maybe_fail_net`` (``VL_FAULT_NET`` /
  ``inject_net_fault``), so chaos tests drive these paths without a
  wire.

Partial results (``?partial=1`` / ``VL_PARTIAL_RESULTS``) are decided
in cluster.py's gather; this module only supplies the policy helpers
(:func:`partial_requested`) and the failure taxonomy that makes "node
down" distinguishable from "query broken": :class:`NodeDownError`
(IOError — partial-eligible) vs :class:`NodeHTTPError` with a 4xx
status (the sub-query itself is bad — always strict).

Lock order: breaker and counter locks are leaves; journal events are
emitted outside them.
"""

from __future__ import annotations

import http.client
import queue
import random
import struct
import threading
import time
import urllib.parse

from ..obs import events, hist, tracing
from .. import config
from ..sched import netfaults
from ..utils import zstd as _zstd


# ---------------- knobs ----------------

def net_retries() -> int:
    """VL_NET_RETRIES: extra attempts per idempotent select sub-query
    after the first (0 disables retrying)."""
    return max(0, config.env_int("VL_NET_RETRIES"))


def breaker_failures() -> int:
    """VL_BREAKER_FAILURES: consecutive transport failures that open a
    node's circuit (>=1; default 2 so one transient blip retries
    without blacklisting the node)."""
    return max(1, config.env_int("VL_BREAKER_FAILURES"))


def breaker_open_s() -> float:
    """VL_BREAKER_OPEN_S: seconds an open circuit refuses requests
    before half-opening one probe (the old fixed 10s disable)."""
    return max(0.05, config.env_float("VL_BREAKER_OPEN_S"))


def spool_max_bytes() -> int:
    """VL_INSERT_SPOOL_MAX_BYTES: per-node durable ingest spool bound
    (0 disables spooling — the old drop-on-outage behavior)."""
    return config.env_int("VL_INSERT_SPOOL_MAX_BYTES")


def partial_default() -> bool:
    """VL_PARTIAL_RESULTS=1 turns partial results on for requests that
    do not carry an explicit ?partial arg."""
    return config.env_bool("VL_PARTIAL_RESULTS")


def partial_requested(args) -> bool:
    """Resolve one request's partial-results mode: explicit ?partial
    arg wins, else the VL_PARTIAL_RESULTS default (strict off)."""
    v = str(args.get("partial", "") or "")
    if v:
        return v in ("1", "true", "yes")
    return partial_default()


_RETRY_BACKOFF_BASE_S = 0.1
_RETRY_BACKOFF_MAX_S = 2.0
# minimum useful remaining budget for another attempt: retrying with
# less than this left only burns the deadline
_RETRY_FLOOR_S = 0.05


# ---------------- failure taxonomy ----------------

class NodeDownError(IOError):
    """A node-side availability failure: refused/reset connection,
    transport error, 5xx after retries, circuit open, or deadline
    exceeded waiting on the node.  The ONLY failure class eligible for
    ?partial=1 degradation — everything else means the query itself
    (or this process) is broken and must stay strict."""


class InsertRejectedError(ValueError):
    """A storage node REJECTED an ingest batch (HTTP 4xx other than
    429): the batch is malformed, not the node — surfaced to the
    caller (HTTP 400) without tripping the breaker, re-routing, or
    spooling (every node would reject it the same way)."""


class NodeHTTPError(Exception):
    """A complete non-200 HTTP response from a node (status, headers,
    body preserved for upstream mapping: 429 -> AdmissionShed with
    Retry-After, 5xx -> NodeDownError after retries).  Other 4xx mean
    the node is alive but rejected the sub-request (version/endpoint
    skew): no breaker trip, no retry, never partial-eligible — the
    query fails as an internal cluster error (HTTP 500 at the
    frontend, exactly like the legacy path's IOError)."""

    def __init__(self, url: str, status: int, headers, body: bytes):
        super().__init__(f"{url}: HTTP {status}")
        self.url = url
        self.status = status
        self.headers = headers if headers is not None else {}
        self.body = body or b""


def retry_after_s(headers, default: float = 1.0) -> float:
    try:
        return max(0.1, float(headers.get("Retry-After") or default))
    except (ValueError, AttributeError):
        return default


# ---------------- counters ----------------

_counts_mu = threading.Lock()
_counts: dict[str, int] = {}


def note(key: str, delta: int = 1) -> None:
    with _counts_mu:
        _counts[key] = _counts.get(key, 0) + delta


def counters() -> dict:
    with _counts_mu:
        return dict(_counts)


# ---------------- per-node circuit breaker ----------------

class CircuitBreaker:
    """One node's health state (shared select + insert; see module
    docstring).  All state under one leaf lock; journal events emitted
    outside it."""

    def __init__(self, url: str):
        self.url = url
        self._mu = threading.Lock()
        self._state = "closed"          # closed | open | half-open
        self._consec = 0
        self._open_until = 0.0
        self._probing = False
        self._probe_t0 = 0.0
        self._insert_throttle_until = 0.0
        self._down_emitted = False
        self._opened_total = 0
        self._failures_total = 0
        # first-frame RTT estimate for hedging: EWMA of mean and of
        # absolute deviation (a cheap p95-style bound: mean + 4*dev)
        self._rtt_mean = 0.0
        self._rtt_dev = 0.0
        self._rtt_n = 0

    # -- admission --
    def allow(self) -> bool:
        """May a request be sent to this node now?  In the half-open
        window exactly one probe is admitted; its outcome (on_success /
        on_failure) decides the next state.  A probe that can resolve
        neither way (caller abandoned the stream) must call
        abandon_probe(); a stale probe also self-expires after the
        open window, so a missed release can never wedge the node
        closed forever."""
        now = time.monotonic()
        with self._mu:
            if self._state == "closed":
                return True
            if now < self._open_until:
                return False
            if self._probing:
                if now - self._probe_t0 < max(breaker_open_s(), 5.0):
                    return False
                # stale probe: its owner vanished without resolving —
                # reclaim the slot rather than refusing forever
            self._state = "half-open"
            self._probing = True
            self._probe_t0 = now
            return True

    def allow_insert(self) -> bool:
        """The ingest-path gate: availability (allow) AND not inside a
        429 Retry-After window.  The throttle is insert-only — parking
        the shared breaker would fail SELECTS with 'node down' for the
        whole window, which node_stream's 429 policy deliberately
        avoids."""
        with self._mu:
            throttled = time.monotonic() < self._insert_throttle_until
        return not throttled and self.allow()

    def abandon_probe(self) -> None:
        """Release a probe slot whose outcome will never be known (the
        consumer closed the sub-query stream mid-probe).  No state
        change, no failure accounting; a no-op when the attempt
        already resolved via on_success/on_failure."""
        with self._mu:
            self._probing = False

    # -- outcome accounting --
    def on_success(self) -> None:
        with self._mu:
            self._probing = False
            recovered = self._down_emitted
            self._down_emitted = False
            self._state = "closed"
            self._consec = 0
            self._open_until = 0.0
        if recovered:
            note("nodes_recovered")
            events.emit("node_recovered", node=self.url)

    def on_failure(self) -> None:
        now = time.monotonic()
        with self._mu:
            was_half_open = self._state == "half-open"
            self._probing = False
            self._consec += 1
            self._failures_total += 1
            went_down = False
            if was_half_open or self._consec >= breaker_failures():
                self._state = "open"
                self._open_until = now + breaker_open_s()
                self._opened_total += 1
                if not self._down_emitted:
                    self._down_emitted = True
                    went_down = True
        if went_down:
            note("nodes_down")
            events.emit("node_down", node=self.url,
                        consecutive_failures=self._consec)

    def throttle(self, seconds: float) -> None:
        """The node shed an INSERT (429): park the ingest path for its
        advertised Retry-After without counting a failure, declaring
        the node down, or touching the select path (allow() is
        unaffected — see allow_insert)."""
        now = time.monotonic()
        with self._mu:
            self._probing = False
            self._insert_throttle_until = max(
                self._insert_throttle_until, now + max(0.1, seconds))

    # -- introspection --
    def health(self) -> float:
        """1.0 closed, 0.5 half-open (probe window), 0.0 open."""
        now = time.monotonic()
        with self._mu:
            if self._state == "closed":
                return 1.0
            if now < self._open_until:
                return 0.0
            return 0.5

    def state(self) -> str:
        now = time.monotonic()
        with self._mu:
            if self._state != "closed" and now >= self._open_until:
                return "half-open"
            return self._state

    def snapshot(self) -> dict:
        with self._mu:
            return {"node": self.url, "state": self._state,
                    "consecutive_failures": self._consec,
                    "opened_total": self._opened_total,
                    "failures_total": self._failures_total,
                    "rtt_ewma_s": round(self._rtt_mean, 6)}

    # -- hedging RTT estimate --
    def observe_rtt(self, dt: float) -> None:
        with self._mu:
            if self._rtt_n == 0:
                self._rtt_mean = dt
                self._rtt_dev = dt / 2
            else:
                self._rtt_dev = (0.8 * self._rtt_dev
                                 + 0.2 * abs(dt - self._rtt_mean))
                self._rtt_mean = 0.8 * self._rtt_mean + 0.2 * dt
            self._rtt_n += 1

    def hedge_delay_s(self) -> float | None:
        """Delay before re-issuing a straggler sub-query, or None when
        hedging is off.  VL_NET_HEDGE_MS pins it (0 = off); otherwise
        the EWMA estimate applies once >= 8 RTT samples exist."""
        env = config.env("VL_NET_HEDGE_MS") or ""
        if env:
            try:
                ms = float(env)
            except ValueError:
                return None
            return None if ms <= 0 else ms / 1000.0
        with self._mu:
            if self._rtt_n < 8:
                return None
            est = self._rtt_mean + 4.0 * self._rtt_dev
        return min(max(est, 0.05), 5.0)


_breakers_mu = threading.Lock()
_breakers: dict[str, CircuitBreaker] = {}


def breaker_for(url: str) -> CircuitBreaker:
    url = url.rstrip("/")
    with _breakers_mu:
        br = _breakers.get(url)
        if br is None:
            br = _breakers[url] = CircuitBreaker(url)
        return br


def breaker_snapshots() -> list[dict]:
    with _breakers_mu:
        brs = list(_breakers.values())
    return [br.snapshot() for br in brs]


def reset_for_tests() -> None:
    """Drop every breaker and counter (process-global state; tests
    that assert exact transitions/counts start clean)."""
    with _breakers_mu:
        _breakers.clear()
    with _counts_mu:
        _counts.clear()


def metrics_samples() -> list:
    """(base, labels, value) samples for server/app.py Metrics.render:
    per-node health gauges + the retry/hedge/partial/spool counters."""
    c = counters()
    out = [
        ("vl_net_retries_total", {}, c.get("retries", 0)),
        ("vl_net_hedges_total", {"won": "true"}, c.get("hedges_won", 0)),
        ("vl_net_hedges_total", {"won": "false"},
         c.get("hedges_lost", 0)),
        ("vl_partial_results_total", {}, c.get("partial_results", 0)),
        ("vl_insert_spooled_blocks_total", {}, c.get("spooled_blocks", 0)),
        ("vl_insert_replayed_blocks_total", {},
         c.get("replayed_blocks", 0)),
        ("vl_insert_spool_overflow_total", {},
         c.get("spool_overflow", 0)),
    ]
    with _breakers_mu:
        brs = list(_breakers.items())
    for url, br in brs:
        # vlint: allow-per-row-emit(metric samples, bounded by node count)
        out.append(("vl_node_health", {"node": url}, br.health()))
        snap = br.snapshot()
        # vlint: allow-per-row-emit(metric samples, bounded by node count)
        out.append(("vl_node_breaker_opens_total", {"node": url},
                    snap["opened_total"]))
    return out


# ---------------- one-shot requests (ingest / vlagent) ----------------

def request(url: str, path: str, body: bytes = b"", *,
            timeout: float = 30.0, deadline: float | None = None,
            headers: dict | None = None, method: str = "POST",
            gate: bool | str = True) -> tuple[int, object, bytes]:
    """One policy-managed HTTP exchange with a node: returns (status,
    headers, body) for ANY complete HTTP response; raises NodeDownError
    on circuit-open / refused / transport failure.  Breaker accounting
    happens here (5xx = failure, 429 = throttle via Retry-After,
    anything else = liveness success); callers classify the status.
    ``gate=True`` gates on the INSERT path (availability AND the 429
    Retry-After throttle); ``gate="select"`` gates on availability only
    (federated introspection / usage polls must not be parked by an
    ingest throttle); ``gate=False`` skips the circuit check (vlagent
    owns its own retry cadence) but still feeds the health state."""
    url = url.rstrip("/")
    br = breaker_for(url)
    if gate:
        allowed = br.allow() if gate == "select" else br.allow_insert()
        if not allowed:
            raise NodeDownError(f"{url}: node circuit open")
    try:
        mode = netfaults.maybe_fail_net(url)
        if mode == "refuse":
            br.on_failure()
            raise NodeDownError(f"{url}: injected net fault: refuse")
        if mode == "5xx":
            br.on_failure()
            return 503, {}, b"injected net fault: 5xx"
        u = urllib.parse.urlsplit(url)
        io_t = timeout
        if deadline is not None:
            io_t = min(io_t, max(deadline - time.monotonic(), 0.01))
        try:
            conn = http.client.HTTPConnection(u.hostname, u.port or 80,
                                              timeout=io_t)
            try:
                conn.request(method, path, body=body,
                             headers=headers or {})
                resp = conn.getresponse()
                status = resp.status
                rheaders = resp.headers
                rbody = resp.read()
            finally:
                conn.close()
        except (OSError, http.client.HTTPException) as e:
            br.on_failure()
            raise NodeDownError(
                f"{url}: {type(e).__name__}: {e}") from None
        if status >= 500:
            br.on_failure()
        elif status == 429:
            br.throttle(retry_after_s(rheaders))
            br.on_success()   # the node ANSWERED: alive, just shedding
        else:
            br.on_success()
        return status, rheaders, rbody
    finally:
        # a probe slot reserved by allow_insert() must never leak on
        # an unclassified exit path (no-op when already resolved)
        br.abandon_probe()


# ---------------- streaming sub-queries (select fan-out) ----------------

class _AttemptReader:
    """One HTTP attempt on its own thread: opens the connection, sends
    the request, and feeds frame payloads through a bounded queue.
    Events: ("frame", payload, wire_len) / ("end",) / ("http", status,
    headers, body) / ("err", exc).  ``abort()`` closes the connection
    from outside, which unblocks any pending socket read."""

    __slots__ = ("url", "path", "body", "headers", "io_timeout",
                 "deadline", "q", "t0", "conn", "_aborted")

    def __init__(self, url: str, path: str, body: bytes, headers: dict,
                 io_timeout: float, deadline: float | None):
        self.url = url
        self.path = path
        self.body = body
        self.headers = headers
        self.io_timeout = io_timeout
        self.deadline = deadline
        self.q: queue.Queue = queue.Queue(maxsize=8)
        self.t0 = time.monotonic()
        self.conn = None
        self._aborted = threading.Event()

    def start(self) -> None:
        threading.Thread(target=self._run, daemon=True).start()

    def abort(self) -> None:
        """Stop the reader: close the connection (wakes a blocked
        read) and drain the queue (wakes a blocked put)."""
        self._aborted.set()
        conn = self.conn
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        try:
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass

    def _put(self, item) -> bool:
        while not self._aborted.is_set():
            try:
                self.q.put(item, timeout=0.25)
                return True
            except queue.Full:
                continue
        return False

    def _read_timeout(self) -> float:
        t = self.io_timeout
        if self.deadline is not None:
            t = min(t, max(self.deadline - time.monotonic(), 0.01))
        return t

    def _read_exact(self, resp, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            sock = self.conn.sock
            if sock is not None:
                # per-READ deadline: a node that hangs or trickles
                # mid-frame times out at the query deadline, not at the
                # transport timeout
                sock.settimeout(self._read_timeout())
            chunk = resp.read(n - len(buf))
            if not chunk:
                raise IOError("truncated frame stream")
            buf += chunk
        return buf

    def _run(self) -> None:
        try:
            u = urllib.parse.urlsplit(self.url)
            conn = http.client.HTTPConnection(u.hostname, u.port or 80,
                                              timeout=self._read_timeout())
            self.conn = conn
            if self._aborted.is_set():
                conn.close()
                return
            conn.request("POST", self.path, body=self.body,
                         headers=self.headers)
            resp = conn.getresponse()
            if resp.status != 200:
                self._put(("http", resp.status, resp.headers,
                           resp.read(1 << 16)))
                return
            while True:
                hdr = self._read_exact(resp, 4)
                n = struct.unpack(">I", hdr)[0]
                if n == 0:
                    self._put(("end",))
                    return
                payload = self._read_exact(resp, n)
                data = _zstd.decompress(payload,
                                        max_output_size=1 << 30)
                if not self._put(("frame", data, n + 4)):
                    return
        # vlint: allow-broad-except(reader thread error channel: the consumer re-raises)
        except Exception as e:
            if not self._aborted.is_set():
                self._put(("err", e))


def _race(url: str, path: str, body: bytes, headers: dict,
          io_timeout: float, deadline: float | None,
          br: CircuitBreaker, span, allow_hedge: bool):
    """One attempt (plus an optional hedge to the same node): yields
    (payload, wire_len) frames from whichever connection answers
    first.  Raises NodeHTTPError / NodeDownError / the reader's
    transport error; the caller owns breaker classification and
    retries."""
    readers: list[_AttemptReader] = []
    try:
        primary = _AttemptReader(url, path, body, headers, io_timeout,
                                 deadline)
        primary.start()
        readers.append(primary)
        hedge_delay = br.hedge_delay_s() if allow_hedge else None
        hedge_at = None if hedge_delay is None else \
            primary.t0 + hedge_delay
        winner = None
        first_ev = None
        first_err = None
        alive = [primary]
        while winner is None:
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                raise NodeDownError(
                    f"{url}: deadline exceeded awaiting node response")
            if hedge_at is not None and len(readers) == 1 and \
                    now >= hedge_at and \
                    (deadline is None or deadline - now > 0.05):
                # the straggler case: re-issue to the SAME node and
                # race the two connections to the first frame
                h = _AttemptReader(url, path, body, headers,
                                   io_timeout, deadline)
                h.start()
                readers.append(h)
                alive.append(h)
                span.set("hedged", True)
            if not alive:
                raise first_err if first_err is not None else \
                    NodeDownError(f"{url}: no reply")
            wait = 0.25
            if deadline is not None:
                wait = min(wait, max(deadline - now, 0.001))
            if hedge_at is not None and len(readers) == 1:
                wait = min(wait, max(hedge_at - now, 0.001))
            if len(alive) == 1:
                try:
                    ev = alive[0].q.get(timeout=wait)
                except queue.Empty:
                    continue
                r = alive[0]
            else:
                # two live connections: poll both
                r = None
                for cand in list(alive):
                    try:
                        ev = cand.q.get_nowait()
                        r = cand
                        break
                    except queue.Empty:
                        continue
                if r is None:
                    time.sleep(0.005)
                    continue
            if ev[0] == "err":
                alive.remove(r)
                if first_err is None:
                    first_err = ev[1]
                continue
            winner = r
            first_ev = ev
        if len(readers) > 1:
            note("hedges_won" if winner is not readers[0]
                 else "hedges_lost")
            span.set("hedge_won", winner is not readers[0])
        for r in readers:
            if r is not winner:
                r.abort()
        ev = first_ev
        first_frame = True
        while True:
            kind = ev[0]
            if kind == "http":
                raise NodeHTTPError(url, ev[1], ev[2], ev[3])
            if kind == "end":
                if first_frame:
                    br.observe_rtt(time.monotonic() - winner.t0)
                br.on_success()
                return
            if kind == "err":
                raise ev[1]
            if first_frame:
                dt = time.monotonic() - winner.t0
                br.observe_rtt(dt)
                hist.NET_FIRST_FRAME.observe(dt)
                first_frame = False
            yield (ev[1], ev[2])
            while True:
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    raise NodeDownError(
                        f"{url}: deadline exceeded mid-stream")
                wait = 0.25
                if deadline is not None:
                    wait = min(wait, max(deadline - now, 0.001))
                try:
                    ev = winner.q.get(timeout=wait)
                    break
                except queue.Empty:
                    continue
    finally:
        for r in readers:
            r.abort()


def node_stream(url: str, path: str, body: bytes,
                headers: dict | None = None, *,
                io_timeout: float = 120.0,
                deadline: float | None = None,
                retries: int | None = None, idempotent: bool = True,
                hedge: bool = True, span=None):
    """Generator of (decompressed frame payload, wire length) from one
    node sub-query, with the full fault policy applied: circuit
    breaker, injected faults, jittered-backoff retries (idempotent
    requests, only before the first delivered frame, never past the
    deadline), hedging, per-read deadlines.  See the module
    docstring."""
    url = url.rstrip("/")
    if span is None:
        span = tracing.current_span()
    br = breaker_for(url)
    max_extra = net_retries() if retries is None else max(0, retries)
    attempt_no = 0
    backoff = _RETRY_BACKOFF_BASE_S
    delivered = False
    while True:
        attempt_no += 1
        span.set("net_attempts", attempt_no)
        if not br.allow():
            raise NodeDownError(f"{url}: node circuit open")
        err: Exception
        try:
            mode = netfaults.maybe_fail_net(url)
            if mode == "refuse":
                raise netfaults.InjectedNetFault(
                    f"{url}: injected net fault: refuse")
            if mode == "5xx":
                raise NodeHTTPError(url, 503, {},
                                    b"injected net fault: 5xx")
            for item in _race(url, path, body, headers or {},
                              io_timeout, deadline, br, span,
                              hedge and idempotent):
                delivered = True
                yield item
            return
        except NodeHTTPError as e:
            if e.status < 500:
                # the node ANSWERED: it is alive.  A 429 surfaces as a
                # shed (the frontend's 429 + Retry-After contract owns
                # the backoff — parking the breaker here would turn an
                # overload blip into fail-fast "node down" errors for
                # every later query); other 4xx mean the sub-query
                # itself is bad.  Neither retries, neither breaks.
                br.on_success()
                raise
            br.on_failure()
            err = NodeDownError(str(e))
        except (OSError, http.client.HTTPException) as e:
            br.on_failure()
            err = e if isinstance(e, NodeDownError) else \
                NodeDownError(f"{url}: {type(e).__name__}: {e}")
        finally:
            # GeneratorExit (consumer stopped pulling: early-done,
            # cancel, a sibling node failing in strict mode) and any
            # exception outside the classified set would otherwise
            # leave a half-open probe reserved forever — release it
            # (no-op when the attempt resolved via on_success/
            # on_failure above)
            br.abandon_probe()
        if delivered or not idempotent or attempt_no > max_extra:
            raise err
        delay = backoff * (0.5 + random.random())
        if deadline is not None and \
                time.monotonic() + delay + _RETRY_FLOOR_S >= deadline:
            raise err
        note("retries")
        span.add("net_retries")
        time.sleep(delay)
        backoff = min(backoff * 2, _RETRY_BACKOFF_MAX_S)
