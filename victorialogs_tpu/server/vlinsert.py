"""Ingestion protocol parsers: jsonline, Elasticsearch bulk, Loki, OTLP,
Datadog, journald.

Reference: app/vlinsert/* — each protocol is a parser feeding rows into a
LogMessageProcessor (SURVEY.md §2.4).  Syslog lives in syslog.py (it owns
TCP/UDP listeners).  All parsers return the number of ingested rows.
"""

from __future__ import annotations

import functools
import json
import time as _time
from .. import config

from ..storage.log_rows import (LogColumns, StreamID,
                                canonical_stream_tags)
from ..utils import protobuf as pb
from ..utils.hashing import stream_id_hash
from ..utils.snappy import SnappyError, decompress as snappy_decompress
from .insertutil import CommonParams, LogMessageProcessor, parse_timestamp


class IngestError(ValueError):
    pass


# Structural errors a malformed request body can provoke while a parser
# walks it.  Handlers translate these to IngestError so the HTTP layer
# answers 400, matching the reference's per-protocol parse-error paths
# (app/vlinsert/datadog/datadog.go, app/vlinsert/loki/loki_protobuf.go).
_PARSE_ERRORS = (pb.PBError, json.JSONDecodeError, UnicodeDecodeError,
                 KeyError, IndexError, TypeError, AttributeError,
                 OverflowError, ValueError, RecursionError)

# Exceptions raised from these modules are server-side faults, not body
# parse failures — the guard re-raises them so the HTTP layer answers 500
# with a traceback instead of blaming the client's payload.
_INTERNAL_MODULE_PREFIXES = ("victorialogs_tpu.storage",
                             "victorialogs_tpu.tpu",
                             "victorialogs_tpu.server.insertutil")


def _raised_internally(e: BaseException) -> bool:
    tb = e.__traceback__
    while tb is not None:
        mod = tb.tb_frame.f_globals.get("__name__", "")
        if mod.startswith(_INTERNAL_MODULE_PREFIXES):
            return True
        tb = tb.tb_next
    return False


def _ingest_guard(proto: str):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(cp, body, lmp, *a, **kw):
            try:
                return fn(cp, body, lmp, *a, **kw)
            except IngestError:
                raise
            except _PARSE_ERRORS as e:
                if _raised_internally(e):
                    raise
                if not isinstance(e, (json.JSONDecodeError, pb.PBError,
                                      UnicodeDecodeError, IngestError)):
                    # structural errors (TypeError/KeyError/...) can also
                    # be latent parser bugs — keep the traceback visible
                    # to operators while still answering 400
                    import traceback
                    traceback.print_exc()
                raise IngestError(
                    f"cannot parse {proto} request: "
                    f"{type(e).__name__}: {e}") from None
        return wrapper
    return deco


def _fields_from_json_obj(obj: dict, prefix: str = "") -> list:
    """Flatten a JSON object into (name, value) string fields the way the
    reference does (nested objects dot-joined, arrays/bools/numbers
    stringified — lib/logstorage/json_parser.go)."""
    out = []
    for k, v in obj.items():
        name = f"{prefix}{k}"
        if isinstance(v, str):
            out.append((name, v))
        elif isinstance(v, bool):
            out.append((name, "true" if v else "false"))
        elif isinstance(v, (int, float)):
            # vlint: allow-per-row-emit(ingest-side non-string value canonicalization)
            out.append((name, json.dumps(v)))
        elif v is None:
            continue
        elif isinstance(v, dict):
            out.extend(_fields_from_json_obj(v, prefix=f"{name}."))
        else:  # arrays stay JSON-encoded
            # vlint: allow-per-row-emit(ingest-side non-string value canonicalization)
            out.append((name, json.dumps(v, separators=(",", ":"))))
    return out


def _pop_time(cp: CommonParams, fields: list) -> tuple[int | None, list]:
    ts = None
    rest = []
    for k, v in fields:
        if k == cp.time_field and ts is None:
            ts = parse_timestamp(v)
        else:
            rest.append((k, v))
    return ts, rest


def _rename_msg(cp: CommonParams, fields: list) -> list:
    """First matching msg field becomes _msg."""
    for mf in cp.msg_fields:
        if mf == "_msg":
            return fields
        for i, (k, v) in enumerate(fields):
            if k == mf:
                out = [f for j, f in enumerate(fields) if j != i
                       and f[0] != "_msg"]
                out.append(("_msg", v))
                return out
    return fields


# ---------------- jsonline ----------------

class _SchemaPlan:
    """Per-schema (exact JSON key tuple) compilation of the row pipeline:
    time-field extraction (_pop_time), msg renaming (_rename_msg) and
    LogRows.add's _time-drop/dedupe/default-_msg — computed ONCE per
    schema instead of per row.  The plan maps raw json.loads value order
    to the final column layout; stream_pos indexes the stream fields
    inside that layout."""

    __slots__ = ("time_idx", "val_idx", "names", "msg_default",
                 "stream_pos", "stream_names")

    def __init__(self, cp: CommonParams, keys: tuple):
        time_idx = -1
        rest = []
        for i, k in enumerate(keys):
            if k == cp.time_field and time_idx < 0:
                time_idx = i
            else:
                rest.append((k, i))
        for mf in cp.msg_fields:
            if mf == "_msg":
                break
            hit = next((p for p, (k, _) in enumerate(rest) if k == mf),
                       None)
            if hit is not None:
                iv = rest[hit][1]
                rest = [kv for p, kv in enumerate(rest)
                        if p != hit and kv[0] != "_msg"]
                rest.append(("_msg", iv))
                break
        seen: set = set()
        clean = []
        has_msg = False
        for k, i in rest:
            if k == "_time":
                continue
            if k == "_msg":
                has_msg = True
            if k in seen:
                continue
            seen.add(k)
            clean.append((k, i))
        self.time_idx = time_idx
        self.msg_default = (not has_msg) and bool(cp.default_msg_value)
        names = [k for k, _ in clean]
        if self.msg_default:
            names.append("_msg")
        self.names = tuple(names)
        self.val_idx = tuple(i for _, i in clean)
        sf = set(cp.stream_fields)
        self.stream_pos = tuple(p for p, k in enumerate(self.names)
                                if k in sf)
        self.stream_names = tuple(self.names[p] for p in self.stream_pos)


_FAST_CHUNK_ROWS = 200_000


class _FastState:
    """Shared accumulation state for the fast jsonline path (columnar
    batch + per-request plan/stream/timestamp caches)."""

    __slots__ = ("cp", "lmp", "lc", "plans", "scache", "tcache", "n")

    def __init__(self, cp: CommonParams, lmp: LogMessageProcessor):
        self.cp = cp
        self.lmp = lmp
        self.lc = LogColumns()
        self.plans: dict = {}
        self.scache: dict = {}
        self.tcache: dict = {}
        self.n = 0


def _fast_fallback_obj(st: _FastState, obj: dict) -> None:
    """Per-row path for rows the columnar form can't express (nested
    objects, arrays, nulls).  Flushes accumulated columnar rows FIRST so
    arrival order is preserved around the fallback row."""
    if st.lc.nrows:
        st.lmp.ingest_columns(st.lc)
        st.lc = LogColumns()
    fields = _fields_from_json_obj(obj)
    ts, fields = _pop_time(st.cp, fields)
    fields = _rename_msg(st.cp, fields)
    st.lmp.add_row(ts, fields)
    st.n += 1


def _fast_add(st: _FastState, plan: _SchemaPlan, vals: list) -> None:
    """One stringified row -> the columnar batch.  vals holds ALL values
    in raw key order, already stringified exactly like the per-row path
    (numbers via json.dumps, bools as true/false)."""
    # the STRINGIFIED time value, exactly what _pop_time would parse on
    # the per-row path (bools become "true" -> None -> now)
    tval = vals[plan.time_idx] if plan.time_idx >= 0 else ""
    if tval:
        ts = st.tcache.get(tval)
        if ts is None:
            ts = parse_timestamp(tval)
            if ts is not None and len(st.tcache) < 65536:
                st.tcache[tval] = ts
    else:
        ts = None
    if ts is None:
        ts = _time.time_ns()
    out_vals = [vals[i] for i in plan.val_idx]
    if plan.msg_default:
        out_vals.append(st.cp.default_msg_value)
    skey = (plan.stream_names,
            tuple(out_vals[p] for p in plan.stream_pos))
    info = st.scache.get(skey)
    if info is None:
        pairs = [(plan.names[p], out_vals[p]) for p in plan.stream_pos]
        tags = canonical_stream_tags(pairs)
        hi, lo = stream_id_hash(tags.encode("utf-8"))
        info = st.scache[skey] = (StreamID(st.cp.tenant, hi, lo), tags)
    lc = st.lc
    g = lc.group(plan.names, plan.stream_pos)
    lc.add(g, st.cp.tenant, ts, out_vals, info[0], info[1])
    st.n += 1
    if lc.nrows >= _FAST_CHUNK_ROWS:
        st.lmp.ingest_columns(lc)
        st.lc = LogColumns()


def _scan_chunk_py(st: _FastState, text: str) -> None:
    """Python-parser chunk scan (no native lib, or native declined)."""
    for line in text.split("\n"):
        if line:
            _ingest_line(st, line)


def _ingest_line(st: _FastState, line) -> None:
    """Parse one JSON line with json.loads and ingest it: scalar rows
    stringify into the columnar batch, rows the columnar form can't
    express (nested objects, arrays, nulls) take the per-row fallback.
    Shared by the no-native chunk scan and the native scanner's flagged
    lines, so semantics and error behavior have exactly one home."""
    # explicit ASCII whitespace only (matches bytes.strip; str.strip
    # would also eat NBSP/U+2028 and silently accept lines the per-row
    # path rejects) — incl. \x0b/\x0c, which the C scanner's trim skips
    ws = " \t\n\r\x0b\x0c" if isinstance(line, str) \
        else b" \t\n\r\x0b\x0c"
    line = line.strip(ws)
    if not line:
        return
    try:
        obj = json.loads(line)
    except json.JSONDecodeError as e:
        raise IngestError(f"cannot parse JSON line: {e}") from None
    if not isinstance(obj, dict):
        raise IngestError("JSON line must be an object")
    vals = list(obj.values())
    ok = True
    for p, v in enumerate(vals):
        t = type(v)
        if t is str:
            continue
        if t is bool:
            vals[p] = "true" if v else "false"
        elif t is int or t is float:
            # vlint: allow-per-row-emit(ingest-side number canonicalization)
            vals[p] = json.dumps(v)
        else:
            ok = False    # nested object / array / null
            break
    if not ok:
        _fast_fallback_obj(st, obj)
        return
    keys = tuple(obj.keys())
    plan = st.plans.get(keys)
    if plan is None:
        plan = st.plans[keys] = _SchemaPlan(st.cp, keys)
    _fast_add(st, plan, vals)


_U32 = 1 << 32


def _vector_ts(tvals: list) -> list | None:
    """Vectorized unix-number timestamp parse for a whole column: exact
    parse_timestamp() int semantics (unit inference by magnitude) when
    every value is an int64 decimal; None -> caller parses per row."""
    import numpy as np
    try:
        ints = np.array(tvals, dtype=np.int64)
    except (ValueError, OverflowError):
        return None
    if (ints == 0).any():
        return None          # 0 means "now": per-row path handles it
    ns = np.where(
        ints < _U32, ints * 1_000_000_000,
        np.where(ints < _U32 * 1_000, ints * 1_000_000,
                 np.where(ints < _U32 * 1_000_000, ints * 1_000, ints)))
    return ns.tolist()


def _scan_chunk_native(st: _FastState, chunk: bytes, scan) -> None:
    """Consume one native vl_jsonline_scan result COLUMN-WISE: contiguous
    runs of non-flagged lines are grouped by schema signature, each
    group's columns materialize as one tight slice loop over the arena,
    timestamps parse vectorized, and the rows land via LogColumns.add_bulk
    — per-row Python work is a few list operations.  Flagged lines
    (nested values, nulls, duplicate keys, malformed JSON, lone
    surrogates) re-parse with json.loads in arrival order, so every
    divergence case keeps the exact semantics and error behavior of the
    per-row path."""
    import numpy as np
    arena, fields, lines, sigs, is_ascii = scan
    arena_s = arena.decode("utf-8") if is_ascii else None
    vo_np = fields[:, 2]
    ve_np = vo_np + fields[:, 3]
    kd_np = fields[:, 4]
    ko_np = fields[:, 0]
    ke_np = ko_np + fields[:, 1]
    fs_np = lines[:, 0]
    fl_np = lines[:, 2]
    M = lines.shape[0]
    dumps = json.dumps

    def col_values(fseg: "np.ndarray", jraw: int) -> list:
        idx = fseg + jraw
        kds = kd_np[idx]
        vos = vo_np[idx].tolist()
        ves = ve_np[idx].tolist()
        if int(kds.max(initial=0)) <= 1:     # strings / exact-int raw
            if arena_s is not None:
                return [arena_s[o:e] for o, e in zip(vos, ves)]
            return [arena[o:e].decode("utf-8")
                    for o, e in zip(vos, ves)]
        out = []
        for o, e, k in zip(vos, ves, kds.tolist()):
            if k <= 1:
                out.append(arena_s[o:e] if arena_s is not None
                           else arena[o:e].decode("utf-8"))
            elif k == 2:
                # vlint: allow-per-row-emit(float re-canonicalization, flagged values only)
                out.append(dumps(float(
                    arena_s[o:e] if arena_s is not None
                    else arena[o:e].decode("utf-8"))))
            elif k == 3:
                out.append("true")
            else:
                out.append("false")
        return out

    def segment(a: int, b: int) -> None:
        seg_sigs = sigs[a:b]
        seg_fs = fs_np[a:b]
        # one stable argsort groups schemas in O(M log M); within a
        # group, line order is preserved (stable sort of equal keys)
        order = np.argsort(seg_sigs, kind="stable")
        ssorted = seg_sigs[order]
        bounds = [0] + (np.nonzero(np.diff(ssorted))[0] + 1).tolist() \
            + [order.shape[0]]
        for gi in range(len(bounds) - 1):
            rows = order[bounds[gi]:bounds[gi + 1]]
            fseg = seg_fs[rows]
            li0 = a + int(rows[0])
            nfl = int(lines[li0, 1])
            pkey = (nfl, int(ssorted[bounds[gi]]))
            plan = st.plans.get(pkey)
            if plan is None:
                f0 = int(fs_np[li0])
                if arena_s is not None:
                    keys = tuple(arena_s[int(ko_np[f0 + j]):
                                         int(ke_np[f0 + j])]
                                 for j in range(nfl))
                else:
                    keys = tuple(
                        arena[int(ko_np[f0 + j]):
                              int(ke_np[f0 + j])].decode("utf-8")
                        for j in range(nfl))
                plan = st.plans[pkey] = _SchemaPlan(st.cp, keys)
            n = rows.shape[0]
            # output columns in plan order
            out_cols = [col_values(fseg, j) for j in plan.val_idx]
            if plan.msg_default:
                out_cols.append([st.cp.default_msg_value] * n)
            # timestamps
            if plan.time_idx >= 0:
                tvals = col_values(fseg, plan.time_idx)
                ts_list = _vector_ts(tvals)
                if ts_list is None:
                    tc = st.tcache
                    ts_list = []
                    ap = ts_list.append
                    for tv in tvals:
                        if tv:
                            ts = tc.get(tv)
                            if ts is None:
                                ts = parse_timestamp(tv)
                                if ts is not None and len(tc) < 65536:
                                    tc[tv] = ts
                        else:
                            ts = None
                        ap(ts if ts is not None else _time.time_ns())
            else:
                tns = _time.time_ns
                ts_list = [tns() for _ in range(n)]
            # stream identity per row: refs into the group's interned
            # stream table, cached under the RAW stream-value tuple —
            # one cheap str-tuple dict hit per row; the StreamID hash
            # and dataclass construction are paid once per unique
            # stream (intern_stream), not per row
            scache = st.scache
            snames = plan.stream_names
            lc = st.lc
            g = lc.group(plan.names, plan.stream_pos)
            kidx = g.key_idx
            if plan.stream_pos:
                scols = [out_cols[p] for p in plan.stream_pos]
                srefs = []
                ap = srefs.append
                for skv in zip(*scols):
                    si = kidx.get(skv)
                    if si is None:
                        info = scache.get((snames, skv))
                        if info is None:
                            pairs = list(zip(snames, skv))
                            tags = canonical_stream_tags(pairs)
                            hi, lo = stream_id_hash(
                                tags.encode("utf-8"))
                            info = scache[(snames, skv)] = \
                                (StreamID(st.cp.tenant, hi, lo), tags)
                        si = kidx[skv] = lc.intern_stream(
                            g, st.cp.tenant, info[0], info[1])
                    ap(si)
            else:
                si = kidx.get(())
                if si is None:
                    info = scache.get((snames, ()))
                    if info is None:
                        tags = canonical_stream_tags([])
                        hi, lo = stream_id_hash(tags.encode("utf-8"))
                        info = scache[(snames, ())] = \
                            (StreamID(st.cp.tenant, hi, lo), tags)
                    si = kidx[()] = lc.intern_stream(
                        g, st.cp.tenant, info[0], info[1])
                srefs = [si] * n
            lc.add_bulk_refs(g, ts_list, out_cols, srefs)
            st.n += n
            if lc.nrows >= _FAST_CHUNK_ROWS:
                st.lmp.ingest_columns(lc)
                st.lc = LogColumns()

    fb = np.nonzero(fl_np)[0].tolist()
    seg_start = 0
    for stop in fb + [M]:
        if stop > seg_start:
            segment(seg_start, stop)
        if stop < M:
            ro, rl = int(lines[stop, 3]), int(lines[stop, 4])
            _ingest_line(st, chunk[ro:ro + rl])
        seg_start = stop + 1


_NATIVE_CHUNK = 4 << 20   # scan buffer bound (fields/lines arrays)
# shard a single large body across VL_INGEST_THREADS workers past this
# size: the native scan (ctypes, GIL dropped) and the numpy/zstd encode
# both run truly parallel, the reference's per-CPU rowsBuffer shards
# (lib/logstorage/datadb.go:667-747) mapped onto request threads
_MT_MIN_BODY = 8 << 20


def _scan_span(st: _FastState, body: bytes, pos: int, end_all: int,
               use_native: bool) -> None:
    """Scan body[pos:end_all] (newline-aligned) in _NATIVE_CHUNK steps
    into st — the shared inner loop of the serial and sharded paths."""
    from .. import native
    while pos < end_all:
        end = min(pos + _NATIVE_CHUNK, end_all)
        if end < end_all:
            nl = body.rfind(b"\n", pos, end)
            end = nl + 1 if nl > pos else end_all
        chunk = body[pos:end]
        pos = end
        scan = native.jsonline_scan_native(chunk) if use_native else None
        if scan is None:
            _scan_chunk_py(st, chunk.decode("utf-8"))
        else:
            _scan_chunk_native(st, chunk, scan)


def _jsonline_fast(cp: CommonParams, body: bytes,
                   lmp: LogMessageProcessor) -> int:
    """Bulk columnar jsonline ingestion: the native strict-subset
    scanner (vl_jsonline_scan) tokenizes newline-aligned chunks into
    key/value spans over an unescape arena; rows map through per-schema
    plans straight into LogColumns batches.  Rows the columnar form
    can't express fall back to the per-row path line by line.

    Large bodies shard across VL_INGEST_THREADS workers (each with its
    own scan state and LogColumns batch; only the final sink append is
    lock-serialized).  Rows within a shard keep arrival order; shards
    interleave — same contract as concurrent client connections."""
    from .. import native
    try:
        # upfront validation for the whole body, exactly like the
        # per-line path's decode (errors must fire BEFORE any ingestion)
        text = body.decode("utf-8")
    except UnicodeDecodeError as e:
        raise IngestError(f"request body is not valid UTF-8: {e}") \
            from None
    if not native.available():
        st = _FastState(cp, lmp)
        _scan_chunk_py(st, text)     # one pass over the validated text
        lmp.ingest_columns(st.lc)
        return st.n
    del text
    blen = len(body)
    nthreads = config.env_int("VL_INGEST_THREADS")
    if nthreads > 1 and blen >= _MT_MIN_BODY:
        return _jsonline_fast_mt(cp, body, lmp, nthreads)
    st = _FastState(cp, lmp)
    _scan_span(st, body, 0, blen, True)
    lmp.ingest_columns(st.lc)
    return st.n


def _jsonline_fast_mt(cp: CommonParams, body: bytes,
                      lmp: LogMessageProcessor, nthreads: int) -> int:
    """Shard one body across worker threads at newline boundaries."""
    from concurrent.futures import ThreadPoolExecutor

    blen = len(body)
    bounds = [0]
    for k in range(1, nthreads):
        want = blen * k // nthreads
        nl = body.find(b"\n", want)
        cut = nl + 1 if nl >= 0 else blen
        bounds.append(max(cut, bounds[-1]))
    bounds.append(blen)
    spans = [(s, e) for s, e in zip(bounds[:-1], bounds[1:]) if s < e]
    states = [_FastState(cp, lmp) for _ in spans]
    # contextvars don't cross thread spawns: carry the ambient ingest
    # batch onto the workers so the sink's ledger rolls (accepted /
    # forwarded / stored) still attribute to this request's batch
    from ..obs import ingestledger
    batch = ingestledger.current_batch()

    def work(k: int) -> None:
        s, e = spans[k]
        st = states[k]
        with ingestledger.use_batch(batch):
            _scan_span(st, body, s, e, True)
            # hand the shard's batch to the sink ON the worker: the
            # sink's numpy block build / i1 encode / zstd all drop the
            # GIL, so shard K's sink work overlaps shard K+1's scan
            # instead of serializing on the request thread after the
            # barrier (ingest_columns is lock-serialized internally)
            lmp.ingest_columns(st.lc)
        st.lc = LogColumns()

    with ThreadPoolExecutor(max_workers=len(spans)) as pool:
        # surface the first worker error (e.g. IngestError) to the caller
        for fut in [pool.submit(work, k) for k in range(len(spans))]:
            fut.result()
    return sum(st.n for st in states)


@_ingest_guard("jsonline")
def handle_jsonline(cp: CommonParams, body: bytes,
                    lmp: LogMessageProcessor) -> int:
    if not cp.ignore_fields and not cp.extra_fields and \
            lmp.supports_columns():
        return _jsonline_fast(cp, body, lmp)
    n = 0
    for line in body.split(b"\n"):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            raise IngestError(f"cannot parse JSON line: {e}") from None
        if not isinstance(obj, dict):
            raise IngestError("JSON line must be an object")
        fields = _fields_from_json_obj(obj)
        ts, fields = _pop_time(cp, fields)
        fields = _rename_msg(cp, fields)
        lmp.add_row(ts, fields)
        n += 1
    return n


# ---------------- elasticsearch bulk ----------------

@_ingest_guard("Elasticsearch bulk")
def handle_elasticsearch_bulk(cp: CommonParams, body: bytes,
                              lmp: LogMessageProcessor) -> tuple[int, dict]:
    lines = body.split(b"\n")
    n = 0
    i = 0
    while i < len(lines):
        action_line = lines[i].strip()
        i += 1
        if not action_line:
            continue
        try:
            action = json.loads(action_line)
        except json.JSONDecodeError:
            raise IngestError("invalid bulk action line") from None
        op = next(iter(action), "")
        if op not in ("create", "index"):
            continue  # delete/update are ignored for logs
        if i >= len(lines):
            break
        doc_line = lines[i].strip()
        i += 1
        if not doc_line:
            continue
        try:
            obj = json.loads(doc_line)
        except json.JSONDecodeError:
            raise IngestError("invalid bulk document line") from None
        fields = _fields_from_json_obj(obj)
        # ES convention: @timestamp, message
        ts = None
        rest = []
        for k, v in fields:
            if ts is None and k in ("@timestamp", "timestamp",
                                    cp.time_field):
                ts = parse_timestamp(v)
            else:
                rest.append((k, v))
        out = []
        for k, v in rest:
            out.append(("_msg", v) if k in ("message", "msg") and
                       not any(x[0] == "_msg" for x in rest) else (k, v))
        out = _rename_msg(cp, out)
        lmp.add_row(ts, out)
        n += 1
    resp = {"took": 0, "errors": False,
            "items": [{"create": {"status": 201}}] * n}
    return n, resp


# ---------------- loki ----------------

def _protocol_stream_bulk(lmp: LogMessageProcessor, cp: CommonParams,
                          labels: list, ts_list: list,
                          lines: list) -> None:
    """Columnar bulk add for protocol streams (Loki): many (ts, line)
    entries sharing one label set.  Replicates LogMessageProcessor.
    add_row(..., stream_fields=labels) + LogRows.add semantics: labels
    become row fields (keep-first dedupe, '_time' keys dropped), the
    line is '_msg', and the stream identity is the label pairs that
    survived cleaning."""
    seen: set = set()
    clean: list = []
    for k, v in labels:
        if k == "_time" or k in seen:
            continue
        seen.add(k)
        clean.append((k, v))
    if "_msg" not in seen:
        clean.append(("_msg", None))     # per-row line slot
    names = tuple(k for k, _ in clean)
    label_names = {k for k, _ in labels}
    stream_pairs = [(k, v) for k, v in clean
                    if k in label_names and v is not None]
    stream_pos = tuple(p for p, (k, v) in enumerate(clean)
                       if k in label_names and v is not None)
    tags = canonical_stream_tags(stream_pairs)
    hi, lo = stream_id_hash(tags.encode("utf-8"))
    sid = StreamID(cp.tenant, hi, lo)
    n = len(ts_list)
    cols = [lines if v is None else [v] * n for _k, v in clean]
    lc = LogColumns()
    g = lc.group(names, stream_pos)
    lc.add_bulk(g, cp.tenant, ts_list, cols, [sid] * n, [tags] * n)
    lmp.ingest_columns(lc)


@_ingest_guard("Loki JSON")
def handle_loki_json(cp: CommonParams, body: bytes,
                     lmp: LogMessageProcessor) -> int:
    try:
        obj = json.loads(body)
    except json.JSONDecodeError as e:
        raise IngestError(f"cannot parse Loki JSON: {e}") from None
    n = 0
    bulk_ok = not cp.ignore_fields and not cp.extra_fields and \
        lmp.supports_columns()
    for stream in obj.get("streams", []):
        labels = stream.get("stream", {})
        stream_fields = [(str(k), str(v)) for k, v in labels.items()]
        ts_bulk: list = []
        ln_bulk: list = []
        for entry in stream.get("values", []):
            if len(entry) < 2 or not isinstance(entry[1], str):
                raise IngestError(
                    "Loki values entry must be [ts, line] with a string "
                    "line")
            ts = parse_timestamp(int(entry[0])) if str(entry[0]).isdigit() \
                else parse_timestamp(entry[0])
            attrs = entry[2] if len(entry) > 2 and \
                isinstance(entry[2], dict) else None
            if bulk_ok and not attrs and ts is not None and \
                    isinstance(entry[1], str):
                ts_bulk.append(ts)
                ln_bulk.append(entry[1])
                n += 1
                continue
            if ts_bulk:
                # keep arrival order around per-row entries (same
                # discipline as _fast_fallback_obj)
                _protocol_stream_bulk(lmp, cp, stream_fields, ts_bulk,
                                      ln_bulk)
                ts_bulk, ln_bulk = [], []
            fields = [("_msg", entry[1])]
            if attrs:
                fields.extend((str(k), str(v)) for k, v in attrs.items())
            lmp.add_row(ts, fields, stream_fields=stream_fields)
            n += 1
        if ts_bulk:
            _protocol_stream_bulk(lmp, cp, stream_fields, ts_bulk,
                                  ln_bulk)
    return n


def _parse_loki_labels(s: str) -> list:
    """Parse Loki's `{a="b", c="d"}` label string."""
    from ..storage.stream_filter import parse_stream_tags
    return sorted(parse_stream_tags(s).items())


@_ingest_guard("Loki protobuf")
def handle_loki_protobuf(cp: CommonParams, body: bytes,
                         lmp: LogMessageProcessor) -> int:
    try:
        raw = snappy_decompress(body)
    except SnappyError as e:
        raise IngestError(f"cannot snappy-decompress Loki push: {e}") \
            from None
    n = 0
    for fnum, _wt, val in pb.iter_fields(raw):
        if fnum != 1:
            continue
        labels = []
        entries = []
        for f2, _w2, v2 in pb.iter_fields(val):
            if f2 == 1:
                labels = _parse_loki_labels(v2.decode("utf-8", "replace"))
            elif f2 == 2:
                entries.append(v2)
        bulk_ok = not cp.ignore_fields and not cp.extra_fields and \
            lmp.supports_columns()
        ts_bulk: list = []
        ln_bulk: list = []
        for ent in entries:
            ts_ns = None
            line = ""
            attrs = []
            for f3, _w3, v3 in pb.iter_fields(ent):
                if f3 == 1:  # Timestamp{seconds=1, nanos=2}
                    secs = nanos = 0
                    for f4, _w4, v4 in pb.iter_fields(v3):
                        if f4 == 1:
                            secs = v4
                        elif f4 == 2:
                            nanos = v4
                    ts_ns = secs * 1_000_000_000 + nanos
                elif f3 == 2:
                    line = v3.decode("utf-8", "replace")
                elif f3 == 3:  # structured metadata LabelPairAdapter
                    k = v = ""
                    for f4, _w4, v4 in pb.iter_fields(v3):
                        if f4 == 1:
                            k = v4.decode("utf-8", "replace")
                        elif f4 == 2:
                            v = v4.decode("utf-8", "replace")
                    if k:
                        attrs.append((k, v))
            if bulk_ok and not attrs and ts_ns is not None:
                ts_bulk.append(ts_ns)
                ln_bulk.append(line)
                n += 1
                continue
            if ts_bulk:
                # keep arrival order around per-row entries
                _protocol_stream_bulk(lmp, cp, labels, ts_bulk, ln_bulk)
                ts_bulk, ln_bulk = [], []
            lmp.add_row(ts_ns, [("_msg", line)] + attrs,
                        stream_fields=labels)
            n += 1
        if ts_bulk:
            _protocol_stream_bulk(lmp, cp, labels, ts_bulk, ln_bulk)
    return n


# ---------------- OTLP logs ----------------

def _otlp_any_value(buf: bytes) -> str:
    for fnum, wt, val in pb.iter_fields(buf):
        if fnum == 1:
            return val.decode("utf-8", "replace")
        if fnum == 2:
            return "true" if val else "false"
        if fnum == 3:  # int64 varint (two's complement for negatives)
            return str(val - (1 << 64) if val >= (1 << 63) else val)
        if fnum == 4:
            return repr(pb.fixed64_f(val))
        if fnum == 5:  # array
            vals = [_otlp_any_value(v) for f, _w, v in pb.iter_fields(val)
                    if f == 1]
            # vlint: allow-per-row-emit(OTLP any-value array canonicalization)
            return json.dumps(vals, separators=(",", ":"))
        if fnum == 6:  # kvlist
            obj = {}
            for f, _w, v in pb.iter_fields(val):
                if f == 1:
                    k, vv = _otlp_kv(v)
                    obj[k] = vv
            # vlint: allow-per-row-emit(OTLP kvlist canonicalization)
            return json.dumps(obj, separators=(",", ":"))
        if fnum == 7:
            return val.hex()
    return ""


def _otlp_kv(buf: bytes) -> tuple[str, str]:
    k = v = ""
    for fnum, _wt, val in pb.iter_fields(buf):
        if fnum == 1:
            k = val.decode("utf-8", "replace")
        elif fnum == 2:
            v = _otlp_any_value(val)
    return k, v


_OTLP_SEVERITIES = {
    1: "TRACE", 5: "DEBUG", 9: "INFO", 13: "WARN", 17: "ERROR", 21: "FATAL",
}


def _otlp_severity(num: int) -> str:
    base = ((num - 1) // 4) * 4 + 1 if num >= 1 else 0
    name = _OTLP_SEVERITIES.get(base, "")
    if not name:
        return str(num)
    off = num - base
    return name + (str(off + 1) if off else "")


@_ingest_guard("OTLP protobuf")
def handle_otlp_protobuf(cp: CommonParams, body: bytes,
                         lmp: LogMessageProcessor) -> int:
    n = 0
    for f1, _w, rl in pb.iter_fields(body):
        if f1 != 1:  # resource_logs
            continue
        resource_attrs = []
        scope_bufs = []
        for f2, _w2, v2 in pb.iter_fields(rl):
            if f2 == 1:  # Resource{attributes=1}
                for f3, _w3, v3 in pb.iter_fields(v2):
                    if f3 == 1:
                        resource_attrs.append(_otlp_kv(v3))
            elif f2 == 2:
                scope_bufs.append(v2)
        for sl in scope_bufs:
            for f3, _w3, lr_buf in pb.iter_fields(sl):
                if f3 != 2:  # log_records
                    continue
                ts = None
                sev_text = ""
                sev_num = 0
                body_s = ""
                attrs = []
                for f4, w4, v4 in pb.iter_fields(lr_buf):
                    if f4 == 1:
                        ts = pb.fixed64_u(v4)
                    elif f4 == 2:
                        sev_num = v4
                    elif f4 == 3:
                        sev_text = v4.decode("utf-8", "replace")
                    elif f4 == 5:
                        body_s = _otlp_any_value(v4)
                    elif f4 == 6:
                        attrs.append(_otlp_kv(v4))
                    elif f4 == 11 and ts is None:
                        ts = pb.fixed64_u(v4)
                fields = [("_msg", body_s)]
                sev = sev_text or (_otlp_severity(sev_num) if sev_num else "")
                if sev:
                    fields.append(("severity", sev))
                fields.extend(attrs)
                fields.extend(resource_attrs)
                lmp.add_row(ts, fields)
                n += 1
    return n


@_ingest_guard("OTLP JSON")
def handle_otlp_json(cp: CommonParams, body: bytes,
                     lmp: LogMessageProcessor) -> int:
    try:
        obj = json.loads(body)
    except json.JSONDecodeError as e:
        raise IngestError(f"cannot parse OTLP JSON: {e}") from None
    n = 0
    for rl in obj.get("resourceLogs", []):
        resource_attrs = [(a.get("key", ""), _otlp_json_value(a.get("value")))
                          for a in rl.get("resource", {})
                          .get("attributes", [])]
        for sl in rl.get("scopeLogs", []):
            for rec in sl.get("logRecords", []):
                ts = parse_timestamp(int(rec["timeUnixNano"])) \
                    if rec.get("timeUnixNano") else None
                fields = [("_msg", _otlp_json_value(rec.get("body")))]
                sev = rec.get("severityText") or ""
                if sev:
                    fields.append(("severity", sev))
                fields.extend((a.get("key", ""),
                               _otlp_json_value(a.get("value")))
                              for a in rec.get("attributes", []))
                fields.extend(resource_attrs)
                lmp.add_row(ts, fields)
                n += 1
    return n


def _otlp_json_value(v) -> str:
    if v is None:
        return ""
    if "stringValue" in v:
        return v["stringValue"]
    if "intValue" in v:
        return str(v["intValue"])
    if "doubleValue" in v:
        return repr(float(v["doubleValue"]))
    if "boolValue" in v:
        return "true" if v["boolValue"] else "false"
    return json.dumps(v, separators=(",", ":"))


# ---------------- datadog ----------------

@_ingest_guard("Datadog")
def handle_datadog(cp: CommonParams, body: bytes,
                   lmp: LogMessageProcessor) -> int:
    try:
        obj = json.loads(body)
    except json.JSONDecodeError as e:
        raise IngestError(f"cannot parse Datadog JSON: {e}") from None
    if isinstance(obj, dict):
        obj = [obj]
    n = 0
    for item in obj:
        if not isinstance(item, dict):
            continue
        fields = []
        msg = item.get("message", "")
        # vlint: allow-per-row-emit(datadog non-string message fallback)
        msg_s = msg if isinstance(msg, str) else json.dumps(msg)
        fields.append(("_msg", msg_s))
        for k in ("ddsource", "service", "hostname", "status"):
            if item.get(k):
                fields.append((k, str(item[k])))
        tags = item.get("ddtags", "")
        for tag in str(tags).split(","):
            if ":" in tag:
                k, v = tag.split(":", 1)
                fields.append((k, v))
            elif tag:
                fields.append((tag, "no_label_value"))
        ts = parse_timestamp(item.get("timestamp") or item.get("date"))
        lmp.add_row(ts, fields)
        n += 1
    return n


# ---------------- journald export format ----------------

@_ingest_guard("journald")
def handle_journald(cp: CommonParams, body: bytes,
                    lmp: LogMessageProcessor) -> int:
    n = 0
    i = 0
    size = len(body)
    fields: list = []
    while i < size:
        nl = body.find(b"\n", i)
        if nl < 0:
            nl = size
        line = body[i:nl]
        if not line:  # blank line: end of entry
            if fields:
                n += _emit_journald(cp, fields, lmp)
                fields = []
            i = nl + 1
            continue
        eq = line.find(b"=")
        if eq >= 0:  # FIELD=value
            fields.append((line[:eq].decode("utf-8", "replace"),
                           line[eq + 1:].decode("utf-8", "replace")))
            i = nl + 1
        else:        # binary field: FIELD\n<8-byte LE size><data>\n
            name = line.decode("utf-8", "replace")
            j = nl + 1
            if j + 8 > size:
                break
            ln = int.from_bytes(body[j:j + 8], "little")
            data = body[j + 8:j + 8 + ln]
            fields.append((name, data.decode("utf-8", "replace")))
            i = j + 8 + ln + 1  # trailing newline
    if fields:
        n += _emit_journald(cp, fields, lmp)
    return n


def _emit_journald(cp: CommonParams, raw: list,
                   lmp: LogMessageProcessor) -> int:
    ts = None
    fields = []
    for k, v in raw:
        if k == "__REALTIME_TIMESTAMP":  # microseconds
            try:
                ts = int(v) * 1000
            except ValueError:
                pass
            continue
        if k.startswith("__"):
            continue
        if k == "MESSAGE":
            fields.append(("_msg", v))
        else:
            fields.append((k, v))
    lmp.add_row(ts, fields)
    return 1
